"""Shape manipulation / indexing / initialization operators.

Parity: ``src/operator/tensor/matrix_op.cc``, ``indexing_op.cc``,
``init_op.cc``, ``control_flow_op.cc`` (where), cast/one_hot/sequence ops.
All static-shape-friendly for XLA (dynamic-output ops like boolean_mask get
bounded-shape formulations in :mod:`.contrib`).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register

# ---------------------------------------------------------------------------
# reshape family (matrix_op.cc)
# ---------------------------------------------------------------------------


def _mx_reshape(data, shape):
    """Implement MXNet Reshape's special codes 0, -1, -2, -3, -4.

    Reference semantics: src/operator/tensor/matrix_op-inl.h (ReshapeParam).
    0=copy dim, -1=infer, -2=copy all remaining, -3=merge two dims,
    -4=split dim (followed by two sizes, -1 allowed in one).
    """
    src = list(data.shape)
    out = []
    i = 0  # index into src
    j = 0  # index into shape spec
    shape = list(shape)
    while j < len(shape):
        s = shape[j]
        if s == 0:
            out.append(src[i]); i += 1
        elif s == -1:
            out.append(-1); i += 1
        elif s == -2:
            out.extend(src[i:]); i = len(src)
        elif s == -3:
            out.append(src[i] * src[i + 1]); i += 2
        elif s == -4:
            d1, d2 = shape[j + 1], shape[j + 2]
            if d1 == -1:
                d1 = src[i] // d2
            if d2 == -1:
                d2 = src[i] // d1
            out.extend([d1, d2]); i += 1; j += 2
        else:
            out.append(s)
            if i < len(src):
                i += 1
        j += 1
    return jnp.reshape(data, tuple(out))


@register("Reshape", num_inputs=1, aliases=("reshape",))
def _reshape(data, shape=None, reverse=False, **ignored):
    if reverse:
        rs = _mx_reshape(jnp.reshape(data, data.shape[::-1]), list(shape)[::-1])
        return jnp.reshape(rs, rs.shape[::-1])
    return _mx_reshape(data, shape)


@register("Flatten", num_inputs=1, aliases=("flatten",))
def _flatten(data):
    return jnp.reshape(data, (data.shape[0], -1))


@register("transpose", num_inputs=1)
def _transpose(data, axes=None):
    if axes is None or (isinstance(axes, (tuple, list)) and len(axes) == 0):
        axes = tuple(reversed(range(data.ndim)))
    return jnp.transpose(data, axes)


@register("expand_dims", num_inputs=1)
def _expand_dims(data, axis=0):
    return jnp.expand_dims(data, axis)


@register("squeeze", num_inputs=1)
def _squeeze(data, axis=None):
    return jnp.squeeze(data, axis=axis)


@register("swapaxes", num_inputs=1, aliases=("SwapAxis",))
def _swapaxes(data, dim1=0, dim2=0):
    return jnp.swapaxes(data, dim1, dim2)


@register("depth_to_space", num_inputs=1)
def _depth_to_space(data, block_size):
    b, c, h, w = data.shape
    bs = block_size
    x = data.reshape(b, bs, bs, c // (bs * bs), h, w)
    x = x.transpose(0, 3, 4, 1, 5, 2)
    return x.reshape(b, c // (bs * bs), h * bs, w * bs)


@register("space_to_depth", num_inputs=1)
def _space_to_depth(data, block_size):
    b, c, h, w = data.shape
    bs = block_size
    x = data.reshape(b, c, h // bs, bs, w // bs, bs)
    x = x.transpose(0, 3, 5, 1, 2, 4)
    return x.reshape(b, c * bs * bs, h // bs, w // bs)


def _canon_slice(begin, end, step, shape):
    slices = []
    for i, dim in enumerate(shape):
        b = begin[i] if i < len(begin) else None
        e = end[i] if i < len(end) else None
        s = (step[i] if i < len(step) else None) if step else None
        slices.append(slice(b, e, s))
    return tuple(slices)


@register("slice", num_inputs=1, aliases=("crop",))
def _slice(data, begin=(), end=(), step=()):
    return data[_canon_slice(list(begin), list(end), list(step or ()), data.shape)]


@register("slice_axis", num_inputs=1)
def _slice_axis(data, axis=0, begin=0, end=None):
    idx = [slice(None)] * data.ndim
    idx[axis] = slice(begin, end)
    return data[tuple(idx)]


@register("slice_like", num_inputs=2)
def _slice_like(data, shape_like, axes=()):
    axes = list(axes) if axes else list(range(min(data.ndim, shape_like.ndim)))
    idx = [slice(None)] * data.ndim
    for a in axes:
        idx[a] = slice(0, shape_like.shape[a])
    return data[tuple(idx)]


@register("broadcast_to", num_inputs=1)
def _broadcast_to(data, shape=()):
    tgt = tuple(d if s == 0 else s for s, d in zip(shape, data.shape)) \
        if len(shape) == data.ndim else tuple(shape)
    return jnp.broadcast_to(data, tgt)


@register("broadcast_like", num_inputs=2)
def _broadcast_like(lhs, rhs, lhs_axes=None, rhs_axes=None):
    if lhs_axes is None:
        return jnp.broadcast_to(lhs, rhs.shape)
    tgt = list(lhs.shape)
    for la, ra in zip(lhs_axes, rhs_axes):
        tgt[la] = rhs.shape[ra]
    return jnp.broadcast_to(lhs, tuple(tgt))


@register("broadcast_axis", num_inputs=1, aliases=("broadcast_axes",))
def _broadcast_axis(data, axis=(), size=()):
    axis = (axis,) if isinstance(axis, int) else axis
    size = (size,) if isinstance(size, int) else size
    tgt = list(data.shape)
    for a, s in zip(axis, size):
        tgt[a] = s
    return jnp.broadcast_to(data, tuple(tgt))


@register("tile", num_inputs=1)
def _tile(data, reps=()):
    return jnp.tile(data, tuple(reps))


@register("repeat", num_inputs=1)
def _repeat(data, repeats=1, axis=None):
    return jnp.repeat(data, repeats, axis=axis)


@register("pad", num_inputs=1, aliases=("Pad",))
def _pad(data, mode="constant", pad_width=(), constant_value=0.0):
    pw = [(pad_width[2 * i], pad_width[2 * i + 1]) for i in range(len(pad_width) // 2)]
    if mode == "constant":
        return jnp.pad(data, pw, constant_values=constant_value)
    if mode == "edge":
        return jnp.pad(data, pw, mode="edge")
    if mode == "reflect":
        return jnp.pad(data, pw, mode="reflect")
    raise ValueError("unknown pad mode %r" % mode)


@register("reverse", num_inputs=1, aliases=("flip",))
def _reverse(data, axis=()):
    axis = (axis,) if isinstance(axis, int) else tuple(axis)
    return jnp.flip(data, axis=axis)


@register("Concat", aliases=("concat",))
def _concat(*args, dim=1, num_args=None):
    return jnp.concatenate(args, axis=dim)


@register("stack")
def _stack(*args, axis=0, num_args=None):
    return jnp.stack(args, axis=axis)


@register("SliceChannel", aliases=("split",), num_inputs=1,
          num_outputs=None)
def _split(data, num_outputs=2, axis=1, squeeze_axis=False):
    parts = jnp.split(data, num_outputs, axis=axis)
    if squeeze_axis:
        parts = [jnp.squeeze(p, axis=axis) for p in parts]
    return tuple(parts)


@register("split_v2", num_inputs=1, num_outputs=None)
def _split_v2(data, indices=(), axis=0, squeeze_axis=False, sections=0):
    if sections:
        parts = jnp.split(data, sections, axis=axis)
    else:
        parts = jnp.split(data, list(indices), axis=axis)
    if squeeze_axis:
        parts = [jnp.squeeze(p, axis=axis) for p in parts]
    return tuple(parts)


# ---------------------------------------------------------------------------
# indexing (indexing_op.cc)
# ---------------------------------------------------------------------------


@register("take", num_inputs=2)
def _take(a, indices, axis=0, mode="clip"):
    idx = indices.astype(jnp.int32)
    if mode == "wrap":
        idx = jnp.mod(idx, a.shape[axis])
    else:
        idx = jnp.clip(idx, 0, a.shape[axis] - 1)
    return jnp.take(a, idx, axis=axis)


@register("batch_take", num_inputs=2)
def _batch_take(a, indices):
    return jnp.take_along_axis(a, indices.astype(jnp.int32)[:, None], axis=1)[:, 0]


@register("pick", num_inputs=2)
def _pick(data, index, axis=-1, keepdims=False, mode="clip"):
    idx = jnp.clip(index.astype(jnp.int32), 0, data.shape[axis] - 1)
    idx_exp = jnp.expand_dims(idx, axis=axis)
    out = jnp.take_along_axis(data, idx_exp, axis=axis)
    return out if keepdims else jnp.squeeze(out, axis=axis)


@register("gather_nd", num_inputs=2)
def _gather_nd(data, indices):
    idx = tuple(indices[i].astype(jnp.int32) for i in range(indices.shape[0]))
    return data[idx]


@register("scatter_nd", num_inputs=2, differentiable=False)
def _scatter_nd(data, indices, shape=()):
    out = jnp.zeros(tuple(shape), dtype=data.dtype)
    idx = tuple(indices[i].astype(jnp.int32) for i in range(indices.shape[0]))
    return out.at[idx].set(data)


@register("_scatter_set_nd", num_inputs=3, differentiable=False)
def _scatter_set_nd(lhs, rhs, indices, shape=()):
    idx = tuple(indices[i].astype(jnp.int32) for i in range(indices.shape[0]))
    return lhs.at[idx].set(rhs)


@register("one_hot", num_inputs=1, differentiable=False)
def _one_hot(indices, depth=1, on_value=1.0, off_value=0.0, dtype="float32"):
    oh = jax.nn.one_hot(indices.astype(jnp.int32), depth, dtype=dtype)
    return oh * (on_value - off_value) + off_value


@register("where", num_inputs=3)
def _where(condition, x, y):
    return jnp.where(condition.astype(bool), x, y)


@register("Embedding", num_inputs=2)
def _embedding(data, weight, input_dim=None, output_dim=None, dtype="float32",
               sparse_grad=False):
    return jnp.take(weight, data.astype(jnp.int32), axis=0)


# ---------------------------------------------------------------------------
# casting
# ---------------------------------------------------------------------------


@register("Cast", num_inputs=1, aliases=("cast",))
def _cast(data, dtype="float32"):
    from ..base import np_dtype

    return data.astype(np_dtype(dtype))


@register("amp_cast", num_inputs=1)
def _amp_cast(data, dtype="float16"):
    from ..base import np_dtype

    # AMP casts only retype floating data; integer/bool edges (indices,
    # masks) pass through so convert_model can insert casts on every input
    # edge without dtype inference (amp.py _get_fun_to_wrap semantics)
    if not jnp.issubdtype(data.dtype, jnp.floating):
        return data
    return data.astype(np_dtype(dtype))


@register("amp_multicast")
def _amp_multicast(*args, num_outputs=None, cast_narrow=False):
    dtypes = [a.dtype for a in args]
    widest = jnp.result_type(*dtypes) if not cast_narrow else min(
        dtypes, key=lambda d: jnp.finfo(d).bits if jnp.issubdtype(d, jnp.floating) else 64)
    return tuple(a.astype(widest) for a in args)


# ---------------------------------------------------------------------------
# init ops (init_op.cc) — zero-input operators
# ---------------------------------------------------------------------------


def _to_dt(dtype):
    from ..base import np_dtype

    return np_dtype(dtype)


@register("_zeros", num_inputs=0, differentiable=False, aliases=("zeros",))
def _zeros(shape=(), ctx=None, dtype="float32"):
    return jnp.zeros(tuple(shape) if not isinstance(shape, int) else (shape,), _to_dt(dtype))


@register("_ones", num_inputs=0, differentiable=False, aliases=("ones",))
def _ones(shape=(), ctx=None, dtype="float32"):
    return jnp.ones(tuple(shape) if not isinstance(shape, int) else (shape,), _to_dt(dtype))


@register("_full", num_inputs=0, differentiable=False, aliases=("full",))
def _full(shape=(), value=0.0, ctx=None, dtype="float32"):
    return jnp.full(tuple(shape) if not isinstance(shape, int) else (shape,), value, _to_dt(dtype))


@register("_arange", num_inputs=0, differentiable=False, aliases=("arange",))
def _arange(start=0, stop=None, step=1.0, repeat=1, ctx=None, dtype="float32",
            infer_range=False):
    out = jnp.arange(start, stop, step, dtype=_to_dt(dtype))
    if repeat != 1:
        out = jnp.repeat(out, repeat)
    return out


@register("_linspace", num_inputs=0, differentiable=False, aliases=("linspace",))
def _linspace(start=0, stop=1, num=50, endpoint=True, ctx=None, dtype="float32"):
    return jnp.linspace(start, stop, int(num), endpoint=endpoint, dtype=_to_dt(dtype))


@register("zeros_like", num_inputs=1, differentiable=False)
def _zeros_like(data):
    return jnp.zeros_like(data)


@register("ones_like", num_inputs=1, differentiable=False)
def _ones_like(data):
    return jnp.ones_like(data)


@register("_eye", num_inputs=0, differentiable=False, aliases=("eye",))
def _eye(N=1, M=0, k=0, ctx=None, dtype="float32"):
    return jnp.eye(int(N), int(M) if M else None, k=int(k), dtype=_to_dt(dtype))


@register("shape_array", num_inputs=1, differentiable=False)
def _shape_array(data):
    return jnp.array(data.shape, dtype=jnp.int64)


@register("size_array", num_inputs=1, differentiable=False)
def _size_array(data):
    return jnp.array([data.size], dtype=jnp.int64)


# ---------------------------------------------------------------------------
# sequence ops (sequence_mask/last/reverse.cc) — SP/ring-attention building
# blocks; static-shape via masking
# ---------------------------------------------------------------------------


def _seq_len_mask(sequence_length, maxlen, batch, use_sequence_length):
    if not use_sequence_length or sequence_length is None:
        return jnp.full((batch, maxlen), True)
    steps = jnp.arange(maxlen)[None, :]
    return steps < sequence_length.astype(jnp.int32)[:, None]


@register("SequenceMask", aliases=("sequence_mask",))
def _sequence_mask(data, sequence_length=None, use_sequence_length=False, value=0.0,
                   axis=0):
    if not use_sequence_length or sequence_length is None:
        return data
    # data layout: (seq, batch, ...) for axis=0 or (batch, seq, ...) for axis=1
    maxlen = data.shape[axis]
    steps = jnp.arange(maxlen)
    if axis == 0:
        mask = steps[:, None] < sequence_length.astype(jnp.int32)[None, :]
    else:
        mask = steps[None, :] < sequence_length.astype(jnp.int32)[:, None]
    mask = mask.reshape(mask.shape + (1,) * (data.ndim - 2))
    return jnp.where(mask, data, jnp.asarray(value, data.dtype))


@register("SequenceLast", aliases=("sequence_last",))
def _sequence_last(data, sequence_length=None, use_sequence_length=False, axis=0):
    if not use_sequence_length or sequence_length is None:
        return jnp.take(data, -1, axis=axis)
    idx = (sequence_length.astype(jnp.int32) - 1)
    if axis == 0:
        return jnp.take_along_axis(
            data, idx.reshape((1, -1) + (1,) * (data.ndim - 2)), axis=0)[0]
    return jnp.take_along_axis(
        data, idx.reshape((-1, 1) + (1,) * (data.ndim - 2)), axis=1)[:, 0]


@register("SequenceReverse", aliases=("sequence_reverse",))
def _sequence_reverse(data, sequence_length=None, use_sequence_length=False, axis=0):
    if not use_sequence_length or sequence_length is None:
        return jnp.flip(data, axis=0)
    maxlen = data.shape[0]
    steps = jnp.arange(maxlen)[:, None]
    lens = sequence_length.astype(jnp.int32)[None, :]
    rev_idx = jnp.where(steps < lens, lens - 1 - steps, steps)
    return jnp.take_along_axis(data, rev_idx.reshape(rev_idx.shape + (1,) * (data.ndim - 2)),
                               axis=0)


@register("_np_nonzero", num_inputs=1, differentiable=False)
def _nonzero(data, size=None):
    return jnp.stack(jnp.nonzero(data, size=size or data.size, fill_value=-1), axis=-1)


@register("_np_unique", num_inputs=1, differentiable=False, no_trace=True,
          num_outputs=1)
def _unique(data, return_index=False, return_inverse=False,
            return_counts=False, axis=None):
    """np.unique (src/operator/numpy/np_unique_op.cc): output shape is
    data-dependent, so the op is host-evaluated (no_trace) like the
    reference's CPU-only kernel.  Inside jit use jnp.unique with a static
    ``size=`` instead."""
    import numpy as _onp

    outs = _onp.unique(_onp.asarray(data), return_index=return_index,
                       return_inverse=return_inverse,
                       return_counts=return_counts, axis=axis)
    if isinstance(outs, tuple):
        return tuple(jnp.asarray(o) for o in outs)
    return jnp.asarray(outs)


@register("tril", num_inputs=1)
def _tril(data, k=0):
    return jnp.tril(data, k=k)


def _regression_output(data, label, grad_scale, fwd_fn, grad_fn):
    """Regression heads are loss layers: forward transforms data, backward
    ignores the incoming cotangent and emits grad_fn(pred, label) *
    grad_scale / features-per-sample (src/operator/regression_output-inl.h
    backward Assign)."""
    lab = label.reshape(data.shape) if label.shape != data.shape else label
    num_output = data.size // data.shape[0] if data.ndim > 0 else 1

    @jax.custom_vjp
    def f(d, l):
        return fwd_fn(d)

    def fwd(d, l):
        return fwd_fn(d), (d, l)

    def bwd(res, g):
        d, l = res
        grad = grad_fn(fwd_fn(d), l) * (grad_scale / num_output)
        return grad.astype(d.dtype), jnp.zeros_like(l)

    f.defvjp(fwd, bwd)
    return f(data, lab)


@register("LinearRegressionOutput", num_inputs=2, aliases=("linear_regression_output",))
def _linreg_out(data, label, grad_scale=1.0):
    return _regression_output(data, label, grad_scale,
                              lambda d: d, lambda p, l: p - l)


@register("LogisticRegressionOutput", num_inputs=2, aliases=("logistic_regression_output",))
def _logreg_out(data, label, grad_scale=1.0):
    return _regression_output(data, label, grad_scale,
                              jax.nn.sigmoid, lambda p, l: p - l)


@register("MAERegressionOutput", num_inputs=2, aliases=("mae_regression_output",))
def _maereg_out(data, label, grad_scale=1.0):
    return _regression_output(data, label, grad_scale,
                              lambda d: d, lambda p, l: jnp.sign(p - l))
