"""Detection / bounding-box contrib ops.

Reference semantics: ``src/operator/contrib/multibox_prior.cc:40-70``,
``multibox_target.cc:80-280``, ``multibox_detection.cc:46-195``,
``bounding_box.cc`` (box_nms/box_iou/bipartite_matching),
``src/operator/roi_pooling.cc``, ``src/operator/contrib/roi_align.cc``.

All ops are static-shape XLA formulations: NMS and bipartite matching are
bounded ``fori_loop``s over masks instead of data-dependent compaction, so
the whole SSD graph (priors → targets → loss, or priors → detection) stays
inside one compiled program.
"""
from __future__ import annotations

import ast
import math
from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register

__all__ = []


def _tuple(v, n=None, typ=float):
    if isinstance(v, str):
        v = ast.literal_eval(v)
    if not isinstance(v, (tuple, list)):
        v = (v,) * (n or 1)
    return tuple(typ(x) for x in v)


# ---------------------------------------------------------------------------
# IoU helpers
# ---------------------------------------------------------------------------

def _corner_iou(lhs, rhs):
    """IoU between corner boxes lhs (..., 4) and rhs (..., 4), broadcast
    over leading dims (multibox_target.cc CalculateOverlap)."""
    il = jnp.maximum(lhs[..., 0], rhs[..., 0])
    it = jnp.maximum(lhs[..., 1], rhs[..., 1])
    ir = jnp.minimum(lhs[..., 2], rhs[..., 2])
    ib = jnp.minimum(lhs[..., 3], rhs[..., 3])
    iw = jnp.maximum(ir - il, 0)
    ih = jnp.maximum(ib - it, 0)
    inter = iw * ih
    area_l = jnp.maximum(lhs[..., 2] - lhs[..., 0], 0) * \
        jnp.maximum(lhs[..., 3] - lhs[..., 1], 0)
    area_r = jnp.maximum(rhs[..., 2] - rhs[..., 0], 0) * \
        jnp.maximum(rhs[..., 3] - rhs[..., 1], 0)
    union = area_l + area_r - inter
    # double-where keeps the zero-union branch out of the gradient (the
    # 0 * NaN = NaN trap) — box_iou is differentiable
    safe_union = jnp.where(union > 0, union, 1.0)
    return jnp.where(union > 0, inter / safe_union, 0.0)


def _to_corner(boxes, fmt):
    if fmt == "corner":
        return boxes
    # center: (x, y, w, h) → corners
    x, y, w, h = (boxes[..., i] for i in range(4))
    return jnp.stack([x - w / 2, y - h / 2, x + w / 2, y + h / 2], axis=-1)


def _from_corner(boxes, fmt):
    if fmt == "corner":
        return boxes
    l, t, r, b = (boxes[..., i] for i in range(4))
    return jnp.stack([(l + r) / 2, (t + b) / 2, r - l, b - t], axis=-1)


# ---------------------------------------------------------------------------
# MultiBoxPrior
# ---------------------------------------------------------------------------

@register("_contrib_MultiBoxPrior", num_inputs=1, differentiable=False,
          aliases=("MultiBoxPrior",))
def _multibox_prior(data, sizes=(1.0,), ratios=(1.0,), clip=False,
                    steps=(-1.0, -1.0), offsets=(0.5, 0.5)):
    """Anchor boxes per feature-map pixel (multibox_prior.cc:40-70):
    num_sizes + num_ratios - 1 anchors, corner format, normalized coords."""
    sizes = _tuple(sizes)
    ratios = _tuple(ratios)
    steps = _tuple(steps, 2)
    offsets = _tuple(offsets, 2)
    in_h, in_w = data.shape[2], data.shape[3]
    step_y = steps[0] if steps[0] > 0 else 1.0 / in_h
    step_x = steps[1] if steps[1] > 0 else 1.0 / in_w
    cy = (jnp.arange(in_h, dtype=jnp.float32) + offsets[0]) * step_y
    cx = (jnp.arange(in_w, dtype=jnp.float32) + offsets[1]) * step_x

    ws, hs = [], []
    r0 = math.sqrt(ratios[0])
    for s in sizes:
        ws.append(s * in_h / in_w * r0 / 2)
        hs.append(s / r0 / 2)
    for r in ratios[1:]:
        rr = math.sqrt(r)
        ws.append(sizes[0] * in_h / in_w * rr / 2)
        hs.append(sizes[0] / rr / 2)
    k = len(ws)
    ws = jnp.asarray(ws, jnp.float32)
    hs = jnp.asarray(hs, jnp.float32)

    cxg = jnp.broadcast_to(cx[None, :, None], (in_h, in_w, k))
    cyg = jnp.broadcast_to(cy[:, None, None], (in_h, in_w, k))
    out = jnp.stack([cxg - ws, cyg - hs, cxg + ws, cyg + hs], axis=-1)
    out = out.reshape(1, in_h * in_w * k, 4)
    if clip:
        out = jnp.clip(out, 0.0, 1.0)
    return out


# ---------------------------------------------------------------------------
# MultiBoxTarget
# ---------------------------------------------------------------------------

def _encode_loc(anchors, gt, variances):
    """(gx-ax)/aw/vx, (gy-ay)/ah/vy, log(gw/aw)/vw, log(gh/ah)/vh
    (multibox_target.cc:32-54 AssignLocTargets)."""
    aw = anchors[..., 2] - anchors[..., 0]
    ah = anchors[..., 3] - anchors[..., 1]
    ax = (anchors[..., 0] + anchors[..., 2]) * 0.5
    ay = (anchors[..., 1] + anchors[..., 3]) * 0.5
    gw = gt[..., 2] - gt[..., 0]
    gh = gt[..., 3] - gt[..., 1]
    gx = (gt[..., 0] + gt[..., 2]) * 0.5
    gy = (gt[..., 1] + gt[..., 3]) * 0.5
    safe = lambda v: jnp.maximum(v, 1e-12)  # noqa: E731
    return jnp.stack([
        (gx - ax) / safe(aw) / variances[0],
        (gy - ay) / safe(ah) / variances[1],
        jnp.log(safe(gw) / safe(aw)) / variances[2],
        jnp.log(safe(gh) / safe(ah)) / variances[3]], axis=-1)


def _target_one(anchors, label, cls_pred, overlap_threshold, ignore_label,
                negative_mining_ratio, negative_mining_thresh,
                minimum_negative_samples, variances):
    """Single-sample target assignment (multibox_target.cc:91-280)."""
    num_anchors = anchors.shape[0]
    num_labels = label.shape[0]
    gt_valid = label[:, 0] != -1.0                      # (L,)
    has_gt = jnp.any(gt_valid)
    overlaps = _corner_iou(anchors[:, None, :], label[None, :, 1:5])  # (A,L)
    overlaps = jnp.where(gt_valid[None, :], overlaps, -1.0)

    # --- stage 1: greedy bipartite matching (multibox_target.cc:112-148)
    def bip_step(_, carry):
        m_iou, m_gt, a_matched, g_matched = carry
        masked = jnp.where(a_matched[:, None] | g_matched[None, :],
                           -1.0, overlaps)
        flat = jnp.argmax(masked)
        bi = (flat // num_labels).astype(jnp.int32)
        bj = (flat % num_labels).astype(jnp.int32)
        val = masked[bi, bj]
        ok = val > 1e-6
        m_iou = m_iou.at[bi].set(jnp.where(ok, val, m_iou[bi]))
        m_gt = m_gt.at[bi].set(jnp.where(ok, bj, m_gt[bi]))
        a_matched = a_matched.at[bi].set(a_matched[bi] | ok)
        g_matched = g_matched.at[bj].set(g_matched[bj] | ok)
        return m_iou, m_gt, a_matched, g_matched

    m_iou = jnp.full((num_anchors,), -1.0)
    m_gt = jnp.full((num_anchors,), -1, jnp.int32)
    a_matched = jnp.zeros((num_anchors,), bool)
    g_matched = jnp.zeros((num_labels,), bool)
    m_iou, m_gt, a_matched, _ = lax.fori_loop(
        0, num_labels, bip_step, (m_iou, m_gt, a_matched, g_matched))

    # --- stage 2: per-anchor threshold matching (:150-179)
    best_gt = jnp.argmax(overlaps, axis=1)
    best_iou = jnp.max(overlaps, axis=1)
    thr_pos = (~a_matched) & (best_iou > overlap_threshold) \
        & (overlap_threshold > 0) & has_gt
    m_iou = jnp.where(a_matched, m_iou, best_iou)
    m_gt = jnp.where(a_matched, m_gt, best_gt.astype(jnp.int32))
    positive = a_matched | thr_pos

    # --- stage 3: negatives (:181-248)
    if negative_mining_ratio > 0:
        num_pos = jnp.sum(positive)
        num_neg = jnp.minimum(
            (num_pos * negative_mining_ratio).astype(jnp.int32),
            num_anchors - num_pos)
        num_neg = jnp.maximum(num_neg, int(minimum_negative_samples))
        eligible = (~positive) & (m_iou < negative_mining_thresh)
        # hardest negatives = lowest background-class probability
        bg_prob = jax.nn.softmax(cls_pred, axis=0)[0]        # (A,)
        key = jnp.where(eligible, bg_prob, jnp.inf)
        order = jnp.argsort(key, stable=True)
        rank = jnp.argsort(order, stable=True)
        negative = eligible & (rank < num_neg)
    else:
        negative = ~positive

    # --- assign targets (:250-277)
    gt_cls = label[:, 0]                                  # (L,)
    cls_of_match = jnp.take(gt_cls, jnp.maximum(m_gt, 0)) + 1.0
    cls_target = jnp.where(positive, cls_of_match,
                           jnp.where(negative, 0.0, float(ignore_label)))
    gt_box_of_match = jnp.take(label[:, 1:5], jnp.maximum(m_gt, 0), axis=0)
    loc = _encode_loc(anchors, gt_box_of_match, variances)  # (A,4)
    loc_target = jnp.where(positive[:, None], loc, 0.0)
    loc_mask = jnp.where(positive[:, None], jnp.ones_like(loc), 0.0)

    # no valid gt → all-init outputs (:106 guard)
    cls_target = jnp.where(has_gt, cls_target, float(ignore_label))
    loc_target = jnp.where(has_gt, loc_target, 0.0)
    loc_mask = jnp.where(has_gt, loc_mask, 0.0)
    return (loc_target.reshape(-1), loc_mask.reshape(-1),
            cls_target.astype(anchors.dtype))


@register("_contrib_MultiBoxTarget", num_inputs=3, num_outputs=3,
          differentiable=False, aliases=("MultiBoxTarget",))
def _multibox_target(anchor, label, cls_pred, overlap_threshold=0.5,
                     ignore_label=-1.0, negative_mining_ratio=-1.0,
                     negative_mining_thresh=0.5, minimum_negative_samples=0,
                     variances=(0.1, 0.1, 0.2, 0.2)):
    """SSD training targets → (loc_target (N,A*4), loc_mask (N,A*4),
    cls_target (N,A)) (multibox_target.cc:80)."""
    variances = _tuple(variances, 4)
    anchors = anchor.reshape(-1, 4)
    f = partial(_target_one, overlap_threshold=float(overlap_threshold),
                ignore_label=float(ignore_label),
                negative_mining_ratio=float(negative_mining_ratio),
                negative_mining_thresh=float(negative_mining_thresh),
                minimum_negative_samples=int(minimum_negative_samples),
                variances=variances)
    return jax.vmap(lambda lab, cp: f(anchors, lab, cp))(label, cls_pred)


# ---------------------------------------------------------------------------
# MultiBoxDetection
# ---------------------------------------------------------------------------

def _decode_loc(anchors, pred, variances, clip):
    """Inverse of _encode_loc (multibox_detection.cc:46-80
    TransformLocations)."""
    aw = anchors[..., 2] - anchors[..., 0]
    ah = anchors[..., 3] - anchors[..., 1]
    ax = (anchors[..., 0] + anchors[..., 2]) * 0.5
    ay = (anchors[..., 1] + anchors[..., 3]) * 0.5
    ox = pred[..., 0] * variances[0] * aw + ax
    oy = pred[..., 1] * variances[1] * ah + ay
    ow = jnp.exp(pred[..., 2] * variances[2]) * aw / 2
    oh = jnp.exp(pred[..., 3] * variances[3]) * ah / 2
    out = jnp.stack([ox - ow, oy - oh, ox + ow, oy + oh], axis=-1)
    if clip:
        out = jnp.clip(out, 0.0, 1.0)
    return out


def _greedy_nms(ids, boxes, nkeep, nms_threshold, force_suppress):
    """Greedy suppression over score-sorted rows: row j dies if an earlier
    surviving row i (same class unless force_suppress) has IoU ≥ thresh
    (multibox_detection.cc:176-193).  O(A) fori_loop with vector body."""
    num = ids.shape[0]

    def step(i, ids_):
        alive_i = (ids_[i] >= 0) & (i < nkeep)
        iou = _corner_iou(boxes[i], boxes)                 # (A,)
        same = jnp.where(force_suppress, True, ids_ == ids_[i])
        j = jnp.arange(num)
        kill = alive_i & (j > i) & (j < nkeep) & (ids_ >= 0) & same \
            & (iou >= nms_threshold)
        return jnp.where(kill, -1.0, ids_)

    return lax.fori_loop(0, num, step, ids)


def _detect_one(anchors, cls_prob, loc_pred, threshold, clip, variances,
                nms_threshold, force_suppress, nms_topk, background_id):
    num_classes, num_anchors = cls_prob.shape
    # foreground = every class row except background_id
    cls_idx = [j for j in range(num_classes) if j != background_id]
    fg = cls_prob[jnp.asarray(cls_idx), :]
    score = jnp.max(fg, axis=0)
    best = jnp.argmax(fg, axis=0)
    # map back to original class index, then to contiguous 0-based fg id
    # (reference emits id-1 with background first; general background_id
    # keeps the same contiguous numbering over non-background classes)
    row_id = jnp.where(score < threshold, -1.0, best.astype(jnp.float32))
    boxes = _decode_loc(anchors, loc_pred.reshape(-1, 4), variances, clip)

    # sort by (valid, score) desc — replaces the compaction in :132-146
    key = jnp.where(row_id >= 0, score, -jnp.inf)
    order = jnp.argsort(-key, stable=True)
    row_id = jnp.take(row_id, order)
    score = jnp.take(score, order)
    boxes = jnp.take(boxes, order, axis=0)
    valid_count = jnp.sum(row_id >= 0)
    nkeep = valid_count if nms_topk <= 0 else jnp.minimum(
        jnp.int32(nms_topk), valid_count)
    # beyond-topk valid rows are dropped (:162-168)
    row_id = jnp.where(jnp.arange(num_anchors) < nkeep, row_id, -1.0)

    if 0 < nms_threshold <= 1:
        row_id = _greedy_nms(row_id, boxes, nkeep, nms_threshold,
                             force_suppress)
    out = jnp.concatenate([row_id[:, None], score[:, None], boxes], axis=1)
    return jnp.where(row_id[:, None] >= 0, out, -1.0)


@register("_contrib_MultiBoxDetection", num_inputs=3, differentiable=False,
          aliases=("MultiBoxDetection",))
def _multibox_detection(cls_prob, loc_pred, anchor, clip=True,
                        threshold=0.01, background_id=0, nms_threshold=0.5,
                        force_suppress=False,
                        variances=(0.1, 0.1, 0.2, 0.2), nms_topk=-1):
    """SSD decode+NMS → (N, A, 6) rows [cls_id, score, xmin, ymin, xmax,
    ymax]; suppressed rows are -1 (multibox_detection.cc:85)."""
    variances = _tuple(variances, 4)
    anchors = anchor.reshape(-1, 4)
    f = partial(_detect_one, threshold=float(threshold), clip=bool(clip),
                variances=variances, nms_threshold=float(nms_threshold),
                force_suppress=bool(force_suppress), nms_topk=int(nms_topk),
                background_id=int(background_id))
    return jax.vmap(lambda cp, lp: f(anchors, cp, lp))(cls_prob, loc_pred)


# ---------------------------------------------------------------------------
# bounding_box.cc ops
# ---------------------------------------------------------------------------

@register("_contrib_box_iou", num_inputs=2, aliases=("box_iou",))
def _box_iou(lhs, rhs, format="corner"):  # noqa: A002
    """Pairwise IoU: out shape lhs.shape[:-1] + rhs.shape[:-1]
    (bounding_box.cc:121)."""
    lhs = _to_corner(lhs, format)
    rhs = _to_corner(rhs, format)
    ls = lhs.shape[:-1]
    rs = rhs.shape[:-1]
    lhs = lhs.reshape((-1, 4))
    rhs = rhs.reshape((-1, 4))
    out = _corner_iou(lhs[:, None, :], rhs[None, :, :])
    return out.reshape(ls + rs)


@register("_contrib_box_nms", num_inputs=1, differentiable=False,
          aliases=("box_nms",))
def _box_nms(data, overlap_thresh=0.5, valid_thresh=0.0, topk=-1,
             coord_start=2, score_index=1, id_index=-1, background_id=-1,
             force_suppress=False, in_format="corner", out_format="corner"):
    """NMS over (..., num_box, k) rows; surviving rows sorted by score
    descending, suppressed rows all -1 (bounding_box.cc:40)."""
    shape = data.shape
    num_box, width = shape[-2], shape[-1]
    flat = data.reshape((-1, num_box, width))

    def one(rows):
        score = rows[:, score_index]
        if id_index >= 0:
            ids = rows[:, id_index]
            bg_ok = (ids != background_id) if background_id >= 0 else True
        else:
            ids = jnp.zeros((num_box,))
            bg_ok = True
        valid = (score > valid_thresh) & bg_ok
        key = jnp.where(valid, score, -jnp.inf)
        order = jnp.argsort(-key, stable=True)
        rows_s = jnp.take(rows, order, axis=0)
        valid_s = jnp.take(valid, order)
        nkeep = jnp.sum(valid_s)
        if topk > 0:
            nkeep = jnp.minimum(nkeep, jnp.int32(topk))
        boxes = _to_corner(
            rows_s[:, coord_start:coord_start + 4], in_format)
        ids_s = jnp.take(ids, order)
        marker = jnp.where(valid_s & (jnp.arange(num_box) < nkeep),
                           ids_s if id_index >= 0 else 0.0, -jnp.inf)

        def step(i, mk):
            alive_i = mk[i] > -jnp.inf
            iou = _corner_iou(boxes[i], boxes)
            same = jnp.where(bool(force_suppress) or id_index < 0,
                             True, mk == mk[i])
            j = jnp.arange(num_box)
            kill = alive_i & (j > i) & (mk > -jnp.inf) & same \
                & (iou >= overlap_thresh)
            return jnp.where(kill, -jnp.inf, mk)

        marker = lax.fori_loop(0, num_box, step, marker)
        keep = marker > -jnp.inf
        out_rows = rows_s
        if out_format != in_format:
            out_rows = out_rows.at[:, coord_start:coord_start + 4].set(
                _from_corner(boxes, out_format))
        return jnp.where(keep[:, None], out_rows, -1.0)

    return jax.vmap(one)(flat).reshape(shape)


@register("_contrib_bipartite_matching", num_inputs=1, num_outputs=2,
          differentiable=False, aliases=("bipartite_matching",))
def _bipartite_matching(dist, is_ascend=False, threshold=None):
    """Greedy bipartite matching over (..., M, N) scores → (row_match,
    col_match) index arrays, -1 = unmatched (bounding_box.cc:162)."""
    shape = dist.shape
    m, n = shape[-2], shape[-1]
    flat = dist.reshape((-1, m, n))
    sign = 1.0 if is_ascend else -1.0
    thr = threshold

    def one(d):
        def step(_, carry):
            rmatch, cmatch = carry
            masked = jnp.where((rmatch[:, None] >= 0) | (cmatch[None, :] >= 0),
                               jnp.inf * 1.0, sign * d)
            idx = jnp.argmin(masked)
            bi, bj = idx // n, idx % n
            val = d[bi, bj]
            ok = jnp.isfinite(masked[bi, bj])
            if thr is not None:
                ok = ok & ((val <= thr) if is_ascend else (val >= thr))
            rmatch = rmatch.at[bi].set(jnp.where(ok, bj, rmatch[bi]))
            cmatch = cmatch.at[bj].set(jnp.where(ok, bi, cmatch[bj]))
            return rmatch, cmatch

        rmatch = jnp.full((m,), -1.0)
        cmatch = jnp.full((n,), -1.0)
        rmatch, cmatch = lax.fori_loop(0, min(m, n), step, (rmatch, cmatch))
        return rmatch, cmatch

    r, c = jax.vmap(one)(flat)
    return r.reshape(shape[:-1]), c.reshape(shape[:-2] + (n,))


# ---------------------------------------------------------------------------
# ROI ops
# ---------------------------------------------------------------------------

@register("ROIPooling", num_inputs=2)
def _roi_pooling(data, rois, pooled_size=(1, 1), spatial_scale=1.0):
    """Max pooling over quantized ROI bins (src/operator/roi_pooling.cc).
    data (N,C,H,W); rois (R,5) rows [batch_idx, x1, y1, x2, y2]."""
    ph, pw = _tuple(pooled_size, 2, int)
    n, c, h, w = data.shape
    scale = float(spatial_scale)

    def one(roi):
        b = roi[0].astype(jnp.int32)
        img = jnp.take(data, b, axis=0)                   # (C,H,W)
        x1 = jnp.round(roi[1] * scale)
        y1 = jnp.round(roi[2] * scale)
        x2 = jnp.round(roi[3] * scale)
        y2 = jnp.round(roi[4] * scale)
        roi_w = jnp.maximum(x2 - x1 + 1, 1.0)
        roi_h = jnp.maximum(y2 - y1 + 1, 1.0)
        bin_h = roi_h / ph
        bin_w = roi_w / pw
        i = jnp.arange(ph, dtype=jnp.float32)
        j = jnp.arange(pw, dtype=jnp.float32)
        hstart = jnp.clip(jnp.floor(i * bin_h) + y1, 0, h)
        hend = jnp.clip(jnp.ceil((i + 1) * bin_h) + y1, 0, h)
        wstart = jnp.clip(jnp.floor(j * bin_w) + x1, 0, w)
        wend = jnp.clip(jnp.ceil((j + 1) * bin_w) + x1, 0, w)
        rr = jnp.arange(h, dtype=jnp.float32)
        cc = jnp.arange(w, dtype=jnp.float32)
        mrow = (rr[None, :] >= hstart[:, None]) & (rr[None, :] < hend[:, None])
        mcol = (cc[None, :] >= wstart[:, None]) & (cc[None, :] < wend[:, None])
        mask = mrow[:, None, :, None] & mcol[None, :, None, :]  # (ph,pw,H,W)
        vals = jnp.where(mask[None], img[:, None, None, :, :], -jnp.inf)
        pooled = jnp.max(vals, axis=(-2, -1))             # (C,ph,pw)
        return jnp.where(jnp.isfinite(pooled), pooled, 0.0)

    return jax.vmap(one)(rois.astype(jnp.float32)).astype(data.dtype)


@register("_contrib_ROIAlign", num_inputs=2, aliases=("ROIAlign",))
def _roi_align(data, rois, pooled_size=(1, 1), spatial_scale=1.0, sample_ratio=-1,
               position_sensitive=False, aligned=False):
    """Bilinear ROI align (src/operator/contrib/roi_align.cc).  With
    sample_ratio<=0 a fixed 2×2 sample grid per bin is used (the reference
    picks ceil(roi/bin) adaptively, which is data-dependent — a fixed grid
    keeps shapes static for XLA)."""
    ph, pw = _tuple(pooled_size, 2, int)
    n, c, h, w = data.shape
    scale = float(spatial_scale)
    sr = int(sample_ratio) if int(sample_ratio) > 0 else 2
    off = 0.5 if aligned else 0.0

    def bilinear(img, y, x):
        """img (C,H,W); sample at continuous (y, x)."""
        y = jnp.clip(y, 0.0, h - 1.0)
        x = jnp.clip(x, 0.0, w - 1.0)
        y0 = jnp.floor(y).astype(jnp.int32)
        x0 = jnp.floor(x).astype(jnp.int32)
        y1 = jnp.minimum(y0 + 1, h - 1)
        x1 = jnp.minimum(x0 + 1, w - 1)
        ly, lx = y - y0, x - x0
        v00 = img[:, y0, x0]
        v01 = img[:, y0, x1]
        v10 = img[:, y1, x0]
        v11 = img[:, y1, x1]
        return (v00 * (1 - ly) * (1 - lx) + v01 * (1 - ly) * lx +
                v10 * ly * (1 - lx) + v11 * ly * lx)

    def one(roi):
        b = roi[0].astype(jnp.int32)
        img = jnp.take(data, b, axis=0)
        x1 = roi[1] * scale - off
        y1 = roi[2] * scale - off
        x2 = roi[3] * scale - off
        y2 = roi[4] * scale - off
        roi_w = jnp.maximum(x2 - x1, 1.0) if not aligned else (x2 - x1)
        roi_h = jnp.maximum(y2 - y1, 1.0) if not aligned else (y2 - y1)
        bin_h = roi_h / ph
        bin_w = roi_w / pw
        iy = jnp.arange(sr, dtype=jnp.float32)
        # sample offsets inside a bin: (k+0.5)/sr
        offs = (iy + 0.5) / sr
        gy = y1 + (jnp.arange(ph, dtype=jnp.float32)[:, None] +
                   offs[None, :]) * bin_h          # (ph, sr)
        gx = x1 + (jnp.arange(pw, dtype=jnp.float32)[:, None] +
                   offs[None, :]) * bin_w          # (pw, sr)
        yy = gy.reshape(-1)                         # (ph*sr,)
        xx = gx.reshape(-1)                         # (pw*sr,)
        samp = jax.vmap(lambda y: jax.vmap(
            lambda x: bilinear(img, y, x))(xx))(yy)  # (ph*sr, pw*sr, C)
        samp = samp.reshape(ph, sr, pw, sr, c)
        return jnp.mean(samp, axis=(1, 3)).transpose(2, 0, 1)  # (C,ph,pw)

    return jax.vmap(one)(rois.astype(jnp.float32)).astype(data.dtype)
