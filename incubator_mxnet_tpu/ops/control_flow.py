"""Higher-order control-flow ops over subgraphs.

Reference: ``src/operator/control_flow.cc:1089,1150,1211`` (_foreach,
_while_loop, _cond as stateful ops executing a CachedOp subgraph per
iteration, with hand-written gradients).

TPU-native design: the subgraph (a Symbol) is stored as a node attribute;
evaluation lowers to ``lax.scan`` / ``lax.while_loop`` / ``lax.cond``
INSIDE the enclosing jitted program, so the loop compiles to one XLA While
op and gradients come from ``jax.vjp`` through the scan — no hand-written
backward graphs.

Node input convention (set by symbol/contrib.py frontends):
  [data..., states..., free-captured vars...]  with name lists in attrs.
"""
from __future__ import annotations

from jax import lax

from .registry import register

__all__ = []


def _eval_sub(subgraph, bindings):
    from ..symbol.symbol import _eval_graph
    return _eval_graph(subgraph, bindings)


@register("_foreach", num_inputs=None, needs_rng=False)
def _foreach(*arrays, subgraph=None, data_names=(), state_names=(),
             free_names=(), num_out_data=0):
    """scan the subgraph over axis 0 of each data input
    (control_flow.cc:1089).  Outputs: [stacked data outputs...,
    final states...]."""
    nd_ = len(data_names)
    ns = len(state_names)
    data = arrays[:nd_]
    states = tuple(arrays[nd_:nd_ + ns])
    free = dict(zip(free_names, arrays[nd_ + ns:]))

    def body(carry, xs):
        bind = dict(free)
        bind.update(zip(data_names, xs))
        bind.update(zip(state_names, carry))
        outs = _eval_sub(subgraph, bind)
        return tuple(outs[num_out_data:]), tuple(outs[:num_out_data])

    carry, stacked = lax.scan(body, states, tuple(data))
    out = list(stacked) + list(carry)
    return tuple(out) if len(out) != 1 else out[0]


@register("_while_loop", num_inputs=None)
def _while_loop(*arrays, cond_graph=None, body_graph=None, var_names=(),
                free_names=(), max_iterations=0, num_out_data=0):
    """Bounded while loop (control_flow.cc:1150).  Step outputs are written
    into max_iterations-sized buffers (rows past the final iteration stay
    zero); returns [out_bufs..., final loop vars...]."""
    import jax.numpy as jnp

    nv = len(var_names)
    loop_vars = tuple(arrays[:nv])
    free = dict(zip(free_names, arrays[nv:]))
    max_iterations = int(max_iterations)

    def run_cond(vs):
        bind = dict(free)
        bind.update(zip(var_names, vs))
        (c,) = _eval_sub(cond_graph, bind)
        return c.astype(bool).reshape(())

    def run_body(vs):
        bind = dict(free)
        bind.update(zip(var_names, vs))
        outs = _eval_sub(body_graph, bind)
        return outs[:num_out_data], tuple(outs[num_out_data:])

    # Bounded scan with a live-mask instead of lax.while_loop: the loop
    # count is already bounded by max_iterations, and scan (unlike
    # while_loop) is reverse-differentiable, so while_loop graphs train.
    def step(carry, _):
        alive, vs = carry
        alive = alive & run_cond(vs)
        outs, new_vs = run_body(vs)
        outs = [jnp.where(alive, o, jnp.zeros_like(o)) for o in outs]
        vs = tuple(jnp.where(alive, nv, v) for nv, v in zip(new_vs, vs))
        return (alive, vs), tuple(outs)

    (_, final_vars), bufs = lax.scan(
        step, (jnp.bool_(True), loop_vars), None, length=max_iterations)
    out = list(bufs) + list(final_vars)
    return tuple(out) if len(out) != 1 else out[0]


@register("_cond", num_inputs=None)
def _cond(*arrays, pred_graph=None, then_graph=None, else_graph=None,
          pred_names=(), branch_names=(), free_names=()):
    """lax.cond over then/else subgraphs (control_flow.cc:1211)."""
    np_ = len(pred_names)
    nb = len(branch_names)
    pred_in = arrays[:np_]
    branch_in = tuple(arrays[np_:np_ + nb])
    free = dict(zip(free_names, arrays[np_ + nb:]))

    bind_p = dict(free)
    bind_p.update(zip(pred_names, pred_in))
    (p,) = _eval_sub(pred_graph, bind_p)

    def run(graph, ins):
        bind = dict(free)
        bind.update(zip(branch_names, ins))
        return tuple(_eval_sub(graph, bind))

    out = lax.cond(p.astype(bool).reshape(()),
                   lambda ins: run(then_graph, ins),
                   lambda ins: run(else_graph, ins), branch_in)
    return out if len(out) != 1 else out[0]
