"""Optimizer update operators.

Parity: ``src/operator/optimizer_op.cc`` (sgd/sgd_mom/adam/rmsprop/ftrl/
signsgd/signum/nag/ftml/lamb/adagrad + mp_* master-weight and multi_* fused
variants) and ``contrib/adamw.cc``.  Each update is a pure function returning
the new weight (and new states); the Updater/Trainer commits them in place.
On TPU the multi-tensor variants just vmap/loop inside one jit — XLA fuses
them into a single fused update program, which is what the hand-written
multi_sgd CUDA kernels were for.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register


def _apply_wd(weight, grad, wd, rescale_grad, clip_gradient):
    g = grad * rescale_grad
    if clip_gradient is not None and clip_gradient >= 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    return g + wd * weight


@register("sgd_update", num_inputs=2, differentiable=False, mutate_idx=(0,))
def _sgd_update(weight, grad, lr=0.01, wd=0.0, rescale_grad=1.0,
                clip_gradient=-1.0, lazy_update=True):
    g = _apply_wd(weight, grad, wd, rescale_grad, clip_gradient)
    return weight - lr * g


@register("sgd_mom_update", num_inputs=3, differentiable=False, mutate_idx=(0, 2))
def _sgd_mom_update(weight, grad, mom, lr=0.01, momentum=0.0, wd=0.0,
                    rescale_grad=1.0, clip_gradient=-1.0, lazy_update=True):
    g = _apply_wd(weight, grad, wd, rescale_grad, clip_gradient)
    new_mom = momentum * mom - lr * g
    return weight + new_mom, new_mom


@register("mp_sgd_update", num_inputs=3, differentiable=False, mutate_idx=(0, 2))
def _mp_sgd_update(weight, grad, weight32, lr=0.01, wd=0.0, rescale_grad=1.0,
                   clip_gradient=-1.0, lazy_update=True):
    g = _apply_wd(weight32, grad.astype(jnp.float32), wd, rescale_grad, clip_gradient)
    w32 = weight32 - lr * g
    return w32.astype(weight.dtype), w32


@register("mp_sgd_mom_update", num_inputs=4, differentiable=False, mutate_idx=(0, 2, 3))
def _mp_sgd_mom_update(weight, grad, mom, weight32, lr=0.01, momentum=0.0, wd=0.0,
                       rescale_grad=1.0, clip_gradient=-1.0, lazy_update=True):
    g = _apply_wd(weight32, grad.astype(jnp.float32), wd, rescale_grad, clip_gradient)
    new_mom = momentum * mom - lr * g
    w32 = weight32 + new_mom
    return w32.astype(weight.dtype), new_mom, w32


@register("mp_adam_update", num_inputs=5, differentiable=False,
          mutate_idx=(0, 2, 3, 4))
def _mp_adam_update(weight, grad, mean, var, weight32, lr=0.001, beta1=0.9,
                    beta2=0.999, epsilon=1e-8, wd=0.0, rescale_grad=1.0,
                    clip_gradient=-1.0, lazy_update=True):
    """Adam on the f32 master copy: grad is promoted, mean/var/weight32
    stay f32, and only the committed weight is cast back — the master-
    weight analog of mp_sgd_mom_update for the adam family (the
    reference grew the same shape as contrib mp adamw)."""
    g = _apply_wd(weight32, grad.astype(jnp.float32), wd, rescale_grad,
                  clip_gradient)
    new_mean = beta1 * mean + (1 - beta1) * g
    new_var = beta2 * var + (1 - beta2) * jnp.square(g)
    w32 = weight32 - lr * new_mean / (jnp.sqrt(new_var) + epsilon)
    return w32.astype(weight.dtype), new_mean, new_var, w32


@register("nag_mom_update", num_inputs=3, differentiable=False, mutate_idx=(0, 2))
def _nag_mom_update(weight, grad, mom, lr=0.01, momentum=0.0, wd=0.0,
                    rescale_grad=1.0, clip_gradient=-1.0):
    g = _apply_wd(weight, grad, wd, rescale_grad, clip_gradient)
    new_mom = momentum * mom + g
    return weight - lr * (g + momentum * new_mom), new_mom


@register("signsgd_update", num_inputs=2, differentiable=False, mutate_idx=(0,))
def _signsgd_update(weight, grad, lr=0.01, wd=0.0, rescale_grad=1.0,
                    clip_gradient=-1.0):
    g = _apply_wd(weight, grad, wd, rescale_grad, clip_gradient)
    return weight - lr * jnp.sign(g)


@register("signum_update", num_inputs=3, differentiable=False, mutate_idx=(0, 2))
def _signum_update(weight, grad, mom, lr=0.01, momentum=0.0, wd=0.0,
                   rescale_grad=1.0, clip_gradient=-1.0, wd_lh=0.0):
    g = grad * rescale_grad
    if clip_gradient is not None and clip_gradient >= 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    new_mom = momentum * mom - (1 - momentum) * (g + wd * weight)
    w = weight * (1 - lr * wd_lh) + lr * jnp.sign(new_mom)
    return w, new_mom


@register("adam_update", num_inputs=4, differentiable=False, mutate_idx=(0, 2, 3))
def _adam_update(weight, grad, mean, var, lr=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, wd=0.0, rescale_grad=1.0, clip_gradient=-1.0,
                 lazy_update=True):
    g = _apply_wd(weight, grad, wd, rescale_grad, clip_gradient)
    new_mean = beta1 * mean + (1 - beta1) * g
    new_var = beta2 * var + (1 - beta2) * jnp.square(g)
    w = weight - lr * new_mean / (jnp.sqrt(new_var) + epsilon)
    return w, new_mean, new_var


@register("ftml_update", num_inputs=5, differentiable=False, mutate_idx=(0, 2, 3, 4))
def _ftml_update(weight, grad, d, v, z, lr=0.1, beta1=0.6, beta2=0.999,
                 epsilon=1e-8, t=1, wd=0.0, rescale_grad=1.0, clip_grad=-1.0):
    g = grad * rescale_grad + wd * weight
    if clip_grad is not None and clip_grad >= 0:
        g = jnp.clip(g, -clip_grad, clip_grad)
    new_v = beta2 * v + (1 - beta2) * jnp.square(g)
    t = jnp.asarray(t, jnp.float32)  # f32 bias correction (x64 is on)
    d_t = (1 - beta1 ** t) / lr * (jnp.sqrt(new_v / (1 - beta2 ** t)) + epsilon)
    sigma = d_t - beta1 * d
    new_z = beta1 * z + (1 - beta1) * g - sigma * weight
    w = -new_z / d_t
    return w, d_t, new_v, new_z


@register("rmsprop_update", num_inputs=3, differentiable=False, mutate_idx=(0, 2))
def _rmsprop_update(weight, grad, n, lr=0.001, gamma1=0.95, epsilon=1e-8,
                    wd=0.0, rescale_grad=1.0, clip_gradient=-1.0,
                    clip_weights=-1.0):
    g = _apply_wd(weight, grad, wd, rescale_grad, clip_gradient)
    new_n = (1 - gamma1) * jnp.square(g) + gamma1 * n
    w = weight - lr * g / jnp.sqrt(new_n + epsilon)
    if clip_weights is not None and clip_weights > 0:
        w = jnp.clip(w, -clip_weights, clip_weights)
    return w, new_n


@register("rmspropalex_update", num_inputs=5, differentiable=False,
          mutate_idx=(0, 2, 3, 4))
def _rmspropalex_update(weight, grad, n, g_state, delta, lr=0.001, gamma1=0.95,
                        gamma2=0.9, epsilon=1e-8, wd=0.0, rescale_grad=1.0,
                        clip_gradient=-1.0, clip_weights=-1.0):
    g = _apply_wd(weight, grad, wd, rescale_grad, clip_gradient)
    new_n = (1 - gamma1) * jnp.square(g) + gamma1 * n
    new_g = (1 - gamma1) * g + gamma1 * g_state
    new_delta = gamma2 * delta - lr * g / jnp.sqrt(new_n - jnp.square(new_g) + epsilon)
    w = weight + new_delta
    if clip_weights is not None and clip_weights > 0:
        w = jnp.clip(w, -clip_weights, clip_weights)
    return w, new_n, new_g, new_delta


@register("ftrl_update", num_inputs=4, differentiable=False, mutate_idx=(0, 2, 3))
def _ftrl_update(weight, grad, z, n, lr=0.1, lamda1=0.01, beta=1.0, wd=0.0,
                 rescale_grad=1.0, clip_gradient=-1.0):
    g = grad * rescale_grad
    if clip_gradient is not None and clip_gradient >= 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    new_n = n + jnp.square(g)
    sigma = (jnp.sqrt(new_n) - jnp.sqrt(n)) / lr
    new_z = z + g - sigma * weight
    w = jnp.where(
        jnp.abs(new_z) > lamda1,
        -(new_z - jnp.sign(new_z) * lamda1) / ((beta + jnp.sqrt(new_n)) / lr + wd),
        0.0,
    ).astype(weight.dtype)
    return w, new_z, new_n


@register("_sparse_adagrad_update", num_inputs=3, differentiable=False,
          mutate_idx=(0, 2), aliases=("adagrad_update",))
def _adagrad_update(weight, grad, history, lr=0.01, epsilon=1e-7, wd=0.0,
                    rescale_grad=1.0, clip_gradient=-1.0):
    g = _apply_wd(weight, grad, wd, rescale_grad, clip_gradient)
    new_h = history + jnp.square(g)
    return weight - lr * g / (jnp.sqrt(new_h) + epsilon), new_h


@register("lamb_update_phase1", num_inputs=4, differentiable=False)
def _lamb_phase1(weight, grad, mean, var, beta1=0.9, beta2=0.999, epsilon=1e-6,
                 t=1, bias_correction=True, wd=0.0, rescale_grad=1.0,
                 clip_gradient=-1.0):
    g = grad * rescale_grad
    if clip_gradient is not None and clip_gradient >= 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    new_mean = beta1 * mean + (1 - beta1) * g
    new_var = beta2 * var + (1 - beta2) * jnp.square(g)
    if bias_correction:
        # f32 bias correction: python-float ** int array is weak f64
        # under the package-wide x64 flag and would promote the weight
        t = jnp.asarray(t, jnp.float32)
        mhat = new_mean / (1 - beta1 ** t)
        vhat = new_var / (1 - beta2 ** t)
    else:
        mhat, vhat = new_mean, new_var
    gw = mhat / (jnp.sqrt(vhat) + epsilon) + wd * weight
    return gw, new_mean, new_var


@register("lamb_update_phase2", num_inputs=3, differentiable=False)
def _lamb_phase2(weight, g, r1_r2=None, lr=0.01, lower_bound=-1.0, upper_bound=-1.0):
    r1 = jnp.linalg.norm(weight.reshape(-1))
    r2 = jnp.linalg.norm(g.reshape(-1))
    if lower_bound is not None and lower_bound >= 0:
        r1 = jnp.maximum(r1, lower_bound)
    if upper_bound is not None and upper_bound >= 0:
        r1 = jnp.minimum(r1, upper_bound)
    ratio = jnp.where((r1 > 0) & (r2 > 0), r1 / r2, 1.0)
    return weight - lr * ratio * g


@register("_adamw_update", num_inputs=5, differentiable=False, aliases=("adamw_update",))
def _adamw_update(weight, grad, mean, var, rescale_grad_arr, lr=0.001, beta1=0.9,
                  beta2=0.999, epsilon=1e-8, wd=0.0, eta=1.0, clip_gradient=-1.0):
    g = grad * rescale_grad_arr
    if clip_gradient is not None and clip_gradient >= 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    new_mean = beta1 * mean + (1 - beta1) * g
    new_var = beta2 * var + (1 - beta2) * jnp.square(g)
    w = weight - eta * (lr * new_mean / (jnp.sqrt(new_var) + epsilon) + wd * weight)
    return w, new_mean, new_var


def tree_all_finite(leaves):
    """ONE fused all-finite reduction over a list of arrays: a scalar
    bool that is True iff every element of every leaf is finite.

    The per-leaf ``jnp.all(isfinite(...))`` partials AND-reduce into a
    single scalar inside one traced program — XLA fuses the whole
    reduction, so there is exactly one device value to read (one
    device→host sync for eager callers, zero for in-program users like
    the fused step's non-finite guard).  Integer leaves are always
    finite and skipped.
    """
    ok = jnp.array(True)
    for a in leaves:
        if not jnp.issubdtype(jnp.asarray(a).dtype, jnp.floating):
            continue
        # isfinite runs in the leaf's own dtype: a downcast to f32
        # would misread finite f64 values beyond f32 range as inf
        ok = jnp.logical_and(ok, jnp.all(jnp.isfinite(a)))
    return ok


@register("all_finite", differentiable=False)
def _all_finite(*arrays, init_output=True):
    return tree_all_finite(arrays).reshape(1).astype(jnp.float32)


@register("multi_all_finite", differentiable=False)
def _multi_all_finite(*arrays, num_arrays=1, init_output=True):
    return _all_finite(*arrays)


# multi-tensor fused updates: XLA fuses the python loop into one program
def _multi(update_fn, n_per):
    def impl(*arrays, lrs=(), wds=(), num_weights=None, **kw):
        num = int(num_weights if num_weights is not None else len(arrays) // n_per)
        outs = []
        for i in range(num):
            group = arrays[i * n_per:(i + 1) * n_per]
            res = update_fn(*group, lr=lrs[i], wd=wds[i], **kw)
            outs.extend(res if isinstance(res, tuple) else (res,))
        return tuple(outs) if len(outs) > 1 else outs[0]

    return impl


register("multi_sgd_update", _multi(_sgd_update, 2), differentiable=False)
register("multi_sgd_mom_update", _multi(_sgd_mom_update, 3), differentiable=False)
register("multi_mp_sgd_update", _multi(_mp_sgd_update, 3), differentiable=False)
register("multi_mp_sgd_mom_update", _multi(_mp_sgd_mom_update, 4), differentiable=False)
