"""Operator registry.

Reference model: ``NNVM_REGISTER_OP`` + typed attributes (FCompute<cpu/gpu>,
FInferShape, FGradient, ... — see ``include/mxnet/op_attr_types.h:217-315``
and SURVEY.md Appendix A).  TPU-native model: every op registers ONE
implementation — a pure JAX function (``fn``) that XLA compiles for TPU *and*
CPU — and gradients come from ``jax.vjp`` at record time instead of a
registered FGradient pass.  Shape/dtype inference is ``jax.eval_shape`` over
the same fn, so there is no separate inference code to keep in sync.

The registry drives three frontends:
- ``mx.nd.*``    eager execution (+ autograd tape)       [Imperative::Invoke]
- ``mx.sym.*``   graph node creation                      [nnvm::Symbol]
- direct raw-array calls inside traced programs           [FCompute<tpu>]
"""
from __future__ import annotations

import functools
import inspect
from typing import Any, Callable, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp

__all__ = ["Op", "register", "get_op", "list_ops", "invoke", "invoke_raw", "OPS"]

OPS: Dict[str, "Op"] = {}


class Op:
    """A registered operator.

    Attributes
    ----------
    fn : callable(*arrays, **attrs) -> array or tuple of arrays
        Pure JAX implementation (the FCompute<tpu> equivalent).
    num_inputs : int or None (variadic)
    num_outputs : int
    differentiable : bool — False skips tape recording (e.g. argmax, shape ops
        with int outputs).
    needs_rng : bool — fn takes a ``key`` kwarg supplied from the stateful
        PRNG (eager) or trace key (compiled); mirrors ResourceRequest::kRandom.
    mutate_idx : tuple — indices of inputs the reference op mutates
        (FMutateInputs); kept as metadata for executor aliasing/donation.
    aux_update : callable(in_vals, out_vals, **attrs) -> {input_idx: new_val}
        or None — functional form of the reference's FMutateInputs side
        effects: given the op's traced inputs/outputs, returns replacement
        values for the mutated inputs (e.g. BatchNorm running stats).  The
        symbolic Executor and any whole-graph trace commit these through the
        generic aux-write channel; eager frontends commit them directly.
    """

    def __init__(self, name, fn, num_inputs=None, num_outputs=1,
                 differentiable=True, needs_rng=False, mutate_idx=(),
                 aliases=(), doc="", aux_update=None, no_trace=False):
        self.name = name
        self.fn = fn
        self.num_inputs = num_inputs
        self.num_outputs = num_outputs
        self.differentiable = differentiable
        self.needs_rng = needs_rng
        self.mutate_idx = tuple(mutate_idx)
        self.aliases = tuple(aliases)
        self.doc = doc or (fn.__doc__ or "")
        self.aux_update = aux_update
        # no_trace: fn must run on concrete arrays only (data-dependent
        # output shapes, host callbacks) — excluded from jit wrapping
        self.no_trace = no_trace

    def __repr__(self):
        return "Op(%s)" % self.name

    # -- inference ---------------------------------------------------------
    def infer(self, in_avals: Sequence[jax.ShapeDtypeStruct], **attrs):
        """Infer output shapes/dtypes via abstract evaluation."""
        out = jax.eval_shape(functools.partial(self.fn, **attrs), *in_avals)
        return out if isinstance(out, (tuple, list)) else (out,)


def register(name, fn=None, **kwargs):
    """Register an op (decorator or direct). ``aliases`` adds extra names."""
    def _do(f):
        op = Op(name, f, **kwargs)
        OPS[name] = op
        for a in op.aliases:
            OPS[a] = op
        return f

    if fn is not None:
        return _do(fn)
    return _do


def get_op(name: str) -> Op:
    try:
        return OPS[name]
    except KeyError:
        raise NotImplementedError(
            "operator %r is not registered in this framework (reference parity "
            "gap — see SURVEY.md §2.4)" % name
        ) from None


def list_ops() -> List[str]:
    return sorted(OPS.keys())


def _dmlc_type_name(default):
    """Map a python default to a dmlc::Parameter-style type string
    (dmlc/parameter.h field-type names as they appear in op docs)."""
    if isinstance(default, bool):
        return "boolean"
    if isinstance(default, int):
        return "int"
    if isinstance(default, float):
        return "float"
    if isinstance(default, str):
        return "string"
    if isinstance(default, (tuple, list)):
        return "Shape(tuple)"
    if default is None:
        return "string or None"
    return type(default).__name__


def op_info(name: str) -> Dict[str, Any]:
    """dmlc::Parameter-style reflection for a registered op.

    The reference exposes each op's parameter schema (declared via
    DMLC_DECLARE_PARAMETER, dmlc/parameter.h) through
    MXSymbolGetAtomicSymbolInfo (src/c_api/c_api_symbolic.cc) and code-gens
    python wrappers + docs from it.  Here the schema is derived from the
    FCompute signature itself: leading positional parameters are tensor
    inputs, keyword parameters (with defaults) are op attributes.

    Returns dict with: name, description, inputs [(name, type)], arguments
    [(name, type_str, default_repr or None)], num_outputs, aliases.
    """
    import inspect

    # the symbol layer owns the authoritative input-vs-attribute
    # classification (it drives graph composition); reuse it so reflection,
    # composition and docs can never disagree
    from ..symbol.symbol import _input_arg_names

    op = get_op(name)
    sig = inspect.signature(op.fn)
    in_names = _input_arg_names(op)
    inputs: List[Any] = []
    arguments: List[Any] = []
    for p in sig.parameters.values():
        if p.kind == p.VAR_POSITIONAL:
            inputs.append((p.name, "NDArray[]"))
            continue
        if p.kind == p.VAR_KEYWORD:
            continue
        if op.needs_rng and p.name == "key":
            continue  # internal PRNG resource (ResourceRequest::kRandom)
        if in_names is not None and p.name in in_names:
            inputs.append((p.name, "NDArray" if
                           p.default is inspect.Parameter.empty
                           else "NDArray, optional"))
        elif p.default is inspect.Parameter.empty:
            if p.kind in (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD):
                inputs.append((p.name, "NDArray"))  # variadic-op leading arg
            else:
                arguments.append((p.name, "required", None))
        else:
            arguments.append((p.name, "%s, optional" %
                              _dmlc_type_name(p.default), repr(p.default)))
    return {
        "name": op.name,
        "description": (op.doc or "").strip(),
        "inputs": inputs,
        "arguments": arguments,
        "num_outputs": op.num_outputs,
        "aliases": list(op.aliases),
    }


def op_doc(name: str) -> str:
    """Render op_info as a reference-style docstring (the text
    MXSymbolGetAtomicSymbolInfo feeds into generated wrappers)."""
    info = op_info(name)
    lines = [info["name"], ""]
    if info["description"]:
        lines += [info["description"], ""]
    if info["inputs"]:
        lines.append("Inputs:")
        for n, t in info["inputs"]:
            lines.append("    %s : %s" % (n, t))
        lines.append("")
    if info["arguments"]:
        lines.append("Parameters:")
        for n, t, d in info["arguments"]:
            lines.append("    %s : %s%s" % (n, t,
                                            "" if d is None
                                            else ", default=%s" % d))
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# Invocation
# ---------------------------------------------------------------------------


# AMP cast policy (contrib/amp): when active, inputs of ops in `lo` are
# cast to the low-precision target and inputs of ops in `hi` to float32
# before dispatch — the runtime analog of the reference's ReducePrecision
# graph pass (src/nnvm/low_precision_pass.cc).
AMP_POLICY: Dict[str, Any] = {"active": False, "target": None,
                              "lo": frozenset(), "hi": frozenset(),
                              "cond": {}}


def _amp_cast_inputs(op: Op, arrays, attrs=None):
    if not AMP_POLICY["active"]:
        return arrays
    name = op.name
    cond = AMP_POLICY["cond"].get(name)
    if cond is not None and attrs is not None \
            and str(attrs.get(cond[0])) in cond[1]:
        tgt = jnp.float32      # conditional fp32 (e.g. softrelu Activation)
    elif name in AMP_POLICY["lo"]:
        tgt = AMP_POLICY["target"]
    elif name in AMP_POLICY["hi"]:
        tgt = jnp.float32
    else:
        return arrays
    return [a.astype(tgt)
            if a is not None and hasattr(a, "dtype")
            and jnp.issubdtype(a.dtype, jnp.floating) and a.dtype != tgt
            else a for a in arrays]


def invoke_raw(op: Op, arrays: Sequence[Any], **attrs):
    """Run op.fn on raw jax arrays (trace-safe path)."""
    if op.needs_rng and "key" not in attrs:
        from .. import rng

        attrs["key"] = rng.next_key()
    return op.fn(*_amp_cast_inputs(op, list(arrays), attrs), **attrs)


def invoke(name: str, inputs: Sequence[Any], out=None, **attrs):
    """Imperative invoke on NDArrays, with autograd recording.

    Mirrors Imperative::Invoke (``src/imperative/imperative.cc:89``): infer +
    execute + (if recording) tape.  Returns NDArray or list of NDArrays.
    """
    from .. import profiler

    if profiler.is_running():
        import time
        t0 = time.monotonic()
        try:
            return _invoke_impl(name, inputs, out, **attrs)
        finally:
            profiler.record_op(name, (time.monotonic() - t0) * 1e6)
    return _invoke_impl(name, inputs, out, **attrs)


# eager-dispatch jit cache: one compiled executable per (op, static attrs)
# — the Imperative::Invoke fast path.  Without it each eager op executes
# primitive-by-primitive (one tiny dispatch per jnp call); with it the whole
# op body is a single cached XLA computation, which is what makes
# non-hybridized Gluon usable (the reference's imperative path is its fast
# path for the same reason: one fused engine push per op).
_EAGER_JIT: Dict[Any, Any] = {}


def _attr_key(v):
    if isinstance(v, (list,)):
        return tuple(_attr_key(x) for x in v)
    hash(v)
    return v


def _eager_fn(op: Op, attrs):
    """Jitted op body with attrs baked static, or None when not cacheable
    (unhashable attrs like subgraph Symbols, rng key operands, or ops
    flagged no_trace e.g. data-dependent-shape kernels)."""
    if op.no_trace or op.needs_rng:
        return None
    from .. import autograd, tracing

    if tracing.current_trace() is not None:
        # inside a whole-graph trace (CachedOp/Executor) the op body is
        # being traced into the outer program — a nested jit is pure
        # overhead AND would poison the cache with the trace's train mode
        return None
    try:
        # ambient train mode is baked into the traced program (BatchNorm /
        # Dropout read it at trace time), so it must be part of the key
        key = (op.name, autograd.is_training(), tuple(sorted(
            (k, _attr_key(v)) for k, v in attrs.items())))
        hash(key)
    except TypeError:
        return None
    fn = _EAGER_JIT.get(key)
    if fn is None:
        fn = jax.jit(functools.partial(op.fn, **attrs))
        _EAGER_JIT[key] = fn
    return fn


def _invoke_impl(name: str, inputs: Sequence[Any], out=None, **attrs):
    from .. import autograd
    from ..ndarray import NDArray

    op = OPS[name] if name in OPS else get_op(name)
    datas = [
        None if i is None else (i._data if isinstance(i, NDArray) else jnp.asarray(i))
        for i in inputs
    ]
    datas = _amp_cast_inputs(op, datas, attrs)

    if op.needs_rng:
        from .. import rng

        attrs.setdefault("key", rng.next_key())

    recording = (
        autograd.is_recording()
        and op.differentiable
        and any(autograd.requires_grad(i) for i in inputs if isinstance(i, NDArray))
    )
    jfn = _eager_fn(op, attrs)

    if recording:
        # differentiate only wrt non-None tensor inputs
        live = [j for j, d in enumerate(datas) if d is not None]
        body = (lambda *a: jfn(*a)) if jfn is not None \
            else (lambda *a: op.fn(*a, **attrs))

        def fn(*xs, _datas=tuple(datas), _live=tuple(live)):
            full = list(_datas)
            for j, x in zip(_live, xs):
                full[j] = x
            return body(*full)

        out_datas, vjp_fn = jax.vjp(fn, *[datas[j] for j in live])
        live_inputs = [inputs[j] for j in live]
    else:
        out_datas = jfn(*datas) if jfn is not None \
            else op.fn(*datas, **attrs)

    multi = isinstance(out_datas, (tuple, list))
    outs_list = list(out_datas) if multi else [out_datas]
    nd_outs = [NDArray(o) for o in outs_list]

    if recording:
        node = autograd.TapeNode(vjp_fn, live_inputs, nd_outs, name=name)
        autograd.attach_node(nd_outs, node)

    if out is not None:
        # write into provided output buffer(s) — reference kWriteTo semantics.
        # Fewer buffers than outputs is allowed (trailing state outputs are
        # dropped, matching reference ops whose extra states are mutated
        # internally); MORE is an error — the surplus handles would silently
        # keep stale data.
        outs = out if isinstance(out, (tuple, list)) else [out]
        if len(outs) > len(nd_outs):
            raise ValueError(
                "op %r produced %d output(s) but %d output buffer(s) were "
                "provided" % (name, len(nd_outs), len(outs)))
        for dst, src in zip(outs, nd_outs):
            dst._data = src._data
            dst._ag_node = getattr(src, "_ag_node", None)
            dst._ag_out_idx = getattr(src, "_ag_out_idx", 0)
        return out
    if multi:
        return nd_outs
    return nd_outs[0]
