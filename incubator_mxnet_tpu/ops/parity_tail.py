"""Operator parity tail: the remaining user-visible reference ops.

Closes the registry gap found by diffing every ``NNVM_REGISTER_OP`` /
``MXNET_OPERATOR_REGISTER_*`` site in ``/root/reference/src/operator``
against this registry.  Grouped: elementwise/compare aliases, utility
tensors, im2col/col2im, straight-through estimators, contrib helpers,
``*_like`` samplers, and multi-tensor / mixed-precision optimizer updates.

Internal-only reference names (graph-pass helpers, MKLDNN/TensorRT/TVM
subgraph ops, DGL sampling) are intentionally absent — their jobs belong
to XLA or are out of scope per SURVEY §7.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .optimizer_ops import _apply_wd
from .registry import OPS, register


def _alias(new_name, existing):
    """Register ``new_name`` as another name for an existing op, and record
    it on the Op so reflection / the generated catalog can find it."""
    op = OPS[existing]
    OPS[new_name] = op
    if new_name not in op.aliases:
        op.aliases = op.aliases + (new_name,)


# -- elementwise comparisons (elemwise forms of the broadcast_* family;
# reference spells less as "lesser" on the broadcast side) ------------------
for _n, _b in (("equal", "broadcast_equal"),
               ("not_equal", "broadcast_not_equal"),
               ("greater", "broadcast_greater"),
               ("greater_equal", "broadcast_greater_equal"),
               ("less", "broadcast_lesser"),
               ("less_equal", "broadcast_lesser_equal")):
    _alias(_n, _b)
_alias("BatchNorm_v1", "BatchNorm")
_alias("_scatter_plus_scalar", "_plus_scalar")
_alias("_scatter_minus_scalar", "_minus_scalar")
_alias("_grad_add", "elemwise_add")


@register("_logical_and_scalar", num_inputs=1)
def _logical_and_scalar(data, scalar=0.0):
    return ((data != 0) & (float(scalar) != 0)).astype(data.dtype)


@register("_logical_or_scalar", num_inputs=1)
def _logical_or_scalar(data, scalar=0.0):
    return ((data != 0) | (float(scalar) != 0)).astype(data.dtype)


@register("_logical_xor_scalar", num_inputs=1)
def _logical_xor_scalar(data, scalar=0.0):
    return ((data != 0) ^ (float(scalar) != 0)).astype(data.dtype)


_alias("_hypot", "broadcast_hypot")


@register("_hypot_scalar", num_inputs=1)
def _hypot_scalar(data, scalar=0.0):
    return jnp.hypot(data, float(scalar))


# -- tensor utilities --------------------------------------------------------

@register("moments", num_inputs=1, num_outputs=2)
def _moments(data, axes=None, keepdims=False):
    """mean+var in one op (src/operator/nn/moments.cc).  Two-pass deviation
    form: E[x^2]-E[x]^2 cancels catastrophically for large-mean float32."""
    axes = tuple(axes) if axes is not None else None
    mean = jnp.mean(data, axis=axes, keepdims=True)
    var = jnp.mean(jnp.square(data - mean), axis=axes,
                   keepdims=bool(keepdims))
    if not keepdims:
        mean = mean.reshape(var.shape)
    return mean, var


@register("reshape_like", num_inputs=2)
def _reshape_like(lhs, rhs, lhs_begin=None, lhs_end=None, rhs_begin=None,
                  rhs_end=None):
    """Reshape lhs to rhs's shape, optionally only over the [begin, end)
    axis ranges (src/operator/tensor/elemwise_unary_op_basic.cc)."""
    if lhs_begin is None and rhs_begin is None:
        return lhs.reshape(rhs.shape)
    lb = int(lhs_begin or 0)
    le = lhs.ndim if lhs_end is None else int(lhs_end)
    rb = int(rhs_begin or 0)
    re = rhs.ndim if rhs_end is None else int(rhs_end)
    new_shape = lhs.shape[:lb] + rhs.shape[rb:re] + lhs.shape[le:]
    return lhs.reshape(new_shape)


@register("softmax_cross_entropy", num_inputs=2)
def _softmax_cross_entropy(data, label):
    """Summed CE over the batch (src/operator/loss_binary_op.cc)."""
    logp = jax.nn.log_softmax(data, axis=-1)
    picked = jnp.take_along_axis(
        logp, label.astype(jnp.int32)[:, None], axis=-1)
    return -jnp.sum(picked)


@register("_histogram", num_inputs=1, differentiable=False, num_outputs=2,
          aliases=("histogram",))
def _histogram(data, bin_cnt=10, range=None):  # noqa: A002 - parity name
    if range is None:
        counts, edges = jnp.histogram(data, bins=int(bin_cnt))
    else:
        counts, edges = jnp.histogram(
            data, bins=int(bin_cnt),
            range=(float(range[0]), float(range[1])))
    return counts, edges


@register("_ravel_multi_index", num_inputs=1, differentiable=False)
def _ravel_multi_index(data, shape=None):
    idx = tuple(data[i] for i in range(data.shape[0]))
    return jnp.ravel_multi_index(idx, tuple(shape), mode="clip")


@register("_unravel_index", num_inputs=1, differentiable=False)
def _unravel_index(data, shape=None):
    return jnp.stack(jnp.unravel_index(data, tuple(shape)))


_alias("_split_v2", "split_v2")  # tensor.py op; num_outputs resolved at
#                                  compose time (symbol._compose_num_outputs)


@register("_slice_assign", num_inputs=2)
def _slice_assign(data, value, begin=(), end=(), step=()):
    idx = tuple(slice(b if b is not None else None,
                      e if e is not None else None,
                      s if s else None)
                for b, e, s in zip(begin, end,
                                   step or (None,) * len(begin)))
    return data.at[idx].set(value)


@register("_slice_assign_scalar", num_inputs=1)
def _slice_assign_scalar(data, scalar=0.0, begin=(), end=(), step=()):
    idx = tuple(slice(b if b is not None else None,
                      e if e is not None else None,
                      s if s else None)
                for b, e, s in zip(begin, end,
                                   step or (None,) * len(begin)))
    return data.at[idx].set(float(scalar))


@register("_identity_with_attr_like_rhs", num_inputs=2)
def _identity_with_attr_like_rhs(lhs, rhs):
    return lhs


@register("_zeros_without_dtype", num_inputs=0, differentiable=False)
def _zeros_without_dtype(shape=(), ctx=None, dtype=None):
    return jnp.zeros(tuple(shape),
                     jnp.float32 if dtype in (None, -1) else dtype)


@register("_np_all", num_inputs=1, differentiable=False, aliases=("all",))
def _np_all(data, axis=None, keepdims=False):
    return jnp.all(data, axis=axis if axis is None else tuple(
        axis) if isinstance(axis, (tuple, list)) else int(axis),
        keepdims=bool(keepdims))


@register("_np_any", num_inputs=1, differentiable=False, aliases=("any",))
def _np_any(data, axis=None, keepdims=False):
    return jnp.any(data, axis=axis if axis is None else tuple(
        axis) if isinstance(axis, (tuple, list)) else int(axis),
        keepdims=bool(keepdims))


# -- im2col / col2im (src/operator/nn/im2col.cc) -----------------------------

def _im2col_impl(data, kernel, stride, dilate, pad):
    n, c = data.shape[:2]
    patches = lax.conv_general_dilated_patches(
        data, filter_shape=tuple(kernel), window_strides=tuple(stride),
        padding=[(p, p) for p in pad], rhs_dilation=tuple(dilate))
    # patches: (N, C*prod(kernel), *out_spatial) -> (N, C*prod(k), L)
    return patches.reshape(n, c * int(np.prod(kernel)), -1)


@register("im2col", num_inputs=1)
def _im2col(data, kernel=None, stride=None, dilate=None, pad=None):
    nsp = data.ndim - 2
    kernel = tuple(kernel)
    stride = tuple(stride) if stride else (1,) * nsp
    dilate = tuple(dilate) if dilate else (1,) * nsp
    pad = tuple(pad) if pad else (0,) * nsp
    return _im2col_impl(data, kernel, stride, dilate, pad)


@register("col2im", num_inputs=1)
def _col2im(data, output_size=None, kernel=None, stride=None, dilate=None,
            pad=None):
    """Adjoint of im2col: scatter-add columns back (exactly the VJP of the
    patch extraction, which is how the reference's col2im kernel is used)."""
    nsp = len(tuple(output_size))
    kernel = tuple(kernel)
    stride = tuple(stride) if stride else (1,) * nsp
    dilate = tuple(dilate) if dilate else (1,) * nsp
    pad = tuple(pad) if pad else (0,) * nsp
    n = data.shape[0]
    c = data.shape[1] // int(np.prod(kernel))
    x_shape = (n, c) + tuple(int(s) for s in output_size)
    zeros = jnp.zeros(x_shape, data.dtype)
    _, vjp = jax.vjp(
        lambda x: _im2col_impl(x, kernel, stride, dilate, pad), zeros)
    (out,) = vjp(data)
    return out


# -- straight-through / gradient-shaping (contrib) ---------------------------

@jax.custom_vjp
def _ste_round(x):
    return jnp.rint(x)


_ste_round.defvjp(lambda x: (jnp.rint(x), None), lambda _, g: (g,))


@register("_contrib_round_ste", num_inputs=1)
def _round_ste(data):
    return _ste_round(data)


@jax.custom_vjp
def _ste_sign(x):
    return jnp.sign(x)


_ste_sign.defvjp(lambda x: (jnp.sign(x), None), lambda _, g: (g,))


@register("_contrib_sign_ste", num_inputs=1)
def _sign_ste(data):
    return _ste_sign(data)


def _make_grad_mult():
    @jax.custom_vjp
    def f(x, s):
        return x

    f.defvjp(lambda x, s: (x, s),
             lambda s, g: (g * s, jnp.zeros_like(s)))
    return f


_grad_mult = _make_grad_mult()


@register("_contrib_gradientmultiplier", num_inputs=1)
def _gradientmultiplier(data, scalar=1.0):
    return _grad_mult(data, jnp.asarray(float(scalar), data.dtype))


@register("_contrib_quadratic", num_inputs=1,
          aliases=("_npx_quadratic",))
def _quadratic(data, a=0.0, b=0.0, c=0.0):
    """The tutorial custom op (src/operator/contrib/quadratic_op.cc)."""
    return float(a) * jnp.square(data) + float(b) * data + float(c)


@register("_contrib_allclose", num_inputs=2, differentiable=False)
def _allclose(a, b, rtol=1e-5, atol=1e-8, equal_nan=False):
    return jnp.allclose(a, b, rtol=float(rtol), atol=float(atol),
                        equal_nan=bool(equal_nan)).astype(jnp.float32)


@register("_contrib_arange_like", num_inputs=1, differentiable=False)
def _arange_like(data, start=0.0, step=1.0, repeat=1, axis=None):
    def ramp(n):
        k = max(int(repeat), 1)
        base = jnp.arange((n + k - 1) // k, dtype=jnp.float32)
        vals = float(start) + float(step) * base
        return jnp.repeat(vals, k)[:n].astype(data.dtype)

    if axis is None:
        return ramp(data.size).reshape(data.shape)
    return ramp(data.shape[int(axis)])


@register("_contrib_getnnz", num_inputs=1, differentiable=False)
def _getnnz(data, axis=None):
    return jnp.sum(data != 0, axis=axis).astype(jnp.int64)


@register("_contrib_box_encode", num_inputs=4, differentiable=False,
          num_outputs=2)
def _box_encode(samples, matches, anchors, refs, means=None, stds=None):
    """SSD target encoding (src/operator/contrib/bounding_box.cc):
    corner-format anchors/refs -> (center offset / size log) targets."""
    means = jnp.asarray(means if means is not None else (0., 0., 0., 0.))
    stds = jnp.asarray(stds if stds is not None else (.1, .1, .2, .2))
    ref = jnp.take_along_axis(refs, matches[..., None].astype(jnp.int32),
                              axis=1)
    aw = anchors[..., 2] - anchors[..., 0]
    ah = anchors[..., 3] - anchors[..., 1]
    ax = (anchors[..., 0] + anchors[..., 2]) / 2
    ay = (anchors[..., 1] + anchors[..., 3]) / 2
    rw = ref[..., 2] - ref[..., 0]
    rh = ref[..., 3] - ref[..., 1]
    rx = (ref[..., 0] + ref[..., 2]) / 2
    ry = (ref[..., 1] + ref[..., 3]) / 2
    t = jnp.stack([(rx - ax) / aw, (ry - ay) / ah,
                   jnp.log(jnp.maximum(rw / aw, 1e-12)),
                   jnp.log(jnp.maximum(rh / ah, 1e-12))], axis=-1)
    t = (t - means) / stds
    valid = (samples > 0.5)[..., None]
    return jnp.where(valid, t, 0.0), jnp.broadcast_to(
        valid, t.shape).astype(t.dtype)


@register("_contrib_box_decode", num_inputs=2, differentiable=False)
def _box_decode(data, anchors, std0=0.1, std1=0.1, std2=0.2, std3=0.2,
                clip=-1.0, format="corner"):  # noqa: A002 - parity name
    if format == "corner":
        aw = anchors[..., 2] - anchors[..., 0]
        ah = anchors[..., 3] - anchors[..., 1]
        ax = (anchors[..., 0] + anchors[..., 2]) / 2
        ay = (anchors[..., 1] + anchors[..., 3]) / 2
    else:  # center
        ax, ay, aw, ah = (anchors[..., i] for i in range(4))
    dx = data[..., 0] * float(std0) * aw + ax
    dy = data[..., 1] * float(std1) * ah + ay
    dw = jnp.exp(data[..., 2] * float(std2)) * aw / 2
    dh = jnp.exp(data[..., 3] * float(std3)) * ah / 2
    out = jnp.stack([dx - dw, dy - dh, dx + dw, dy + dh], axis=-1)
    if clip > 0:
        out = jnp.clip(out, 0, float(clip))
    return out


# -- *_like samplers (src/operator/random/sample_op.cc) ----------------------

def _like_sampler(name, draw):
    @register(name, num_inputs=1, differentiable=False, needs_rng=True)
    def _fn(data, key=None, **attrs):
        return draw(key, data.shape, attrs).astype(data.dtype)
    return _fn


_like_sampler("_random_uniform_like",
              lambda k, s, a: jax.random.uniform(
                  k, s, minval=float(a.get("low", 0.0)),
                  maxval=float(a.get("high", 1.0))))
_like_sampler("_random_normal_like",
              lambda k, s, a: float(a.get("loc", 0.0)) +
              float(a.get("scale", 1.0)) * jax.random.normal(k, s))
_like_sampler("_random_exponential_like",
              lambda k, s, a: jax.random.exponential(k, s) /
              float(a.get("lam", 1.0)))
_like_sampler("_random_gamma_like",
              lambda k, s, a: jax.random.gamma(
                  k, float(a.get("alpha", 1.0)), s) *
              float(a.get("beta", 1.0)))
_like_sampler("_random_poisson_like",
              lambda k, s, a: jax.random.poisson(
                  k, float(a.get("lam", 1.0)), s).astype(jnp.float32))


def _neg_binomial(key, shape, k, p):
    # NB(k, p) = Poisson(Gamma(k, (1-p)/p))
    kg, kp = jax.random.split(key)
    lam = jax.random.gamma(kg, k, shape) * (1.0 - p) / p
    return jax.random.poisson(kp, lam, shape).astype(jnp.float32)


_like_sampler("_random_negative_binomial_like",
              lambda key, s, a: _neg_binomial(
                  key, s, float(a.get("k", 1.0)), float(a.get("p", 0.5))))
_like_sampler("_random_generalized_negative_binomial_like",
              lambda key, s, a: _neg_binomial(
                  key, s, 1.0 / max(float(a.get("alpha", 1.0)), 1e-6),
                  1.0 / (1.0 + max(float(a.get("alpha", 1.0)), 1e-6) *
                         float(a.get("mu", 1.0)))))


@register("_sample_unique_zipfian", num_inputs=0, differentiable=False,
          num_outputs=2, no_trace=True, needs_rng=True)
def _sample_unique_zipfian(range_max=None, shape=None, key=None):
    """Unique zipfian candidate sampling (sampled-softmax helper,
    src/operator/random/unique_sample_op.cc) — host-evaluated."""
    import numpy as onp

    seed = int(jax.device_get(jax.random.key_data(key))[-1]) & 0x7FFFFFFF
    rng = onp.random.RandomState(seed)
    n = int(shape[0]) if shape else 1
    rmax = int(range_max)
    # inverse-CDF zipf over [0, rmax)
    out, seen, trials = [], set(), 0
    while len(out) < n and trials < 100 * n:
        u = rng.rand()
        v = int(onp.exp(u * onp.log(rmax + 1.0)) - 1.0)
        trials += 1
        if v not in seen:
            seen.add(v)
            out.append(v)
    while len(out) < n:
        out.append(rng.randint(rmax))
    return (jnp.asarray(out, jnp.int64),
            jnp.asarray([trials], jnp.int64))


# -- multi-tensor / mixed-precision optimizer tail ---------------------------

@register("multi_sum_sq", differentiable=False, num_outputs=None)
def _multi_sum_sq(*arrays, num_arrays=None):
    return tuple(jnp.sum(jnp.square(a.astype(jnp.float32))) for a in arrays)


@register("reset_arrays", differentiable=False, num_outputs=None)
def _reset_arrays(*arrays, num_arrays=None):
    return tuple(jnp.zeros_like(a) for a in arrays)


@register("multi_lars", num_inputs=3, differentiable=False)
def _multi_lars(lrs, weights_sum_sq, grads_sum_sq, wds=None, eta=0.001,
                eps=1e-8, rescale_grad=1.0):
    """LARS trust-ratio scaling of a vector of learning rates
    (src/operator/contrib/multi_lars.cc)."""
    wds = jnp.asarray(wds, jnp.float32) if wds is not None else \
        jnp.zeros_like(lrs)
    wn = jnp.sqrt(weights_sum_sq)
    gn = jnp.sqrt(grads_sum_sq) * float(rescale_grad)
    trust = jnp.where(
        (wn > 0) & (gn > 0),
        float(eta) * wn / (gn + wds * wn + float(eps)), 1.0)
    return lrs * trust


@register("mp_nag_mom_update", num_inputs=4, differentiable=False,
          mutate_idx=(0, 2, 3))
def _mp_nag_mom_update(weight, grad, mom, weight32, lr=0.01, momentum=0.0,
                       wd=0.0, rescale_grad=1.0, clip_gradient=-1.0):
    g = _apply_wd(weight32, grad.astype(jnp.float32), wd, rescale_grad,
                  clip_gradient)
    new_mom = momentum * mom + g
    w32 = weight32 - lr * (g + momentum * new_mom)
    return w32.astype(weight.dtype), new_mom, w32


def _lamb_phase1(weight32, grad, mean, var, beta1, beta2, epsilon, t, wd,
                 rescale_grad, clip_gradient, bias_correction):
    g = grad.astype(jnp.float32) * rescale_grad
    if clip_gradient > 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    new_mean = beta1 * mean + (1 - beta1) * g
    new_var = beta2 * var + (1 - beta2) * jnp.square(g)
    m, v = new_mean, new_var
    if bias_correction:
        m = m / (1 - beta1 ** t)
        v = v / (1 - beta2 ** t)
    return m / (jnp.sqrt(v) + epsilon) + wd * weight32, new_mean, new_var


@register("mp_lamb_update_phase1", num_inputs=5, differentiable=False,
          mutate_idx=(2, 3))
def _mp_lamb_update_phase1(weight, grad, mean, var, weight32, beta1=0.9,
                           beta2=0.999, epsilon=1e-6, t=1, wd=0.0,
                           rescale_grad=1.0, clip_gradient=-1.0,
                           bias_correction=True):
    out, new_mean, new_var = _lamb_phase1(
        weight32, grad, mean, var, float(beta1), float(beta2),
        float(epsilon), int(t), float(wd), float(rescale_grad),
        float(clip_gradient), bool(bias_correction))
    return out, new_mean, new_var


@register("mp_lamb_update_phase2", num_inputs=5, differentiable=False,
          mutate_idx=(0,))
def _mp_lamb_update_phase2(weight, g, r1, r2, weight32, lr=0.01,
                           lower_bound=-1.0, upper_bound=-1.0):
    ratio = jnp.where((r1 > 0) & (r2 > 0), r1 / r2, 1.0)
    if lower_bound > 0:
        ratio = jnp.maximum(ratio, lower_bound)
    if upper_bound > 0:
        ratio = jnp.minimum(ratio, upper_bound)
    w32 = weight32 - lr * ratio * g
    return w32.astype(weight.dtype), w32


@register("_mp_adamw_update", num_inputs=5, differentiable=False,
          mutate_idx=(0, 2, 3, 4))
def _mp_adamw_update(weight, grad, mean, var, weight32, lr=0.001, beta1=0.9,
                     beta2=0.999, epsilon=1e-8, wd=0.0, eta=1.0,
                     rescale_grad=1.0, clip_gradient=-1.0):
    g = grad.astype(jnp.float32) * float(rescale_grad)
    if float(clip_gradient) > 0:
        g = jnp.clip(g, -float(clip_gradient), float(clip_gradient))
    new_mean = float(beta1) * mean + (1 - float(beta1)) * g
    new_var = float(beta2) * var + (1 - float(beta2)) * jnp.square(g)
    w32 = weight32 - float(eta) * (
        float(lr) * new_mean / (jnp.sqrt(new_var) + float(epsilon)) +
        float(wd) * weight32)
    return w32.astype(weight.dtype), new_mean, new_var, w32


def _preloaded_group(arrays, per_weight, trailing):
    """Split the flat variadic input of preloaded_multi_* ops: N groups of
    ``per_weight`` tensors followed by ``trailing`` scalars (lrs, wds)."""
    nw = (len(arrays) - trailing) // per_weight
    groups = [arrays[i * per_weight:(i + 1) * per_weight]
              for i in range(nw)]
    return groups, arrays[nw * per_weight:]


@register("preloaded_multi_sgd_update", differentiable=False,
          num_outputs=None)
def _preloaded_multi_sgd_update(*arrays, num_weights=None, rescale_grad=1.0,
                                clip_gradient=-1.0):
    groups, (lrs, wds) = _preloaded_group(list(arrays), 2, 2)
    outs = []
    for i, (w, g) in enumerate(groups):
        gg = _apply_wd(w, g, wds[i], rescale_grad, clip_gradient)
        outs.append(w - lrs[i] * gg)
    return tuple(outs)


@register("preloaded_multi_sgd_mom_update", differentiable=False,
          num_outputs=None)
def _preloaded_multi_sgd_mom_update(*arrays, num_weights=None, momentum=0.0,
                                    rescale_grad=1.0, clip_gradient=-1.0):
    groups, (lrs, wds) = _preloaded_group(list(arrays), 3, 2)
    outs = []
    for i, (w, g, m) in enumerate(groups):
        gg = _apply_wd(w, g, wds[i], rescale_grad, clip_gradient)
        new_m = momentum * m - lrs[i] * gg
        outs.extend([w + new_m, new_m])
    return tuple(outs)


@register("preloaded_multi_mp_sgd_update", differentiable=False,
          num_outputs=None)
def _preloaded_multi_mp_sgd_update(*arrays, num_weights=None,
                                   rescale_grad=1.0, clip_gradient=-1.0):
    groups, (lrs, wds) = _preloaded_group(list(arrays), 3, 2)
    outs = []
    for i, (w, g, w32) in enumerate(groups):
        gg = _apply_wd(w32, g.astype(jnp.float32), wds[i], rescale_grad,
                       clip_gradient)
        new_w32 = w32 - lrs[i] * gg
        outs.extend([new_w32.astype(w.dtype), new_w32])
    return tuple(outs)


@register("preloaded_multi_mp_sgd_mom_update", differentiable=False,
          num_outputs=None)
def _preloaded_multi_mp_sgd_mom_update(*arrays, num_weights=None,
                                       momentum=0.0, rescale_grad=1.0,
                                       clip_gradient=-1.0):
    groups, (lrs, wds) = _preloaded_group(list(arrays), 4, 2)
    outs = []
    for i, (w, g, m, w32) in enumerate(groups):
        gg = _apply_wd(w32, g.astype(jnp.float32), wds[i], rescale_grad,
                       clip_gradient)
        new_m = momentum * m - lrs[i] * gg
        new_w32 = w32 + new_m
        outs.extend([new_w32.astype(w.dtype), new_m, new_w32])
    return tuple(outs)


@register("_contrib_group_adagrad_update", num_inputs=3,
          differentiable=False, mutate_idx=(0, 2))
def _group_adagrad_update(weight, grad, history, lr=0.01, rescale_grad=1.0,
                          clip_gradient=-1.0, epsilon=1e-5):
    """Row-wise adagrad (proximal variant without wd,
    src/operator/contrib/optimizer_op.cc)."""
    g = grad * float(rescale_grad)
    if float(clip_gradient) > 0:
        g = jnp.clip(g, -float(clip_gradient), float(clip_gradient))
    red_axes = tuple(range(1, g.ndim))
    new_hist = history + jnp.mean(jnp.square(g), axis=red_axes)
    shape = (-1,) + (1,) * (g.ndim - 1)
    return (weight - float(lr) * g /
            (jnp.sqrt(new_hist).reshape(shape) + float(epsilon)), new_hist)


# -- last named contrib gaps -------------------------------------------------

def edge_id(csr, u, v):
    """Edge-id lookup in a CSR adjacency: out[i] = data[k] where
    (indices[k] == v[i]) within row u[i]'s span, else -1
    (src/operator/contrib/dgl_graph.cc _contrib_edge_id).  Takes the
    CSRNDArray directly — CSR structure is python-side here, so this is a
    sparse-frontend function rather than a registry op."""
    import numpy as np

    from ..ndarray import ndarray as _nd

    indptr = np.asarray(csr.indptr.asnumpy())
    indices = np.asarray(csr.indices.asnumpy())
    data = np.asarray(csr.data.asnumpy())
    uu = np.asarray(u.asnumpy() if hasattr(u, "asnumpy") else u).astype(int)
    vv = np.asarray(v.asnumpy() if hasattr(v, "asnumpy") else v).astype(int)
    out = np.full(uu.shape, -1.0, np.float32)
    for i, (a, b) in enumerate(zip(uu, vv)):
        lo, hi = indptr[a], indptr[a + 1]
        hit = np.nonzero(indices[lo:hi] == b)[0]
        if hit.size:
            out[i] = data[lo + hit[0]]
    return _nd.array(out)


def _make_kl_sparse_reg():
    @jax.custom_vjp
    def f(x, sparseness_target, penalty, momentum):
        return x

    def fwd(x, sparseness_target, penalty, momentum):
        # rho_hat per hidden unit (mean over the batch axis); the reference
        # keeps a momentum-smoothed estimate in aux state — here the batch
        # estimate is used directly (momentum accepted for API parity)
        rho_hat = jnp.clip(jnp.mean(x, axis=0), 1e-6, 1 - 1e-6)
        return x, (rho_hat, sparseness_target, penalty, x.shape[0])

    def bwd(res, g):
        # coerce residuals: the eager-jit invoke path can hand them back as
        # frontend array wrappers without operator overloads (JAX 0.9
        # literal handling) — jnp.asarray restores jnp semantics
        rho_hat = jnp.asarray(res[0])
        rho = jnp.asarray(res[1])
        penalty = jnp.asarray(res[2])
        n = res[3]
        g = jnp.asarray(g)
        # d/dx sum KL(rho || rho_hat(x)) with rho_hat = mean over batch:
        # (-rho/rho_hat + (1-rho)/(1-rho_hat)) / n per element
        kl_grad = (penalty / n) * (-rho / rho_hat + (1 - rho) / (1 - rho_hat))
        return (g + jnp.broadcast_to(kl_grad, g.shape),
                jnp.zeros_like(rho), jnp.zeros_like(penalty), None)

    f.defvjp(fwd, bwd)
    return f


_kl_sparse_reg = _make_kl_sparse_reg()


@register("IdentityAttachKLSparseReg", num_inputs=1)
def _identity_attach_kl_sparse_reg(data, sparseness_target=0.1, penalty=0.001,
                                   momentum=0.9):
    """Identity forward; backward adds the gradient of a KL sparsity
    penalty on batch-mean activations (src/operator/
    identity_attach_KL_sparse_reg.cc — sparse-autoencoder regularizer)."""
    return _kl_sparse_reg(data,
                          jnp.asarray(float(sparseness_target), jnp.float32),
                          jnp.asarray(float(penalty), jnp.float32),
                          float(momentum))


@register("_contrib_hawkesll", num_inputs=7, num_outputs=2)
def _hawkesll(mu, alpha, beta, lags, marks, valid_length=None,
              max_time=None):
    """Log-likelihood of a multivariate Hawkes process with exponential
    kernels (src/operator/contrib/hawkes_ll.cc).

    mu: (K,) background intensities; alpha: (K,) branching scales;
    beta: (K,) decay rates; lags: (N, T) inter-arrival times;
    marks: (N, T) int event types; valid_length: (N,) events per row;
    max_time: (N,) observation horizon.  Returns (loglik (N,), last decayed
    states (N, K)).
    """
    K = mu.shape[0]
    N, T = lags.shape
    marks = marks.astype(jnp.int32)
    vl = (jnp.full((N,), T) if valid_length is None
          else valid_length.astype(jnp.int32).reshape(-1))
    mt = (jnp.sum(lags, axis=1) if max_time is None
          else max_time.reshape(-1))

    def seq_ll(lag_row, mark_row, n_valid, horizon):
        def step(carry, inp):
            t, states, ll = carry
            dt, k, idx = inp
            # decay all states to the new event time
            states = states * jnp.exp(-beta * dt)
            lam = mu[k] + alpha[k] * beta[k] * states[k]
            valid = idx < n_valid
            ll = ll + jnp.where(valid, jnp.log(jnp.maximum(lam, 1e-30)), 0.0)
            states = states + jnp.where(valid,
                                        jax.nn.one_hot(k, K, dtype=states.dtype),
                                        jnp.zeros((K,), states.dtype))
            return (t + jnp.where(valid, dt, 0.0), states, ll), None

        init = (jnp.asarray(0.0, jnp.float32),
                jnp.zeros((K,), jnp.float32), jnp.asarray(0.0, jnp.float32))
        (t_last, states, ll), _ = jax.lax.scan(
            step, init, (lag_row.astype(jnp.float32), mark_row,
                         jnp.arange(T)))
        # compensator: integral of intensity over [0, horizon]
        # background: sum_k mu_k * horizon; excitation: for each event of
        # type k at time t_i: alpha_k * (1 - exp(-beta_k (horizon - t_i)))
        states_T = states * jnp.exp(-beta * (horizon - t_last))
        # accumulated excitation integral equals alpha_k * (n_events_k -
        # decayed remainder at horizon)
        counts = jnp.zeros((K,), jnp.float32)

        def count_step(c, inp):
            k, idx = inp
            return c + jnp.where(idx < n_valid,
                                 jax.nn.one_hot(k, K, dtype=c.dtype),
                                 jnp.zeros((K,), c.dtype)), None

        counts, _ = jax.lax.scan(count_step, counts,
                                 (mark_row, jnp.arange(T)))
        compensator = jnp.sum(mu * horizon) + jnp.sum(
            alpha * (counts - states_T))
        return ll - compensator, states_T

    lls, states = jax.vmap(seq_ll)(lags, marks, vl, mt)
    return lls, states
