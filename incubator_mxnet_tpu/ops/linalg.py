"""Linear-algebra operators (the ``la_op`` family).

Parity: ``src/operator/tensor/la_op.cc`` / ``la_op-inl.h`` — the LAPACK ops
MXNet exposes as ``mx.nd.linalg.*`` (potrf, potri, gemm, gemm2, trmm, trsm,
syrk, gelqf, syevd, sumlogdiag, extractdiag/makediag, extracttrian/maketrian,
inverse, det, slogdet) via ``src/operator/c_lapack_api.cc``.

TPU-native: every op is a jnp/lax.linalg composition — XLA lowers cholesky/
triangular-solve/qr/eigh to its native TPU implementations, and batching over
leading dims is free (the reference hand-loops LAPACK per matrix). Gradients
come from JAX's builtin JVP rules for the decompositions.
All ops operate on the last two axes with arbitrary leading batch dims.
"""
from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from .registry import register


def _tr(x, do):
    return jnp.swapaxes(x, -1, -2) if do else x


@register("_linalg_gemm", num_inputs=3, aliases=("linalg_gemm",))
def _gemm(A, B, C, transpose_a=False, transpose_b=False, alpha=1.0, beta=1.0,
          axis=-2):
    """alpha * op(A) @ op(B) + beta * C  (la_op.cc GEMM); `axis` names the
    matrix-row axis (moveaxis to -2, compute, move back)."""
    A, B, C = (jnp.moveaxis(x, axis, -2) for x in (A, B, C))
    out = alpha * jnp.matmul(_tr(A, transpose_a), _tr(B, transpose_b))
    return jnp.moveaxis(out + beta * C, -2, axis)


@register("_linalg_gemm2", num_inputs=2, aliases=("linalg_gemm2",))
def _gemm2(A, B, transpose_a=False, transpose_b=False, alpha=1.0, axis=-2):
    A, B = jnp.moveaxis(A, axis, -2), jnp.moveaxis(B, axis, -2)
    out = alpha * jnp.matmul(_tr(A, transpose_a), _tr(B, transpose_b))
    return jnp.moveaxis(out, -2, axis)


@register("_linalg_potrf", num_inputs=1, aliases=("linalg_potrf",))
def _potrf(A):
    """Cholesky factor L (lower) of a SPD matrix: A = L Lᵀ."""
    return jnp.linalg.cholesky(A)


@register("_linalg_potri", num_inputs=1, aliases=("linalg_potri",))
def _potri(A):
    """Inverse of the SPD matrix whose Cholesky factor is the input L:
    out = (L Lᵀ)⁻¹ (la_op.cc potri semantics — input is the factor)."""
    eye = jnp.broadcast_to(jnp.eye(A.shape[-1], dtype=A.dtype), A.shape)
    linv = lax.linalg.triangular_solve(A, eye, left_side=True, lower=True)
    return jnp.matmul(jnp.swapaxes(linv, -1, -2), linv)


@register("_linalg_trmm", num_inputs=2, aliases=("linalg_trmm",))
def _trmm(A, B, transpose=False, rightside=False, lower=True, alpha=1.0):
    """Triangular matrix multiply: out = alpha * op(tri(A)) @ B (or B @ op)."""
    tri = jnp.tril(A) if lower else jnp.triu(A)
    tri = _tr(tri, transpose)
    return alpha * (jnp.matmul(B, tri) if rightside else jnp.matmul(tri, B))


@register("_linalg_trsm", num_inputs=2, aliases=("linalg_trsm",))
def _trsm(A, B, transpose=False, rightside=False, lower=True, alpha=1.0):
    """Solve op(tri(A)) X = alpha B (or X op(tri(A)) = alpha B)."""
    out = lax.linalg.triangular_solve(
        A, alpha * B, left_side=not rightside, lower=lower,
        transpose_a=transpose)
    return out


@register("_linalg_syrk", num_inputs=1, aliases=("linalg_syrk",))
def _syrk(A, transpose=False, alpha=1.0):
    """alpha * A Aᵀ (or alpha * Aᵀ A when transpose)."""
    At = jnp.swapaxes(A, -1, -2)
    return alpha * (jnp.matmul(At, A) if transpose else jnp.matmul(A, At))


@register("_linalg_gelqf", num_inputs=1, num_outputs=2,
          aliases=("linalg_gelqf",))
def _gelqf(A):
    """LQ factorization A = L Q with Q orthonormal rows (m <= n)."""
    q, r = jnp.linalg.qr(jnp.swapaxes(A, -1, -2), mode="reduced")
    L = jnp.swapaxes(r, -1, -2)
    Q = jnp.swapaxes(q, -1, -2)
    # canonical form: diag(L) >= 0 (LAPACK convention used by the reference)
    d = jnp.sign(jnp.diagonal(L, axis1=-2, axis2=-1))
    d = jnp.where(d == 0, 1.0, d).astype(A.dtype)
    return L * d[..., None, :], Q * d[..., :, None]


@register("_linalg_syevd", num_inputs=1, num_outputs=2,
          aliases=("linalg_syevd",))
def _syevd(A):
    """Symmetric eigendecomposition: A = Uᵀ diag(L) U (rows of U are the
    eigenvectors, la_op.cc syevd convention)."""
    w, v = jnp.linalg.eigh(A)
    return jnp.swapaxes(v, -1, -2), w


@register("_linalg_sumlogdiag", num_inputs=1, aliases=("linalg_sumlogdiag",))
def _sumlogdiag(A):
    return jnp.sum(jnp.log(jnp.diagonal(A, axis1=-2, axis2=-1)), axis=-1)


@register("_linalg_extractdiag", num_inputs=1, aliases=("linalg_extractdiag",))
def _extractdiag(A, offset=0):
    return jnp.diagonal(A, offset=offset, axis1=-2, axis2=-1)


@register("_linalg_makediag", num_inputs=1, aliases=("linalg_makediag",))
def _makediag(d, offset=0):
    base = jnp.zeros(d.shape[:-1] + (d.shape[-1] + abs(offset),) * 2, d.dtype)
    idx = jnp.arange(d.shape[-1])
    r, c = (idx, idx + offset) if offset >= 0 else (idx - offset, idx)
    return base.at[..., r, c].set(d)


@register("_linalg_extracttrian", num_inputs=1, aliases=("linalg_extracttrian",))
def _extracttrian(A, offset=0, lower=True):
    n = A.shape[-1]
    rows, cols = jnp.tril_indices(n, k=offset) if lower else \
        jnp.triu_indices(n, k=offset)
    return A[..., rows, cols]


@register("_linalg_maketrian", num_inputs=1, aliases=("linalg_maketrian",))
def _maketrian(d, offset=0, lower=True):
    # infer n from packed length: len = n(n+1)/2 shifted by offset
    ln = d.shape[-1]
    n = 0
    while _packed_len(n, offset, lower) < ln:
        n += 1
    rows, cols = jnp.tril_indices(n, k=offset) if lower else \
        jnp.triu_indices(n, k=offset)
    base = jnp.zeros(d.shape[:-1] + (n, n), d.dtype)
    return base.at[..., rows, cols].set(d)


def _packed_len(n, offset, lower):
    import numpy as _np

    r, _ = (_np.tril_indices(n, k=offset) if lower
            else _np.triu_indices(n, k=offset))
    return len(r)


@register("_linalg_inverse", num_inputs=1, aliases=("linalg_inverse",))
def _inverse(A):
    return jnp.linalg.inv(A)


@register("_linalg_det", num_inputs=1, aliases=("linalg_det",))
def _det(A):
    return jnp.linalg.det(A)


@register("_linalg_slogdet", num_inputs=1, num_outputs=2,
          aliases=("linalg_slogdet",))
def _slogdet(A):
    sign, logabs = jnp.linalg.slogdet(A)
    return sign, logabs


@register("_npi_einsum", num_inputs=None, aliases=("einsum",))
def _einsum(*operands, subscripts=""):
    return jnp.einsum(subscripts, *operands)


@register("_npi_tensordot", num_inputs=2, aliases=("tensordot",))
def _tensordot(a, b, axes=2):
    if isinstance(axes, (list, tuple)):
        axes = tuple(tuple(x) if isinstance(x, (list, tuple)) else x
                     for x in axes)
    return jnp.tensordot(a, b, axes=axes)
