"""Fused RNN operator (rnn_relu / rnn_tanh / lstm / gru).

Parity: the reference's fused ``RNN`` op (``src/operator/rnn-inl.h:56``,
cuDNN path ``rnn.cu``, CPU fused ``rnn_impl.h``).  TPU-native: one
``lax.scan`` per layer/direction — XLA compiles the whole recurrence into a
single fused loop on-device, which is this hardware's analog of the cuDNN
fused kernel.

Parameter packing (flat vector, matching the reference's layout contract:
per layer, per direction: i2h weights, h2h weights, then at the very end all
biases in the same order):  gate order is i,f,g,o for LSTM and r,z,n for GRU
(reference convention, rnn-inl.h).
"""
from __future__ import annotations

import functools
from typing import List, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register

__all__ = ["rnn_param_size", "rnn_cell_step", "rnn_layer_scan"]

_GATES = {"rnn_relu": 1, "rnn_tanh": 1, "lstm": 4, "gru": 3}


def rnn_param_size(num_layers, input_size, state_size, mode="lstm",
                   bidirectional=False):
    """Total flat parameter count (reference GetRnnParamSize semantics)."""
    ngates = _GATES[mode]
    ndir = 2 if bidirectional else 1
    size = 0
    for layer in range(num_layers):
        in_sz = input_size if layer == 0 else state_size * ndir
        size += ndir * ngates * state_size * (in_sz + state_size  # weights
                                              + 2)  # two bias vectors
    return size


def _unpack_params(params, num_layers, input_size, state_size, mode, ndir):
    """Split the flat vector into per-(layer,dir) (Wx, Wh, bx, bh)."""
    ngates = _GATES[mode]
    out = []
    offset = 0
    # weights first, then biases — matching the packed layout contract
    for layer in range(num_layers):
        in_sz = input_size if layer == 0 else state_size * ndir
        for d in range(ndir):
            wx_n = ngates * state_size * in_sz
            wh_n = ngates * state_size * state_size
            wx = params[offset:offset + wx_n].reshape(ngates * state_size, in_sz)
            offset += wx_n
            wh = params[offset:offset + wh_n].reshape(ngates * state_size,
                                                      state_size)
            offset += wh_n
            out.append([wx, wh, None, None])
    i = 0
    for layer in range(num_layers):
        for d in range(ndir):
            b_n = ngates * state_size
            out[i][2] = params[offset:offset + b_n]
            offset += b_n
            out[i][3] = params[offset:offset + b_n]
            offset += b_n
            i += 1
    return [tuple(o) for o in out]


def rnn_cell_step(mode, x, states, wx, wh, bx, bh):
    """One timestep. states: (h,) or (h, c). Returns (out, new_states)."""
    h = states[0]
    gates = x @ wx.T + h @ wh.T + bx + bh
    hidden = wh.shape[-1]
    if mode == "rnn_relu":
        h2 = jnp.maximum(gates, 0)
        return h2, (h2,)
    if mode == "rnn_tanh":
        h2 = jnp.tanh(gates)
        return h2, (h2,)
    if mode == "lstm":
        c = states[1]
        i, f, g, o = (gates[..., k * hidden:(k + 1) * hidden] for k in range(4))
        i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
        g = jnp.tanh(g)
        c2 = f * c + i * g
        h2 = o * jnp.tanh(c2)
        return h2, (h2, c2)
    if mode == "gru":
        # gru needs separate bias application for the candidate gate
        gx = x @ wx.T + bx
        gh = h @ wh.T + bh
        r = jax.nn.sigmoid(gx[..., :hidden] + gh[..., :hidden])
        z = jax.nn.sigmoid(gx[..., hidden:2 * hidden] + gh[..., hidden:2 * hidden])
        n = jnp.tanh(gx[..., 2 * hidden:] + r * gh[..., 2 * hidden:])
        h2 = (1 - z) * n + z * h
        return h2, (h2,)
    raise ValueError(mode)


def rnn_layer_scan(mode, data, h0, c0, wx, wh, bx, bh, reverse=False):
    """Scan one layer/direction over time. data: (seq, batch, in)."""
    init = (h0,) if mode != "lstm" else (h0, c0)

    def step(carry, x):
        out, new = rnn_cell_step(mode, x, carry, wx, wh, bx, bh)
        return new, out

    carry, outs = lax.scan(step, init, data, reverse=reverse)
    return outs, carry


@register("RNN", needs_rng=True)
def _rnn(data, parameters, state, state_cell=None, state_size=None,
         num_layers=1, mode="lstm", bidirectional=False, p=0.0,
         state_outputs=False, projection_size=None, use_sequence_length=False,
         sequence_length=None, lstm_state_clip_min=None,
         lstm_state_clip_max=None, lstm_state_clip_nan=False, key=None):
    """Fused multi-layer (bi)RNN.

    data: (seq, batch, input).  state: (num_layers*ndir, batch, hidden).
    Outputs: out (seq, batch, hidden*ndir) [+ final h [+ final c for lstm]]
    when state_outputs.
    """
    ndir = 2 if bidirectional else 1
    state_size = int(state_size)
    num_layers = int(num_layers)
    layers = _unpack_params(parameters, num_layers, data.shape[-1],
                            state_size, mode, ndir)
    from . import nn as _opsnn

    train = _opsnn._is_train()

    x = data
    h_finals: List = []
    c_finals: List = []
    idx = 0
    for layer in range(num_layers):
        outs_dirs = []
        for d in range(ndir):
            wx, wh, bx, bh = layers[idx]
            s = layer * ndir + d
            batch = data.shape[1]
            h0 = state[s].astype(data.dtype)
            if h0.shape[0] != batch:  # batch-1 begin_state (legacy mx.rnn)
                h0 = jnp.broadcast_to(h0, (batch,) + h0.shape[1:])
            c0 = state_cell[s].astype(data.dtype) \
                if (mode == "lstm" and state_cell is not None) \
                else jnp.zeros_like(h0)
            if c0.shape[0] != batch:
                c0 = jnp.broadcast_to(c0, (batch,) + c0.shape[1:])
            outs, carry = rnn_layer_scan(mode, x, h0, c0, wx, wh, bx, bh,
                                         reverse=(d == 1))
            outs_dirs.append(outs)
            h_finals.append(carry[0])
            if mode == "lstm":
                c_finals.append(carry[1])
            idx += 1
        x = outs_dirs[0] if ndir == 1 else jnp.concatenate(outs_dirs, axis=-1)
        if train and p > 0 and layer < num_layers - 1 and key is not None:
            mask = jax.random.bernoulli(jax.random.fold_in(key, layer),
                                        1.0 - p, x.shape)
            x = jnp.where(mask, x / (1.0 - p), 0.0).astype(x.dtype)

    out = x
    if not state_outputs:
        return out
    h_out = jnp.stack(h_finals, axis=0)
    if mode == "lstm":
        c_out = jnp.stack(c_finals, axis=0)
        return out, h_out, c_out
    return out, h_out
