"""Neural-network operators: conv, pooling, norm, dense, dropout, softmax-loss.

Parity: ``src/operator/nn/*`` (Convolution convolution.cc:399, BatchNorm
batch_norm.cc:493, Pooling pooling.cc:365, FullyConnected
fully_connected.cc:258, softmax.cc, dropout, LayerNorm/GroupNorm/InstanceNorm,
LRN, Activation, UpSampling) plus ``softmax_output.cc`` and ``leaky_relu``.

TPU-native: every op is a pure jnp/lax function that XLA lowers onto the
MXU (convs/matmuls) and fuses elementwise tails into.  There is no cuDNN-style
wrapper layer: `lax.conv_general_dilated` / `reduce_window` ARE the fused
kernels.  Layouts follow the reference's NCHW default for API parity; XLA
re-layouts internally for the TPU's native tiling.
"""
from __future__ import annotations

import functools
import itertools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .registry import OPS, register


def _is_train():
    from .. import autograd, tracing

    tc = tracing.current_trace()
    if tc is not None:
        return tc.training
    return autograd.is_training()


# ---------------------------------------------------------------------------
# FullyConnected (fully_connected.cc:258-348)
# ---------------------------------------------------------------------------


@register("FullyConnected", aliases=("fully_connected",))
def _fully_connected(data, weight, bias=None, num_hidden=None, no_bias=False,
                     flatten=True):
    if flatten:
        x = data.reshape(data.shape[0], -1)
    else:
        x = data
    # weight: (num_hidden, input_dim) — reference layout
    out = jnp.matmul(x, weight.T)
    if bias is not None and not no_bias:
        out = out + bias
    return out


# ---------------------------------------------------------------------------
# Convolution / Deconvolution (convolution.cc, deconvolution.cc)
# ---------------------------------------------------------------------------


def _conv_dims(ndim, layout):
    """Build lax dimension_numbers for NC* layouts (1/2/3 spatial dims)."""
    if layout is None or layout.startswith("NC"):
        lhs = "NC" + "DHW"[3 - (ndim - 2):]
        return (lhs, "OI" + "DHW"[3 - (ndim - 2):], lhs)
    if layout in ("NWC", "NHWC", "NDHWC"):
        spatial = layout[1:-1]
        return (layout, "O" + spatial + "I", layout)
    raise ValueError("unsupported conv layout %r" % layout)


@register("Convolution", aliases=("conv",))
def _convolution(data, weight, bias=None, kernel=None, stride=None, dilate=None,
                 pad=None, num_filter=None, num_group=1, workspace=1024,
                 no_bias=False, cudnn_tune=None, cudnn_off=False, layout=None):
    nspatial = data.ndim - 2
    stride = tuple(stride) if stride else (1,) * nspatial
    dilate = tuple(dilate) if dilate else (1,) * nspatial
    pad = tuple(pad) if pad else (0,) * nspatial
    dn = lax.conv_dimension_numbers(data.shape, weight.shape,
                                    _conv_dims(data.ndim, layout))
    out = lax.conv_general_dilated(
        data, weight,
        window_strides=stride,
        padding=[(p, p) for p in pad],
        rhs_dilation=dilate,
        dimension_numbers=dn,
        feature_group_count=int(num_group),
    )
    if bias is not None and not no_bias:
        if layout in ("NWC", "NHWC", "NDHWC"):
            out = out + bias
        else:
            out = out + bias.reshape((1, -1) + (1,) * nspatial)
    return out


@register("Deconvolution", aliases=("deconv",))
def _deconvolution(data, weight, bias=None, kernel=None, stride=None, dilate=None,
                   pad=None, adj=None, target_shape=None, num_filter=None,
                   num_group=1, workspace=512, no_bias=True, cudnn_tune=None,
                   cudnn_off=False, layout=None):
    nspatial = data.ndim - 2
    stride = tuple(stride) if stride else (1,) * nspatial
    dilate = tuple(dilate) if dilate else (1,) * nspatial
    pad = tuple(pad) if pad else (0,) * nspatial
    # weight layout (in_channels, out_channels/group, *kernel) — reference
    dn = lax.conv_dimension_numbers(data.shape, weight.shape,
                                    _conv_dims(data.ndim, layout))
    out = lax.conv_general_dilated(
        data, jnp.flip(weight, axis=tuple(range(2, weight.ndim))).swapaxes(0, 1)
        if num_group == 1 else weight,
        window_strides=(1,) * nspatial,
        padding=[(d * (k - 1) - p, d * (k - 1) - p + a)
                 for k, p, d, a in zip(weight.shape[2:], pad, dilate,
                                       tuple(adj) if adj else (0,) * nspatial)],
        lhs_dilation=stride,
        rhs_dilation=dilate,
        dimension_numbers=dn,
        feature_group_count=int(num_group),
    )
    if bias is not None and not no_bias:
        out = out + bias.reshape((1, -1) + (1,) * nspatial)
    return out


# ---------------------------------------------------------------------------
# Pooling (pooling.cc:365)
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3, 4))
def _maxpool_sws_impl(data, window, strides, padding, in_shape):
    return lax.reduce_window(data, -jnp.inf, lax.max, window, strides, padding)


def _maxpool_sws(data, window, strides, padding):
    return _maxpool_sws_impl(data, window, strides, padding,
                             tuple(data.shape))


def _maxpool_sws_fwd(data, window, strides, padding, in_shape):
    from ..parallel import maxpool_idx

    p = maxpool_idx.plan(in_shape, data.dtype.itemsize, window, strides,
                         padding)
    if p is not None:
        # argmax-carrying forward (parallel/maxpool_idx.py): the winner
        # offset rides out of the pooling pass as a 1-byte plane, so
        # the backward never re-reads data/out to rediscover it — at
        # 224 px that re-read was the stem ghost-BN output, the GL202
        # census' sole remaining multi-pass tensor
        out, first = maxpool_idx.maxpool_with_index(data, window, strides,
                                                    padding, p)
        return out, (first,)
    out = _maxpool_sws_impl(data, window, strides, padding, in_shape)
    return out, (data, out)


def shifted_window_unpool(data, out, g, window, strides, padding,
                          _shift_mask=0):
    """Shifted-window mask max-pool backward: route ``g`` to the FIRST
    argmax of each window (row-major scan order) with a handful of
    fused elementwise passes instead of XLA's ``select-and-scatter``.

    One shifted strided view of the padded input per in-window offset:
    position p of the padded input contributes to window w iff
    p = w*stride + offset.  The reference's active Pooling backward
    (pool.h unpool_max_*_cpu) routes the WHOLE gradient to a single
    argmax — the first match in row-major window scan order, which is
    also ``select_and_scatter_add``'s GE-select tie rule, so the result
    is BIT-exact vs XLA's own gradient (post-ReLU zero ties are common;
    giving every tie the full gradient would inflate dX by the tie
    count).  Shared by the model-level ``_maxpool_sws`` custom VJP and
    the ``maxpool_bwd_mask`` graftpass (analysis/passes.py).

    ``_shift_mask`` is a test-only fault knob: a non-zero value offsets
    the winner index, deliberately mis-routing the gradient — the
    GL301 contract probe must refuse such a mask.
    """
    neg = np.asarray(-jnp.inf, data.dtype)[()]
    xp = lax.pad(data, neg, [(lo, hi, 0) for lo, hi in padding])
    offsets = list(itertools.product(*[range(k) for k in window]))
    noff = len(offsets)
    views = []
    first = jnp.full(out.shape, noff, jnp.int32)
    for lin, offset in enumerate(offsets):
        # (out-1)*stride + window <= padded dim by reduce_window's output
        # formula, so every shifted view is in bounds
        limit = [o + (y - 1) * s + 1
                 for o, y, s in zip(offset, out.shape, strides)]
        xs = lax.slice(xp, offset, limit, strides)
        views.append((offset, limit))
        first = jnp.minimum(first, jnp.where(xs == out, jnp.int32(lin),
                                             jnp.int32(noff)))
    if _shift_mask:
        first = (first + jnp.int32(_shift_mask)) % jnp.int32(noff)
    dxp = jnp.zeros(xp.shape, g.dtype)
    for lin, (offset, limit) in enumerate(views):
        contrib = jnp.where(first == lin, g, jnp.zeros((), g.dtype))
        dxp = dxp + lax.pad(contrib, np.asarray(0, g.dtype)[()], [
            (o, d - l, s - 1)
            for o, d, l, s in zip(offset, xp.shape, limit, strides)])
    dx = lax.slice(dxp, [lo for lo, _ in padding],
                   [d - hi for d, (_, hi) in zip(xp.shape, padding)])
    return dx.astype(data.dtype)


def _maxpool_sws_bwd(window, strides, padding, in_shape, res, g):
    if len(res) == 1:
        from ..parallel import maxpool_idx

        (first,) = res
        return (maxpool_idx.indexed_unpool(first, g, in_shape, window,
                                           strides, padding),)
    data, out = res
    return (shifted_window_unpool(data, out, g, window, strides, padding),)


_maxpool_sws_impl.defvjp(_maxpool_sws_fwd, _maxpool_sws_bwd)


@register("Pooling", aliases=("pool",))
def _pooling(data, kernel=None, pool_type="max", global_pool=False,
             cudnn_off=False, pooling_convention="valid", stride=None, pad=None,
             p_value=2, count_include_pad=True, layout=None):
    nspatial = data.ndim - 2
    if global_pool:
        axes = tuple(range(2, data.ndim))
        if pool_type == "max":
            return jnp.max(data, axis=axes, keepdims=True)
        if pool_type in ("avg", "sum"):
            red = jnp.sum if pool_type == "sum" else jnp.mean
            return red(data, axis=axes, keepdims=True)
        if pool_type == "lp":
            return jnp.power(jnp.sum(jnp.power(jnp.abs(data), p_value), axis=axes,
                                     keepdims=True), 1.0 / p_value)
    kernel = tuple(kernel)
    stride = tuple(stride) if stride else (1,) * nspatial
    pad = tuple(pad) if pad else (0,) * nspatial
    window = (1, 1) + kernel
    strides = (1, 1) + stride
    if pooling_convention == "full":
        # ceil-mode output: pad high edge enough for ceil division
        padding = [(0, 0), (0, 0)] + [
            (p, p + s - 1) for p, s in zip(pad, stride)
        ]
    else:
        padding = [(0, 0), (0, 0)] + [(p, p) for p in pad]

    # NB: scalar init values keep the reduce recognizable as the max/add
    # monoid so XLA uses the dedicated (differentiable) pooling primitives.
    if pool_type == "max":
        # init must carry the operand dtype (an int-typed pool — e.g. the
        # int8 inference path — rejects a python-int/int64 init)
        if jnp.issubdtype(data.dtype, jnp.floating):
            # custom VJP: XLA's autodiff of reduce_window-max is
            # select-and-scatter, which is slow on TPU (1.5 ms/step in the
            # ResNet-50 profile, docs/PERF.md).  The shifted-window mask
            # backward is a handful of fused elementwise passes and
            # matches the reference's active unpool semantics (pool.h
            # unpool_max_*_cpu: the whole gradient goes to the first
            # argmax in window scan order, not to every tie).
            return _maxpool_sws(data, window, strides, tuple(padding))
        init = np.asarray(jnp.iinfo(data.dtype).min, data.dtype)[()]
        return lax.reduce_window(data, init, lax.max, window, strides, padding)
    if pool_type in ("avg", "sum"):
        summed = lax.reduce_window(data, 0.0 if jnp.issubdtype(
            data.dtype, jnp.floating) else 0, lax.add, window, strides, padding)
        if pool_type == "sum":
            return summed
        if count_include_pad:
            return summed / np.prod(kernel)
        ones = jnp.ones_like(data)
        counts = lax.reduce_window(ones, 0.0, lax.add, window, strides, padding)
        return summed / counts
    if pool_type == "lp":
        powed = lax.reduce_window(jnp.power(jnp.abs(data), p_value), 0.0,
                                  lax.add, window, strides, padding)
        return jnp.power(powed, 1.0 / p_value)
    raise ValueError("unknown pool_type %r" % pool_type)


# ---------------------------------------------------------------------------
# Normalization (batch_norm.cc:493, layer_norm.cc, group_norm.cc, ...)
# ---------------------------------------------------------------------------


@register("BatchNorm", aliases=("batch_norm",))
def _batch_norm(data, gamma, beta, moving_mean, moving_var, eps=1e-3,
                momentum=0.9, fix_gamma=True, use_global_stats=False,
                output_mean_var=False, axis=1, cudnn_off=False):
    """Normalize over all axes except ``axis``.

    Training (and not use_global_stats): batch statistics; otherwise moving
    stats.  Running-stat *updates* are the caller's job (gluon layer /
    executor aux-write) — this fn is pure.
    """
    red_axes = tuple(i for i in range(data.ndim) if i != axis)
    bshape = [1] * data.ndim
    bshape[axis] = data.shape[axis]
    use_batch = _is_train() and not use_global_stats
    if use_batch:
        # single-pass stats (E[x], E[x^2] in one read of the activation —
        # jnp.var would re-read it for the deviation pass); f32 accumulation
        # keeps bf16 inputs well-conditioned
        x32 = data.astype(jnp.float32)
        mean = jnp.mean(x32, axis=red_axes)
        var = jnp.maximum(
            jnp.mean(jnp.square(x32), axis=red_axes) - jnp.square(mean), 0.0)
    else:
        mean, var = moving_mean, moving_var
    g = jnp.ones_like(gamma) if fix_gamma else gamma
    inv = lax.rsqrt(var.astype(jnp.float32) + eps)
    scale = (g.astype(jnp.float32) * inv).reshape(bshape)
    shift = (beta.astype(jnp.float32) - mean.astype(jnp.float32) * g.astype(jnp.float32) * inv).reshape(bshape)
    out = (data.astype(jnp.float32) * scale + shift).astype(data.dtype)
    if output_mean_var:
        return out, mean, var
    return out


@register("batch_norm_stats", num_inputs=1, differentiable=False)
def _batch_norm_stats(data, axis=1):
    """Helper (not in reference): batch mean/var for running-stat updates."""
    red_axes = tuple(i for i in range(data.ndim) if i != axis)
    x = data.astype(jnp.float32)
    mean = jnp.mean(x, axis=red_axes)
    # same single-pass form as the BatchNorm body so whole-graph CSE folds
    # the two computations into one reduction
    var = jnp.maximum(
        jnp.mean(jnp.square(x), axis=red_axes) - jnp.square(mean), 0.0)
    return mean, var


def _batch_norm_aux_update(in_vals, out_vals, momentum=0.9, axis=1,
                           use_global_stats=False, **_):
    """Running-stat update for BatchNorm's mutated inputs (moving_mean=3,
    moving_var=4) — the single source of the momentum math shared by the
    gluon layer, TrainStep and the symbolic Executor
    (``src/operator/nn/batch_norm.cc`` stateful forward)."""
    if use_global_stats and str(use_global_stats).lower() != "false":
        return {}
    mean, var = _batch_norm_stats(in_vals[0], axis=int(axis))
    m = float(momentum)
    old_m, old_v = in_vals[3], in_vals[4]
    return {3: (m * old_m.astype(jnp.float32)
                + (1 - m) * mean).astype(old_m.dtype),
            4: (m * old_v.astype(jnp.float32)
                + (1 - m) * var).astype(old_v.dtype)}


OPS["BatchNorm"].aux_update = _batch_norm_aux_update
OPS["BatchNorm"].mutate_idx = (3, 4)


def _ghost_bn_common(data, residual, gamma, beta, moving_mean, moving_var,
                     eps, group, act="relu", donate_residual=False):
    """Shared body for the fused ghost-BN ops.  Training: Pallas fused
    kernel (parallel/fused_bn.py) with group statistics; eval: moving-stat
    normalize (+add) (+relu) as plain jnp (XLA fuses it fine)."""
    if _is_train():
        from ..parallel.fused_bn import ghost_bn_act, ghost_bn_stats_merge

        out, m, v = ghost_bn_act(data, gamma.astype(jnp.float32),
                                 beta.astype(jnp.float32),
                                 residual=residual, eps=eps, act=act,
                                 group=group,
                                 donate_residual=donate_residual)
        bm, bv = ghost_bn_stats_merge(m, v)
        return out, bm, bv
    inv = lax.rsqrt(moving_var.astype(jnp.float32) + eps)
    g32 = gamma.astype(jnp.float32)
    scale = (g32 * inv).reshape(1, -1, 1, 1)
    shift = (beta.astype(jnp.float32)
             - moving_mean.astype(jnp.float32) * g32 * inv).reshape(1, -1, 1, 1)
    y = data.astype(jnp.float32) * scale + shift
    if residual is not None:
        y = y + residual.astype(jnp.float32)
    if act == "relu":
        y = jnp.maximum(y, 0.0)
    return (y.astype(data.dtype),
            moving_mean.astype(jnp.float32), moving_var.astype(jnp.float32))


@register("_contrib_GhostBNReLU", num_inputs=5, num_outputs=3,
          mutate_idx=(3, 4))
def _ghost_bn_relu(data, gamma, beta, moving_mean, moving_var, eps=1e-3,
                   momentum=0.9, group=0):
    """Fused ghost-BN + ReLU (TPU Pallas; see parallel/fused_bn.py).

    Outputs: (out, batch_mean, batch_var) — stats feed the running-average
    aux update like BatchNorm's (``src/operator/nn/batch_norm.cc:493``
    stateful forward), with group (ghost) statistics in training.
    """
    return _ghost_bn_common(data, None, gamma, beta, moving_mean, moving_var,
                            float(eps), int(group))


@register("_contrib_GhostBNAddReLU", num_inputs=6, num_outputs=3,
          mutate_idx=(4, 5))
def _ghost_bn_add_relu(data, residual, gamma, beta, moving_mean, moving_var,
                       eps=1e-3, momentum=0.9, group=0, donate_residual=0):
    """Fused ghost-BN + residual add + ReLU (the bottleneck-exit pattern).

    ``donate_residual=1`` declares the residual tensor dead after this
    op (a downsample-shortcut output, consumed by nothing else): the
    Pallas fwd writes Y over its VMEM window, which is what lets the
    56x56x256 block-0 exits fuse at batch 256.  NEVER set it for an
    identity shortcut — the surrounding program still reads that
    tensor.
    """
    return _ghost_bn_common(data, residual, gamma, beta, moving_mean,
                            moving_var, float(eps), int(group),
                            donate_residual=bool(int(donate_residual)))


@register("_contrib_GhostBNAddReLUDual", num_inputs=6, num_outputs=4,
          mutate_idx=(4, 5))
def _ghost_bn_add_relu_dual(data, residual, gamma, beta, moving_mean,
                            moving_var, eps=1e-3, momentum=0.9, group=0,
                            donate_residual=0):
    """Dual-output fused ghost-BN + residual add + ReLU.

    Outputs ``(out, out_sc, batch_mean, batch_var)`` where ``out_sc`` is
    the SAME tensor as ``out`` exposed in a second output position: a
    block exit routes the next block's conv path through ``out`` and its
    shortcut through ``out_sc``, so autodiff delivers the two cotangents
    separately and the fused bwd kernel sums them on the VMEM window
    load — the residual-join add_any (read 2x + write of a full exit
    tensor per block) disappears from the step program (docs/PERF.md
    round 20).  Same statistics, aux protocol and ``donate_residual``
    semantics as ``_contrib_GhostBNAddReLU``.
    """
    if _is_train():
        from ..parallel.fused_bn import ghost_bn_act, ghost_bn_stats_merge

        out, out_sc, m, v = ghost_bn_act(
            data, gamma.astype(jnp.float32), beta.astype(jnp.float32),
            residual=residual, eps=float(eps), act="relu", group=int(group),
            donate_residual=bool(int(donate_residual)), dual_out=True)
        bm, bv = ghost_bn_stats_merge(m, v)
        return out, out_sc, bm, bv
    out, bm, bv = _ghost_bn_common(
        data, residual, gamma, beta, moving_mean, moving_var, float(eps),
        int(group), donate_residual=bool(int(donate_residual)))
    return out, out, bm, bv


@register("_contrib_GhostBN", num_inputs=5, num_outputs=3,
          mutate_idx=(3, 4))
def _ghost_bn_noact(data, gamma, beta, moving_mean, moving_var, eps=1e-3,
                    momentum=0.9, group=0):
    """Fused ghost-BN WITHOUT activation (the downsample-branch BN: a
    1x1-conv shortcut is normalized but not rectified).  Same group
    statistics and aux protocol as ``_contrib_GhostBNReLU``."""
    return _ghost_bn_common(data, None, gamma, beta, moving_mean,
                            moving_var, float(eps), int(group), act="none")


@register("_contrib_GhostBNReLUNS", num_inputs=3, num_outputs=1)
def _ghost_bn_relu_nostats(data, gamma, beta, eps=1e-3, group=0):
    """Stats-free fused ghost-BN + ReLU: no running-stat aux state at
    all (the pipeline-parallel form — aux writes cannot escape the
    pipelined scan, so a pipelined stage must carry none).  Normalizes
    with ghost batch statistics in EVERY mode; eval-time consumers that
    need moving averages want the stateful op instead."""
    return _ghost_bn_nostats_common(data, gamma, beta, eps, group, "relu")


@register("_contrib_GhostBNNS", num_inputs=3, num_outputs=1)
def _ghost_bn_nostats(data, gamma, beta, eps=1e-3, group=0):
    """Stats-free fused ghost-BN WITHOUT activation (the pipelined
    downsample-branch form: normalized, never rectified, no aux
    state)."""
    return _ghost_bn_nostats_common(data, gamma, beta, eps, group, "none")


def _ghost_bn_nostats_common(data, gamma, beta, eps, group, act):
    from ..parallel.fused_bn import ghost_bn_act

    out, _, _ = ghost_bn_act(data, gamma.astype(jnp.float32),
                             beta.astype(jnp.float32), eps=float(eps),
                             act=act, group=int(group))
    return out


def _ghost_bn_aux_update(in_vals, out_vals, momentum=0.9, **_):
    m = float(momentum)
    base = 3 if len(in_vals) == 5 else 4
    old_m, old_v = in_vals[base], in_vals[base + 1]
    return {base: (m * old_m.astype(jnp.float32)
                   + (1 - m) * out_vals[1]).astype(old_m.dtype),
            base + 1: (m * old_v.astype(jnp.float32)
                       + (1 - m) * out_vals[2]).astype(old_v.dtype)}


def _ghost_bn_aux_update_dual(in_vals, out_vals, momentum=0.9, **_):
    # dual op output layout is (out, out_sc, bm, bv) — drop the extra
    # output position so the shared formula sees (out, bm, bv)
    return _ghost_bn_aux_update(in_vals,
                                (out_vals[0],) + tuple(out_vals[2:]),
                                momentum=momentum)


OPS["_contrib_GhostBNReLU"].aux_update = _ghost_bn_aux_update
OPS["_contrib_GhostBNAddReLU"].aux_update = _ghost_bn_aux_update
OPS["_contrib_GhostBNAddReLUDual"].aux_update = _ghost_bn_aux_update_dual
OPS["_contrib_GhostBN"].aux_update = _ghost_bn_aux_update


@register("LayerNorm", aliases=("layer_norm",))
def _layer_norm(data, gamma, beta, axis=-1, eps=1e-5, output_mean_var=False):
    mean = jnp.mean(data.astype(jnp.float32), axis=axis, keepdims=True)
    var = jnp.var(data.astype(jnp.float32), axis=axis, keepdims=True)
    inv = lax.rsqrt(var + eps)
    norm = (data.astype(jnp.float32) - mean) * inv
    bshape = [1] * data.ndim
    bshape[axis] = data.shape[axis]
    out = (norm * gamma.astype(jnp.float32).reshape(bshape)
           + beta.astype(jnp.float32).reshape(bshape)).astype(data.dtype)
    if output_mean_var:
        return out, jnp.squeeze(mean, axis), jnp.squeeze(inv, axis)
    return out


@register("GroupNorm", aliases=("group_norm",))
def _group_norm(data, gamma, beta, num_groups=1, eps=1e-5, output_mean_var=False):
    n, c = data.shape[:2]
    g = int(num_groups)
    x = data.reshape((n, g, c // g) + data.shape[2:]).astype(jnp.float32)
    red = tuple(range(2, x.ndim))
    mean = jnp.mean(x, axis=red, keepdims=True)
    var = jnp.var(x, axis=red, keepdims=True)
    norm = ((x - mean) * lax.rsqrt(var + eps)).reshape(data.shape)
    bshape = (1, c) + (1,) * (data.ndim - 2)
    out = (norm * gamma.astype(jnp.float32).reshape(bshape)
           + beta.astype(jnp.float32).reshape(bshape)).astype(data.dtype)
    if output_mean_var:
        return out, mean.reshape(n, g), var.reshape(n, g)
    return out


@register("InstanceNorm", aliases=("instance_norm",))
def _instance_norm(data, gamma, beta, eps=1e-3):
    red = tuple(range(2, data.ndim))
    x = data.astype(jnp.float32)
    mean = jnp.mean(x, axis=red, keepdims=True)
    var = jnp.var(x, axis=red, keepdims=True)
    norm = (x - mean) * lax.rsqrt(var + eps)
    bshape = (1, data.shape[1]) + (1,) * (data.ndim - 2)
    return (norm * gamma.reshape(bshape) + beta.reshape(bshape)).astype(data.dtype)


@register("L2Normalization", aliases=("l2_normalization",), num_inputs=1)
def _l2_normalization(data, eps=1e-10, mode="instance"):
    x = data.astype(jnp.float32)
    if mode == "instance":
        norm = jnp.sqrt(jnp.sum(jnp.square(x.reshape(x.shape[0], -1)), axis=1))
        norm = norm.reshape((-1,) + (1,) * (data.ndim - 1))
    elif mode == "channel":
        norm = jnp.sqrt(jnp.sum(jnp.square(x), axis=1, keepdims=True))
    elif mode == "spatial":
        norm = jnp.sqrt(jnp.sum(jnp.square(x.reshape(x.shape[0], x.shape[1], -1)),
                                axis=2)).reshape(x.shape[:2] + (1,) * (data.ndim - 2))
    else:
        raise ValueError(mode)
    return (x / (norm + eps)).astype(data.dtype)


@register("LRN", aliases=("lrn",), num_inputs=1)
def _lrn(data, alpha=1e-4, beta=0.75, knorm=2.0, nsize=5):
    x = data.astype(jnp.float32)
    sq = jnp.square(x)
    half = nsize // 2
    padded = jnp.pad(sq, [(0, 0), (half, half), (0, 0), (0, 0)])
    win = sum(padded[:, i:i + x.shape[1]] for i in range(nsize))
    return (x / jnp.power(knorm + alpha * win / nsize, beta)).astype(data.dtype)


# ---------------------------------------------------------------------------
# Activations (activation.cc, leaky_relu.cc)
# ---------------------------------------------------------------------------


@register("Activation", num_inputs=1)
def _activation(data, act_type="relu"):
    if act_type == "relu":
        return jnp.maximum(data, 0)
    if act_type == "sigmoid":
        return jax.nn.sigmoid(data)
    if act_type == "tanh":
        return jnp.tanh(data)
    if act_type == "softrelu":
        return jax.nn.softplus(data)
    if act_type == "softsign":
        return jax.nn.soft_sign(data)
    raise ValueError("unknown act_type %r" % act_type)


@register("LeakyReLU", needs_rng=True)
def _leaky_relu(data, gamma=None, act_type="leaky", slope=0.25, lower_bound=0.125,
                upper_bound=0.334, key=None):
    if act_type == "leaky":
        return jnp.where(data >= 0, data, slope * data)
    if act_type == "prelu":
        g = gamma.reshape((1, -1) + (1,) * (data.ndim - 2)) if gamma.ndim == 1 and data.ndim > 2 else gamma
        return jnp.where(data >= 0, data, g * data)
    if act_type == "elu":
        return jnp.where(data >= 0, data, slope * jnp.expm1(data))
    if act_type == "selu":
        alpha, scale = 1.6732632423543772, 1.0507009873554805
        return scale * jnp.where(data >= 0, data, alpha * jnp.expm1(data))
    if act_type == "gelu":
        return jax.nn.gelu(data, approximate=False)
    if act_type == "rrelu":
        if _is_train():
            s = jax.random.uniform(key, data.shape, jnp.float32, lower_bound, upper_bound)
            return jnp.where(data >= 0, data, s.astype(data.dtype) * data)
        s = (lower_bound + upper_bound) / 2.0
        return jnp.where(data >= 0, data, s * data)
    raise ValueError("unknown act_type %r" % act_type)


@register("SoftmaxActivation", num_inputs=1, aliases=("softmax_activation",))
def _softmax_activation(data, mode="instance"):
    if mode == "channel":
        return jax.nn.softmax(data, axis=1)
    return jax.nn.softmax(data.reshape(data.shape[0], -1), axis=-1).reshape(data.shape)


# ---------------------------------------------------------------------------
# Dropout (dropout.cc)
# ---------------------------------------------------------------------------


@register("Dropout", num_inputs=1, needs_rng=True)
def _dropout(data, p=0.5, mode="training", axes=(), cudnn_off=False, key=None):
    if p <= 0 or (mode != "always" and not _is_train()):
        return data
    keep = 1.0 - p
    shape = list(data.shape)
    for a in axes or ():
        shape[a] = 1
    mask = jax.random.bernoulli(key, keep, tuple(shape))
    return jnp.where(mask, data / keep, jnp.zeros((), data.dtype))


# ---------------------------------------------------------------------------
# SoftmaxOutput (softmax_output.cc:155) — custom gradient: d = (p - onehot(y))
# ---------------------------------------------------------------------------


@register("SoftmaxOutput", num_inputs=2, aliases=("Softmax", "softmax_output"))
def _softmax_output(data, label, grad_scale=1.0, ignore_label=-1.0,
                    multi_output=False, use_ignore=False, preserve_shape=False,
                    normalization="null", out_grad=False, smooth_alpha=0.0):
    @jax.custom_vjp
    def f(d, l):
        return _softmax_fwd(d)

    def _softmax_fwd(d):
        if multi_output:
            return jax.nn.softmax(d, axis=1)
        if preserve_shape:
            return jax.nn.softmax(d, axis=-1)
        return jax.nn.softmax(d.reshape(d.shape[0], -1), axis=-1).reshape(d.shape)

    def fwd(d, l):
        out = _softmax_fwd(d)
        return out, (out, l)

    def bwd(res, g):
        out, l = res
        if multi_output:
            # out: (n, c, ...) label: (n, ...)
            oh = jax.nn.one_hot(l.astype(jnp.int32), out.shape[1], dtype=out.dtype,
                                axis=1)
            grad = out - oh
            if use_ignore:
                mask = (l != ignore_label).astype(out.dtype)
                grad = grad * jnp.expand_dims(mask, 1)
        elif preserve_shape:
            # out (..., C), label (...): per-position softmax grad
            k = out.shape[-1]
            oh = jax.nn.one_hot(l.astype(jnp.int32), k, dtype=out.dtype)
            if smooth_alpha:
                oh = oh * (1.0 - smooth_alpha) + smooth_alpha / (k - 1) * (1.0 - oh)
            grad = out - oh
            if use_ignore:
                mask = (l != ignore_label).astype(out.dtype)
                grad = grad * mask[..., None]
        else:
            flat = out.reshape(out.shape[0], -1)
            oh = jax.nn.one_hot(l.reshape(-1).astype(jnp.int32), flat.shape[-1],
                                dtype=out.dtype)
            if smooth_alpha:
                k = flat.shape[-1]
                oh = oh * (1.0 - smooth_alpha) + smooth_alpha / (k - 1) * (1.0 - oh)
            grad = (flat - oh).reshape(out.shape)
            if use_ignore:
                mask = (l.reshape(-1) != ignore_label).astype(out.dtype)
                grad = grad * mask.reshape((-1,) + (1,) * (grad.ndim - 1))
        scale = grad_scale
        if normalization == "batch":
            scale = scale / out.shape[0]
        elif normalization == "valid" and use_ignore:
            valid = jnp.maximum(jnp.sum((l != ignore_label).astype(out.dtype)), 1.0)
            scale = scale / valid
        grad = grad * scale
        if out_grad:
            grad = grad * g
        return grad, jnp.zeros_like(l)

    f.defvjp(fwd, bwd)
    return f(data, label)


# ---------------------------------------------------------------------------
# Resizing (upsampling.cc, contrib bilinear_resize)
# ---------------------------------------------------------------------------


@register("UpSampling", needs_rng=False)
def _upsampling(*args, scale=1, sample_type="nearest", num_args=1, num_filter=0,
                multi_input_mode="concat", workspace=512):
    data = args[0]
    if sample_type == "nearest":
        outs = []
        for a in args:
            s = scale
            o = jnp.repeat(jnp.repeat(a, s, axis=2), s, axis=3)
            outs.append(o)
        if len(outs) == 1:
            return outs[0]
        if multi_input_mode == "sum":
            return sum(outs)
        return jnp.concatenate(outs, axis=1)
    # bilinear: args = (data, weight) — implement as resize (weight unused
    # in the common initialization case)
    n, c, h, w = data.shape
    return jax.image.resize(data, (n, c, h * scale, w * scale), method="bilinear")


@register("_contrib_BilinearResize2D", num_inputs=1, aliases=("BilinearResize2D",))
def _bilinear_resize(data, height=None, width=None, scale_height=None,
                     scale_width=None, mode="size", align_corners=True):
    n, c, h, w = data.shape
    oh = int(height) if height else int(round(h * scale_height))
    ow = int(width) if width else int(round(w * scale_width))
    return jax.image.resize(data, (n, c, oh, ow), method="bilinear")


@register("_contrib_AdaptiveAvgPooling2D", num_inputs=1)
def _adaptive_avg_pool(data, output_size=(1, 1)):
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    n, c, h, w = data.shape
    oh, ow = output_size
    x = data.reshape(n, c, oh, h // oh, ow, w // ow)
    return x.mean(axis=(3, 5))


# ---------------------------------------------------------------------------
# CTC loss (nn/ctc_loss.cc)
# ---------------------------------------------------------------------------


@register("CTCLoss", aliases=("ctc_loss", "_contrib_CTCLoss"))
def _ctc_loss(data, label, data_lengths=None, label_lengths=None,
              use_data_lengths=False, use_label_lengths=False, blank_label="first"):
    import optax

    # data: (seq, batch, alphabet) -> optax wants (batch, seq, alphabet)
    logits = jnp.swapaxes(data, 0, 1)
    b, t, k = logits.shape
    labels = label.astype(jnp.int32)
    if blank_label == "first":
        # optax uses blank=0 by default; mxnet 'first' means blank==0 and
        # labels are 1-based already
        pass
    else:
        labels = labels + 1  # shift so blank can sit at 0
    logit_pad = jnp.zeros((b, t))
    if use_data_lengths and data_lengths is not None:
        steps = jnp.arange(t)[None, :]
        logit_pad = (steps >= data_lengths.astype(jnp.int32)[:, None]).astype(jnp.float32)
    lab_pad = (labels <= 0).astype(jnp.float32)
    if use_label_lengths and label_lengths is not None:
        steps = jnp.arange(labels.shape[1])[None, :]
        lab_pad = (steps >= label_lengths.astype(jnp.int32)[:, None]).astype(jnp.float32)
    return optax.ctc_loss(logits, logit_pad, labels, lab_pad, blank_id=0)
