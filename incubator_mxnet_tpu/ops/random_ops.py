"""Random sampling operators.

Parity: ``src/operator/random/sample_op.cc`` (uniform/normal/gamma/
exponential/poisson/negative_binomial/generalized_negative_binomial/randint),
multisample, shuffle.  Stateful-generator semantics come from :mod:`..rng`
(keys threaded automatically by the registry's ``needs_rng``), matching the
reference's per-device philox resource streams.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register


def _shape(shape):
    if shape is None:
        return ()
    if isinstance(shape, int):
        return (shape,)
    return tuple(shape)


def _dt(dtype):
    from ..base import np_dtype

    return np_dtype(dtype if dtype not in (None, "None") else "float32")


@register("_random_uniform", num_inputs=0, needs_rng=True, differentiable=False,
          aliases=("uniform", "random_uniform"))
def _uniform(low=0.0, high=1.0, shape=None, ctx=None, dtype=None, key=None):
    return jax.random.uniform(key, _shape(shape), _dt(dtype), low, high)


@register("_random_normal", num_inputs=0, needs_rng=True, differentiable=False,
          aliases=("normal", "random_normal"))
def _normal(loc=0.0, scale=1.0, shape=None, ctx=None, dtype=None, key=None):
    return loc + scale * jax.random.normal(key, _shape(shape), _dt(dtype))


@register("_random_gamma", num_inputs=0, needs_rng=True, differentiable=False,
          aliases=("random_gamma",))
def _gamma(alpha=1.0, beta=1.0, shape=None, ctx=None, dtype=None, key=None):
    return jax.random.gamma(key, alpha, _shape(shape), _dt(dtype)) * beta


@register("_random_exponential", num_inputs=0, needs_rng=True, differentiable=False,
          aliases=("random_exponential",))
def _exponential(lam=1.0, shape=None, ctx=None, dtype=None, key=None):
    return jax.random.exponential(key, _shape(shape), _dt(dtype)) / lam


@register("_random_poisson", num_inputs=0, needs_rng=True, differentiable=False,
          aliases=("random_poisson",))
def _poisson(lam=1.0, shape=None, ctx=None, dtype=None, key=None):
    return jax.random.poisson(key, lam, _shape(shape)).astype(_dt(dtype))


@register("_random_negative_binomial", num_inputs=0, needs_rng=True,
          differentiable=False, aliases=("random_negative_binomial",))
def _neg_binomial(k=1, p=1.0, shape=None, ctx=None, dtype=None, key=None):
    k1, k2 = jax.random.split(key)
    lam = jax.random.gamma(k1, k, _shape(shape)) * ((1 - p) / p)
    return jax.random.poisson(k2, lam, _shape(shape)).astype(_dt(dtype))


@register("_random_generalized_negative_binomial", num_inputs=0, needs_rng=True,
          differentiable=False, aliases=("random_generalized_negative_binomial",))
def _gen_neg_binomial(mu=1.0, alpha=1.0, shape=None, ctx=None, dtype=None, key=None):
    k1, k2 = jax.random.split(key)
    r = 1.0 / alpha
    p = r / (r + mu)
    lam = jax.random.gamma(k1, r, _shape(shape)) * ((1 - p) / p)
    return jax.random.poisson(k2, lam, _shape(shape)).astype(_dt(dtype))


@register("_random_randint", num_inputs=0, needs_rng=True, differentiable=False,
          aliases=("random_randint", "randint"))
def _randint(low=0, high=1, shape=None, ctx=None, dtype="int32", key=None):
    return jax.random.randint(key, _shape(shape), int(low), int(high),
                              _dt(dtype or "int32"))


@register("_sample_multinomial", num_inputs=1, needs_rng=True, differentiable=False,
          aliases=("sample_multinomial", "multinomial"))
def _multinomial(data, shape=None, get_prob=False, dtype="int32", key=None):
    n = 1 if shape is None else int(jnp.prod(jnp.array(_shape(shape))) or 1)
    logits = jnp.log(jnp.maximum(data, 1e-38))
    if data.ndim == 1:
        out = jax.random.categorical(key, logits, shape=(n,))
        out = out.reshape(_shape(shape)) if shape else out[0]
    else:
        out = jax.random.categorical(key, logits[:, None, :].repeat(n, axis=1), axis=-1)
        out = out.reshape((data.shape[0],) + _shape(shape)) if shape else out[:, 0]
    out = out.astype(_dt(dtype))
    if get_prob:
        prob = jnp.take_along_axis(
            jnp.log(jnp.maximum(data, 1e-38)).reshape(-1, data.shape[-1]),
            out.reshape(-1, 1).astype(jnp.int32), axis=-1).reshape(out.shape)
        return out, prob
    return out


# per-element distributions (sample_*: parameters given as arrays)
@register("_sample_uniform", num_inputs=2, needs_rng=True, differentiable=False,
          aliases=("sample_uniform",))
def _sample_uniform(low, high, shape=None, dtype=None, key=None):
    s = _shape(shape)
    out_shape = low.shape + s
    u = jax.random.uniform(key, out_shape, _dt(dtype))
    return low.reshape(low.shape + (1,) * len(s)) + u * (high - low).reshape(
        low.shape + (1,) * len(s))


@register("_sample_normal", num_inputs=2, needs_rng=True, differentiable=False,
          aliases=("sample_normal",))
def _sample_normal(mu, sigma, shape=None, dtype=None, key=None):
    s = _shape(shape)
    out_shape = mu.shape + s
    z = jax.random.normal(key, out_shape, _dt(dtype))
    return mu.reshape(mu.shape + (1,) * len(s)) + z * sigma.reshape(
        sigma.shape + (1,) * len(s))


@register("_shuffle", num_inputs=1, needs_rng=True, differentiable=False,
          aliases=("shuffle",))
def _shuffle_op(data, key=None):
    return jax.random.permutation(key, data, axis=0)


@register("bernoulli", num_inputs=0, needs_rng=True, differentiable=False)
def _bernoulli(prob=0.5, shape=None, dtype="float32", key=None):
    return jax.random.bernoulli(key, prob, _shape(shape)).astype(_dt(dtype))
