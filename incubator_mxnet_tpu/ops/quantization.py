"""INT8 quantization ops.

Reference: ``src/operator/quantization/`` — quantize.cc, quantize_v2.cc,
dequantize.cc, requantize.cc, quantized_fully_connected.cc,
quantized_conv.cc.  TPU-native: int8 matmul/conv run on the MXU via
``lax.dot_general``/``lax.conv`` with ``preferred_element_type=int32``
accumulation, exactly the int8 path XLA compiles natively.

Quantization convention (matches the reference's signed path): symmetric
int8 with scale = 127 / max(|min|, |max|); zero-point free, so the MXU
kernel needs no zero-point correction terms.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register

__all__ = ["dequantize_tensor", "quantize_tensor", "symmetric_quantize"]


def symmetric_quantize(w, qmax=127.0):
    """Symmetric per-tensor quantization: ``(int8-container codes,
    amax_f32)`` with scale ``qmax/amax`` — the one guarded
    implementation shared by :func:`quantize_tensor` (qmax 127) and
    the ``quantize_int8``/``quantize_int4`` graftpasses (qmax 127/7).

    Degenerate-tensor guard (graftrange GL402 flags the unguarded
    form): an all-zero tensor has ``amax == 0``, so a bare
    ``qmax/amax`` divides by zero, and a NaN'd channel poisons ``amax``
    so ``rint(NaN)`` lands undefined int8 codes.  The divisor is
    clamped away from zero (``jnp.maximum(amax, tiny)`` — a *known*
    positive lower bound, not a ``where`` whose untaken arm still
    divides), non-finite codes are zeroed, and a degenerate tensor
    publishes ``amax = 0`` so ``dequantize`` reconstructs exact
    zeros."""
    qmax = jnp.float32(qmax)
    amax = jnp.max(jnp.abs(w)).astype(jnp.float32)
    ok = jnp.isfinite(amax) & (amax > 0)
    amax = jnp.where(ok, amax, jnp.float32(0.0))
    scale = jnp.where(
        ok, qmax / jnp.maximum(amax, jnp.float32(2.0 ** -126)),
        jnp.float32(1.0))
    q = jnp.rint(w.astype(jnp.float32) * scale)
    q = jnp.where(jnp.isfinite(q), q, jnp.float32(0.0))
    q = jnp.clip(q, -qmax, qmax).astype(jnp.int8)
    return q, amax


def quantize_tensor(w):
    """Symmetric per-tensor int8 of one weight: ``(q_int8, amax_f32)``.

    The serving engine's weight-only int8 tier (``serve/engine.py``
    ``dtype="int8"``) quantizes eligible parameters ONCE at load with
    exactly the ``_contrib_quantize_v2`` convention (scale =
    127/amax, zero-point free), so a tensor round-tripped through the
    engine and one through the reference-parity ops land on identical
    codes.  Returns float32 ``amax`` so ``dequantize_tensor`` is
    dtype-stable regardless of the input precision.  All-zero and
    non-finite inputs are contained (zero codes, ``amax = 0``) instead
    of dividing by zero into NaN codes — see
    :func:`symmetric_quantize`."""
    return symmetric_quantize(w, qmax=127.0)


def dequantize_tensor(q, amax, dtype=jnp.float32):
    """Inverse of :func:`quantize_tensor`: ``real = q * amax / 127``
    (the ``_contrib_dequantize`` convention), cast to ``dtype``."""
    return (q.astype(jnp.float32) * (amax / 127.0)).astype(dtype)


def _range_scale(min_r, max_r):
    amax = jnp.maximum(jnp.abs(min_r), jnp.abs(max_r))
    return jnp.where(amax > 0, 127.0 / amax, 1.0)


def _check_out_type(out_type):
    if str(out_type) not in ("int8", "auto"):
        raise NotImplementedError(
            "out_type=%r: only symmetric int8 quantization is implemented "
            "(the reference's affine uint8 encoding is not)" % (out_type,))


@register("_contrib_quantize", num_inputs=3, num_outputs=3,
          differentiable=False)
def _quantize(data, min_range, max_range, out_type="int8"):
    """float → int8 with explicit range (quantize.cc)."""
    _check_out_type(out_type)
    scale = _range_scale(min_range, max_range)
    q = jnp.clip(jnp.rint(data * scale), -127, 127).astype(jnp.int8)
    amax = jnp.maximum(jnp.abs(min_range), jnp.abs(max_range))
    return q, -amax, amax


@register("_contrib_quantize_v2", num_inputs=1, num_outputs=3,
          differentiable=False)
def _quantize_v2(data, min_calib_range=None, max_calib_range=None,
                 out_type="int8"):
    """float → int8; range from calibration attrs or the data itself
    (quantize_v2.cc)."""
    _check_out_type(out_type)
    if min_calib_range is not None and max_calib_range is not None:
        min_r = jnp.float32(min_calib_range)
        max_r = jnp.float32(max_calib_range)
    else:
        min_r = jnp.min(data).astype(jnp.float32)
        max_r = jnp.max(data).astype(jnp.float32)
    amax = jnp.maximum(jnp.abs(min_r), jnp.abs(max_r))
    scale = jnp.where(amax > 0, 127.0 / amax, 1.0)
    q = jnp.clip(jnp.rint(data * scale), -127, 127).astype(jnp.int8)
    return q, -amax, amax


@register("_contrib_dequantize", num_inputs=3, differentiable=False)
def _dequantize(data, min_range, max_range, out_type="float32"):
    """int8 → float (dequantize.cc)."""
    scale = _range_scale(min_range, max_range)
    return data.astype(jnp.float32) / scale


@register("_contrib_requantize", num_inputs=3, num_outputs=3,
          differentiable=False)
def _requantize(data, min_range, max_range, min_calib_range=None,
                max_calib_range=None):
    """int32 (accumulator) → int8 with a narrower calibrated range
    (requantize.cc)."""
    # same convention as dequantize: real = x * amax / 127 (dtype-free)
    in_scale = jnp.maximum(jnp.abs(min_range), jnp.abs(max_range)) / 127.0
    real = data.astype(jnp.float32) * in_scale
    if min_calib_range is not None and max_calib_range is not None:
        amax = jnp.maximum(abs(float(min_calib_range)),
                           abs(float(max_calib_range)))
        amax = jnp.float32(amax)
    else:
        amax = jnp.max(jnp.abs(real))
    scale = jnp.where(amax > 0, 127.0 / amax, 1.0)
    q = jnp.clip(jnp.rint(real * scale), -127, 127).astype(jnp.int8)
    return q, -amax, amax


@register("_contrib_quantized_elemwise_add", num_inputs=6, num_outputs=3,
          differentiable=False)
def _quantized_elemwise_add(a, b, min_a, max_a, min_b, max_b):
    """int8 + int8 -> int8 residual add with scale alignment
    (quantized_elemwise_add.cc).  Output range is the sum of input
    ranges (exact containment, no data-dependent rescan)."""
    sa = jnp.maximum(jnp.abs(min_a), jnp.abs(max_a)) / 127.0
    sb = jnp.maximum(jnp.abs(min_b), jnp.abs(max_b)) / 127.0
    amax_out = sa * 127.0 + sb * 127.0
    real = a.astype(jnp.float32) * sa + b.astype(jnp.float32) * sb
    scale = jnp.where(amax_out > 0, 127.0 / amax_out, 1.0)
    q = jnp.clip(jnp.rint(real * scale), -127, 127).astype(jnp.int8)
    return q, -amax_out, amax_out


@register("_contrib_quantized_fully_connected", num_inputs=9, num_outputs=3,
          differentiable=False)
def _quantized_fully_connected(data, weight, bias, min_data, max_data,
                               min_weight, max_weight, min_bias=None,
                               max_bias=None, num_hidden=0, no_bias=False,
                               flatten=True, **ignored):
    """int8×int8→int32 dense layer (quantized_fully_connected.cc).
    Output is the int32 accumulator + its float range."""
    x = data
    if flatten and x.ndim > 2:
        x = x.reshape(x.shape[0], -1)
    acc = lax.dot_general(x, weight, (((x.ndim - 1,), (1,)), ((), ())),
                          preferred_element_type=jnp.int32)
    d_scale = _range_scale(min_data, max_data)
    w_scale = _range_scale(min_weight, max_weight)
    out_scale = d_scale * w_scale                     # int32 per 1.0 float
    if bias is not None and not no_bias:
        b_scale = _range_scale(min_bias, max_bias)
        # rescale int8 bias into the accumulator's scale
        b = jnp.rint(bias.astype(jnp.float32) / b_scale * out_scale)
        acc = acc + b.astype(jnp.int32)
    # declared so dequantize's x*amax/127 recovers floats: amax=127/scale
    amax = 127.0 / out_scale
    return acc, -amax, amax


@register("_contrib_quantized_conv", num_inputs=9, num_outputs=3,
          differentiable=False)
def _quantized_conv(data, weight, bias, min_data, max_data, min_weight,
                    max_weight, min_bias=None, max_bias=None, kernel=None,
                    stride=(1, 1), pad=(0, 0), dilate=(1, 1),
                    num_filter=0, num_group=1, no_bias=False,
                    layout="NCHW", **ignored):
    """int8 convolution with int32 accumulation (quantized_conv.cc)."""
    if layout != "NCHW":
        raise NotImplementedError(
            "quantized_conv only supports layout='NCHW', got %r" % (layout,))
    stride = tuple(int(s) for s in stride)
    pad = tuple(int(p) for p in pad)
    dilate = tuple(int(d) for d in dilate)
    acc = lax.conv_general_dilated(
        data.astype(jnp.int8), weight.astype(jnp.int8),
        window_strides=stride,
        padding=[(pad[0], pad[0]), (pad[1], pad[1])],
        rhs_dilation=dilate,
        dimension_numbers=("NCHW", "OIHW", "NCHW"),
        feature_group_count=int(num_group),
        preferred_element_type=jnp.int32)
    d_scale = _range_scale(min_data, max_data)
    w_scale = _range_scale(min_weight, max_weight)
    out_scale = d_scale * w_scale
    if bias is not None and not no_bias:
        b_scale = _range_scale(min_bias, max_bias)
        b = jnp.rint(bias.astype(jnp.float32) / b_scale * out_scale)
        acc = acc + b.astype(jnp.int32).reshape(1, -1, 1, 1)
    amax = 127.0 / out_scale
    return acc, -amax, amax
