"""Elementwise / broadcast / reduction / linalg operators.

Parity: ``src/operator/tensor/elemwise_*`` , ``broadcast_reduce_op*``,
``dot-inl.h``, ``la_op``.  Every op is one pure jnp/lax function — XLA fuses
elementwise chains automatically (the reference needed a runtime NVRTC fusion
pass, ``src/executor/pointwise_fusion_pass.cc``, for the same effect).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register

# ---------------------------------------------------------------------------
# binary broadcast + elemwise (reference: elemwise_binary_broadcast_op_basic.cc)
# ---------------------------------------------------------------------------

_BINARY = {
    "broadcast_add": jnp.add,
    "broadcast_sub": jnp.subtract,
    "broadcast_mul": jnp.multiply,
    "broadcast_div": jnp.divide,
    "broadcast_mod": jnp.mod,
    "broadcast_power": jnp.power,
    "broadcast_maximum": jnp.maximum,
    "broadcast_minimum": jnp.minimum,
    "broadcast_hypot": jnp.hypot,
}
_BINARY_ALIASES = {
    "broadcast_add": ("elemwise_add", "_add", "_plus", "_Plus"),
    "broadcast_sub": ("elemwise_sub", "_sub", "_minus", "_Minus"),
    "broadcast_mul": ("elemwise_mul", "_mul", "_Mul"),
    "broadcast_div": ("elemwise_div", "_div", "_Div"),
    "broadcast_power": ("_power", "_Power", "pow"),
    "broadcast_mod": ("_mod",),
    "broadcast_maximum": ("_maximum",),
    "broadcast_minimum": ("_minimum",),
}

for _name, _f in _BINARY.items():
    register(_name, (lambda f: lambda lhs, rhs: f(lhs, rhs))(_f),
             num_inputs=2, aliases=_BINARY_ALIASES.get(_name, ()))

_CMP = {
    "broadcast_equal": jnp.equal,
    "broadcast_not_equal": jnp.not_equal,
    "broadcast_greater": jnp.greater,
    "broadcast_greater_equal": jnp.greater_equal,
    "broadcast_lesser": jnp.less,
    "broadcast_lesser_equal": jnp.less_equal,
    "broadcast_logical_and": jnp.logical_and,
    "broadcast_logical_or": jnp.logical_or,
    "broadcast_logical_xor": jnp.logical_xor,
}
for _name, _f in _CMP.items():
    # comparisons output same-dtype-as-input in mxnet (0/1 floats)
    register(
        _name,
        (lambda f: lambda lhs, rhs: f(lhs, rhs).astype(jnp.result_type(lhs, rhs)))(_f),
        num_inputs=2,
        differentiable=False,
        aliases=(_name.replace("broadcast_", "_"),),
    )


@register("_scatter_elemwise_div", num_inputs=2)
def _scatter_div(lhs, rhs):
    return lhs / rhs


# scalar ops (reference: elemwise_binary_scalar_op*.cc)
def _scalar_op(name, fn, reverse_fn=None, differentiable=True, aliases=()):
    register(name, (lambda f: lambda data, scalar=1.0: f(data, scalar))(fn),
             num_inputs=1, differentiable=differentiable, aliases=aliases)
    if reverse_fn is not None:
        register("_r" + name.lstrip("_"),
                 (lambda f: lambda data, scalar=1.0: f(data, scalar))(reverse_fn),
                 num_inputs=1, differentiable=differentiable)


_scalar_op("_plus_scalar", lambda x, s: x + s, aliases=("_PlusScalar",))
_scalar_op("_minus_scalar", lambda x, s: x - s, lambda x, s: s - x, aliases=("_MinusScalar",))
_scalar_op("_mul_scalar", lambda x, s: x * s, aliases=("_MulScalar",))
_scalar_op("_div_scalar", lambda x, s: x / s, lambda x, s: s / x, aliases=("_DivScalar",))
_scalar_op("_power_scalar", lambda x, s: jnp.power(x, s), lambda x, s: jnp.power(s, x))
_scalar_op("_mod_scalar", lambda x, s: jnp.mod(x, s), lambda x, s: jnp.mod(s, x))
_scalar_op("_maximum_scalar", jnp.maximum)
_scalar_op("_minimum_scalar", jnp.minimum)
_scalar_op("_equal_scalar", lambda x, s: (x == s).astype(x.dtype), differentiable=False)
_scalar_op("_not_equal_scalar", lambda x, s: (x != s).astype(x.dtype), differentiable=False)
_scalar_op("_greater_scalar", lambda x, s: (x > s).astype(x.dtype), differentiable=False)
_scalar_op("_greater_equal_scalar", lambda x, s: (x >= s).astype(x.dtype), differentiable=False)
_scalar_op("_lesser_scalar", lambda x, s: (x < s).astype(x.dtype), differentiable=False)
_scalar_op("_lesser_equal_scalar", lambda x, s: (x <= s).astype(x.dtype), differentiable=False)


# ---------------------------------------------------------------------------
# unary (reference: elemwise_unary_op_basic.cc, _trig.cc, _logexp.cc, _pow.cc)
# ---------------------------------------------------------------------------

_UNARY = {
    "relu": lambda x: jnp.maximum(x, 0),
    "sigmoid": jax.nn.sigmoid,
    "hard_sigmoid": lambda x, alpha=0.2, beta=0.5: jnp.clip(alpha * x + beta, 0, 1),
    "softsign": jax.nn.soft_sign,
    "tanh": jnp.tanh,
    "exp": jnp.exp,
    "expm1": jnp.expm1,
    "log": jnp.log,
    "log10": jnp.log10,
    "log2": jnp.log2,
    "log1p": jnp.log1p,
    "sqrt": jnp.sqrt,
    "rsqrt": lax.rsqrt,
    "cbrt": jnp.cbrt,
    "rcbrt": lambda x: 1.0 / jnp.cbrt(x),
    "square": jnp.square,
    "reciprocal": lambda x: 1.0 / x,
    "negative": jnp.negative,
    "abs": jnp.abs,
    "erf": jax.scipy.special.erf,
    "erfinv": jax.scipy.special.erfinv,
    "gamma": lambda x: jnp.exp(jax.scipy.special.gammaln(x)),
    "gammaln": jax.scipy.special.gammaln,
    "sin": jnp.sin,
    "cos": jnp.cos,
    "tan": jnp.tan,
    "arcsin": jnp.arcsin,
    "arccos": jnp.arccos,
    "arctan": jnp.arctan,
    "sinh": jnp.sinh,
    "cosh": jnp.cosh,
    "arcsinh": jnp.arcsinh,
    "arccosh": jnp.arccosh,
    "arctanh": jnp.arctanh,
    "degrees": jnp.degrees,
    "radians": jnp.radians,
    "identity": lambda x: x,
    "logical_not": lambda x: jnp.logical_not(x).astype(x.dtype),
}
_UNARY_ALIASES = {
    "identity": ("_copy",),
    "abs": ("_abs",),
    "negative": ("_neg",),
}
for _name, _f in _UNARY.items():
    register(_name, (lambda f: lambda data, **kw: f(data, **kw))(_f),
             num_inputs=1, aliases=_UNARY_ALIASES.get(_name, ()))

_UNARY_NONDIFF = {
    "sign": jnp.sign,
    "round": jnp.round,
    "rint": jnp.rint,
    "ceil": jnp.ceil,
    "floor": jnp.floor,
    "trunc": jnp.trunc,
    "fix": jnp.trunc,
}
for _name, _f in _UNARY_NONDIFF.items():
    register(_name, (lambda f: lambda data: f(data))(_f), num_inputs=1,
             differentiable=False)


@register("clip", num_inputs=1)
def _clip(data, a_min=None, a_max=None):
    return jnp.clip(data, a_min, a_max)


@register("BlockGrad", num_inputs=1, aliases=("stop_gradient",))
def _block_grad(data):
    return lax.stop_gradient(data)


@register("make_loss", num_inputs=1, aliases=("MakeLoss",))
def _make_loss(data, grad_scale=1.0, valid_thresh=0.0, normalization="null"):
    return data


@register("smooth_l1", num_inputs=1)
def _smooth_l1(data, scalar=1.0):
    s2 = scalar * scalar
    a = jnp.abs(data)
    return jnp.where(a < 1.0 / s2, 0.5 * s2 * data * data, a - 0.5 / s2)


@register("gelu", num_inputs=1)
def _gelu(data, approximate=False):
    return jax.nn.gelu(data, approximate=bool(approximate))


@register("add_n", aliases=("ElementWiseSum", "_sum"))
def _add_n(*args):
    out = args[0]
    for a in args[1:]:
        out = out + a
    return out


# ---------------------------------------------------------------------------
# reductions (reference: broadcast_reduce_op_value.cc)
# ---------------------------------------------------------------------------


def _norm_axis(axis, ndim, exclude=False):
    if axis is None:
        return None
    if isinstance(axis, int):
        axis = (axis,)
    axis = tuple(a % ndim for a in axis)
    if exclude:
        axis = tuple(a for a in range(ndim) if a not in axis)
    return axis


def _reduce(fn):
    def impl(data, axis=None, keepdims=False, exclude=False):
        ax = _norm_axis(axis, data.ndim, exclude)
        return fn(data, axis=ax, keepdims=bool(keepdims))

    return impl


register("sum", _reduce(jnp.sum), num_inputs=1, aliases=("sum_axis",))
register("mean", _reduce(jnp.mean), num_inputs=1)
register("prod", _reduce(jnp.prod), num_inputs=1)
register("nansum", _reduce(jnp.nansum), num_inputs=1)
register("nanprod", _reduce(jnp.nanprod), num_inputs=1)
register("max", _reduce(jnp.max), num_inputs=1, aliases=("max_axis",))
register("min", _reduce(jnp.min), num_inputs=1, aliases=("min_axis",))


@register("norm", num_inputs=1)
def _norm(data, ord=2, axis=None, keepdims=False):  # noqa: A002
    ax = _norm_axis(axis, data.ndim)
    if ord == 1:
        return jnp.sum(jnp.abs(data), axis=ax, keepdims=bool(keepdims))
    return jnp.sqrt(jnp.sum(jnp.square(data), axis=ax, keepdims=bool(keepdims)))


@register("argmax", num_inputs=1, differentiable=False)
def _argmax(data, axis=None, keepdims=False):
    out = jnp.argmax(data, axis=axis, keepdims=bool(keepdims))
    return out.astype(jnp.float32)  # mxnet returns float indices


@register("argmin", num_inputs=1, differentiable=False)
def _argmin(data, axis=None, keepdims=False):
    return jnp.argmin(data, axis=axis, keepdims=bool(keepdims)).astype(jnp.float32)


@register("argmax_channel", num_inputs=1, differentiable=False)
def _argmax_channel(data):
    return jnp.argmax(data, axis=1).astype(jnp.float32)


# ---------------------------------------------------------------------------
# sorting / topk (reference: src/operator/tensor/ordering_op.cc)
# ---------------------------------------------------------------------------


@register("sort", num_inputs=1)
def _sort(data, axis=-1, is_ascend=True):
    out = jnp.sort(data, axis=axis)
    if not is_ascend:
        out = jnp.flip(out, axis=axis)
    return out


@register("argsort", num_inputs=1, differentiable=False)
def _argsort(data, axis=-1, is_ascend=True, dtype="float32"):
    idx = jnp.argsort(data, axis=axis)
    if not is_ascend:
        idx = jnp.flip(idx, axis=axis)
    return idx.astype(dtype)


@register("topk", num_inputs=1, differentiable=False)
def _topk(data, axis=-1, k=1, ret_typ="indices", is_ascend=False, dtype="float32"):
    axis = axis if axis is not None else -1
    moved = jnp.moveaxis(data, axis, -1)  # lax.top_k works on the last axis
    if is_ascend:
        vals, idx = lax.top_k(-moved, k)
        vals = -vals
    else:
        vals, idx = lax.top_k(moved, k)
    if ret_typ == "mask":
        onehot = jax.nn.one_hot(idx, moved.shape[-1], dtype=data.dtype)
        mask = onehot.sum(axis=-2)
        return jnp.moveaxis(mask, -1, axis)
    vals = jnp.moveaxis(vals, -1, axis)
    idx = jnp.moveaxis(idx, -1, axis).astype(dtype)
    if ret_typ == "indices":
        return idx
    if ret_typ == "value":
        return vals
    return vals, idx  # 'both'


# ---------------------------------------------------------------------------
# dot / batch_dot / linalg (reference: dot-inl.h, la_op.cc)
# ---------------------------------------------------------------------------


@register("dot", num_inputs=2)
def _dot(lhs, rhs, transpose_a=False, transpose_b=False):
    a = lhs.T if transpose_a else lhs
    b = rhs.T if transpose_b else rhs
    if a.ndim == 1 and b.ndim == 1:
        return jnp.dot(a, b)
    # mxnet dot contracts last axis of a with first axis of b
    return jnp.tensordot(a, b, axes=([a.ndim - 1], [0]))


@register("batch_dot", num_inputs=2)
def _batch_dot(lhs, rhs, transpose_a=False, transpose_b=False):
    a = jnp.swapaxes(lhs, -1, -2) if transpose_a else lhs
    b = jnp.swapaxes(rhs, -1, -2) if transpose_b else rhs
    return jnp.matmul(a, b)


@register("khatri_rao")
def _khatri_rao(*mats):
    out = mats[0]
    for m in mats[1:]:
        out = (out[:, None, :] * m[None, :, :]).reshape(-1, out.shape[-1])
    return out


# linalg family (subset used by la_op tests; all bottom out in XLA's
# native decompositions rather than LAPACK bindings)
register("_linalg_gemm2", lambda a, b, transpose_a=False, transpose_b=False,
         alpha=1.0: alpha * jnp.matmul(
             jnp.swapaxes(a, -1, -2) if transpose_a else a,
             jnp.swapaxes(b, -1, -2) if transpose_b else b), num_inputs=2,
         aliases=("linalg_gemm2",))
register("_linalg_potrf", lambda a: jnp.linalg.cholesky(a), num_inputs=1,
         aliases=("linalg_potrf",))
register("_linalg_trmm", lambda a, b, transpose=False, rightside=False, alpha=1.0:
         alpha * (jnp.matmul(b, jnp.swapaxes(a, -1, -2) if transpose else a)
                  if rightside else
                  jnp.matmul(jnp.swapaxes(a, -1, -2) if transpose else a, b)),
         num_inputs=2, aliases=("linalg_trmm",))
register("_linalg_syrk", lambda a, transpose=False, alpha=1.0:
         alpha * (jnp.matmul(jnp.swapaxes(a, -1, -2), a) if transpose
                  else jnp.matmul(a, jnp.swapaxes(a, -1, -2))),
         num_inputs=1, aliases=("linalg_syrk",))
register("_linalg_sumlogdiag", lambda a: jnp.sum(jnp.log(jnp.diagonal(a, axis1=-2, axis2=-1)), axis=-1),
         num_inputs=1, aliases=("linalg_sumlogdiag",))
register("_linalg_extractdiag", lambda a, offset=0: jnp.diagonal(a, offset=offset, axis1=-2, axis2=-1),
         num_inputs=1, aliases=("linalg_extractdiag",))
register("_linalg_inverse", lambda a: jnp.linalg.inv(a), num_inputs=1,
         aliases=("linalg_inverse",))
register("_linalg_det", lambda a: jnp.linalg.det(a), num_inputs=1, aliases=("linalg_det",))
register("_linalg_slogdet", lambda a: jnp.linalg.slogdet(a), num_outputs=2,
         num_inputs=1, aliases=("linalg_slogdet",))


@register("log_softmax", num_inputs=1)
def _log_softmax(data, axis=-1, temperature=None):
    if temperature is not None and temperature != 1.0:
        data = data / temperature
    return jax.nn.log_softmax(data, axis=axis)


@register("softmax", num_inputs=1)
def _softmax(data, axis=-1, temperature=None, length=None):
    if temperature is not None and temperature != 1.0:
        data = data / temperature
    return jax.nn.softmax(data, axis=axis)


@register("softmin", num_inputs=1)
def _softmin(data, axis=-1, temperature=None):
    if temperature is not None and temperature != 1.0:
        data = data / temperature
    return jax.nn.softmax(-data, axis=axis)


@register("cumsum", num_inputs=1)
def _cumsum(a, axis=None, dtype=None):
    if axis is None:
        a = a.reshape(-1)
        axis = 0
    out = jnp.cumsum(a, axis=axis)
    return out.astype(dtype) if dtype is not None else out


@register("diag", num_inputs=1)
def _diag(data, k=0, axis1=0, axis2=1):
    if data.ndim == 1:
        return jnp.diag(data, k=k)
    return jnp.diagonal(data, offset=k, axis1=axis1, axis2=axis2)
