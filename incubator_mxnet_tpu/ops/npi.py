"""numpy-internal op names (``_npi_*`` / ``_np_*`` / ``_npx_*``).

The reference's ``mx.np`` frontend bottoms out in these registered names
(``src/operator/numpy/**``), and invoke-by-name consumers (the C ABI,
exported symbol JSON) reference them directly.  Here ``mx.np`` dispatches
straight to jnp, so these registrations exist for ABI/name parity: most
are aliases onto the canonical ops, the rest are thin jnp bodies.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import OPS, register
from .parity_tail import _alias

# -- direct renames onto existing canonical ops ------------------------------

_RENAMES = {
    "_npi_absolute": "abs",
    "_npi_add_scalar": "_plus_scalar",
    "_npi_subtract_scalar": "_minus_scalar",
    "_npi_rsubtract_scalar": "_rminus_scalar",
    "_npi_multiply_scalar": "_mul_scalar",
    "_npi_true_divide_scalar": "_div_scalar",
    "_npi_rtrue_divide_scalar": "_rdiv_scalar",
    "_npi_power_scalar": "_power_scalar",
    "_npi_rpower_scalar": "_rpower_scalar",
    "_npi_mod_scalar": "_mod_scalar",
    "_npi_rmod_scalar": "_rmod_scalar",
    "_npi_subtract": "broadcast_sub",
    "_npi_multiply": "broadcast_mul",
    "_npi_true_divide": "broadcast_div",

    "_npi_unique": "_np_unique",
    "_npx_nonzero": "_np_nonzero",
    "_np_copy": "_copy",

    "_npi_cholesky": "linalg_potrf",
    "_npi_tensordot_int_axes": "tensordot",

}


def _register_renames_and_autoaliases():
    for new, old in _RENAMES.items():
        if new not in OPS and old in OPS:
            _alias(new, old)
    # automatic: _npi_sin -> sin, _npi_mod -> broadcast_mod, ...
    auto_src = (
        "arange arccos arccosh arcsin arcsinh arctan arctanh argmax "
                 "argmin bernoulli bitwise_and cbrt ceil choice cos cosh "
                 "degrees exp expm1 eye fix flip floor hypot identity lcm "
                 "log log10 log1p log2 logical_not mean multinomial negative "
                 "normal ones power radians reciprocal rint sign sin sinh "
        "sqrt square stack tan tanh tril trunc uniform where zeros "
        "mod dot cumsum diag hsplit split").split()
    for base in auto_src:
        npi = "_npi_" + base
        if npi in OPS:
            continue
        for cand in (base, "broadcast_" + base, "sample_" + base,
                     "_random_" + base):
            if cand in OPS:
                _alias(npi, cand)
                break


_register_renames_and_autoaliases()


# -- thin jnp bodies for names with no canonical equivalent ------------------

@register("_npi_arctan2", num_inputs=2, aliases=("arctan2",))
def _arctan2(x1, x2):
    return jnp.arctan2(x1, x2)


@register("_npi_arctan2_scalar", num_inputs=1)
def _arctan2_scalar(x, scalar=0.0):
    return jnp.arctan2(x, float(scalar))


@register("_npi_rarctan2_scalar", num_inputs=1)
def _rarctan2_scalar(x, scalar=0.0):
    return jnp.arctan2(float(scalar), x)


@register("_npi_copysign", num_inputs=2, aliases=("copysign",))
def _copysign(x1, x2):
    return jnp.copysign(x1, x2)


@register("_npi_copysign_scalar", num_inputs=1)
def _copysign_scalar(x, scalar=0.0):
    return jnp.copysign(x, float(scalar))


@register("_npi_rcopysign_scalar", num_inputs=1)
def _rcopysign_scalar(x, scalar=0.0):
    return jnp.copysign(float(scalar), x)


@register("_npi_ldexp", num_inputs=2, aliases=("ldexp",))
def _ldexp(x1, x2):
    return x1 * jnp.power(2.0, x2)


@register("_npi_ldexp_scalar", num_inputs=1)
def _ldexp_scalar(x, scalar=0.0):
    return x * float(2.0 ** scalar)


@register("_npi_rldexp_scalar", num_inputs=1)
def _rldexp_scalar(x, scalar=0.0):
    return float(scalar) * jnp.power(2.0, x)


@register("_npi_bitwise_not", num_inputs=1, differentiable=False)
def _bitwise_not(x):
    return jnp.bitwise_not(x)  # bool invert and integer ~ both correct


@register("_npi_concatenate", aliases=("concatenate",))
def _concatenate(*data, axis=0):
    return jnp.concatenate(data, axis=None if axis is None else int(axis))


@register("_npi_around", num_inputs=1, aliases=("around",))
def _around(x, decimals=0):
    return jnp.round(x, int(decimals))


@register("_npi_average", num_inputs=1, aliases=("average",))
def _average(a, weights=None, axis=None, returned=False):
    ax = None if axis is None else int(axis)
    w = None if weights is None else jnp.asarray(weights)
    out = jnp.average(a, axis=ax, weights=w)
    if returned:
        scl = jnp.sum(w, axis=ax) if w is not None else \
            jnp.asarray(a.size if ax is None else a.shape[ax], out.dtype)
        return out, jnp.broadcast_to(scl, out.shape)
    return out


@register("_npi_bitwise_or", num_inputs=2, differentiable=False,
          aliases=("bitwise_or",))
def _bitwise_or(x1, x2):
    return jnp.bitwise_or(x1, x2)


@register("_npi_bitwise_or_scalar", num_inputs=1, differentiable=False)
def _bitwise_or_scalar(x, scalar=0):
    return jnp.bitwise_or(x, int(scalar))


@register("_npi_bitwise_xor", num_inputs=2, differentiable=False,
          aliases=("bitwise_xor",))
def _bitwise_xor(x1, x2):
    return jnp.bitwise_xor(x1, x2)


@register("_npi_bitwise_xor_scalar", num_inputs=1, differentiable=False)
def _bitwise_xor_scalar(x, scalar=0):
    return jnp.bitwise_xor(x, int(scalar))


@register("_npi_lcm_scalar", num_inputs=1, differentiable=False)
def _lcm_scalar(x, scalar=1):
    return jnp.lcm(x, int(scalar))


@register("_npi_lcm", num_inputs=2, differentiable=False, aliases=("lcm",))
def _lcm(x1, x2):
    return jnp.lcm(x1, x2)


@register("_npi_deg2rad", num_inputs=1)
def _deg2rad(x):
    return jnp.deg2rad(x)


@register("_npi_rad2deg", num_inputs=1)
def _rad2deg(x):
    return jnp.rad2deg(x)


@register("_npi_nan_to_num", num_inputs=1)
def _nan_to_num(x, nan=0.0, posinf=None, neginf=None, copy=True):
    return jnp.nan_to_num(x, nan=float(nan),
                          posinf=None if posinf is None else float(posinf),
                          neginf=None if neginf is None else float(neginf))


@register("_npi_diff", num_inputs=1, aliases=("diff",))
def _diff(x, n=1, axis=-1):
    return jnp.diff(x, n=int(n), axis=int(axis))


@register("_npi_rot90", num_inputs=1, aliases=("rot90",))
def _rot90(x, k=1, axes=(0, 1)):
    return jnp.rot90(x, k=int(k), axes=tuple(axes))


@register("_np_roll", num_inputs=1, aliases=("roll",))
def _roll(x, shift=None, axis=None):
    sh = tuple(shift) if isinstance(shift, (tuple, list)) else int(shift)
    ax = tuple(axis) if isinstance(axis, (tuple, list)) else \
        (None if axis is None else int(axis))
    return jnp.roll(x, sh, axis=ax)


@register("_np_moveaxis", num_inputs=1, aliases=("moveaxis",))
def _moveaxis(x, source=None, destination=None):
    return jnp.moveaxis(x, source, destination)


@register("_np_trace", num_inputs=1, aliases=("trace",))
def _trace(x, offset=0, axis1=0, axis2=1):
    return jnp.trace(x, offset=int(offset), axis1=int(axis1),
                     axis2=int(axis2))


@register("_np_diagonal", num_inputs=1, aliases=("diagonal",))
def _diagonal(x, offset=0, axis1=0, axis2=1):
    return jnp.diagonal(x, offset=int(offset), axis1=int(axis1),
                        axis2=int(axis2))


@register("_np_diagflat", num_inputs=1, aliases=("diagflat",))
def _diagflat(x, k=0):
    return jnp.diagflat(x, k=int(k))


@register("_npi_std", num_inputs=1, aliases=("std",))
def _std(x, axis=None, ddof=0, keepdims=False):
    ax = tuple(axis) if isinstance(axis, (tuple, list)) else \
        (None if axis is None else int(axis))
    return jnp.std(x, axis=ax, ddof=int(ddof), keepdims=bool(keepdims))


@register("_npi_var", num_inputs=1, aliases=("var",))
def _var(x, axis=None, ddof=0, keepdims=False):
    ax = tuple(axis) if isinstance(axis, (tuple, list)) else \
        (None if axis is None else int(axis))
    return jnp.var(x, axis=ax, ddof=int(ddof), keepdims=bool(keepdims))


@register("_npi_full_like", num_inputs=1, differentiable=False)
def _full_like(x, fill_value=0.0, dtype=None):
    return jnp.full_like(x, float(fill_value),
                         dtype=None if dtype is None else dtype)


@register("_npi_logspace", num_inputs=0, differentiable=False)
def _logspace(start=0.0, stop=1.0, num=50, endpoint=True, base=10.0,
              dtype=None, ctx=None):
    return jnp.logspace(float(start), float(stop), int(num),
                        endpoint=bool(endpoint), base=float(base),
                        dtype=dtype)


@register("_npi_indices", num_inputs=0, differentiable=False)
def _indices(dimensions=(), dtype=None, ctx=None):
    return jnp.indices(tuple(dimensions),
                       dtype=jnp.int32 if dtype is None else dtype)


@register("_npi_hanning", num_inputs=0, differentiable=False)
def _hanning(M=1, dtype=None, ctx=None):  # noqa: N803 - numpy name
    n = int(M)
    if n < 1:
        return jnp.zeros((0,))
    if n == 1:
        return jnp.ones((1,))
    i = jnp.arange(n)
    return 0.5 - 0.5 * jnp.cos(2 * jnp.pi * i / (n - 1))


@register("_npi_hamming", num_inputs=0, differentiable=False)
def _hamming(M=1, dtype=None, ctx=None):  # noqa: N803 - numpy name
    n = int(M)
    if n < 1:
        return jnp.zeros((0,))
    if n == 1:
        return jnp.ones((1,))
    i = jnp.arange(n)
    return 0.54 - 0.46 * jnp.cos(2 * jnp.pi * i / (n - 1))


@register("_npi_blackman", num_inputs=0, differentiable=False)
def _blackman(M=1, dtype=None, ctx=None):  # noqa: N803 - numpy name
    n = int(M)
    if n < 1:
        return jnp.zeros((0,))
    if n == 1:
        return jnp.ones((1,))
    i = jnp.arange(n)
    w = 2 * jnp.pi * i / (n - 1)
    return 0.42 - 0.5 * jnp.cos(w) + 0.08 * jnp.cos(2 * w)


@register("_npi_column_stack", aliases=("column_stack",))
def _column_stack(*arrays):
    return jnp.column_stack(arrays)


@register("_npi_vstack", aliases=("vstack",))
def _vstack(*arrays):
    return jnp.vstack(arrays)


@register("_npi_dstack", aliases=("dstack",))
def _dstack(*arrays):
    return jnp.dstack(arrays)


@register("_npi_solve", num_inputs=2, aliases=("linalg_solve",))
def _solve(a, b):
    return jnp.linalg.solve(a, b)


@register("_npi_tensorinv", num_inputs=1, no_trace=True,
          differentiable=False)
def _tensorinv(a, ind=2):
    # host-evaluated: LAPACK-class op, CPU-only in the reference too; the
    # TPU backend has no stable lowering (observed libtpu abort for svd)
    import numpy as onp

    return jnp.asarray(onp.linalg.tensorinv(onp.asarray(a), ind=int(ind)))


@register("_npi_tensorsolve", num_inputs=2, no_trace=True,
          differentiable=False)
def _tensorsolve(a, b, a_axes=None):
    import numpy as onp

    return jnp.asarray(onp.linalg.tensorsolve(onp.asarray(a),
                                              onp.asarray(b), axes=a_axes))


@register("_npi_svd", num_inputs=1, num_outputs=3, no_trace=True,
          differentiable=False, aliases=("linalg_gesvd",))
def _svd(a):
    import numpy as onp

    u, s, vt = onp.linalg.svd(onp.asarray(a), full_matrices=False)
    return jnp.asarray(u), jnp.asarray(s), jnp.asarray(vt)


@register("_npi_bincount", num_inputs=1, differentiable=False,
          no_trace=True, aliases=("bincount",))
def _bincount(x, minlength=0, weights=None):
    import numpy as onp

    w = None if weights is None else onp.asarray(weights)
    return jnp.asarray(onp.bincount(onp.asarray(x).astype(onp.int64),
                                    weights=w, minlength=int(minlength)))


@register("_npi_delete", num_inputs=1, differentiable=False, no_trace=True)
def _delete(arr, obj=None, start=None, stop=None, step=None, axis=None):
    import numpy as onp

    if obj is None and start is not None:
        obj = slice(int(start), None if stop is None else int(stop),
                    None if step is None else int(step))
    elif isinstance(obj, (tuple, list)):
        obj = [int(i) for i in obj]
    else:
        obj = int(obj)
    return jnp.asarray(onp.delete(onp.asarray(arr), obj, axis=axis))


@register("_npi_boolean_mask_assign_scalar", num_inputs=2)
def _boolean_mask_assign_scalar(data, mask, value=0.0):
    return jnp.where(mask.astype(bool), float(value), data)


@register("_npi_boolean_mask_assign_tensor", num_inputs=3)
def _boolean_mask_assign_tensor(data, mask, value):
    return jnp.where(mask.astype(bool), value, data)


@register("_npi_share_memory", num_inputs=2, differentiable=False,
          no_trace=True)
def _share_memory(a, b):
    # jax arrays never alias user buffers — matches np.shares_memory on
    # distinct ndarrays
    return jnp.asarray(False)


@register("_npi_normal_n", num_inputs=0, differentiable=False,
          needs_rng=True)
def _normal_n(loc=0.0, scale=1.0, size=None, key=None, dtype=None,
              ctx=None):
    return float(loc) + float(scale) * jax.random.normal(
        key, tuple(size) if size else ())


@register("_npi_uniform_n", num_inputs=0, differentiable=False,
          needs_rng=True)
def _uniform_n(low=0.0, high=1.0, size=None, key=None, dtype=None,
               ctx=None):
    return jax.random.uniform(key, tuple(size) if size else (),
                              minval=float(low), maxval=float(high))


@register("_npi_choice", num_inputs=0, differentiable=False, needs_rng=True)
def _choice(a=0, size=None, replace=True, weights=None, key=None, ctx=None):
    shape = tuple(size) if size else ()
    p = None if weights is None else jnp.asarray(weights)
    return jax.random.choice(key, int(a), shape, replace=bool(replace), p=p)


# -- remaining visible-name tail (final parity diff) -------------------------

for _np_name, _target in (("_np_broadcast_to", "broadcast_to"),
                          ("_np_cumsum", "cumsum"),
                          ("_np_diag", "diag"),
                          ("_np_dot", "dot"),
                          ("_np_max", "max"),
                          ("_np_min", "min"),
                          ("_np_prod", "prod"),
                          ("_np_reshape", "reshape"),
                          ("_np_squeeze", "squeeze"),
                          ("_np_sum", "sum"),
                          ("_np_transpose", "transpose"),
                          ("_rnn_param_concat", "concat"),
                          ("_contrib_SparseEmbedding", "Embedding")):
    if _target in OPS and _np_name not in OPS:
        _alias(_np_name, _target)


@register("_image_to_tensor", num_inputs=1, aliases=("to_tensor",))
def _image_to_tensor(x):
    """HWC uint8 [0,255] -> CHW float [0,1] (src/operator/image/
    image_random.cc ToTensor)."""
    x = x.astype(jnp.float32) / 255.0
    perm = (2, 0, 1) if x.ndim == 3 else (0, 3, 1, 2)
    return jnp.transpose(x, perm)


@register("_image_normalize", num_inputs=1)
def _image_normalize(x, mean=(0.0,), std=(1.0,)):
    """Per-channel normalize of CHW/NCHW float images."""
    mean = jnp.asarray(mean, jnp.float32)
    std = jnp.asarray(std, jnp.float32)
    shape = (-1, 1, 1) if x.ndim == 3 else (1, -1, 1, 1)
    return (x - mean.reshape(shape)) / std.reshape(shape)


@register("_image_resize", num_inputs=1)
def _image_resize(x, size=None, keep_ratio=False, interp=1):
    """Resize HWC/NHWC images (image_resize.cc); bilinear/nearest via
    jax.image.resize."""
    method = "nearest" if int(interp) == 0 else "linear"
    if isinstance(size, (tuple, list)):
        w, h = int(size[0]), int(size[1])
    else:
        w = h = int(size)
    if x.ndim == 3:
        shape = (h, w, x.shape[2])
    else:
        shape = (x.shape[0], h, w, x.shape[3])
    return jax.image.resize(x.astype(jnp.float32), shape,
                            method=method).astype(x.dtype)


@register("_image_crop", num_inputs=1)
def _image_crop(x, x_=0, y=0, width=1, height=1, x0=None, y0=None):
    """Spatial crop of HWC/NHWC images (image crop op)."""
    left = int(x0 if x0 is not None else x_)
    top = int(y0 if y0 is not None else y)
    if x.ndim == 3:
        return x[top:top + int(height), left:left + int(width), :]
    return x[:, top:top + int(height), left:left + int(width), :]


@register("cast_storage", num_inputs=1, differentiable=False,
          no_trace=True)
def _cast_storage(data, stype="default"):
    """dense<->CSR<->row_sparse (cast_storage.cc) — delegates to the sparse
    module; dense arrays pass through for 'default'."""
    if stype in ("default", None):
        return data
    raise NotImplementedError(
        "cast_storage to %r at the op layer: use "
        "ndarray.sparse.cast_storage on NDArray inputs (sparse formats "
        "carry python-side index structure)" % stype)


@register("_square_sum", num_inputs=1, differentiable=False)
def _square_sum(data, axis=None, keepdims=False):
    ax = None if axis is None else int(axis)
    return jnp.sum(jnp.square(data), axis=ax, keepdims=bool(keepdims))


@register("_multi_adamw_update", differentiable=False, num_outputs=None)
def _multi_adamw_update(*arrays, num_weights=None, lrs=(), wds=(), etas=(),
                        beta1=0.9, beta2=0.999, epsilon=1e-8,
                        rescale_grad=1.0, clip_gradient=-1.0):
    """Batched adamw (contrib/adamw.cc multi form): groups of
    (weight, grad, mean, var)."""
    out = []
    nw = len(arrays) // 4
    for i in range(nw):
        w, g, m, v = arrays[4 * i:4 * i + 4]
        g = g * float(rescale_grad)
        if float(clip_gradient) > 0:
            g = jnp.clip(g, -float(clip_gradient), float(clip_gradient))
        nm = float(beta1) * m + (1 - float(beta1)) * g
        nv = float(beta2) * v + (1 - float(beta2)) * jnp.square(g)
        w = w - float(etas[i]) * (
            float(lrs[i]) * nm / (jnp.sqrt(nv) + float(epsilon)) +
            float(wds[i]) * w)
        out.extend([w, nm, nv])
    return tuple(out)


@register("_multi_mp_adamw_update", differentiable=False, num_outputs=None)
def _multi_mp_adamw_update(*arrays, num_weights=None, lrs=(), wds=(),
                           etas=(), beta1=0.9, beta2=0.999, epsilon=1e-8,
                           rescale_grad=1.0, clip_gradient=-1.0):
    """Mixed-precision batched adamw: groups of (weight, grad, mean, var,
    weight32)."""
    out = []
    nw = len(arrays) // 5
    for i in range(nw):
        w, g, m, v, w32 = arrays[5 * i:5 * i + 5]
        g = g.astype(jnp.float32) * float(rescale_grad)
        if float(clip_gradient) > 0:
            g = jnp.clip(g, -float(clip_gradient), float(clip_gradient))
        nm = float(beta1) * m + (1 - float(beta1)) * g
        nv = float(beta2) * v + (1 - float(beta2)) * jnp.square(g)
        nw32 = w32 - float(etas[i]) * (
            float(lrs[i]) * nm / (jnp.sqrt(nv) + float(epsilon)) +
            float(wds[i]) * w32)
        out.extend([nw32.astype(w.dtype), nm, nv, nw32])
    return tuple(out)


@register("_contrib_calibrate_entropy", num_inputs=2, num_outputs=2,
          differentiable=False, no_trace=True)
def _calibrate_entropy(hist, hist_edges, num_quantized_bins=255):
    """KL-optimal quantization threshold from a histogram
    (src/operator/quantization/calibrate.cc) — delegates to the
    quantization module's calibrator."""
    import numpy as onp

    from ..contrib.quantization import _entropy_threshold_from_hist

    t = _entropy_threshold_from_hist(onp.asarray(hist),
                                     onp.asarray(hist_edges),
                                     int(num_quantized_bins))
    return (jnp.asarray(-t, jnp.float32), jnp.asarray(t, jnp.float32))
