"""Operator long tail: spatial warping, deformable ops, RPN proposals,
fused transformer matmuls, fft/count_sketch, masking/index utilities.

Reference parity targets (``/root/reference``):
- SpatialTransformer/GridGenerator (``src/operator/spatial_transformer.cc``,
  ``grid_generator.cc``), BilinearSampler (``bilinear_sampler.cc``),
  Correlation (``correlation.cc``), Crop (``crop.cc``)
- DeformableConvolution / DeformablePSROIPooling
  (``src/operator/contrib/deformable_convolution.cc``,
  ``deformable_psroi_pooling.cc``)
- Proposal / MultiProposal (``src/operator/contrib/proposal.cc``,
  ``multi_proposal.cc``)
- SyncBatchNorm (``src/operator/contrib/sync_batch_norm.cc``)
- interleaved_matmul_* + div_sqrt_dim
  (``src/operator/contrib/transformer.cc:125-255``)
- fft / ifft / count_sketch (``src/operator/contrib/fft.cc``, ``ifft.cc``,
  ``count_sketch.cc``)
- boolean_mask / index_copy / index_array
  (``src/operator/contrib/boolean_mask.cc``, ``index_copy.cc``,
  ``index_array.cc``)

TPU-native notes: everything is a pure jnp/lax function with static output
shapes except ``boolean_mask`` (inherently dynamic — eager-only, like the
reference's CPU-sync path).  Bilinear sampling is the shared primitive for
the whole warping family, expressed as gathers so XLA vectorizes it;
displacement/tap enumerations are static Python loops that unroll into the
program (K*K taps, D*D displacements — small constants the MXU pipeline
eats).  SyncBatchNorm under GSPMD needs no special comm: a batch-sharded
global array's mean IS the cross-device mean (the all-reduce is inserted by
the partitioner), which is exactly what the reference's cross-GPU reduction
emulates.
"""
from __future__ import annotations

import math as _math

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .nn import _batch_norm, _batch_norm_aux_update
from .registry import OPS, register

__all__ = []


# ---------------------------------------------------------------------------
# bilinear sampling primitive
# ---------------------------------------------------------------------------

def _bilinear_gather(data, xs, ys):
    """Sample data (N,C,H,W) at float pixel coords xs/ys (N, ...) with
    zero padding outside; differentiable in data and coords."""
    n, c, h, w = data.shape
    out_shape = xs.shape[1:]
    xs = xs.reshape(n, -1)
    ys = ys.reshape(n, -1)
    x0 = jnp.floor(xs)
    y0 = jnp.floor(ys)
    wx = xs - x0
    wy = ys - y0

    def tap(yi, xi):
        valid = ((xi >= 0) & (xi <= w - 1) & (yi >= 0)
                 & (yi <= h - 1)).astype(data.dtype)
        xc = jnp.clip(xi, 0, w - 1).astype(jnp.int32)
        yc = jnp.clip(yi, 0, h - 1).astype(jnp.int32)
        # gather per batch: (N, C, P)
        flat = data.reshape(n, c, h * w)
        idx = yc * w + xc  # (N, P)
        vals = jnp.take_along_axis(flat, idx[:, None, :].repeat(c, 1),
                                   axis=2)
        return vals * valid[:, None, :]

    v00 = tap(y0, x0)
    v01 = tap(y0, x0 + 1)
    v10 = tap(y0 + 1, x0)
    v11 = tap(y0 + 1, x0 + 1)
    wx = wx[:, None, :]
    wy = wy[:, None, :]
    out = (v00 * (1 - wx) * (1 - wy) + v01 * wx * (1 - wy)
           + v10 * (1 - wx) * wy + v11 * wx * wy)
    return out.reshape((n, c) + out_shape)


# ---------------------------------------------------------------------------
# GridGenerator / BilinearSampler / SpatialTransformer / Crop / Correlation
# ---------------------------------------------------------------------------

@register("GridGenerator", num_inputs=1)
def _grid_generator(data, transform_type="affine", target_shape=(0, 0)):
    """Affine: (N,6) params -> (N,2,H,W) sampling grid in [-1,1]; warp:
    (N,2,H,W) flow -> normalized identity+flow grid
    (grid_generator.cc semantics)."""
    if transform_type == "affine":
        h, w = int(target_shape[0]), int(target_shape[1])
        theta = data.reshape(-1, 2, 3)
        xs = jnp.linspace(-1.0, 1.0, w)
        ys = jnp.linspace(-1.0, 1.0, h)
        gx, gy = jnp.meshgrid(xs, ys)  # (H, W)
        ones = jnp.ones_like(gx)
        src = jnp.stack([gx.ravel(), gy.ravel(), ones.ravel()])  # (3, HW)
        out = jnp.einsum("nij,jp->nip", theta.astype(jnp.float32),
                         src.astype(jnp.float32))
        return out.reshape(-1, 2, h, w).astype(data.dtype)
    # warp: flow field added to the identity pixel grid, then normalized
    n, _two, h, w = data.shape
    gx, gy = jnp.meshgrid(jnp.arange(w, dtype=jnp.float32),
                          jnp.arange(h, dtype=jnp.float32))
    fx = data[:, 0].astype(jnp.float32) + gx
    fy = data[:, 1].astype(jnp.float32) + gy
    nx = 2.0 * fx / max(w - 1, 1) - 1.0
    ny = 2.0 * fy / max(h - 1, 1) - 1.0
    return jnp.stack([nx, ny], axis=1).astype(data.dtype)


@register("BilinearSampler", num_inputs=2)
def _bilinear_sampler(data, grid, cudnn_off=False):
    """Sample data (N,C,H,W) at grid (N,2,Ho,Wo) of normalized (x,y) in
    [-1,1]; zero padding outside (bilinear_sampler.cc)."""
    n, c, h, w = data.shape
    xs = (grid[:, 0].astype(jnp.float32) + 1.0) * (w - 1) / 2.0
    ys = (grid[:, 1].astype(jnp.float32) + 1.0) * (h - 1) / 2.0
    return _bilinear_gather(data.astype(jnp.float32), xs, ys).astype(
        data.dtype)


@register("SpatialTransformer", num_inputs=2)
def _spatial_transformer(data, loc, target_shape=(0, 0),
                         transform_type="affine", sampler_type="bilinear",
                         cudnn_off=False):
    """Affine grid from loc (N,6) + bilinear sampling
    (spatial_transformer.cc)."""
    grid = _grid_generator(loc, transform_type="affine",
                           target_shape=target_shape)
    return _bilinear_sampler(data, grid)


@register("Crop", num_inputs=None, differentiable=True)
def _crop(*args, offset=(0, 0), h_w=(0, 0), num_args=0, center_crop=False):
    """v1 Crop (crop.cc): crop args[0] to h_w or to args[1]'s spatial
    shape, at offset or centered."""
    data = args[0]
    if len(args) > 1 and args[1] is not None:
        th, tw = args[1].shape[2], args[1].shape[3]
    else:
        th, tw = int(h_w[0]), int(h_w[1])
    h, w = data.shape[2], data.shape[3]
    if center_crop:
        y0, x0 = (h - th) // 2, (w - tw) // 2
    else:
        y0, x0 = int(offset[0]), int(offset[1])
    return data[:, :, y0:y0 + th, x0:x0 + tw]


@register("Correlation", num_inputs=2)
def _correlation(data1, data2, kernel_size=1, max_displacement=1, stride1=1,
                 stride2=1, pad_size=0, is_multiply=True):
    """FlowNet correlation volume (correlation.cc): for each displacement
    (dy,dx) on the stride2 grid, the patchwise product (or abs-diff) of
    data1 and shifted data2, averaged over the kernel window and channels."""
    k = int(kernel_size)
    md = int(max_displacement)
    s1, s2, pad = int(stride1), int(stride2), int(pad_size)
    n, c, h, w = data1.shape
    d1 = jnp.pad(data1.astype(jnp.float32),
                 ((0, 0), (0, 0), (pad, pad), (pad, pad)))
    d2 = jnp.pad(data2.astype(jnp.float32),
                 ((0, 0), (0, 0), (pad, pad), (pad, pad)))
    grid_radius = md // s2
    disps = [(dy * s2, dx * s2)
             for dy in range(-grid_radius, grid_radius + 1)
             for dx in range(-grid_radius, grid_radius + 1)]
    hp, wp = h + 2 * pad, w + 2 * pad
    planes = []
    for dy, dx in disps:
        shifted = jnp.roll(d2, shift=(-dy, -dx), axis=(2, 3))
        prod = d1 * shifted if is_multiply else -jnp.abs(d1 - shifted)
        summed = prod.mean(axis=1)  # over channels -> (N, Hp, Wp)
        if k > 1:
            summed = lax.reduce_window(
                summed, 0.0, lax.add, (1, k, k), (1, 1, 1),
                [(0, 0), (k // 2, k // 2), (k // 2, k // 2)]) / (k * k)
        planes.append(summed)
    out = jnp.stack(planes, axis=1)  # (N, D*D, Hp, Wp)
    out = out[:, :, ::s1, ::s1]
    return out.astype(data1.dtype)


# ---------------------------------------------------------------------------
# Deformable ops
# ---------------------------------------------------------------------------

@register("_contrib_DeformableConvolution", num_inputs=None,
          aliases=("DeformableConvolution",))
def _deformable_convolution(data, offset, weight, bias=None, kernel=(3, 3),
                            num_filter=1, stride=(1, 1), pad=(0, 0),
                            dilate=(1, 1), num_deformable_group=1,
                            num_group=1, no_bias=False, workspace=1024,
                            layout=None):
    """Deformable conv v1 (deformable_convolution.cc): each kernel tap
    samples the input at its integer position plus a learned fractional
    offset (bilinear), then the taps contract with the weights — expressed
    here as K*K bilinear gathers + one matmul per tap (MXU-friendly; no
    im2col scratch)."""
    kh, kw = int(kernel[0]), int(kernel[1])
    sh, sw = int(stride[0]), int(stride[1])
    ph, pw = int(pad[0]), int(pad[1])
    dh, dw = int(dilate[0]), int(dilate[1])
    g = int(num_deformable_group)
    n, c, h, w = data.shape
    f = int(num_filter)
    ho = (h + 2 * ph - dh * (kh - 1) - 1) // sh + 1
    wo = (w + 2 * pw - dw * (kw - 1) - 1) // sw + 1

    base_y = jnp.arange(ho, dtype=jnp.float32) * sh - ph
    base_x = jnp.arange(wo, dtype=jnp.float32) * sw - pw
    gy, gx = jnp.meshgrid(base_y, base_x, indexing="ij")  # (Ho, Wo)

    dataf = data.astype(jnp.float32)
    off = offset.astype(jnp.float32).reshape(n, g, kh * kw, 2, ho, wo)
    cg = c // g
    out = jnp.zeros((n, f, ho, wo), jnp.float32)
    wmat = weight.astype(jnp.float32)
    for i in range(kh):
        for j in range(kw):
            tapi = i * kw + j
            sampled_groups = []
            for gi in range(g):
                dy = off[:, gi, tapi, 0]          # (N, Ho, Wo)
                dx = off[:, gi, tapi, 1]
                ys = gy[None] + i * dh + dy
                xs = gx[None] + j * dw + dx
                part = _bilinear_gather(
                    dataf[:, gi * cg:(gi + 1) * cg], xs, ys)
                sampled_groups.append(part)
            sampled = jnp.concatenate(sampled_groups, axis=1)  # (N,C,Ho,Wo)
            out = out + jnp.einsum("nchw,fc->nfhw", sampled, wmat[:, :, i, j])
    if bias is not None and not no_bias:
        out = out + bias.astype(jnp.float32).reshape(1, -1, 1, 1)
    return out.astype(data.dtype)


@register("_contrib_DeformablePSROIPooling", num_inputs=None,
          aliases=("DeformablePSROIPooling",))
def _deformable_psroi_pooling(data, rois, trans=None, spatial_scale=1.0,
                              output_dim=1, group_size=1, pooled_size=1,
                              part_size=0, sample_per_part=1, trans_std=0.0,
                              no_trans=False):
    """Position-sensitive ROI pooling with learned part offsets
    (deformable_psroi_pooling.cc).  data channels = output_dim * group^2;
    each pooled cell averages sample_per_part^2 bilinear samples from its
    position-sensitive channel group, optionally displaced by trans."""
    ps = int(pooled_size)
    gs = int(group_size)
    spp = int(sample_per_part)
    od = int(output_dim)
    part = int(part_size) or ps
    n, c, h, w = data.shape
    r = rois.shape[0]
    dataf = data.astype(jnp.float32)
    roisf = rois.astype(jnp.float32)

    batch_idx = roisf[:, 0].astype(jnp.int32)
    x1 = roisf[:, 1] * spatial_scale - 0.5
    y1 = roisf[:, 2] * spatial_scale - 0.5
    x2 = (roisf[:, 3] + 1.0) * spatial_scale - 0.5
    y2 = (roisf[:, 4] + 1.0) * spatial_scale - 0.5
    rw = jnp.maximum(x2 - x1, 0.1)
    rh = jnp.maximum(y2 - y1, 0.1)
    bin_w = rw / ps
    bin_h = rh / ps

    data_per_roi = dataf[batch_idx]  # (R, C, H, W)
    outs = []
    for py in range(ps):
        for px in range(ps):
            if no_trans or trans is None:
                ty = jnp.zeros((r,), jnp.float32)
                tx = jnp.zeros((r,), jnp.float32)
            else:
                tpy = min(py * part // ps, part - 1)
                tpx = min(px * part // ps, part - 1)
                transf = trans.astype(jnp.float32)
                cls = jnp.zeros((r,), jnp.int32)  # class-agnostic offsets
                ty = transf[jnp.arange(r) % transf.shape[0], 0, tpy,
                            tpx] * trans_std * rh
                tx = transf[jnp.arange(r) % transf.shape[0], 1, tpy,
                            tpx] * trans_std * rw
                del cls
            acc = 0.0
            for sy in range(spp):
                for sx in range(spp):
                    ys = (y1 + py * bin_h + (sy + 0.5) * bin_h / spp
                          + ty)[:, None, None]
                    xs = (x1 + px * bin_w + (sx + 0.5) * bin_w / spp
                          + tx)[:, None, None]
                    acc = acc + _bilinear_gather(data_per_roi, xs, ys)
            acc = acc / (spp * spp)  # (R, C, 1, 1)
            gy = min(py * gs // ps, gs - 1)
            gx = min(px * gs // ps, gs - 1)
            chan = acc[:, (gy * gs + gx) * od:(gy * gs + gx + 1) * od, 0, 0]
            outs.append(chan)  # (R, output_dim)
    out = jnp.stack(outs, axis=-1).reshape(r, od, ps, ps)
    return out.astype(data.dtype)


# ---------------------------------------------------------------------------
# RPN Proposal / MultiProposal
# ---------------------------------------------------------------------------

def _make_anchors(feat_h, feat_w, stride, scales, ratios):
    base = float(stride)
    px, py = (base - 1) / 2.0, (base - 1) / 2.0
    anchors = []
    for ratio in ratios:
        size = base * base
        size_r = size / ratio
        ws = round(_math.sqrt(size_r))
        hs = round(ws * ratio)
        for scale in scales:
            w_s, h_s = ws * scale, hs * scale
            anchors.append([px - (w_s - 1) / 2, py - (h_s - 1) / 2,
                            px + (w_s - 1) / 2, py + (h_s - 1) / 2])
    a = jnp.asarray(anchors, jnp.float32)  # (A, 4)
    sx = jnp.arange(feat_w, dtype=jnp.float32) * stride
    sy = jnp.arange(feat_h, dtype=jnp.float32) * stride
    gy, gx = jnp.meshgrid(sy, sx, indexing="ij")
    shifts = jnp.stack([gx.ravel(), gy.ravel(), gx.ravel(), gy.ravel()],
                       axis=1)  # (HW, 4)
    return (shifts[:, None, :] + a[None]).reshape(-1, 4)  # (HW*A, 4)


def _proposal_one(scores, deltas, im_info, anchors, pre_n, post_n,
                  nms_thresh, min_size, iou_loss):
    """scores (K,), deltas (K,4), anchors (K,4) -> (post_n, 5) [score,box]"""
    widths = anchors[:, 2] - anchors[:, 0] + 1.0
    heights = anchors[:, 3] - anchors[:, 1] + 1.0
    ctr_x = anchors[:, 0] + 0.5 * (widths - 1)
    ctr_y = anchors[:, 1] + 0.5 * (heights - 1)
    if iou_loss:
        x1 = anchors[:, 0] + deltas[:, 0]
        y1 = anchors[:, 1] + deltas[:, 1]
        x2 = anchors[:, 2] + deltas[:, 2]
        y2 = anchors[:, 3] + deltas[:, 3]
    else:
        px = deltas[:, 0] * widths + ctr_x
        py = deltas[:, 1] * heights + ctr_y
        pw = jnp.exp(jnp.clip(deltas[:, 2], -10, 10)) * widths
        ph = jnp.exp(jnp.clip(deltas[:, 3], -10, 10)) * heights
        x1 = px - 0.5 * (pw - 1)
        y1 = py - 0.5 * (ph - 1)
        x2 = px + 0.5 * (pw - 1)
        y2 = py + 0.5 * (ph - 1)
    imh, imw = im_info[0], im_info[1]
    x1 = jnp.clip(x1, 0, imw - 1.0)
    y1 = jnp.clip(y1, 0, imh - 1.0)
    x2 = jnp.clip(x2, 0, imw - 1.0)
    y2 = jnp.clip(y2, 0, imh - 1.0)
    ms = min_size * im_info[2]
    keep = ((x2 - x1 + 1) >= ms) & ((y2 - y1 + 1) >= ms)
    scores = jnp.where(keep, scores, -1.0)

    pre_n = min(pre_n, scores.shape[0])
    top_scores, order = lax.top_k(scores, pre_n)
    boxes = jnp.stack([x1, y1, x2, y2], axis=1)[order]  # (pre_n, 4)

    # greedy NMS over the static pre_n set (proposal.cc NonMaximumSuppress)
    def area(b):
        return (b[:, 2] - b[:, 0] + 1) * (b[:, 3] - b[:, 1] + 1)

    areas = area(boxes)

    def body(i, state):
        alive, picked_boxes, picked_scores, count = state
        # highest-scoring alive candidate
        masked = jnp.where(alive, top_scores, -jnp.inf)
        j = jnp.argmax(masked)
        ok = (masked[j] > -jnp.inf) & (count < post_n)
        bj = boxes[j]
        xx1 = jnp.maximum(boxes[:, 0], bj[0])
        yy1 = jnp.maximum(boxes[:, 1], bj[1])
        xx2 = jnp.minimum(boxes[:, 2], bj[2])
        yy2 = jnp.minimum(boxes[:, 3], bj[3])
        inter = jnp.maximum(xx2 - xx1 + 1, 0) * jnp.maximum(yy2 - yy1 + 1, 0)
        iou = inter / (areas + areas[j] - inter)
        suppress = iou > nms_thresh
        new_alive = alive & ~suppress & (jnp.arange(alive.shape[0]) != j)
        picked_boxes = lax.cond(
            ok, lambda pb: pb.at[count].set(bj), lambda pb: pb, picked_boxes)
        picked_scores = lax.cond(
            ok, lambda s: s.at[count].set(top_scores[j]), lambda s: s,
            picked_scores)
        return (jnp.where(ok, new_alive, alive), picked_boxes, picked_scores,
                count + ok.astype(jnp.int32))

    alive0 = top_scores > -1.0
    pb0 = jnp.zeros((post_n, 4), jnp.float32)
    ps0 = jnp.zeros((post_n,), jnp.float32)
    _alive, pboxes, pscores, cnt = lax.fori_loop(
        0, pre_n, body, (alive0, pb0, ps0, jnp.int32(0)))
    # pad empty slots with the first proposal (proposal.cc pads similarly)
    has = jnp.arange(post_n) < cnt
    pboxes = jnp.where(has[:, None], pboxes, pboxes[0])
    pscores = jnp.where(has, pscores, pscores[0])
    return pboxes, pscores


@register("_contrib_Proposal", num_inputs=3, differentiable=False,
          aliases=("Proposal",))
def _proposal(cls_prob, bbox_pred, im_info, rpn_pre_nms_top_n=6000,
              rpn_post_nms_top_n=300, threshold=0.7, rpn_min_size=16,
              scales=(4, 8, 16, 32), ratios=(0.5, 1, 2), feature_stride=16,
              output_score=False, iou_loss=False):
    """RPN proposal layer (proposal.cc): anchors + deltas -> clipped,
    min-size-filtered, NMS-pruned (batch_idx, x1, y1, x2, y2) rois."""
    n, two_a, fh, fw = cls_prob.shape
    a = two_a // 2
    anchors = _make_anchors(fh, fw, int(feature_stride),
                            [float(s) for s in scales],
                            [float(r) for r in ratios])
    outs, scores_out = [], []
    for b in range(n):
        fg = cls_prob[b, a:].astype(jnp.float32)          # (A, H, W)
        scores = fg.transpose(1, 2, 0).reshape(-1)         # HW*A order
        deltas = bbox_pred[b].astype(jnp.float32).reshape(
            a, 4, fh, fw).transpose(2, 3, 0, 1).reshape(-1, 4)
        boxes, sc = _proposal_one(
            scores, deltas, im_info[b].astype(jnp.float32), anchors,
            int(rpn_pre_nms_top_n), int(rpn_post_nms_top_n),
            float(threshold), float(rpn_min_size), bool(iou_loss))
        rois = jnp.concatenate(
            [jnp.full((boxes.shape[0], 1), float(b), jnp.float32), boxes],
            axis=1)
        outs.append(rois)
        scores_out.append(sc[:, None])
    rois = jnp.concatenate(outs, axis=0)
    if output_score:
        return rois, jnp.concatenate(scores_out, axis=0)
    return rois


OPS["_contrib_MultiProposal"] = OPS["_contrib_Proposal"]
OPS["MultiProposal"] = OPS["_contrib_Proposal"]


# ---------------------------------------------------------------------------
# SyncBatchNorm
# ---------------------------------------------------------------------------

@register("_contrib_SyncBatchNorm", aliases=("SyncBatchNorm",))
def _sync_batch_norm(data, gamma, beta, moving_mean, moving_var, eps=1e-3,
                     momentum=0.9, fix_gamma=True, use_global_stats=False,
                     output_mean_var=False, ndev=1, key=None, axis=1):
    """Cross-device BatchNorm (sync_batch_norm.cc).  Under GSPMD the batch
    axis is sharded over the mesh and jnp.mean over it already reduces
    across devices (the partitioner inserts the all-reduce), so the
    single-program BatchNorm IS synchronized — ndev/key are accepted for
    API parity and unused."""
    return _batch_norm(data, gamma, beta, moving_mean, moving_var, eps=eps,
                       momentum=momentum, fix_gamma=fix_gamma,
                       use_global_stats=use_global_stats,
                       output_mean_var=output_mean_var, axis=axis)


OPS["_contrib_SyncBatchNorm"].aux_update = _batch_norm_aux_update
OPS["_contrib_SyncBatchNorm"].mutate_idx = (3, 4)


# ---------------------------------------------------------------------------
# fused transformer matmuls (transformer.cc:125-255)
# ---------------------------------------------------------------------------

@register("_contrib_div_sqrt_dim", num_inputs=1)
def _div_sqrt_dim(data):
    return data / jnp.sqrt(jnp.float32(data.shape[-1])).astype(data.dtype)


def _split_qkv(qkv, heads, n_parts):
    """(S, B, heads*hd*n) -> tuple of (B*heads, S, hd)"""
    s, b, proj = qkv.shape
    hd = proj // (heads * n_parts)
    tmp = qkv.reshape(s, b, heads, n_parts, hd)
    outs = []
    for i in range(n_parts):
        p = tmp[:, :, :, i, :].transpose(1, 2, 0, 3)  # (B, heads, S, hd)
        outs.append(p.reshape(b * heads, s, hd))
    return outs


@register("_contrib_interleaved_matmul_selfatt_qk", num_inputs=1)
def _interleaved_matmul_selfatt_qk(queries_keys_values, heads=1):
    """(S, B, H*hd*3) -> scaled QK^T scores (B*H, S, S)."""
    q, k, _v = _split_qkv(queries_keys_values, int(heads), 3)
    q = q / jnp.sqrt(jnp.float32(q.shape[-1])).astype(q.dtype)
    return jnp.einsum("bqd,bkd->bqk", q, k)


@register("_contrib_interleaved_matmul_selfatt_valatt", num_inputs=2)
def _interleaved_matmul_selfatt_valatt(queries_keys_values, attention,
                                       heads=1):
    """attention (B*H, S, S) @ V -> (S, B, H*hd)."""
    s, b, proj3 = queries_keys_values.shape
    h = int(heads)
    _q, _k, v = _split_qkv(queries_keys_values, h, 3)
    out = jnp.einsum("bqk,bkd->bqd", attention, v)  # (B*H, S, hd)
    hd = out.shape[-1]
    return out.reshape(b, h, s, hd).transpose(2, 0, 1, 3).reshape(s, b,
                                                                  h * hd)


@register("_contrib_interleaved_matmul_encdec_qk", num_inputs=2)
def _interleaved_matmul_encdec_qk(queries, keys_values, heads=1):
    """queries (Sq, B, H*hd), keys_values (Sk, B, H*hd*2) ->
    (B*H, Sq, Sk)."""
    h = int(heads)
    (q,) = _split_qkv(queries, h, 1)
    k, _v = _split_qkv(keys_values, h, 2)
    q = q / jnp.sqrt(jnp.float32(q.shape[-1])).astype(q.dtype)
    return jnp.einsum("bqd,bkd->bqk", q, k)


@register("_contrib_interleaved_matmul_encdec_valatt", num_inputs=2)
def _interleaved_matmul_encdec_valatt(keys_values, attention, heads=1):
    sk, b, proj2 = keys_values.shape
    h = int(heads)
    _k, v = _split_qkv(keys_values, h, 2)
    out = jnp.einsum("bqk,bkd->bqd", attention, v)
    hd = out.shape[-1]
    sq = attention.shape[1]
    return out.reshape(b, h, sq, hd).transpose(2, 0, 1, 3).reshape(sq, b,
                                                                   h * hd)


# ---------------------------------------------------------------------------
# fft / ifft / count_sketch
# ---------------------------------------------------------------------------

@register("_contrib_fft", num_inputs=1)
def _fft(data, compute_size=128):
    """1-D FFT over the last dim; output interleaves [re, im, re, im, ...]
    (fft.cc: (N, d) -> (N, 2d))."""
    spec = jnp.fft.fft(data.astype(jnp.float32), axis=-1)
    out = jnp.stack([spec.real, spec.imag], axis=-1)
    return out.reshape(data.shape[:-1] + (2 * data.shape[-1],)).astype(
        jnp.float32)


@register("_contrib_ifft", num_inputs=1)
def _ifft(data, compute_size=128):
    """Inverse of _contrib_fft: interleaved complex (N, 2d) -> real (N, d),
    unnormalized like cuFFT (ifft(fft(x)) == x * d — ifft.cc)."""
    d = data.shape[-1] // 2
    pairs = data.astype(jnp.float32).reshape(data.shape[:-1] + (d, 2))
    spec = pairs[..., 0] + 1j * pairs[..., 1]
    out = jnp.fft.ifft(spec, axis=-1).real * d
    return out.astype(jnp.float32)


@register("_contrib_count_sketch", num_inputs=3, differentiable=False)
def _count_sketch(data, h, s, out_dim=1, processing_batch_size=32):
    """Count sketch projection (count_sketch.cc): out[:, h[j]] +=
    s[j] * data[:, j]."""
    k = int(out_dim)
    n, d = data.shape
    hv = jnp.broadcast_to(h.astype(jnp.int32).reshape(-1, d), (n, d))
    sv = jnp.broadcast_to(s.astype(data.dtype).reshape(-1, d), (n, d))
    out = jnp.zeros((n, k), data.dtype)
    rows = jnp.broadcast_to(jnp.arange(n)[:, None], (n, d))
    return out.at[rows, hv].add(data * sv)


# ---------------------------------------------------------------------------
# boolean_mask / index_copy / index_array
# ---------------------------------------------------------------------------

@register("_contrib_boolean_mask", num_inputs=2, aliases=("boolean_mask",),
          no_trace=True)
def _boolean_mask(data, index, axis=0):
    """Select slices where index != 0 (boolean_mask.cc).  Output shape is
    data-dependent → eager-only, like the reference's CPU-sync kernel; use
    masking idioms inside compiled code."""
    import numpy as onp

    idx = onp.nonzero(onp.asarray(index) != 0)[0]
    return jnp.take(data, jnp.asarray(idx), axis=int(axis))


@register("_contrib_index_copy", num_inputs=3)
def _index_copy(old, index, new):
    """Functional index_copy (index_copy.cc): rows of ``new`` written into
    ``old`` at ``index``."""
    return old.at[index.astype(jnp.int32)].set(new.astype(old.dtype))


@register("_contrib_index_array", num_inputs=1, differentiable=False)
def _index_array(data, axes=None):
    """Per-element N-D indices (index_array.cc): output shape
    data.shape + (len(axes),)."""
    shape = data.shape
    if axes is None:
        axes = tuple(range(len(shape)))
    else:
        axes = tuple(int(a) for a in axes)
    grids = jnp.meshgrid(*[jnp.arange(s, dtype=jnp.int64) for s in shape],
                         indexing="ij")
    return jnp.stack([grids[a] for a in axes], axis=-1)
