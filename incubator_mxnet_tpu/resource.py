"""Per-context shared resources (ResourceManager parity).

Reference: ``include/mxnet/resource.h:38-130`` + ``src/resource.cc:87`` —
ops request shared resources (``kTempSpace`` scratch, ``kRandom`` /
``kParallelRandom`` generators) from a per-device manager instead of
allocating privately.

TPU-native mapping: device scratch inside compiled programs is XLA's
business (buffer assignment), so ``kTempSpace`` here serves the HOST side —
pooled aligned buffers from the native storage manager
(``src/native/storage.cc``) reused across requests, which is what IO
pipelines, decoders and checkpoint writers need.  ``kRandom`` hands out the
process PRNG stream (``rng.py``); ``kParallelRandom`` derives independent
streams by folding in a per-resource index (the philox analog of the
reference's sliced parallel sample streams).
"""
from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

__all__ = ["ResourceRequest", "Resource", "request", "ResourceManager"]


class ResourceRequest:
    """resource.h:38 ResourceRequest::Type."""

    kRandom = "random"
    kTempSpace = "temp_space"
    kParallelRandom = "parallel_random"

    def __init__(self, type):  # noqa: A002
        self.type = type


class Resource:
    """A granted resource (resource.h:130 surface)."""

    def __init__(self, req: ResourceRequest, manager: "ResourceManager",
                 idx: int):
        self.req = req
        self._manager = manager
        self._idx = idx

    # -- kTempSpace ---------------------------------------------------------
    def get_space(self, shape, dtype="float32") -> np.ndarray:
        """Host scratch of at least the requested size, recycled from the
        pooled storage manager; contents are undefined (resource.h:130)."""
        if self.req.type != ResourceRequest.kTempSpace:
            raise TypeError("get_space on a %s resource" % self.req.type)
        return self._manager._temp_space(shape, dtype, self._idx)

    get_host_space = get_space

    # -- kRandom / kParallelRandom -----------------------------------------
    def get_random(self):
        """A fresh PRNG key from this resource's stream."""
        import jax

        from . import rng

        if self.req.type == ResourceRequest.kRandom:
            return rng.next_key()
        if self.req.type == ResourceRequest.kParallelRandom:
            with jax.ensure_compile_time_eval():
                return jax.random.fold_in(rng.next_key(), self._idx)
        raise TypeError("get_random on a %s resource" % self.req.type)


class ResourceManager:
    """Per-process manager (src/resource.cc:87 analog): temp buffers are
    cached by slot so repeated requests reuse one growing allocation, like
    the reference's per-device temp space."""

    def __init__(self):
        self._slots: Dict[int, np.ndarray] = {}
        self._handles: Dict[int, object] = {}  # native allocs kept alive
        self._count = 0

    def request(self, req: ResourceRequest) -> Resource:
        idx = self._count
        self._count += 1
        return Resource(req, self, idx)

    def _temp_space(self, shape, dtype, idx) -> np.ndarray:
        nbytes = int(np.prod(shape)) * np.dtype(dtype).itemsize
        buf = self._slots.get(idx)
        if buf is None or buf.nbytes < nbytes:
            # pooled aligned allocation via the native storage manager when
            # available; plain numpy otherwise
            try:
                from . import storage

                handle = storage.alloc(max(nbytes, 64))
                buf = handle.array
                old = self._handles.get(idx)
                self._handles[idx] = handle  # keep the native alloc alive
                if old is not None:
                    storage.free(old)
            except Exception:
                buf = np.empty(max(nbytes, 64), np.uint8)
                self._handles.pop(idx, None)
            self._slots[idx] = buf
        return buf[:nbytes].view(np.dtype(dtype)).reshape(shape)


_MANAGER: Optional[ResourceManager] = None


def _manager() -> ResourceManager:
    global _MANAGER
    if _MANAGER is None:
        _MANAGER = ResourceManager()
    return _MANAGER


def request(req) -> Resource:
    """Request a resource from the global manager
    (``ResourceManager::Get()->Request`` analog)."""
    if isinstance(req, str):
        req = ResourceRequest(req)
    return _manager().request(req)
