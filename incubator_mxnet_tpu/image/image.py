"""``mx.image`` — image loading, augmentation, ImageIter (reference:
python/mxnet/image/image.py — imdecode :95, imresize :136, ImageIter
:1139, Augmenter :615, CreateAugmenter :1002).

The reference decodes JPEG via OpenCV inside the C++ iterator; here PIL
does host-side decode (numpy HWC uint8) and all augmenters are pure
numpy — the device only ever sees the final batched tensor, keeping
host→HBM transfers to one per batch.
"""
from __future__ import annotations

import io as _io
import os
import random as _pyrandom
from typing import List, Optional

import numpy as np

from .. import recordio
from ..io.io import DataBatch, DataDesc, DataIter
from ..ndarray import NDArray
from ..ndarray import ndarray as nd

__all__ = ["imread", "imdecode", "imresize", "resize_short", "fixed_crop",
           "center_crop", "random_crop", "random_size_crop",
           "color_normalize", "Augmenter", "SequentialAug", "RandomOrderAug",
           "CastAug", "ResizeAug", "ForceResizeAug", "RandomCropAug",
           "RandomSizedCropAug", "CenterCropAug", "BrightnessJitterAug",
           "ContrastJitterAug", "SaturationJitterAug", "HueJitterAug",
           "ColorJitterAug", "LightingAug", "ColorNormalizeAug",
           "RandomGrayAug", "HorizontalFlipAug", "CreateAugmenter",
           "ImageIter"]


def _to_nd(a):
    return a if isinstance(a, NDArray) else nd.array(a)


def _to_np(a):
    return a.asnumpy() if isinstance(a, NDArray) else np.asarray(a)


def imdecode(buf, flag=1, to_rgb=True, **kwargs):
    """Decode an image byte buffer → HWC uint8 NDArray (image.py:95)."""
    from PIL import Image
    img = Image.open(_io.BytesIO(bytes(buf)))
    if flag == 0:
        img = img.convert("L")
        arr = np.asarray(img)[:, :, None]
    else:
        img = img.convert("RGB")
        arr = np.asarray(img)
        if not to_rgb:
            arr = arr[:, :, ::-1]  # BGR like OpenCV default
    return nd.array(np.ascontiguousarray(arr), dtype="uint8")


def imread(filename, flag=1, to_rgb=True):
    """Read an image file (image.py:180)."""
    with open(filename, "rb") as f:
        return imdecode(f.read(), flag=flag, to_rgb=to_rgb)


_PIL_INTERP = {0: 0, 1: 2, 2: 3, 3: 0, 4: 1}  # cv2 code → PIL resample


def imresize(src, w, h, interp=1):
    """Resize to exactly (w, h) (image.py:136)."""
    from PIL import Image
    arr = _to_np(src)
    squeeze = arr.shape[-1] == 1
    img = Image.fromarray(arr.squeeze(-1) if squeeze else arr)
    img = img.resize((int(w), int(h)), _PIL_INTERP.get(interp, 2))
    out = np.asarray(img)
    if squeeze:
        out = out[:, :, None]
    return nd.array(out, dtype=str(arr.dtype))


def resize_short(src, size, interp=2):
    """Resize shorter edge to ``size`` keeping aspect (image.py:349)."""
    arr = _to_np(src)
    h, w = arr.shape[:2]
    if h > w:
        new_w, new_h = size, int(h * size / w)
    else:
        new_w, new_h = int(w * size / h), size
    return imresize(src, new_w, new_h, interp)


def fixed_crop(src, x0, y0, w, h, size=None, interp=2):
    """Crop a fixed region, optionally resize (image.py:393)."""
    arr = _to_np(src)
    out = arr[y0:y0 + h, x0:x0 + w]
    if size is not None and (w, h) != size:
        return imresize(out, size[0], size[1], interp)
    return nd.array(out, dtype=str(arr.dtype))


def center_crop(src, size, interp=2):
    """Center crop → (cropped, (x0, y0, w, h)) (image.py:470)."""
    arr = _to_np(src)
    h, w = arr.shape[:2]
    new_w, new_h = size
    x0 = int((w - new_w) / 2)
    y0 = int((h - new_h) / 2)
    out = fixed_crop(src, x0, y0, new_w, new_h, size, interp)
    return out, (x0, y0, new_w, new_h)


def random_crop(src, size, interp=2):
    """Uniform random crop → (cropped, region) (image.py:429)."""
    arr = _to_np(src)
    h, w = arr.shape[:2]
    new_w, new_h = min(size[0], w), min(size[1], h)
    x0 = _pyrandom.randint(0, w - new_w)
    y0 = _pyrandom.randint(0, h - new_h)
    out = fixed_crop(src, x0, y0, new_w, new_h, size, interp)
    return out, (x0, y0, new_w, new_h)


def random_size_crop(src, size, area, ratio, interp=2):
    """Random area+aspect crop (Inception-style) (image.py:523)."""
    arr = _to_np(src)
    h, w = arr.shape[:2]
    src_area = h * w
    if isinstance(area, (int, float)):
        area = (area, 1.0)
    for _ in range(10):
        target_area = _pyrandom.uniform(*area) * src_area
        log_ratio = (np.log(ratio[0]), np.log(ratio[1]))
        new_ratio = np.exp(_pyrandom.uniform(*log_ratio))
        new_w = int(round(np.sqrt(target_area * new_ratio)))
        new_h = int(round(np.sqrt(target_area / new_ratio)))
        if new_w <= w and new_h <= h:
            x0 = _pyrandom.randint(0, w - new_w)
            y0 = _pyrandom.randint(0, h - new_h)
            out = fixed_crop(src, x0, y0, new_w, new_h, size, interp)
            return out, (x0, y0, new_w, new_h)
    return center_crop(src, size, interp)


def color_normalize(src, mean, std=None):
    """(src - mean) / std (image.py:500)."""
    arr = _to_np(src).astype(np.float32)
    arr = arr - _to_np(mean).astype(np.float32)
    if std is not None:
        arr = arr / _to_np(std).astype(np.float32)
    return nd.array(arr.astype(np.float32))


# ---------------------------------------------------------------------------
# augmenters (image.py:615-1000)
# ---------------------------------------------------------------------------

class Augmenter:
    """Base augmenter (image.py:615)."""

    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def dumps(self):
        import json
        return json.dumps([self.__class__.__name__.lower(), self._kwargs])

    def __call__(self, src):
        raise NotImplementedError


class SequentialAug(Augmenter):
    def __init__(self, ts):
        super().__init__()
        self.ts = ts

    def __call__(self, src):
        for t in self.ts:
            src = t(src)
        return src


class RandomOrderAug(Augmenter):
    def __init__(self, ts):
        super().__init__()
        self.ts = ts

    def __call__(self, src):
        ts = list(self.ts)
        _pyrandom.shuffle(ts)
        for t in ts:
            src = t(src)
        return src


class CastAug(Augmenter):
    def __init__(self, typ="float32"):
        super().__init__(type=typ)
        self.typ = typ

    def __call__(self, src):
        return nd.array(_to_np(src).astype(self.typ))


class ResizeAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size, self.interp = size, interp

    def __call__(self, src):
        return resize_short(src, self.size, self.interp)


class ForceResizeAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size, self.interp = size, interp

    def __call__(self, src):
        return imresize(src, self.size[0], self.size[1], self.interp)


class RandomCropAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size, self.interp = size, interp

    def __call__(self, src):
        return random_crop(src, self.size, self.interp)[0]


class RandomSizedCropAug(Augmenter):
    def __init__(self, size, area, ratio, interp=2):
        super().__init__(size=size, area=area, ratio=ratio, interp=interp)
        self.size, self.area, self.ratio, self.interp = \
            size, area, ratio, interp

    def __call__(self, src):
        return random_size_crop(src, self.size, self.area, self.ratio,
                                self.interp)[0]


class CenterCropAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size, self.interp = size, interp

    def __call__(self, src):
        return center_crop(src, self.size, self.interp)[0]


class HorizontalFlipAug(Augmenter):
    def __init__(self, p):
        super().__init__(p=p)
        self.p = p

    def __call__(self, src):
        if _pyrandom.random() < self.p:
            return nd.array(_to_np(src)[:, ::-1].copy())
        return _to_nd(src)


class BrightnessJitterAug(Augmenter):
    def __init__(self, brightness):
        super().__init__(brightness=brightness)
        self.brightness = brightness

    def __call__(self, src):
        alpha = 1.0 + _pyrandom.uniform(-self.brightness, self.brightness)
        return nd.array(_to_np(src).astype(np.float32) * alpha)


_GRAY = np.array([0.299, 0.587, 0.114], np.float32)


class ContrastJitterAug(Augmenter):
    def __init__(self, contrast):
        super().__init__(contrast=contrast)
        self.contrast = contrast

    def __call__(self, src):
        alpha = 1.0 + _pyrandom.uniform(-self.contrast, self.contrast)
        arr = _to_np(src).astype(np.float32)
        gray = (arr * _GRAY).sum(axis=2, keepdims=True).mean()
        return nd.array(arr * alpha + gray * (1 - alpha))


class SaturationJitterAug(Augmenter):
    def __init__(self, saturation):
        super().__init__(saturation=saturation)
        self.saturation = saturation

    def __call__(self, src):
        alpha = 1.0 + _pyrandom.uniform(-self.saturation, self.saturation)
        arr = _to_np(src).astype(np.float32)
        gray = (arr * _GRAY).sum(axis=2, keepdims=True)
        return nd.array(arr * alpha + gray * (1 - alpha))


class HueJitterAug(Augmenter):
    def __init__(self, hue):
        super().__init__(hue=hue)
        self.hue = hue

    def __call__(self, src):
        alpha = _pyrandom.uniform(-self.hue, self.hue)
        arr = _to_np(src).astype(np.float32)
        u = np.cos(alpha * np.pi)
        w = np.sin(alpha * np.pi)
        t_yiq = np.array([[0.299, 0.587, 0.114],
                          [0.596, -0.274, -0.321],
                          [0.211, -0.523, 0.311]], np.float32)
        t_rgb = np.array([[1.0, 0.956, 0.621],
                          [1.0, -0.272, -0.647],
                          [1.0, -1.107, 1.705]], np.float32)
        rot = np.array([[1, 0, 0], [0, u, -w], [0, w, u]], np.float32)
        m = t_rgb @ rot @ t_yiq
        return nd.array(arr @ m.T)


class ColorJitterAug(RandomOrderAug):
    def __init__(self, brightness, contrast, saturation):
        ts = []
        if brightness > 0:
            ts.append(BrightnessJitterAug(brightness))
        if contrast > 0:
            ts.append(ContrastJitterAug(contrast))
        if saturation > 0:
            ts.append(SaturationJitterAug(saturation))
        super().__init__(ts)


class LightingAug(Augmenter):
    """PCA noise (AlexNet-style) (image.py:906)."""

    def __init__(self, alphastd, eigval, eigvec):
        super().__init__(alphastd=alphastd)
        self.alphastd = alphastd
        self.eigval = _to_np(eigval)
        self.eigvec = _to_np(eigvec)

    def __call__(self, src):
        alpha = np.random.normal(0, self.alphastd, size=(3,))
        rgb = (self.eigvec * alpha * self.eigval).sum(axis=1)
        return nd.array(_to_np(src).astype(np.float32) + rgb)


class ColorNormalizeAug(Augmenter):
    def __init__(self, mean, std):
        super().__init__()
        self.mean = mean
        self.std = std

    def __call__(self, src):
        return color_normalize(src, self.mean, self.std)


class RandomGrayAug(Augmenter):
    def __init__(self, p):
        super().__init__(p=p)
        self.p = p

    def __call__(self, src):
        if _pyrandom.random() < self.p:
            arr = _to_np(src).astype(np.float32)
            gray = (arr * _GRAY).sum(axis=2, keepdims=True)
            return nd.array(np.repeat(gray, 3, axis=2))
        return _to_nd(src)


def CreateAugmenter(data_shape, resize=0, rand_crop=False, rand_resize=False,  # noqa: N802
                    rand_mirror=False, mean=None, std=None, brightness=0,
                    contrast=0, saturation=0, hue=0, pca_noise=0,
                    rand_gray=0, inter_method=2):
    """Standard augmenter pipeline factory (image.py:1002)."""
    auglist: List[Augmenter] = []
    if resize > 0:
        auglist.append(ResizeAug(resize, inter_method))
    crop_size = (data_shape[2], data_shape[1])
    if rand_resize:
        auglist.append(RandomSizedCropAug(crop_size, (0.08, 1.0),
                                          (3 / 4., 4 / 3.), inter_method))
    elif rand_crop:
        auglist.append(RandomCropAug(crop_size, inter_method))
    else:
        auglist.append(CenterCropAug(crop_size, inter_method))
    if rand_mirror:
        auglist.append(HorizontalFlipAug(0.5))
    auglist.append(CastAug())
    if brightness or contrast or saturation:
        auglist.append(ColorJitterAug(brightness, contrast, saturation))
    if hue:
        auglist.append(HueJitterAug(hue))
    if pca_noise > 0:
        eigval = np.array([55.46, 4.794, 1.148])
        eigvec = np.array([[-0.5675, 0.7192, 0.4009],
                           [-0.5808, -0.0045, -0.8140],
                           [-0.5836, -0.6948, 0.4203]])
        auglist.append(LightingAug(pca_noise, eigval, eigvec))
    if rand_gray > 0:
        auglist.append(RandomGrayAug(rand_gray))
    if mean is True:
        mean = np.array([123.68, 116.28, 103.53])
    if std is True:
        std = np.array([58.395, 57.12, 57.375])
    if mean is not None or std is not None:
        auglist.append(ColorNormalizeAug(
            mean if mean is not None else np.zeros(3, np.float32), std))
    return auglist


# ---------------------------------------------------------------------------
# ImageIter (image.py:1139)
# ---------------------------------------------------------------------------

class ImageIter(DataIter):
    """Image iterator reading .rec files or an image list, with augmenter
    chain; emits NCHW float batches (image.py:1139)."""

    def __init__(self, batch_size, data_shape, label_width=1,
                 path_imgrec=None, path_imglist=None, path_root="",
                 path_imgidx=None, shuffle=False, part_index=0, num_parts=1,
                 aug_list=None, imglist=None, data_name="data",
                 label_name="softmax_label", dtype="float32",
                 last_batch_handle="pad", **kwargs):
        super().__init__(batch_size)
        assert len(data_shape) == 3 and data_shape[0] in (1, 3)
        self.data_shape = data_shape
        self.label_width = label_width
        self.path_root = path_root
        self.shuffle = shuffle
        self.dtype = dtype

        self.imgrec = None
        self.imglist = None
        if path_imgrec:
            if path_imgidx and os.path.exists(path_imgidx):
                self.imgrec = recordio.MXIndexedRecordIO(
                    path_imgidx, path_imgrec, "r")
                self.seq = list(self.imgrec.keys)
            else:
                self.imgrec = recordio.MXRecordIO(path_imgrec, "r")
                self.seq = None
        elif path_imglist or imglist is not None:
            entries = {}
            if path_imglist:
                with open(path_imglist) as f:
                    for line in f:
                        parts = line.strip().split("\t")
                        label = np.array(parts[1:-1], np.float32)
                        entries[int(parts[0])] = (label, parts[-1])
            else:
                for i, item in enumerate(imglist):
                    label = np.array(item[0] if isinstance(item[0],
                                                           (list, tuple))
                                     else [item[0]], np.float32)
                    entries[i] = (label, item[1])
            self.imglist = entries
            self.seq = list(entries.keys())
        else:
            raise ValueError("path_imgrec, path_imglist or imglist required")

        if num_parts > 1 and self.seq is not None:
            n_per = len(self.seq) // num_parts
            self.seq = self.seq[part_index * n_per:(part_index + 1) * n_per]

        if aug_list is None:
            aug_list = CreateAugmenter(data_shape)
        self.auglist = aug_list

        self.provide_data = [DataDesc(data_name,
                                      (batch_size,) + data_shape, dtype)]
        self.provide_label = [DataDesc(label_name,
                                       (batch_size, label_width)
                                       if label_width > 1
                                       else (batch_size,), dtype)]
        self.cur = 0
        self.reset()

    def reset(self):
        self.cur = 0
        if self.shuffle and self.seq is not None:
            _pyrandom.shuffle(self.seq)
        if self.imgrec is not None and self.seq is None:
            self.imgrec.reset()

    def next_sample(self):
        """Next (label, decoded image array) (image.py:1246)."""
        if self.seq is not None:
            if self.cur >= len(self.seq):
                raise StopIteration
            idx = self.seq[self.cur]
            self.cur += 1
            if self.imgrec is not None:
                s = self.imgrec.read_idx(idx)
                header, img = recordio.unpack(s)
                label = header.label
                return label, imdecode(img)
            label, fname = self.imglist[idx]
            return label, imread(os.path.join(self.path_root, fname))
        s = self.imgrec.read()
        if s is None:
            raise StopIteration
        header, img = recordio.unpack(s)
        return header.label, imdecode(img)

    def next(self):  # noqa: A003
        c, h, w = self.data_shape
        batch_data = np.zeros((self.batch_size, h, w, c), np.float32)
        batch_label = np.zeros((self.batch_size, self.label_width),
                               np.float32)
        i = 0
        try:
            while i < self.batch_size:
                label, img = self.next_sample()
                for aug in self.auglist:
                    img = aug(img)
                arr = _to_np(img)
                if arr.ndim == 2:
                    arr = arr[:, :, None]
                batch_data[i] = arr
                batch_label[i] = np.asarray(label, np.float32).reshape(-1)[
                    :self.label_width]
                i += 1
        except StopIteration:
            if i == 0:
                raise
        pad = self.batch_size - i
        data = nd.array(batch_data.transpose(0, 3, 1, 2), dtype=self.dtype)
        label = nd.array(batch_label if self.label_width > 1
                         else batch_label[:, 0], dtype=self.dtype)
        return DataBatch([data], [label], pad=pad)
