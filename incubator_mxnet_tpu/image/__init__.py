"""``mx.image`` (reference: python/mxnet/image/__init__.py)."""
from .image import *  # noqa: F401,F403
from .image import __all__ as _img_all
from .detection import *  # noqa: F401,F403
from .detection import __all__ as _det_all

__all__ = list(_img_all) + list(_det_all)
