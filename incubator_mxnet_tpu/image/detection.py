"""``mx.image`` detection iterator (reference:
python/mxnet/image/detection.py — ImageDetIter :626).

Labels are object lists: each image's label is (N_obj, 5+) rows
[class, xmin, ymin, xmax, ymax, ...] in normalized coords, padded with -1
rows to the batch-wide maximum (the header format MultiBoxTarget
consumes)."""
from __future__ import annotations

import random as _pyrandom
from typing import List

import numpy as np

from ..io.io import DataBatch, DataDesc
from ..ndarray import ndarray as nd
from .image import (Augmenter, ImageIter, _to_np, imresize)

__all__ = ["ImageDetIter", "DetAugmenter", "DetHorizontalFlipAug",
           "DetBorrowAug", "CreateDetAugmenter"]


class DetAugmenter:
    """Augmenter operating on (image, label) jointly (detection.py:41)."""

    def __call__(self, src, label):
        raise NotImplementedError


class DetBorrowAug(DetAugmenter):
    """Wrap an image-only Augmenter (detection.py:116)."""

    def __init__(self, augmenter: Augmenter):
        self.augmenter = augmenter

    def __call__(self, src, label):
        return self.augmenter(src), label


class DetHorizontalFlipAug(DetAugmenter):
    """Flip image and mirror box x-coords (detection.py:147)."""

    def __init__(self, p):
        self.p = p

    def __call__(self, src, label):
        if _pyrandom.random() < self.p:
            src = nd.array(_to_np(src)[:, ::-1].copy())
            valid = label[:, 0] >= 0
            tmp = 1.0 - label[valid, 1]
            label[valid, 1] = 1.0 - label[valid, 3]
            label[valid, 3] = tmp
        return src, label


def CreateDetAugmenter(data_shape, resize=0, rand_mirror=False, mean=None,  # noqa: N802
                       std=None, **kwargs):
    """Standard detection augmenter chain (detection.py:489)."""
    from .image import (CastAug, ColorNormalizeAug, ForceResizeAug)
    auglist: List[DetAugmenter] = []
    auglist.append(DetBorrowAug(ForceResizeAug(
        (data_shape[2], data_shape[1]))))
    if rand_mirror:
        auglist.append(DetHorizontalFlipAug(0.5))
    auglist.append(DetBorrowAug(CastAug()))
    if mean is True:
        mean = np.array([123.68, 116.28, 103.53])
    if std is True:
        std = np.array([58.395, 57.12, 57.375])
    if mean is not None or std is not None:
        auglist.append(DetBorrowAug(ColorNormalizeAug(
            mean if mean is not None else np.zeros(3, np.float32), std)))
    return auglist


class ImageDetIter(ImageIter):
    """Detection iterator: object-list labels (detection.py:626)."""

    def __init__(self, batch_size, data_shape, path_imgrec=None,
                 path_imglist=None, path_root="", imglist=None,
                 shuffle=False, aug_list=None, data_name="data",
                 label_name="label", **kwargs):
        if aug_list is None:
            aug_list = CreateDetAugmenter(data_shape)
        super().__init__(batch_size, data_shape, label_width=-1,
                         path_imgrec=path_imgrec, path_imglist=path_imglist,
                         path_root=path_root, imglist=imglist,
                         shuffle=shuffle, aug_list=[],
                         data_name=data_name, label_name=label_name,
                         **kwargs)
        if self.imglist is not None:
            # imglist labels are documented flat [cls, x1, y1, x2, y2]*N;
            # ALWAYS synthesize the packed [2, 5] header (guessing whether
            # a label is pre-packed misclassifies flat labels whose first
            # values look like a header)
            for key, (lab, fname) in list(self.imglist.items()):
                flat = np.asarray(lab, np.float32).reshape(-1)
                assert flat.size % 5 == 0, \
                    "imglist detection label must be [cls,x1,y1,x2,y2]*N"
                self.imglist[key] = (
                    np.concatenate([[2.0, 5.0], flat]).astype(np.float32),
                    fname)
        self.det_auglist = aug_list
        # probe max objects to fix the label pad shape
        self.max_objects = self._estimate_label_shape()
        self.provide_label = [DataDesc(
            label_name, (batch_size, self.max_objects, 5), "float32")]

    def _parse_label(self, label):
        """Packed label → (N_obj, obj_width) [cls, x1, y1, x2, y2, ...]
        (detection.py:772: header = [header_width, obj_width, extras...]).

        Every label must carry the header (imglist entries get one
        synthesized at construction); malformed labels raise instead of
        being silently reinterpreted."""
        raw = np.asarray(label, np.float32).reshape(-1)
        if raw.size >= 2:
            header_width = int(raw[0])
            obj_width = int(raw[1])
            if 2 <= header_width < raw.size and obj_width >= 5 and \
                    (raw.size - header_width) % obj_width == 0:
                return raw[header_width:].reshape(-1, obj_width)
        raise ValueError(
            "invalid detection label of size %d: expected packed header "
            "[header_width, obj_width, ...] followed by objects "
            "(detection.py pack_label format)" % raw.size)

    def _iter_labels(self):
        """Yield labels only — record headers are unpacked without JPEG
        decode (the reference scans packed label headers the same way,
        detection.py:700)."""
        from .. import recordio as _rec
        if self.imglist is not None:
            for label, _ in self.imglist.values():
                yield label
            return
        if self.seq is not None:
            for idx in self.seq:
                header, _ = _rec.unpack(self.imgrec.read_idx(idx))
                yield header.label
            return
        self.imgrec.reset()
        while True:
            s = self.imgrec.read()
            if s is None:
                break
            header, _ = _rec.unpack(s)
            yield header.label
        self.imgrec.reset()

    def _estimate_label_shape(self):
        max_count = 1
        for label in self._iter_labels():
            max_count = max(max_count, self._parse_label(label).shape[0])
        self.reset()
        return max_count

    def next(self):  # noqa: A003
        c, h, w = self.data_shape
        batch_data = np.zeros((self.batch_size, h, w, c), np.float32)
        batch_label = np.full((self.batch_size, self.max_objects, 5), -1.0,
                              np.float32)
        i = 0
        try:
            while i < self.batch_size:
                label, img = self.next_sample()
                objs = self._parse_label(label).copy()
                for aug in self.det_auglist:
                    img, objs = aug(img, objs)
                arr = _to_np(img)
                if arr.ndim == 2:
                    arr = arr[:, :, None]
                if arr.shape[:2] != (h, w):
                    arr = _to_np(imresize(arr, w, h))
                batch_data[i] = arr
                n = min(objs.shape[0], self.max_objects)
                batch_label[i, :n] = objs[:n]
                i += 1
        except StopIteration:
            if i == 0:
                raise
        pad = self.batch_size - i
        data = nd.array(batch_data.transpose(0, 3, 1, 2))
        return DataBatch([data], [nd.array(batch_label)], pad=pad)
