"""NDArray: the imperative tensor.

Parity surface: ``python/mxnet/ndarray/ndarray.py`` (5,071 LoC) over the C++
NDArray (``include/mxnet/ndarray.h:82``).  TPU-native design: the storage is
a ``jax.Array`` (XLA buffer).  The reference's engine-Var asynchrony maps to
JAX async dispatch — every op returns immediately with a future-backed array;
``wait_to_read`` ≡ ``block_until_ready`` and surfaces deferred errors exactly
like Engine::WaitForVar rethrows captured exceptions.

Mutation (``a += b``, ``a[i] = x``, optimizer in-place updates) is realized by
swapping the underlying immutable buffer (``_data``) — the moral equivalent of
the engine bumping the Var version on a write.
"""
from __future__ import annotations

import functools
from typing import Any, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..base import np_dtype
from ..context import Context, current_context
from ..ops import registry as _reg

__all__ = ["NDArray", "array", "zeros", "ones", "full", "empty", "arange",
           "concat", "stack", "waitall", "from_jax", "onehot_encode"]


class NDArray:
    __slots__ = ("_data", "_ctx", "_ag_node", "_ag_out_idx", "_ag_grad",
                 "_ag_grad_req", "__weakref__",
                 # C-ABI pins (capi_impl.py): host buffer + pristine
                 # snapshot for MXNDArrayGetData write-back, shm segment
                 # for GetSharedMemHandle, fresh-grad flag for
                 # Get/SetGradState
                 "_capi_host_buf", "_capi_host_snap", "_capi_shm",
                 "_fresh_grad")

    def __init__(self, data, ctx: Optional[Context] = None):
        if isinstance(data, NDArray):
            data = data._data
        elif not isinstance(data, jax.Array):
            data = jnp.asarray(data)
        if ctx is not None:
            dev = ctx.jax_device()
            if dev is not None and getattr(data, "sharding", None) is not None:
                try:
                    if data.sharding.device_set != {dev}:
                        data = jax.device_put(data, dev)
                except Exception:
                    data = jax.device_put(data, dev)
        self._data = data
        self._ctx = ctx
        self._ag_node = None
        self._ag_out_idx = 0
        self._ag_grad = None
        self._ag_grad_req = "write"

    # ------------------------------------------------------------------ meta
    @property
    def shape(self):
        return tuple(self._data.shape)

    @property
    def dtype(self):
        return np.dtype(self._data.dtype)

    @property
    def size(self):
        return int(np.prod(self.shape)) if self.shape else 1

    @property
    def ndim(self):
        return self._data.ndim

    @property
    def context(self) -> Context:
        if self._ctx is not None:
            return self._ctx
        try:
            dev = list(self._data.devices())[0]
            return Context("cpu" if dev.platform == "cpu" else "tpu", dev.id)
        except Exception:
            return current_context()

    ctx = context

    @property
    def stype(self):
        return "default"

    @property
    def grad(self):
        return self._ag_grad

    @property
    def T(self):
        return self.transpose()

    def __repr__(self):
        return "\n%s\n<NDArray %s @%s>" % (
            np.asarray(self._data), "x".join(str(s) for s in self.shape), self.context)

    def __len__(self):
        if not self.shape:
            raise TypeError("len() of unsized object")
        return self.shape[0]

    def __bool__(self):
        if self.size != 1:
            raise ValueError("The truth value of an NDArray with multiple elements "
                             "is ambiguous.")
        return bool(np.asarray(self._data))

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    # ------------------------------------------------------------- transfers
    def asnumpy(self) -> np.ndarray:
        return np.asarray(self._data)

    def asscalar(self):
        if self.size != 1:
            raise ValueError("The current array is not a scalar")
        return self.asnumpy().reshape(())[()]

    def item(self):
        return self.asscalar()

    def tolist(self):
        return self.asnumpy().tolist()

    def wait_to_read(self):
        try:
            self._data.block_until_ready()
        except AttributeError:
            pass
        return self

    def copy(self) -> "NDArray":
        return NDArray(self._data, self._ctx)

    def copyto(self, other):
        if isinstance(other, Context):
            return NDArray(self._data, other)
        other._data = jnp.asarray(self._data, other.dtype)
        return other

    def as_in_context(self, ctx: Context) -> "NDArray":
        return NDArray(self._data, ctx)

    as_in_ctx = as_in_context

    def astype(self, dtype, copy=True) -> "NDArray":
        dt = np_dtype(dtype)
        if not copy and self.dtype == dt:
            return self
        return NDArray(self._data.astype(dt), self._ctx)

    def asjax(self) -> jax.Array:
        """TPU-native accessor: the underlying jax.Array (zero-copy)."""
        return self._data

    def to_dlpack_for_read(self):
        return jax.dlpack.to_dlpack(self._data)

    to_dlpack_for_write = to_dlpack_for_read

    # ------------------------------------------------------------- autograd
    def attach_grad(self, grad_req="write", stype=None):
        from .. import autograd

        g = NDArray(jnp.zeros(self.shape, self.dtype), self._ctx)
        autograd.mark_variables([self], [g], [grad_req])

    def backward(self, out_grad=None, retain_graph=False, train_mode=True):
        from .. import autograd

        autograd.backward([self], [out_grad] if out_grad is not None else None,
                          retain_graph=retain_graph, train_mode=train_mode)

    def detach(self) -> "NDArray":
        return NDArray(self._data, self._ctx)

    # ------------------------------------------------------------ arithmetic
    def _binop(self, other, opname, reverse=False):
        if isinstance(other, (int, float, bool, np.number)):
            other = NDArray(jnp.asarray(other, self.dtype))
        lhs, rhs = (other, self) if reverse else (self, other)
        return _reg.invoke(opname, [lhs, rhs])

    def __add__(self, o):
        return self._binop(o, "broadcast_add")

    __radd__ = __add__

    def __sub__(self, o):
        return self._binop(o, "broadcast_sub")

    def __rsub__(self, o):
        return self._binop(o, "broadcast_sub", reverse=True)

    def __mul__(self, o):
        return self._binop(o, "broadcast_mul")

    __rmul__ = __mul__

    def __truediv__(self, o):
        return self._binop(o, "broadcast_div")

    def __rtruediv__(self, o):
        return self._binop(o, "broadcast_div", reverse=True)

    def __mod__(self, o):
        return self._binop(o, "broadcast_mod")

    def __pow__(self, o):
        return self._binop(o, "broadcast_power")

    def __matmul__(self, o):
        return _reg.invoke("dot", [self, o])

    def __neg__(self):
        return _reg.invoke("negative", [self])

    def __abs__(self):
        return _reg.invoke("abs", [self])

    def __eq__(self, o):  # noqa: D105 - mxnet semantics: elementwise
        if o is None:
            return False
        return self._binop(o, "broadcast_equal")

    def __ne__(self, o):
        if o is None:
            return True
        return self._binop(o, "broadcast_not_equal")

    def __gt__(self, o):
        return self._binop(o, "broadcast_greater")

    def __ge__(self, o):
        return self._binop(o, "broadcast_greater_equal")

    def __lt__(self, o):
        return self._binop(o, "broadcast_lesser")

    def __le__(self, o):
        return self._binop(o, "broadcast_lesser_equal")

    def __hash__(self):
        return id(self)

    # in-place: swap buffer (engine write-Var analog)
    def __iadd__(self, o):
        self._data = (self + o)._data
        return self

    def __isub__(self, o):
        self._data = (self - o)._data
        return self

    def __imul__(self, o):
        self._data = (self * o)._data
        return self

    def __itruediv__(self, o):
        self._data = (self / o)._data
        return self

    # ------------------------------------------------------------- indexing
    @staticmethod
    def _index_key(k):
        """NDArray key → jax index: bool masks stay bool (advanced boolean
        indexing, mx.np semantics); numeric keys become int32."""
        if k.dtype == np.bool_:
            return k._data
        return k._data.astype(jnp.int32)

    def __getitem__(self, key):
        if isinstance(key, NDArray):
            key = self._index_key(key)
        elif isinstance(key, tuple):
            key = tuple(self._index_key(k) if isinstance(k, NDArray) else k
                        for k in key)
        return NDArray(self._data[key], self._ctx)

    def __setitem__(self, key, value):
        if isinstance(key, NDArray):
            key = self._index_key(key)
        elif isinstance(key, tuple):
            key = tuple(self._index_key(k) if isinstance(k, NDArray) else k
                        for k in key)
        if isinstance(value, NDArray):
            value = value._data
        if key is Ellipsis or (isinstance(key, slice) and key == slice(None)):
            self._data = jnp.broadcast_to(jnp.asarray(value, self.dtype),
                                          self.shape)
        else:
            self._data = self._data.at[key].set(jnp.asarray(value, self.dtype))

    def slice_assign(self, rhs, begin, end, step=None):
        idx = tuple(slice(b, e, s) for b, e, s in
                    zip(begin, end, step or (None,) * len(begin)))
        self._data = self._data.at[idx].set(rhs._data if isinstance(rhs, NDArray) else rhs)
        return self

    # ------------------------------------------------------------ op methods
    def reshape(self, *shape, **kwargs):
        if len(shape) == 1 and isinstance(shape[0], (tuple, list)):
            shape = tuple(shape[0])
        shape = kwargs.get("shape", shape)
        return _reg.invoke("Reshape", [self], shape=shape,
                           reverse=kwargs.get("reverse", False))

    def reshape_like(self, other):
        return _reg.invoke("Reshape", [self], shape=other.shape)

    def transpose(self, axes=None):
        return _reg.invoke("transpose", [self], axes=axes)

    def flatten(self):
        return _reg.invoke("Flatten", [self])

    def expand_dims(self, axis):
        return _reg.invoke("expand_dims", [self], axis=axis)

    def squeeze(self, axis=None):
        return _reg.invoke("squeeze", [self], axis=axis)

    def swapaxes(self, dim1, dim2):
        return _reg.invoke("swapaxes", [self], dim1=dim1, dim2=dim2)

    def broadcast_to(self, shape):
        return _reg.invoke("broadcast_to", [self], shape=shape)

    def broadcast_like(self, other):
        return _reg.invoke("broadcast_like", [self, other])

    def slice(self, begin, end, step=None):  # noqa: A003
        return _reg.invoke("slice", [self], begin=begin, end=end, step=step or ())

    def slice_axis(self, axis, begin, end):
        return _reg.invoke("slice_axis", [self], axis=axis, begin=begin, end=end)

    def take(self, indices, axis=0, mode="clip"):
        return _reg.invoke("take", [self, indices])

    def one_hot(self, depth, **kw):
        return _reg.invoke("one_hot", [self], depth=depth, **kw)

    def tile(self, reps):
        return _reg.invoke("tile", [self], reps=reps)

    def repeat(self, repeats, axis=None):
        return _reg.invoke("repeat", [self], repeats=repeats, axis=axis)

    def flip(self, axis):
        return _reg.invoke("reverse", [self], axis=axis)

    def pad(self, mode="constant", pad_width=(), constant_value=0.0):
        return _reg.invoke("pad", [self], mode=mode, pad_width=pad_width,
                           constant_value=constant_value)

    def split(self, num_outputs, axis=1, squeeze_axis=False):
        return _reg.invoke("SliceChannel", [self], num_outputs=num_outputs,
                           axis=axis, squeeze_axis=squeeze_axis)

    def clip(self, a_min, a_max):
        return _reg.invoke("clip", [self], a_min=a_min, a_max=a_max)

    def abs(self):  # noqa: A003
        return _reg.invoke("abs", [self])

    def sign(self):
        return _reg.invoke("sign", [self])

    def sqrt(self):
        return _reg.invoke("sqrt", [self])

    def square(self):
        return _reg.invoke("square", [self])

    def exp(self):
        return _reg.invoke("exp", [self])

    def log(self):
        return _reg.invoke("log", [self])

    def relu(self):
        return _reg.invoke("relu", [self])

    def sigmoid(self):
        return _reg.invoke("sigmoid", [self])

    def tanh(self):
        return _reg.invoke("tanh", [self])

    def softmax(self, axis=-1):
        return _reg.invoke("softmax", [self], axis=axis)

    def log_softmax(self, axis=-1):
        return _reg.invoke("log_softmax", [self], axis=axis)

    def sum(self, axis=None, keepdims=False, exclude=False):  # noqa: A003
        return _reg.invoke("sum", [self], axis=axis, keepdims=keepdims,
                           exclude=exclude)

    def mean(self, axis=None, keepdims=False, exclude=False):
        return _reg.invoke("mean", [self], axis=axis, keepdims=keepdims,
                           exclude=exclude)

    def prod(self, axis=None, keepdims=False):
        return _reg.invoke("prod", [self], axis=axis, keepdims=keepdims)

    def max(self, axis=None, keepdims=False):  # noqa: A003
        return _reg.invoke("max", [self], axis=axis, keepdims=keepdims)

    def min(self, axis=None, keepdims=False):  # noqa: A003
        return _reg.invoke("min", [self], axis=axis, keepdims=keepdims)

    def norm(self, ord=2, axis=None, keepdims=False):  # noqa: A002
        return _reg.invoke("norm", [self], ord=ord, axis=axis, keepdims=keepdims)

    def argmax(self, axis=None, keepdims=False):
        return _reg.invoke("argmax", [self], axis=axis, keepdims=keepdims)

    def argmin(self, axis=None, keepdims=False):
        return _reg.invoke("argmin", [self], axis=axis, keepdims=keepdims)

    def argsort(self, axis=-1, is_ascend=True):
        return _reg.invoke("argsort", [self], axis=axis, is_ascend=is_ascend)

    def sort(self, axis=-1, is_ascend=True):
        return _reg.invoke("sort", [self], axis=axis, is_ascend=is_ascend)

    def topk(self, axis=-1, k=1, ret_typ="indices", is_ascend=False):
        return _reg.invoke("topk", [self], axis=axis, k=k, ret_typ=ret_typ,
                           is_ascend=is_ascend)

    def dot(self, other, transpose_a=False, transpose_b=False):
        return _reg.invoke("dot", [self, other], transpose_a=transpose_a,
                           transpose_b=transpose_b)

    def zeros_like(self):
        return _reg.invoke("zeros_like", [self])

    def ones_like(self):
        return _reg.invoke("ones_like", [self])

    def tostype(self, stype):
        if stype != "default":
            from .sparse import cast_storage

            return cast_storage(self, stype)
        return self


# ---------------------------------------------------------------------------
# factories
# ---------------------------------------------------------------------------


def array(source_array, ctx=None, dtype=None) -> NDArray:
    if isinstance(source_array, NDArray):
        data = source_array._data
    else:
        data = jnp.asarray(source_array)
    if dtype is not None:
        data = data.astype(np_dtype(dtype))
    elif not isinstance(source_array, (np.ndarray, jax.Array, NDArray)):
        if data.dtype == jnp.float64:
            data = data.astype(jnp.float32)
    return NDArray(data, ctx)


def from_jax(x, ctx=None) -> NDArray:
    return NDArray(x, ctx)


def zeros(shape, ctx=None, dtype="float32", **kw) -> NDArray:
    return NDArray(jnp.zeros(shape if not isinstance(shape, int) else (shape,),
                             np_dtype(dtype)), ctx)


def ones(shape, ctx=None, dtype="float32", **kw) -> NDArray:
    return NDArray(jnp.ones(shape if not isinstance(shape, int) else (shape,),
                            np_dtype(dtype)), ctx)


def full(shape, val, ctx=None, dtype="float32", **kw) -> NDArray:
    return NDArray(jnp.full(shape if not isinstance(shape, int) else (shape,), val,
                            np_dtype(dtype)), ctx)


def empty(shape, ctx=None, dtype="float32") -> NDArray:
    return zeros(shape, ctx, dtype)


def arange(start, stop=None, step=1.0, repeat=1, ctx=None, dtype="float32") -> NDArray:
    out = jnp.arange(start, stop, step, np_dtype(dtype))
    if repeat != 1:
        out = jnp.repeat(out, repeat)
    return NDArray(out, ctx)


def concat(*args, dim=1):
    return _reg.invoke("Concat", list(args), dim=dim)


def stack(*args, axis=0):
    return _reg.invoke("stack", list(args), axis=axis)


def onehot_encode(indices, out):
    depth = out.shape[1]
    res = _reg.invoke("one_hot", [indices], depth=depth)
    out._data = res._data
    return out


def waitall():
    from .. import engine

    engine.waitall()
