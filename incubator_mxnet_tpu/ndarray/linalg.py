"""``mx.nd.linalg`` — LAPACK-style operator namespace.

Parity: ``python/mxnet/ndarray/linalg.py`` over the la_op family
(``src/operator/tensor/la_op.cc``); implementations in ``ops/linalg.py``.
"""
from __future__ import annotations

from ..ops import registry as _reg

__all__ = ["gemm", "gemm2", "potrf", "potri", "trmm", "trsm", "syrk",
           "gelqf", "syevd", "sumlogdiag", "extractdiag", "makediag",
           "extracttrian", "maketrian", "inverse", "det", "slogdet"]


def _make(opname):
    def fn(*inputs, **attrs):
        attrs.pop("name", None)
        return _reg.invoke(opname, list(inputs), **attrs)

    fn.__name__ = opname.replace("_linalg_", "")
    fn.__doc__ = _reg.get_op(opname).doc
    return fn


gemm = _make("_linalg_gemm")
gemm2 = _make("_linalg_gemm2")
potrf = _make("_linalg_potrf")
potri = _make("_linalg_potri")
trmm = _make("_linalg_trmm")
trsm = _make("_linalg_trsm")
syrk = _make("_linalg_syrk")
gelqf = _make("_linalg_gelqf")
syevd = _make("_linalg_syevd")
sumlogdiag = _make("_linalg_sumlogdiag")
extractdiag = _make("_linalg_extractdiag")
makediag = _make("_linalg_makediag")
extracttrian = _make("_linalg_extracttrian")
maketrian = _make("_linalg_maketrian")
inverse = _make("_linalg_inverse")
det = _make("_linalg_det")
slogdet = _make("_linalg_slogdet")
