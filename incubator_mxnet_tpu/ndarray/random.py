"""``mx.nd.random`` namespace (python/mxnet/ndarray/random.py parity)."""
from __future__ import annotations

from ..ops import registry as _reg

__all__ = ["uniform", "normal", "gamma", "exponential", "poisson",
           "negative_binomial", "generalized_negative_binomial", "randint",
           "multinomial", "shuffle", "bernoulli", "seed"]


def _invoke0(name, out=None, **kw):
    return _reg.invoke(name, [], out=out, **kw)


def uniform(low=0.0, high=1.0, shape=None, dtype=None, ctx=None, out=None, **kw):
    return _invoke0("_random_uniform", out=out, low=low, high=high,
                    shape=shape if shape is not None else (1,), dtype=dtype)


def normal(loc=0.0, scale=1.0, shape=None, dtype=None, ctx=None, out=None, **kw):
    return _invoke0("_random_normal", out=out, loc=loc, scale=scale,
                    shape=shape if shape is not None else (1,), dtype=dtype)


def randn(*shape, loc=0.0, scale=1.0, dtype=None, ctx=None, **kw):
    return normal(loc, scale, shape or (1,), dtype, ctx)


def gamma(alpha=1.0, beta=1.0, shape=None, dtype=None, ctx=None, out=None, **kw):
    return _invoke0("_random_gamma", out=out, alpha=alpha, beta=beta,
                    shape=shape if shape is not None else (1,), dtype=dtype)


def exponential(scale=1.0, shape=None, dtype=None, ctx=None, out=None, **kw):
    return _invoke0("_random_exponential", out=out, lam=1.0 / scale,
                    shape=shape if shape is not None else (1,), dtype=dtype)


def poisson(lam=1.0, shape=None, dtype=None, ctx=None, out=None, **kw):
    return _invoke0("_random_poisson", out=out, lam=lam,
                    shape=shape if shape is not None else (1,), dtype=dtype)


def negative_binomial(k=1, p=1.0, shape=None, dtype=None, ctx=None, out=None, **kw):
    return _invoke0("_random_negative_binomial", out=out, k=k, p=p,
                    shape=shape if shape is not None else (1,), dtype=dtype)


def generalized_negative_binomial(mu=1.0, alpha=1.0, shape=None, dtype=None,
                                  ctx=None, out=None, **kw):
    return _invoke0("_random_generalized_negative_binomial", out=out, mu=mu,
                    alpha=alpha, shape=shape if shape is not None else (1,),
                    dtype=dtype)


def randint(low, high, shape=None, dtype="int32", ctx=None, out=None, **kw):
    return _invoke0("_random_randint", out=out, low=low, high=high,
                    shape=shape if shape is not None else (1,), dtype=dtype)


def multinomial(data, shape=None, get_prob=False, dtype="int32", **kw):
    return _reg.invoke("_sample_multinomial", [data], shape=shape,
                       get_prob=get_prob, dtype=dtype)


def shuffle(data, **kw):
    return _reg.invoke("_shuffle", [data])


def bernoulli(prob=0.5, shape=None, dtype="float32", ctx=None, out=None, **kw):
    return _invoke0("bernoulli", out=out, prob=prob,
                    shape=shape if shape is not None else (1,), dtype=dtype)


def seed(seed_state, ctx="all"):
    from .. import rng

    rng.seed(seed_state)
