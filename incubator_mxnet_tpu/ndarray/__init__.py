"""``mx.nd`` — imperative operator namespace.

Op functions are generated from the registry the way the reference code-gens
python wrappers from ``MXSymbolGetAtomicSymbolInfo``
(``python/mxnet/ndarray/register.py``): here it is a module ``__getattr__``
that resolves any registered op name to an eager invoke wrapper, so
``mx.nd.<op>(...)`` works for every op in :mod:`..ops`.
"""
from __future__ import annotations

import functools
import inspect
import sys

from ..ops import registry as _reg
from .ndarray import (NDArray, arange, array, concat, empty, from_jax, full,
                      onehot_encode, ones, stack, waitall, zeros)
from . import utils
from .utils import load, save
from . import random  # noqa: F401
from . import linalg  # noqa: F401
from . import contrib  # noqa: F401
from . import sparse
from .sparse import (BaseSparseNDArray, CSRNDArray, RowSparseNDArray,
                     cast_storage)

__all__ = ["NDArray", "array", "zeros", "ones", "full", "empty", "arange",
           "concat", "stack", "waitall", "save", "load", "random", "from_jax",
           "sparse", "BaseSparseNDArray", "CSRNDArray", "RowSparseNDArray",
           "cast_storage"]


def _input_names(op: "_reg.Op"):
    """Positional no-default params of op.fn = tensor inputs (FListInputNames)."""
    try:
        sig = inspect.signature(op.fn)
    except (TypeError, ValueError):
        return None
    names = []
    for p in sig.parameters.values():
        if p.kind == inspect.Parameter.VAR_POSITIONAL:
            return None  # variadic
        if p.kind in (inspect.Parameter.POSITIONAL_ONLY,
                      inspect.Parameter.POSITIONAL_OR_KEYWORD):
            if p.default is inspect.Parameter.empty:
                names.append(p.name)
            elif p.name in ("bias", "gamma", "sequence_length", "label_lengths",
                            "data_lengths", "r1_r2", "min_bias", "max_bias",
                            "valid_length", "max_time"):
                names.append(p.name)  # optional tensor inputs
    return names


def _attr_names(op: "_reg.Op", n_inputs: int):
    """Keyword-param names after the tensor inputs, in signature order."""
    try:
        sig = inspect.signature(op.fn)
    except (TypeError, ValueError):
        return []
    names = [p.name for p in sig.parameters.values()
             if p.kind in (inspect.Parameter.POSITIONAL_ONLY,
                           inspect.Parameter.POSITIONAL_OR_KEYWORD)]
    return names[n_inputs:]


def _make_wrapper(name: str, op: "_reg.Op"):
    in_names = _input_names(op)
    attr_names = _attr_names(op, len(in_names)) if in_names is not None else []

    def wrapper(*args, out=None, name=None, **kwargs):  # noqa: A002
        inputs = list(args)
        if in_names is not None:
            # trailing positional args beyond the tensor inputs are attrs
            # (reference op-call convention: nd.swapaxes(x, 0, 1))
            if len(inputs) > len(in_names):
                extras = inputs[len(in_names):]
                inputs = inputs[:len(in_names)]
                for attr, val in zip(attr_names, extras):
                    kwargs.setdefault(attr, val)
            # allow inputs passed as kwargs by reference name
            for n in in_names[len(inputs):]:
                if n in kwargs:
                    inputs.append(kwargs.pop(n))
                else:
                    break
        if op.num_inputs not in (0, None):
            kwargs.pop("ctx", None)
        return _reg.invoke(op.name, inputs, out=out, **kwargs)

    wrapper.__name__ = name
    # full dmlc::Parameter-style schema docstring (MXSymbolGetAtomicSymbolInfo
    # analog) so help(mx.nd.op) shows inputs + typed parameters
    wrapper.__doc__ = _reg.op_doc(op.name)
    return wrapper


def __getattr__(name):
    if name.startswith("__"):
        raise AttributeError(name)
    try:
        op = _reg.get_op(name)
    except NotImplementedError:
        raise AttributeError("mx.nd has no operator %r" % name) from None
    w = _make_wrapper(name, op)
    setattr(sys.modules[__name__], name, w)
    return w
