"""``mx.nd.contrib`` — resolves ``name`` to the ``_contrib_name`` op
(reference: python/mxnet/ndarray/contrib.py + generated op wrappers)."""
from __future__ import annotations

import sys

from ..ops import registry as _reg

__all__ = []


def __getattr__(name):
    if name.startswith("__"):
        raise AttributeError(name)
    from . import _make_wrapper
    for cand in ("_contrib_" + name, name):
        if cand in _reg.OPS:
            w = _make_wrapper(name, _reg.OPS[cand])
            setattr(sys.modules[__name__], name, w)
            return w
    raise AttributeError("mx.nd.contrib has no operator %r" % name)
