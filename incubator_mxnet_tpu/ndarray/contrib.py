"""``mx.nd.contrib`` — resolves ``name`` to the ``_contrib_name`` op, plus
imperative control flow (reference: python/mxnet/ndarray/contrib.py —
foreach :187, while_loop :320, cond :452)."""
from __future__ import annotations

import sys

from ..ops import registry as _reg

__all__ = ["foreach", "while_loop", "cond"]


from ..base import _as_list


def foreach(body, data, init_states, name="foreach"):
    """Eager scan: iterate ``body(data_t, states)`` over axis 0
    (ndarray/contrib.py:187).  The Python loop runs on NDArrays so the
    autograd tape records every step; under a hybridize trace the loop
    unrolls into the compiled graph."""
    data_list = _as_list(data)
    states = _as_list(init_states)
    single_state = not isinstance(init_states, (list, tuple))
    length = data_list[0].shape[0]
    outputs = None
    for i in range(length):
        eles = [d[i] for d in data_list]
        outs, states = body(eles[0] if len(eles) == 1 else eles,
                            states[0] if single_state else states)
        states = _as_list(states)
        outs = _as_list(outs)
        if outputs is None:
            outputs = [[] for _ in outs]
        for buf, o in zip(outputs, outs):
            buf.append(o)
    from .ndarray import stack
    stacked = [stack(*buf, axis=0) for buf in (outputs or [])]
    out = stacked[0] if len(stacked) == 1 else stacked
    return out, (states[0] if single_state else states)


def while_loop(cond, func, loop_vars, max_iterations=None, name="while_loop"):
    """Eager bounded while loop (ndarray/contrib.py:320).  Step outputs are
    stacked and zero-padded to ``max_iterations`` rows."""
    from .ndarray import stack

    if max_iterations is None:
        raise ValueError("max_iterations is required")
    single_var = not isinstance(loop_vars, (list, tuple))
    vs = _as_list(loop_vars)
    steps = []
    n_iter = 0
    while n_iter < max_iterations and bool(cond(*vs).asnumpy().item()):
        outs, new_vs = func(*vs)
        vs = _as_list(new_vs)
        steps.append(_as_list(outs))
        n_iter += 1
    if not steps:
        raise ValueError("while_loop made zero iterations; output shapes "
                         "are undefined (matches the reference error)")
    n_out = len(steps[0])
    outputs = []
    for j in range(n_out):
        rows = [s[j] for s in steps]
        pad = [rows[0] * 0] * (int(max_iterations) - len(rows))
        outputs.append(stack(*(rows + pad), axis=0))
    out = outputs[0] if n_out == 1 else outputs
    return out, (vs[0] if single_var else vs)


def cond(pred, then_func, else_func, name="cond"):
    """Eager conditional (ndarray/contrib.py:452)."""
    if bool(pred.asnumpy().item()):
        return then_func()
    return else_func()


def __getattr__(name):
    if name.startswith("__"):
        raise AttributeError(name)
    from . import _make_wrapper
    for cand in ("_contrib_" + name, name):
        if cand in _reg.OPS:
            w = _make_wrapper(name, _reg.OPS[cand])
            setattr(sys.modules[__name__], name, w)
            return w
    raise AttributeError("mx.nd.contrib has no operator %r" % name)
