"""NDArray container save/load.

Parity: ``NDArray::Save/Load`` (``src/ndarray/ndarray.cc:1596,1719``) and
``mx.nd.save/load`` — a file holding a list of arrays or a dict of named
arrays.  Format here is a single ``.npz``-style zip with a manifest entry
(`__mx_tpu_format__`) recording list-vs-dict; readable with plain numpy.
"""
from __future__ import annotations

import json
import zipfile
from typing import Dict, List, Union

import numpy as np

from .ndarray import NDArray, array

__all__ = ["save", "load", "load_frombuffer"]

_FORMAT_KEY = "__mx_tpu_format__"


def save(fname: str, data) -> None:
    if isinstance(data, NDArray):
        data = [data]
    if isinstance(data, dict):
        manifest = {"kind": "dict", "names": list(data.keys())}
        arrays = {("v%d" % i): v.asnumpy() for i, (k, v) in enumerate(data.items())}
    elif isinstance(data, (list, tuple)):
        manifest = {"kind": "list", "names": None}
        arrays = {("v%d" % i): v.asnumpy() for i, v in enumerate(data)}
    else:
        raise ValueError("data must be NDArray, list of NDArrays, or dict")
    arrays[_FORMAT_KEY] = np.frombuffer(json.dumps(manifest).encode(), dtype=np.uint8)
    np.savez(fname if fname.endswith(".npz") else fname, **arrays)
    # np.savez appends .npz; rename back for exact-name parity
    import os

    if not fname.endswith(".npz") and os.path.exists(fname + ".npz"):
        os.replace(fname + ".npz", fname)


def load(fname: str) -> Union[List[NDArray], Dict[str, NDArray]]:
    with np.load(fname, allow_pickle=False) as z:
        files = dict(z)
    manifest = json.loads(bytes(files.pop(_FORMAT_KEY)).decode())
    n = len(files)
    vals = [array(files["v%d" % i]) for i in range(n)]
    if manifest["kind"] == "dict":
        return dict(zip(manifest["names"], vals))
    return vals


def load_frombuffer(buf: bytes):
    import io

    bio = io.BytesIO(buf)
    with np.load(bio, allow_pickle=False) as z:
        files = dict(z)
    manifest = json.loads(bytes(files.pop(_FORMAT_KEY)).decode())
    vals = [array(files["v%d" % i]) for i in range(len(files))]
    if manifest["kind"] == "dict":
        return dict(zip(manifest["names"], vals))
    return vals
