"""NDArray container save/load.

Parity: ``NDArray::Save/Load`` (``src/ndarray/ndarray.cc:1596,1719``) and
``mx.nd.save/load`` — a file holding a list of arrays or a dict of named
arrays.

Two on-disk formats:
- **reference format** (default for ``save``): the stock MXNet versioned-
  magic named-NDArray blob (``legacy_io.py``; magic 0x112 + NDARRAY_V2) —
  checkpoints interoperate with stock MXNet in both directions;
- **npz**: an ``.npz`` zip with a manifest entry (rounds 1-2 format);
  ``load`` sniffs the first bytes and accepts both.
"""
from __future__ import annotations

import json
import os
import struct
import tempfile
import zipfile
from typing import Dict, List, Union

import numpy as np

from . import legacy_io
from .ndarray import NDArray, array

__all__ = ["save", "load", "load_frombuffer"]

_FORMAT_KEY = "__mx_tpu_format__"


def _atomic_write_via(fname: str, write_fn) -> None:
    """Crash-safe file replace: stream via ``write_fn(file)`` into a
    sibling temp file, fsync, then ``os.replace`` onto the target.  A
    crash mid-write leaves either the previous complete file or nothing
    new — never a torn ``.params`` blob that ``load`` half-parses
    (docs/RESILIENCE.md)."""
    d = os.path.dirname(os.path.abspath(fname)) or "."
    fd, tmp = tempfile.mkstemp(prefix=os.path.basename(fname) + ".",
                               suffix=".tmp", dir=d)
    try:
        # mkstemp creates 0600 regardless of umask; published files must
        # keep the permissions a plain open() would have given them
        umask = os.umask(0)
        os.umask(umask)
        os.chmod(tmp, 0o666 & ~umask)
        with os.fdopen(fd, "wb") as f:
            write_fn(f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, fname)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def _atomic_write(fname: str, buf: bytes) -> None:
    _atomic_write_via(fname, lambda f: f.write(buf))


def save(fname: str, data, format="params") -> None:  # noqa: A002
    """Save arrays; ``format='params'`` (default) writes the reference
    binary container, ``format='npz'`` the numpy container.  Both write
    temp-then-rename, so an interrupted save never tears the file."""
    if isinstance(data, NDArray):
        data = [data]
    if format == "npz":
        return _save_npz(fname, data)
    if isinstance(data, dict):
        names = list(data.keys())
        arrays = list(data.values())
    elif isinstance(data, (list, tuple)):
        names = None
        arrays = list(data)
    else:
        raise ValueError("data must be NDArray, list of NDArrays, or dict")
    _atomic_write(fname, legacy_io.save_legacy(arrays, names))


def _save_npz(fname: str, data) -> None:
    if isinstance(data, dict):
        manifest = {"kind": "dict", "names": list(data.keys())}
        arrays = {("v%d" % i): v.asnumpy()
                  for i, (k, v) in enumerate(data.items())}
    elif isinstance(data, (list, tuple)):
        manifest = {"kind": "list", "names": None}
        arrays = {("v%d" % i): v.asnumpy() for i, v in enumerate(data)}
    else:
        raise ValueError("data must be NDArray, list of NDArrays, or dict")
    arrays[_FORMAT_KEY] = np.frombuffer(json.dumps(manifest).encode(),
                                        dtype=np.uint8)
    # stream the zip straight into the temp file (no in-memory copy of
    # the whole container) and commit atomically — this also keeps the
    # exact target name, where np.savez on a path would append ".npz"
    _atomic_write_via(fname, lambda f: np.savez(f, **arrays))


def load(fname: str) -> Union[List[NDArray], Dict[str, NDArray]]:
    with open(fname, "rb") as f:
        head = f.read(8)
    if legacy_io.is_legacy_container(head):
        return legacy_io.load_legacy(fname)
    with np.load(fname, allow_pickle=False) as z:
        files = dict(z)
    return _from_npz_files(files)


def _from_npz_files(files):
    manifest = json.loads(bytes(files.pop(_FORMAT_KEY)).decode())
    n = len(files)
    vals = [array(files["v%d" % i]) for i in range(n)]
    if manifest["kind"] == "dict":
        return dict(zip(manifest["names"], vals))
    return vals


def load_frombuffer(buf: bytes):
    import io

    if legacy_io.is_legacy_container(bytes(buf[:8])):
        return legacy_io.load_legacy_buffer(bytes(buf))
    bio = io.BytesIO(buf)
    with np.load(bio, allow_pickle=False) as z:
        files = dict(z)
    return _from_npz_files(files)
