"""Reference-compatible NDArray binary container (.params files).

Byte-compatible implementation of the reference's named-NDArray blob format
so checkpoints interoperate with stock MXNet in both directions:

  file   := uint64 kMXAPINDArrayListMagic(0x112) | uint64 reserved(0)
            | vec<ndarray> | vec<string names>          (ndarray.cc:1831-1857)
  vec<T> := uint64 count | T...                         (dmlc serializer)
  string := uint64 len | bytes
  ndarray(V2/V3) := uint32 magic(0xF993fac9/a) | int32 stype
            | [storage_shape if sparse] | shape | int32 dev_type
            | int32 dev_id | int32 type_flag
            | [int32 aux_type, aux_shape]*nad | raw data | raw aux data
                                                        (ndarray.cc:1596-1669)
  shape  := int32 ndim | int64 dim...                   (tuple.h:703-713)
  legacy V1 (0xF993fac8): shape | ctx | type_flag | data; pre-V1: the
  "magic" word is ndim followed by uint32 dims          (ndarray.cc:1672-1717)

Storage types: dense=0, row_sparse=1 (aux: int64 row idx), csr=2
(aux: int64 indptr, int64 indices) — ``include/mxnet/ndarray.h:61``.
Type flags: f32=0 f64=1 f16=2 u8=3 i32=4 i8=5 i64=6 bool=7
(``mshadow/base.h:307-314``).
"""
from __future__ import annotations

import struct
from typing import Dict, List, Tuple, Union

import numpy as np

__all__ = ["MAGIC_LIST", "is_legacy_container", "save_legacy", "load_legacy",
           "load_legacy_buffer"]

MAGIC_LIST = 0x112
_V1 = 0xF993FAC8
_V2 = 0xF993FAC9
_V3 = 0xF993FACA

_FLAG_OF = {np.dtype(np.float32): 0, np.dtype(np.float64): 1,
            np.dtype(np.float16): 2, np.dtype(np.uint8): 3,
            np.dtype(np.int32): 4, np.dtype(np.int8): 5,
            np.dtype(np.int64): 6, np.dtype(np.bool_): 7}
_DTYPE_OF = {v: k for k, v in _FLAG_OF.items()}

_KCPU = 1


def is_legacy_container(head: bytes) -> bool:
    return len(head) >= 8 and struct.unpack("<Q", head[:8])[0] == MAGIC_LIST


class _Writer:
    def __init__(self):
        self.parts: List[bytes] = []

    def u32(self, v):
        self.parts.append(struct.pack("<I", v))

    def i32(self, v):
        self.parts.append(struct.pack("<i", v))

    def u64(self, v):
        self.parts.append(struct.pack("<Q", v))

    def shape(self, shp):
        self.parts.append(struct.pack("<i", len(shp)))
        self.parts.append(np.asarray(shp, "<i8").tobytes())

    def raw(self, b):
        self.parts.append(b)

    def getvalue(self):
        return b"".join(self.parts)


class _Reader:
    def __init__(self, buf: bytes):
        self.buf = buf
        self.pos = 0

    def _take(self, n):
        if self.pos + n > len(self.buf):
            raise ValueError("truncated NDArray container")
        b = self.buf[self.pos: self.pos + n]
        self.pos += n
        return b

    def u32(self):
        return struct.unpack("<I", self._take(4))[0]

    def i32(self):
        return struct.unpack("<i", self._take(4))[0]

    def u64(self):
        return struct.unpack("<Q", self._take(8))[0]

    def shape(self, ndim=None, dim_dtype="<i8"):
        if ndim is None:
            ndim = self.i32()
        itemsize = np.dtype(dim_dtype).itemsize
        return tuple(int(x) for x in
                     np.frombuffer(self._take(itemsize * ndim), dim_dtype))

    def raw(self, n):
        return self._take(n)


def _write_one(w: _Writer, arr) -> None:
    """Serialize one array (dense NDArray or CSR/RowSparse) as V2."""
    from .ndarray import NDArray
    from .sparse import CSRNDArray, RowSparseNDArray

    w.u32(_V2)
    if isinstance(arr, CSRNDArray):
        data = np.asarray(arr.data.asnumpy())
        indices = np.asarray(arr.indices.asnumpy(), np.int64)
        indptr = np.asarray(arr.indptr.asnumpy(), np.int64)
        w.i32(2)  # kCSRStorage
        w.shape(data.shape)              # storage shape
        w.shape(arr.shape)
        w.i32(_KCPU)
        w.i32(0)
        w.i32(_FLAG_OF[np.dtype(data.dtype)])
        w.i32(6)                          # aux 0: indptr int64
        w.shape(indptr.shape)
        w.i32(6)                          # aux 1: indices int64
        w.shape(indices.shape)
        w.raw(np.ascontiguousarray(data).tobytes())
        w.raw(indptr.tobytes())
        w.raw(indices.tobytes())
        return
    if isinstance(arr, RowSparseNDArray):
        data = np.asarray(arr.data.asnumpy())
        indices = np.asarray(arr.indices.asnumpy(), np.int64)
        w.i32(1)  # kRowSparseStorage
        w.shape(data.shape)
        w.shape(arr.shape)
        w.i32(_KCPU)
        w.i32(0)
        w.i32(_FLAG_OF[np.dtype(data.dtype)])
        w.i32(6)
        w.shape(indices.shape)
        w.raw(np.ascontiguousarray(data).tobytes())
        w.raw(indices.tobytes())
        return
    npv = arr.asnumpy() if isinstance(arr, NDArray) else np.asarray(arr)
    if np.dtype(npv.dtype) not in _FLAG_OF:
        npv = npv.astype(np.float32)
    w.i32(0)  # kDefaultStorage
    w.shape(npv.shape)
    w.i32(_KCPU)
    w.i32(0)
    w.i32(_FLAG_OF[np.dtype(npv.dtype)])
    w.raw(np.ascontiguousarray(npv).tobytes())


def _read_one(r: _Reader):
    from .ndarray import array as nd_array
    from .sparse import csr_matrix, row_sparse_array

    magic = r.u32()
    if magic in (_V2, _V3):
        stype = r.i32()
        nad = {0: 0, 1: 1, 2: 2}.get(stype)
        if nad is None:
            raise ValueError("unknown storage type %d" % stype)
        sshape = r.shape() if nad else None
        shape = r.shape()
        if len(shape) == 0:
            return nd_array(np.zeros((0,), np.float32))
        r.i32()  # dev_type
        r.i32()  # dev_id
        flag = r.i32()
        dt = _DTYPE_OF[flag]
        aux = []
        for _ in range(nad):
            aflag = r.i32()
            ashape = r.shape()
            aux.append((_DTYPE_OF[aflag], ashape))
        n = int(np.prod(sshape if nad else shape)) if (sshape or shape) else 0
        data = np.frombuffer(r.raw(n * dt.itemsize), dt).reshape(
            sshape if nad else shape)
        aux_vals = []
        for adt, ashape in aux:
            cnt = int(np.prod(ashape)) if ashape else 0
            aux_vals.append(np.frombuffer(
                r.raw(cnt * adt.itemsize), adt).reshape(ashape))
        if stype == 0:
            return nd_array(data)
        if stype == 1:
            return row_sparse_array((data, aux_vals[0]), shape=shape)
        return csr_matrix((data.reshape(-1), aux_vals[1], aux_vals[0]),
                          shape=shape)
    # legacy paths (ndarray.cc:1672 LegacyLoad)
    if magic == _V1:
        shape = r.shape()
    else:
        shape = r.shape(ndim=magic, dim_dtype="<u4")
    if len(shape) == 0:
        return nd_array(np.zeros((0,), np.float32))
    r.i32()
    r.i32()
    flag = r.i32()
    dt = _DTYPE_OF[flag]
    n = int(np.prod(shape))
    data = np.frombuffer(r.raw(n * dt.itemsize), dt).reshape(shape)
    return nd_array(data)


def save_legacy(data, names=None) -> bytes:
    w = _Writer()
    w.u64(MAGIC_LIST)
    w.u64(0)
    w.u64(len(data))
    for arr in data:
        _write_one(w, arr)
    names = names or []
    w.u64(len(names))
    for n in names:
        b = n.encode()
        w.u64(len(b))
        w.raw(b)
    return w.getvalue()


def load_legacy_buffer(buf: bytes):
    r = _Reader(buf)
    if r.u64() != MAGIC_LIST:
        raise ValueError("not an NDArray container (bad magic)")
    r.u64()  # reserved
    n = r.u64()
    arrays = [_read_one(r) for _ in range(n)]
    n_names = r.u64()
    names = [r.raw(r.u64()).decode() for _ in range(n_names)]
    if names:
        return dict(zip(names, arrays))
    return arrays


def load_legacy(fname: str):
    with open(fname, "rb") as f:
        return load_legacy_buffer(f.read())
