"""``mx.nd.sparse`` — CSR and row-sparse tensors.

Parity surface: ``python/mxnet/ndarray/sparse.py`` (BaseSparseNDArray :107,
CSRNDArray :287, RowSparseNDArray :561) over C++ storage types
``kCSRStorage``/``kRowSparseStorage`` (``include/mxnet/ndarray.h:61-66``)
and the sparse kernels in ``src/operator/tensor/`` (dot CSR×dense,
cast_storage, sparse_retain, square_sum) plus the row-sparse optimizer
updates (``src/operator/optimizer_op.cc:895`` `_sparse_adagrad_update`
and the lazy-update paths of sgd/adam).

TPU-native design
-----------------
TPUs have no sparse MXU path, so (as SURVEY.md §7 "Hard parts" prescribes)
sparse storage lives as *static-shape* coordinate arrays (``jax.Array``):

- CSR:        ``data (nnz,)``, ``indices (nnz,) int64``, ``indptr (n+1,)``
- row_sparse: ``data (k, *row_shape)``, ``indices (k,) int64``

Compute that matters stays on-device and static-shaped:
``dot(csr, dense)`` lowers to ``take`` + ``segment_sum`` (nnz is static, so
XLA compiles it once per sparsity pattern); row-sparse optimizer updates
lower to scatter (``at[rows].add``) touching only the live rows — the lazy
update semantics of the reference.  Storage *conversions* (find the nonzero
pattern) are inherently data-dependent-shape, so they run on host numpy,
exactly like the reference runs cast_storage on CPU for most flows.
"""
from __future__ import annotations

import os
import warnings
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..base import np_dtype
from ..context import Context
from ..ops import registry as _reg
from .ndarray import NDArray

__all__ = [
    "BaseSparseNDArray", "CSRNDArray", "RowSparseNDArray",
    "csr_matrix", "row_sparse_array", "array", "zeros", "empty",
    "cast_storage", "dot", "retain", "add", "subtract", "multiply",
]

def _as_jax(x):
    return x._data if isinstance(x, NDArray) else jnp.asarray(x)


def _log_fallback(op, stypes):
    """MXNET_STORAGE_FALLBACK_LOG_VERBOSE analog (src/common/utils.h)."""
    from .. import config

    if config.get("MXNET_STORAGE_FALLBACK_LOG_VERBOSE"):
        warnings.warn(
            "%s: storage fallback to dense for stypes %s" % (op, stypes),
            stacklevel=3)


class BaseSparseNDArray:
    """Common interface of CSRNDArray / RowSparseNDArray.

    Deliberately NOT an NDArray subclass: like the reference, most dense
    operators raise on sparse inputs instead of silently densifying; explicit
    ``tostype('default')`` densifies.
    """

    stype = None  # set by subclass

    def __init__(self, shape, dtype, ctx=None):
        self._shape = tuple(int(s) for s in shape)
        self._dtype = np.dtype(dtype)
        self._ctx = ctx

    # ------------------------------------------------------------------ meta
    @property
    def shape(self):
        return self._shape

    @property
    def dtype(self):
        return self._dtype

    @property
    def ndim(self):
        return len(self._shape)

    @property
    def size(self):
        return int(np.prod(self._shape)) if self._shape else 1

    @property
    def context(self):
        from ..context import current_context

        return self._ctx if self._ctx is not None else current_context()

    ctx = context

    @property
    def grad(self):
        return None

    def __len__(self):
        return self._shape[0]

    def __repr__(self):
        return "\n<%s %s @%s>" % (
            type(self).__name__, "x".join(str(s) for s in self._shape),
            self.context)

    # ------------------------------------------------------------- transfers
    def asnumpy(self):
        return np.asarray(self._dense_data())

    def wait_to_read(self):
        return self

    def asscalar(self):
        if self.size != 1:
            raise ValueError("The current array is not a scalar")
        return self.asnumpy().reshape(())[()]

    def astype(self, dtype, copy=True):
        raise NotImplementedError

    def todense(self) -> NDArray:
        return NDArray(self._dense_data(), self._ctx)

    def tostype(self, stype):
        if stype == self.stype:
            return self
        if stype == "default":
            return self.todense()
        return cast_storage(self, stype)

    def as_in_context(self, ctx):
        out = self.copy()
        out._ctx = ctx
        return out

    def copyto(self, other):
        if isinstance(other, Context):
            return self.as_in_context(other)
        if isinstance(other, NDArray):
            other._data = self._dense_data()
            return other
        raise TypeError("copyto: unsupported target %r" % (other,))

    def check_format(self, full_check=True):
        """Validate the storage format (reference
        ``python/mxnet/ndarray/sparse.py:check_format`` /
        MXNDArraySyncCheckFormat): raises on inconsistent aux arrays."""
        if self.stype == "csr":
            indptr = np.asarray(self.indptr.asnumpy(), np.int64)
            indices = np.asarray(self.indices.asnumpy(), np.int64)
            if indptr.shape != (self.shape[0] + 1,):
                raise ValueError("csr indptr length %d != rows+1 (%d)"
                                 % (indptr.size, self.shape[0] + 1))
            if indptr[0] != 0 or np.any(np.diff(indptr) < 0):
                raise ValueError("csr indptr must start at 0 and be "
                                 "non-decreasing")
            if indptr[-1] > indices.size:
                raise ValueError("csr indptr[-1]=%d exceeds nnz capacity %d"
                                 % (indptr[-1], indices.size))
            live = indices[:indptr[-1]]
            if full_check and live.size:
                if live.min() < 0 or live.max() >= self.shape[1]:
                    raise ValueError("csr column index out of range")
                # columns must ascend within each row (reference
                # CSRIndicesNotSortedError); vectorized: non-ascending
                # adjacent pairs are violations unless they straddle a
                # row boundary (diff position j compares entries j, j+1;
                # j+1 being a row start makes it a boundary pair)
                if live.size > 1:
                    bad = np.diff(live) <= 0
                    starts = indptr[1:-1]
                    starts = starts[(starts > 0) & (starts < live.size)]
                    bad[starts - 1] = False
                    if np.any(bad):
                        raise ValueError("csr indices not sorted within row")
        elif self.stype == "row_sparse":
            indices = np.asarray(self.indices.asnumpy(), np.int64)
            if full_check and indices.size:
                if indices.min() < 0 or indices.max() >= self.shape[0]:
                    raise ValueError("row_sparse row index out of range")
                if np.any(np.diff(indices) <= 0):
                    raise ValueError("row_sparse indices must be sorted "
                                     "and unique")

    # arithmetic — same-stype fast paths in subclasses; fallback densifies
    def _fallback_binop(self, other, opname, reverse=False):
        _log_fallback(opname, (self.stype, getattr(other, "stype", "scalar")))
        lhs = self.todense()
        rhs = other.todense() if isinstance(other, BaseSparseNDArray) else other
        if reverse:
            lhs, rhs = rhs, lhs
        return _reg.invoke(opname, [lhs, rhs] if isinstance(rhs, NDArray)
                           else [lhs, NDArray(jnp.asarray(rhs, self.dtype))])

    def __add__(self, other):
        return self._fallback_binop(other, "broadcast_add")

    def __sub__(self, other):
        return self._fallback_binop(other, "broadcast_sub")

    def __mul__(self, other):
        return self._fallback_binop(other, "broadcast_mul")

    def __truediv__(self, other):
        return self._fallback_binop(other, "broadcast_div")


class CSRNDArray(BaseSparseNDArray):
    """2-D compressed-sparse-row tensor (``python/mxnet/ndarray/sparse.py:287``)."""

    stype = "csr"

    def __init__(self, data, indices, indptr, shape, dtype=None, ctx=None):
        data, indices, indptr = (_as_jax(data), _as_jax(indices),
                                 _as_jax(indptr))
        if dtype is not None:
            data = data.astype(np_dtype(dtype))
        super().__init__(shape, data.dtype, ctx)
        if len(self._shape) != 2:
            raise ValueError("CSRNDArray is 2-D only, got shape %r" % (shape,))
        self.data = NDArray(data, ctx)
        self.indices = NDArray(jnp.asarray(indices, jnp.int64), ctx)
        self.indptr = NDArray(jnp.asarray(indptr, jnp.int64), ctx)

    @property
    def nnz(self):
        return int(self.indices.shape[0])

    def _dense_data(self):
        n, m = self._shape
        flat = self.indptr._data  # (n+1,)
        counts = flat[1:] - flat[:-1]
        row_ids = jnp.repeat(jnp.arange(n, dtype=jnp.int64), counts,
                             total_repeat_length=self.nnz)
        out = jnp.zeros((n, m), self._dtype)
        return out.at[row_ids, self.indices._data].add(self.data._data)

    def _row_ids(self):
        counts = self.indptr._data[1:] - self.indptr._data[:-1]
        return jnp.repeat(jnp.arange(self._shape[0], dtype=jnp.int64), counts,
                          total_repeat_length=self.nnz)

    def copy(self):
        return CSRNDArray(self.data._data, self.indices._data,
                          self.indptr._data, self._shape, ctx=self._ctx)

    def astype(self, dtype, copy=True):
        return CSRNDArray(self.data._data.astype(np_dtype(dtype)),
                          self.indices._data, self.indptr._data,
                          self._shape, ctx=self._ctx)

    def __getitem__(self, key):
        """Row slicing returns a CSR slice (host-side repack)."""
        if isinstance(key, int):
            nrows = self._shape[0]
            if not -nrows <= key < nrows:
                raise IndexError(
                    "index %d is out of bounds for axis 0 with size %d"
                    % (key, nrows))
            if key < 0:
                key += nrows
            key = slice(key, key + 1)
        if not isinstance(key, slice) or key.step not in (None, 1):
            raise ValueError("CSRNDArray supports contiguous row slicing only")
        start, stop, _ = key.indices(self._shape[0])
        indptr = np.asarray(self.indptr._data)
        lo, hi = int(indptr[start]), int(indptr[stop])
        return CSRNDArray(self.data._data[lo:hi], self.indices._data[lo:hi],
                          indptr[start:stop + 1] - lo,
                          (stop - start, self._shape[1]), ctx=self._ctx)


class RowSparseNDArray(BaseSparseNDArray):
    """Row-sparse tensor (``python/mxnet/ndarray/sparse.py:561``): a subset of
    rows is stored; all other rows are zero.  The canonical gradient type for
    embeddings."""

    stype = "row_sparse"

    def __init__(self, data, indices, shape, dtype=None, ctx=None):
        data, indices = _as_jax(data), _as_jax(indices)
        if dtype is not None:
            data = data.astype(np_dtype(dtype))
        super().__init__(shape, data.dtype, ctx)
        self.data = NDArray(data, ctx)          # (k, *row_shape)
        self.indices = NDArray(jnp.asarray(indices, jnp.int64), ctx)  # (k,)
        if self.data.shape[1:] != self._shape[1:]:
            raise ValueError("row shape mismatch: %r vs %r"
                             % (self.data.shape, self._shape))

    def _dense_data(self):
        out = jnp.zeros(self._shape, self._dtype)
        # .add (not .set): tolerates duplicate indices like reference's
        # row-sparse aggregation
        return out.at[self.indices._data].add(self.data._data)

    def copy(self):
        return RowSparseNDArray(self.data._data, self.indices._data,
                                self._shape, ctx=self._ctx)

    def astype(self, dtype, copy=True):
        return RowSparseNDArray(self.data._data.astype(np_dtype(dtype)),
                                self.indices._data, self._shape, ctx=self._ctx)

    def retain(self, indices):
        return retain(self, indices)

    def __add__(self, other):
        if isinstance(other, RowSparseNDArray) and other.shape == self.shape:
            # canonical row_sparse form (reference invariant): indices sorted
            # and unique — merge duplicates by summation
            idx = np.concatenate([np.asarray(self.indices._data),
                                  np.asarray(other.indices._data)])
            dat = jnp.concatenate([self.data._data, other.data._data])
            uniq, inv = np.unique(idx, return_inverse=True)
            merged = jax.ops.segment_sum(dat, jnp.asarray(inv),
                                         num_segments=len(uniq))
            return RowSparseNDArray(merged, uniq, self._shape, ctx=self._ctx)
        return super().__add__(other)


# ---------------------------------------------------------------------------
# factories
# ---------------------------------------------------------------------------


def csr_matrix(arg1, shape=None, ctx=None, dtype=None) -> CSRNDArray:
    """``mx.nd.sparse.csr_matrix``: from (data, indices, indptr) or dense."""
    if isinstance(arg1, tuple) and len(arg1) == 3:
        data, indices, indptr = arg1
        if shape is None:
            raise ValueError("shape is required for (data, indices, indptr)")
        return CSRNDArray(data, indices, indptr, shape, dtype=dtype, ctx=ctx)
    dense = arg1.asnumpy() if isinstance(arg1, NDArray) else np.asarray(arg1)
    return _dense_to_csr(dense, ctx=ctx, dtype=dtype)


def row_sparse_array(arg1, shape=None, ctx=None, dtype=None) -> RowSparseNDArray:
    if isinstance(arg1, tuple) and len(arg1) == 2:
        data, indices = arg1
        if shape is None:
            raise ValueError("shape is required for (data, indices)")
        return RowSparseNDArray(data, indices, shape, dtype=dtype, ctx=ctx)
    dense = arg1.asnumpy() if isinstance(arg1, NDArray) else np.asarray(arg1)
    return _dense_to_rsp(dense, ctx=ctx, dtype=dtype)


def array(source_array, ctx=None, dtype=None):
    if isinstance(source_array, BaseSparseNDArray):
        out = source_array.copy() if dtype is None else source_array.astype(dtype)
        if ctx is not None:
            out._ctx = ctx
        return out
    raise ValueError("Please use mx.nd.array to create a dense array")


def zeros(stype, shape, ctx=None, dtype="float32"):
    dtype = np_dtype(dtype)
    if isinstance(shape, int):
        shape = (shape,)
    if stype == "csr":
        return CSRNDArray(jnp.zeros((0,), dtype), jnp.zeros((0,), jnp.int64),
                          jnp.zeros((shape[0] + 1,), jnp.int64), shape, ctx=ctx)
    if stype == "row_sparse":
        return RowSparseNDArray(jnp.zeros((0,) + tuple(shape[1:]), dtype),
                                jnp.zeros((0,), jnp.int64), shape, ctx=ctx)
    if stype == "default":
        from . import ndarray as _dense

        return _dense.zeros(shape, ctx=ctx, dtype=dtype)
    raise ValueError("unknown storage type %r" % stype)


def empty(stype, shape, ctx=None, dtype="float32"):
    return zeros(stype, shape, ctx=ctx, dtype=dtype)


def _dense_to_csr(dense: np.ndarray, ctx=None, dtype=None) -> CSRNDArray:
    if dtype is not None:
        dense = dense.astype(np_dtype(dtype))
    if dense.ndim != 2:
        raise ValueError("csr requires 2-D input")
    rows, cols = np.nonzero(dense)
    indptr = np.zeros(dense.shape[0] + 1, np.int64)
    np.add.at(indptr[1:], rows, 1)
    indptr = np.cumsum(indptr)
    return CSRNDArray(dense[rows, cols], cols.astype(np.int64), indptr,
                      dense.shape, ctx=ctx)


def _dense_to_rsp(dense: np.ndarray, ctx=None, dtype=None) -> RowSparseNDArray:
    if dtype is not None:
        dense = dense.astype(np_dtype(dtype))
    flat = dense.reshape(dense.shape[0], -1)
    live = np.nonzero(np.any(flat != 0, axis=1))[0]
    return RowSparseNDArray(dense[live], live.astype(np.int64), dense.shape,
                            ctx=ctx)


# ---------------------------------------------------------------------------
# storage conversion / structural ops
# ---------------------------------------------------------------------------


def cast_storage(arr, stype):
    """``mx.nd.cast_storage`` (src/operator/tensor/cast_storage.cc).

    Pattern discovery is data-dependent-shape → host numpy; the result's
    arrays are device-resident again.
    """
    cur = getattr(arr, "stype", "default")
    if cur == stype:
        return arr
    dense = arr.asnumpy()
    if stype == "default":
        return NDArray(jnp.asarray(dense), getattr(arr, "_ctx", None))
    if stype == "csr":
        return _dense_to_csr(dense, ctx=getattr(arr, "_ctx", None))
    if stype == "row_sparse":
        return _dense_to_rsp(dense, ctx=getattr(arr, "_ctx", None))
    raise ValueError("unknown storage type %r" % stype)


def retain(rsp: RowSparseNDArray, indices) -> RowSparseNDArray:
    """``_sparse_retain`` (src/operator/tensor/sparse_retain.cc): keep only
    the given rows of a row_sparse array."""
    if not isinstance(rsp, RowSparseNDArray):
        raise TypeError("retain expects RowSparseNDArray")
    want = np.asarray(indices.asnumpy() if isinstance(indices, NDArray)
                      else indices).astype(np.int64).ravel()
    have = np.asarray(rsp.indices._data)
    pos = {int(r): i for i, r in enumerate(have)}
    keep_rows = [r for r in want if int(r) in pos]
    sel = np.asarray([pos[int(r)] for r in keep_rows], np.int64)
    return RowSparseNDArray(rsp.data._data[sel],
                            np.asarray(keep_rows, np.int64), rsp.shape,
                            ctx=rsp._ctx)


# ---------------------------------------------------------------------------
# compute: sparse dot
# ---------------------------------------------------------------------------


def dot(lhs, rhs, transpose_a=False, transpose_b=False):
    """``mx.nd.sparse.dot``: CSR × dense (src/operator/tensor/dot-inl.h).

    Static-shape device compute: nnz is a compile-time constant, so the
    gather/segment-sum program is XLA-compiled once per sparsity layout.
    """
    if isinstance(lhs, CSRNDArray) and isinstance(rhs, NDArray):
        if transpose_b:
            rhs = rhs.transpose()
        d, col, row = lhs.data._data, lhs.indices._data, lhs._row_ids()
        if not transpose_a:
            # out[i,:] = Σ_{k in row i} data[k] * rhs[col[k],:]
            contrib = d[:, None] * rhs._data[col]
            out = jax.ops.segment_sum(contrib, row,
                                      num_segments=lhs.shape[0])
        else:
            # out[j,:] = Σ_{k: col[k]==j} data[k] * rhs[row[k],:]
            contrib = d[:, None] * rhs._data[row]
            out = jax.ops.segment_sum(contrib, col,
                                      num_segments=lhs.shape[1])
        return NDArray(out, lhs._ctx)
    if isinstance(lhs, NDArray) and isinstance(rhs, CSRNDArray):
        # op_a(A) @ op_b(B) = (op_!b(B) @ op_!a(A))ᵀ
        return dot(rhs, lhs, transpose_a=not transpose_b,
                   transpose_b=not transpose_a).transpose()
    if isinstance(lhs, NDArray) and isinstance(rhs, NDArray):
        return _reg.invoke("dot", [lhs, rhs], transpose_a=transpose_a,
                           transpose_b=transpose_b)
    raise TypeError("sparse.dot: unsupported combination (%s, %s)"
                    % (getattr(lhs, "stype", "?"), getattr(rhs, "stype", "?")))


def add(lhs, rhs):
    if isinstance(lhs, BaseSparseNDArray):
        return lhs + rhs
    return rhs + lhs


def subtract(lhs, rhs):
    if isinstance(lhs, BaseSparseNDArray):
        return lhs - rhs
    return (rhs - lhs) * -1.0


def multiply(lhs, rhs):
    if isinstance(lhs, BaseSparseNDArray):
        return lhs * rhs
    return rhs * lhs


# ---------------------------------------------------------------------------
# row-sparse (lazy) optimizer updates
# ---------------------------------------------------------------------------
# Reference semantics (optimizer_op.cc lazy_update): only rows present in the
# gradient are updated; untouched rows keep weight AND state unchanged.
# Realized as jit-compiled scatter programs over the live rows.


def _prep(grad: RowSparseNDArray, rescale_grad, clip_gradient):
    g = grad.data._data * rescale_grad
    # reference convention: clip_gradient < 0 means disabled
    if clip_gradient is not None and clip_gradient >= 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    return g, grad.indices._data


def _dense_update(opname, weight, grad, states, **kw):
    """std_update path (lazy_update=False): densify and run the dense op so
    wd decay reaches ALL rows, matching optimizer_op.cc std semantics."""
    res = _reg.invoke(opname, [weight, grad.todense()] + list(states), **kw)
    if not isinstance(res, (list, tuple)):
        res = [res]
    for dst, src in zip([weight] + list(states), res):
        dst._data = src._data
    return weight


@jax.jit
def _rsp_sgd(w, g, rows, lr, wd):
    upd = g + wd * w[rows]
    return w.at[rows].add(-lr * upd)


@jax.jit
def _rsp_sgd_mom(w, mom, g, rows, lr, wd, momentum):
    m_rows = momentum * mom[rows] - lr * (g + wd * w[rows])
    return w.at[rows].add(m_rows), mom.at[rows].set(m_rows)


@jax.jit
def _rsp_adam(w, mean, var, g, rows, lr, beta1, beta2, epsilon, wd):
    g = g + wd * w[rows]
    m_rows = beta1 * mean[rows] + (1 - beta1) * g
    v_rows = beta2 * var[rows] + (1 - beta2) * g * g
    step = lr * m_rows / (jnp.sqrt(v_rows) + epsilon)
    return (w.at[rows].add(-step), mean.at[rows].set(m_rows),
            var.at[rows].set(v_rows))


@jax.jit
def _rsp_adagrad(w, hist, g, rows, lr, epsilon, wd):
    # matches dense _sparse_adagrad_update: wd folded into g, eps outside sqrt
    g = g + wd * w[rows]
    h_rows = hist[rows] + g * g
    step = lr * g / (jnp.sqrt(h_rows) + epsilon)
    return w.at[rows].add(-step), hist.at[rows].set(h_rows)


def sgd_update(weight, grad, lr, wd=0.0, rescale_grad=1.0,
               clip_gradient=None, lazy_update=True):
    if not lazy_update:
        return _dense_update("sgd_update", weight, grad, [], lr=lr, wd=wd,
                             rescale_grad=rescale_grad,
                             clip_gradient=clip_gradient)
    g, rows = _prep(grad, rescale_grad, clip_gradient)
    weight._data = _rsp_sgd(weight._data, g, rows, lr, wd)
    return weight


def sgd_mom_update(weight, grad, mom, lr, momentum=0.0, wd=0.0,
                   rescale_grad=1.0, clip_gradient=None, lazy_update=True):
    if not lazy_update:
        return _dense_update("sgd_mom_update", weight, grad, [mom], lr=lr,
                             momentum=momentum, wd=wd,
                             rescale_grad=rescale_grad,
                             clip_gradient=clip_gradient)
    g, rows = _prep(grad, rescale_grad, clip_gradient)
    weight._data, mom._data = _rsp_sgd_mom(weight._data, mom._data, g, rows,
                                           lr, wd, momentum)
    return weight


def adam_update(weight, grad, mean, var, lr, beta1=0.9, beta2=0.999,
                epsilon=1e-8, wd=0.0, rescale_grad=1.0, clip_gradient=None,
                lazy_update=True):
    if not lazy_update:
        return _dense_update("adam_update", weight, grad, [mean, var], lr=lr,
                             beta1=beta1, beta2=beta2, epsilon=epsilon, wd=wd,
                             rescale_grad=rescale_grad,
                             clip_gradient=clip_gradient)
    g, rows = _prep(grad, rescale_grad, clip_gradient)
    weight._data, mean._data, var._data = _rsp_adam(
        weight._data, mean._data, var._data, g, rows, lr, beta1, beta2,
        epsilon, wd)
    return weight


def adagrad_update(weight, grad, history, lr, epsilon=1e-7, wd=0.0,
                   rescale_grad=1.0, clip_gradient=None):
    g, rows = _prep(grad, rescale_grad, clip_gradient)
    weight._data, history._data = _rsp_adagrad(
        weight._data, history._data, g, rows, lr, epsilon, wd)
    return weight
