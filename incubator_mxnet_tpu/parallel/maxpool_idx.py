"""Argmax-carrying max-pool forward (round 20).

The shifted-window maxpool backward (``ops.nn.shifted_window_unpool``)
recomputes the winner index from ``(data, out)``: at 224 px that is a
411 MB elementwise re-read of the stem ghost-BN output (the sole GL202
census survivor of rounds 14-19) plus a 103 MB read of the pooled
output, and the scatter accumulates in PADDED coordinates — a
(256, 64, 114, 114) write that the stem BN backward kernel then reads
back through its gY window at the padded size.  This module moves the
argmax to the FORWARD: one Pallas pass emits the pooled maximum
together with the winning in-window offset (int8, row-major-first tie
rule — bit-identical to ``select_and_scatter_add``'s GE-select and to
the reference's pool.h ``unpool_max_*_cpu``), so the backward routes
gradients from the 51 MB index plane alone and accumulates directly in
UNPADDED input coordinates (negative edge padding clips the
contributions that the old code parked in pad rows and sliced away).

Per-step delta at batch 256 / 224 px bf16 (priced by
analysis/cost_model.py):

    fwd   +51 MB   int8 index plane write (the data read moves from
                   the reduction category to this kernel, same bytes)
    bwd  -411 MB   no data re-read (census survivor gone)
         -103 MB   no pooled-output read
          -15 MB   dX written at 112x112, not 114x114
          -15 MB   stem BN bwd reads gY at 112x112, not 114x114

The kernel grid is (N, C / c_blk) with whole-spatial blocks — the stem
shape (256, 64, 112, 112) needs 1.8 MB of VMEM per x block, nowhere
near the fused-BN window problem — and every program reads and writes
disjoint slices, so the cost model's one-read custom-call contract
holds by construction.  Shapes the plan cannot place (rank != 4,
pooling over N/C, >127 in-window offsets, VMEM misfit) fall back to
``None`` and the caller keeps the shifted-window recompute path.
"""

from __future__ import annotations

import functools
import itertools
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_I0 = np.int32(0)  # index-map literal pinned to i32 (package enables x64)

_CompilerParams = getattr(pltpu, "CompilerParams", None) \
    or getattr(pltpu, "TPUCompilerParams")

__all__ = ["MaxPoolPlan", "plan", "maxpool_with_index", "indexed_unpool"]

#: per-program VMEM ceiling for the (x, padded x, out, idx) working set,
#: double-buffered.  Deliberately small: the kernel is bandwidth-bound
#: and gains nothing from large blocks.
_BLOCK_BUDGET = 8 * 1024 * 1024


def _rup(x, m):
    return -(-x // m) * m


def _use_interpret():
    try:
        return jax.default_backend() != "tpu"
    except Exception:
        return True


class MaxPoolPlan(NamedTuple):
    c_blk: int
    out_hw: Tuple[int, int]


def plan(shape, itemsize, window, strides, padding) -> Optional[MaxPoolPlan]:
    """Place the indexed forward, or ``None`` for the fallback path.

    ``window``/``strides`` are full-rank NCHW (leading (1, 1)),
    ``padding`` is the full-rank ``((0,0),(0,0),(ph,ph'),(pw,pw'))``
    reduce_window config (pooling_convention="full" pads the high edge
    asymmetrically — supported)."""
    if len(shape) != 4 or len(window) != 4:
        return None
    if tuple(window[:2]) != (1, 1) or tuple(strides[:2]) != (1, 1):
        return None
    if tuple(padding[0]) != (0, 0) or tuple(padding[1]) != (0, 0):
        return None
    noff = window[2] * window[3]
    if not 2 <= noff <= 127:        # int8 index plane; 1x1 is a copy
        return None
    n, c, h, w = shape
    oh = (h + sum(padding[2]) - window[2]) // strides[2] + 1
    ow = (w + sum(padding[3]) - window[3]) // strides[3] + 1
    if oh < 1 or ow < 1:
        return None
    hp = h + sum(padding[2])
    wp = w + sum(padding[3])
    sub = 16 if itemsize == 2 else 8
    per_c = (_rup(h, sub) * _rup(w, 128) + _rup(hp, sub) * _rup(wp, 128)
             + _rup(oh, sub) * _rup(ow, 128)) * itemsize \
        + _rup(oh, 32) * _rup(ow, 128)          # int8 index tile
    for cb in range(min(c, 64), 0, -1):
        if c % cb == 0 and 2 * cb * per_c <= _BLOCK_BUDGET:
            return MaxPoolPlan(cb, (oh, ow))
    return None


def _kernel(x_ref, out_ref, idx_ref, *, window, strides, padding, out_hw):
    x = x_ref[...]
    neg = np.asarray(-jnp.inf, x.dtype)[()]
    xp = jnp.pad(x, ((0, 0), (0, 0), tuple(padding[2]), tuple(padding[3])),
                 constant_values=neg)
    oh, ow = out_hw
    sh, sw = strides[2], strides[3]
    best = None
    idx = None
    lin = 0
    for i in range(window[2]):
        for j in range(window[3]):
            xs = lax.slice(
                xp, (0, 0, i, j),
                (xp.shape[0], xp.shape[1],
                 i + (oh - 1) * sh + 1, j + (ow - 1) * sw + 1),
                (1, 1, sh, sw))
            if best is None:
                best = xs
                idx = jnp.zeros(xs.shape, jnp.int32)
            else:
                # strict > keeps the EARLIER offset on ties: the final
                # index is the first in-window argmax in row-major scan
                # order, the same winner shifted_window_unpool derives
                # from (data, out) and select_and_scatter_add's
                # GE-select picks
                idx = jnp.where(xs > best, jnp.int32(lin), idx)
                best = jnp.maximum(best, xs)
            lin += 1
    out_ref[...] = best
    idx_ref[...] = idx.astype(jnp.int8)


def maxpool_with_index(data, window, strides, padding, p: MaxPoolPlan):
    """Pooled max + int8 winner-offset plane, one read of ``data``."""
    n, c, h, w = data.shape
    oh, ow = p.out_hw
    cb = p.c_blk
    xspec = pl.BlockSpec((1, cb, h, w), lambda i, j: (i, j, _I0, _I0))
    ospec = pl.BlockSpec((1, cb, oh, ow), lambda i, j: (i, j, _I0, _I0))
    kern = functools.partial(_kernel, window=tuple(window),
                             strides=tuple(strides),
                             padding=tuple(tuple(q) for q in padding),
                             out_hw=p.out_hw)
    return pl.pallas_call(
        kern, grid=(n, c // cb), in_specs=[xspec],
        out_specs=[ospec, ospec],
        out_shape=[jax.ShapeDtypeStruct((n, c, oh, ow), data.dtype),
                   jax.ShapeDtypeStruct((n, c, oh, ow), jnp.int8)],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel")),
        interpret=_use_interpret())(data)


def indexed_unpool(first, g, in_shape, window, strides, padding):
    """Backward from the saved index plane alone.

    ``dx[p] += g[w]`` exactly when window ``w`` covers ``p`` at offset
    ``first[w]``.  One fused elementwise region reading (first, g):
    no data/out recompute, and the per-offset contributions are placed
    with interior-dilated ``lax.pad`` whose (possibly NEGATIVE) edge
    config lands them directly in unpadded input coordinates — a
    contribution whose target falls in a pad row is clipped, which is
    exact because a -inf pad cell never wins the forward argmax."""
    offsets = list(itertools.product(*[range(k) for k in window]))
    zero = np.asarray(0, g.dtype)[()]
    dx = None
    for lin, offset in enumerate(offsets):
        contrib = jnp.where(first == jnp.int8(lin), g, zero)
        cfg = []
        for o, (plo, _), s, xd, od in zip(offset, padding, strides,
                                          in_shape, g.shape):
            lo = o - plo
            cfg.append((lo, xd - lo - ((od - 1) * s + 1), s - 1))
        piece = lax.pad(contrib, zero, cfg)
        dx = piece if dx is None else dx + piece
    return dx
