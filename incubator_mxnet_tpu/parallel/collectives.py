"""Collective communication primitives.

The distributed communication backend (SURVEY.md §5.8): where the reference
routed gradients through CommDevice/NCCL/ps-lite, these are thin named
wrappers over XLA collectives that ride ICI within a slice and DCN across
slices.  Use inside ``shard_map`` bodies (or rely on GSPMD inserting them
automatically from shardings).
"""
from __future__ import annotations

from jax import lax

__all__ = ["allreduce", "allgather", "reduce_scatter", "alltoall", "ppermute",
           "axis_size", "axis_index", "pmean", "broadcast_from"]


def allreduce(x, axis_name):
    """Sum across the axis (ncclAllReduce / dist_sync analog)."""
    return lax.psum(x, axis_name)


def pmean(x, axis_name):
    return lax.pmean(x, axis_name)


def _concrete_axis_size(axis_name):
    """Axis size as a concrete int when available (inside shard_map/pmap
    the named axis has a static size), else None — the same trick the
    eager ppermute check uses."""
    try:
        n = lax.psum(1, axis_name)
    except NameError:
        return None
    return n if isinstance(n, int) else None


def _check_dim(x, dim, axis_name, op, role, extra=0):
    """Eager shape validation for sharding collectives: ``dim`` must be a
    real dimension of ``x`` (``extra=1`` admits one past the end — an
    untiled all_gather stacks shards onto a NEW axis).  Raises ValueError
    naming the axis instead of letting XLA surface a cryptic shape error
    at compile time."""
    ndim = getattr(x, "ndim", None)
    if ndim is not None and not (0 <= dim < ndim + extra):
        raise ValueError(
            "%s over axis %r: %s %d is out of range for a %d-dimensional "
            "operand (shape %s)"
            % (op, axis_name, role, dim, ndim, tuple(x.shape)))


def _check_divisible(x, dim, axis_name, n, op, role):
    if n is None:
        return
    size = x.shape[dim]
    if size % n:
        raise ValueError(
            "%s over axis %r (size %d): %s dimension %d has size %d, "
            "which does not divide by the axis size — each rank must "
            "receive an equal shard (pad the dimension to a multiple of "
            "%d, or see the pad-and-slice path in "
            "parallel/train_step.py zero=1)"
            % (op, axis_name, n, role, dim, size, n))


def allgather(x, axis_name, axis=0, tiled=True):
    """Gather shards (ncclAllGather analog).

    Eagerly validates that ``axis`` is a real dimension of ``x`` (the
    concat dimension; untiled gathers may also name the one-past-the-end
    position — they stack shards onto a NEW axis), raising a
    ``ValueError`` naming the collective axis instead of a cryptic XLA
    shape error.
    """
    _check_dim(x, axis, axis_name, "allgather", "concat",
               extra=0 if tiled else 1)
    return lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def reduce_scatter(x, axis_name, scatter_dimension=0, tiled=True):
    """Sum then scatter (ncclReduceScatter analog; ZeRO grad sharding).

    Eagerly validates the scatter dimension: it must exist and its size
    must divide the axis size (each rank receives an equal shard), else
    a ``ValueError`` naming the axis is raised at trace time.
    """
    _check_dim(x, scatter_dimension, axis_name, "reduce_scatter", "scatter")
    _check_divisible(x, scatter_dimension, axis_name,
                     _concrete_axis_size(axis_name), "reduce_scatter",
                     "scatter")
    return lax.psum_scatter(x, axis_name,
                            scatter_dimension=scatter_dimension, tiled=tiled)


def alltoall(x, axis_name, split_axis, concat_axis, tiled=True):
    """All-to-all (ncclAllToAll analog; MoE dispatch/combine).

    Eagerly validates both dimensions and that the split dimension
    divides the axis size, raising a ``ValueError`` naming the axis.
    """
    _check_dim(x, split_axis, axis_name, "alltoall", "split")
    _check_dim(x, concat_axis, axis_name, "alltoall", "concat")
    _check_divisible(x, split_axis, axis_name,
                     _concrete_axis_size(axis_name), "alltoall", "split")
    return lax.all_to_all(x, axis_name, split_axis=split_axis,
                          concat_axis=concat_axis, tiled=tiled)


def ppermute(x, axis_name, perm):
    """Collective permute with eager graftlint GL001 validation.

    A malformed permutation (duplicated sources/destinations, ranks
    outside the axis) deadlocks or silently drops a shard on hardware;
    here it raises a ``ValueError`` naming the axis and the offending
    ranks *at trace time*.  Partial (non-bijective) permutations are
    legal — that is the pipeline fill/drain pattern.
    """
    perm = [(int(s), int(d)) for s, d in perm]
    n = _concrete_axis_size(axis_name)  # concrete inside shard_map/pmap
    if n is not None:
        from ..analysis.trace_lint import validate_permutation

        validate_permutation(perm, n, axis_name)
    return lax.ppermute(x, axis_name, perm)


def axis_size(axis_name):
    return lax.psum(1, axis_name)


def axis_index(axis_name):
    return lax.axis_index(axis_name)


def broadcast_from(x, axis_name, src=0):
    """Broadcast src's shard to all (ncclBcast analog)."""
    import jax.numpy as jnp

    idx = lax.axis_index(axis_name)
    masked = jnp.where(idx == src, x, jnp.zeros_like(x))
    return lax.psum(masked, axis_name)
