"""Collective communication primitives.

The distributed communication backend (SURVEY.md §5.8): where the reference
routed gradients through CommDevice/NCCL/ps-lite, these are thin named
wrappers over XLA collectives that ride ICI within a slice and DCN across
slices.  Use inside ``shard_map`` bodies (or rely on GSPMD inserting them
automatically from shardings).
"""
from __future__ import annotations

from jax import lax

__all__ = ["allreduce", "allgather", "reduce_scatter", "alltoall", "ppermute",
           "axis_size", "axis_index", "pmean", "broadcast_from"]


def allreduce(x, axis_name):
    """Sum across the axis (ncclAllReduce / dist_sync analog)."""
    return lax.psum(x, axis_name)


def pmean(x, axis_name):
    return lax.pmean(x, axis_name)


def allgather(x, axis_name, axis=0, tiled=True):
    """Gather shards (ncclAllGather analog)."""
    return lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def reduce_scatter(x, axis_name, scatter_dimension=0, tiled=True):
    """Sum then scatter (ncclReduceScatter analog; ZeRO grad sharding)."""
    return lax.psum_scatter(x, axis_name,
                            scatter_dimension=scatter_dimension, tiled=tiled)


def alltoall(x, axis_name, split_axis, concat_axis, tiled=True):
    return lax.all_to_all(x, axis_name, split_axis=split_axis,
                          concat_axis=concat_axis, tiled=tiled)


def ppermute(x, axis_name, perm):
    """Collective permute with eager graftlint GL001 validation.

    A malformed permutation (duplicated sources/destinations, ranks
    outside the axis) deadlocks or silently drops a shard on hardware;
    here it raises a ``ValueError`` naming the axis and the offending
    ranks *at trace time*.  Partial (non-bijective) permutations are
    legal — that is the pipeline fill/drain pattern.
    """
    perm = [(int(s), int(d)) for s, d in perm]
    try:
        n = lax.psum(1, axis_name)  # concrete int inside shard_map/pmap
    except NameError:
        n = None
    if isinstance(n, int):
        from ..analysis.trace_lint import validate_permutation

        validate_permutation(perm, n, axis_name)
    return lax.ppermute(x, axis_name, perm)


def axis_size(axis_name):
    return lax.psum(1, axis_name)


def axis_index(axis_name):
    return lax.axis_index(axis_name)


def broadcast_from(x, axis_name, src=0):
    """Broadcast src's shard to all (ncclBcast analog)."""
    import jax.numpy as jnp

    idx = lax.axis_index(axis_name)
    masked = jnp.where(idx == src, x, jnp.zeros_like(x))
    return lax.psum(masked, axis_name)
