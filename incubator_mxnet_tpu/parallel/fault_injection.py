"""Fault-injection harness for the resilience layer.

Deterministic, test-grade fault injectors for the failure classes
``docs/RESILIENCE.md`` claims to survive:

- **bad numerics** — :func:`poison_batch` / :class:`NaNInjector` make a
  chosen step produce non-finite gradients (a NaN/inf planted in the
  input propagates through the forward AND the backward pass, which is
  exactly how a corrupt record or an fp16 overflow presents);
- **failed writes** — :func:`fail_writes` interposes the checkpoint
  module's byte-writer and raises ``OSError`` on selected writes
  (transient by default, so retry-with-backoff is exercised; persistent
  to prove a failed save never corrupts the last committed checkpoint);
- **silent corruption** — :func:`corrupt_checkpoint` bit-flips or
  truncates a *committed* array file, the torn-write/bit-rot case the
  per-file checksums exist to catch;
- **input-pipeline faults** — :func:`flaky_reads` / :func:`slow_reads` /
  :func:`kill_worker` interpose the resilient loader's record puller
  (``io/resilient.py::_pull``) with transient errnos, injected latency
  and silent worker death, and :func:`truncate_record` tears a record
  file at a byte offset exactly like a crash mid-write — together they
  drive ``tests/test_resilient_io.py``;
- **request-level faults** — :func:`malformed_request` builds payloads
  the batcher must reject per-request (wrong rank/shape/dtype,
  unconvertible objects) without killing the batch or the queue,
  :func:`slow_client` stalls request admission (the trickling-client
  case the deadline-triggered flush exists for) by interposing
  ``serve/batcher.py::_admit``, and :func:`burst_arrivals` submits a
  thundering herd the bounded queue must absorb or shed as
  ``Backpressure`` — together they drive ``tests/test_serve.py``;
- **serving chaos** — :func:`kill_batcher_worker` silently kills the
  continuous batcher's worker thread mid-batch (the watchdog must fail
  the lost batch and respawn within budget),
  :func:`engine_failure_burst` makes the next N engine executions
  raise (retry-with-backoff absorbs a short burst; a long one trips
  the circuit breaker into the degradation ladder), :func:`nan_params`
  builds a poisoned hot-weight-swap candidate (the canary must reject
  it and roll back), :func:`deadline_storm` submits a burst whose
  SLO deadlines expire in the queue (shed before compute, never served
  dead), and :func:`swap_storm` fires N back-to-back canaried hot
  weight swaps from a background thread under the caller's live
  traffic — the flywheel promotion storm: p99 must hold its bound,
  ``recompile_count`` must not move, every request keeps
  exactly-one-version attribution, and a poisoned candidate mid-storm
  must roll back with the incumbent bitwise intact — together they
  drive ``tests/test_serve_resilience.py``, ``tests/test_flywheel.py``
  and the ``tools/serve_bench.py --chaos`` legs.  The first two
  interpose ``serve/batcher.py::_serve_batch``, the engine-execution
  choke point, exactly like ``slow_client`` interposes ``_admit``;
- **supervised-training chaos** — :func:`hang_step` wedges the
  supervised step callable (the ``parallel/supervisor.py::_run_step``
  choke point, exactly like ``_patched_serve`` wedges the batcher) so
  the rank stops heartbeating mid-step — the watchdog's hang detector
  must fire within its auto-calibrated stall timeout; with a small
  ``duration`` and a large ``count`` it is the per-step slowdown the
  STRAGGLER detector exists for; :func:`loss_bomb` plants finite
  exploding gradients (the live params are scaled in place, so the
  loss explodes while every gradient stays finite — invisible to
  ``nonfinite="skip"``, the divergence detector's regression case;
  only a checkpoint rollback restores health) — together they drive
  ``tests/test_supervisor.py`` and the ``tools/supervise.py --chaos``
  matrix;
- **async push/pull chaos** — :func:`slow_link` adds per-message
  latency to one rank's (or every rank's) pushes and pulls through the
  parameter-service transport choke points
  (``parallel/param_service.py::_deliver_push``/``_deliver_pull``,
  exactly like ``hang_step`` interposes ``supervisor._run_step``) —
  the slow-NIC straggler whose peers must keep training inside the
  staleness bound; :func:`drop_push` deterministically loses a
  fraction of push payloads on the wire (the step still completes —
  fire-and-forget semantics — so the clock advances while the update
  is gone), the lossy-transport case error-feedback compression and
  the bounded-staleness invariant must both survive — together they
  drive ``tests/test_param_service.py``;
- **host loss** — :func:`kill_process` is a REAL ungraceful process
  death (SIGKILL: no atexit, no flushes — what a preempted VM looks
  like), :func:`host_loss_during_save` arms it on the N-th checkpoint
  write so a host dies exactly mid-stage (the torn multi-process
  checkpoint the commit protocol must never publish),
  :func:`coordinator_unreachable` makes the ``jax.distributed``
  rendezvous fail like a dead coordinator, and
  :func:`straggler_process` delays this process's done-marker so the
  commit coordinator's bounded wait is exercised — together they drive
  ``tests/test_elastic.py``.

Everything here is process-local monkeypatching or direct file surgery
(plus the one genuinely lethal :func:`kill_process`, used only in
spawned subprocess tests): cheap enough for tier-1.
"""
from __future__ import annotations

import errno as _errno
import os
import signal as _signal
import time
from contextlib import contextmanager
from typing import Optional

import numpy as np

__all__ = ["NaNInjector", "burst_arrivals", "coordinator_unreachable",
           "corrupt_checkpoint", "corrupt_compile_cache", "deadline_storm",
           "drop_push", "engine_failure_burst",
           "fail_writes", "flaky_reads", "hang_step",
           "host_loss_during_save", "kill_batcher_worker",
           "kill_process", "kill_worker", "loss_bomb",
           "malformed_request",
           "nan_params", "poison_batch", "slow_client", "slow_link",
           "slow_reads",
           "straggler_process", "swap_storm", "truncate_record"]


def poison_batch(x, value=float("nan"), index=0):
    """Copy of batch ``x`` with ``value`` (NaN by default) planted at
    flat position ``index`` — one poisoned element is enough to make
    every gradient of a dense net non-finite."""
    from ..ndarray import NDArray

    arr = np.array(x.asnumpy() if isinstance(x, NDArray) else x)
    flat = arr.reshape(-1)
    flat[index] = value
    return NDArray(arr) if isinstance(x, NDArray) else arr


class NaNInjector:
    """Wrap a train step so its ``at_steps``-th calls (0-based) see a
    poisoned batch: ``inj = NaNInjector(step, at_steps=(2,))`` then call
    ``inj(x, y)`` in place of ``step(x, y)``."""

    def __init__(self, step, at_steps=(0,), value=float("nan")):
        self.step = step
        self.at_steps = set(int(s) for s in at_steps)
        self.value = value
        self.calls = 0

    def __call__(self, x, y):
        if self.calls in self.at_steps:
            x = poison_batch(x, self.value)
        self.calls += 1
        return self.step(x, y)

    def __getattr__(self, name):
        # transparent proxy: the supervised loop reads step_count/
        # loss_scale/skipped_steps and drives checkpoints through the
        # wrapped step, so an injected step is a drop-in replacement
        return getattr(self.step, name)


@contextmanager
def fail_writes(at=0, count=1, exc: Optional[BaseException] = None):
    """Make the checkpoint writer raise on selected file writes.

    ``at`` — 0-based ordinal of the first write (within this context)
    that fails; ``count`` — how many consecutive writes fail from there
    (so the default ``at=0, count=1`` is one transient fault the
    retry loop must absorb; a large ``count`` is a persistent outage).
    Yields a stats object whose ``.failed`` counts injected faults.
    """
    from . import checkpoint as _ckpt

    exc = exc or OSError("injected write failure")
    real = _ckpt._write_bytes

    class _Stats:
        seen = 0
        failed = 0

    stats = _Stats()

    def flaky(path, data):
        i = stats.seen
        stats.seen += 1
        if at <= i < at + count:
            stats.failed += 1
            raise exc
        return real(path, data)

    _ckpt._write_bytes = flaky
    try:
        yield stats
    finally:
        _ckpt._write_bytes = real


@contextmanager
def _patched_pull(flaky):
    """Interpose ``io/resilient.py::_pull`` (the one choke point every
    resilient read goes through) with ``flaky(real_pull, next_fn)``."""
    from ..io import resilient as _res

    real = _res._pull
    _res._pull = lambda next_fn: flaky(real, next_fn)
    try:
        yield
    finally:
        _res._pull = real


@contextmanager
def flaky_reads(every_k=3, errno=None, count=None):
    """Make every ``every_k``-th resilient read raise a transient
    ``OSError`` (default errno EIO) BEFORE touching the underlying
    iterator — the retry immediately after targets the same record, so
    retry-with-backoff must absorb the fault with no record lost.

    ``count`` bounds the total number of injected faults (``None`` =
    unbounded).  Yields a stats object whose ``.failed`` counts
    injections and ``.seen`` all reads."""
    eno = _errno.EIO if errno is None else int(errno)

    class _Stats:
        seen = 0
        failed = 0

    stats = _Stats()

    def flaky(real, next_fn):
        i = stats.seen
        stats.seen += 1
        if i % every_k == every_k - 1 and \
                (count is None or stats.failed < count):
            stats.failed += 1
            raise OSError(eno, "injected flaky read (#%d)" % i)
        return real(next_fn)

    with _patched_pull(flaky):
        yield stats


@contextmanager
def slow_reads(latency_s, at=0, count=None):
    """Add ``latency_s`` seconds to resilient reads from the ``at``-th
    onward (``count`` bounds how many; ``None`` = all) — the hung-read
    case a per-read timeout must surface as an error instead of
    blocking the training loop forever."""
    class _Stats:
        seen = 0
        slowed = 0

    stats = _Stats()

    def slow(real, next_fn):
        i = stats.seen
        stats.seen += 1
        if i >= at and (count is None or stats.slowed < count):
            stats.slowed += 1
            time.sleep(latency_s)
        return real(next_fn)

    with _patched_pull(slow):
        yield stats


@contextmanager
def kill_worker(at=0, count=1):
    """Silently kill the prefetch worker on selected reads: raises
    ``SystemExit`` (a ``BaseException`` — it escapes the read-policy
    ``except Exception`` and the thread machinery swallows it) BEFORE
    the underlying iterator is touched, so no record is lost and the
    respawned worker continues exactly where the dead one stood."""
    class _Stats:
        seen = 0
        killed = 0

    stats = _Stats()

    def kill(real, next_fn):
        i = stats.seen
        stats.seen += 1
        if at <= i < at + count:
            stats.killed += 1
            raise SystemExit("injected worker death (#%d)" % i)
        return real(next_fn)

    with _patched_pull(kill):
        yield stats


def truncate_record(path, offset):
    """Tear a record file at byte ``offset`` — exactly what a crash
    mid-write leaves behind.  Returns the number of bytes cut off."""
    size = os.path.getsize(path)
    if not 0 <= offset < size:
        raise ValueError("offset %d outside file %r (size %d)"
                         % (offset, path, size))
    with open(path, "r+b") as f:
        f.truncate(int(offset))
    return size - int(offset)


def corrupt_checkpoint(directory, step=None, what="bitflip", which=0):
    """Damage a COMMITTED checkpoint in place; returns the path touched.

    ``what``: ``"bitflip"`` flips one bit mid-payload of the
    ``which``-th array file (silent corruption a checksum must catch);
    ``"truncate"`` halves the file (torn write); ``"manifest"``
    truncates the manifest itself; ``"torn_manifest"`` reproduces a
    crash in the middle of the manifest commit itself — the manifest
    is cut mid-JSON *and* a half-renamed ``manifest.json.tmp`` twin is
    left beside it, exactly what a host loss between the manifest
    write and the directory fsync can leave on some filesystems.
    ``restore`` must treat both the same way: unparseable manifest →
    corrupt candidate → fall back to the last fully-committed step.
    """
    from .checkpoint import _MANIFEST, _STEP_FMT, CheckpointManager

    mgr = CheckpointManager(directory, process_count=1)
    step = mgr.latest_step() if step is None else int(step)
    if step is None:
        raise ValueError("no committed checkpoint under %r" % (directory,))
    d = os.path.join(str(directory), _STEP_FMT % step)
    if what in ("manifest", "torn_manifest"):
        path = os.path.join(d, _MANIFEST)
        data = open(path, "rb").read()
        if what == "torn_manifest":
            # the half-renamed twin: full content under the pre-rename
            # name, torn content under the committed name
            with open(path + ".tmp", "wb") as f:
                f.write(data)
        with open(path, "r+b") as f:
            f.truncate(max(len(data) // 2, 1))
        return path
    names = sorted(n for n in os.listdir(d) if n.endswith(".bin"))
    if not names:
        raise ValueError("no array files in %r" % d)
    path = os.path.join(d, names[int(which) % len(names)])
    if what == "truncate":
        with open(path, "r+b") as f:
            f.truncate(max(os.path.getsize(path) // 2, 1))
    elif what == "bitflip":
        with open(path, "r+b") as f:
            data = bytearray(f.read())
            data[len(data) // 2] ^= 0x10
            f.seek(0)
            f.write(data)
    else:
        raise ValueError("what must be 'bitflip', 'truncate', 'manifest' "
                         "or 'torn_manifest', got %r" % (what,))
    return path


def corrupt_compile_cache(directory, what="truncate", which=0):
    """Damage a persistent compile-cache entry (``parallel/aot.py``
    ``CompileCache``) in place; returns the path touched.

    ``what``: ``"truncate"`` halves the entry (torn write — what a
    crash mid-publish would leave if the atomic rename discipline were
    ever broken); ``"bitflip"`` flips one bit mid-payload (silent
    corruption); ``"garbage"`` replaces the whole entry with
    non-pickle bytes.  Every case must degrade to
    recompile-with-warning: never a crash, never a wrong executable.
    """
    names = sorted(n for n in os.listdir(str(directory))
                   if n.endswith(".xc"))
    if not names:
        raise ValueError("no compile-cache entries under %r" % (directory,))
    path = os.path.join(str(directory), names[int(which) % len(names)])
    if what == "truncate":
        with open(path, "r+b") as f:
            f.truncate(max(os.path.getsize(path) // 2, 1))
    elif what == "bitflip":
        with open(path, "r+b") as f:
            data = bytearray(f.read())
            data[len(data) // 2] ^= 0x10
            f.seek(0)
            f.write(data)
    elif what == "garbage":
        with open(path, "wb") as f:
            f.write(b"not a cache entry")
    else:
        raise ValueError("what must be 'truncate', 'bitflip' or "
                         "'garbage', got %r" % (what,))
    return path


# ---------------------------------------------------------------------------
# request-level scenarios (serving: serve/batcher.py)
# ---------------------------------------------------------------------------

class _BadPayload:
    """An object whose array conversion raises — a request body that is
    not even parseable, the worst malformed-request class."""

    def __array__(self, *a, **k):
        raise ValueError("injected unconvertible request payload")


def malformed_request(sample_shape, kind="rank"):
    """A request payload that must be REJECTED per-request by the
    batcher — and must never kill the batch it rode in, the worker
    thread, or the queue (the graceful-degradation contract,
    ``tests/test_serve.py``).

    ``kind``: ``"rank"`` — an extra dimension (wrong shape);
    ``"shape"`` — right rank, wrong extents; ``"dtype"`` — object/str
    payload that cannot cast to the engine's sample dtype;
    ``"unconvertible"`` — ``np.asarray`` itself raises.
    """
    shape = tuple(int(s) for s in sample_shape)
    if kind == "rank":
        return np.zeros((2,) + shape, np.float32)
    if kind == "shape":
        return np.zeros(tuple(s + 1 for s in shape) or (3,), np.float32)
    if kind == "dtype":
        return np.full(shape, "poison", dtype=object)
    if kind == "unconvertible":
        return _BadPayload()
    raise ValueError("kind must be 'rank', 'shape', 'dtype' or "
                     "'unconvertible', got %r" % (kind,))


@contextmanager
def slow_client(delay_s, at=0, count=None):
    """Stall request ADMISSION by ``delay_s`` seconds from the ``at``-th
    submit onward (``count`` bounds how many; ``None`` = all) — the
    trickling-client case: requests arrive slower than a bucket fills,
    so the batcher's deadline-triggered flush (not the size trigger)
    must bound every admitted request's wait.  Interposes
    ``serve/batcher.py::_admit``, the admission choke point, exactly
    like ``flaky_reads`` interposes ``io/resilient.py::_pull``."""
    from ..serve import batcher as _batcher

    class _Stats:
        seen = 0
        slowed = 0

    stats = _Stats()
    real = _batcher._admit

    def slow(req):
        i = stats.seen
        stats.seen += 1
        if i >= at and (count is None or stats.slowed < count):
            stats.slowed += 1
            time.sleep(delay_s)
        return real(req)

    _batcher._admit = slow
    try:
        yield stats
    finally:
        _batcher._admit = real


@contextmanager
def _patched_serve(flaky):
    """Interpose ``serve/batcher.py::_serve_batch`` (the engine-
    execution choke point every flushed batch goes through) with
    ``flaky(real_serve, engine, xv)``."""
    from ..serve import batcher as _batcher

    real = _batcher._serve_batch
    _batcher._serve_batch = lambda engine, xv: flaky(real, engine, xv)
    try:
        yield
    finally:
        _batcher._serve_batch = real


@contextmanager
def kill_batcher_worker(at=0, count=1):
    """Silently kill the continuous batcher's worker thread on selected
    batch executions: raises ``SystemExit`` (a ``BaseException`` — it
    escapes the worker loop's ``except Exception`` and the thread
    machinery swallows it, exactly how a C-extension abort or an
    injected thread death presents).  The batch's futures are in
    nobody's queue anymore: the watchdog must fail them loudly AND
    respawn the worker within its bounded budget — no request may hang
    and later traffic must serve again.  Yields a stats object whose
    ``.killed`` counts injections."""
    class _Stats:
        seen = 0
        killed = 0

    stats = _Stats()

    def kill(real, engine, xv):
        i = stats.seen
        stats.seen += 1
        if at <= i < at + count:
            stats.killed += 1
            raise SystemExit("injected batcher worker death (#%d)" % i)
        return real(engine, xv)

    with _patched_serve(kill):
        yield stats


@contextmanager
def engine_failure_burst(n=3, exc=None, engine=None):
    """Make the next ``n`` engine executions fail with a transient
    ``RuntimeError`` (or ``exc``) — a device runtime hiccup burst.  A
    short burst is absorbed by per-batch retry-with-backoff; a long one
    must trip the circuit breaker into the degradation ladder (int8
    fallback tier, then priority-aware shedding) instead of failing
    every request slowly.  ``engine`` restricts the fault to ONE
    engine's executions (so a fallback tier stays healthy while the
    primary burns); ``None`` faults every engine.  Yields a stats
    object whose ``.failed`` counts injections."""
    class _Stats:
        seen = 0
        failed = 0

    stats = _Stats()

    def burst(real, eng, xv):
        i = stats.seen
        stats.seen += 1
        if stats.failed < n and (engine is None or eng is engine):
            stats.failed += 1
            raise exc or RuntimeError(
                "injected engine failure burst (#%d)" % i)
        return real(eng, xv)

    with _patched_serve(burst):
        yield stats


def nan_params(engine, value=float("nan"), index=0):
    """A poisoned hot-weight-swap candidate: the ENGINE's currently
    pinned param signature, copied host-side, with ``value`` (NaN by
    default) planted at flat position ``index`` of the first floating
    parameter — what a torn weight export or a diverged training run
    hands the swap path.  ``update_params`` must reject it on the
    canary batch (non-finite output) and roll back automatically; the
    old version keeps serving.  Returns the candidate as a list in the
    engine's parameter order."""
    if not getattr(engine, "_params", None):
        raise ValueError("engine has no collected params — warmup() it "
                         "first (the swap path requires it anyway)")
    raw = [np.array(p._data._data) for p in engine._params]
    for a in raw:
        if np.issubdtype(a.dtype, np.floating):
            a.reshape(-1)[index] = value
            break
    else:
        raise ValueError("engine has no floating parameter to poison")
    return raw


def deadline_storm(batcher, payloads, deadline=1e-4, priority=0):
    """Submit every payload back-to-back with an SLO deadline so tight
    it expires while the request sits in the queue — the storm of
    already-dead work an overloaded service must shed BEFORE compute
    (``DeadlineExceeded``), never serve dead and never hang.  Returns
    ``(futures, shed_count)`` like :func:`burst_arrivals`; every future
    is guaranteed (by the batcher's reaper) to resolve within
    deadline + grace + one watchdog tick."""
    from ..serve.batcher import Backpressure

    futures, shed = [], 0
    for p in payloads:
        try:
            futures.append(batcher.submit(p, block=False,
                                          deadline=deadline,
                                          priority=priority))
        except Backpressure:
            shed += 1
    return futures, shed


def burst_arrivals(batcher, payloads, block=False):
    """Submit every payload back-to-back with NO pacing — the thundering
    herd a bounded queue must absorb (or shed as ``Backpressure``, never
    grow without bound).  Returns ``(futures, shed_count)``; with
    ``block=False`` (default) a full queue sheds instead of waiting,
    which is what an open-loop burst looks like."""
    from ..serve.batcher import Backpressure

    futures, shed = [], 0
    for p in payloads:
        try:
            futures.append(batcher.submit(p, block=block))
        except Backpressure:
            shed += 1
    return futures, shed


def _live_param_snapshot(engine):
    """``(version, [host leaves])`` of the engine's live param version —
    the bitwise-restore oracle for rejected swaps."""
    import jax

    ver, vals = engine._live
    return ver, [np.asarray(jax.device_get(l))
                 for l in jax.tree_util.tree_leaves(vals)]


def _leaves_equal(a: np.ndarray, b: np.ndarray) -> bool:
    if a.shape != b.shape or a.dtype != b.dtype:
        return False
    if np.issubdtype(a.dtype, np.floating):
        return bool(np.array_equal(a, b, equal_nan=True))
    return bool(np.array_equal(a, b))


@contextmanager
def swap_storm(engine, n_swaps=5, interval=0.02, perturb=0.02,
               canary_tol=0.5, poison_at=None, seed=0):
    """``n_swaps`` back-to-back canaried hot weight swaps from a
    background thread — the promotion storm a flywheel daemon chasing a
    fast trainer produces — while the caller keeps serving live traffic
    inside the ``with`` block (typically a ``poisson_loadtest``).

    Each candidate is the LIVE incumbent's params (snapshotted at storm
    start — not the net's pinned init, which a promotion-churned engine
    may have long since replaced) perturbed by a small deterministic
    relative factor (``perturb``), so it passes the canary drift gate
    (``canary_tol``) and commits a real new version;
    ``poison_at=k`` replaces the ``k``-th candidate with
    :func:`nan_params` — the storm's rollback leg: the canary must
    reject it (``SwapRejected``) and the incumbent must keep serving
    BITWISE unchanged, which the yielded stats record as
    ``poison_rejected`` / ``incumbent_bitwise_ok``.

    The acceptance contract the chaos legs assert
    (``tools/serve_bench.py --chaos``, ``tests/test_flywheel.py``):
    p99 under the storm stays within the declared bound of the
    storm-free baseline, ``engine.recompile_count`` does not move (a
    swap is zero-recompile by GL011 construction), no future hangs, and
    every request is attributed to exactly one version.

    Yields a stats object: ``attempted``, ``committed``, ``rejected``,
    ``versions`` (list of committed version numbers), and for the
    poison leg ``poison_rejected`` / ``incumbent_bitwise_ok`` (``None``
    when ``poison_at`` is ``None``); a storm-thread crash surfaces in
    ``error`` instead of dying silently.  The thread is joined on
    exit."""
    import threading

    from ..serve.resilience import SwapRejected

    if not getattr(engine, "_params", None):
        raise ValueError("warmup() the engine first — the storm replays "
                         "the canaried swap path")
    # perturb what is actually being SERVED: the live tuple, cast back
    # to the engine's declared param dtypes so GL011 sees a clean match
    _ver0, _live0 = _live_param_snapshot(engine)
    sig = getattr(engine, "_param_sig", None) or []
    base = [np.asarray(a, np.dtype(sig[i][2]) if i < len(sig) else a.dtype)
            for i, a in enumerate(_live0)]
    rng = np.random.RandomState(seed)

    class _Stats:
        attempted = 0
        committed = 0
        rejected = 0
        versions: list = []
        poison_rejected = None
        incumbent_bitwise_ok = None
        error = None

    stats = _Stats()
    stats.versions = []

    def one_candidate():
        out = []
        for a in base:
            if np.issubdtype(a.dtype, np.floating):
                out.append(np.asarray(
                    a * (1.0 + perturb * rng.uniform(-1.0, 1.0)),
                    a.dtype))
            else:
                out.append(np.array(a))
        return out

    def storm():
        try:
            for i in range(n_swaps):
                stats.attempted += 1
                if poison_at is not None and i == poison_at:
                    before = _live_param_snapshot(engine)
                    try:
                        engine.update_params(nan_params(engine),
                                             canary_tol=canary_tol,
                                             context="swap_storm")
                        stats.poison_rejected = False
                    except SwapRejected:
                        stats.poison_rejected = True
                    after = _live_param_snapshot(engine)
                    stats.incumbent_bitwise_ok = (
                        before[0] == after[0]
                        and len(before[1]) == len(after[1])
                        and all(_leaves_equal(x, y)
                                for x, y in zip(before[1], after[1])))
                else:
                    try:
                        v = engine.update_params(one_candidate(),
                                                 canary_tol=canary_tol,
                                                 context="swap_storm")
                        stats.committed += 1
                        stats.versions.append(int(v))
                    except SwapRejected:
                        stats.rejected += 1
                time.sleep(interval)
        except BaseException as e:  # surface, never die silently
            stats.error = "%s: %s" % (type(e).__name__, e)

    t = threading.Thread(target=storm, name="swap-storm", daemon=True)
    t.start()
    try:
        yield stats
    finally:
        t.join(timeout=120.0)
        if t.is_alive():
            stats.error = stats.error or \
                "swap storm thread failed to finish"


# ---------------------------------------------------------------------------
# supervised-training chaos (parallel/supervisor.py)
# ---------------------------------------------------------------------------

@contextmanager
def _patched_run_step(flaky):
    """Interpose ``parallel/supervisor.py::_run_step`` (the choke point
    every supervised step call goes through) with
    ``flaky(real_run, step, x, y)``."""
    from . import supervisor as _sup

    real = _sup._run_step
    _sup._run_step = lambda step, x, y: flaky(real, step, x, y)
    try:
        yield
    finally:
        _sup._run_step = real


@contextmanager
def hang_step(at=0, duration=3600.0, count=1):
    """Wedge the supervised step callable: the ``at``-th through
    ``at+count-1``-th calls (0-based) sleep ``duration`` seconds BEFORE
    the step runs — the rank stops heartbeating mid-step, exactly what
    a wedged collective or a stuck device transfer looks like from the
    outside.  A long single wedge is the HANG case (the watchdog must
    detect the heartbeat gap, kill the job and respawn it); a small
    ``duration`` with a large ``count`` is the per-step slowdown the
    STRAGGLER detector exists for.  Yields a stats object whose
    ``.hung`` counts injections."""
    class _Stats:
        seen = 0
        hung = 0

    stats = _Stats()

    def wedge(real, step, x, y):
        i = stats.seen
        stats.seen += 1
        if at <= i < at + count:
            stats.hung += 1
            time.sleep(duration)
        return real(step, x, y)

    with _patched_run_step(wedge):
        yield stats


@contextmanager
def loss_bomb(at=0, factor=1e4):
    """Finite exploding gradients at supervised step call ``at``
    (0-based): the step's live float params are scaled in place by
    ``factor`` through the same choke point, so the NEXT loss explodes
    by orders of magnitude while every gradient stays FINITE —
    ``nonfinite="skip"`` never fires, the skip counter never moves,
    and the run burns compute on garbage forever.  This is the
    divergence detector's regression case: the loss-EMA explosion
    verdict must fire and the in-process rollback to the last
    committed checkpoint must restore health (the bomb is one-shot, so
    the replayed steps run clean).  Yields a stats object whose
    ``.fired``/``.params_scaled`` record the injection."""
    from . import supervisor as _sup

    class _Stats:
        seen = 0
        fired = 0
        params_scaled = 0

    stats = _Stats()

    def bomb(real, step, x, y):
        i = stats.seen
        stats.seen += 1
        if i == at:
            stats.fired += 1
            stats.params_scaled = _sup._scale_params(step, factor)
        return real(step, x, y)

    with _patched_run_step(bomb):
        yield stats


# ---------------------------------------------------------------------------
# async push/pull chaos (parallel/param_service.py)
# ---------------------------------------------------------------------------

@contextmanager
def _patched_transport(push=None, pull=None):
    """Interpose the parameter-service transport choke points
    (``parallel/param_service.py::_deliver_push``/``_deliver_pull`` —
    every client push/pull goes through them) with
    ``push(real_push, service, rank, updates)`` and/or
    ``pull(real_pull, service, rank, timeout)``."""
    from . import param_service as _ps

    real_push, real_pull = _ps._deliver_push, _ps._deliver_pull
    if push is not None:
        _ps._deliver_push = \
            lambda svc, rank, updates: push(real_push, svc, rank, updates)
    if pull is not None:
        _ps._deliver_pull = \
            lambda svc, rank, timeout: pull(real_pull, svc, rank, timeout)
    try:
        yield
    finally:
        _ps._deliver_push, _ps._deliver_pull = real_push, real_pull


@contextmanager
def slow_link(rank, delay_s):
    """Add ``delay_s`` seconds to every push AND pull of ``rank``
    (``None`` = every rank) — the slow-NIC/congested-link straggler as
    seen from the parameter service: the slowed rank's clock falls
    behind while healthy peers keep pushing, so the bounded-staleness
    invariant (peers block only past ``staleness_bound``) is exercised
    for real rather than simulated.  Yields a stats object whose
    ``.delayed`` counts injected latencies."""
    class _Stats:
        pushes = 0
        pulls = 0
        delayed = 0

    stats = _Stats()

    def spush(real, svc, r, updates):
        stats.pushes += 1
        if rank is None or r == rank:
            stats.delayed += 1
            time.sleep(delay_s)
        return real(svc, r, updates)

    def spull(real, svc, r, timeout):
        stats.pulls += 1
        if rank is None or r == rank:
            stats.delayed += 1
            time.sleep(delay_s)
        return real(svc, r, timeout)

    with _patched_transport(push=spush, pull=spull):
        yield stats


@contextmanager
def drop_push(p, seed=0):
    """Deterministically lose fraction ``p`` of push PAYLOADS on the
    wire: the dropped push still commits its step (fire-and-forget —
    the clock advances, so no peer deadlocks on a lossy link) but the
    gradient update never reaches the server.  Training must degrade
    gracefully — with error-feedback compression the next surviving
    push re-carries what the residual banked, NOT silently diverge.
    Yields a stats object whose ``.dropped``/``.seen`` count pushes."""
    if not 0.0 <= float(p) <= 1.0:
        raise ValueError("drop probability must be in [0, 1], got %r"
                         % (p,))
    rng = np.random.default_rng(int(seed))

    class _Stats:
        seen = 0
        dropped = 0

    stats = _Stats()

    def drop(real, svc, r, updates):
        stats.seen += 1
        if rng.random() < float(p):
            stats.dropped += 1
            return real(svc, r, {})  # payload lost, step still commits
        return real(svc, r, updates)

    with _patched_transport(push=drop):
        yield stats


# ---------------------------------------------------------------------------
# host-loss scenarios (multi-process / elastic training)
# ---------------------------------------------------------------------------

def kill_process():
    """Ungraceful death of THIS process — SIGKILL to self, the closest
    userspace analog of a preempted VM or a kernel panic: no atexit
    hooks, no buffer flushes, no signal handlers, collectives on peers
    hang until their own timeouts.  Only for spawned subprocess tests
    (``tests/elastic_worker.py``); it does not return."""
    os.kill(os.getpid(), _signal.SIGKILL)
    time.sleep(60)  # pragma: no cover — the signal wins


@contextmanager
def host_loss_during_save(at=1):
    """Arm :func:`kill_process` on the ``at``-th (0-based) checkpoint
    file write inside this context: the process dies exactly mid-stage,
    leaving torn shard files / a torn done-marker in the shared staging
    directory — the half-written multi-host checkpoint the commit
    protocol must never publish.  Yields a stats object counting writes
    seen before the kill."""
    from . import checkpoint as _ckpt

    real = _ckpt._write_bytes

    class _Stats:
        seen = 0

    stats = _Stats()

    def lethal(path, data):
        i = stats.seen
        stats.seen += 1
        if i == at:
            # tear the file first: a real host loss interrupts write(2)
            # mid-buffer, so successors must cope with partial bytes
            with open(path, "wb") as f:
                f.write(data[:max(len(data) // 2, 1)])
            kill_process()
        return real(path, data)

    _ckpt._write_bytes = lethal
    try:
        yield stats
    finally:
        _ckpt._write_bytes = real


@contextmanager
def coordinator_unreachable(message="connection refused (injected)"):
    """Make the ``jax.distributed`` rendezvous fail as if the
    coordinator host is gone: ``parallel/distributed.py``'s backend
    call raises immediately instead of blocking out a real gRPC
    deadline.  The bootstrap must surface a clear
    ``DistributedInitError`` naming coordinator and rank."""
    from . import distributed as _dist

    real = _dist._raw_initialize

    def refuse(coordinator, num_processes, rank, timeout):
        raise ConnectionError("%s [coordinator %s]" % (message, coordinator))

    _dist._raw_initialize = refuse
    try:
        yield
    finally:
        _dist._raw_initialize = real


@contextmanager
def straggler_process(delay_s):
    """Delay THIS process's done-marker by ``delay_s`` seconds during a
    multi-process checkpoint save — the straggling-host case the commit
    coordinator's bounded ``commit_timeout`` wait must either absorb
    (slow peer) or abort on (lost peer) without ever publishing a
    partial checkpoint."""
    from . import checkpoint as _ckpt

    real = _ckpt._write_bytes

    class _Stats:
        delayed = 0

    stats = _Stats()

    def slow(path, data):
        if os.path.basename(path).startswith("done-"):
            stats.delayed += 1
            time.sleep(delay_s)
        return real(path, data)

    _ckpt._write_bytes = slow
    try:
        yield stats
    finally:
        _ckpt._write_bytes = real
