"""Bounded-staleness async parameter service (ps-lite's asynchronous
push/pull kvstore — ``kvstore_dist_server.h`` — rebuilt jax-native on
the PR-7 process protocol; SURVEY §2.9, ROADMAP item 5).

Three pieces, composable and individually testable:

- :class:`ParamService` — the server: authoritative parameter buffers,
  a server-side optimizer (:class:`ServiceUpdater` wrapping the fused
  step's :class:`~.train_step.FunctionalOptimizer`), and the
  **bounded-staleness clock** (:class:`StalenessClock`).  Each rank may
  run up to ``staleness_bound`` steps ahead of the slowest live peer
  before its pull blocks; ``staleness_bound=0`` is BSP (every pull
  waits for all peers — synchronous semantics over the async wire).
  Keys are dp-sharded across ``num_shards`` server shards by stable
  hash (ps-lite's server partitioning; per-shard push volume is
  accounted for graftcost).  Ranks join/leave with
  :meth:`ParamService.register` / :meth:`~ParamService.deregister` —
  a departed straggler stops holding the staleness bound hostage, the
  elastic analog of the checkpoint protocol's width changes.

- :class:`ServiceClient` — the rank-side half: compresses pushes
  through the error-feedback compressors
  (``kvstore/gradient_compression.py`` — top-k / random-k / int8 /
  2-bit), decompression happens server-side from the self-describing
  payload.  ``state_dict()`` / ``load_state_dict()`` checkpoint the
  compressor residuals, the per-key sparse step counters and (when the
  client owns its service) the full server state + staleness clock, so
  kill-and-resume is bit-identical on the unfaulted path.

- :class:`SyncPolicy` — the sync→async policy ladder: under
  ``mode="auto"`` the supervisor's straggler verdicts
  (``supervisor.straggler_verdicts``) degrade the step from allreduce
  to async push/pull after ``degrade_after`` consecutive straggler
  observations, and recover back after ``recover_after`` clean ones.
  Pure state machine — the fast tier-1 representative of the chaos
  matrix's async-degradation leg.

All transport flows through the module-level :func:`_deliver_push` /
:func:`_deliver_pull` choke points so the fault harness can interpose
link slowdowns and push loss (``fault_injection.slow_link`` /
``drop_push``) without touching the service.

Thread-based by design: CPU jaxlib cannot compile cross-process
programs (``distributed.collectives_supported``), so the in-process
service is the tier-1 story; multi-process ranks reach the same
object through the legacy wire host (``kvstore/async_host.py``) or a
future RPC transport — the protocol (push payloads, clock semantics,
checkpoint state) is transport-agnostic.
"""
from __future__ import annotations

import threading
import zlib
from typing import Any, Dict, List, Optional

import jax.numpy as jnp
import numpy as np

__all__ = ["ParamService", "ServiceClient", "ServiceUpdater",
           "StalenessClock", "SyncPolicy", "StalenessTimeout"]


class StalenessTimeout(RuntimeError):
    """A bounded-staleness pull waited past its deadline — the slowest
    live peer never caught up (a hung rank that nothing deregistered)."""


class StalenessClock:
    """Per-rank committed-push counts over the set of LIVE ranks.

    ``staleness(rank) = count[rank] - min(live counts)`` — how far this
    rank has run ahead of the slowest live peer.  The service blocks a
    pull while ``staleness(rank) > bound``.  Not thread-safe by itself;
    the service serializes access under its condition lock."""

    def __init__(self):
        self._count: Dict[int, int] = {}
        self._live: Dict[int, bool] = {}

    def register(self, rank: int, at_step: Optional[int] = None) -> None:
        """Join (or re-join) at ``at_step`` — defaults to the current
        minimum so a fresh rank neither blocks on day-one staleness nor
        releases peers early."""
        if rank not in self._count or at_step is not None:
            self._count[rank] = int(at_step) if at_step is not None \
                else self.min_step()
        self._live[rank] = True

    def deregister(self, rank: int) -> None:
        self._live[rank] = False

    def advance(self, rank: int) -> int:
        self._count[rank] = self._count.get(rank, 0) + 1
        return self._count[rank]

    def step(self, rank: int) -> int:
        return self._count.get(rank, 0)

    def live_ranks(self) -> List[int]:
        return sorted(r for r, ok in self._live.items() if ok)

    def min_step(self) -> int:
        live = [self._count[r] for r, ok in self._live.items() if ok]
        return min(live) if live else 0

    def staleness(self, rank: int) -> int:
        return self.step(rank) - self.min_step()

    # -- checkpoint protocol -------------------------------------------
    def state_dict(self) -> Dict[str, Dict[str, np.ndarray]]:
        return {"count": {str(r): np.int64(c)
                          for r, c in sorted(self._count.items())},
                "live": {str(r): np.int64(1 if ok else 0)
                         for r, ok in sorted(self._live.items())}}

    def load_state_dict(self, state: Dict) -> None:
        self._count = {int(r): int(c)
                       for r, c in dict(state["count"]).items()}
        self._live = {int(r): bool(int(v))
                      for r, v in dict(state["live"]).items()}


class ServiceUpdater:
    """Server-side optimizer: one
    :class:`~.train_step.FunctionalOptimizer` state per key, applied
    per push (ps-lite's async ``ApplyUpdates`` semantics — every push
    is its own update; there is no cross-rank gradient barrier)."""

    def __init__(self, optimizer=None):
        if optimizer is None:
            from .train_step import FunctionalOptimizer

            optimizer = FunctionalOptimizer("sgd", learning_rate=0.01,
                                            momentum=0.0)
        self.opt = optimizer
        self._state: Dict[str, Any] = {}
        self._count: Dict[str, int] = {}

    def init_key(self, key: str, value) -> None:
        if key in self._count:
            return
        self._count[key] = 0
        if self.opt.has_state:
            self._state[key] = self.opt.init([jnp.asarray(value)])[0]

    def apply(self, key: str, weight, grad):
        """One applied update: ``(weight, grad) -> new_weight`` with the
        per-key state and 1-based count (adam bias correction)."""
        self._count[key] = self._count.get(key, 0) + 1
        s = self._state.get(key) if self.opt.has_state else None
        w2, s2 = self.opt.apply_single(jnp.asarray(weight),
                                       jnp.asarray(grad), s,
                                       self._count[key])
        if self.opt.has_state:
            self._state[key] = s2
        return w2

    def state_dict(self) -> Dict:
        return {"count": {k: np.int64(v)
                          for k, v in sorted(self._count.items())},
                "state": {k: self._state[k]
                          for k in sorted(self._state)}}

    def load_state_dict(self, state: Dict) -> None:
        self._count = {str(k): int(v)
                       for k, v in dict(state["count"]).items()}
        self._state = {str(k): v for k, v in dict(state["state"]).items()}


def _payload_nbytes(payload) -> int:
    """Wire bytes of one push payload (compressed dict or dense array)."""
    if isinstance(payload, dict):
        n = 0
        for k, v in payload.items():
            if hasattr(v, "nbytes"):
                n += int(v.nbytes)
            elif hasattr(v, "dtype"):  # 0-d jax scalar
                n += int(np.dtype(v.dtype).itemsize)
        return n
    return int(np.asarray(payload).nbytes)


def _dense_nbytes(payload, fallback) -> int:
    if isinstance(payload, dict):
        shape, dtype = payload["shape"], payload["dtype"]
        return int(np.prod(shape, dtype=np.int64)
                   * np.dtype(dtype).itemsize)
    return int(np.asarray(fallback if fallback is not None
                          else payload).nbytes)


# ---------------------------------------------------------------------------
# transport choke points — the fault harness interposes HERE
# (fault_injection.slow_link / drop_push), like supervisor._run_step
# ---------------------------------------------------------------------------

def _deliver_push(service: "ParamService", rank: int, updates: Dict):
    """The one path every push takes from a client into the service."""
    return service._apply_push(rank, updates)


def _deliver_pull(service: "ParamService", rank: int,
                  timeout: Optional[float]):
    """The one path every pull takes — blocking happens inside."""
    return service._collect_pull(rank, timeout)


class ParamService:
    """In-process bounded-staleness parameter server (thread-safe)."""

    def __init__(self, updater: Optional[ServiceUpdater] = None,
                 staleness_bound: int = 4, num_shards: int = 1):
        if int(staleness_bound) < 0:
            raise ValueError("staleness_bound must be >= 0, got %r"
                             % (staleness_bound,))
        if int(num_shards) < 1:
            raise ValueError("num_shards must be >= 1, got %r"
                             % (num_shards,))
        self.staleness_bound = int(staleness_bound)
        self.num_shards = int(num_shards)
        self.updater = updater or ServiceUpdater()
        self.clock = StalenessClock()
        self._params: Dict[str, Any] = {}
        self._versions: Dict[str, int] = {}
        self._cv = threading.Condition()
        # -- observability / accounting ---------------------------------
        self.max_observed_staleness = 0   # over every pull ever served
        self.push_nbytes = 0              # wire bytes actually pushed
        self.push_dense_nbytes = 0        # what uncompressed would cost
        self.shard_push_nbytes = [0] * self.num_shards
        self.pulls_blocked = 0            # pulls that had to wait

    # -- membership -----------------------------------------------------
    def register(self, rank: int, at_step: Optional[int] = None) -> None:
        with self._cv:
            self.clock.register(rank, at_step)
            self._cv.notify_all()

    def deregister(self, rank: int) -> None:
        """A departed rank stops counting toward the staleness minimum —
        waiters re-evaluate immediately (elastic leave; a SIGKILLed
        straggler is deregistered by its supervisor)."""
        with self._cv:
            self.clock.deregister(rank)
            self._cv.notify_all()

    # -- key space ------------------------------------------------------
    def shard_of(self, key: str) -> int:
        return zlib.crc32(str(key).encode()) % self.num_shards

    def init(self, key: str, value) -> None:
        """Rank-0-wins init semantics (kvstore ``init``): the first
        value for a key sticks, later inits are no-ops.  The service
        stores its OWN copy — the caller's buffer may later be donated
        by a fused step program."""
        with self._cv:
            if key not in self._params:
                self._params[key] = jnp.array(value)  # copy, not alias
                self._versions[key] = 0
                self.updater.init_key(key, value)

    def sync_params(self, named_values: Dict) -> None:
        """Force-overwrite the authoritative params (no rank-0-wins):
        the policy ladder calls this on a sync→async degrade so the
        service resumes from the collective rung's CURRENT state, not
        its seed-time snapshot.  Values are copied."""
        with self._cv:
            for key, v in named_values.items():
                if key not in self._params:
                    raise KeyError("sync_params to uninitialized key %r"
                                   % (key,))
                self._params[key] = jnp.array(v)  # copy, not alias
                self._versions[key] += 1
            self._cv.notify_all()

    def keys(self) -> List[str]:
        with self._cv:
            return sorted(self._params)

    # -- push/pull (reached through the module choke points) ------------
    def push(self, rank: int, updates: Dict, commit: bool = True):
        """Apply one step's (possibly compressed) gradient payloads and
        advance the pusher's clock.  ``updates`` maps key -> payload
        (a dense array, or a compressor payload dict)."""
        return _deliver_push(self, rank, updates) if commit \
            else self._apply_push(rank, updates, commit=False)

    def pull(self, rank: int, timeout: Optional[float] = None) -> Dict:
        """All parameters, BLOCKING while this rank's effective
        staleness exceeds ``staleness_bound``.  Raises
        :class:`StalenessTimeout` past ``timeout`` seconds (None waits
        forever).  Returns ``{key: value}``; the bounded-staleness
        invariant is observable as :attr:`max_observed_staleness`."""
        return _deliver_pull(self, rank, timeout)

    def _apply_push(self, rank: int, updates: Dict, commit: bool = True):
        from ..kvstore.gradient_compression import decompress_payload

        dense = {k: decompress_payload(v) for k, v in updates.items()}
        with self._cv:
            for key, g in dense.items():
                if key not in self._params:
                    raise KeyError("push to uninitialized key %r" % (key,))
                self._params[key] = self.updater.apply(
                    key, self._params[key], g)
                self._versions[key] += 1
                nb = _payload_nbytes(updates[key])
                self.push_nbytes += nb
                self.push_dense_nbytes += _dense_nbytes(updates[key], g)
                self.shard_push_nbytes[self.shard_of(key)] += nb
            if commit:
                self.clock.advance(rank)
                self._cv.notify_all()

    def _collect_pull(self, rank: int, timeout: Optional[float]):
        import time as _time

        end = None if timeout is None else _time.monotonic() + timeout
        with self._cv:
            waited = False
            while self.clock.staleness(rank) > self.staleness_bound:
                if not waited:
                    self.pulls_blocked += 1
                    waited = True
                remaining = None if end is None else end - _time.monotonic()
                if (remaining is not None and remaining <= 0) or \
                        not self._cv.wait(timeout=remaining):
                    raise StalenessTimeout(
                        "rank %d pull blocked > %.1fs at staleness %d "
                        "(bound %d; live ranks %s, clock %s) — a hung "
                        "peer nothing deregistered"
                        % (rank, timeout, self.clock.staleness(rank),
                           self.staleness_bound, self.clock.live_ranks(),
                           {r: self.clock.step(r)
                            for r in self.clock.live_ranks()}))
            # the staleness every pull OBSERVES is bounded by
            # construction: record it so tests can assert the invariant
            obs = self.clock.staleness(rank)
            if obs > self.max_observed_staleness:
                self.max_observed_staleness = obs
            return dict(self._params)

    # -- checkpoint protocol (CheckpointManager-compatible pytree) ------
    def state_dict(self) -> Dict:
        with self._cv:
            return {"params": {k: self._params[k]
                               for k in sorted(self._params)},
                    "versions": {k: np.int64(self._versions[k])
                                 for k in sorted(self._versions)},
                    "clock": self.clock.state_dict(),
                    "updater": self.updater.state_dict()}

    def load_state_dict(self, state: Dict) -> None:
        with self._cv:
            self._params = {str(k): jnp.asarray(v)
                            for k, v in dict(state["params"]).items()}
            self._versions = {str(k): int(v)
                              for k, v in dict(state["versions"]).items()}
            self.clock.load_state_dict(state["clock"])
            self.updater.load_state_dict(state["updater"])
            self._cv.notify_all()


class ServiceClient:
    """Rank-side push/pull glue: compression + error feedback on the
    push path, checkpointable alongside the owning train step."""

    def __init__(self, service: ParamService, rank: int = 0,
                 compressor=None, owns_service: bool = False):
        self.service = service
        self.rank = int(rank)
        self.compressor = compressor
        self._owns_service = bool(owns_service)
        service.register(self.rank)

    def init_params(self, named_values: Dict) -> None:
        """Seed the server (rank-0-wins) and pre-create every residual
        slot so the checkpoint treedef is stable from attach time —
        a resume before the first push must see the same state tree a
        mid-run save produced."""
        for k, v in named_values.items():
            self.service.init(k, v)
            if self.compressor is not None:
                res = self.compressor._residual
                if k not in res:
                    res[k] = jnp.zeros(jnp.asarray(v).shape,
                                       jnp.asarray(v).dtype)
                if hasattr(self.compressor, "_step_of"):
                    self.compressor._step_of.setdefault(k, 0)

    def sync_params(self, named_values: Dict) -> None:
        """Force the server's authoritative params to these values
        (degrade-time handoff from the collective rung)."""
        self.service.sync_params(named_values)

    def push_step(self, grads: Dict) -> None:
        """One step's gradients → (compressed) payloads → the service.
        Advances this rank's staleness clock once per call."""
        if self.compressor is not None:
            payloads = {k: self.compressor.compress(k, jnp.asarray(g))
                        for k, g in grads.items()}
        else:
            payloads = {k: jnp.asarray(g) for k, g in grads.items()}
        self.service.push(self.rank, payloads)

    def pull_params(self, timeout: Optional[float] = None) -> Dict:
        return self.service.pull(self.rank, timeout=timeout)

    def leave(self) -> None:
        self.service.deregister(self.rank)

    # -- checkpoint protocol -------------------------------------------
    def state_dict(self) -> Dict:
        comp = {}
        if self.compressor is not None:
            comp = self.compressor.state_dict()
        out = {"compressor": comp,
               "rank_step": np.int64(self.service.clock.step(self.rank))}
        if self._owns_service:
            out["service"] = self.service.state_dict()
        return out

    def load_state_dict(self, state: Dict) -> None:
        state = dict(state)
        if self.compressor is not None and state.get("compressor"):
            self.compressor.load_state_dict(state["compressor"])
        if self._owns_service and "service" in state:
            self.service.load_state_dict(state["service"])
        else:
            # re-register at the saved position: the clock survives the
            # kill even when the service outlived this rank
            self.service.register(self.rank,
                                  at_step=int(state["rank_step"]))


class SyncPolicy:
    """The sync→async policy ladder (pure state machine).

    ``mode="allreduce"`` / ``"async"`` pin the rung; ``"auto"`` starts
    at allreduce and moves on straggler evidence: ``degrade_after``
    consecutive observations with a non-empty straggler set switch to
    async push/pull, ``recover_after`` consecutive clean observations
    switch back.  Hysteresis on both edges — one noisy heartbeat frame
    must not flap the step between collectives and the service."""

    def __init__(self, mode: str = "auto", degrade_after: int = 2,
                 recover_after: int = 8):
        if mode not in ("auto", "allreduce", "async"):
            raise ValueError("sync mode must be 'auto', 'allreduce' or "
                             "'async', got %r" % (mode,))
        if int(degrade_after) < 1 or int(recover_after) < 1:
            raise ValueError("degrade_after/recover_after must be >= 1")
        self.mode = mode
        self.degrade_after = int(degrade_after)
        self.recover_after = int(recover_after)
        self.effective = "async" if mode == "async" else "allreduce"
        self._dirty = 0
        self._clean = 0
        #: (observation index, new effective mode) transition log
        self.transitions: List = []
        self._seen = 0

    def observe(self, straggler_ranks) -> str:
        """Feed one straggler-detector frame; returns the effective
        mode after it."""
        self._seen += 1
        if self.mode != "auto":
            return self.effective
        if straggler_ranks:
            self._dirty += 1
            self._clean = 0
        else:
            self._clean += 1
            self._dirty = 0
        if self.effective == "allreduce" and \
                self._dirty >= self.degrade_after:
            self.effective = "async"
            self.transitions.append((self._seen, "async"))
        elif self.effective == "async" and \
                self._clean >= self.recover_after:
            self.effective = "allreduce"
            self.transitions.append((self._seen, "allreduce"))
        return self.effective
