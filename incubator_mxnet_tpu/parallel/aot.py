"""Shared AOT-compile + lint plumbing for compiled-program builders.

Two independent builders assemble long-lived XLA programs from gluon
nets — the fused training step (``parallel/train_step.py``) and the
serving engine (``serve/engine.py``) — and both follow the same ritual:

1. trace the jitted callable ONCE with the GL004 effect hooks active
   (:func:`traced_with_effects` — the very trace jit caches for the
   first call, so the lint costs one jaxpr walk, not an extra trace);
2. assemble a :class:`~..analysis.LintReport` from the effect
   diagnostics + the jaxpr walk + any builder-specific checks and apply
   the ``"error"``/``"warn"``/``"off"`` policy (:func:`finish_lint`);
3. lower + compile with a timed phase split (:func:`compile_timed`) so
   benchmarks can report where startup time goes — the reference's
   analog is cuDNN autotune + InitCachedOps cost at bind
   (``src/executor/graph_executor.cc:1220``).

This module is the ONE copy of that ritual.  The builders keep their
own policy (what counts as an extra diagnostic, when to mark
themselves linted); the mechanics live here.

It also owns the **persistent on-disk compile cache**
(:class:`CompileCache`): every AOT build routed through
:func:`compile_timed` can consult a directory of serialized XLA
executables keyed by (lowered-program hash, mesh shape + axis names,
builder knobs, jax/jaxlib version, backend + device count) before
paying ``lowered.compile()`` — so a retune or a restart pays
trace-but-not-compile across *processes*, not just within one.  Writes
are atomic (temp + fsync + rename, the ``CheckpointManager``
discipline, through the same ``checkpoint._write_bytes`` choke point
``fault_injection.fail_writes`` interposes); corrupt or stale entries
degrade to a recompile with a warning, never a crash and never a wrong
executable; the directory is LRU-swept to a byte cap.  Resolution:
explicit ``cache=`` argument > ``MXTPU_COMPILE_CACHE`` env
(``config.py``) > off.  :data:`XLA_COMPILES` counts real
``lowered.compile()`` invocations — the "0 XLA compiles on a warm
cache" contract the autotuner's tests assert.
"""
from __future__ import annotations

import os
import time
from typing import Any, Dict, Iterable, Optional, Sequence, Tuple

__all__ = ["CompileCache", "XLA_COMPILES", "compile_timed",
           "default_compile_cache", "finish_lint", "lint_served_program",
           "resolve_mode", "traced_with_effects"]


class _CompileCounter:
    """Process-wide count of real XLA ``lowered.compile()`` calls made
    through :func:`compile_timed` (cache hits do NOT increment it).
    Incremented under a lock — batcher workers compile post-warmup
    bucket programs concurrently with main-thread builds, and a lost
    increment would let a real compile escape the warm-cache "0 XLA
    compiles" assertions (the same hazard serve/batcher.py's stats
    counters lock against)."""

    __slots__ = ("count", "_lock")

    def __init__(self):
        import threading

        self.count = 0
        self._lock = threading.Lock()

    def bump(self):
        with self._lock:
            self.count += 1


#: the one instance every builder shares
XLA_COMPILES = _CompileCounter()


def resolve_mode(value: Optional[str], env_var: str, default: str,
                 allowed: Sequence[str], what: str) -> str:
    """The shared knob-resolution order: explicit argument > env var
    (``config.py``) > ``default``.  Raises ``ValueError`` naming the
    knob on anything outside ``allowed``."""
    if value is None:
        from .. import config as _cfg

        value = str(_cfg.get(env_var, default) or default).lower()
    if value not in allowed:
        raise ValueError("%s must be one of %s, got %r"
                         % (what, "/".join(repr(a) for a in allowed),
                            value))
    return value


def traced_with_effects(jit_obj, args: tuple, capture: bool = True):
    """Trace ``jit_obj`` (via ``.trace(*args)`` — the trace the first
    call reuses) with the GL004 effect-capture hooks active.  Returns
    ``(traced, effect_diagnostics)``; ``capture=False`` skips the hook
    (an empty diagnostics list comes back)."""
    from contextlib import nullcontext

    from ..analysis.trace_lint import capture_effect_diagnostics

    cm = capture_effect_diagnostics() if capture else nullcontext([])
    with cm as effects:
        traced = jit_obj.trace(*args)
    return traced, list(effects)


def finish_lint(closed_jaxpr, *, mode: str, effects: Iterable = (),
                donated_leaves: Sequence[int] = (), extra: Iterable = (),
                suppress: Tuple[str, ...] = (),
                what: str = "compiled program", stacklevel: int = 5):
    """Assemble and enforce one lint report over a traced program.

    ``effects`` are GL004 diagnostics captured during the trace,
    ``donated_leaves`` flat invar indices for the GL003 walk, ``extra``
    builder-specific diagnostics (GL006/GL007 for the train step,
    GL010 for the serving engine).  ``mode="error"`` raises
    :class:`~..analysis.LintError` on error-severity findings; any
    findings at all are warned (so ``"warn"`` mode surfaces them and
    ``"error"`` mode surfaces the non-fatal ones).  Returns the report.
    """
    from ..analysis import LintReport, Severity, lint_jaxpr

    report = LintReport(suppress=suppress)
    report.extend(effects)
    report.extend(lint_jaxpr(closed_jaxpr,
                             donated_leaves=donated_leaves).diagnostics)
    report.extend(extra)
    if mode == "error":
        report.raise_if_errors()
    if report.errors or report.warnings:
        import warnings as _warnings

        _warnings.warn("graftlint: %s has findings\n%s"
                       % (what, report.format(Severity.WARNING)),
                       stacklevel=stacklevel)
    return report


def lint_served_program(traced, effects, args: tuple,
                        donate_argnums: Sequence[int], *, mode: str,
                        suppress: Tuple[str, ...] = (),
                        what: str = "inference program",
                        param_argnum: int = 0, stacklevel: int = 6):
    """The serving-side lint ritual shared by ``serve/engine.py`` and
    ``serve/cache.py``: GL001–GL004 over the traced program plus GL010
    (``check_inference_param_donation``) against the builder's own
    donation spec — the params argument (``param_argnum``) must never
    be donated.  ONE copy, like :func:`finish_lint` for the generic
    half."""
    import jax

    from ..analysis.trace_lint import (check_inference_param_donation,
                                       donated_leaf_indices)

    donated = donated_leaf_indices(args, donate_argnums)
    off = sum(len(jax.tree_util.tree_leaves(a))
              for a in args[:param_argnum])
    n_param = len(jax.tree_util.tree_leaves(args[param_argnum]))
    extra = check_inference_param_donation(
        donated, range(off, off + n_param), where=what)
    return finish_lint(traced.jaxpr, mode=mode, effects=effects,
                       donated_leaves=donated, extra=extra,
                       suppress=suppress, what=what,
                       stacklevel=stacklevel)


class CompileCache:
    """Persistent on-disk cache of compiled XLA executables.

    Entries are pickled ``jax.experimental.serialize_executable``
    payloads under ``<directory>/<key>.xc``; the key (sha256) covers
    the LOWERED program text (which embeds shapes, dtypes and GSPMD
    shardings), the caller's ``extra`` tuple (mesh shape + axis names,
    builder knobs), the jax + jaxlib versions, and the backend platform
    / device-count / device-kind — anything that could make a stored
    executable wrong for the process loading it.  A key-or-version
    mismatch inside a loaded entry, an unpicklable blob, or a torn file
    all take the same path: warn, drop the entry, recompile.

    Entries are pickles: point the cache only at directories you trust
    (the same standing as ``.jax_cache/`` and checkpoint dirs).
    """

    #: bump to orphan every existing entry on a format change
    VERSION = 1
    _SUFFIX = ".xc"

    def __init__(self, directory: str, max_bytes: int = 512 << 20):
        import threading

        self.directory = str(directory)
        self.max_bytes = int(max_bytes)
        self.hits = 0
        self.misses = 0
        self.dropped = 0       # corrupt/stale entries evicted on load
        self.store_failures = 0
        self._unsupported = False  # backend refused serialization
        # the env-default instance is shared across builder threads
        # (batcher workers compile buckets concurrently)
        self._lock = threading.Lock()

    def _count(self, attr: str):
        with self._lock:
            setattr(self, attr, getattr(self, attr) + 1)

    # -- key -----------------------------------------------------------
    def key_for(self, lowered, extra: Sequence[Any] = ()) -> str:
        """Cache key for one lowered program under the current backend."""
        import hashlib

        import jax
        import jaxlib

        h = hashlib.sha256()
        h.update(lowered.as_text().encode())
        devs = jax.devices()
        h.update(repr((self.VERSION, jax.__version__, jaxlib.__version__,
                       jax.default_backend(), len(devs),
                       getattr(devs[0], "device_kind", "?"),
                       tuple(extra))).encode())
        return h.hexdigest()

    def _path(self, key: str) -> str:
        return os.path.join(self.directory, key + self._SUFFIX)

    # -- load ----------------------------------------------------------
    def load(self, key: str):
        """The compiled executable for ``key``, or None (miss / corrupt
        entry — corrupt entries are warned about and deleted so the
        recompile's store can replace them)."""
        import pickle

        path = self._path(key)
        try:
            with open(path, "rb") as f:
                blob = f.read()
        except OSError:
            self._count("misses")
            return None
        try:
            from jax.experimental import serialize_executable as _se

            payload = pickle.loads(blob)
            if payload.get("key") != key \
                    or payload.get("version") != self.VERSION:
                raise ValueError("entry key/version mismatch")
            compiled = _se.deserialize_and_load(
                payload["exec"], payload["in_tree"], payload["out_tree"])
        except Exception as e:  # noqa: BLE001 — ANY bad entry => recompile
            import warnings

            warnings.warn(
                "compile cache: corrupt or stale entry %s (%s: %s) — "
                "dropping it and recompiling" % (os.path.basename(path),
                                                 type(e).__name__, e),
                stacklevel=3)
            self._count("dropped")
            try:
                os.remove(path)
            except OSError:
                pass
            return None
        try:  # refresh LRU recency
            os.utime(path)
        except OSError:
            pass
        self._count("hits")
        return compiled

    # -- store ---------------------------------------------------------
    def store(self, key: str, compiled) -> bool:
        """Serialize + publish one entry atomically (temp + fsync +
        rename through ``checkpoint._write_bytes`` — the choke point
        ``fault_injection.fail_writes`` interposes).  Best-effort: any
        failure warns and returns False; the caller already holds the
        freshly-compiled executable."""
        import pickle

        if self._unsupported:
            return False
        try:
            import jax
            from jax.experimental import serialize_executable as _se

            payload, in_tree, out_tree = _se.serialize(compiled)
            blob = pickle.dumps({"version": self.VERSION, "key": key,
                                 "jax": jax.__version__,
                                 "exec": payload, "in_tree": in_tree,
                                 "out_tree": out_tree})
        except Exception as e:  # noqa: BLE001 — some backends can't serialize
            import warnings

            self._unsupported = True
            self._count("store_failures")
            warnings.warn("compile cache: this backend cannot serialize "
                          "executables (%s: %s) — cache disabled for "
                          "stores this process" % (type(e).__name__, e),
                          stacklevel=3)
            return False
        from .checkpoint import _write_bytes

        path = self._path(key)
        tmp = path + ".tmp.%d" % os.getpid()
        try:
            os.makedirs(self.directory, exist_ok=True)
            _write_bytes(tmp, blob)
            os.replace(tmp, path)
        except OSError as e:
            import warnings

            self._count("store_failures")
            warnings.warn("compile cache: failed to store %s (%s) — "
                          "continuing uncached" % (os.path.basename(path),
                                                   e), stacklevel=3)
            try:
                os.remove(tmp)
            except OSError:
                pass
            return False
        self._sweep()
        return True

    def _sweep(self):
        """Size-capped LRU: drop oldest-touched entries (and stray temp
        files) until the directory fits ``max_bytes``."""
        try:
            entries = []
            with os.scandir(self.directory) as it:
                for de in it:
                    if de.name.endswith(self._SUFFIX):
                        st = de.stat()
                        entries.append((st.st_mtime, st.st_size, de.path))
                    elif ".tmp." in de.name:
                        # a crashed writer's stage file: never visible as
                        # an entry, reap it past a grace period
                        st = de.stat()
                        if time.time() - st.st_mtime > 300:
                            os.remove(de.path)
        except OSError:
            return
        total = sum(s for _, s, _ in entries)
        if total <= self.max_bytes:
            return
        for _, size, path in sorted(entries):
            try:
                os.remove(path)
            except OSError:
                continue
            total -= size
            if total <= self.max_bytes:
                break


_DEFAULT_CACHES: Dict[Tuple[str, int], CompileCache] = {}


def default_compile_cache() -> Optional[CompileCache]:
    """The env-configured cache (``MXTPU_COMPILE_CACHE`` directory,
    ``MXTPU_COMPILE_CACHE_MB`` cap), or None when unset.  One
    :class:`CompileCache` instance per (dir, cap) so hit/miss counters
    aggregate across builders."""
    from .. import config as _cfg

    directory = str(_cfg.get("MXTPU_COMPILE_CACHE", "") or "").strip()
    if not directory:
        return None
    cap = int(_cfg.get("MXTPU_COMPILE_CACHE_MB", 512)) << 20
    key = (os.path.abspath(os.path.expanduser(directory)), cap)
    cache = _DEFAULT_CACHES.get(key)
    if cache is None:
        cache = _DEFAULT_CACHES[key] = CompileCache(key[0], max_bytes=cap)
    return cache


def compile_timed(traced, t_trace: float = 0.0, *,
                  cache: Optional[CompileCache] = None,
                  cache_extra: Sequence[Any] = ()) -> Tuple[object,
                                                            Dict[str, Any]]:
    """Lower + compile an already-traced program, returning
    ``(compiled, {"trace": s, "compile": s, "cache": ...})``.
    ``t_trace`` is the wall time the caller already spent tracing
    (lowering is part of the trace phase — it is Python/JAX work, not
    XLA).

    When a :class:`CompileCache` is active (explicit ``cache=`` or the
    ``MXTPU_COMPILE_CACHE`` env), the lowered program is looked up
    first: a hit deserializes the stored executable and reports
    ``compile: 0.0, cache: "hit"`` without touching XLA; a miss
    compiles, bumps :data:`XLA_COMPILES` and stores the result
    (``cache: "stored"``, or ``"store-failed"`` when serialization is
    unavailable).  ``cache_extra`` feeds the key — pass mesh shape +
    axis names and builder knobs so distinct configs can never collide;
    graftsched callers (TrainStep/ServeEngine) include the canonical
    ``PassSchedule`` hash here, so two schedules of the same program
    never share an executable while the SAME schedule cross-process
    hits at zero XLA compiles.
    """
    t0 = time.time()
    lowered = traced.lower()
    t_trace = t_trace + (time.time() - t0)
    if cache is None:
        cache = default_compile_cache()
    times: Dict[str, Any] = {"trace": t_trace}
    key = None
    if cache is not None:
        key = cache.key_for(lowered, extra=cache_extra)
        times["cache_key"] = key
        hit = cache.load(key)
        if hit is not None:
            times["cache"] = "hit"
            times["compile"] = 0.0
            return hit, times
    t0 = time.time()
    compiled = lowered.compile()
    XLA_COMPILES.bump()
    times["compile"] = time.time() - t0
    if cache is not None:
        times["cache"] = "stored" if cache.store(key, compiled) \
            else "store-failed"
    else:
        times["cache"] = "off"
    return compiled, times
