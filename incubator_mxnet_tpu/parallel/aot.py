"""Shared AOT-compile + lint plumbing for compiled-program builders.

Two independent builders assemble long-lived XLA programs from gluon
nets — the fused training step (``parallel/train_step.py``) and the
serving engine (``serve/engine.py``) — and both follow the same ritual:

1. trace the jitted callable ONCE with the GL004 effect hooks active
   (:func:`traced_with_effects` — the very trace jit caches for the
   first call, so the lint costs one jaxpr walk, not an extra trace);
2. assemble a :class:`~..analysis.LintReport` from the effect
   diagnostics + the jaxpr walk + any builder-specific checks and apply
   the ``"error"``/``"warn"``/``"off"`` policy (:func:`finish_lint`);
3. lower + compile with a timed phase split (:func:`compile_timed`) so
   benchmarks can report where startup time goes — the reference's
   analog is cuDNN autotune + InitCachedOps cost at bind
   (``src/executor/graph_executor.cc:1220``).

This module is the ONE copy of that ritual.  The builders keep their
own policy (what counts as an extra diagnostic, when to mark
themselves linted); the mechanics live here.
"""
from __future__ import annotations

import time
from typing import Dict, Iterable, Optional, Sequence, Tuple

__all__ = ["compile_timed", "finish_lint", "lint_served_program",
           "resolve_mode", "traced_with_effects"]


def resolve_mode(value: Optional[str], env_var: str, default: str,
                 allowed: Sequence[str], what: str) -> str:
    """The shared knob-resolution order: explicit argument > env var
    (``config.py``) > ``default``.  Raises ``ValueError`` naming the
    knob on anything outside ``allowed``."""
    if value is None:
        from .. import config as _cfg

        value = str(_cfg.get(env_var, default) or default).lower()
    if value not in allowed:
        raise ValueError("%s must be one of %s, got %r"
                         % (what, "/".join(repr(a) for a in allowed),
                            value))
    return value


def traced_with_effects(jit_obj, args: tuple, capture: bool = True):
    """Trace ``jit_obj`` (via ``.trace(*args)`` — the trace the first
    call reuses) with the GL004 effect-capture hooks active.  Returns
    ``(traced, effect_diagnostics)``; ``capture=False`` skips the hook
    (an empty diagnostics list comes back)."""
    from contextlib import nullcontext

    from ..analysis.trace_lint import capture_effect_diagnostics

    cm = capture_effect_diagnostics() if capture else nullcontext([])
    with cm as effects:
        traced = jit_obj.trace(*args)
    return traced, list(effects)


def finish_lint(closed_jaxpr, *, mode: str, effects: Iterable = (),
                donated_leaves: Sequence[int] = (), extra: Iterable = (),
                suppress: Tuple[str, ...] = (),
                what: str = "compiled program", stacklevel: int = 5):
    """Assemble and enforce one lint report over a traced program.

    ``effects`` are GL004 diagnostics captured during the trace,
    ``donated_leaves`` flat invar indices for the GL003 walk, ``extra``
    builder-specific diagnostics (GL006/GL007 for the train step,
    GL010 for the serving engine).  ``mode="error"`` raises
    :class:`~..analysis.LintError` on error-severity findings; any
    findings at all are warned (so ``"warn"`` mode surfaces them and
    ``"error"`` mode surfaces the non-fatal ones).  Returns the report.
    """
    from ..analysis import LintReport, Severity, lint_jaxpr

    report = LintReport(suppress=suppress)
    report.extend(effects)
    report.extend(lint_jaxpr(closed_jaxpr,
                             donated_leaves=donated_leaves).diagnostics)
    report.extend(extra)
    if mode == "error":
        report.raise_if_errors()
    if report.errors or report.warnings:
        import warnings as _warnings

        _warnings.warn("graftlint: %s has findings\n%s"
                       % (what, report.format(Severity.WARNING)),
                       stacklevel=stacklevel)
    return report


def lint_served_program(traced, effects, args: tuple,
                        donate_argnums: Sequence[int], *, mode: str,
                        suppress: Tuple[str, ...] = (),
                        what: str = "inference program",
                        param_argnum: int = 0, stacklevel: int = 6):
    """The serving-side lint ritual shared by ``serve/engine.py`` and
    ``serve/cache.py``: GL001–GL004 over the traced program plus GL010
    (``check_inference_param_donation``) against the builder's own
    donation spec — the params argument (``param_argnum``) must never
    be donated.  ONE copy, like :func:`finish_lint` for the generic
    half."""
    import jax

    from ..analysis.trace_lint import (check_inference_param_donation,
                                       donated_leaf_indices)

    donated = donated_leaf_indices(args, donate_argnums)
    off = sum(len(jax.tree_util.tree_leaves(a))
              for a in args[:param_argnum])
    n_param = len(jax.tree_util.tree_leaves(args[param_argnum]))
    extra = check_inference_param_donation(
        donated, range(off, off + n_param), where=what)
    return finish_lint(traced.jaxpr, mode=mode, effects=effects,
                       donated_leaves=donated, extra=extra,
                       suppress=suppress, what=what,
                       stacklevel=stacklevel)


def compile_timed(traced, t_trace: float = 0.0) -> Tuple[object,
                                                         Dict[str, float]]:
    """Lower + compile an already-traced program, returning
    ``(compiled, {"trace": s, "compile": s})``.  ``t_trace`` is the
    wall time the caller already spent tracing (lowering is part of
    the trace phase — it is Python/JAX work, not XLA)."""
    t0 = time.time()
    lowered = traced.lower()
    t_trace = t_trace + (time.time() - t0)
    t0 = time.time()
    compiled = lowered.compile()
    return compiled, {"trace": t_trace, "compile": time.time() - t0}
