"""Device mesh management.

The reference's parallelism substrate is KVStore comm trees + ps-lite
(SURVEY.md §2.5/§5.8); the TPU-native substrate is a ``jax.sharding.Mesh``
with named axes and XLA collectives over ICI/DCN.  Axis convention:

- ``dp`` — data parallel (batch sharding; grads all-reduced by XLA)
- ``tp`` — tensor parallel (weight sharding inside layers)
- ``pp`` — pipeline parallel (stage sharding, see .pipeline — forward
  AND the 1F1B/GPipe backward training schedule with microbatch grad
  accumulation, reachable via ``make_train_step(pipeline_stages=...)``)
- ``sp`` — sequence/context parallel (ring attention, see .ring_attention)
- ``ep`` — expert parallel (MoE expert sharding, see .moe — aux
  load-balancing loss + capacity factor route through the fused step)
"""
from __future__ import annotations

from typing import Dict, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

try:  # jax >= 0.5 exports it at top level
    from jax import shard_map
except ImportError:  # jax 0.4.x
    from jax.experimental.shard_map import shard_map

__all__ = ["Mesh", "NamedSharding", "PartitionSpec", "P", "make_mesh",
           "replicated", "shard_along", "current_devices", "shard_map",
           "global_devices", "spans_processes"]

P = PartitionSpec


def current_devices(platform=None):
    devs = jax.devices()
    if platform:
        devs = [d for d in devs if d.platform == platform]
    return devs


def global_devices(platform=None):
    """Every process's devices in deterministic ``(process_index, id)``
    order — the canonical device list for a process-spanning mesh
    (every process must enumerate identically for one GSPMD program to
    span them; ``parallel/distributed.py::make_process_mesh`` builds on
    this)."""
    return sorted(current_devices(platform),
                  key=lambda d: (d.process_index, d.id))


def spans_processes(mesh: Mesh) -> bool:
    """True when the mesh contains devices of more than one process —
    the multihost/multi-process regime where state arrays are global
    and checkpoints need the per-process commit protocol."""
    return any(d.process_index != jax.process_index()
               for d in mesh.devices.flat)


def make_mesh(axes: Dict[str, int], devices: Optional[Sequence] = None) -> Mesh:
    """Create a Mesh with named axes, e.g. make_mesh({'dp': 4, 'tp': 2}).

    Axis sizes must multiply to the device count; an axis size of -1 is
    inferred from the remaining devices.
    """
    devices = list(devices if devices is not None else jax.devices())
    names = list(axes.keys())
    sizes = list(axes.values())
    unknown = [i for i, s in enumerate(sizes) if s == -1]
    known = int(np.prod([s for s in sizes if s != -1])) if sizes else 1
    if unknown:
        if len(unknown) > 1:
            raise ValueError("only one axis may be -1")
        sizes[unknown[0]] = len(devices) // known
    total = int(np.prod(sizes))
    if total != len(devices):
        raise ValueError("mesh axes %s=%s need %d devices, have %d"
                         % (names, sizes, total, len(devices)))
    arr = np.array(devices).reshape(sizes)
    return Mesh(arr, axis_names=tuple(names))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shard_along(mesh: Mesh, axis_name: str, dim: int = 0,
                ndim: int = 1) -> NamedSharding:
    spec = [None] * ndim
    spec[dim] = axis_name
    return NamedSharding(mesh, P(*spec))
