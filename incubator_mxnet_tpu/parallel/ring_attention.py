"""Sequence/context parallelism: ring attention + Ulysses all-to-all.

The reference has NO long-context parallelism (SURVEY.md §5.7) — only
bucketing and fused attention matmuls.  Here sequence scaling is a
first-class capability of the sharding layer:

- :func:`ring_attention` — blockwise-softmax (flash-style numerics)
  attention where K/V blocks rotate around the ``sp`` mesh axis via
  ``lax.ppermute`` (ICI neighbor exchange), overlapping compute with
  communication.  Memory per device is O(seq_local²-block), enabling
  sequences sharded across the pod.
- :func:`ulysses_attention` — all-to-all resharding (seq-sharded ->
  head-sharded), dense local attention, then the inverse all-to-all.
- :func:`sharded_self_attention` — host-level wrapper: shard_map over a mesh
  axis for eager arrays.
"""
from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .collectives import ppermute  # eager GL001-validated collective
from .mesh import shard_map  # version-compat import, one home

__all__ = ["attention_reference", "ring_attention", "ulysses_attention",
           "sharded_self_attention"]


def attention_reference(q, k, v, causal=False, scale=None):
    """Dense softmax attention (correctness oracle). q,k,v: (B,H,S,D)."""
    d = q.shape[-1]
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    if causal:
        qlen, klen = s.shape[-2], s.shape[-1]
        mask = jnp.tril(jnp.ones((qlen, klen), bool), klen - qlen)
        s = jnp.where(mask, s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v)


def _block_attn_update(q, k, v, m, l, o, scale, mask=None):
    """One flash-attention accumulation step with a K/V block."""
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    if mask is not None:
        s = jnp.where(mask, s, -jnp.inf)
    m_blk = jnp.max(s, axis=-1)
    m_new = jnp.maximum(m, m_blk)
    # guard fully-masked rows (exp(-inf - -inf))
    safe_m = jnp.where(jnp.isneginf(m_new), 0.0, m_new)
    p = jnp.exp(s - safe_m[..., None])
    if mask is not None:
        p = jnp.where(mask, p, 0.0)
    alpha = jnp.exp(jnp.where(jnp.isneginf(m), -jnp.inf, m - safe_m))
    alpha = jnp.where(jnp.isneginf(m), 0.0, alpha)
    l_new = l * alpha + jnp.sum(p, axis=-1)
    o_new = o * alpha[..., None] + jnp.einsum("bhqk,bhkd->bhqd", p, v)
    return m_new, l_new, o_new


def ring_attention(q, k, v, axis_name="sp", causal=False, scale=None):
    """Ring attention over a shard_map axis.

    Inside ``shard_map``: q,k,v are the LOCAL sequence shards
    (B,H,S_local,D).  K/V rotate around the ring; each device accumulates
    its queries' attention over every block with streaming-softmax state.
    """
    d = q.shape[-1]
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    n = lax.psum(1, axis_name)
    my_idx = lax.axis_index(axis_name)
    s_local = q.shape[-2]

    b, h, sq, _ = q.shape
    m0 = jnp.full((b, h, sq), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((b, h, sq), jnp.float32)
    o0 = jnp.zeros(q.shape, jnp.float32)
    # constants must carry the 'varying over sp' type to sit in the scan carry
    try:
        m0, l0, o0 = (lax.pcast(x, (axis_name,), to="varying")
                      for x in (m0, l0, o0))
    except AttributeError:  # older jax without the VMA system
        pass
    qf = q.astype(jnp.float32)

    perm = [(i, (i + 1) % n) for i in range(n)]

    def body(step, carry):
        m, l, o, k_blk, v_blk = carry
        # source shard of the current block after `step` rotations
        src = (my_idx - step) % n
        if causal:
            q_pos = my_idx * s_local + jnp.arange(s_local)[:, None]
            k_pos = src * s_local + jnp.arange(s_local)[None, :]
            mask = (k_pos <= q_pos)[None, None]
        else:
            mask = None
        m, l, o = _block_attn_update(qf, k_blk.astype(jnp.float32),
                                     v_blk.astype(jnp.float32),
                                     m, l, o, scale, mask)
        k_blk = ppermute(k_blk, axis_name, perm)
        v_blk = ppermute(v_blk, axis_name, perm)
        return m, l, o, k_blk, v_blk

    m, l, o, _, _ = lax.fori_loop(0, n, body, (m0, l0, o0, k, v))
    out = o / jnp.maximum(l, 1e-38)[..., None]
    return out.astype(q.dtype)


def ulysses_attention(q, k, v, axis_name="sp", causal=False, scale=None):
    """Ulysses-style SP: all-to-all heads<->sequence, dense local attention.

    Inside shard_map with seq-sharded q,k,v (B,H,S_local,D) and H divisible
    by the axis size: reshards to (B,H_local,S_full,D), attends densely,
    reshards back.
    """
    n = lax.psum(1, axis_name)
    # split heads across devices, gather sequence: (B,H,S_l,D)->(B,H/n,S,D)
    def to_seq(x):
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)

    def to_heads(x):
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)

    q2, k2, v2 = to_seq(q), to_seq(k), to_seq(v)
    from .flash_attention import flash_attention
    out = flash_attention(q2, k2, v2, causal=causal, scale=scale)
    return to_heads(out)


def sharded_self_attention(q, k, v, mesh: Mesh, seq_axis="sp", causal=False,
                           impl="ring", scale=None):
    """Host-level entry: shard q,k,v over ``seq_axis`` on dim 2 and run the
    chosen SP attention as one compiled SPMD program."""
    fn = ring_attention if impl == "ring" else ulysses_attention
    spec = P(None, None, seq_axis, None)
    # pallas_call (flash kernel in the ulysses path) doesn't carry
    # varying-mesh-axis metadata; skip the replication/vma check
    # (named check_vma on jax >= 0.6, check_rep on 0.4.x)
    try:
        mapped = shard_map(
            functools.partial(fn, axis_name=seq_axis, causal=causal,
                              scale=scale),
            mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
            check_vma=False)
    except TypeError:
        mapped = shard_map(
            functools.partial(fn, axis_name=seq_axis, causal=causal,
                              scale=scale),
            mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
            check_rep=False)
    return jax.jit(mapped)(q, k, v)
