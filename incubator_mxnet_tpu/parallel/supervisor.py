"""Self-healing training: the run supervisor (docs/RESILIENCE.md §7).

Every recovery primitive already exists — atomic checkpoints with
last-good fallback (``parallel/checkpoint.py``), mid-epoch iterator
resume (``io/resilient.py``), elastic dp-shrink restore over sharded
optimizer state (``parallel/distributed.py``), in-step non-finite
containment (``nonfinite="skip"``) — but nothing *drives* them: a
wedged collective, a silent skip-streak, or a dead host still needs a
human to notice, diagnose, and relaunch.  This module closes the loop:
**detection → policy ladder → automatic resume**, with a forensic
ledger proving what happened.

Three layers, mirroring ``serve/resilience.py`` (policy) over
``serve/batcher.py`` (mechanics):

- **heartbeat protocol** — each rank emits a step-boundary heartbeat
  (step, loss, loss_scale, skipped_steps, wall time) as an atomic
  per-rank file in the checkpoint directory, written through the same
  ``checkpoint._write_bytes`` choke point the checkpoint files use (so
  ``fault_injection.fail_writes`` interposes for free, and a heartbeat
  outage degrades with a warning instead of killing training);

- **detectors** — pure, unit-testable verdict functions over the
  heartbeat set: *hang* (no fresh heartbeat within ``stall_timeout``,
  auto-calibrated from a step-time EMA), *straggler* (a live rank whose
  applied-step count fell a factor behind the median), *divergence*
  (:class:`DivergenceDetector`: a skip streak past its budget — the
  GL012 hazard — or a finite-but-exploding loss EMA that
  ``nonfinite="skip"`` cannot catch);

- **policy ladder** — bounded, in escalation order:

  1. **in-process rollback** (:func:`run_supervised`, inside each
     rank): a divergence verdict restores the last committed
     checkpoint — params, optimizer state, RNG, loss scale AND the
     data-stream position — and resumes; bounded by ``max_rollbacks``,
     after which the rank exits :data:`EXIT_DIVERGED` for the outer
     supervisor to escalate;
  2. **kill-and-respawn** (:class:`Supervisor`): a lost or wedged rank
     kills the whole job (XLA collectives are SPMD all-or-nothing) and
     relaunches it with jittered backoff; ranks restore from the last
     committed checkpoint on startup; bounded by ``max_restarts`` per
     width;
  3. **elastic shrink**: an exhausted restart budget relaunches at a
     narrower dp width (``width // shrink_factor``) — the elastic
     restore re-shards ZeRO state and re-splits iterator parts
     (docs/RESILIENCE.md §5) — with a fresh restart budget;
  4. **give-up**: widths and budgets exhausted → a ``post_mortem``
     ledger event with the full evidence, and a clean nonzero return.
     Never a hang: the watch loop is bounded by ``run(timeout=)``.

Every event (heartbeat gap, verdict, rollback, restart, shrink,
recovery + MTTR, resolution, post-mortem) is appended to a JSONL
**health ledger** committed atomically next to the checkpoints —
per-writer files (``health.jsonl`` for the supervisor,
``health-rNNNNN.jsonl`` per rank) so concurrent writers never race,
merged by :func:`read_ledger`.

``tools/supervise.py`` is the CLI: it launches ranks through the
``tools/launch.py`` DMLC_* env protocol and drives the chaos matrix
(``--chaos kill_process|hang_step|straggler_process|
host_loss_during_save|loss_bomb|all``).
"""
from __future__ import annotations

import json
import math
import os
import random
import time
import warnings
from typing import Any, Callable, Dict, List, Optional, Sequence

__all__ = ["DivergenceDetector", "DivergenceError", "EXIT_DIVERGED",
           "HealthLedger", "HeartbeatEmitter", "StepClock", "Supervisor",
           "SupervisorConfig", "SupervisorError", "committed_steps",
           "hang_verdicts", "read_heartbeats", "read_ledger",
           "run_supervised", "straggler_verdicts"]

#: Worker exit code for "divergence rollback budget exhausted" — the
#: in-process rung of the ladder handing off to the outer supervisor.
EXIT_DIVERGED = 13

_HEARTBEAT_FMT = "heartbeat-r%05d.json"
_LEDGER_SUPERVISOR = "health.jsonl"
_LEDGER_RANK_FMT = "health-r%05d.jsonl"


class SupervisorError(RuntimeError):
    """The supervised run cannot make progress (configuration error,
    or the bounded-call backstop tripped)."""


class DivergenceError(SupervisorError):
    """Divergence persisted through the in-process rollback budget —
    the caller (or the outer :class:`Supervisor`, via
    :data:`EXIT_DIVERGED`) must escalate to the next ladder rung."""


# ---------------------------------------------------------------------------
# heartbeat protocol
# ---------------------------------------------------------------------------

def _atomic_write_json(path: str, payload: Dict) -> None:
    """Write ``payload`` as JSON with the checkpoint layer's atomicity
    discipline: bytes through ``checkpoint._write_bytes`` (the fault-
    injection choke point) into a temp twin, then ``os.replace`` — a
    reader never sees a torn file, only the old or the new one."""
    from . import checkpoint as _ckpt

    data = json.dumps(payload, sort_keys=True).encode()
    tmp = path + ".tmp"
    _ckpt._write_bytes(tmp, data)
    os.replace(tmp, path)


class HeartbeatEmitter:
    """Per-rank step-boundary heartbeat writer.

    ``emit()`` publishes ``{rank, seq, step, loss, loss_scale,
    skipped_steps, status, time}`` atomically to
    ``heartbeat-rNNNNN.json`` in ``directory``.  A write failure warns
    and counts (``write_failures``) instead of raising: losing a
    heartbeat must degrade monitoring, never kill the training step
    that produced it."""

    def __init__(self, directory: str, rank: int = 0):
        self.directory = str(directory)
        self.rank = int(rank)
        self.seq = 0
        self.write_failures = 0
        self.path = os.path.join(self.directory,
                                 _HEARTBEAT_FMT % self.rank)

    def emit(self, step: int, loss: Optional[float] = None,
             loss_scale: Optional[float] = None, skipped_steps: int = 0,
             status: str = "running", **extra) -> Dict:
        self.seq += 1
        hb = {"rank": self.rank, "seq": self.seq, "step": int(step),
              "loss": None if loss is None else float(loss),
              "loss_scale": None if loss_scale is None
              else float(loss_scale),
              "skipped_steps": int(skipped_steps), "status": str(status),
              "time": time.time()}
        hb.update(extra)
        try:
            os.makedirs(self.directory, exist_ok=True)
            _atomic_write_json(self.path, hb)
        except OSError as e:
            self.write_failures += 1
            warnings.warn("heartbeat write failed (rank %d, seq %d): %s "
                          "— monitoring degraded, training continues"
                          % (self.rank, self.seq, e))
        return hb


def read_heartbeats(directory: str) -> Dict[int, Dict]:
    """All readable per-rank heartbeats under ``directory`` as
    ``{rank: payload}``.  Torn/unparseable files are skipped (the
    atomic-replace discipline makes them rare; a crash can still leave
    a ``.tmp`` twin, which is ignored by name)."""
    out: Dict[int, Dict] = {}
    if not os.path.isdir(str(directory)):
        return out
    for name in os.listdir(str(directory)):
        if not (name.startswith("heartbeat-r") and name.endswith(".json")):
            continue
        try:
            with open(os.path.join(str(directory), name)) as f:
                hb = json.load(f)
            out[int(hb["rank"])] = hb
        except (OSError, ValueError, KeyError, TypeError):
            continue
    return out


def committed_steps(directory: str) -> List[int]:
    """Committed checkpoint steps under ``directory``, ascending —
    the supervisor's (manager-free) view of what a restarted rank will
    restore from.  Only atomically-renamed ``step-NNNNNNNN`` dirs
    count; torn ``.tmp-step-*`` stages are invisible, exactly like
    ``CheckpointManager.steps()``."""
    if not os.path.isdir(str(directory)):
        return []
    out = []
    for name in os.listdir(str(directory)):
        if name.startswith("step-"):
            try:
                out.append(int(name[5:]))
            except ValueError:
                continue
    return sorted(out)


# ---------------------------------------------------------------------------
# health ledger
# ---------------------------------------------------------------------------

class HealthLedger:
    """Append-only JSONL event log, one writer per file.

    Each event is ``{"event": ..., "seq": n, "time": wall, **fields}``,
    appended as ONE fsync'd line (O(1) per event — the history is never
    rewritten).  Readers tolerate a torn trailing line (a crash
    mid-append), and re-opening a file whose last byte is not a newline
    first terminates the torn line so the next record cannot fuse onto
    it.  One ledger file has exactly ONE writer (the supervisor owns
    ``health.jsonl``, each rank its ``health-rNNNNN.jsonl``) and
    :func:`read_ledger` merges them by time."""

    def __init__(self, path: str):
        self.path = str(path)
        self._events: List[Dict] = list(_read_jsonl(self.path))
        self._seq = max((e.get("seq", 0) for e in self._events),
                        default=0)
        try:
            with open(self.path, "rb") as f:
                f.seek(-1, os.SEEK_END)
                self._needs_newline = f.read(1) != b"\n"
        except OSError:
            self._needs_newline = False  # absent or empty file

    def append(self, event: str, **fields) -> Dict:
        self._seq += 1
        rec = {"event": str(event), "seq": self._seq,
               "time": time.time()}
        rec.update(fields)
        self._events.append(rec)
        line = json.dumps(rec, sort_keys=True, default=str) + "\n"
        if self._needs_newline:
            line = "\n" + line  # seal a previous torn append
        try:
            parent = os.path.dirname(self.path)
            if parent:
                os.makedirs(parent, exist_ok=True)
            with open(self.path, "ab") as f:
                f.write(line.encode())
                f.flush()
                os.fsync(f.fileno())
            self._needs_newline = False
        except OSError as e:
            warnings.warn("health-ledger write failed (%s): %s — event "
                          "kept in memory only" % (self.path, e))
        return rec

    def events(self, event: Optional[str] = None) -> List[Dict]:
        if event is None:
            return list(self._events)
        return [e for e in self._events if e.get("event") == event]


def _read_jsonl(path: str):
    try:
        with open(path) as f:
            raw = f.read()
    except OSError:
        return
    for line in raw.splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            yield json.loads(line)
        except ValueError:
            continue  # torn trailing line from a pre-atomic writer


def read_ledger(directory: str) -> List[Dict]:
    """Every health event under ``directory`` (the supervisor's file
    plus every rank's), merged in time order — the forensic record a
    post-mortem walks (docs/RESILIENCE.md §7)."""
    events: List[Dict] = []
    if not os.path.isdir(str(directory)):
        return events
    for name in sorted(os.listdir(str(directory))):
        if name == _LEDGER_SUPERVISOR or (name.startswith("health-r")
                                          and name.endswith(".jsonl")):
            events.extend(_read_jsonl(os.path.join(str(directory), name)))
    events.sort(key=lambda e: (e.get("time", 0.0), e.get("seq", 0)))
    return events


# ---------------------------------------------------------------------------
# detectors (pure verdict functions — tests/test_supervisor.py)
# ---------------------------------------------------------------------------

class StepClock:
    """EMA of step (heartbeat-arrival) intervals, the auto-calibration
    behind the hang detector: ``stall_timeout()`` answers
    ``max(floor, factor × EMA)`` once two arrivals have been seen, else
    ``startup_timeout`` (the first step pays compile time — a fixed
    small timeout would kill every cold start)."""

    def __init__(self, alpha: float = 0.3, factor: float = 8.0,
                 floor: float = 2.0, startup_timeout: float = 120.0):
        if not 0 < alpha <= 1:
            raise ValueError("alpha must be in (0, 1], got %r" % (alpha,))
        self.alpha = float(alpha)
        self.factor = float(factor)
        self.floor = float(floor)
        self.startup_timeout = float(startup_timeout)
        self.ema: Optional[float] = None
        self._last: Optional[float] = None

    def observe(self, now: float) -> None:
        """Feed one arrival (any rank's NEW heartbeat)."""
        if self._last is not None:
            dt = max(0.0, now - self._last)
            self.ema = dt if self.ema is None else \
                self.alpha * dt + (1 - self.alpha) * self.ema
        self._last = now

    def stall_timeout(self) -> float:
        if self.ema is None:
            return self.startup_timeout
        return max(self.floor, self.factor * self.ema)


def hang_verdicts(heartbeats: Dict[int, Dict], now: float,
                  timeout: float,
                  last_seen: Optional[Dict[int, float]] = None
                  ) -> List[Dict]:
    """Ranks whose freshest heartbeat is older than ``timeout``.

    ``last_seen`` (rank → local arrival time on the CALLER's clock,
    maintained by the watcher) takes precedence over the heartbeat's
    own ``time`` stamp so a cross-host clock skew can't fabricate a
    hang; ranks with no heartbeat at all are the CALLER's to age (it
    knows launch time).  When ``last_seen`` is given, ``now`` must be
    on ITS clock and a rank absent from it starts aging at ``now``
    (the payload stamp is wall time — aging it against a monotonic
    ``now`` would yield a huge negative age that can never flag); the
    payload stamp is consulted only when no ``last_seen`` is supplied
    at all, i.e. a pure wall-clock caller.
    Returns ``[{rank, age, timeout}]``."""
    out = []
    for rank, hb in sorted(heartbeats.items()):
        if hb.get("status") in ("done", "diverged", "failed"):
            continue  # a finished rank stops beating by design
        if last_seen is None:
            seen = hb.get("time", now)
        else:
            seen = last_seen.get(rank, now)
        age = now - seen
        if age > timeout:
            out.append({"rank": rank, "age": age, "timeout": timeout})
    return out


def straggler_verdicts(heartbeats: Dict[int, Dict],
                       factor: float = 3.0,
                       min_lag: int = 4) -> List[Dict]:
    """Live ranks whose applied-step count fell behind the (upper)
    median by more than a factor of ``factor`` AND at least ``min_lag``
    steps — the still-beating-but-slow host the hang detector cannot
    see.  Startup jitter never flags: below ``min_lag`` steps of lag
    there is no verdict.  Ranks that already finished (``"done"``)
    keep anchoring the median — a crawling rank whose healthy peers
    all completed is still a straggler — but only ``"running"`` ranks
    can be flagged."""
    live = {r: hb for r, hb in heartbeats.items()
            if hb.get("status") == "running"}
    ref = [hb for hb in heartbeats.values()
           if hb.get("status") in ("running", "done")]
    if not live or len(ref) < 2:
        return []
    steps = sorted(int(hb.get("step", 0)) for hb in ref)
    median = steps[len(steps) // 2]
    out = []
    for rank, hb in sorted(live.items()):
        step = int(hb.get("step", 0))
        lag = median - step
        if lag >= max(int(min_lag), 1) and step * float(factor) < median:
            out.append({"rank": rank, "step": step, "median": median,
                        "lag": lag})
    return out


class DivergenceDetector:
    """Per-rank divergence verdicts over the (loss, applied-step,
    skipped-step) stream — the failure class ``nonfinite="skip"``
    cannot catch, in two shapes:

    - ``"skip_streak"`` — ``skip_streak_budget``-many CONSECUTIVE
      skipped steps (cumulative ``skipped_steps`` rising while the
      applied step count stands still): under a static loss scale the
      scale never adapts, so an unbounded streak is a stalled run that
      looks alive (graftlint GL012 flags the config; this detector
      catches it live);
    - ``"loss_explosion"`` — the EMA of *finite* losses grew by
      ``explosion_factor`` over its own post-warmup minimum, sustained
      for ``patience`` consecutive updates (one hot batch is noise; an
      exploding trend is divergence).  A non-finite loss observation
      is never fed to the EMA (the skip guard already owns that step).
      The minimum is LEAKY (``baseline_leak`` per update): it slowly
      forgets ancient lows, so a run long-converged at a tiny loss is
      not flagged for a benign drift measured against a stale
      months-old minimum — a real explosion outruns the leak by orders
      of magnitude.
    """

    def __init__(self, skip_streak_budget: Optional[int] = None,
                 explosion_factor: float = 1e3, ema_alpha: float = 0.2,
                 patience: int = 2, warmup: int = 3,
                 baseline_leak: float = 0.01):
        if skip_streak_budget is not None and int(skip_streak_budget) < 1:
            raise ValueError("skip_streak_budget must be >= 1 or None, "
                             "got %r" % (skip_streak_budget,))
        if float(explosion_factor) <= 1:
            raise ValueError("explosion_factor must be > 1, got %r"
                             % (explosion_factor,))
        if int(patience) < 1:
            raise ValueError("patience must be >= 1, got %r" % (patience,))
        self.skip_streak_budget = None if skip_streak_budget is None \
            else int(skip_streak_budget)
        self.explosion_factor = float(explosion_factor)
        self.ema_alpha = float(ema_alpha)
        self.patience = int(patience)
        self.warmup = int(warmup)
        if float(baseline_leak) < 0:
            raise ValueError("baseline_leak must be >= 0, got %r"
                             % (baseline_leak,))
        self.baseline_leak = float(baseline_leak)
        self.reset()

    def reset(self) -> None:
        """Forget history — call after a rollback restored known-good
        state (the pre-rollback EMA would instantly re-flag it)."""
        self.skip_streak = 0
        self.ema: Optional[float] = None
        self.ema_min: Optional[float] = None
        self._finite_seen = 0
        self._hot = 0
        self._last_step: Optional[int] = None
        self._last_skipped = 0

    def update(self, step: int, loss: Optional[float],
               skipped_steps: int = 0) -> Optional[str]:
        step, skipped_steps = int(step), int(skipped_steps)
        # -- skip streak: skips rising while the applied step stalls
        if self._last_step is not None:
            if skipped_steps > self._last_skipped and \
                    step <= self._last_step:
                self.skip_streak += skipped_steps - self._last_skipped
            elif step > self._last_step:
                self.skip_streak = 0
        self._last_step, self._last_skipped = step, skipped_steps
        if self.skip_streak_budget is not None and \
                self.skip_streak >= self.skip_streak_budget:
            return "skip_streak"
        # -- loss-explosion EMA (finite observations only)
        if loss is None or not math.isfinite(loss):
            return None
        self._finite_seen += 1
        a = self.ema_alpha
        self.ema = loss if self.ema is None else a * loss + (1 - a) * self.ema
        if self._finite_seen < self.warmup:
            return None
        if self.ema_min is None:
            self.ema_min = abs(self.ema)
        else:
            # leaky minimum: the baseline rises toward the current
            # level a little every update, bounding the lookback
            self.ema_min = min(self.ema_min * (1 + self.baseline_leak),
                               abs(self.ema))
        baseline = max(self.ema_min, 1e-12)
        if abs(self.ema) > self.explosion_factor * baseline:
            self._hot += 1
            if self._hot >= self.patience:
                return "loss_explosion"
        else:
            self._hot = 0
        return None

    @property
    def suspicious(self) -> bool:
        """True while the stream looks unhealthy but is still below
        verdict threshold — an active skip streak, a hot explosion
        count, or a loss EMA more than 10× its post-warmup minimum.
        The supervised loop DEFERS boundary checkpoints while this
        holds: a checkpoint of a quietly-diverging run would poison
        the very rollback target the verdict needs (conservative by
        design — a genuine sustained 10× loss rise defers saves until
        it either trips the verdict or decays back)."""
        if self.skip_streak > 0 or self._hot > 0:
            return True
        if self.ema is not None and self.ema_min is not None:
            return abs(self.ema) > 10.0 * max(self.ema_min, 1e-12)
        return False


# ---------------------------------------------------------------------------
# config
# ---------------------------------------------------------------------------

class SupervisorConfig:
    """Knobs for both halves of the loop (worker rung + watchdog).
    All durations are seconds; see docs/RESILIENCE.md §7 for the
    threshold table."""

    def __init__(self,
                 # detection
                 stall_timeout: Optional[float] = None,
                 stall_factor: float = 8.0,
                 min_stall_timeout: float = 2.0,
                 startup_timeout: float = 120.0,
                 straggler_factor: float = 3.0,
                 straggler_min_lag: int = 4,
                 straggler_grace: float = 2.0,
                 skip_streak_budget: int = 16,
                 explosion_factor: float = 1e3,
                 ema_alpha: float = 0.2,
                 divergence_patience: int = 2,
                 # ladder budgets
                 max_rollbacks: int = 1,
                 max_restarts: int = 2,
                 backoff: float = 0.25,
                 min_width: int = 1,
                 shrink_factor: int = 2,
                 # mechanics
                 poll_interval: float = 0.05,
                 checkpoint_every: Optional[int] = 2):
        if stall_timeout is not None and float(stall_timeout) <= 0:
            raise ValueError("stall_timeout must be positive seconds or "
                             "None (auto), got %r" % (stall_timeout,))
        if int(max_restarts) < 0 or int(max_rollbacks) < 0:
            raise ValueError("budgets must be >= 0")
        if int(shrink_factor) < 2:
            raise ValueError("shrink_factor must be >= 2, got %r"
                             % (shrink_factor,))
        if int(min_width) < 1:
            raise ValueError("min_width must be >= 1, got %r"
                             % (min_width,))
        self.stall_timeout = stall_timeout
        self.stall_factor = float(stall_factor)
        self.min_stall_timeout = float(min_stall_timeout)
        self.startup_timeout = float(startup_timeout)
        self.straggler_factor = float(straggler_factor)
        self.straggler_min_lag = int(straggler_min_lag)
        self.straggler_grace = float(straggler_grace)
        self.skip_streak_budget = int(skip_streak_budget)
        self.explosion_factor = float(explosion_factor)
        self.ema_alpha = float(ema_alpha)
        self.divergence_patience = int(divergence_patience)
        self.max_rollbacks = int(max_rollbacks)
        self.max_restarts = int(max_restarts)
        self.backoff = float(backoff)
        self.min_width = int(min_width)
        self.shrink_factor = int(shrink_factor)
        self.poll_interval = float(poll_interval)
        self.checkpoint_every = None if checkpoint_every is None \
            else int(checkpoint_every)

    def make_detector(self,
                      skip_budget: Optional[int] = None
                      ) -> DivergenceDetector:
        return DivergenceDetector(
            skip_streak_budget=self.skip_streak_budget
            if skip_budget is None else skip_budget,
            explosion_factor=self.explosion_factor,
            ema_alpha=self.ema_alpha,
            patience=self.divergence_patience)


# ---------------------------------------------------------------------------
# the supervised train loop (runs INSIDE each rank)
# ---------------------------------------------------------------------------

def _run_step(step, x, y):
    """The one choke point every supervised step call goes through —
    module-level so the fault harness can interpose a wedge
    (``fault_injection.hang_step``) or a finite gradient bomb
    (``fault_injection.loss_bomb``) without touching the loop."""
    return step(x, y)


def _save_checkpoint(step, manager, data_iter):
    """Boundary-save choke point (``fault_injection`` scenarios that
    must die or stall exactly mid-save arm themselves here)."""
    return step.save_checkpoint(manager, data_iter=data_iter)


def _scale_params(step, factor: float) -> int:
    """Multiply every floating trainable param of ``step`` in place by
    ``factor`` — the ``loss_bomb`` payload: gradients stay FINITE, the
    loss explodes, ``nonfinite="skip"`` never fires, and only a
    checkpoint rollback restores health.  Returns how many params were
    scaled."""
    import jax.numpy as jnp
    import numpy as np

    step._ensure_built()
    n = 0
    for p in step._gp:
        arr = p._data._data
        if np.issubdtype(np.dtype(arr.dtype), np.floating):
            p._data._data = arr * jnp.asarray(factor, dtype=arr.dtype)
            n += 1
    return n


def _next_batch(data_iter):
    """One (x, y) from a DataIter-protocol iterator, resetting across
    epoch ends."""
    try:
        batch = data_iter.next()
    except StopIteration:
        data_iter.reset()
        batch = data_iter.next()
    return batch.data[0], batch.label[0]


def run_supervised(step, data_iter, manager, until_step: int,
                   config: Optional[SupervisorConfig] = None,
                   rank: int = 0, heartbeat_dir: Optional[str] = None,
                   ledger: Optional[HealthLedger] = None,
                   on_step: Optional[Callable[[Dict], None]] = None
                   ) -> Dict:
    """Drive ``step`` to ``until_step`` applied updates under
    supervision — the per-rank half of the ladder.

    Every step boundary: emit a heartbeat, feed the divergence
    detector, honor the periodic checkpoint schedule
    (``config.checkpoint_every`` applied steps, iterator state
    included).  On a divergence verdict: roll back to the last
    committed checkpoint (data stream included — the replayed batches
    are the SAME batches), bounded by ``config.max_rollbacks``; an
    exhausted budget (or no committed checkpoint to roll back to)
    raises :class:`DivergenceError` — the outer supervisor's cue to
    respawn/shrink.  If the manager already holds a committed step, the
    loop RESUMES from it first (the respawn rung lands here).

    Returns ``{"losses": [...], "final_step": n, "rollbacks": k,
    "restored_from": step-or-None}``.  Bounded: a loop that cannot
    reach ``until_step`` within ``8×until_step + 64`` calls raises
    :class:`SupervisorError` instead of spinning forever.
    """
    cfg = config or SupervisorConfig()
    hb_dir = str(heartbeat_dir or manager.directory)
    emitter = HeartbeatEmitter(hb_dir, rank)
    if ledger is None:
        ledger = HealthLedger(os.path.join(hb_dir,
                                           _LEDGER_RANK_FMT % rank))
    budget = getattr(step, "skip_streak_budget", None)
    detector = cfg.make_detector(skip_budget=budget)
    restored_from = None
    if manager.latest_step() is not None:
        restored_from = step.restore_checkpoint(manager,
                                                data_iter=data_iter)
        ledger.append("resume", rank=rank, from_step=int(restored_from))
    rollbacks = 0
    fault_t: Optional[float] = None
    fault_target: Optional[int] = None  # rollback step; recovered past it
    losses: List[float] = []
    calls = 0
    max_calls = 8 * int(until_step) + 64
    while step.step_count < int(until_step):
        if calls >= max_calls:
            emitter.emit(step.step_count, status="failed")
            raise SupervisorError(
                "supervised loop made no progress: %d calls produced "
                "only %d/%d applied steps" % (calls, step.step_count,
                                              until_step))
        calls += 1
        x, y = _next_batch(data_iter)
        out = _run_step(step, x, y)
        loss = float(out.asscalar())
        applied = step.step_count
        skipped = step.skipped_steps
        losses.append(loss)
        hb = emitter.emit(applied, loss=loss, loss_scale=step.loss_scale,
                          skipped_steps=skipped)
        if on_step is not None:
            on_step(hb)
        if getattr(step, "sync", "allreduce") == "auto":
            # sync→async policy ladder (docs/RESILIENCE.md §8): the
            # straggler detector's verdicts feed the step's hysteresis
            # policy every boundary; a rung switch is a ledger event.
            # EVERY rank sees the same shared heartbeat set, so every
            # rank flips on (approximately) the same frame — including
            # the straggler itself, which must stop blocking its peers
            stragglers = straggler_verdicts(
                read_heartbeats(hb_dir), factor=cfg.straggler_factor,
                min_lag=cfg.straggler_min_lag)
            before = step.sync_mode
            after = step.observe_stragglers(
                [v["rank"] for v in stragglers])
            if after != before:
                ledger.append(
                    "sync_degrade" if after == "async" else "sync_recover",
                    rank=rank, mode=after, step=applied,
                    stragglers=[v["rank"] for v in stragglers])
        if fault_t is not None and fault_target is not None and \
                applied > fault_target:
            # first APPLIED step past the rollback point = recovered —
            # a post-rollback step that was itself skipped is not
            # progress, and must not mint a recovery/MTTR record
            ledger.append("recovered", rank=rank, mode="rollback",
                          step=applied, mttr=time.time() - fault_t)
            fault_t = fault_target = None
        verdict = detector.update(applied, loss, skipped)
        if verdict is not None:
            fault_t = time.time()
            ledger.append("divergence", rank=rank, verdict=verdict,
                          step=applied, loss=loss,
                          skip_streak=detector.skip_streak)
            last = manager.latest_step()
            if rollbacks >= cfg.max_rollbacks or last is None:
                emitter.emit(applied, loss=loss, status="diverged",
                             skipped_steps=skipped)
                ledger.append("rollback_exhausted", rank=rank,
                              rollbacks=rollbacks,
                              budget=cfg.max_rollbacks,
                              has_checkpoint=last is not None)
                raise DivergenceError(
                    "divergence (%s) at step %d persisted through %d "
                    "rollback(s)%s — escalate (respawn/shrink) or "
                    "inspect the health ledger" %
                    (verdict, applied, rollbacks,
                     "" if last is not None
                     else "; no committed checkpoint to roll back to"))
            to = step.restore_checkpoint(manager, data_iter=data_iter)
            fault_target = int(to)
            rollbacks += 1
            detector.reset()
            ledger.append("rollback", rank=rank, to_step=int(to),
                          verdict=verdict)
            emitter.emit(step.step_count, status="running",
                         skipped_steps=step.skipped_steps)
            continue
        if cfg.checkpoint_every is not None and applied > 0 and \
                applied % cfg.checkpoint_every == 0 and \
                applied > (manager.latest_step() or -1) and \
                not detector.suspicious:
            try:
                _save_checkpoint(step, manager, data_iter)
            except BaseException as e:
                # a failed periodic save must not kill a healthy rank:
                # the last committed checkpoint still stands, and the
                # outer supervisor owns any escalation (a dead PEER
                # surfaces through ITS exit, not ours)
                ledger.append("save_failed", rank=rank, step=applied,
                              error="%s: %s" % (type(e).__name__, e))
                warnings.warn("supervised checkpoint save at step %d "
                              "failed (%s: %s); continuing on the last "
                              "committed checkpoint" %
                              (applied, type(e).__name__, e))
    emitter.emit(step.step_count, loss=losses[-1] if losses else None,
                 loss_scale=step.loss_scale,
                 skipped_steps=step.skipped_steps, status="done")
    ledger.append("done", rank=rank, step=step.step_count,
                  rollbacks=rollbacks)
    return {"losses": losses, "final_step": int(step.step_count),
            "rollbacks": rollbacks, "restored_from": restored_from}


# ---------------------------------------------------------------------------
# the watchdog + policy ladder (runs in the SUPERVISOR process)
# ---------------------------------------------------------------------------

class Supervisor:
    """Process-0 watchdog owning a fleet of training ranks.

    ``launch(width, attempt)`` (caller-supplied) starts one job at the
    given dp width and returns a list of process handles exposing the
    ``subprocess.Popen`` liveness surface (``poll() -> rc|None``,
    ``terminate()``, ``kill()``, ``wait(timeout=)``) — the real CLI
    spawns interpreters through the ``tools/launch.py`` DMLC_* env
    protocol, the ladder tests drive scripted stubs.

    :meth:`run` watches heartbeats + process exits, forms verdicts
    (hang / straggler / lost rank / in-worker divergence escalation),
    and walks the bounded ladder: kill-and-respawn with jittered
    backoff (``max_restarts`` per width) → elastic shrink
    (``width // shrink_factor``, fresh budget) → give-up post-mortem.
    Ranks re-enter through :func:`run_supervised`, which restores the
    last committed checkpoint — so every recovery resumes from
    committed state, and a torn stage is never visible by construction.
    """

    def __init__(self, launch: Callable[[int, int], Sequence[Any]],
                 width: int, directory: str,
                 config: Optional[SupervisorConfig] = None):
        if int(width) < 1:
            raise ValueError("width must be >= 1, got %r" % (width,))
        self.launch = launch
        self.width = int(width)
        self.directory = str(directory)
        self.config = config or SupervisorConfig()
        os.makedirs(self.directory, exist_ok=True)
        self.ledger = HealthLedger(os.path.join(self.directory,
                                                _LEDGER_SUPERVISOR))
        self.restarts = 0        # total, all widths
        self.shrinks = 0
        self.mttrs: List[float] = []
        self._procs: List[Any] = []

    # -- mechanics -------------------------------------------------------
    def _kill_job(self):
        live = [p for p in self._procs if p.poll() is None]
        for p in live:
            try:
                p.terminate()
            except OSError:
                pass
        for p in live:
            try:
                p.wait(timeout=5)
            except Exception:
                try:
                    p.kill()
                    p.wait(timeout=5)
                except Exception:
                    pass
        self._procs = []

    def _clear_heartbeats(self):
        """Drop stale heartbeat files before a relaunch: a dead rank's
        old file (or a rank beyond a shrunken width) must not age into
        a fake hang verdict against the fresh job."""
        for name in os.listdir(self.directory):
            if name.startswith("heartbeat-r"):
                try:
                    os.unlink(os.path.join(self.directory, name))
                except OSError:
                    pass

    def _resume_target(self) -> int:
        steps = committed_steps(self.directory)
        return (steps[-1] + 1) if steps else 1

    # -- the watch loop --------------------------------------------------
    def run(self, timeout: float = 600.0) -> Dict:
        """Supervise until the job resolves or the ladder gives up.
        Returns the outcome record (also appended to the ledger):
        ``{"outcome": "resolved"|"gave_up", "width": final_width,
        "restarts": n, "shrinks": k, "mttrs": [...], ...}``.  Bounded
        by ``timeout`` — on expiry the job is killed and a post-mortem
        written: the supervisor itself never hangs."""
        cfg = self.config
        width = self.width
        attempt = 0
        restarts_this_width = 0
        deadline = time.monotonic() + float(timeout)
        # ONE clock per rank: the EMA must measure a rank's own
        # heartbeat interval — feeding all ranks into one clock would
        # calibrate the timeout to step_time / width and flag healthy
        # wide fleets as hung.  The WIDEST rank's timeout governs.
        clocks: Dict[int, StepClock] = {}

        def stall_bound() -> float:
            if cfg.stall_timeout:
                return cfg.stall_timeout
            bounds = [c.stall_timeout() for c in clocks.values()
                      if c.ema is not None]
            return max(bounds) if bounds else cfg.startup_timeout

        last_seen: Dict[int, float] = {}
        last_seq: Dict[int, int] = {}
        straggler_since: Dict[int, float] = {}
        pending_fault: Optional[Dict] = None
        self._clear_heartbeats()
        self._procs = list(self.launch(width, attempt))
        launch_t = time.monotonic()
        self.ledger.append("launch", width=width, attempt=attempt)

        def verdictify(verdict: str, **detail) -> None:
            nonlocal pending_fault
            self.ledger.append("fault", verdict=verdict, width=width,
                               attempt=attempt, **detail)
            if pending_fault is None:
                pending_fault = {"verdict": verdict,
                                 "t": time.monotonic(),
                                 "resume_target": self._resume_target()}

        while True:
            if time.monotonic() > deadline:
                self._kill_job()
                return self._post_mortem("supervisor timeout", width)
            time.sleep(cfg.poll_interval)
            now_mono = time.monotonic()
            hbs = read_heartbeats(self.directory)
            for rank, hb in hbs.items():
                if hb.get("seq", 0) > last_seq.get(rank, 0):
                    last_seq[rank] = hb["seq"]
                    last_seen[rank] = now_mono
                    clocks.setdefault(rank, StepClock(
                        factor=cfg.stall_factor,
                        floor=cfg.min_stall_timeout,
                        startup_timeout=cfg.startup_timeout,
                    )).observe(now_mono)
            # recovery confirmation: a fresh heartbeat past the resume
            # target closes the pending fault and records its MTTR
            if pending_fault is not None:
                tgt = pending_fault["resume_target"]
                if any(hb.get("step", -1) >= tgt for hb in hbs.values()):
                    mttr = time.monotonic() - pending_fault["t"]
                    self.mttrs.append(mttr)
                    self.ledger.append("recovered", mode="respawn",
                                       verdict=pending_fault["verdict"],
                                       mttr=mttr, width=width)
                    pending_fault = None
            rcs = [p.poll() for p in self._procs]
            if rcs and all(rc == 0 for rc in rcs):
                out = {"outcome": "resolved", "width": width,
                       "restarts": self.restarts, "shrinks": self.shrinks,
                       "mttrs": list(self.mttrs),
                       "final_step": max(
                           [hb.get("step", 0) for hb in hbs.values()],
                           default=0)}
                self.ledger.append("resolved", **out)
                return out
            # -- lost / diverged ranks ----------------------------------
            dead = [(r, rc) for r, rc in enumerate(rcs)
                    if rc not in (None, 0)]
            if dead:
                rank, rc = dead[0]
                verdict = "divergence_exhausted" \
                    if rc == EXIT_DIVERGED else "lost_rank"
                verdictify(verdict, rank=rank, returncode=rc)
            else:
                # -- hang: no fresh heartbeat within the stall timeout
                stall = stall_bound()
                hung = hang_verdicts(hbs, now_mono, stall,
                                     last_seen=last_seen)
                # ranks that never beat at all age from launch time
                beatless = [r for r in range(width) if r not in hbs]
                if beatless and now_mono - launch_t > max(
                        stall, cfg.startup_timeout):
                    hung.extend({"rank": r,
                                 "age": now_mono - launch_t,
                                 "timeout": cfg.startup_timeout}
                                for r in beatless)
                if hung:
                    for h in hung:
                        self.ledger.append("heartbeat_gap", **h)
                    verdictify("hang", ranks=[h["rank"] for h in hung],
                               stall_timeout=stall)
                else:
                    # -- straggler: beating, but a factor behind
                    strag = straggler_verdicts(
                        hbs, factor=cfg.straggler_factor,
                        min_lag=cfg.straggler_min_lag)
                    for s in strag:
                        r = s["rank"]
                        if r not in straggler_since:
                            straggler_since[r] = now_mono
                            self.ledger.append("straggler", **s)
                    for r in list(straggler_since):
                        if r not in {s["rank"] for s in strag}:
                            del straggler_since[r]
                    over = [r for r, t0 in straggler_since.items()
                            if now_mono - t0 > cfg.straggler_grace]
                    if over:
                        verdictify("straggler", ranks=sorted(over))
                    else:
                        continue  # healthy poll
            # -- the ladder: respawn → shrink → give up -----------------
            self._kill_job()
            restarts_this_width += 1
            if restarts_this_width > cfg.max_restarts:
                if width > cfg.min_width:
                    new_width = max(cfg.min_width,
                                    width // cfg.shrink_factor)
                    self.ledger.append("shrink", from_width=width,
                                       to_width=new_width,
                                       restarts_at_width=restarts_this_width
                                       - 1)
                    self.shrinks += 1
                    width = new_width
                    restarts_this_width = 1  # this relaunch counts
                else:
                    return self._post_mortem(
                        "restart budget exhausted at min width", width)
            attempt += 1
            self.restarts += 1
            time.sleep(cfg.backoff * attempt *
                       (0.5 + random.random()))  # jittered
            self._clear_heartbeats()
            last_seen.clear()
            last_seq.clear()
            straggler_since.clear()
            # fresh calibration for the fresh job: folding the outage
            # interval (kill → backoff → respawn → first compile) into
            # the EMA would inflate the stall timeout for the whole
            # relaunch, and after a shrink the clocks of ranks beyond
            # the new width must stop contributing to the bound
            clocks.clear()
            self._procs = list(self.launch(width, attempt))
            launch_t = time.monotonic()
            self.ledger.append("restart", width=width, attempt=attempt,
                               restarts_at_width=restarts_this_width)

    def _post_mortem(self, reason: str, width: int) -> Dict:
        """Give up loudly: one ledger event carrying the evidence a
        human (or the next tool) needs — no hang, no silent exit."""
        events = self.ledger.events()
        counts: Dict[str, int] = {}
        for e in events:
            counts[e["event"]] = counts.get(e["event"], 0) + 1
        out = {"outcome": "gave_up", "reason": reason, "width": width,
               "restarts": self.restarts, "shrinks": self.shrinks,
               "mttrs": list(self.mttrs),
               "committed_steps": committed_steps(self.directory),
               "last_heartbeats": read_heartbeats(self.directory),
               "event_counts": counts}
        self.ledger.append("post_mortem", **out)
        return out
