"""Pipeline parallelism over a ``pp`` mesh axis — forward AND backward.

The reference's only model parallelism is manual ``group2ctx`` layer
placement with engine-inserted copies (SURVEY.md §2.5).  TPU-native: stages
are sharded over the ``pp`` axis inside one SPMD program; activations flow
stage→stage via ``lax.ppermute`` (ICI neighbor hop) in a software-pipelined
schedule of ``num_micro + num_stages - 1`` ticks.

Training: the schedule is written as a ``lax.scan`` over ticks, so the
whole pipeline is reverse-differentiable.  ``jax.grad`` of a loss over
``spmd_pipeline`` yields the backward pipeline schedule as the transposed
scan — ticks run in reverse, cotangents hop stage←stage through the
inverted ``ppermute``, and each rank accumulates its stage's parameter
gradients across microbatches in the scan-transpose carry (the GPipe
fill/drain schedule; 1F1B's steady state is the same tick sequence
executed from the transpose).  Activation stash: by default the scan
saves each tick's residuals (GPipe memory profile, ``num_micro + n - 1``
live stage activations per rank); ``remat=True`` checkpoints the stage
function so only stage *inputs* are stashed and the stage recomputes in
its backward tick — the 1F1B-style memory/compute trade.  Everything is
one jitted XLA program: zero per-microbatch Python dispatch.

ZeRO interplay: on a dp x pp mesh, ``make_train_step(...,
pipeline_stages=K, zero=1)`` composes with this schedule cleanly —
microbatch gradients accumulate ON-RANK in the scan-transpose carry, so
the ZeRO-1 dp grad reduction runs ONCE per step on the accumulated
grads (never per microbatch), and the dp-sharded optimizer state/update
live entirely outside the pipelined scan (train_step._apply_zero).
"""
from __future__ import annotations

from typing import Callable, Sequence

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from .collectives import ppermute  # eager GL001-validated collective
from .mesh import shard_map  # version-compat import, one home

__all__ = ["spmd_pipeline", "pipeline_apply", "stack_stage_params",
           "stage_congruence_mismatch"]


def stage_congruence_mismatch(first, stage, idx):
    """Shared congruence check for uniform-stage SPMD pipelining (used
    by :func:`stack_stage_params` and ``TrainStep._collect_pipeline``).

    ``first``/``stage``: per-parameter ``(shape, dtype)`` signatures of
    stage 0 and stage ``idx``.  Returns a human reason string when the
    stages are not structurally congruent, else None.
    """
    if len(stage) != len(first):
        return ("stage 0 has %d params, stage %d has %d"
                % (len(first), idx, len(stage)))
    for i, (a, b) in enumerate(zip(first, stage)):
        if tuple(a[0]) != tuple(b[0]) or a[1] != b[1]:
            return ("stage %d param %d is %s%s; stage 0 has %s%s"
                    % (idx, i, b[1], tuple(b[0]), a[1], tuple(a[0])))
    return None


def spmd_pipeline(stage_fn: Callable, stage_params, microbatches,
                  axis_name="pp", remat=False):
    """Run a uniform-stage pipeline inside shard_map.  Differentiable.

    stage_fn(params, x) -> y with y.shape == x.shape (uniform widths).
    stage_params: this device's stage parameters (already sharded).
    microbatches: (num_micro, mb, feat) — identical on every stage (stage 0
    consumes them; later stages consume ppermuted activations).
    remat: checkpoint ``stage_fn`` so the backward ticks recompute stage
    activations from stashed stage inputs instead of stashing every
    intermediate (GPipe stash → 1F1B-style memory profile).
    Returns (num_micro, mb, feat) — the final-stage outputs (valid on every
    device via a masked psum broadcast).
    """
    n = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    num_micro = microbatches.shape[0]
    steps = num_micro + n - 1
    perm = [(i, i + 1) for i in range(n - 1)]
    fn = jax.checkpoint(stage_fn) if remat else stage_fn

    buf0 = jnp.zeros_like(microbatches[0])
    outs0 = jnp.zeros_like(microbatches)
    try:
        buf0 = lax.pcast(buf0, (axis_name,), to="varying")
        outs0 = lax.pcast(outs0, (axis_name,), to="varying")
    except AttributeError:
        pass

    def body(carry, t):
        buf, outs = carry
        inject = microbatches[jnp.clip(t, 0, num_micro - 1)]
        x = jnp.where(idx == 0, inject, buf)
        y = fn(stage_params, x)
        # stage 0 only computes for t < num_micro; stage s for s <= t < s+num_micro
        active = (t >= idx) & (t < idx + num_micro)
        y = jnp.where(active, y, buf)
        out_slot = jnp.clip(t - (n - 1), 0, num_micro - 1)
        is_out = (idx == n - 1) & (t >= n - 1)
        outs = outs.at[out_slot].set(jnp.where(is_out, y, outs[out_slot]))
        buf = ppermute(y, axis_name, perm)
        return (buf, outs), None

    # scan (not fori_loop): the transpose of this scan IS the backward
    # pipeline schedule — reversed ticks, inverted ppermute, per-rank
    # gradient accumulation in the transpose carry
    (_, outs), _ = lax.scan(body, (buf0, outs0), jnp.arange(steps))
    # broadcast final-stage outputs to all stages (masked psum)
    outs = jnp.where(idx == n - 1, outs, jnp.zeros_like(outs))
    return lax.psum(outs, axis_name)


def stack_stage_params(stage_param_lists: Sequence[Sequence]):
    """Stack per-stage parameter lists along a new leading (pp) axis.

    ``stage_param_lists[s][i]`` is stage ``s``'s i-th parameter array;
    stages must be structurally congruent (same count, shapes, dtypes) —
    the SPMD pipeline runs ONE stage program with per-rank parameter
    values, so heterogeneous stages cannot be expressed.  Returns a list
    of ``(num_stages, *param_shape)`` arrays.
    """
    first = stage_param_lists[0]
    sig0 = [(tuple(a.shape), a.dtype) for a in first]
    for s, plist in enumerate(stage_param_lists[1:], 1):
        reason = stage_congruence_mismatch(
            sig0, [(tuple(b.shape), b.dtype) for b in plist], s)
        if reason:
            raise ValueError(
                "pipeline stages must be structurally identical "
                "(congruent): %s" % reason)
    return [jnp.stack([plist[i] for plist in stage_param_lists])
            for i in range(len(first))]


def pipeline_apply(stage_fn, all_stage_params, x, mesh: Mesh, num_micro=4,
                   axis_name="pp", remat=False):
    """Host-level: shard stage params over pp (leading axis) and run the
    pipeline on batch ``x`` split into ``num_micro`` microbatches."""
    assert x.shape[0] % num_micro == 0
    micro = x.reshape((num_micro, x.shape[0] // num_micro) + x.shape[1:])

    def inner(params, mb):
        params = jax.tree.map(lambda p: p[0], params)  # local stage slice
        return spmd_pipeline(stage_fn, params, mb, axis_name, remat=remat)

    pspec = P(axis_name)
    mapped = shard_map(inner, mesh=mesh,
                       in_specs=(jax.tree.map(lambda _: pspec,
                                              all_stage_params), P()),
                       out_specs=P())
    out = jax.jit(mapped)(all_stage_params, micro)
    return out.reshape((-1,) + out.shape[2:])
