"""Pipeline parallelism (GPipe-style) over a ``pp`` mesh axis.

The reference's only model parallelism is manual ``group2ctx`` layer
placement with engine-inserted copies (SURVEY.md §2.5).  TPU-native: stages
are sharded over the ``pp`` axis inside one SPMD program; activations flow
stage→stage via ``lax.ppermute`` (ICI neighbor hop) in a software-pipelined
schedule of ``num_micro + num_stages - 1`` ticks.
"""
from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax import shard_map
from jax.sharding import Mesh, PartitionSpec as P

__all__ = ["spmd_pipeline", "pipeline_apply"]


def spmd_pipeline(stage_fn: Callable, stage_params, microbatches,
                  axis_name="pp"):
    """Run a uniform-stage pipeline inside shard_map.

    stage_fn(params, x) -> y with y.shape == x.shape (uniform widths).
    stage_params: this device's stage parameters (already sharded).
    microbatches: (num_micro, mb, feat) — identical on every stage (stage 0
    consumes them; later stages consume ppermuted activations).
    Returns (num_micro, mb, feat) — the final-stage outputs (valid on every
    device via a masked psum broadcast).
    """
    n = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    num_micro = microbatches.shape[0]
    steps = num_micro + n - 1
    perm = [(i, i + 1) for i in range(n - 1)]

    buf0 = jnp.zeros_like(microbatches[0])
    outs0 = jnp.zeros_like(microbatches)
    try:
        buf0 = lax.pcast(buf0, (axis_name,), to="varying")
        outs0 = lax.pcast(outs0, (axis_name,), to="varying")
    except AttributeError:
        pass

    def body(t, carry):
        buf, outs = carry
        inject = microbatches[jnp.clip(t, 0, num_micro - 1)]
        x = jnp.where(idx == 0, inject, buf)
        y = stage_fn(stage_params, x)
        # stage 0 only computes for t < num_micro; stage s for s <= t < s+num_micro
        active = (t >= idx) & (t < idx + num_micro)
        y = jnp.where(active, y, buf)
        out_slot = jnp.clip(t - (n - 1), 0, num_micro - 1)
        is_out = (idx == n - 1) & (t >= n - 1)
        outs = outs.at[out_slot].set(jnp.where(is_out, y, outs[out_slot]))
        buf = lax.ppermute(y, axis_name, perm)
        return buf, outs

    _, outs = lax.fori_loop(0, steps, body, (buf0, outs0))
    # broadcast final-stage outputs to all stages (masked psum)
    outs = jnp.where(idx == n - 1, outs, jnp.zeros_like(outs))
    return lax.psum(outs, axis_name)


def pipeline_apply(stage_fn, all_stage_params, x, mesh: Mesh, num_micro=4,
                   axis_name="pp"):
    """Host-level: shard stage params over pp (leading axis) and run the
    pipeline on batch ``x`` split into ``num_micro`` microbatches."""
    assert x.shape[0] % num_micro == 0
    micro = x.reshape((num_micro, x.shape[0] // num_micro) + x.shape[1:])

    def inner(params, mb):
        params = jax.tree.map(lambda p: p[0], params)  # local stage slice
        return spmd_pipeline(stage_fn, params, mb, axis_name)

    pspec = P(axis_name)
    mapped = shard_map(inner, mesh=mesh,
                       in_specs=(jax.tree.map(lambda _: pspec,
                                              all_stage_params), P()),
                       out_specs=P())
    out = jax.jit(mapped)(all_stage_params, micro)
    return out.reshape((-1,) + out.shape[2:])
