"""Multi-process (multi-host) bootstrap for elastic training.

The reference framework's distributed substrate is ps-lite: a scheduler
process rendezvouses N workers and ``tools/launch.py`` exports the
``DMLC_*`` environment that names it (SURVEY.md §2.9).  The TPU-native
substrate is ``jax.distributed``: every process dials the coordinator
(process 0), after which ``jax.devices()`` returns the GLOBAL device
list and one GSPMD program spans all hosts.  This module is the one
home for that bootstrap plus the process-topology helpers the elastic
checkpoint layer (``parallel/checkpoint.py``) builds on:

- :func:`initialize` — idempotent rendezvous from explicit args or the
  ``DMLC_*`` launcher environment (same contract ``kvstore/dist.py``
  has always consumed; that module now delegates here);
- :func:`barrier` — a named cross-process sync point;
- :func:`make_process_mesh` — a process-spanning ``dp×pp×...`` mesh
  with a deterministic global device order, so every process builds
  the IDENTICAL mesh object;
- :func:`resplit_iter_state` — the elastic data-stream half: re-split
  the PR-5 per-process iterator states saved at N data shards onto M
  restarted processes (reusing the ``part_index``/``num_parts``
  stamping), refusing loudly when the parts have diverged.

Everything is importable and callable in a plain single-process run:
``initialize`` is a no-op at world size 1, ``barrier`` returns
immediately, and ``make_process_mesh`` degrades to ``make_mesh``.
"""
from __future__ import annotations

import os
from typing import Dict, Optional, Sequence

import jax

from .mesh import Mesh, global_devices, make_mesh

__all__ = ["DistributedInitError", "barrier", "collectives_supported",
           "initialize", "is_initialized", "make_process_mesh",
           "process_count", "process_index", "resplit_iter_state"]


class DistributedInitError(RuntimeError):
    """The multi-process rendezvous failed (coordinator unreachable,
    world-size/rank mismatch, double-init with different topology)."""


_INITIALIZED = False
_BARRIER_COUNT = 0


def _env_world() -> int:
    return int(os.environ.get("DMLC_NUM_WORKER", "1"))


def _raw_initialize(coordinator: str, num_processes: int, rank: int,
                    timeout: Optional[float]) -> None:
    """The actual ``jax.distributed.initialize`` call — module-level so
    the fault harness (``fault_injection.coordinator_unreachable``) can
    interpose a failing coordinator without real sockets/timeouts."""
    kwargs = {}
    if timeout is not None:
        kwargs["initialization_timeout"] = int(timeout)
    jax.distributed.initialize(coordinator_address=coordinator,
                               num_processes=num_processes,
                               process_id=rank, **kwargs)


def is_initialized() -> bool:
    """True once this process has rendezvoused with its peers."""
    return _INITIALIZED


def process_index() -> int:
    """This process's rank (0 in a single-process run)."""
    return jax.process_index() if _INITIALIZED else 0


def process_count() -> int:
    """World size (1 in a single-process run)."""
    return jax.process_count() if _INITIALIZED else 1


def initialize(coordinator: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None,
               timeout: Optional[float] = None) -> int:
    """Rendezvous this process with its peers (idempotent).

    Arguments default to the ``DMLC_*`` environment exported by
    ``tools/launch.py`` (the reference launcher contract:
    ``DMLC_PS_ROOT_URI``/``PORT`` name the coordinator,
    ``DMLC_NUM_WORKER`` the world size, ``DMLC_WORKER_ID`` this rank).
    Returns the world size.  A world size of 1 returns immediately
    WITHOUT latching, so a later call with a real topology still works.

    Failures surface as :class:`DistributedInitError` naming the
    coordinator and rank — the raw backend error (a gRPC deadline, a
    refused connection) rides along as ``__cause__``.
    """
    global _INITIALIZED
    if _INITIALIZED:
        return jax.process_count()
    num_processes = num_processes if num_processes is not None \
        else _env_world()
    if num_processes <= 1:
        return 1
    if coordinator is None:
        uri = os.environ.get("DMLC_PS_ROOT_URI", "127.0.0.1")
        port = os.environ.get("DMLC_PS_ROOT_PORT", "9091")
        coordinator = "%s:%s" % (uri, port)
    rank = process_id if process_id is not None else int(
        os.environ.get("DMLC_WORKER_ID", "0"))
    try:
        _raw_initialize(coordinator, int(num_processes), int(rank), timeout)
    except Exception as e:
        raise DistributedInitError(
            "jax.distributed rendezvous failed: process %d/%d could not "
            "join coordinator %s (%s).  Check that the coordinator "
            "process is up, the DMLC_* environment matches on every "
            "host, and no stale process holds the port."
            % (rank, num_processes, coordinator, e)) from e
    _INITIALIZED = True
    return int(num_processes)


def barrier(tag: Optional[str] = None) -> None:
    """Block until every process reaches this barrier (no-op at world
    size 1).  ``tag`` names the sync point in errors/traces; untagged
    barriers auto-number so two different call sites can never pair up
    with each other across processes."""
    global _BARRIER_COUNT
    if process_count() <= 1:
        return
    from jax.experimental import multihost_utils

    _BARRIER_COUNT += 1
    multihost_utils.sync_global_devices(
        "mxtpu_barrier_%s" % (tag or _BARRIER_COUNT))


_COLLECTIVES_OK: Optional[bool] = None


def collectives_supported() -> bool:
    """Whether the backend can COMPILE cross-process computations.

    Some CPU jaxlib builds rendezvous fine (``jax.distributed`` init,
    process indices, shared-filesystem protocols all work) but refuse
    multi-process programs ("Multiprocess computations aren't
    implemented on the CPU backend").  Probed once with a barrier and
    cached; trivially True at world size 1.  Callers that can degrade —
    per-process replicated training instead of one GSPMD program — use
    this to choose (``tests/elastic_worker.py``)."""
    global _COLLECTIVES_OK
    if process_count() <= 1:
        return True
    if _COLLECTIVES_OK is None:
        try:
            barrier("collectives-probe")
        except Exception:
            _COLLECTIVES_OK = False
        else:
            _COLLECTIVES_OK = True
    return _COLLECTIVES_OK


def make_process_mesh(axes: Dict[str, int],
                      devices: Optional[Sequence] = None) -> Mesh:
    """A process-spanning mesh over the GLOBAL device list.

    Like :func:`~.mesh.make_mesh` (``-1`` axis inference included) but
    the default device list is every process's devices in the
    deterministic ``(process_index, device id)`` order — so every
    process constructs the IDENTICAL mesh, which GSPMD requires for a
    multi-process program.  On a single process this is exactly
    ``make_mesh``.
    """
    if devices is None:
        devices = global_devices()
    return make_mesh(axes, devices=devices)


# ---------------------------------------------------------------------------
# elastic data-stream re-split
# ---------------------------------------------------------------------------

_PART_KEYS = ("part_index", "num_parts")


def _strip_part_stamps(state):
    """Copy of an iterator-state tree with every ``part_index``/
    ``num_parts`` stamp removed (recursively) — the part-invariant
    core two shards of the same stream must agree on."""
    if isinstance(state, dict):
        return {k: _strip_part_stamps(v) for k, v in state.items()
                if k not in _PART_KEYS}
    if isinstance(state, (list, tuple)):
        return [_strip_part_stamps(v) for v in state]
    return state


def _restamp_parts(state, part_index: int, num_parts: int):
    """Copy of an iterator-state tree with every dict that carries the
    part stamping re-stamped to the new shard identity."""
    if isinstance(state, dict):
        out = {k: _restamp_parts(v, part_index, num_parts)
               for k, v in state.items()}
        if all(k in state for k in _PART_KEYS):
            out["part_index"] = int(part_index)
            out["num_parts"] = int(num_parts)
        return out
    if isinstance(state, (list, tuple)):
        return [_restamp_parts(v, part_index, num_parts) for v in state]
    return state


def resplit_iter_state(parts: Dict, part_index: int, num_parts: int):
    """Re-split per-process iterator states saved at N data shards onto
    the ``part_index``-th of ``num_parts`` restarted shards.

    ``parts`` is the checkpoint's ``data_iter_parts`` section: saved
    rank (int or str — JSON keys) → that rank's ``state_dict()``.

    Policy (the docs/RESILIENCE.md re-shard matrix):

    - **same width** (``num_parts == len(parts)``): each restarted
      process takes its own saved part verbatim — nothing to re-split;
    - **changed width**: only possible when every saved part carries
      the SAME part-invariant state (identical epoch/cursor/RNG once
      the ``part_index``/``num_parts`` stamps are stripped) — i.e. the
      processes iterated replicated data, or a sharded reader at an
      epoch boundary.  The surviving state is re-stamped with the new
      shard identity.  Parts that have diverged (a sharded record
      reader mid-epoch: each shard holds different records and a
      different RNG) CANNOT be re-split bit-exactly, and this raises
      ``ValueError`` naming the saved-vs-requested split instead of
      silently replaying or skipping data.
    """
    if not parts:
        raise ValueError("no saved iterator parts to re-split")
    by_rank = {int(k): v for k, v in parts.items()}
    saved_n = len(by_rank)
    if sorted(by_rank) != list(range(saved_n)):
        raise ValueError(
            "saved iterator parts are not contiguous ranks: %r"
            % (sorted(by_rank),))
    if not 0 <= int(part_index) < int(num_parts):
        raise ValueError("part_index %d outside num_parts %d"
                         % (part_index, num_parts))
    if int(num_parts) == saved_n:
        return by_rank[int(part_index)]
    import json as _json

    cores = [_json.dumps(_strip_part_stamps(by_rank[r]), sort_keys=True)
             for r in range(saved_n)]
    if any(c != cores[0] for c in cores[1:]):
        diverged = [r for r in range(1, saved_n) if cores[r] != cores[0]]
        raise ValueError(
            "iterator state saved at num_parts=%d cannot be re-split to "
            "num_parts=%d: parts %s diverged from part 0 (a sharded "
            "record stream mid-epoch holds different records per part). "
            "Resume at the saved width, or restart the epoch with fresh "
            "iterators at the new width."
            % (saved_n, num_parts, diverged))
    return _restamp_parts(by_rank[0], int(part_index), int(num_parts))
