"""Atomic, shard-aware checkpoint/resume for fused training state.

The legacy container paths (``ndarray/utils.save``, ``Trainer.save_states``,
``Block.save_parameters``) assume a replicated, host-resident parameter
set.  PR 3's ZeRO-1 sharding broke that assumption: optimizer state is
dp-sharded and donated, so a naive save either gathers N× memory onto one
host or silently writes one rank's shard.  This module is the durable
half of the resilience layer (``docs/RESILIENCE.md``):

- **per-array manifest** — dtype, shape, sharding and a checksum per
  file, so restore can verify integrity *before* touching live state;
- **per-shard files** — a dp-sharded leaf (ZeRO-1 optimizer state) is
  written one file per distinct shard straight from its device buffer:
  no all-gather, no N× host spike;
- **atomic commit** — everything is written into a ``.tmp-step-*``
  staging directory, fsync'd, and published with ONE ``os.replace``;
  a crash mid-save leaves the previous checkpoint untouched;
- **last-good fallback** — restore walks back to the newest intact
  checkpoint when the latest fails checksum/manifest validation;
- **bounded retry** — transient ``OSError`` s on reads/writes retry
  with exponential backoff before giving up;
- **preemption hook** — SIGTERM flips a flag; the train step saves at
  the next step boundary (``TrainStep.attach_checkpoint``).

Array payloads are raw little-endian bytes (``ndarray.tobytes``) rather
than ``.npy``: it round-trips every dtype jax uses (including bfloat16
via ml_dtypes) and keeps checksumming trivial.
"""
from __future__ import annotations

import json
import os
import shutil
import signal
import time
import warnings
import zlib
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

__all__ = ["CheckpointError", "CheckpointCorruptError", "CheckpointManager",
           "checkpoint_requested", "install_preemption_hook",
           "request_checkpoint", "request_seq"]

_FORMAT_VERSION = 1
_MANIFEST = "manifest.json"
_STEP_FMT = "step-%08d"
_TMP_PREFIX = ".tmp-"
_DISCARD_PREFIX = ".discard-"


class CheckpointError(RuntimeError):
    """No usable checkpoint (nothing saved yet, or every candidate is
    corrupt)."""


class CheckpointCorruptError(CheckpointError):
    """A specific checkpoint failed integrity validation: missing file,
    unparseable/mismatched manifest, or checksum mismatch."""


# ---------------------------------------------------------------------------
# integrity + I/O primitives (the fault-injection patch points)
# ---------------------------------------------------------------------------

def _checksum(data: bytes) -> str:
    """``"algo:hex"`` over the payload.  crc32c (Castagnoli) when the
    optional ``crc32c`` module is present, else zlib's crc32 — the algo
    rides the manifest so verification always recomputes the same one."""
    try:
        import crc32c  # type: ignore

        return "crc32c:%08x" % (crc32c.crc32c(data) & 0xFFFFFFFF)
    except ImportError:
        return "crc32:%08x" % (zlib.crc32(data) & 0xFFFFFFFF)


def _verify_checksum(data: bytes, recorded: str,
                     fallback_crc32: Optional[str] = None) -> bool:
    """Verify against the recorded primary checksum; when its algorithm
    is unavailable here (checkpoint written where ``crc32c`` was
    installed, restored where it is not), fall back to the plain-crc32
    digest every manifest also records — intact data must never be
    rejected just because an optional module is missing."""
    algo, _, hexval = recorded.partition(":")
    if algo == "crc32":
        return ("%08x" % (zlib.crc32(data) & 0xFFFFFFFF)) == hexval
    if algo == "crc32c":
        try:
            import crc32c  # type: ignore
        except ImportError:
            if fallback_crc32 is not None:
                return ("%08x" % (zlib.crc32(data) & 0xFFFFFFFF)) \
                    == fallback_crc32
            return False  # nothing verifiable -> fail safe
        return ("%08x" % (crc32c.crc32c(data) & 0xFFFFFFFF)) == hexval
    return False


def _write_bytes(path: str, data: bytes) -> None:
    """Write + flush + fsync one file.  Module-level so the fault
    harness (``parallel/fault_injection.py``) can interpose failures."""
    with open(path, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())


def _read_bytes(path: str) -> bytes:
    with open(path, "rb") as f:
        return f.read()


def _fsync_dir(path: str) -> None:
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _with_retries(fn, retries: int, backoff: float, what: str):
    """Run ``fn`` retrying transient ``OSError`` s with exponential
    backoff; the LAST failure propagates."""
    for attempt in range(retries + 1):
        try:
            return fn()
        except OSError:
            if attempt == retries:
                raise
            time.sleep(backoff * (2 ** attempt))


# ---------------------------------------------------------------------------
# leaf (de)serialization
# ---------------------------------------------------------------------------

def _distinct_shards(leaf) -> Optional[List[Any]]:
    """The distinct device shards of a jax.Array, or None when the leaf
    is effectively replicated (every device holds the full value — one
    file suffices).  On a dp×pp mesh a P('dp') leaf has one shard per
    device but only ``dp`` distinct indices; duplicates are dropped so
    each unique shard is written exactly once."""
    shards = getattr(leaf, "addressable_shards", None)
    if not shards or len(shards) < 2:
        return None
    seen: Dict[Tuple, Any] = {}
    for s in shards:
        key = tuple((sl.start, sl.stop, sl.step) for sl in s.index)
        seen.setdefault(key, s)
    if len(seen) < 2:
        return None
    return sorted(seen.values(),
                  key=lambda s: tuple(sl.start or 0 for sl in s.index))


def _index_to_json(index) -> List[List[Optional[int]]]:
    return [[sl.start, sl.stop] for sl in index]


def _index_from_json(spec, shape) -> Tuple[slice, ...]:
    return tuple(slice(lo, hi) for (lo, hi) in spec)


def _leaf_np(x) -> np.ndarray:
    return np.asarray(x)


# ---------------------------------------------------------------------------
# the manager
# ---------------------------------------------------------------------------

class CheckpointManager:
    """Atomic checkpoints of an arbitrary pytree of arrays under one
    directory, newest-intact-wins restore.

    ``save(step, state)`` stages every leaf (sharded leaves one file per
    distinct shard, straight from the device buffers), writes the
    manifest last, fsyncs, and commits with a single atomic rename —
    then retires checkpoints beyond ``keep_last``.  ``restore(like)``
    validates checksums/manifest and falls back to the next-older
    checkpoint on corruption.  ``retries``/``backoff`` bound the
    retry-with-backoff loop around every file read/write.
    """

    def __init__(self, directory: str, keep_last: int = 3, retries: int = 2,
                 backoff: float = 0.05):
        self.directory = str(directory)
        if keep_last is not None and int(keep_last) < 1:
            raise ValueError("keep_last must be >= 1 or None, got %r"
                             % (keep_last,))
        self.keep_last = None if keep_last is None else int(keep_last)
        self.retries = int(retries)
        self.backoff = float(backoff)

    # -- layout ---------------------------------------------------------
    def _step_dir(self, step: int) -> str:
        return os.path.join(self.directory, _STEP_FMT % step)

    def steps(self) -> List[int]:
        """Committed step numbers, ascending."""
        if not os.path.isdir(self.directory):
            return []
        out = []
        for name in os.listdir(self.directory):
            if name.startswith("step-"):
                try:
                    out.append(int(name[5:]))
                except ValueError:
                    continue
        return sorted(out)

    def latest_step(self) -> Optional[int]:
        steps = self.steps()
        return steps[-1] if steps else None

    # -- save -----------------------------------------------------------
    def save(self, step: int, state, meta: Optional[Dict] = None) -> str:
        """Stage + atomically commit ``state`` as checkpoint ``step``.
        Returns the committed directory path.

        ``meta`` — optional JSON-safe dict committed atomically with the
        arrays (it rides the manifest, which is written last).  Used for
        non-array sidecar state like the data-iterator position
        (``TrainStep.save_checkpoint(data_iter=...)``); older manifests
        without it restore fine (backward-compatible section)."""
        step = int(step)
        flat, _ = jax.tree_util.tree_flatten_with_path(state)
        os.makedirs(self.directory, exist_ok=True)
        tmp = os.path.join(self.directory, _TMP_PREFIX + (_STEP_FMT % step))
        final = self._step_dir(step)
        self._sweep_stale()
        os.makedirs(tmp)
        try:
            entries = []
            for i, (path, leaf) in enumerate(flat):
                entries.append(self._save_leaf(
                    tmp, "arr_%05d" % i, jax.tree_util.keystr(path), leaf))
            manifest = {"format_version": _FORMAT_VERSION, "step": step,
                        "arrays": entries}
            if meta is not None:
                manifest["meta"] = meta
            # the manifest commits the content of the staging dir: it is
            # written LAST, so a torn stage never looks complete
            buf = json.dumps(manifest, indent=1).encode()
            _with_retries(
                lambda: _write_bytes(os.path.join(tmp, _MANIFEST), buf),
                self.retries, self.backoff, _MANIFEST)
            _fsync_dir(tmp)
            discard = None
            committed = False
            try:
                if os.path.isdir(final):
                    # re-saving the same step: move the committed dir
                    # ASIDE (never delete it before the new one is
                    # committed — a crash here leaves the data on disk,
                    # and every OTHER checkpoint untouched)
                    discard = os.path.join(
                        self.directory, _DISCARD_PREFIX + (_STEP_FMT % step))
                    shutil.rmtree(discard, ignore_errors=True)
                    os.replace(final, discard)
                os.replace(tmp, final)  # THE commit point
                committed = True
            finally:
                if discard is not None and os.path.isdir(discard):
                    if committed:
                        shutil.rmtree(discard, ignore_errors=True)
                    elif not os.path.isdir(final):
                        # the commit rename failed after the old dir
                        # moved aside: roll it back so the previously
                        # committed checkpoint is still restorable
                        os.replace(discard, final)
            _fsync_dir(self.directory)
        except BaseException:
            shutil.rmtree(tmp, ignore_errors=True)
            raise
        self._retire()
        return final

    def _sweep_stale(self):
        """Remove staging/discard debris from crashed earlier saves.
        Runs at save time: the manager is single-writer per directory,
        so anything with a tmp/discard prefix is an orphan by now —
        without this, every hard kill mid-save would leak one
        full-state-sized directory forever."""
        for name in os.listdir(self.directory):
            if name.startswith(_TMP_PREFIX) or \
                    name.startswith(_DISCARD_PREFIX):
                shutil.rmtree(os.path.join(self.directory, name),
                              ignore_errors=True)

    def _save_leaf(self, tmp: str, name: str, key: str, leaf) -> Dict:
        dtype = np.dtype(getattr(leaf, "dtype", None)
                         or np.asarray(leaf).dtype)
        shape = list(np.shape(leaf))
        sharding = getattr(leaf, "sharding", None)
        spec = getattr(sharding, "spec", None)
        entry = {"key": key, "dtype": dtype.name, "shape": shape,
                 "spec": None if spec is None else str(spec), "files": []}
        shards = _distinct_shards(leaf) if isinstance(leaf, jax.Array) \
            else None
        if shards is None:
            # replicated / host leaf: one device->host copy, one file
            data = _leaf_np(leaf).tobytes()
            entry["files"].append(self._write_payload(
                tmp, name + ".bin", data, index=None, part_shape=shape))
        else:
            # sharded leaf (ZeRO-1 state): each distinct shard straight
            # off its device buffer — never gathered
            for k, s in enumerate(shards):
                part = _leaf_np(s.data)
                entry["files"].append(self._write_payload(
                    tmp, "%s.shard%03d.bin" % (name, k), part.tobytes(),
                    index=_index_to_json(s.index),
                    part_shape=list(part.shape)))
        return entry

    def _write_payload(self, tmp, fname, data, index, part_shape) -> Dict:
        _with_retries(
            lambda: _write_bytes(os.path.join(tmp, fname), data),
            self.retries, self.backoff, fname)
        return {"file": fname, "checksum": _checksum(data),
                # always-verifiable fallback digest (see _verify_checksum)
                "crc32": "%08x" % (zlib.crc32(data) & 0xFFFFFFFF),
                "nbytes": len(data), "index": index,
                "part_shape": part_shape}

    def _retire(self):
        if self.keep_last is None:
            return
        steps = self.steps()
        for s in steps[:-self.keep_last]:
            shutil.rmtree(self._step_dir(s), ignore_errors=True)

    # -- restore --------------------------------------------------------
    def restore(self, like, step: Optional[int] = None, shardings=None,
                return_meta: bool = False):
        """Load the newest intact checkpoint (or exactly ``step``) into
        the structure of ``like``; returns ``(step, state)`` — or
        ``(step, state, meta)`` with ``return_meta=True``, where
        ``meta`` is the manifest's sidecar dict (``None`` for
        checkpoints written without one).

        ``shardings`` — an optional pytree congruent with ``like`` whose
        leaves are placements (``NamedSharding``/device) — puts every
        restored leaf straight back on its training layout.  Corrupt
        candidates are skipped with a warning (last-good fallback)
        unless ``step`` pinned one explicitly.
        """
        def pack(s, loaded):
            state, meta = loaded
            return (s, state, meta) if return_meta else (s, state)

        if step is not None:
            return pack(int(step), self._load(int(step), like, shardings))
        candidates = list(reversed(self.steps()))
        if not candidates:
            raise CheckpointError(
                "no checkpoints under %r" % self.directory)
        last_err: Optional[Exception] = None
        for s in candidates:
            try:
                return pack(s, self._load(s, like, shardings))
            except CheckpointCorruptError as e:
                warnings.warn(
                    "checkpoint %s is corrupt (%s); falling back to the "
                    "previous one" % (_STEP_FMT % s, e), stacklevel=2)
                last_err = e
        raise CheckpointError(
            "no intact checkpoint under %r (%d candidate(s), newest "
            "error: %s)" % (self.directory, len(candidates), last_err))

    def _load(self, step: int, like, shardings):
        d = self._step_dir(step)
        try:
            raw = _with_retries(
                lambda: _read_bytes(os.path.join(d, _MANIFEST)),
                self.retries, self.backoff, _MANIFEST)
            manifest = json.loads(raw.decode())
        except FileNotFoundError as e:
            raise CheckpointCorruptError("missing manifest: %s" % e)
        except (OSError, ValueError) as e:
            raise CheckpointCorruptError("unreadable manifest: %s" % e)
        if manifest.get("format_version") != _FORMAT_VERSION:
            raise CheckpointCorruptError(
                "manifest format_version %r != %d"
                % (manifest.get("format_version"), _FORMAT_VERSION))
        flat, treedef = jax.tree_util.tree_flatten_with_path(like)
        entries = manifest.get("arrays", [])
        if len(entries) != len(flat):
            raise CheckpointCorruptError(
                "manifest has %d arrays, expected %d (training state "
                "structure changed?)" % (len(entries), len(flat)))
        flat_sh: List[Any] = [None] * len(flat)
        if shardings is not None:
            sh_flat, sh_def = jax.tree_util.tree_flatten_with_path(shardings)
            if len(sh_flat) != len(flat):
                raise ValueError("shardings tree is not congruent with "
                                 "the state tree")
            flat_sh = [s for _, s in sh_flat]
        leaves = []
        for (path, _), entry, sh in zip(flat, entries, flat_sh):
            key = jax.tree_util.keystr(path)
            if entry.get("key") != key:
                raise CheckpointCorruptError(
                    "manifest entry %r does not match state leaf %r"
                    % (entry.get("key"), key))
            try:
                leaves.append(self._load_leaf(d, entry, sh))
            except CheckpointCorruptError:
                raise
            except (KeyError, IndexError, TypeError, ValueError) as e:
                # manifest content that parses as JSON but decodes to
                # garbage (mangled dtype name, wrong part_shape/index):
                # corruption, not a caller error — the last-good
                # fallback in restore() must still engage
                raise CheckpointCorruptError(
                    "undecodable manifest entry %r: %s" % (key, e))
        return (jax.tree_util.tree_unflatten(treedef, leaves),
                manifest.get("meta"))

    def _load_leaf(self, d: str, entry: Dict, sharding):
        dtype = np.dtype(entry["dtype"])
        shape = tuple(entry["shape"])
        files = entry["files"]
        if len(files) == 1 and files[0].get("index") is None:
            arr = self._read_part(d, files[0], dtype).reshape(shape)
        else:
            arr = np.empty(shape, dtype)
            for f in files:
                part = self._read_part(d, f, dtype) \
                    .reshape(tuple(f["part_shape"]))
                arr[_index_from_json(f["index"], shape)] = part
        if sharding is not None:
            return jax.device_put(arr, sharding)
        return jnp.asarray(arr)

    def _read_part(self, d: str, f: Dict, dtype) -> np.ndarray:
        path = os.path.join(d, f["file"])
        try:
            buf = _with_retries(lambda: _read_bytes(path),
                                self.retries, self.backoff, f["file"])
        except FileNotFoundError as e:
            raise CheckpointCorruptError("missing array file: %s" % e)
        if len(buf) != int(f["nbytes"]):
            raise CheckpointCorruptError(
                "%s: %d bytes on disk, manifest says %d (torn write?)"
                % (f["file"], len(buf), f["nbytes"]))
        if not _verify_checksum(buf, f["checksum"], f.get("crc32")):
            raise CheckpointCorruptError(
                "%s: checksum mismatch (%s)" % (f["file"], f["checksum"]))
        return np.frombuffer(buf, dtype)


# ---------------------------------------------------------------------------
# preemption: SIGTERM -> checkpoint at the next step boundary
# ---------------------------------------------------------------------------

# monotonically increasing request sequence (incrementing an int is
# atomic under the GIL, safe from a signal handler).  Each consumer
# (TrainStep._maybe_checkpoint) remembers the last sequence it honored,
# so ONE request reaches EVERY attached step loop — a global clear
# would let the first loop to hit a boundary steal the request from
# the others.
_CKPT_SEQ = 0


def request_checkpoint() -> None:
    """Ask every step loop with an attached manager to checkpoint at its
    next step boundary (what the SIGTERM hook calls)."""
    global _CKPT_SEQ
    _CKPT_SEQ += 1


def request_seq() -> int:
    """Current request sequence number (consumers compare-and-store)."""
    return _CKPT_SEQ


def checkpoint_requested(since: int = 0) -> bool:
    """True when a checkpoint request newer than ``since`` is pending."""
    return _CKPT_SEQ > since


def install_preemption_hook(signals=(signal.SIGTERM,), chain=True):
    """Install handlers that flip the checkpoint-request flag on
    preemption signals (must run on the main thread).  The handler is
    async-signal-light — it only sets an event; the actual save happens
    at the next step boundary on the training thread, where device
    state is consistent.  ``chain=True`` forwards to any previously
    installed handler.  Returns ``{signum: previous_handler}``."""
    previous = {}

    def _handler(signum, frame):
        request_checkpoint()
        prev = previous.get(signum)
        if chain and callable(prev):
            prev(signum, frame)

    for s in signals:
        previous[s] = signal.signal(s, _handler)
    return previous
