"""Atomic, shard-aware checkpoint/resume for fused training state.

The legacy container paths (``ndarray/utils.save``, ``Trainer.save_states``,
``Block.save_parameters``) assume a replicated, host-resident parameter
set.  PR 3's ZeRO-1 sharding broke that assumption: optimizer state is
dp-sharded and donated, so a naive save either gathers N× memory onto one
host or silently writes one rank's shard.  This module is the durable
half of the resilience layer (``docs/RESILIENCE.md``):

- **per-array manifest** — dtype, shape, sharding and a checksum per
  file, so restore can verify integrity *before* touching live state;
- **per-shard files** — a dp-sharded leaf (ZeRO-1 optimizer state) is
  written one file per distinct shard straight from its device buffer:
  no all-gather, no N× host spike;
- **atomic commit** — everything is written into a ``.tmp-step-*``
  staging directory, fsync'd, and published with ONE ``os.replace``;
  a crash mid-save leaves the previous checkpoint untouched;
- **last-good fallback** — restore walks back to the newest intact
  checkpoint when the latest fails checksum/manifest validation;
- **bounded retry** — transient ``OSError`` s on reads/writes retry
  with exponential backoff before giving up;
- **preemption hook** — SIGTERM flips a flag; the train step saves at
  the next step boundary (``TrainStep.attach_checkpoint``).

Array payloads are raw little-endian bytes (``ndarray.tobytes``) rather
than ``.npy``: it round-trips every dtype jax uses (including bfloat16
via ml_dtypes) and keeps checksumming trivial.

**Multi-process (multi-host) checkpoints.**  When the manager detects a
``jax.distributed`` world (or is constructed with ``process_count>1``)
it runs a coordinated commit protocol over the shared directory:

1. every process stages only the shards IT owns (lowest-ranked owning
   process per distinct shard — nothing is written twice, nothing is
   gathered) into the shared ``.tmp-step-N/``;
2. each process then writes a ``done-pNNNNN.json`` marker carrying its
   file list + checksums (and its per-process ``meta``, e.g. the data
   iterator state), fsyncs;
3. process 0 waits for every marker, verifies the merged shard set
   covers every array completely, writes the SINGLE ``manifest.json``
   last, and publishes with the same atomic rename — so a half-written
   multi-host checkpoint (a host died mid-save) is **never visible**:
   ``steps()`` only ever lists committed directories;
4. the other processes block until the commit appears (bounded by
   ``commit_timeout``) so a save returning means the checkpoint is
   durable on every host.

**Elastic restore.**  ``restore(like, elastic=...)`` accepts a policy
pytree marking which leaves may be re-shaped across a topology change:
a leaf marked with its LOGICAL leading dim (a ZeRO-1 optimizer-state
leaf padded to a multiple of the saved dp width) is re-sliced to the
logical rows and re-padded to the restoring width — so a checkpoint
saved at ``dp=N`` restores onto a ``dp=M`` mesh.  Every other shape
mismatch raises :class:`CheckpointTopologyError` naming the saved and
current topologies (never the corrupt-fallback path: a topology
mismatch is a configuration condition, not bit rot).
"""
from __future__ import annotations

import json
import os
import random
import shutil
import signal
import time
import warnings
import zlib
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

__all__ = ["CheckpointError", "CheckpointCorruptError",
           "CheckpointTopologyError", "CheckpointManager",
           "checkpoint_requested", "install_preemption_hook",
           "request_checkpoint", "request_seq",
           "uninstall_preemption_hook"]

_FORMAT_VERSION = 1
_MANIFEST = "manifest.json"
_STEP_FMT = "step-%08d"
_TMP_PREFIX = ".tmp-"
_DISCARD_PREFIX = ".discard-"
_DONE_FMT = "done-p%05d.json"


class CheckpointError(RuntimeError):
    """No usable checkpoint (nothing saved yet, or every candidate is
    corrupt)."""


class CheckpointCorruptError(CheckpointError):
    """A specific checkpoint failed integrity validation: missing file,
    unparseable/mismatched manifest, or checksum mismatch."""


class CheckpointTopologyError(CheckpointError):
    """The checkpoint is intact but was saved under a training topology
    (mesh widths, pipeline stages, data split) this run cannot re-shard
    onto.  Deliberately NOT a :class:`CheckpointCorruptError`: restore
    must refuse immediately with the two topologies named, not walk
    back to an older checkpoint with the same mismatch."""


# ---------------------------------------------------------------------------
# integrity + I/O primitives (the fault-injection patch points)
# ---------------------------------------------------------------------------

def _checksum(data: bytes) -> str:
    """``"algo:hex"`` over the payload.  crc32c (Castagnoli) when the
    optional ``crc32c`` module is present, else zlib's crc32 — the algo
    rides the manifest so verification always recomputes the same one."""
    try:
        import crc32c  # type: ignore

        return "crc32c:%08x" % (crc32c.crc32c(data) & 0xFFFFFFFF)
    except ImportError:
        return "crc32:%08x" % (zlib.crc32(data) & 0xFFFFFFFF)


def _verify_checksum(data: bytes, recorded: str,
                     fallback_crc32: Optional[str] = None) -> bool:
    """Verify against the recorded primary checksum; when its algorithm
    is unavailable here (checkpoint written where ``crc32c`` was
    installed, restored where it is not), fall back to the plain-crc32
    digest every manifest also records — intact data must never be
    rejected just because an optional module is missing."""
    algo, _, hexval = recorded.partition(":")
    if algo == "crc32":
        return ("%08x" % (zlib.crc32(data) & 0xFFFFFFFF)) == hexval
    if algo == "crc32c":
        try:
            import crc32c  # type: ignore
        except ImportError:
            if fallback_crc32 is not None:
                return ("%08x" % (zlib.crc32(data) & 0xFFFFFFFF)) \
                    == fallback_crc32
            return False  # nothing verifiable -> fail safe
        return ("%08x" % (crc32c.crc32c(data) & 0xFFFFFFFF)) == hexval
    return False


def _write_bytes(path: str, data: bytes) -> None:
    """Write + flush + fsync one file.  Module-level so the fault
    harness (``parallel/fault_injection.py``) can interpose failures."""
    with open(path, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())


def _read_bytes(path: str) -> bytes:
    with open(path, "rb") as f:
        return f.read()


def _fsync_dir(path: str) -> None:
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _with_retries(fn, retries: int, backoff: float, what: str):
    """Run ``fn`` retrying transient ``OSError`` s with exponential
    backoff; the LAST failure propagates.  The sleep is jittered
    (0.5–1.5× the nominal backoff): N processes of a preempted job all
    hit the shared filesystem at the same instant, and synchronized
    retries would re-collide every round (thundering herd)."""
    for attempt in range(retries + 1):
        try:
            return fn()
        except OSError:
            if attempt == retries:
                raise
            time.sleep(backoff * (2 ** attempt) * (0.5 + random.random()))


# ---------------------------------------------------------------------------
# leaf (de)serialization
# ---------------------------------------------------------------------------

def _distinct_shards(leaf) -> Optional[List[Any]]:
    """The distinct device shards of a jax.Array, or None when the leaf
    is effectively replicated (every device holds the full value — one
    file suffices).  On a dp×pp mesh a P('dp') leaf has one shard per
    device but only ``dp`` distinct indices; duplicates are dropped so
    each unique shard is written exactly once."""
    shards = getattr(leaf, "addressable_shards", None)
    if not shards or len(shards) < 2:
        return None
    seen: Dict[Tuple, Any] = {}
    for s in shards:
        key = tuple((sl.start, sl.stop, sl.step) for sl in s.index)
        seen.setdefault(key, s)
    if len(seen) < 2:
        return None
    return sorted(seen.values(),
                  key=lambda s: tuple(sl.start or 0 for sl in s.index))


def _index_to_json(index) -> List[List[Optional[int]]]:
    return [[sl.start, sl.stop] for sl in index]


def _index_from_json(spec, shape) -> Tuple[slice, ...]:
    return tuple(slice(lo, hi) for (lo, hi) in spec)


def _leaf_np(x) -> np.ndarray:
    return np.asarray(x)


def _topology_mismatch(saved: Dict, current: Dict) -> Optional[str]:
    """What — beyond an ELASTIC change — differs between two topology
    stamps (``TrainStep._topology()`` dicts).  Elastic changes, the
    ones restore re-shards by construction, are: the batch-axis (dp)
    width, the process count, and the ZeRO mode (state re-pads either
    way).  Everything else — pipeline staging, non-dp mesh axes, the
    batch axis name — changes the training program or the state layout
    in ways no re-shard covers, and must refuse."""
    for key in ("batch_axis", "pipeline_stages"):
        if saved.get(key) != current.get(key):
            return "%s %r != %r" % (key, saved.get(key), current.get(key))
    sm, cm = saved.get("mesh"), current.get("mesh")
    if (sm is None) != (cm is None):
        return "mesh %r != %r" % (sm, cm)
    if sm:
        if set(sm) != set(cm):
            return "mesh axes %s != %s" % (sorted(sm), sorted(cm))
        ba = current.get("batch_axis")
        for a in sorted(sm):
            if a != ba and sm[a] != cm[a]:
                return ("mesh axis %r width %s != %s (only the %r batch "
                        "axis re-shards elastically)" % (a, sm[a], cm[a],
                                                         ba))
    return None


# ---------------------------------------------------------------------------
# the manager
# ---------------------------------------------------------------------------

class CheckpointManager:
    """Atomic checkpoints of an arbitrary pytree of arrays under one
    directory, newest-intact-wins restore.

    ``save(step, state)`` stages every leaf (sharded leaves one file per
    distinct shard, straight from the device buffers), writes the
    manifest last, fsyncs, and commits with a single atomic rename —
    then retires checkpoints beyond ``keep_last``.  ``restore(like)``
    validates checksums/manifest and falls back to the next-older
    checkpoint on corruption.  ``retries``/``backoff`` bound the
    retry-with-backoff loop around every file read/write.

    ``process_index``/``process_count`` default to the live
    ``jax.distributed`` topology: in a multi-process world every
    process must call ``save``/``restore`` cooperatively on the SAME
    (shared-filesystem) directory, and the module docstring's
    marker-based commit protocol runs.  ``commit_timeout`` bounds how
    long any process waits for its peers at the commit point;
    ``stale_grace`` is how old (seconds since last write) staging
    debris or a retired step directory must be before a multi-process
    sweep may delete it — a peer's FRESH temp files are never deleted
    out from under it (single-process managers keep the original
    single-writer semantics: debris is swept unconditionally).
    """

    def __init__(self, directory: str, keep_last: int = 3, retries: int = 2,
                 backoff: float = 0.05, process_index: Optional[int] = None,
                 process_count: Optional[int] = None,
                 commit_timeout: float = 120.0, stale_grace: float = 300.0):
        self.directory = str(directory)
        if keep_last is not None and int(keep_last) < 1:
            raise ValueError("keep_last must be >= 1 or None, got %r"
                             % (keep_last,))
        self.keep_last = None if keep_last is None else int(keep_last)
        self.retries = int(retries)
        self.backoff = float(backoff)
        if process_count is None:
            # prefer the bootstrap module's latch (no backend touch);
            # fall back to jax for processes that called
            # jax.distributed.initialize directly
            from . import distributed as _dist

            if _dist.is_initialized():
                process_count = _dist.process_count()
            else:
                try:
                    process_count = jax.process_count()
                except Exception:
                    process_count = 1
        if process_index is None:
            process_index = jax.process_index() if int(process_count) > 1 \
                else 0
        self.process_index = int(process_index)
        self.process_count = int(process_count)
        if not 0 <= self.process_index < max(self.process_count, 1):
            raise ValueError("process_index %d outside process_count %d"
                             % (self.process_index, self.process_count))
        self.commit_timeout = float(commit_timeout)
        self.stale_grace = float(stale_grace)
        if self.process_count > 1:
            # GL009: a process-local directory cannot hold a coordinated
            # multi-process checkpoint — every process would commit a
            # private, incomplete copy (docs/ANALYSIS.md)
            from ..analysis.trace_lint import check_process_local_ckpt_dir

            for d in check_process_local_ckpt_dir(self.directory,
                                                  self.process_count):
                warnings.warn(d.format(), stacklevel=3)

    # -- layout ---------------------------------------------------------
    def _step_dir(self, step: int) -> str:
        return os.path.join(self.directory, _STEP_FMT % step)

    def steps(self) -> List[int]:
        """Committed step numbers, ascending."""
        if not os.path.isdir(self.directory):
            return []
        out = []
        for name in os.listdir(self.directory):
            if name.startswith("step-"):
                try:
                    out.append(int(name[5:]))
                except ValueError:
                    continue
        return sorted(out)

    def latest_step(self) -> Optional[int]:
        steps = self.steps()
        return steps[-1] if steps else None

    def _manifest_committed(self, step: int) -> bool:
        """True iff ``step``'s manifest exists, parses and carries the
        expected format version — the commit protocol writes it LAST,
        so a parseable manifest is the committed/torn discriminator."""
        try:
            with open(os.path.join(self._step_dir(step), _MANIFEST)) as f:
                manifest = json.load(f)
        except (OSError, ValueError):
            return False
        return manifest.get("format_version") == _FORMAT_VERSION

    def latest_committed(self) -> Optional[int]:
        """Newest step a consumer may act on: its manifest parses and
        matches the format version.  ``steps()`` filters by NAME only —
        good enough for the manager's own restore (which falls back past
        a corrupt candidate), but a polling consumer (the promotion
        daemon, ``serve/flywheel.py``) must never even SEE a torn
        ``step-*`` dir, e.g. one whose manifest an external fault tore
        mid-write.  Staging (``.tmp-*``) and discard debris are already
        invisible by construction (they never match the step prefix)."""
        for s in reversed(self.steps()):
            if self._manifest_committed(s):
                return s
        return None

    def watch(self, after: Optional[int] = None, timeout: float = 10.0,
              poll: float = 0.05) -> Optional[int]:
        """Block until a committed step NEWER than ``after`` appears;
        return its step number, or ``None`` when ``timeout`` elapses
        first.  The cheap polling primitive the promotion daemon (and
        any other checkpoint consumer) loops on instead of re-deriving
        ``steps()`` scans: only committed manifests are ever surfaced —
        a mid-commit stage or a torn dir can never be returned."""
        deadline = time.monotonic() + float(timeout)
        while True:
            s = self.latest_committed()
            if s is not None and (after is None or s > int(after)):
                return s
            if time.monotonic() >= deadline:
                return None
            time.sleep(poll)

    # -- save -----------------------------------------------------------
    def save(self, step: int, state, meta: Optional[Dict] = None) -> str:
        """Stage + atomically commit ``state`` as checkpoint ``step``.
        Returns the committed directory path.

        ``meta`` — optional JSON-safe dict committed atomically with the
        arrays (it rides the manifest, which is written last).  Used for
        non-array sidecar state like the data-iterator position
        (``TrainStep.save_checkpoint(data_iter=...)``); older manifests
        without it restore fine (backward-compatible section)."""
        step = int(step)
        flat, _ = jax.tree_util.tree_flatten_with_path(state)
        os.makedirs(self.directory, exist_ok=True)
        tmp = os.path.join(self.directory, _TMP_PREFIX + (_STEP_FMT % step))
        final = self._step_dir(step)
        if self.process_count > 1:
            return self._save_multiprocess(step, flat, meta, tmp, final)
        # single-writer: nobody else can own staging debris, including a
        # crashed earlier attempt at THIS step — sweep unconditionally
        # (or the makedirs below would fail on the leftover dir)
        self._sweep_stale()
        os.makedirs(tmp)
        try:
            entries = []
            for i, (path, leaf) in enumerate(flat):
                entries.append(self._save_leaf(
                    tmp, "arr_%05d" % i, jax.tree_util.keystr(path), leaf))
            manifest = {"format_version": _FORMAT_VERSION, "step": step,
                        "arrays": entries}
            if meta is not None:
                manifest["meta"] = meta
            self._write_manifest_and_commit(tmp, final, manifest)
        except BaseException:
            shutil.rmtree(tmp, ignore_errors=True)
            raise
        self._retire()
        return final

    def _write_manifest_and_commit(self, tmp: str, final: str,
                                   manifest: Dict) -> None:
        """The shared commit tail: write ``manifest.json`` LAST (a torn
        stage never looks complete), fsync the staging dir, and publish
        with one atomic rename — rolling a same-step re-save's old dir
        back into place if the rename fails after it moved aside."""
        buf = json.dumps(manifest, indent=1).encode()
        _with_retries(
            lambda: _write_bytes(os.path.join(tmp, _MANIFEST), buf),
            self.retries, self.backoff, _MANIFEST)
        _fsync_dir(tmp)
        discard = None
        committed = False
        try:
            if os.path.isdir(final):
                # re-saving the same step: move the committed dir
                # ASIDE (never delete it before the new one is
                # committed — a crash here leaves the data on disk,
                # and every OTHER checkpoint untouched)
                discard = os.path.join(
                    os.path.dirname(final),
                    _DISCARD_PREFIX + os.path.basename(final))
                shutil.rmtree(discard, ignore_errors=True)
                os.replace(final, discard)
            os.replace(tmp, final)  # THE commit point
            committed = True
        finally:
            if discard is not None and os.path.isdir(discard):
                if committed:
                    shutil.rmtree(discard, ignore_errors=True)
                elif not os.path.isdir(final):
                    # the commit rename failed after the old dir
                    # moved aside: roll it back so the previously
                    # committed checkpoint is still restorable
                    os.replace(discard, final)
        _fsync_dir(self.directory)

    # -- multi-process commit protocol ----------------------------------
    def _save_multiprocess(self, step: int, flat, meta, tmp: str,
                           final: str) -> str:
        """Coordinated save: this process stages only the shards it
        owns plus a done-marker; process 0 verifies every marker and
        publishes the single manifest atomically (module docstring)."""
        # a re-save of an ALREADY-committed step must not let the old
        # commit satisfy the non-coordinators' wait: remember what the
        # committed manifest looked like before this attempt started
        pre_stat = self._manifest_stat(final)
        if self.process_index == 0:
            self._sweep_stale(keep=os.path.basename(tmp))
            # a crashed EARLIER attempt at this same step may have left
            # done-markers in the (grace-protected, unswept) staging
            # dir; merging one would commit a checkpoint mixing two
            # attempts' files.  Drop markers older than stale_grace —
            # a CURRENT attempt's marker (a peer that reached the step
            # boundary just before us) is seconds old and survives.
            if os.path.isdir(tmp):
                now = time.time()
                for name in os.listdir(tmp):
                    if not name.startswith("done-"):
                        continue
                    path = os.path.join(tmp, name)
                    try:
                        if now - os.path.getmtime(path) > self.stale_grace:
                            os.unlink(path)
                    except OSError:
                        continue
        os.makedirs(tmp, exist_ok=True)
        # deliberately NO rmtree-on-failure here: peers may still be
        # writing into the shared staging dir, and an uncommitted stage
        # is invisible anyway — it ages out through _sweep_stale
        skeletons = []
        mine: Dict[str, List] = {}
        for i, (path, leaf) in enumerate(flat):
            name = "arr_%05d" % i
            entry, owned, expected = self._save_leaf_owned(
                tmp, name, jax.tree_util.keystr(path), leaf)
            skeletons.append((entry, expected))
            if owned:
                mine[name] = owned
        marker = {"format_version": _FORMAT_VERSION, "step": step,
                  "process": self.process_index, "files": mine,
                  # launcher-managed elastic jobs bump
                  # MXNET_RESTART_COUNT per relaunch (tools/launch.py
                  # --max-restarts): stamping it rejects a crashed
                  # EARLIER attempt's marker even inside the
                  # stale_grace window.  None (no launcher) degrades to
                  # the age heuristic alone.
                  "attempt": os.environ.get("MXNET_RESTART_COUNT"),
                  "meta": meta}
        _with_retries(
            lambda: _write_bytes(
                os.path.join(tmp, _DONE_FMT % self.process_index),
                json.dumps(marker).encode()),
            self.retries, self.backoff, "done-marker")
        _fsync_dir(tmp)
        if self.process_index != 0:
            self._wait_commit(step, final, pre_stat)
            return final
        markers = self._wait_markers(tmp, step)
        arrays = []
        for i, (entry, expected) in enumerate(skeletons):
            name = "arr_%05d" % i
            collected: List = []
            for r in sorted(markers):
                collected.extend(markers[r]["files"].get(name, []))
            collected.sort(key=lambda kf: kf[0])
            if [k for k, _ in collected] != list(range(expected)):
                raise CheckpointError(
                    "multi-process checkpoint step %d: array %s has "
                    "shard files %s from the %d done-markers, expected "
                    "exactly shards 0..%d — a process staged an "
                    "inconsistent state tree; NOT committing"
                    % (step, name, [k for k, _ in collected],
                       len(markers), expected - 1))
            entry["files"] = [f for _, f in collected]
            arrays.append(entry)
        manifest = {"format_version": _FORMAT_VERSION, "step": step,
                    "arrays": arrays}
        merged_meta = self._merge_meta(markers)
        if merged_meta is not None:
            manifest["meta"] = merged_meta
        self._write_manifest_and_commit(tmp, final, manifest)
        self._retire()
        return final

    def _merge_meta(self, markers: Dict[int, Dict]) -> Optional[Dict]:
        """Process 0's meta is the base; every process's ``data_iter``
        state (its shard of the input stream) is collected under
        ``data_iter_parts`` so elastic restore can re-split the stream
        across a different process count."""
        base = markers[0].get("meta")
        merged = dict(base) if base else {}
        parts = {str(r): m["meta"]["data_iter"] for r, m in markers.items()
                 if m.get("meta") and m["meta"].get("data_iter") is not None}
        if parts:
            merged["data_iter_parts"] = parts
        return merged or None

    def _wait_markers(self, tmp: str, step: int) -> Dict[int, Dict]:
        """Process 0: wait for every peer's done-marker (bounded by
        ``commit_timeout``).  A torn marker (peer died mid-write) never
        parses and therefore never commits a torn checkpoint — the wait
        times out and the stage stays invisible."""
        deadline = time.monotonic() + self.commit_timeout
        need = set(range(self.process_count))
        got: Dict[int, Dict] = {}
        while True:
            for r in sorted(need - set(got)):
                path = os.path.join(tmp, _DONE_FMT % r)
                if not os.path.exists(path):
                    continue
                try:
                    m = json.loads(_read_bytes(path).decode())
                except (OSError, ValueError):
                    continue  # torn/in-flight marker: keep waiting
                if m.get("step") == step and m.get("process") == r \
                        and m.get("attempt") == os.environ.get(
                            "MXNET_RESTART_COUNT"):
                    # attempt mismatch = a crashed earlier attempt's
                    # leftover: keep waiting for THIS attempt's marker
                    got[r] = m
            if len(got) == len(need):
                return got
            if time.monotonic() > deadline:
                raise CheckpointError(
                    "multi-process checkpoint step %d: process 0 timed "
                    "out after %.0fs waiting for done-marker(s) from "
                    "process(es) %s under %s — a host was likely lost "
                    "mid-save; the half-written stage was NOT committed "
                    "and the last committed checkpoint is untouched"
                    % (step, self.commit_timeout,
                       sorted(need - set(got)), tmp))
            time.sleep(0.05)

    @staticmethod
    def _manifest_stat(final: str) -> Optional[Tuple[int, int]]:
        """Identity ``(st_ino, st_mtime_ns)`` of a committed manifest,
        or None when the step is not committed — how a non-coordinator
        tells a FRESH commit from a pre-existing one when a step is
        re-saved (the atomic rename gives the manifest a new inode)."""
        try:
            st = os.stat(os.path.join(final, _MANIFEST))
            return (st.st_ino, st.st_mtime_ns)
        except OSError:
            return None

    def _wait_commit(self, step: int, final: str,
                     pre_stat: Optional[Tuple[int, int]] = None) -> None:
        """Processes != 0: block until the coordinator publishes a
        manifest NEWER than ``pre_stat`` (the commit state observed
        before this save attempt — a re-saved step's OLD commit must
        not count), so ``save`` returning means THIS checkpoint is
        durable everywhere.  ``commit_timeout=0`` skips the wait
        (fire-and-forget staging — how single-process tests drive one
        rank of the protocol at a time)."""
        if self.commit_timeout == 0:
            return
        deadline = time.monotonic() + self.commit_timeout
        while self._manifest_stat(final) in (None, pre_stat):
            if time.monotonic() > deadline:
                raise CheckpointError(
                    "multi-process checkpoint step %d: process %d timed "
                    "out after %.0fs waiting for process 0 to commit %s "
                    "— the coordinator was likely lost mid-save; the "
                    "last committed checkpoint is untouched"
                    % (step, self.process_index, self.commit_timeout,
                       final))
            time.sleep(0.05)

    def _newest_mtime(self, path: str) -> float:
        """Newest mtime of ``path`` or anything directly inside it —
        how fresh a peer's activity in the directory can be."""
        try:
            newest = os.path.getmtime(path)
            for name in os.listdir(path):
                try:
                    newest = max(newest, os.path.getmtime(
                        os.path.join(path, name)))
                except OSError:
                    continue
            return newest
        except OSError:
            return 0.0

    def _sweep_stale(self, keep: Optional[str] = None):
        """Remove staging/discard debris from crashed earlier saves.

        Single-process: the manager is single-writer per directory, so
        anything with a tmp/discard prefix is an orphan by now —
        without this, every hard kill mid-save would leak one
        full-state-sized directory forever.  Multi-process: only
        process 0 sweeps, never the current save's own staging dir
        (``keep``), and never a directory written to within
        ``stale_grace`` seconds — a slow peer's in-flight stage must
        not be deleted out from under it (the thundering-herd case:
        N preempted processes all restart and save at once)."""
        for name in os.listdir(self.directory):
            if not (name.startswith(_TMP_PREFIX)
                    or name.startswith(_DISCARD_PREFIX)):
                continue
            if name == keep:
                continue
            path = os.path.join(self.directory, name)
            if self.process_count > 1 and \
                    time.time() - self._newest_mtime(path) < self.stale_grace:
                continue
            shutil.rmtree(path, ignore_errors=True)

    def _save_leaf(self, tmp: str, name: str, key: str, leaf) -> Dict:
        dtype = np.dtype(getattr(leaf, "dtype", None)
                         or np.asarray(leaf).dtype)
        shape = list(np.shape(leaf))
        sharding = getattr(leaf, "sharding", None)
        spec = getattr(sharding, "spec", None)
        entry = {"key": key, "dtype": dtype.name, "shape": shape,
                 "spec": None if spec is None else str(spec), "files": []}
        shards = _distinct_shards(leaf) if isinstance(leaf, jax.Array) \
            else None
        if shards is None:
            # replicated / host leaf: one device->host copy, one file
            data = _leaf_np(leaf).tobytes()
            entry["files"].append(self._write_payload(
                tmp, name + ".bin", data, index=None, part_shape=shape))
        else:
            # sharded leaf (ZeRO-1 state): each distinct shard straight
            # off its device buffer — never gathered
            for k, s in enumerate(shards):
                part = _leaf_np(s.data)
                entry["files"].append(self._write_payload(
                    tmp, "%s.shard%03d.bin" % (name, k), part.tobytes(),
                    index=_index_to_json(s.index),
                    part_shape=list(part.shape)))
        return entry

    def _save_leaf_owned(self, tmp: str, name: str, key: str,
                         leaf) -> Tuple[Dict, List, int]:
        """Multi-process leaf writer: stage only the distinct shards
        THIS process owns (the lowest-ranked process holding a shard
        writes it — nothing is written twice across hosts, nothing is
        gathered).  Returns ``(manifest-entry skeleton, [[shard_k,
        payload-entry], ...] written here, expected total shard
        count)`` — shard ordinals are derived from the GLOBAL
        device→index map, so every process numbers the same shard the
        same way without communicating."""
        dtype = np.dtype(getattr(leaf, "dtype", None)
                         or np.asarray(leaf).dtype)
        shape = list(np.shape(leaf))
        sharding = getattr(leaf, "sharding", None)
        spec = getattr(sharding, "spec", None)
        entry = {"key": key, "dtype": dtype.name, "shape": shape,
                 "spec": None if spec is None else str(spec), "files": []}
        groups = None  # [(index_key, owner_process, index)] sorted
        if isinstance(leaf, jax.Array) and sharding is not None:
            by_key: Dict[Tuple, Tuple[int, Any]] = {}
            procs = set()
            for dev, idx in sharding.devices_indices_map(
                    tuple(shape)).items():
                procs.add(dev.process_index)
                k = tuple((sl.start, sl.stop, sl.step) for sl in idx)
                owner, _ = by_key.get(k, (dev.process_index, idx))
                by_key[k] = (min(owner, dev.process_index), idx)
            if procs == {self.process_index}:
                # a leaf whose mesh does not span processes at all
                # (per-process replicated training, e.g. on a backend
                # without multi-process compute): identical on every
                # process by SPMD construction, so — like host leaves —
                # process 0 writes the one copy.  (On a SPANNING mesh a
                # NamedSharding enumerates every mesh device, so a
                # single-process owner set can only mean a local mesh.)
                by_key = {k: (0, idx) for k, (_, idx) in by_key.items()}
            groups = sorted(
                ((k, owner, idx) for k, (owner, idx) in by_key.items()),
                key=lambda g: tuple(sl.start or 0 for sl in g[2]))
        if groups is None or len(groups) < 2:
            # replicated (or host) leaf: ONE file, written by the
            # lowest-ranked owning process (process 0 for host leaves —
            # they must be identical everywhere by SPMD construction)
            owner = groups[0][1] if groups else 0
            if owner != self.process_index:
                return entry, [], 1
            data = _leaf_np(leaf).tobytes()
            payload = self._write_payload(tmp, name + ".bin", data,
                                          index=None, part_shape=shape)
            return entry, [[0, payload]], 1
        local = {}
        for s in getattr(leaf, "addressable_shards", ()):
            local[tuple((sl.start, sl.stop, sl.step)
                        for sl in s.index)] = s
        owned = []
        for k, (ikey, owner, idx) in enumerate(groups):
            if owner != self.process_index:
                continue
            shard = local.get(ikey)
            if shard is None:
                raise CheckpointError(
                    "process %d owns shard %d of %s but holds no "
                    "addressable copy — mesh/sharding disagree about "
                    "device placement" % (self.process_index, k, key))
            part = _leaf_np(shard.data)
            payload = self._write_payload(
                tmp, "%s.shard%03d.bin" % (name, k), part.tobytes(),
                index=_index_to_json(shard.index),
                part_shape=list(part.shape))
            owned.append([k, payload])
        return entry, owned, len(groups)

    def _write_payload(self, tmp, fname, data, index, part_shape) -> Dict:
        _with_retries(
            lambda: _write_bytes(os.path.join(tmp, fname), data),
            self.retries, self.backoff, fname)
        return {"file": fname, "checksum": _checksum(data),
                # always-verifiable fallback digest (see _verify_checksum)
                "crc32": "%08x" % (zlib.crc32(data) & 0xFFFFFFFF),
                "nbytes": len(data), "index": index,
                "part_shape": part_shape}

    def _retire(self):
        """Retention beyond ``keep_last``.  Multi-process: only process
        0 retires (N processes racing rmtree on a shared filesystem
        half-delete each other's candidates), and a step directory
        anybody wrote to within ``stale_grace`` seconds is left alone —
        a straggler may still be reading/re-staging it (the cross-host
        retention race)."""
        if self.keep_last is None:
            return
        if self.process_count > 1 and self.process_index != 0:
            return
        steps = self.steps()
        for s in steps[:-self.keep_last]:
            d = self._step_dir(s)
            if self.process_count > 1 and \
                    time.time() - self._newest_mtime(d) < self.stale_grace:
                continue
            shutil.rmtree(d, ignore_errors=True)

    # -- restore --------------------------------------------------------
    def restore(self, like, step: Optional[int] = None, shardings=None,
                return_meta: bool = False, elastic=None, topology=None):
        """Load the newest intact checkpoint (or exactly ``step``) into
        the structure of ``like``; returns ``(step, state)`` — or
        ``(step, state, meta)`` with ``return_meta=True``, where
        ``meta`` is the manifest's sidecar dict (``None`` for
        checkpoints written without one).

        ``shardings`` — an optional pytree congruent with ``like`` whose
        leaves are placements (``NamedSharding``/device) — puts every
        restored leaf straight back on its training layout.  Corrupt
        candidates are skipped with a warning (last-good fallback)
        unless ``step`` pinned one explicitly.

        ``elastic`` — an optional pytree congruent with ``like`` whose
        leaves are ``None`` (the leaf's saved shape must match exactly)
        or an ``int``: the LOGICAL leading dim of a leaf whose stored
        leading dim is padding-dependent (ZeRO-1 optimizer state padded
        to a multiple of the dp width).  A shape mismatch on such a
        leaf is resolved by slicing the saved array to the logical rows
        and zero-re-padding to this run's expectation — the elastic
        dp=N→dp=M re-shard.  Any other shape mismatch raises
        :class:`CheckpointTopologyError` naming the saved topology
        (from the manifest meta) and ``topology`` (this run's).
        """
        def pack(s, loaded):
            state, meta = loaded
            return (s, state, meta) if return_meta else (s, state)

        if step is not None:
            return pack(int(step), self._load(int(step), like, shardings,
                                              elastic, topology))
        candidates = list(reversed(self.steps()))
        if not candidates:
            raise CheckpointError(
                "no checkpoints under %r" % self.directory)
        last_err: Optional[Exception] = None
        for s in candidates:
            try:
                return pack(s, self._load(s, like, shardings, elastic,
                                          topology))
            except CheckpointCorruptError as e:
                warnings.warn(
                    "checkpoint %s is corrupt (%s); falling back to the "
                    "previous one" % (_STEP_FMT % s, e), stacklevel=2)
                last_err = e
        raise CheckpointError(
            "no intact checkpoint under %r (%d candidate(s), newest "
            "error: %s)" % (self.directory, len(candidates), last_err))

    def _load(self, step: int, like, shardings, elastic=None,
              topology=None):
        d = self._step_dir(step)
        try:
            raw = _with_retries(
                lambda: _read_bytes(os.path.join(d, _MANIFEST)),
                self.retries, self.backoff, _MANIFEST)
            manifest = json.loads(raw.decode())
        except FileNotFoundError as e:
            raise CheckpointCorruptError("missing manifest: %s" % e)
        except (OSError, ValueError) as e:
            raise CheckpointCorruptError("unreadable manifest: %s" % e)
        if manifest.get("format_version") != _FORMAT_VERSION:
            raise CheckpointCorruptError(
                "manifest format_version %r != %d"
                % (manifest.get("format_version"), _FORMAT_VERSION))
        flat, treedef = jax.tree_util.tree_flatten_with_path(like)
        entries = manifest.get("arrays", [])
        meta_topo = manifest.get("meta", {}).get("topology") \
            if isinstance(manifest.get("meta"), dict) else None
        if meta_topo is not None and topology is not None:
            mismatch = _topology_mismatch(meta_topo, topology)
            if mismatch:
                raise CheckpointTopologyError(
                    "checkpoint step %d cannot be re-sharded onto this "
                    "run's topology: %s (saved topology: %s; current "
                    "topology: %s)"
                    % (step, mismatch, json.dumps(meta_topo,
                                                  sort_keys=True),
                       json.dumps(topology, sort_keys=True)))
        if len(entries) != len(flat):
            if meta_topo is not None and topology is not None \
                    and meta_topo != topology:
                # a different training topology produces a different
                # state-tree shape (a pipeline width change re-stacks
                # the stage params): refuse with the topologies named,
                # don't walk back to an older checkpoint with the same
                # mismatch
                raise CheckpointTopologyError(
                    "checkpoint step %d has %d state leaves but this "
                    "run expects %d — it was saved under a different "
                    "training topology that cannot be re-sharded "
                    "(saved topology: %s; current topology: %s)"
                    % (step, len(entries), len(flat),
                       json.dumps(meta_topo, sort_keys=True),
                       json.dumps(topology, sort_keys=True)))
            raise CheckpointCorruptError(
                "manifest has %d arrays, expected %d (training state "
                "structure changed?)" % (len(entries), len(flat)))
        flat_sh: List[Any] = [None] * len(flat)
        if shardings is not None:
            sh_flat, sh_def = jax.tree_util.tree_flatten_with_path(shardings)
            if len(sh_flat) != len(flat):
                raise ValueError("shardings tree is not congruent with "
                                 "the state tree")
            flat_sh = [s for _, s in sh_flat]
        flat_el: List[Any] = [None] * len(flat)
        if elastic is not None:
            # None marks "exact shape required" and must survive the
            # flatten (jax drops bare None subtrees), hence is_leaf
            el_flat, _ = jax.tree_util.tree_flatten(
                elastic, is_leaf=lambda x: x is None)
            if len(el_flat) != len(flat):
                raise ValueError("elastic policy tree is not congruent "
                                 "with the state tree")
            flat_el = el_flat
        saved_topo = meta_topo
        leaves = []
        for (path, lk), entry, sh, el in zip(flat, entries, flat_sh,
                                             flat_el):
            key = jax.tree_util.keystr(path)
            if entry.get("key") != key:
                raise CheckpointCorruptError(
                    "manifest entry %r does not match state leaf %r"
                    % (entry.get("key"), key))
            try:
                leaves.append(self._load_leaf(
                    d, entry, sh, want_shape=tuple(np.shape(lk)),
                    elastic_dim=el, saved_topology=saved_topo,
                    topology=topology))
            except (CheckpointCorruptError, CheckpointTopologyError):
                raise
            except (KeyError, IndexError, TypeError, ValueError) as e:
                # manifest content that parses as JSON but decodes to
                # garbage (mangled dtype name, wrong part_shape/index):
                # corruption, not a caller error — the last-good
                # fallback in restore() must still engage
                raise CheckpointCorruptError(
                    "undecodable manifest entry %r: %s" % (key, e))
        return (jax.tree_util.tree_unflatten(treedef, leaves),
                manifest.get("meta"))

    def _load_leaf(self, d: str, entry: Dict, sharding,
                   want_shape: Optional[Tuple] = None, elastic_dim=None,
                   saved_topology=None, topology=None):
        dtype = np.dtype(entry["dtype"])
        shape = tuple(entry["shape"])
        files = entry["files"]
        if len(files) == 1 and files[0].get("index") is None:
            arr = self._read_part(d, files[0], dtype).reshape(shape)
        else:
            arr = np.empty(shape, dtype)
            for f in files:
                part = self._read_part(d, f, dtype) \
                    .reshape(tuple(f["part_shape"]))
                arr[_index_from_json(f["index"], shape)] = part
        if want_shape is not None and shape != want_shape:
            arr = self._elastic_reshape(entry, arr, want_shape,
                                        elastic_dim, saved_topology,
                                        topology)
        return self._place(arr, sharding)

    def _elastic_reshape(self, entry: Dict, arr: np.ndarray,
                         want_shape: Tuple, elastic_dim,
                         saved_topology, topology) -> np.ndarray:
        """Re-shard a topology-dependent leaf: slice its leading dim to
        the logical rows and zero-re-pad to this run's padded width.
        The pad rows are inert under the (elementwise) ZeRO-1 update,
        so the logical state stays bit-identical across widths.  Any
        shape change the policy does not cover is a topology refusal,
        not corruption."""
        shape = tuple(arr.shape)
        ok = (elastic_dim is not None and len(shape) == len(want_shape)
              and len(shape) >= 1 and shape[1:] == want_shape[1:]
              and shape[0] >= int(elastic_dim)
              and want_shape[0] >= int(elastic_dim))
        if not ok:
            topo = ""
            if saved_topology is not None or topology is not None:
                topo = " (saved topology: %s; current topology: %s)" % (
                    json.dumps(saved_topology, sort_keys=True),
                    json.dumps(topology, sort_keys=True))
            raise CheckpointTopologyError(
                "checkpoint leaf %r was saved with shape %s but this "
                "run expects %s — only the padded leading dim of a "
                "ZeRO-sharded optimizer-state leaf can be re-sharded "
                "across topologies%s" % (entry.get("key"), list(shape),
                                         list(want_shape), topo))
        logical = int(elastic_dim)
        out = arr[:logical]
        if want_shape[0] > logical:
            pad = np.zeros((want_shape[0] - logical,) + tuple(want_shape[1:]),
                           arr.dtype)
            out = np.concatenate([out, pad], axis=0)
        return np.ascontiguousarray(out)

    @staticmethod
    def _place(arr: np.ndarray, sharding):
        """Put a restored host array back on its training placement.
        A sharding spanning processes (multihost restore) cannot go
        through ``device_put`` — each process supplies its addressable
        shards through the callback and jax assembles the global
        array."""
        if sharding is None:
            return jnp.asarray(arr)
        if getattr(sharding, "is_fully_addressable", True):
            return jax.device_put(arr, sharding)
        return jax.make_array_from_callback(
            tuple(arr.shape), sharding, lambda idx: arr[idx])

    def _read_part(self, d: str, f: Dict, dtype) -> np.ndarray:
        path = os.path.join(d, f["file"])
        try:
            buf = _with_retries(lambda: _read_bytes(path),
                                self.retries, self.backoff, f["file"])
        except FileNotFoundError as e:
            raise CheckpointCorruptError("missing array file: %s" % e)
        if len(buf) != int(f["nbytes"]):
            raise CheckpointCorruptError(
                "%s: %d bytes on disk, manifest says %d (torn write?)"
                % (f["file"], len(buf), f["nbytes"]))
        if not _verify_checksum(buf, f["checksum"], f.get("crc32")):
            raise CheckpointCorruptError(
                "%s: checksum mismatch (%s)" % (f["file"], f["checksum"]))
        return np.frombuffer(buf, dtype)


# ---------------------------------------------------------------------------
# preemption: SIGTERM -> checkpoint at the next step boundary
# ---------------------------------------------------------------------------

# monotonically increasing request sequence (incrementing an int is
# atomic under the GIL, safe from a signal handler).  Each consumer
# (TrainStep._maybe_checkpoint) remembers the last sequence it honored,
# so ONE request reaches EVERY attached step loop — a global clear
# would let the first loop to hit a boundary steal the request from
# the others.
_CKPT_SEQ = 0


def request_checkpoint() -> None:
    """Ask every step loop with an attached manager to checkpoint at its
    next step boundary (what the SIGTERM hook calls)."""
    global _CKPT_SEQ
    _CKPT_SEQ += 1


def request_seq() -> int:
    """Current request sequence number (consumers compare-and-store)."""
    return _CKPT_SEQ


def checkpoint_requested(since: int = 0) -> bool:
    """True when a checkpoint request newer than ``since`` is pending."""
    return _CKPT_SEQ > since


# signum -> the handler we displaced; the presence of a key means OUR
# hook currently owns that signal (the idempotency token)
_HOOK_PREVIOUS: Dict[int, Any] = {}


def install_preemption_hook(signals=(signal.SIGTERM,), chain=True):
    """Install handlers that flip the checkpoint-request flag on
    preemption signals (must run on the main thread).  The handler is
    async-signal-light — it only sets an event; the actual save happens
    at the next step boundary on the training thread, where device
    state is consistent.  ``chain=True`` forwards to any previously
    installed handler.  Returns ``{signum: previous_handler}``.

    Idempotent: a signal already carrying this hook is left untouched
    (re-installing never chains the hook onto itself, which would
    multiply every request).  Exception-safe: if installing the k-th
    handler raises (bad signal number, non-main thread), the handlers
    already swapped in are rolled back before the error propagates —
    the process is never left half-hooked."""
    installed_now = {}
    try:
        for s in signals:
            s = int(s)
            if s in _HOOK_PREVIOUS and getattr(
                    signal.getsignal(s), "_mxtpu_preemption_hook", False):
                # the LIVE handler is ours: idempotent no-op.  (The
                # latch alone is not enough — third-party code may have
                # displaced the handler since; then we must re-install,
                # chaining to the displacer.)
                continue

            def _handler(signum, frame):
                request_checkpoint()
                prev = _HOOK_PREVIOUS.get(signum)
                if chain and callable(prev):
                    prev(signum, frame)

            _handler._mxtpu_preemption_hook = True
            prev = signal.signal(s, _handler)
            installed_now[s] = prev
            if not getattr(prev, "_mxtpu_preemption_hook", False):
                # never record our own (stale) hook as the previous
                # handler — chaining onto ourselves would multiply
                # every request
                _HOOK_PREVIOUS[s] = prev
            elif s not in _HOOK_PREVIOUS:
                _HOOK_PREVIOUS[s] = None
    except BaseException:
        for s, prev in installed_now.items():
            _HOOK_PREVIOUS.pop(s, None)
            try:
                signal.signal(
                    s, prev if prev is not None else signal.SIG_DFL)
            except (ValueError, OSError):  # pragma: no cover
                pass
        raise
    return {int(s): _HOOK_PREVIOUS[int(s)] for s in signals}


def uninstall_preemption_hook(signals=None):
    """Restore the dispositions :func:`install_preemption_hook`
    displaced (all of them with ``signals=None``).  Returns the
    restored ``{signum: handler}`` map.  Called by the step loop when a
    preemption-triggered save FAILS: leaving the hook installed would
    swallow every further SIGTERM into another doomed save request —
    after this, a repeated signal terminates the process normally."""
    sigs = list(_HOOK_PREVIOUS) if signals is None else \
        [int(s) for s in signals]
    restored = {}
    for s in sigs:
        if s not in _HOOK_PREVIOUS:
            continue
        prev = _HOOK_PREVIOUS.pop(s)
        try:
            signal.signal(s, prev if prev is not None else signal.SIG_DFL)
        except (ValueError, OSError) as e:  # non-main thread / bad signum
            warnings.warn("could not restore handler for signal %d: %s"
                          % (s, e))
            continue
        restored[s] = prev
    return restored
