"""Expert parallelism: mixture-of-experts FFN with experts sharded over
the ``ep`` mesh axis.

Not present in the reference (its closest artifact is manual group2ctx model
parallelism); on TPU this is a natural capability of the sharding layer:
experts live on the leading (expert) dim, annotated with P('ep', ...), and
GSPMD turns the dispatch/combine einsums into all-to-alls over ICI.

Training: ``return_aux=True`` also returns the Switch-style load-balancing
loss ``E * sum_e f_e * p_e`` (f_e = fraction of routing decisions sent to
expert e, p_e = mean router probability), computed on the PRE-capacity
router decisions so overflowed tokens still push the router toward
balance.  ``capacity_factor`` drops routing decisions beyond
``ceil(capacity_factor * T * top_k / E)`` per expert (GShard k-major
priority: every rank-1 choice beats any rank-2 choice); dropped tokens
pass through with zero expert contribution, exactly like the reference
MoE systems' overflow path.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = ["moe_ffn", "moe_ffn_sharded", "load_balancing_loss"]


def load_balancing_loss(probs, top_idx):
    """Switch/GShard auxiliary loss over router decisions.

    probs: (T, E) router softmax; top_idx: (T, K) selected experts.
    Returns ``E * sum_e f_e * p_e`` — minimized (→ 1.0) by a uniform
    router.  The f term is a hard count (no gradient); the p term pulls
    router probabilities toward balance.
    """
    num_experts = probs.shape[-1]
    sel = jax.nn.one_hot(top_idx, num_experts, dtype=probs.dtype)  # (T,K,E)
    f = jnp.mean(jnp.sum(sel, axis=1), axis=0) / sel.shape[1]  # (E,)
    p = jnp.mean(probs, axis=0)  # (E,)
    return num_experts * jnp.sum(f * p)


def moe_ffn(x, gate_w, w1, b1, w2, b2, top_k=1, capacity_factor=None,
            return_aux=False):
    """Token-choice MoE FFN (dense math; shardable).

    x: (tokens, d); gate_w: (d, E); w1: (E, d, hidden); w2: (E, hidden, d).
    Top-k gating with softmax-renormalized weights over the selected
    experts.  With ``capacity_factor``, each expert accepts at most
    ``ceil(capacity_factor * T * top_k / E)`` routing decisions (k-major
    priority); the rest are dropped from the combine.  With
    ``return_aux``, also returns the load-balancing loss.
    """
    num_experts = gate_w.shape[-1]
    logits = x @ gate_w  # (T, E)
    probs = jax.nn.softmax(logits, axis=-1)
    top_p, top_idx = jax.lax.top_k(probs, top_k)  # (T, K)
    top_p = top_p / jnp.sum(top_p, axis=-1, keepdims=True)
    # dispatch tensor: (T, K, E) one-hot -> (E, T) combine weights
    disp = jax.nn.one_hot(top_idx, num_experts, dtype=x.dtype)  # (T,K,E)
    if return_aux:
        # pre-capacity decisions: overflowed tokens still teach the router
        aux = load_balancing_loss(probs, top_idx)
    if capacity_factor is not None:
        tokens = x.shape[0]
        capacity = max(1, int(math.ceil(
            capacity_factor * tokens * top_k / num_experts)))
        # k-major priority (GShard): all rank-1 choices outrank rank-2.
        # positions are COUNTS — computed in int32, not the activation
        # dtype: a bf16 cumsum loses integer precision past 256 decisions
        # and keeps/drops the wrong routing decisions at the boundary
        sel = jnp.swapaxes(disp, 0, 1).reshape(top_k * tokens, num_experts)
        sel_i = (sel > 0).astype(jnp.int32)
        pos = jnp.cumsum(sel_i, axis=0) - sel_i  # earlier decisions/expert
        sel = sel * (pos < capacity).astype(sel.dtype)
        disp = jnp.swapaxes(sel.reshape(top_k, tokens, num_experts), 0, 1)
    combine = jnp.einsum("tk,tke->te", top_p.astype(x.dtype), disp)  # (T,E)
    # expert compute on all tokens, masked-combined (dense formulation —
    # efficient when E is sharded over ep: einsums become a2a + local ffn)
    h = jnp.einsum("td,edf->etf", x, w1) + b1[:, None, :]
    h = jax.nn.gelu(h)
    y = jnp.einsum("etf,efd->etd", h, w2) + b2[:, None, :]
    out = jnp.einsum("etd,te->td", y, combine)
    if return_aux:
        return out, aux
    return out


def moe_ffn_sharded(x, gate_w, w1, b1, w2, b2, mesh: Mesh, top_k=1,
                    axis_name="ep", capacity_factor=None, return_aux=False):
    """Run moe_ffn with experts sharded over ``axis_name`` via GSPMD."""
    from ..analysis import LintReport, check_partition_spec

    # eager GL002: a bad axis name or an expert tensor of unexpected
    # rank would otherwise surface as a GSPMD mis-shard, not an error
    diags = []
    for name, arr, spec in (("w1", w1, P(axis_name, None, None)),
                            ("w2", w2, P(axis_name, None, None)),
                            ("b1", b1, P(axis_name)),
                            ("b2", b2, P(axis_name))):
        diags += check_partition_spec(spec, arr.ndim, mesh,
                                      where="moe_ffn_sharded(%s)" % name,
                                      operand=name)
    if gate_w.shape[-1] % dict(mesh.shape).get(axis_name, 1):
        raise ValueError(
            "moe_ffn_sharded: %d experts do not divide over mesh axis "
            "%r of size %d" % (gate_w.shape[-1], axis_name,
                               dict(mesh.shape).get(axis_name, 1)))
    LintReport(diags).raise_if_errors()
    e_spec = NamedSharding(mesh, P(axis_name))
    repl = NamedSharding(mesh, P())
    fn = jax.jit(functools.partial(moe_ffn, top_k=top_k,
                                   capacity_factor=capacity_factor,
                                   return_aux=return_aux),
                 in_shardings=(repl, repl, NamedSharding(mesh, P(axis_name, None, None)),
                               e_spec,
                               NamedSharding(mesh, P(axis_name, None, None)),
                               e_spec),
                 out_shardings=(repl, repl) if return_aux else repl)
    return fn(x, gate_w, w1, b1, w2, b2)
