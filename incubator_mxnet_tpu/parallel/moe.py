"""Expert parallelism: mixture-of-experts FFN with experts sharded over
the ``ep`` mesh axis.

Not present in the reference (its closest artifact is manual group2ctx model
parallelism); on TPU this is a natural capability of the sharding layer:
experts live on the leading (expert) dim, annotated with P('ep', ...), and
GSPMD turns the dispatch/combine einsums into all-to-alls over ICI.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = ["moe_ffn", "moe_ffn_sharded"]


def moe_ffn(x, gate_w, w1, b1, w2, b2, top_k=1):
    """Token-choice MoE FFN (dense math; shardable).

    x: (tokens, d); gate_w: (d, E); w1: (E, d, hidden); w2: (E, hidden, d).
    Top-k gating with softmax-renormalized weights over the selected experts.
    """
    num_experts = gate_w.shape[-1]
    logits = x @ gate_w  # (T, E)
    probs = jax.nn.softmax(logits, axis=-1)
    top_p, top_idx = jax.lax.top_k(probs, top_k)  # (T, K)
    top_p = top_p / jnp.sum(top_p, axis=-1, keepdims=True)
    # dispatch tensor: (T, K, E) one-hot -> (E, T) combine weights
    disp = jax.nn.one_hot(top_idx, num_experts, dtype=x.dtype)  # (T,K,E)
    combine = jnp.einsum("tk,tke->te", top_p.astype(x.dtype), disp)  # (T,E)
    # expert compute on all tokens, masked-combined (dense formulation —
    # efficient when E is sharded over ep: einsums become a2a + local ffn)
    h = jnp.einsum("td,edf->etf", x, w1) + b1[:, None, :]
    h = jax.nn.gelu(h)
    y = jnp.einsum("etf,efd->etd", h, w2) + b2[:, None, :]
    return jnp.einsum("etd,te->td", y, combine)


def moe_ffn_sharded(x, gate_w, w1, b1, w2, b2, mesh: Mesh, top_k=1,
                    axis_name="ep"):
    """Run moe_ffn with experts sharded over ``axis_name`` via GSPMD."""
    e_spec = NamedSharding(mesh, P(axis_name))
    repl = NamedSharding(mesh, P())
    fn = jax.jit(functools.partial(moe_ffn, top_k=top_k),
                 in_shardings=(repl, repl, NamedSharding(mesh, P(axis_name, None, None)),
                               e_spec,
                               NamedSharding(mesh, P(axis_name, None, None)),
                               e_spec),
                 out_shardings=repl)
    return fn(x, gate_w, w1, b1, w2, b2)
