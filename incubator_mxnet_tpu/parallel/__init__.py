"""``mx.parallel`` — TPU-native parallelism layer (SPMD over device meshes).

Replaces the reference's KVStore comm trees / NCCL / ps-lite stack
(SURVEY.md §2.5, §5.8) with jax.sharding + XLA collectives.
"""
from .mesh import Mesh, NamedSharding, P, PartitionSpec, make_mesh, replicated, shard_along
from .train_step import DynamicLossScale, FunctionalOptimizer, TrainStep, make_train_step
from .flash_attention import flash_attention
from .pipeline import pipeline_apply, spmd_pipeline, stack_stage_params
from .moe import load_balancing_loss, moe_ffn, moe_ffn_sharded
from .checkpoint import (CheckpointError, CheckpointCorruptError,
                         CheckpointManager, install_preemption_hook,
                         request_checkpoint)

__all__ = ["Mesh", "NamedSharding", "P", "PartitionSpec", "make_mesh",
           "replicated", "shard_along", "DynamicLossScale",
           "FunctionalOptimizer", "TrainStep", "make_train_step",
           "flash_attention", "pipeline_apply", "spmd_pipeline",
           "stack_stage_params", "load_balancing_loss", "moe_ffn",
           "moe_ffn_sharded", "CheckpointError", "CheckpointCorruptError",
           "CheckpointManager", "install_preemption_hook",
           "request_checkpoint"]
