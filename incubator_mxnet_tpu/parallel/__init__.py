"""``mx.parallel`` — TPU-native parallelism layer (SPMD over device meshes).

Replaces the reference's KVStore comm trees / NCCL / ps-lite stack
(SURVEY.md §2.5, §5.8) with jax.sharding + XLA collectives.
"""
from .mesh import (Mesh, NamedSharding, P, PartitionSpec, global_devices,
                   make_mesh, replicated, shard_along, spans_processes)
from .train_step import DynamicLossScale, FunctionalOptimizer, TrainStep, make_train_step
from .flash_attention import flash_attention
from .pipeline import pipeline_apply, spmd_pipeline, stack_stage_params
from .moe import load_balancing_loss, moe_ffn, moe_ffn_sharded
from .checkpoint import (CheckpointError, CheckpointCorruptError,
                         CheckpointManager, CheckpointTopologyError,
                         install_preemption_hook, request_checkpoint,
                         uninstall_preemption_hook)
from .supervisor import (DivergenceDetector, DivergenceError, HealthLedger,
                         HeartbeatEmitter, Supervisor, SupervisorConfig,
                         SupervisorError, run_supervised)
from .param_service import (ParamService, ServiceClient, ServiceUpdater,
                            StalenessClock, StalenessTimeout, SyncPolicy)
from . import distributed

__all__ = ["Mesh", "NamedSharding", "P", "PartitionSpec", "make_mesh",
           "replicated", "shard_along", "global_devices", "spans_processes",
           "DynamicLossScale", "FunctionalOptimizer", "TrainStep",
           "make_train_step", "flash_attention", "pipeline_apply",
           "spmd_pipeline", "stack_stage_params", "load_balancing_loss",
           "moe_ffn", "moe_ffn_sharded", "CheckpointError",
           "CheckpointCorruptError", "CheckpointTopologyError",
           "CheckpointManager", "install_preemption_hook",
           "uninstall_preemption_hook", "request_checkpoint",
           "DivergenceDetector", "DivergenceError", "HealthLedger",
           "HeartbeatEmitter", "Supervisor", "SupervisorConfig",
           "SupervisorError", "run_supervised",
           "ParamService", "ServiceClient", "ServiceUpdater",
           "StalenessClock", "StalenessTimeout", "SyncPolicy",
           "distributed"]
