"""Fused training step: forward+backward+optimizer in ONE XLA program.

This is the performance path that replaces the reference's
forward→backward→kvstore-push/pull→optimizer chain (SURVEY.md §3.1/§3.2)
with a single compiled computation: XLA fuses the whole step, donates the
parameter/optimizer buffers (in-place update), and — on a mesh — inserts the
data-parallel gradient all-reduce (the dist_sync_device semantics) as ICI
collectives via GSPMD sharding propagation.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .. import autograd, rng, tracing
from ..ndarray import NDArray
from ..ops import optimizer_ops as _oops
from .pipeline import shard_map, spmd_pipeline

__all__ = ["DynamicLossScale", "FunctionalOptimizer", "make_train_step",
           "TrainStep"]


class DynamicLossScale:
    """Functional dynamic loss-scaling policy — the jit-safe analog of
    ``contrib/amp/loss_scaler.py``.

    The mutable ``LossScaler`` adjusts a host float between steps; here
    the scale and its clean-step counter are *carried device state* of
    the fused step (donated, updated inside the program), so scaling
    composes with donation, ``multi_precision`` and ``zero=1`` without
    any per-step host sync.  Semantics match the reference scaler:
    halve (down to ``min_loss_scale``) on an overflowing step, double
    (up to ``max_loss_scale``) after ``scale_window`` consecutive clean
    steps.
    """

    def __init__(self, init_scale=2.**16, scale_factor=2., scale_window=2000,
                 max_loss_scale=2.**24, min_loss_scale=1.0):
        if init_scale <= 0 or scale_factor <= 1:
            raise ValueError("init_scale must be > 0 and scale_factor > 1")
        if int(scale_window) < 1:
            raise ValueError("scale_window must be >= 1")
        self.init_scale = float(init_scale)
        self.scale_factor = float(scale_factor)
        self.scale_window = int(scale_window)
        self.max_loss_scale = float(max_loss_scale)
        self.min_loss_scale = float(min_loss_scale)

    def __repr__(self):
        return ("DynamicLossScale(init=%g, factor=%g, window=%d, max=%g)"
                % (self.init_scale, self.scale_factor, self.scale_window,
                   self.max_loss_scale))


class FunctionalOptimizer:
    """Pure-functional optimizer over parameter pytrees (the reference's
    optimizer update ops composed into the jitted step).

    ``multi_precision=True`` keeps an f32 master copy of every parameter
    in the optimizer state and routes the update through the ``mp_*``
    master-weight ops: gradients are promoted to f32, momentum/mean/var
    accumulate in f32, and only the committed weight is cast back to the
    parameter dtype — fixing the bf16-param path where grads and
    momentum otherwise accumulate in bf16.  Combined with ``zero=1`` on
    the step, the master copy is dp-sharded, so it costs 1/N per device.

    ``rescale_grad`` multiplies gradients before the update (the
    reference update-op semantics), so ``Trainer(rescale_grad=...)``
    parity holds for scaled losses.
    """

    def __init__(self, name="sgd", learning_rate=0.01, momentum=0.9, wd=0.0,
                 beta1=0.9, beta2=0.999, epsilon=1e-8, clip_gradient=-1.0,
                 rescale_grad=1.0, multi_precision=False):
        self.name = name
        self.lr = learning_rate
        self.momentum = momentum
        self.wd = wd
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon
        # per-element gradient clipping, as in the reference update ops;
        # <= 0 disables
        self.clip_gradient = float(clip_gradient or -1.0)
        self.rescale_grad = float(rescale_grad)
        self.multi_precision = bool(multi_precision)
        if name not in ("sgd", "adam", "lamb", "adamw"):
            raise ValueError("unsupported fused optimizer %r" % name)
        if self.multi_precision and name not in ("sgd", "adam"):
            raise ValueError(
                "multi_precision master weights are implemented for "
                "sgd/adam (the mp_* update ops); got %r" % name)

    @property
    def has_state(self):
        """False only for plain sgd (no momentum, no master weights) —
        the one optimizer whose state pytree is empty."""
        return self.multi_precision or self.name != "sgd" \
            or bool(self.momentum)

    def init(self, param_vals: List[Any]):
        """Fresh per-parameter state.  With ``multi_precision`` every
        parameter gains an f32 master copy as the LAST leaf of its state
        tuple; accumulators are created in f32 regardless of the
        parameter dtype."""
        if self.multi_precision:
            def w32(p):
                # force a DISTINCT buffer: astype is a no-op for f32
                # params, and a master weight aliasing the param buffer
                # makes the donated step execute-fail ("attempt to
                # donate the same buffer twice" — both live in the
                # donated argnums)
                return jnp.array(p, dtype=jnp.float32, copy=True)

            def z32(p):
                return jnp.zeros(p.shape, jnp.float32)

            if self.name == "sgd":
                if self.momentum:
                    return [(z32(p), w32(p)) for p in param_vals]
                return [w32(p) for p in param_vals]
            return [(z32(p), z32(p), w32(p)) for p in param_vals]  # adam
        if self.name == "sgd":
            if self.momentum:
                return [jnp.zeros_like(p) for p in param_vals]
            return []
        return [(jnp.zeros_like(p), jnp.zeros_like(p)) for p in param_vals]

    def state_shardings(self, per_param):
        """Mirror :meth:`init`'s per-parameter state structure with the
        given sharding objects (one entry per parameter) — the single
        place where step builders derive optimizer-state placement."""
        if self.multi_precision:
            if self.name == "sgd" and not self.momentum:
                return list(per_param)
            n = 2 if self.name == "sgd" else 3
            return [(s,) * n for s in per_param]
        if self.name == "sgd":
            return list(per_param) if self.momentum else []
        return [(s, s) for s in per_param]

    def state_range_hints(self):
        """Per-LEAF ``(lo, hi)`` value-range seeds for ONE parameter's
        state tuple, congruent with :meth:`init`'s structure — the
        graftrange analysis' (``analysis/value_range.py``) knowledge of
        optimizer-state invariants: variance accumulators are
        non-negative by construction (they average squared gradients),
        so ``sqrt(var)+eps`` divides clean; momentum/master-weight
        leaves are unknown."""
        var = (0.0, None)
        if self.multi_precision:
            if self.name == "sgd":
                return [None, None] if self.momentum else [None]
            return [None, var, None]       # adam: mean, var, w32
        if self.name == "sgd":
            return [None] if self.momentum else []
        return [None, var]                 # adam/lamb/adamw: mean, var

    def apply_single(self, p, g, s, step_count):
        """One parameter's update: ``(weight, grad, state, step)`` →
        ``(new_weight, new_state)``.

        ``step_count`` is the 1-BASED step number: the fused step
        increments its carried counter BEFORE applying, so adam's
        ``1 - beta**t`` bias correction sees ``t=1`` on the first update
        (``t=0`` would divide by zero — see the regression test in
        tests/test_zero_sharding.py).

        sgd/adam updates are elementwise, so this applies unchanged to
        ZeRO shards; lamb's trust ratio is a global weight/update norm
        and is excluded from sharded application by the caller.
        """
        mp = self.multi_precision
        if not mp:
            g = g.astype(jnp.float32) if p.dtype == jnp.float32 \
                else g.astype(p.dtype)
        if self.name == "sgd":
            if mp:
                if self.momentum:
                    mom32, w32 = s
                    w, m2, w32n = _oops._mp_sgd_mom_update(
                        p, g, mom32, w32, lr=self.lr,
                        momentum=self.momentum, wd=self.wd,
                        rescale_grad=self.rescale_grad,
                        clip_gradient=self.clip_gradient)
                    return w, (m2, w32n)
                w, w32n = _oops._mp_sgd_update(
                    p, g, s, lr=self.lr, wd=self.wd,
                    rescale_grad=self.rescale_grad,
                    clip_gradient=self.clip_gradient)
                return w, w32n
            if self.momentum:
                w, m = _oops._sgd_mom_update(
                    p, g, s, lr=self.lr, momentum=self.momentum, wd=self.wd,
                    rescale_grad=self.rescale_grad,
                    clip_gradient=self.clip_gradient)
                return w, m
            return _oops._sgd_update(
                p, g, lr=self.lr, wd=self.wd,
                rescale_grad=self.rescale_grad,
                clip_gradient=self.clip_gradient), None
        if self.name == "adam":
            # bias correction in f32: with the package-wide x64 flag on,
            # `beta ** int32_t` promotes to f64 and the corrected lr
            # would silently promote every updated PARAM to float64
            # (defeating donation).  t is 1-based — see the docstring.
            t = jnp.asarray(step_count, jnp.float32)
            lr = self.lr * jnp.sqrt(1 - self.beta2 ** t) / (1 - self.beta1 ** t)
            if mp:
                mean, var, w32 = s
                w, m2, v2, w32n = _oops._mp_adam_update(
                    p, g, mean, var, w32, lr=lr, beta1=self.beta1,
                    beta2=self.beta2, epsilon=self.epsilon, wd=self.wd,
                    rescale_grad=self.rescale_grad,
                    clip_gradient=self.clip_gradient)
                return w, (m2, v2, w32n)
            mean, var = s
            w, m2, v2 = _oops._adam_update(
                p, g, mean, var, lr=lr, beta1=self.beta1, beta2=self.beta2,
                epsilon=self.epsilon, wd=self.wd,
                rescale_grad=self.rescale_grad,
                clip_gradient=self.clip_gradient)
            return w, (m2, v2)
        # lamb / adamw
        mean, var = s
        gw, m2, v2 = _oops._lamb_phase1(p, g, mean, var, beta1=self.beta1,
                                        beta2=self.beta2,
                                        epsilon=self.epsilon,
                                        t=step_count, wd=self.wd,
                                        rescale_grad=self.rescale_grad,
                                        clip_gradient=self.clip_gradient)
        w = _oops._lamb_phase2(p, gw, None, lr=self.lr)
        return w, (m2, v2)

    def apply(self, param_vals, grads, states, step_count):
        new_p, new_s = [], []
        for i, (p, g) in enumerate(zip(param_vals, grads)):
            s = states[i] if self.has_state else None
            w, s2 = self.apply_single(p, g, s, step_count)
            new_p.append(w)
            if self.has_state:
                new_s.append(s2)
        return new_p, new_s


class TrainStep:
    """Callable train step bound to a gluon net + loss + fused optimizer.

    Usage::

        step = make_train_step(net, loss_fn, optimizer='sgd', learning_rate=.1)
        loss = step(x, y)      # one XLA program: fwd+bwd+allreduce+update
    """

    def __init__(self, net, loss_fn, opt: FunctionalOptimizer,
                 compute_dtype=None, mesh: Optional[Mesh] = None,
                 batch_axis: str = "dp",
                 param_shardings: Optional[Dict[str, Any]] = None,
                 donate: bool = True, pipeline_stages: Optional[int] = None,
                 num_micro: int = 1, pipeline_axis: str = "pp",
                 pipeline_remat: bool = False, zero: int = 0,
                 lint: Optional[str] = None,
                 lint_suppress: Tuple[str, ...] = (),
                 nonfinite: Optional[str] = None,
                 loss_scale=None, cost: Optional[str] = None,
                 hbm_budget: Optional[float] = None,
                 cost_device: str = "tpu-v5e",
                 passes=None, numerics: Optional[str] = None,
                 input_range=None, skip_streak_budget: Optional[int] = None,
                 sync: str = "allreduce",
                 staleness_bound: Optional[int] = None, compression=None):
        self.net = net
        self.loss_fn = loss_fn
        self.opt = opt
        self.compute_dtype = compute_dtype
        self.mesh = mesh
        self.batch_axis = batch_axis
        self.param_shardings = param_shardings or {}
        self.pipeline_stages = pipeline_stages
        self.num_micro = num_micro
        self.pipeline_axis = pipeline_axis
        self.pipeline_remat = pipeline_remat
        # ZeRO-1 weight-update sharding (arXiv:2004.13336): reduce-
        # scatter grads over the dp axis, update 1/N of the weights per
        # replica against dp-sharded optimizer state, all-gather the
        # result.  0 = off (replicated update), 1 = ZeRO stage 1.
        self.zero = int(zero or 0)
        if self.zero not in (0, 1):
            raise ValueError("zero must be 0 (off) or 1 (ZeRO-1 "
                             "weight-update sharding), got %r" % (zero,))
        if self.zero:
            if mesh is None or batch_axis not in mesh.axis_names:
                raise ValueError(
                    "zero=1 shards the weight update over the %r mesh "
                    "axis — pass a mesh that has it" % batch_axis)
            if opt.name not in ("sgd", "adam"):
                raise ValueError(
                    "zero=1 needs an elementwise update (sgd/adam); "
                    "%r's trust ratio is a global norm over the whole "
                    "weight and cannot run on a 1/N shard" % opt.name)
        self._zero_pad0 = None  # per-gp-param padded leading dim, or None
        # ---- resilience: non-finite step containment + loss scaling ----
        # loss_scale: None (off) | float (static) | "dynamic" |
        # DynamicLossScale instance.  The scale and its counters are
        # device-carried step state (see DynamicLossScale).
        if loss_scale is None:
            self._scale_cfg = None
        elif isinstance(loss_scale, DynamicLossScale):
            self._scale_cfg = loss_scale
        elif isinstance(loss_scale, str):
            if loss_scale != "dynamic":
                raise ValueError("loss_scale must be None, a positive "
                                 "number, 'dynamic' or a DynamicLossScale; "
                                 "got %r" % (loss_scale,))
            self._scale_cfg = DynamicLossScale()
        elif isinstance(loss_scale, (int, float)):
            if loss_scale <= 0:
                raise ValueError("static loss_scale must be positive, "
                                 "got %r" % (loss_scale,))
            self._scale_cfg = float(loss_scale)
        else:
            raise ValueError("loss_scale must be None, a positive number, "
                             "'dynamic' or a DynamicLossScale; got %r"
                             % (loss_scale,))
        self._dynamic_scale = isinstance(self._scale_cfg, DynamicLossScale)
        # nonfinite: what a step with any non-finite gradient does.
        # "skip"  — contain it: params, aux state, optimizer state and the
        #           step counter stay bit-identical (one fused all-finite
        #           reduction + a select guard, still one XLA program);
        # "raise" — contain it AND raise FloatingPointError on the host;
        # "off"   — no guard (the pre-resilience program, bit for bit).
        # Default: "skip" when a dynamic scaler is on (its contract
        # REQUIRES skipping overflowed steps), else "off".
        if nonfinite is None:
            nonfinite = "skip" if self._dynamic_scale else "off"
        if nonfinite not in ("skip", "raise", "off"):
            raise ValueError("nonfinite must be 'skip', 'raise' or 'off', "
                             "got %r" % (nonfinite,))
        if self._dynamic_scale and nonfinite == "off":
            raise ValueError(
                "a dynamic loss scale requires skipping overflowed steps "
                "(they are how it detects the scale is too high) — use "
                "nonfinite='skip' or 'raise', not 'off'")
        self.nonfinite = nonfinite
        # skip_streak_budget: DECLARED bound on consecutive skipped
        # steps — enforcement lives in the supervised loop
        # (parallel/supervisor.py reads it as its detector default);
        # declaring it (or a dynamic scale) is what silences GL012,
        # the unbounded-silent-skip-streak lint.
        if skip_streak_budget is not None and int(skip_streak_budget) < 1:
            raise ValueError("skip_streak_budget must be >= 1 or None, "
                             "got %r" % (skip_streak_budget,))
        self.skip_streak_budget = None if skip_streak_budget is None \
            else int(skip_streak_budget)
        # ---- sync→async policy ladder (parallel/param_service.py) ----
        # sync: "allreduce" (the fused collective step, default),
        # "async" (bounded-staleness push/pull through a ParamService),
        # "auto" (start at allreduce; the supervisor's straggler
        # verdicts degrade to async and recover back — SyncPolicy).
        if sync not in ("allreduce", "async", "auto"):
            raise ValueError("sync must be 'allreduce', 'async' or "
                             "'auto', got %r" % (sync,))
        if sync != "allreduce":
            # v1 surface of the async rung: one process-local replica
            # per rank (the ps-worker model — ranks exchange through
            # the service, not through GSPMD collectives), no loss
            # scaling (pushes are unscaled gradients), no pipelining,
            # no ZeRO (optimizer state lives server-side).
            if mesh is not None:
                raise ValueError(
                    "sync=%r exchanges gradients through the parameter "
                    "service, not through mesh collectives — build the "
                    "async-capable step with mesh=None (one replica per "
                    "rank process)" % (sync,))
            if pipeline_stages is not None:
                raise ValueError("sync=%r does not compose with "
                                 "pipeline_stages" % (sync,))
            if self._scale_cfg is not None:
                raise ValueError(
                    "sync=%r pushes unscaled gradients; loss_scale is "
                    "not supported on the async rung" % (sync,))
        if staleness_bound is not None:
            if sync == "allreduce":
                raise ValueError(
                    "staleness_bound only applies to sync='async'/'auto' "
                    "(the bounded-staleness pull clock)")
            if int(staleness_bound) < 0:
                raise ValueError("staleness_bound must be >= 0, got %r"
                                 % (staleness_bound,))
        self.sync = sync
        self.staleness_bound = 4 if staleness_bound is None \
            else int(staleness_bound)
        from ..kvstore.gradient_compression import make_compressor

        self._compression = make_compressor(compression)
        from .param_service import SyncPolicy

        self.sync_policy = SyncPolicy(mode=sync)
        self._applied_sync = "async" if sync == "async" else "allreduce"
        self._svc_client = None
        self._svc_attaching = False
        self._grad_jit = None
        #: bounded wait for an async pull (StalenessTimeout past it) —
        #: the slow-peer deadline, lowered by tests
        self.pull_timeout = 300.0
        self._scaler_dev = None  # (scale f32, unskipped i32, skipped i32)
        # set by Trainer.make_fused_step so the lint pass can flag the
        # legacy save_states path (GL007) still reachable on the object
        self._legacy_state_origin = None
        self._ckpt_manager = None
        self._ckpt_every = None
        self._ckpt_prev_count = 0
        self._ckpt_seen_request = 0
        self._ckpt_data_iter = None
        # graftlint Level 1 runs over the traced step before its first
        # compile (docs/ANALYSIS.md): "error" raises on error-severity
        # findings, "warn" prints them, "off" skips the lint trace.
        # Resolution order: explicit arg > MXTPU_LINT env > "warn".
        from .aot import resolve_mode as _resolve_mode

        self.lint = _resolve_mode(lint, "MXTPU_LINT", "warn",
                                  ("off", "warn", "error"), "lint")
        self.lint_suppress = tuple(lint_suppress)
        self._linted = False
        # graftcost rides the same pre-compile trace (analysis/
        # cost_model.py, docs/ANALYSIS.md): "report" computes the
        # CostReport (surfaced as step.cost_report), "check" additionally
        # raises on GL2xx errors — GL201 rejects an over-budget config
        # at trace time, before any compile.  Resolution order: explicit
        # arg > MXTPU_COST env > "off".
        self.cost = _resolve_mode(cost, "MXTPU_COST", "off",
                                  ("off", "report", "check"), "cost")
        if hbm_budget is not None and float(hbm_budget) <= 0:
            raise ValueError("hbm_budget must be positive bytes, got %r"
                             % (hbm_budget,))
        self.hbm_budget = float(hbm_budget) if hbm_budget else None
        from ..analysis.cost_model import DEVICE_SPECS as _SPECS

        if cost_device not in _SPECS:
            raise ValueError("unknown cost_device %r (registry: %s)"
                             % (cost_device, sorted(_SPECS)))
        self.cost_device = cost_device
        self.cost_report = None  # set by the cost pass (cost != "off")
        # graftrange rides the same pre-compile trace (analysis/
        # value_range.py, docs/ANALYSIS.md GL4xx): an abstract value-
        # range & precision interpreter over the step program.  "warn"
        # surfaces GL401-GL405 findings, "error" raises BEFORE any
        # compile (like cost="check"'s GL201), "off" (default) skips
        # the walk.  Resolution: explicit arg > MXTPU_NUMERICS > "off".
        self.numerics = _resolve_mode(numerics, "MXTPU_NUMERICS", "off",
                                      ("off", "warn", "error"),
                                      "numerics")
        # input_range: declared value range of the batch — a (lo, hi)
        # tuple for x, or a dict {"x": (lo, hi), "y": (lo, hi)}.  Seeds
        # the range analysis; everything unannotated defaults
        # conservatively (floats unknown-finite, ints to dtype range).
        if input_range is not None and not isinstance(input_range,
                                                      (tuple, list, dict)):
            raise ValueError(
                "input_range must be a (lo, hi) tuple for x or a dict "
                "{'x': (lo, hi), 'y': (lo, hi)}; got %r" % (input_range,))
        self.input_range = input_range
        self.range_report = None  # set by the numerics pass
        # graftpass: an ordered jaxpr->jaxpr rewrite pipeline applied to
        # the traced step before its first compile (analysis/passes.py,
        # docs/PASSES.md).  Resolution: explicit arg > MXTPU_PASSES env
        # > ().  Invar-changing passes (quantize) no-op here — a train
        # step's params are donated and updated in place, so the
        # PassContext advertises no quantizable param invars.
        # ``passes=`` also accepts a PassSchedule (or its canonical
        # dict), pinning a per-site decision vector (graftsched); a
        # plain pass list is the all-sites schedule, bitwise-equivalent
        from ..analysis.passes import resolve_schedule as _resolve_schedule

        self._passes, self._schedule = _resolve_schedule(passes)
        #: flat-aval signature -> (rewritten ClosedJaxpr, out treedef,
        #: probe-verified flag)
        self._pass_programs: Dict[tuple, tuple] = {}
        #: (x, y) aval keys whose program is fully verified — the
        #: per-step fast path around the full-args flatten
        self._pass_fast_verified: set = set()
        self._pass_effects: List[Any] = []
        self.pass_receipts = None  # receipts of the last pipeline run
        if pipeline_stages is not None:
            if mesh is None:
                raise ValueError("pipeline_stages requires a mesh with a "
                                 "%r axis" % pipeline_axis)
            if pipeline_axis not in mesh.axis_names:
                raise ValueError("mesh %s has no %r axis for pipelining"
                                 % (mesh, pipeline_axis))
            if mesh.shape[pipeline_axis] != pipeline_stages:
                raise ValueError(
                    "pipeline_stages=%d but mesh axis %r has size %d"
                    % (pipeline_stages, pipeline_axis,
                       mesh.shape[pipeline_axis]))
            if num_micro < 1:
                raise ValueError("num_micro must be >= 1")
        # stage partition: per-stage lists of indices into the gp list,
        # plus the stage-0 blocks used to trace the (uniform) stage program
        self._stage_idx = None
        self._stage0_blocks = None
        self._stage0_gp = None
        self._gp = None
        self._aux = None
        self._opt_state = None
        self._step_count = 0
        self._key_dev = None   # device-carried PRNG key (donated each step)
        self._step_dev = None  # device-carried int32 step counter
        self._key_epoch = None  # rng.epoch() at key draw (reseed detection)
        self._jit = None
        self._compiled = None
        self._compiled_key = None
        self._multihost = False
        self._donate = donate
        # the ONE donation spec: state args of step(p_vals, aux_vals,
        # opt_state, x, y, key, step_count, scaler_state) — jit, the
        # multi-step scan program, and the GL003 lint all key off this
        self._donate_argnums = (0, 1, 2, 5, 6, 7) if donate else ()
        self._placed = False
        self._shardings = None

    # ------------------------------------------------------------------
    def _collect(self):
        params = list(self.net.collect_params().values())
        self._gp = [p for p in params if p.grad_req != "null"]
        self._aux = [p for p in params if p.grad_req == "null"]
        if self.pipeline_stages is not None:
            self._collect_pipeline()
        if self.zero:
            self._build_zero_plan()

    def _build_zero_plan(self):
        """Per-parameter ZeRO layout: the padded leading dim (a multiple
        of the dp axis size — pad-and-slice, never silently replicate),
        or None for params the dp-sharded update does not cover:

        - params already sharded by ``param_shardings`` (tp/ep): their
          optimizer state shards like the parameter, so it is already
          distributed — ZeRO over dp would fight the existing layout;
        - 0-d (scalar) params: nothing to slice.
        """
        n = self.mesh.shape[self.batch_axis]
        plan = []
        for p in self._gp:
            spec = tuple(self.param_shardings.get(p.name, P()))
            sharded = any(e is not None and e != () for e in spec)
            if sharded or len(p.shape) < 1:
                plan.append(None)
            else:
                plan.append(-(-p.shape[0] // n) * n)  # ceil to multiple
        self._zero_pad0 = plan

    @staticmethod
    def _zero_padded(v, pad0):
        """Pad the leading dim up to ``pad0`` (identity when it already
        divides)."""
        if pad0 is None or pad0 == v.shape[0]:
            return v
        return jnp.pad(v, [(0, pad0 - v.shape[0])]
                       + [(0, 0)] * (v.ndim - 1))

    # ------------------------------------------------------------------
    def _finish_step(self, loss_val, grads, p_vals, aux_vals, new_aux,
                     opt_state, key, step_count, scaler):
        """Shared tail of every step program: (un)scale, guard, update.

        One fused global all-finite reduction over the whole grad tree
        (``ops.optimizer_ops.tree_all_finite`` — a single scalar inside
        the program, NOT per-param host syncs), then the optimizer leg,
        then — when containment is on — a select guard: a step with any
        non-finite gradient leaves params, aux state, optimizer state
        (incl. pipeline/ZeRO shards: the select runs on the final,
        full-tree outputs, so sharded layouts pass through untouched)
        and the step counter bit-identical.  The select form is
        donation-safe: both arms alias the same donated buffers and XLA
        lowers it to a predicated copy.  The dynamic scaler (when
        configured) halves on overflow and doubles after
        ``scale_window`` clean steps, functionally, in the carried
        ``(scale, unskipped, skipped)`` state.
        """
        scale, unskipped, skipped = scaler
        scaling = self._scale_cfg is not None
        guard = self.nonfinite != "off"
        if guard:
            # finiteness is checked on the RAW (still scaled) grads:
            # that is where fp16 overflow appears, and unscaling an inf
            # cannot rescue it anyway
            ok = _oops.tree_all_finite(grads)
        else:
            ok = jnp.array(True)
        if scaling:
            # powers-of-two scales make the multiply exact; compute in
            # the wider of (grad dtype, f32) so f16/bf16 grads unscale
            # in f32 while f64 grads keep their full mantissa
            inv = (1.0 / scale).astype(jnp.float32)

            def unscale(g):
                ct = jnp.promote_types(g.dtype, jnp.float32)
                return (g.astype(ct) * inv.astype(ct)).astype(g.dtype)

            grads = [unscale(g) for g in grads]
            loss_val = loss_val * inv
        c1 = step_count + 1
        new_p, new_s = self._apply_update(p_vals, grads, opt_state, c1)
        if guard:
            def sel(n, o):
                return jnp.where(ok, n, o)

            new_p = [sel(n, o) for n, o in zip(new_p, p_vals)]
            new_aux = [sel(n, o) for n, o in zip(new_aux, aux_vals)]
            new_s = jax.tree.map(sel, new_s, opt_state)
            c1 = sel(c1, step_count)
            skipped = skipped + jnp.where(ok, jnp.int32(0), jnp.int32(1))
            if self._dynamic_scale:
                cfg = self._scale_cfg
                unsk = jnp.where(ok, unskipped + 1, jnp.int32(0))
                grow = unsk >= cfg.scale_window
                scale = jnp.where(
                    ok,
                    jnp.where(grow,
                              jnp.minimum(scale * cfg.scale_factor,
                                          cfg.max_loss_scale),
                              scale),
                    jnp.maximum(scale / cfg.scale_factor,
                                cfg.min_loss_scale)).astype(jnp.float32)
                unskipped = jnp.where(grow, jnp.int32(0), unsk)
        return (loss_val, new_p, list(new_aux), new_s, key, c1,
                (scale, unskipped, skipped), ok)

    def _apply_update(self, p_vals, grads, opt_state, step_count):
        """The optimizer leg of the step program: plain replicated apply,
        or the ZeRO-1 sharded update when ``zero=1``."""
        if not self.zero:
            return self.opt.apply(p_vals, grads, opt_state, step_count)
        return self._apply_zero(p_vals, grads, opt_state, step_count)

    def _apply_zero(self, p_vals, grads, opt_state, step_count):
        """ZeRO-1 weight update over the dp axis (arXiv:2004.13336).

        Inside a ``shard_map`` over the mesh's dp axis: each rank
        consumes only its 1/N gradient and weight shard (sliced by
        ``axis_index``), updates it against its dp-sharded optimizer-
        state shard, and re-materializes the full parameter with
        ``collectives.allgather``.  The grad slice — not an explicit
        collective — is deliberate: on jax 0.4.x the grads reach this
        point dp-replicated (GSPMD has already summed the per-replica
        partials), so slicing is free and exact for ANY axis size, and
        ``all-reduce + per-rank slice`` is precisely the pattern the
        paper's XLA reduce-scatter-creation pass rewrites into a single
        reduce-scatter on TPU; an explicit ``psum_scatter`` here would
        be a REDUNDANT second collective (summing N identical copies,
        with rounding drift for non-power-of-two N) — the waste class
        graftlint GL006 flags for all_gather.  Params/grads enter the
        body replicated and are sliced per rank inside it — also the
        jax 0.4.x-safe pattern (a jit-internal padded operand fed to a
        sharded in_spec risks the GSPMD stacked-operand miscompile,
        graftlint GL002).  Ragged leading dims are padded to a multiple
        of N and the padding is sliced back off after the gather.

        With pipelined grad accumulation (dp×pp), the microbatch grads
        are already summed by the scan transpose, so the grad reduction
        happens ONCE at the end of the step, not per microbatch.
        """
        from . import collectives
        from .mesh import shard_map as _shard_map

        mesh, ax = self.mesh, self.batch_axis
        n = mesh.shape[ax]
        opt = self.opt
        pad0s = self._zero_pad0
        z_idx = [i for i, pad in enumerate(pad0s) if pad is not None]
        r_idx = [i for i, pad in enumerate(pad0s) if pad is None]

        new_p: List[Any] = [None] * len(p_vals)
        new_s: List[Any] = [None] * len(p_vals) if opt.has_state else []
        if r_idx:
            # tp/ep-sharded and scalar params: plain update; their state
            # already shards like the parameter
            rp, rs = opt.apply(
                [p_vals[i] for i in r_idx], [grads[i] for i in r_idx],
                [opt_state[i] for i in r_idx] if opt.has_state else [],
                step_count)
            for j, i in enumerate(r_idx):
                new_p[i] = rp[j]
                if opt.has_state:
                    new_s[i] = rs[j]
        if not z_idx:
            return new_p, new_s

        z_p = [p_vals[i] for i in z_idx]
        z_g = [grads[i] for i in z_idx]
        z_s = [opt_state[i] for i in z_idx] if opt.has_state else []
        z_pad = [pad0s[i] for i in z_idx]
        shard_spec = P(ax)

        def body(zp, zg, zs, c):
            idx = jax.lax.axis_index(ax)
            out_p, out_s = [], []
            for k, (p, g) in enumerate(zip(zp, zg)):
                pad0 = z_pad[k]
                rows = pad0 // n
                p_pad = self._zero_padded(p, pad0)
                g_pad = self._zero_padded(g, pad0)
                g_shard = jax.lax.dynamic_slice_in_dim(
                    g_pad, idx * rows, rows, 0)
                p_shard = jax.lax.dynamic_slice_in_dim(
                    p_pad, idx * rows, rows, 0)
                s_k = zs[k] if opt.has_state else None
                w_shard, s_new = opt.apply_single(p_shard, g_shard, s_k, c)
                w_full = collectives.allgather(w_shard, ax, axis=0,
                                               tiled=True)
                if pad0 != p.shape[0]:
                    w_full = jax.lax.slice_in_dim(w_full, 0, p.shape[0],
                                                  axis=0)
                out_p.append(w_full)
                out_s.append(s_new)
            if opt.has_state:
                return tuple(out_p), tuple(out_s)
            return tuple(out_p)

        repl = P()
        in_specs = (tuple(repl for _ in z_p), tuple(repl for _ in z_g),
                    jax.tree.map(lambda _: shard_spec, z_s), repl)
        if opt.has_state:
            out_specs = (tuple(repl for _ in z_p),
                         tuple(jax.tree.map(lambda _: shard_spec, s)
                               for s in z_s))
        else:
            out_specs = tuple(repl for _ in z_p)
        # per-rank slices/shards differ across dp by construction and
        # re-replicate via the all-gather; skip the conservative
        # replication checker (check_vma on jax >= 0.6, check_rep on 0.4)
        try:
            mapped = _shard_map(body, mesh=mesh, in_specs=in_specs,
                                out_specs=out_specs, check_vma=False)
        except TypeError:
            mapped = _shard_map(body, mesh=mesh, in_specs=in_specs,
                                out_specs=out_specs, check_rep=False)
        res = mapped(tuple(z_p), tuple(z_g), z_s, step_count)
        zp_new, zs_new = res if opt.has_state else (res, None)
        for j, i in enumerate(z_idx):
            new_p[i] = zp_new[j]
            if opt.has_state:
                new_s[i] = zs_new[j]
        return new_p, new_s

    def _collect_pipeline(self):
        """Partition the net's children into ``pipeline_stages`` contiguous,
        structurally congruent stages and map each stage's params back to
        their positions in the flat gp list (so donation/optimizer layout
        is identical to the non-pipelined step)."""
        k = self.pipeline_stages
        try:
            children = list(self.net)
        except TypeError:
            raise ValueError(
                "pipeline_stages needs an iterable stacked net "
                "(e.g. HybridSequential); %r is not iterable"
                % type(self.net).__name__)
        if not children or len(children) % k != 0:
            raise ValueError(
                "cannot split %d child blocks into %d pipeline stages"
                % (len(children), k))
        per = len(children) // k
        groups = [children[s * per:(s + 1) * per] for s in range(k)]
        gp_pos = {id(p): i for i, p in enumerate(self._gp)}
        stage_idx, stage_gp = [], []
        for s, blocks in enumerate(groups):
            ps = [p for b in blocks for p in b.collect_params().values()]
            if any(p.grad_req == "null" for p in ps):
                raise NotImplementedError(
                    "pipeline stage %d carries auxiliary state (BatchNorm "
                    "running stats etc.); aux writes cannot escape the "
                    "pipelined scan — use LayerNorm/GroupNorm inside "
                    "pipeline stages" % s)
            gps = [p for p in ps if id(p) in gp_pos]
            stage_gp.append(gps)
            stage_idx.append([gp_pos[id(p)] for p in gps])
        covered = {i for idx in stage_idx for i in idx}
        if covered != set(range(len(self._gp))):
            raise ValueError(
                "net has trainable parameters outside its child blocks; "
                "the SPMD pipeline owns the full parameter set")
        from .pipeline import stage_congruence_mismatch

        first = stage_gp[0]
        sig0 = [(tuple(p.shape), p.dtype) for p in first]
        for s, ps in enumerate(stage_gp[1:], 1):
            reason = stage_congruence_mismatch(
                sig0, [(tuple(p.shape), p.dtype) for p in ps], s)
            if reason:
                raise ValueError(
                    "pipeline stages must be structurally congruent "
                    "(%s) — uniform-stage SPMD pipelining runs ONE "
                    "stage program with per-rank values" % reason)
        self._stage_idx = stage_idx
        self._stage0_blocks = groups[0]
        self._stage0_gp = first

    def _cast_inputs(self, pv, x):
        """Shared dtype policy: params re-cast to the compute dtype;
        unsigned-int inputs are raw image bytes (ImageRecordUInt8Iter) —
        promote them so convs run in the compute dtype too."""
        compute_dtype = self.compute_dtype
        if compute_dtype is not None:
            pv_c = [v.astype(compute_dtype)
                    if jnp.issubdtype(v.dtype, jnp.floating) else v
                    for v in pv]
            if jnp.issubdtype(x.dtype, jnp.floating) or \
                    jnp.issubdtype(x.dtype, jnp.unsignedinteger):
                x_c = x.astype(compute_dtype)
            else:
                x_c = x
        else:
            pv_c = pv
            x_c = x.astype(jnp.float32) \
                if jnp.issubdtype(x.dtype, jnp.unsignedinteger) else x
        return pv_c, x_c

    def _loss_closure(self, aux_vals, x, y, use_key, scaler):
        """``pv -> (loss, new_aux)`` — the forward+loss closure both the
        fused allreduce step and the async grads-only program
        differentiate (one definition, so the two rungs of the policy
        ladder train the SAME objective)."""
        gp_list, aux_list = self._gp, self._aux
        net, loss_fn = self.net, self.loss_fn

        def loss_of(pv):
            pv_c, x_c = self._cast_inputs(pv, x)
            tc = tracing.TraceContext(use_key, training=True)
            for p, v in zip(gp_list, pv_c):
                tc.bindings[id(p)] = v
            for p, v in zip(aux_list, aux_vals):
                tc.bindings[id(p)] = v
            tracing.push_trace(tc)
            try:
                with autograd.pause():
                    out = net._forward_impl(NDArray(x_c))
                    loss = loss_fn(out, NDArray(y))
                    loss = loss.mean()
            finally:
                tracing.pop_trace()
            # align aux writes to aux_list positions (functional update:
            # unwritten aux flow through unchanged) — no trace-order
            # side channel between tracing and the caller
            new_aux = []
            for p, bound in zip(aux_list, aux_vals):
                w = tc.aux_writes.get(id(p))
                new_aux.append(bound if w is None
                               else w[1].astype(bound.dtype))
            loss_val = loss._data.astype(jnp.float32)
            # aux losses registered during the forward (MoE load
            # balancing etc.) join the objective here, so their
            # gradients flow through the same fused program
            for al in tc.aux_losses:
                loss_val = loss_val + al.astype(jnp.float32)
            if self._scale_cfg is not None:
                # the SCALED loss feeds the backward pass so fp16
                # grads overflow before they denormalize; the
                # reported loss is unscaled again in _finish_step
                loss_val = loss_val * scaler[0]
            return loss_val, new_aux

        return loss_of

    def _make_plain_step(self):
        def step(p_vals, aux_vals, opt_state, x, y, key, step_count, scaler):
            # key/step_count/scaler are DEVICE-carried state (donated,
            # updated in program): a fresh host scalar or an eager key split
            # per step costs ~10-100 ms of serialized host->device transfer
            # through a tunneled runtime, which dominated the measured gap
            key, use_key = jax.random.split(key)
            loss_of = self._loss_closure(aux_vals, x, y, use_key, scaler)
            (loss_val, new_aux), grads = jax.value_and_grad(
                loss_of, has_aux=True)(p_vals)
            return self._finish_step(loss_val, grads, p_vals, aux_vals,
                                     new_aux, opt_state, key, step_count,
                                     scaler)

        return step

    def _make_grad_step(self):
        """The async rung's program: forward+backward ONLY — the
        optimizer lives server-side (``ParamService``'s updater applies
        each push, ps-lite's async ApplyUpdates semantics).  Same loss
        closure as the fused step; aux state and the PRNG key stay
        rank-local device-carried state."""
        def gstep(p_vals, aux_vals, x, y, key):
            key, use_key = jax.random.split(key)
            loss_of = self._loss_closure(aux_vals, x, y, use_key, None)
            (loss_val, new_aux), grads = jax.value_and_grad(
                loss_of, has_aux=True)(p_vals)
            return loss_val, grads, new_aux, key

        return gstep

    def _make_pipeline_step(self):
        """Pipelined fused step: forward microbatches through the SPMD
        1F1B/GPipe schedule, backward via the scan transpose (cotangents
        hop stage←stage through the inverted ppermute), microbatch
        gradient accumulation on-rank, then the optimizer — ONE jitted,
        donated XLA program, zero per-microbatch Python dispatch."""
        loss_fn, opt = self.loss_fn, self.opt
        mesh = self.mesh
        pp_axis = self.pipeline_axis
        num_micro = self.num_micro
        remat = self.pipeline_remat
        n_stage = self.pipeline_stages
        stage_idx = self._stage_idx
        stage0_blocks = self._stage0_blocks
        stage0_gp = self._stage0_gp
        # microbatches keep the batch sharding on their (second) batch dim
        # when the mesh also has a dp axis — pp composes with dp/tp
        mb_spec = P(None, self.batch_axis) \
            if self.batch_axis in mesh.axis_names else P()

        def stage_fn(sp, h):
            # one uniform stage program, traced through stage 0's blocks
            # with this rank's parameter values bound.  key=None: dropout
            # inside pipeline stages would need per-stage key plumbing
            # through the schedule — fail loudly instead of silently
            # desynchronizing the stream
            tc = tracing.TraceContext(None, training=True)
            for p, v in zip(stage0_gp, sp):
                tc.bindings[id(p)] = v
            tracing.push_trace(tc)
            try:
                with autograd.pause():
                    out = NDArray(h)
                    for b in stage0_blocks:
                        out = b._forward_impl(out)
            finally:
                tracing.pop_trace()
            if tc.aux_losses:
                raise NotImplementedError(
                    "aux losses inside pipeline stages cannot escape the "
                    "pipelined scan; place MoE blocks outside the "
                    "pipelined net or train without pipeline_stages")
            return out._data

        def step(p_vals, aux_vals, opt_state, x, y, key, step_count, scaler):
            key, use_key = jax.random.split(key)

            def loss_of(pv):
                pv_c, x_c = self._cast_inputs(pv, x)
                if x_c.shape[0] % num_micro:
                    raise ValueError(
                        "batch %d not divisible into num_micro=%d"
                        % (x_c.shape[0], num_micro))
                # per-stage params, stacked on a leading pp axis; built
                # from the flat list so grads come back per-parameter
                stacked = tuple(
                    jnp.stack([pv_c[stage_idx[s][i]]
                               for s in range(n_stage)])
                    for i in range(len(stage0_gp)))
                micro = x_c.reshape(
                    (num_micro, x_c.shape[0] // num_micro) + x_c.shape[1:])

                def inner(stk, mb):
                    # stage params enter replicated and each rank slices
                    # its own stage by axis index: feeding a jit-internal
                    # stack into shard_map with a P(pp) in_spec miscompiles
                    # on multi-axis meshes (jax 0.4.x GSPMD resharding);
                    # the dynamic-slice form is exact on pp and dp x pp
                    i = jax.lax.axis_index(pp_axis)
                    local = [s_[i] for s_ in stk]
                    return spmd_pipeline(stage_fn, local, mb,
                                         axis_name=pp_axis, remat=remat)

                # pallas_call (the fused ghost-BN kernels a staged
                # block may contain) carries no replication-rule
                # metadata; skip the replication checker like the
                # zero-update leg does (check_vma on jax >= 0.6,
                # check_rep on 0.4)
                try:
                    mapped = shard_map(
                        inner, mesh=mesh,
                        in_specs=(tuple(P() for _ in stacked), mb_spec),
                        out_specs=mb_spec, check_vma=False)
                except TypeError:
                    mapped = shard_map(
                        inner, mesh=mesh,
                        in_specs=(tuple(P() for _ in stacked), mb_spec),
                        out_specs=mb_spec, check_rep=False)
                outs = mapped(stacked, micro)
                flat = outs.reshape((-1,) + outs.shape[2:])
                tc = tracing.TraceContext(use_key, training=True)
                tracing.push_trace(tc)
                try:
                    with autograd.pause():
                        loss = loss_fn(NDArray(flat), NDArray(y))
                        loss = loss.mean()
                finally:
                    tracing.pop_trace()
                loss_val = loss._data.astype(jnp.float32)
                for al in tc.aux_losses:
                    loss_val = loss_val + al.astype(jnp.float32)
                if self._scale_cfg is not None:
                    loss_val = loss_val * scaler[0]
                return loss_val, list(aux_vals)

            (loss_val, new_aux), grads = jax.value_and_grad(
                loss_of, has_aux=True)(p_vals)
            # microbatch grads are already accumulated by the scan
            # transpose; under zero=1 they reduce-scatter ONCE here —
            # and the non-finite guard sees the fully-accumulated tree
            return self._finish_step(loss_val, grads, p_vals, aux_vals,
                                     new_aux, opt_state, key, step_count,
                                     scaler)

        return step

    def _build(self):
        step = self._make_pipeline_step() if self.pipeline_stages \
            else self._make_plain_step()
        self._step_fn = step  # shared by the multi-step (scan) program
        return self._jit_for(step)

    def _jit_for(self, step):
        """jit one step-shaped callable under this step's donation and
        sharding specs — shared by the base program and the graftpass-
        rewritten one (same interface by construction: GL301 gates it)."""
        gp_list, aux_list = self._gp, self._aux
        donate = self._donate_argnums
        if self.mesh is None:
            return jax.jit(step, donate_argnums=donate)

        mesh = self.mesh
        repl = NamedSharding(mesh, P())

        def p_shard(p):
            spec = self.param_shardings.get(p.name, P())
            return NamedSharding(mesh, spec)

        p_sh = [p_shard(p) for p in gp_list]
        aux_sh = [repl for _ in aux_list]
        # a pp- or ep-only mesh has no batch axis: batches stay replicated
        batch_sh = NamedSharding(mesh, P(self.batch_axis)) \
            if self.batch_axis in mesh.axis_names else repl
        # opt state shards like its parameter; under zero=1 the state of
        # every dp-covered param is instead dp-sharded on its (padded)
        # leading dim — the 1/N memory the feature exists for
        if self.zero:
            zsh = NamedSharding(mesh, P(self.batch_axis))
            per_param = [zsh if pad is not None else s
                         for s, pad in zip(p_sh, self._zero_pad0)]
        else:
            per_param = p_sh
        state_sh = self.opt.state_shardings(per_param)
        self._shardings = (p_sh, aux_sh, state_sh, batch_sh, repl)
        return jax.jit(step, donate_argnums=donate,
                       in_shardings=(p_sh, aux_sh, state_sh, batch_sh,
                                     batch_sh, repl, repl, repl),
                       out_shardings=(repl, p_sh, aux_sh, state_sh, repl,
                                      repl, repl, repl))

    # ------------------------------------------------------------------
    # graftpass (analysis/passes.py, docs/PASSES.md)
    def _pass_pipeline_inputs(self, example_args, probe=True):
        """The ONE trace-and-context block behind both pipeline
        entrances (`_maybe_apply_passes` installs, `analyze_schedule`
        reports): returns ``(traced, ctx, n_dev, multihost)`` for the
        step's argument signature."""
        from ..analysis.passes import PassContext
        from ..analysis.trace_lint import donated_leaf_indices
        from .aot import traced_with_effects
        from .mesh import spans_processes

        base = getattr(self, "_base_jit", None) or self._jit
        traced, effects = traced_with_effects(
            base, tuple(example_args), capture=self.lint != "off")
        if effects and not self._pass_effects:
            # GL004 effects surface on the BASE trace (the rewritten
            # program replays a finished trace); stash them for the
            # lint report over the rewritten program
            self._pass_effects = list(effects)
        axis_sizes, n_dev, multihost = None, 1, False
        if self.mesh is not None:
            axis_sizes = {k: int(v)
                          for k, v in dict(self.mesh.shape).items()}
            n_dev = int(self.mesh.size)
            multihost = spans_processes(self.mesh)
        num_seeds = None
        if self.numerics != "off":
            num_seeds = self._numerics_seeds(tuple(example_args))[0]
        ctx = PassContext(
            param_invars=frozenset(),  # donated+updated: not quantizable
            allow_invar_change=False,
            donated_leaves=tuple(donated_leaf_indices(
                tuple(example_args), self._donate_argnums)),
            axis_sizes=axis_sizes,
            # a process-spanning program cannot be evaluated eagerly on
            # this host alone; abstract eval + re-lint still gate it
            probe="off" if (multihost or not probe) else "auto",
            # the graftrange hookup: amp_bf16's per-op GL403 gate rides
            # the step's numerics mode and input annotations
            numerics=self.numerics,
            input_ranges=num_seeds,
            where="fused train step")
        return traced, ctx, n_dev, multihost

    def _maybe_apply_passes(self, example_args, probe=True):
        """Run the configured pass pipeline over the traced step for
        this argument signature and install the verified rewrite as the
        program that compiles.  Idempotent per flat-aval signature; the
        contract gates (GL301/GL302) raise BEFORE any compile, so a
        refused rewrite costs zero executables.  The rewritten step
        keeps the exact invar layout, donation spec and shardings —
        invar-changing passes are refused here by construction.

        ``probe=False`` skips the concrete probe (abstract eval,
        re-lint and cost receipts still gate) — the cheap ranking mode
        ``analyze_cost`` uses so the autotuner's zero-compile phase
        never pays two eager step executions per candidate.  A program
        ranked that way is RE-verified with the probe the first time a
        run path (``__call__``/``aot_compile``/``run_steps``) asks for
        it: nothing unprobed ever compiles."""
        if not self._passes:
            return
        # hot-path fast key: only the batch args vary between calls on
        # one step instance (params/opt-state/scaler avals are pinned
        # at build), so a verified (x, y) signature skips the full
        # O(n_leaves) flatten every subsequent step would otherwise pay
        x_ex, y_ex = example_args[3], example_args[4]
        fast = (tuple(x_ex.shape), str(x_ex.dtype),
                tuple(y_ex.shape), str(y_ex.dtype))
        if fast in self._pass_fast_verified:
            return
        flat = jax.tree_util.tree_leaves(tuple(example_args))
        sig = tuple((tuple(v.shape), str(v.dtype)) for v in flat)
        entry = self._pass_programs.get(sig)
        if entry is not None and (entry[2] or not probe):
            if entry[2]:
                self._pass_fast_verified.add(fast)
            return
        from ..analysis.passes import PassManager

        traced, ctx, n_dev, multihost = self._pass_pipeline_inputs(
            example_args, probe=probe)
        mgr = PassManager(self._passes, schedule=self._schedule,
                          device=self.cost_device, n_devices=n_dev)
        result = mgr.run(traced.jaxpr, ctx)
        self.pass_receipts = result.receipts
        out_tree = jax.tree_util.tree_structure(traced.out_info)
        # multihost counts as verified-as-far-as-possible: the probe
        # can never run there, so a False flag would re-run the whole
        # pipeline (trace + lint + cost walks) on every step
        verified = bool(probe) or multihost
        self._pass_programs[sig] = (result.closed_jaxpr, out_tree,
                                    verified)
        if verified:
            self._pass_fast_verified.add(fast)
        if getattr(self, "_base_jit", None) is None:
            self._base_jit = self._jit
            programs = self._pass_programs

            def step2(p_vals, aux_vals, opt_state, x, y, key, step_count,
                      scaler):
                fl = jax.tree_util.tree_leaves(
                    (p_vals, aux_vals, opt_state, x, y, key, step_count,
                     scaler))
                s = tuple((tuple(v.shape), str(v.dtype)) for v in fl)
                entry = programs.get(s)
                if entry is None:
                    raise RuntimeError(
                        "graftpass: no rewritten program for argument "
                        "signature %r — the pass pipeline runs per batch "
                        "signature before trace; this trace bypassed it"
                        % (s[:4],))
                rj, otree = entry[0], entry[1]
                from jax import core as _jcore

                return jax.tree_util.tree_unflatten(
                    otree, _jcore.eval_jaxpr(rj.jaxpr, rj.consts, *fl))

            self._step_fn = step2
            self._jit = self._jit_for(step2)
            self._multi_jit = None  # rebuilt over the rewritten step

    # ------------------------------------------------------------------
    def _maybe_lint(self, example_args):
        """graftlint Level 1 over the step program, BEFORE its first XLA
        compile: checks collective permutations (GL001), partition specs
        incl. the jax 0.4.x stacked-operand GSPMD hazard (GL002),
        donation aliasing against this step's donate_argnums (GL003),
        and aux effects dropped by remat regions (GL004).  The lint
        walks ``self._jit.trace(...)`` — the very trace jit caches for
        the first call — so it costs one jaxpr walk, not an extra
        trace; steady-state steps pay nothing."""
        if self._linted or (self.lint == "off" and self.cost == "off"
                            and self.numerics == "off"):
            return
        self._lint_trace(self._jit, tuple(example_args))

    def _lint_trace(self, jit_obj, args):
        """The one lint ritual: trace ``jit_obj`` (GL004 hooks active),
        lint the jaxpr, and mark this step linted — only after a
        non-raising lint, so in "error" mode a caught/retried LintError
        re-lints (and re-raises) instead of compiling the flagged
        program.  Returns the traced object (shared with the jit's
        trace cache, so the first call/compile reuses it)."""
        from .aot import traced_with_effects

        lint_here = self.lint != "off" and not self._linted
        cost_here = self.cost != "off" and not self._linted
        num_here = self.numerics != "off" and not self._linted
        traced, effects = traced_with_effects(jit_obj, tuple(args),
                                              capture=lint_here)
        if lint_here and self._pass_effects:
            # GL004 effects were captured on the base trace the pass
            # pipeline consumed (the rewritten program replays it)
            effects = list(effects) + list(self._pass_effects)
        if lint_here:
            self._finish_lint(traced.jaxpr, effects, args)
        if cost_here:
            # same trace, one more walk: the cost model's GL201 gate
            # fires HERE — before lower/compile ever run
            self._finish_cost(traced.jaxpr, args)
        if num_here:
            # same trace, the graftrange walk: GL401-GL405 fire HERE,
            # before lower/compile — numerics="error" rejects the
            # program with zero compiles spent
            self._finish_numerics(traced.jaxpr, args)
        if lint_here or cost_here or num_here:
            self._linted = True
        return traced

    def _finish_lint(self, closed_jaxpr, effect_diags, example_args):
        from ..analysis.trace_lint import donated_leaf_indices
        from .aot import finish_lint

        donated = donated_leaf_indices(tuple(example_args),
                                       self._donate_argnums)
        extra = []
        if self.zero and self._shardings is not None:
            # GL006: a zero=1 step whose optimizer state is still
            # replicated over the dp axis keeps the N× memory the
            # feature exists to remove
            from ..analysis.trace_lint import check_zero_state_shardings

            state_sh = self._shardings[2]
            covered = [sh for sh, pad in zip(state_sh, self._zero_pad0)
                       if pad is not None] if state_sh else []
            extra.extend(check_zero_state_shardings(
                covered, self.batch_axis,
                where="TrainStep(zero=1) optimizer state"))
        if self.zero and self._legacy_state_origin:
            # GL007: the Trainer this step was built from still exposes
            # the legacy save_states/load_states path, which cannot
            # represent dp-sharded optimizer state
            from ..analysis.trace_lint import check_legacy_checkpoint_path

            extra.extend(check_legacy_checkpoint_path(
                self._legacy_state_origin,
                where="Trainer.make_fused_step(zero=1)"))
        # GL012: a silently-unbounded skip streak — nonfinite="skip"
        # under a static scale with no declared skip_streak_budget
        from ..analysis.trace_lint import check_unbounded_skip

        extra.extend(check_unbounded_skip(
            self.nonfinite, self._dynamic_scale, self.skip_streak_budget,
            where="TrainStep(nonfinite='skip', loss_scale=static)"))
        # GL013: error-feedback compression whose residual state can
        # never reach the checkpoint save set (sync='allreduce' steps
        # checkpoint no param-service subtree)
        from ..analysis.trace_lint import check_unsaved_compressor_state

        extra.extend(check_unsaved_compressor_state(
            self._compression, self.sync,
            where="TrainStep(compression=..., sync='allreduce')"))
        finish_lint(closed_jaxpr, mode=self.lint, effects=effect_diags,
                    donated_leaves=donated, extra=extra,
                    suppress=self.lint_suppress,
                    what="fused train step", stacklevel=5)

    # ------------------------------------------------------------------
    # graftcost (analysis/cost_model.py, docs/ANALYSIS.md GL2xx)
    def _cost_shard_factors(self, example_args):
        """Per-flat-invar shard divisors congruent with the step's
        argument pytree — the resident-bytes model's view of the
        in_shardings (a ``P('dp')`` ZeRO state leaf on dp=8 costs 1/8
        per device)."""
        if self.mesh is None or self._shardings is None:
            return None

        from ..analysis.cost_model import shard_factor

        p_sh, aux_sh, state_sh, batch_sh, repl = self._shardings
        sh_args = (list(p_sh), list(aux_sh), state_sh, batch_sh, batch_sh,
                   repl, repl, (repl, repl, repl))
        is_sh = lambda s: hasattr(s, "spec") or hasattr(s, "_partitions")  # noqa: E731
        flat_sh = jax.tree_util.tree_leaves(sh_args, is_leaf=is_sh)
        flat_args = jax.tree_util.tree_leaves(tuple(example_args))
        if len(flat_sh) != len(flat_args):
            return None  # structure drifted; fall back to unsharded bytes
        return [shard_factor(s) for s in flat_sh]

    def _cost_analyze(self, closed_jaxpr, example_args, device=None,
                      hbm_budget=None):
        """One CostReport for the traced step program, with this step's
        donation spec, shardings and knob metadata applied."""
        from ..analysis.cost_model import analyze_jaxpr, shard_factor
        from ..analysis.trace_lint import donated_leaf_indices

        device = device or self.cost_device
        if hbm_budget is None:
            hbm_budget = self.hbm_budget
        donated = donated_leaf_indices(tuple(example_args),
                                       self._donate_argnums)
        factors = self._cost_shard_factors(example_args)
        axis_sizes, n_dev = None, 1
        if self.mesh is not None:
            axis_sizes = {k: int(v) for k, v in dict(self.mesh.shape).items()}
            n_dev = int(self.mesh.size)
        # optimizer-state bytes: exact, from the state leaves and their
        # placements (the ZeRO-1 1/N figures test_zero_sharding measures)
        is_sh = lambda s: hasattr(s, "spec") or hasattr(s, "_partitions")  # noqa: E731
        state_leaves = jax.tree_util.tree_leaves(self._opt_state)
        opt_total = float(sum(
            int(np.prod(v.shape)) * np.dtype(v.dtype).itemsize
            for v in state_leaves))
        if self.mesh is not None and self._shardings is not None:
            sh_leaves = jax.tree_util.tree_leaves(self._shardings[2],
                                                  is_leaf=is_sh)
            opt_dev = float(sum(
                int(np.prod(v.shape)) * np.dtype(v.dtype).itemsize
                / shard_factor(s)
                for v, s in zip(state_leaves, sh_leaves))) \
                if len(sh_leaves) == len(state_leaves) else opt_total
        else:
            opt_dev = opt_total
        p_bytes = float(sum(
            int(np.prod(p._data._data.shape))
            * np.dtype(p._data._data.dtype).itemsize
            for p in (self._gp or []) + (self._aux or [])))
        report = analyze_jaxpr(
            closed_jaxpr, axis_sizes=axis_sizes, donated_leaves=donated,
            invar_shard_factors=factors, device=device, n_devices=n_dev,
            hbm_budget=hbm_budget,
            meta={"zero": self.zero,
                  "pipeline_stages": self.pipeline_stages,
                  "num_micro": self.num_micro,
                  "pipeline_remat": bool(self.pipeline_remat),
                  "donate": bool(self._donate),
                  "optimizer": self.opt.name,
                  "multi_precision": bool(self.opt.multi_precision),
                  "batch_axis": self.batch_axis})
        report.opt_state_bytes = opt_total
        report.opt_state_bytes_per_device = opt_dev
        report.param_bytes = p_bytes
        if self.sync != "allreduce" or self._compression is not None:
            # trace-time push-volume pricing for the async rung: what
            # one compressed push costs vs its dense f32 wire, priced
            # from shapes alone — zero compiles spent
            from ..analysis.cost_model import push_volume_report

            entries = [(p.name, tuple(p._data._data.shape),
                        str(p._data._data.dtype)) for p in (self._gp or [])]
            report.meta["push_volume"] = push_volume_report(
                entries, self._compression)
        report.diagnostics.extend(self._cost_config_diags(report))
        return report

    def _cost_config_diags(self, report):
        """GL204: knob settings that pay memory or recompute for
        nothing — donation off (peak raised by a full param/state copy,
        zero traffic saved), or pipeline_remat recompute while peak sits
        far under the budget."""
        from ..analysis import Diagnostic, Severity as Sev

        diags = []
        if not self._donate:
            diags.append(Diagnostic(
                "GL204", Sev.WARNING,
                "donate=False: peak memory carries a second full copy of "
                "params and optimizer state (%.1f MB) and saves zero HBM "
                "traffic in exchange"
                % ((report.param_bytes + report.opt_state_bytes_per_device)
                   / 1e6),
                where="TrainStep(donate=False)",
                hint="the knob is make_train_step(donate=True) (the "
                     "default) — leave donation on unless you must "
                     "re-read the old params after the step"))
        if self.pipeline_remat:
            cap = report.hbm_budget or report.spec().hbm_bytes
            if report.peak_bytes < 0.5 * cap:
                diags.append(Diagnostic(
                    "GL204", Sev.WARNING,
                    "pipeline_remat=True pays recompute HBM traffic while "
                    "predicted peak memory (%.1f MB) sits under half the "
                    "budget (%.1f MB) — the stash it avoids would have fit"
                    % (report.peak_bytes / 1e6, cap / 1e6),
                    where="TrainStep(pipeline_remat=True)",
                    hint="the knob is make_train_step(pipeline_remat="
                         "False); drop it (or lower hbm_budget if the "
                         "headroom is intentional) — tools/autotune.py "
                         "searches it as part of the train space"))
        return diags

    def _finish_cost(self, closed_jaxpr, example_args):
        """The in-step cost pass: store the report; ``cost=\"check\"``
        raises :class:`~..analysis.LintError` on error-severity GL2xx
        findings (GL201 over-budget) BEFORE lower/compile, and warns the
        advisory ones.  ``cost=\"report\"`` is silent — read
        ``step.cost_report``."""
        from ..analysis import LintReport, Severity

        report = self._cost_analyze(closed_jaxpr, example_args)
        rep = LintReport(suppress=self.lint_suppress)
        rep.extend(report.diagnostics)
        report.diagnostics = list(rep.diagnostics)
        self.cost_report = report
        if self.cost == "check":
            rep.raise_if_errors()
            if rep.warnings:
                import warnings as _warnings

                _warnings.warn("graftcost: fused train step has findings\n"
                               + rep.format(Severity.WARNING),
                               stacklevel=4)

    def _analysis_args(self, x, y):
        """The step's abstract 8-tuple argument signature for the given
        batch — the zero-compile analysis entrances (`analyze_cost`,
        `analyze_schedule`) share it."""
        self._ensure_built()

        def aval(a):
            if isinstance(a, jax.ShapeDtypeStruct):
                return a
            if isinstance(a, NDArray):
                a = a._data
            return jax.ShapeDtypeStruct(a.shape, a.dtype)

        pv = [aval(p._data._data) for p in self._gp]
        av = [aval(p._data._data) for p in self._aux]
        sv = jax.tree_util.tree_map(aval, self._opt_state)
        return (pv, av, sv, aval(x), aval(y), aval(self._key_dev),
                aval(self._step_dev),
                tuple(aval(v) for v in self._scaler_dev))

    def analyze_schedule(self, x, y):
        """Run the configured pass pipeline over the traced step in
        report-everything mode and return the
        :class:`~..analysis.passes.PipelineResult` — per-site receipt
        rows included — WITHOUT installing anything, compiling
        anything, or raising on refusals.  ONE abstract trace; the
        autotuner's site table (``autotune.schedule_site_table``) is
        built from exactly this."""
        from ..analysis.passes import PassManager

        args = self._analysis_args(x, y)
        traced, ctx, n_dev, _multihost = self._pass_pipeline_inputs(
            args, probe=False)
        mgr = PassManager(self._passes, schedule=self._schedule,
                          device=self.cost_device, n_devices=n_dev,
                          raise_on_error=False)
        return mgr.run(traced.jaxpr, ctx)

    def analyze_cost(self, x, y, device=None, hbm_budget=None):
        """Cost the step for the given batch WITHOUT compiling or
        running it: traces abstractly (``jit.trace`` on avals — the
        trace the first real call would reuse) and returns the
        :class:`~..analysis.cost_model.CostReport`.  ``x``/``y`` may be
        arrays, NDArrays or ``jax.ShapeDtypeStruct``s."""
        args = self._analysis_args(x, y)
        # with a pass pipeline configured the costed program is the
        # REWRITTEN one — what would actually compile (post-pass cost,
        # the autotuner's ranking signal for `--passes` candidates).
        # probe=False: ranking a candidate must never pay two eager
        # step executions — the probe runs when a run path installs
        # the program for real (nothing unprobed ever compiles)
        self._maybe_apply_passes(args, probe=False)
        traced = self._jit.trace(*args)
        return self._cost_analyze(traced.jaxpr, args, device=device,
                                  hbm_budget=hbm_budget)

    # ------------------------------------------------------------------
    # graftrange (analysis/value_range.py, docs/ANALYSIS.md GL4xx)
    def _numerics_seeds(self, example_args):
        """``(input_ranges, invar_labels)`` for the step program's flat
        invars: declared batch annotations (``input_range=``),
        optimizer-state invariants (variance accumulators are
        non-negative), the loss-scale config's bounds and the 1-based
        step counter.  Params/aux default to unknown-finite — training
        moves them, so an observed init range would be a lie."""
        (p_vals, aux_vals, opt_state, _x, _y, _key, _step,
         _scaler) = example_args
        seeds: Dict[int, Any] = {}
        labels: Dict[int, str] = {}
        idx = 0
        for p in self._gp:
            labels[idx] = "param:%s" % p.name
            idx += 1
        for p in self._aux:
            labels[idx] = "aux:%s" % p.name
            idx += 1
        state_leaves = len(jax.tree_util.tree_leaves(opt_state))
        hints = self.opt.state_range_hints()
        if hints and self._gp and \
                state_leaves == len(self._gp) * len(hints):
            for i, p in enumerate(self._gp):
                for j, h in enumerate(hints):
                    labels[idx] = "opt:%s[%d]" % (p.name, j)
                    if h is not None:
                        seeds[idx] = h
                    idx += 1
        else:
            idx += state_leaves
        ir = self.input_range
        x_r = y_r = None
        if isinstance(ir, dict):
            x_r, y_r = ir.get("x"), ir.get("y")
        elif ir is not None:
            x_r = tuple(ir)
        labels[idx] = "x"
        if x_r is not None:
            seeds[idx] = tuple(x_r)
        idx += 1
        labels[idx] = "y"
        if y_r is not None:
            seeds[idx] = tuple(y_r)
        idx += 1
        labels[idx] = "rng_key"
        idx += 1
        labels[idx] = "step"
        # the carried counter is incremented BEFORE the update applies,
        # so adam's 1-beta**t bias correction sees t >= 1 (never /0)
        seeds[idx] = (0.0, float(2**31 - 1))
        idx += 1
        if self._dynamic_scale:
            cfg = self._scale_cfg
            scale_seed = (cfg.min_loss_scale, cfg.max_loss_scale, True)
        elif self._scale_cfg is not None:
            s = float(self._scale_cfg)
            scale_seed = (s, s, True)
        else:
            scale_seed = (1.0, 1.0, True)
        for name, seed in (("loss_scale", scale_seed),
                           ("ls_unskipped", (0.0, float(2**31 - 1))),
                           ("ls_skipped", (0.0, float(2**31 - 1)))):
            labels[idx] = name
            seeds[idx] = seed
            idx += 1
        return seeds, labels

    def _numerics_analyze(self, closed_jaxpr, example_args):
        """One RangeReport for the traced step program: the GL401/402/
        403/404 value-range walk seeded with this step's annotations,
        plus the GL405 loss-scale advisory from the step config."""
        from ..analysis.value_range import analyze_ranges, loss_scale_diags

        seeds, labels = self._numerics_seeds(example_args)
        axis_sizes = None
        if self.mesh is not None:
            axis_sizes = {k: int(v)
                          for k, v in dict(self.mesh.shape).items()}
        report = analyze_ranges(
            closed_jaxpr, input_ranges=seeds, invar_labels=labels,
            axis_sizes=axis_sizes,
            meta={"what": "fused train step",
                  "compute_dtype": str(self.compute_dtype),
                  "loss_scale": repr(self._scale_cfg),
                  "input_range": repr(self.input_range)})
        report.diagnostics.extend(loss_scale_diags(
            self.compute_dtype,
            self._scale_cfg if isinstance(self._scale_cfg, float)
            else None,
            self._dynamic_scale,
            where="TrainStep(loss_scale=%r, compute_dtype=%r)"
                  % (self._scale_cfg, self.compute_dtype)))
        # pass-emitted numerics advisories (amp_bf16's GL403 per-op
        # exclusions) belong in the step's numerics report too
        for r in (self.pass_receipts or ()):
            report.diagnostics.extend(
                d for d in r.diagnostics if d.code.startswith("GL4"))
        return report

    def _finish_numerics(self, closed_jaxpr, example_args):
        """The in-step numerics pass: store ``step.range_report``;
        ``numerics="error"`` raises :class:`~..analysis.LintError` on
        error-severity GL4xx findings BEFORE lower/compile (the GL201
        discipline), ``"warn"`` warns them."""
        from ..analysis import LintReport, Severity

        report = self._numerics_analyze(closed_jaxpr, example_args)
        rep = LintReport(suppress=self.lint_suppress)
        rep.extend(report.diagnostics)
        report.diagnostics = list(rep.diagnostics)
        self.range_report = report
        if self.numerics == "error":
            rep.raise_if_errors()
        if rep.diagnostics:
            import warnings as _warnings

            _warnings.warn("graftrange: fused train step has findings\n"
                           + rep.format(Severity.WARNING), stacklevel=5)

    def analyze_numerics(self, x, y, input_range=None):
        """Range-analyze the step for the given batch WITHOUT compiling
        or running it (abstract ``jit.trace`` — the trace the first
        real call reuses; with a pass pipeline configured the analyzed
        program is the REWRITTEN one, so an amp_bf16 demotion shows its
        bf16 edges).  Returns the
        :class:`~..analysis.value_range.RangeReport`; mode policy is
        NOT applied — the caller (the autotuner's GL403/GL405 pruning)
        reads ``report.errors`` itself.  ``input_range`` overrides the
        step's annotation for this analysis."""
        self._ensure_built()
        if input_range is not None:
            prev, self.input_range = self.input_range, input_range
        else:
            prev = self.input_range

        def aval(a):
            if isinstance(a, jax.ShapeDtypeStruct):
                return a
            if isinstance(a, NDArray):
                a = a._data
            return jax.ShapeDtypeStruct(a.shape, a.dtype)

        try:
            pv = [aval(p._data._data) for p in self._gp]
            av = [aval(p._data._data) for p in self._aux]
            sv = jax.tree_util.tree_map(aval, self._opt_state)
            args = (pv, av, sv, aval(x), aval(y), aval(self._key_dev),
                    aval(self._step_dev),
                    tuple(aval(v) for v in self._scaler_dev))
            self._maybe_apply_passes(args, probe=False)
            traced = self._jit.trace(*args)
            return self._numerics_analyze(traced.jaxpr, args)
        finally:
            self.input_range = prev

    # ------------------------------------------------------------------
    def _ensure_built(self):
        if self._gp is None:
            self._collect()
            if any(p._data is None for p in self._gp + self._aux):
                raise RuntimeError("initialize() the net before make_train_step")
        if self._opt_state is None:
            pv = [p._data._data for p in self._gp]
            if self.zero:
                # state is born PADDED (leading dim a multiple of the dp
                # axis) so device_put onto the P(dp) shardings slices it
                # evenly; master weights inherit the zero padding
                pv = [self._zero_padded(v, pad)
                      for v, pad in zip(pv, self._zero_pad0)]
            self._opt_state = self.opt.init(pv)
        if self._jit is None:
            self._jit = self._build()
            from .mesh import spans_processes

            self._multihost = self.mesh is not None \
                and spans_processes(self.mesh)
        if self._key_dev is None or self._key_epoch != rng.epoch():
            # (re)draw the carried key — also when the user reseeded after
            # steps already ran (mx.random.seed / rng.set_state must keep
            # affecting the training stream)
            self._key_epoch = rng.epoch()
            self._key_dev = rng.next_key()
            if self._placed:
                if self._multihost:
                    from jax.experimental import multihost_utils as mhu

                    self._key_dev = mhu.host_local_array_to_global_array(
                        self._key_dev, self.mesh, self._shardings[4].spec)
                else:
                    self._key_dev = jax.device_put(self._key_dev,
                                                   self._shardings[4])
        if self._step_dev is None:
            self._step_dev = jnp.int32(self._step_count)
        if self._scaler_dev is None:
            init_scale = self._scale_cfg.init_scale if self._dynamic_scale \
                else float(self._scale_cfg or 1.0)
            self._scaler_dev = (jnp.float32(init_scale), jnp.int32(0),
                                jnp.int32(0))
        # an async-capable step materializes its service client EAGERLY
        # so the checkpoint treedef is identical before and after a
        # policy-ladder degrade (a pre-degrade save must restore into a
        # post-degrade step and vice versa)
        if self.sync != "allreduce" and self._svc_client is None \
                and not self._svc_attaching:
            self._svc_attaching = True
            try:
                self.attach_param_service()
            finally:
                self._svc_attaching = False

    def _place_state(self, p_vals, aux_vals):
        """One-time placement of params/opt-state on their target shardings
        (donation then updates the buffers in place every step).  Multihost:
        host-local replicas (identical after seeded init / broadcast) become
        global arrays — dist_sync_device ≡ one GSPMD program over every
        process's devices (SURVEY §5.8)."""
        p_sh, aux_sh, state_sh, _, repl = self._shardings
        if self._multihost:
            # every host holds the FULL state value (identical after
            # seeded init / broadcast); each device fetches its slice of
            # it through the callback.  NOT host_local_array_to_global:
            # that treats the local value as this host's SHARD, which
            # would stack N full copies of a dp-sharded ZeRO-1 state
            # leaf into an N×-too-tall global array.
            def _globalize(v, s):
                host = np.asarray(v)
                return jax.make_array_from_callback(
                    host.shape, s, lambda idx: host[idx])

            p_vals = [_globalize(v, s) for v, s in zip(p_vals, p_sh)]
            aux_vals = [_globalize(v, s) for v, s in zip(aux_vals, aux_sh)]
            self._opt_state = jax.tree.map(_globalize, self._opt_state,
                                           state_sh)
            # carried key/step/scaler must be identical across hosts
            # (same seed); promote the host-local replicas too
            self._key_dev = _globalize(self._key_dev, repl)
            self._step_dev = _globalize(self._step_dev, repl)
            self._scaler_dev = tuple(_globalize(v, repl)
                                     for v in self._scaler_dev)
        else:
            p_vals = [jax.device_put(v, s) for v, s in zip(p_vals, p_sh)]
            aux_vals = [jax.device_put(v, s)
                        for v, s in zip(aux_vals, aux_sh)]
            self._opt_state = jax.tree.map(
                jax.device_put, self._opt_state, state_sh)
            self._key_dev = jax.device_put(self._key_dev, repl)
            self._step_dev = jax.device_put(self._step_dev, repl)
            self._scaler_dev = tuple(jax.device_put(v, repl)
                                     for v in self._scaler_dev)
        self._placed = True
        return p_vals, aux_vals

    def _place_batch(self, xv, yv):
        """Shard the batch over the mesh's batch axis; multihost treats the
        process-local batch as this host's shard of the global batch."""
        batch_sh = self._shardings[3]
        if self._multihost:
            from jax.experimental import multihost_utils as mhu

            return (mhu.host_local_array_to_global_array(
                        xv, self.mesh, batch_sh.spec),
                    mhu.host_local_array_to_global_array(
                        yv, self.mesh, batch_sh.spec))
        return jax.device_put(xv, batch_sh), jax.device_put(yv, batch_sh)

    @property
    def schedule_hash(self):
        """Canonical hash of the active pass schedule (graftsched,
        analysis/passes.py::PassSchedule) — the legacy whole-pass list
        hashes as its all-sites schedule, so the same decisions always
        key the same; None with no passes configured."""
        from ..analysis.passes import PassSchedule

        if self._schedule is not None:
            return self._schedule.hash()
        if not self._passes:
            return None
        return PassSchedule.from_passes(self._passes).hash()

    def _cache_extra(self):
        """This step's contribution to the compile-cache key (beyond the
        lowered program itself): mesh shape + axis names and the knob
        set, so two configs that somehow lower alike still key apart."""
        mesh = None if self.mesh is None else \
            tuple(sorted((str(a), int(s))
                         for a, s in dict(self.mesh.shape).items()))
        return ("train_step", mesh, self.batch_axis, self.zero,
                self.pipeline_stages, self.num_micro,
                bool(self.pipeline_remat), bool(self._donate),
                self.opt.name, bool(self.opt.multi_precision),
                str(self.compute_dtype), self.nonfinite,
                self._dynamic_scale,
                tuple(p.name for p in self._passes),
                # graftsched: two schedules never share an executable
                ("sched", self.schedule_hash))

    def aot_compile(self, x, y, cache=None):
        """Ahead-of-time trace + lower + compile the fused step for the given
        batch, returning per-phase wall seconds ``{"trace": s, "compile": s}``.

        Splits Python/JAX trace time from XLA compile time (the reference's
        analog is cuDNN autotune + InitCachedOps cost at bind,
        ``src/executor/graph_executor.cc:1220``) so benchmarks can report
        where startup time goes.  The compiled executable is installed as
        this step's callable, so subsequent ``step(x, y)`` calls with the
        same shapes skip compilation.

        ``cache`` is an optional :class:`~.aot.CompileCache` (default:
        the ``MXTPU_COMPILE_CACHE`` env) — on a warm cache the XLA
        compile is skipped entirely (``times["cache"] == "hit"``,
        ``times["compile"] == 0.0``), even in a fresh process.
        """
        import time as _time

        self._ensure_built()
        xv = x._data if isinstance(x, NDArray) else jnp.asarray(x)
        yv = y._data if isinstance(y, NDArray) else jnp.asarray(y)
        p_vals = [p._data._data for p in self._gp]
        aux_vals = [p._data._data for p in self._aux]
        if self.mesh is not None:
            # compile against the PLACED (global, sharded) avals — the same
            # arrays __call__ will pass — or the executable never matches
            if not self._placed:
                p_vals, aux_vals = self._place_state(p_vals, aux_vals)
                for p, v in zip(self._gp, p_vals):
                    p._data._data = v
                for p, v in zip(self._aux, aux_vals):
                    p._data._data = v
            xv, yv = self._place_batch(xv, yv)
        # lint rides THIS trace — no separate lint trace, so the trace/
        # compile split below stays honest (the jaxpr walk is ms-scale)
        from .aot import compile_timed

        t0 = _time.time()
        self._maybe_apply_passes((p_vals, aux_vals, self._opt_state, xv,
                                  yv, self._key_dev, self._step_dev,
                                  self._scaler_dev))
        traced = self._lint_trace(self._jit,
                                  (p_vals, aux_vals, self._opt_state, xv,
                                   yv, self._key_dev, self._step_dev,
                                   self._scaler_dev))
        compiled, times = compile_timed(traced, t_trace=_time.time() - t0,
                                        cache=cache,
                                        cache_extra=self._cache_extra())
        self._compiled = compiled
        self._compiled_key = ((xv.shape, str(xv.dtype)),
                              (yv.shape, str(yv.dtype)))
        return times

    def _build_multi(self):
        """K steps in ONE compiled program: lax.scan over stacked batches.

        Removes per-step dispatch/launch entirely (useful when host
        latency or program-launch overhead matters — e.g. tunneled or
        congested runtimes) and is the natural carrier for gradient-
        accumulation-style loops.  Params/opt-state/key/step thread
        through the scan carry; returns per-step losses.
        """
        step = self._step_fn

        def multi(p_vals, aux_vals, opt_state, xs, ys, key, step_count,
                  scaler):
            def body(carry, xy):
                p, a, st, k, c, sc = carry
                x, y = xy
                loss, p2, a2, s2, k2, c2, sc2, ok = step(p, a, st, x, y,
                                                         k, c, sc)
                return (p2, a2, s2, k2, c2, sc2), (loss, ok)

            carry, (losses, oks) = jax.lax.scan(
                body, (p_vals, aux_vals, opt_state, key, step_count,
                       scaler), (xs, ys))
            p, a, st, k, c, sc = carry
            return losses, p, a, st, k, c, sc, oks

        donate = self._donate_argnums
        if self.mesh is None:
            return jax.jit(multi, donate_argnums=donate)
        p_sh, aux_sh, state_sh, batch_sh, repl = self._shardings
        stack_sh = NamedSharding(self.mesh, P(None, self.batch_axis)) \
            if self.batch_axis in self.mesh.axis_names else repl
        return jax.jit(multi, donate_argnums=donate,
                       in_shardings=(p_sh, aux_sh, state_sh, stack_sh,
                                     stack_sh, repl, repl, repl),
                       out_shardings=(repl, p_sh, aux_sh, state_sh, repl,
                                      repl, repl, repl))

    def run_steps(self, xs, ys):
        """Run ``K = len(xs)`` steps as one program (see _build_multi).
        ``xs``/``ys``: stacked arrays with a leading K axis, or sequences
        of per-step batches.  Returns the K losses as an NDArray."""
        self._ensure_built()
        if isinstance(xs, (list, tuple)):
            xs = jnp.stack([x._data if isinstance(x, NDArray)
                            else jnp.asarray(x) for x in xs])
        else:
            xs = xs._data if isinstance(xs, NDArray) else jnp.asarray(xs)
        if isinstance(ys, (list, tuple)):
            ys = jnp.stack([y._data if isinstance(y, NDArray)
                            else jnp.asarray(y) for y in ys])
        else:
            ys = ys._data if isinstance(ys, NDArray) else jnp.asarray(ys)
        p_vals = [p._data._data for p in self._gp]
        aux_vals = [p._data._data for p in self._aux]
        if self.mesh is not None:
            if not self._placed:
                p_vals, aux_vals = self._place_state(p_vals, aux_vals)
            from jax.sharding import NamedSharding as _NS

            stack_sh = _NS(self.mesh, P(None, self.batch_axis)) \
                if self.batch_axis in self.mesh.axis_names \
                else _NS(self.mesh, P())
            if self._multihost:
                from jax.experimental import multihost_utils as mhu

                xs = mhu.host_local_array_to_global_array(
                    xs, self.mesh, stack_sh.spec)
                ys = mhu.host_local_array_to_global_array(
                    ys, self.mesh, stack_sh.spec)
            else:
                xs = jax.device_put(xs, stack_sh)
                ys = jax.device_put(ys, stack_sh)
        if self._passes:
            # the scan body is the SINGLE-step program: run the pipeline
            # for the per-step signature before the multi program traces
            # — derived from the PLACED (global on multihost) batch, the
            # shapes the scan body will actually carry
            def sd(a):
                return jax.ShapeDtypeStruct(a.shape, a.dtype)

            self._maybe_apply_passes((
                [sd(v) for v in p_vals], [sd(v) for v in aux_vals],
                jax.tree_util.tree_map(sd, self._opt_state),
                jax.ShapeDtypeStruct(xs.shape[1:], xs.dtype),
                jax.ShapeDtypeStruct(ys.shape[1:], ys.dtype),
                sd(self._key_dev), sd(self._step_dev),
                tuple(sd(v) for v in self._scaler_dev)))
        if getattr(self, "_multi_jit", None) is None:
            self._multi_jit = self._build_multi()
        k = xs.shape[0]
        if not self._linted and (self.lint != "off" or self.cost != "off"):
            # lint rides the multi-step program's OWN trace (shared with
            # the compile below via jit's trace cache) — the scan body
            # is the step, so the walker sees the same hazards
            self._lint_trace(self._multi_jit,
                             (p_vals, aux_vals, self._opt_state, xs, ys,
                              self._key_dev, self._step_dev,
                              self._scaler_dev))
        (losses, new_p, new_aux, new_s, self._key_dev, self._step_dev,
         self._scaler_dev, oks) = \
            self._multi_jit(p_vals, aux_vals, self._opt_state, xs, ys,
                            self._key_dev, self._step_dev, self._scaler_dev)
        # host mirror; with nonfinite containment the DEVICE counter is
        # authoritative (skipped steps do not advance it)
        self._step_count += int(k)
        for pp, v in zip(self._gp, new_p):
            pp._data._data = v
        for pp, v in zip(self._aux, new_aux):
            pp._data._data = v
        self._opt_state = new_s
        # boundary checkpoint BEFORE a possible raise: a pending
        # preemption save must not be dropped by an overflowing stack
        self._maybe_checkpoint()
        if self.nonfinite == "raise":
            import numpy as _np

            bad = _np.flatnonzero(~_np.asarray(oks))
            if bad.size:
                raise FloatingPointError(
                    "non-finite gradients in %d of %d scanned steps "
                    "(offsets %s); params/optimizer state were left "
                    "unchanged for those steps"
                    % (bad.size, int(k), bad[:8].tolist()))
        return NDArray(losses)

    # ------------------------------------------------------------------
    # sync→async policy ladder (parallel/param_service.py,
    # docs/RESILIENCE.md §8)
    @property
    def sync_mode(self) -> str:
        """The EFFECTIVE rung right now: ``"allreduce"`` or
        ``"async"`` (``sync="auto"`` moves between them)."""
        return self._applied_sync

    def attach_param_service(self, service=None, rank: int = 0):
        """Bind this step to a :class:`~.param_service.ParamService`
        (created in-process, owned and checkpointed by this step, when
        ``service=None``) and seed it with the current parameters
        (rank-0-wins ``init`` semantics).  Returns the
        :class:`~.param_service.ServiceClient`."""
        from .param_service import (ParamService, ServiceClient,
                                    ServiceUpdater)

        if self.sync == "allreduce":
            raise ValueError(
                "this step was built with sync='allreduce'; rebuild with "
                "make_train_step(sync='async'|'auto') to push/pull "
                "through a parameter service")
        self._ensure_built()
        owns = service is None
        if owns:
            service = ParamService(updater=ServiceUpdater(self.opt),
                                   staleness_bound=self.staleness_bound)
        self._svc_client = ServiceClient(service, rank=int(rank),
                                         compressor=self._compression,
                                         owns_service=owns)
        # positional keys (ps-lite uses int keys too): gluon auto-names
        # drift across rebuilds, positions don't — a resumed process
        # must map its fresh params onto the saved service state
        self._svc_client.init_params(
            {str(i): p._data._data for i, p in enumerate(self._gp)})
        return self._svc_client

    def set_sync_mode(self, mode: str) -> None:
        """Pin the effective rung at a step boundary.  Degrading to
        ``"async"`` starts pushing through the attached service (the
        server holds the authoritative copy from then on); recovering
        to ``"allreduce"`` first adopts the service's parameters so the
        collective rung resumes from the async rung's progress."""
        if mode not in ("allreduce", "async"):
            raise ValueError("sync mode must be 'allreduce' or 'async', "
                             "got %r" % (mode,))
        if self.sync == "allreduce" and mode == "async":
            raise ValueError("step was built with sync='allreduce' — it "
                             "has no async rung")
        if mode == self._applied_sync:
            return
        if mode == "async":
            self._ensure_built()  # attaches the service client
            # the service adopts THIS replica's CURRENT params as the
            # authoritative copy — its seed-time snapshot is stale by
            # however many collective steps ran (and the fused rung
            # donated those seed buffers anyway)
            self._svc_client.sync_params(
                {str(i): p._data._data for i, p in enumerate(self._gp)})
        elif self._svc_client is not None:
            pulled = self._svc_client.pull_params(timeout=self.pull_timeout)
            for i, p in enumerate(self._gp):
                if str(i) in pulled:
                    # copy: the fused rung will DONATE this buffer, and
                    # the service must keep its own copy alive
                    p._data._data = jnp.array(pulled[str(i)])
        self._applied_sync = mode
        self.sync_policy.effective = mode

    def observe_stragglers(self, straggler_ranks) -> str:
        """One straggler-detector frame into the policy ladder
        (``supervisor.straggler_verdicts`` rank list, possibly empty);
        applies any rung switch the policy decides and returns the
        effective mode.  The supervised loop calls this every step
        boundary under ``sync="auto"``."""
        mode = self.sync_policy.observe(straggler_ranks)
        if mode != self._applied_sync:
            self.set_sync_mode(mode)
        return self._applied_sync

    def _async_call(self, x, y):
        """One async step: local fwd+bwd, compressed push, bounded-
        staleness pull, install the pulled params.  Counters advance
        exactly as the fused rung's (the checkpoint boundary hook and
        the supervisor read the same step count either way)."""
        self._ensure_built()
        xv = x._data if isinstance(x, NDArray) else jnp.asarray(x)
        yv = y._data if isinstance(y, NDArray) else jnp.asarray(y)
        p_vals = [p._data._data for p in self._gp]
        aux_vals = [p._data._data for p in self._aux]
        if self._grad_jit is None:
            from ..kvstore.gradient_compression import _donate_ok

            self._grad_jit = jax.jit(
                self._make_grad_step(),
                donate_argnums=(1, 4) if self._donate and _donate_ok()
                else ())
        loss, grads, new_aux, self._key_dev = self._grad_jit(
            p_vals, aux_vals, xv, yv, self._key_dev)
        for p, v in zip(self._aux, new_aux):
            p._data._data = v
        client = self._svc_client
        client.push_step({str(i): g for i, g in enumerate(grads)})
        pulled = client.pull_params(timeout=self.pull_timeout)
        for i, p in enumerate(self._gp):
            p._data._data = jnp.asarray(pulled[str(i)])
        self._step_count += 1
        self._step_dev = self._step_dev + 1
        self._maybe_checkpoint()
        return NDArray(loss)

    def __call__(self, x, y):
        if self._applied_sync == "async":
            return self._async_call(x, y)
        self._ensure_built()

        xv = x._data if isinstance(x, NDArray) else jnp.asarray(x)
        yv = y._data if isinstance(y, NDArray) else jnp.asarray(y)
        p_vals = [p._data._data for p in self._gp]
        aux_vals = [p._data._data for p in self._aux]
        if self.mesh is not None:
            if not self._placed:
                p_vals, aux_vals = self._place_state(p_vals, aux_vals)
            xv, yv = self._place_batch(xv, yv)
        self._maybe_apply_passes((p_vals, aux_vals, self._opt_state, xv,
                                  yv, self._key_dev, self._step_dev,
                                  self._scaler_dev))
        self._maybe_lint((p_vals, aux_vals, self._opt_state, xv, yv,
                          self._key_dev, self._step_dev, self._scaler_dev))
        # the AOT executable is shape-pinned; any other batch shape/dtype
        # falls back to the jit wrapper, which retraces transparently
        fn = self._jit
        if self._compiled is not None and self._compiled_key == (
                (xv.shape, str(xv.dtype)), (yv.shape, str(yv.dtype))):
            fn = self._compiled
        (loss, new_p, new_aux, new_s, self._key_dev, self._step_dev,
         self._scaler_dev, ok) = fn(
            p_vals, aux_vals, self._opt_state, xv, yv, self._key_dev,
            self._step_dev, self._scaler_dev)
        # host mirror of the device counter, advanced only on success so the
        # two can't drift when a step raises (bad shapes, donation errors);
        # with nonfinite containment the DEVICE counter is authoritative
        # (a skipped step does not advance it)
        self._step_count += 1
        for p, v in zip(self._gp, new_p):
            p._data._data = v
        for p, v in zip(self._aux, new_aux):
            p._data._data = v
        self._opt_state = new_s
        # the boundary checkpoint runs BEFORE a possible raise below: a
        # pending preemption save must not be dropped because the final
        # step happened to overflow
        self._maybe_checkpoint()
        if self.nonfinite == "raise" and not bool(ok):
            # state is already installed — and provably unchanged, the
            # guard selected the old buffers — so training CAN continue
            # after catching this
            raise FloatingPointError(
                "non-finite gradients after %d applied updates (call %d "
                "of this step); params/optimizer state were left "
                "unchanged (nonfinite='raise')"
                % (int(self._step_dev), self._step_count))
        return NDArray(loss)

    # ------------------------------------------------------------------
    @property
    def loss_scale(self):
        """The CURRENT loss scale (reads the carried device state)."""
        if self._scaler_dev is None:
            return self._scale_cfg.init_scale if self._dynamic_scale \
                else float(self._scale_cfg or 1.0)
        return float(self._scaler_dev[0])

    @property
    def skipped_steps(self):
        """How many steps the non-finite guard has skipped so far."""
        return 0 if self._scaler_dev is None else int(self._scaler_dev[2])

    @property
    def step_count(self):
        """Applied-update count (device counter: skipped steps excluded)."""
        return self._step_count if self._step_dev is None \
            else int(self._step_dev)

    # ------------------------------------------------------------------
    # durable state (parallel/checkpoint.py)
    def _checkpoint_state(self):
        """The full training state as one pytree: params, aux state,
        optimizer state (dp-sharded leaves stay sharded — the manager
        saves per-rank shards without gathering), PRNG key, device step
        counter and loss-scale state."""
        self._ensure_built()
        state = {"params": [p._data._data for p in self._gp],
                 "aux": [p._data._data for p in self._aux],
                 "opt_state": self._opt_state,
                 "rng_key": self._key_dev,
                 "step": self._step_dev,
                 "loss_scale": self._scaler_dev}
        if self._svc_client is not None:
            # async rung durable state: compressor residuals (+ sparse
            # step counters), the bounded-staleness clock and — when
            # this step owns the service — the authoritative server
            # params/updater state (docs/RESILIENCE.md §8 resume flow)
            state["param_service"] = self._svc_client.state_dict()
        return state

    def _checkpoint_shardings(self):
        """Placement tree congruent with :meth:`_checkpoint_state` —
        what restore uses to put every restored leaf back on its exact
        device layout (None leaves mean default placement)."""
        if self.mesh is None or self._shardings is None:
            return None
        p_sh, aux_sh, state_sh, _, repl = self._shardings
        return {"params": list(p_sh), "aux": list(aux_sh),
                "opt_state": state_sh, "rng_key": repl, "step": repl,
                "loss_scale": (repl, repl, repl)}

    def _as_manager(self, directory_or_manager, keep_last=3):
        from .checkpoint import CheckpointManager

        if isinstance(directory_or_manager, CheckpointManager):
            return directory_or_manager
        return CheckpointManager(directory_or_manager, keep_last=keep_last)

    @staticmethod
    def _host_int(x) -> int:
        """Host value of a replicated device scalar — via the first
        addressable shard, which works for multihost global arrays
        (``device_get`` would demand full addressability)."""
        if hasattr(x, "addressable_data"):
            return int(np.asarray(x.addressable_data(0)))
        return int(jax.device_get(x))

    def _topology(self):
        """JSON description of this step's training topology — stamped
        into every checkpoint's meta so an elastic restore can name
        saved-vs-current in its refusals."""
        mesh = None if self.mesh is None else \
            {a: int(s) for a, s in self.mesh.shape.items()}
        return {"mesh": mesh, "batch_axis": self.batch_axis,
                "zero": self.zero,
                "pipeline_stages": self.pipeline_stages,
                "processes": jax.process_count()}

    def _elastic_policy(self):
        """Pytree congruent with :meth:`_checkpoint_state` marking what
        an elastic (changed-dp-width) restore may re-shape: ``None``
        leaves demand the exact saved shape; an ``int`` is the LOGICAL
        leading dim of a ZeRO-1 optimizer-state leaf whose stored dim
        is padded to a multiple of the dp width — the manager re-slices
        and re-pads those (``CheckpointManager.restore(elastic=)``).
        Everything else — params, aux, RNG key, step counter,
        loss-scale state — is topology-independent by construction.

        The marks are computed for every ZeRO-ELIGIBLE param (≥1-d, not
        tp/ep-sharded) regardless of this step's own ``zero`` mode: a
        ZeRO-mode change is itself elastic (the state re-pads either
        way, ``checkpoint._topology_mismatch``), so a ``zero=0`` run
        must still be able to un-pad a ``zero=1`` checkpoint's
        optimizer state."""
        if self.zero and self._zero_pad0 is not None:
            covered = [pad is not None for pad in self._zero_pad0]
        else:
            covered = []
            for p in self._gp:
                spec = tuple(self.param_shardings.get(p.name, P()))
                sharded = any(e is not None and e != () for e in spec)
                covered.append(not sharded and len(p.shape) >= 1)
        marks = [int(p.shape[0]) if c else None
                 for p, c in zip(self._gp, covered)]
        policy = {"params": [None] * len(self._gp),
                  "aux": [None] * len(self._aux),
                  "opt_state": self.opt.state_shardings(marks),
                  "rng_key": None, "step": None,
                  "loss_scale": (None, None, None)}
        if self._svc_client is not None:
            # exact-shape leaves: residuals/clock/server params never
            # re-pad (the async rung is mesh-free by construction)
            policy["param_service"] = jax.tree_util.tree_map(
                lambda _: None, self._svc_client.state_dict())
        return policy

    def save_checkpoint(self, directory_or_manager, keep_last=3,
                        data_iter=None):
        """Atomically checkpoint the full training state (see
        ``docs/RESILIENCE.md``).  Returns the committed directory.

        ``data_iter`` — an iterator implementing the iterator-state
        protocol (``state_dict()``; ``io/io.py``): its mid-epoch
        position rides the manifest, committed atomically with the
        arrays, so ``restore_checkpoint(..., data_iter=)`` resumes the
        data stream at the exact next batch instead of silently
        replaying the epoch from batch 0.  Defaults to the iterator
        bound by ``attach_checkpoint(data_iter=...)``.

        On a process-spanning (multihost) mesh every process must call
        this cooperatively with the same shared directory: each stages
        only its addressable shards plus a done-marker, and process 0
        verifies all markers before atomically publishing the single
        manifest (``parallel/checkpoint.py``'s commit protocol)."""
        self._ensure_built()
        mgr = self._as_manager(directory_or_manager, keep_last)
        state = self._checkpoint_state()
        if data_iter is None:
            data_iter = self._ckpt_data_iter
        meta = {"topology": self._topology()}
        if data_iter is not None:
            meta["data_iter"] = data_iter.state_dict()
        return mgr.save(self._host_int(self._step_dev), state, meta=meta)

    def restore_checkpoint(self, directory_or_manager, step=None,
                           data_iter=None):
        """Restore params/optimizer state/RNG/step/loss-scale from the
        newest intact checkpoint (or ``step=``), placing every leaf back
        on its training sharding.  Returns the restored step number.
        Training resumes bit-identically to the uninterrupted run.

        ``data_iter`` — restore the data stream too: the iterator is
        ``load_state_dict``-ed to the checkpointed mid-epoch position
        (exact next batch, same shuffle order).  Raises
        :class:`~.checkpoint.CheckpointError` when the checkpoint was
        saved without iterator state — resuming would replay data.
        Defaults to the iterator bound by
        ``attach_checkpoint(data_iter=...)`` (symmetric with
        ``save_checkpoint``); an implicitly-bound iterator facing a
        checkpoint without iterator state warns instead of raising, so
        attaching first and restoring second keeps working against
        pre-protocol checkpoints.  The reverse mismatch — the
        checkpoint carries iterator state but no iterator was passed
        or attached — warns too: the restored run would silently
        replay its epoch from batch 0.

        **Elastic restore**: a checkpoint saved on a different dp width
        (e.g. dp=8 → this step's dp=4) restores bit-exactly — the
        dp-padded ZeRO-1 optimizer-state leaves are re-sliced/re-padded
        to this width, per-process iterator states are re-split across
        the new process count, and everything else (params, RNG key,
        step counter, loss-scale state) is topology-independent.  What
        CANNOT be re-sharded (a pipeline width change, a diverged
        sharded data stream, a different batching) raises
        :class:`~.checkpoint.CheckpointTopologyError` naming the saved
        and current topologies."""
        from .checkpoint import CheckpointTopologyError

        self._ensure_built()
        mgr = self._as_manager(directory_or_manager)
        like = self._checkpoint_state()
        step_no, state, meta = mgr.restore(
            like, step=step, shardings=self._checkpoint_shardings(),
            return_meta=True, elastic=self._elastic_policy(),
            topology=self._topology())
        saved_topo = (meta or {}).get("topology")
        explicit_iter = data_iter is not None
        if data_iter is None:
            data_iter = self._ckpt_data_iter
        if data_iter is not None:
            iter_state = self._resolve_iter_state(meta, saved_topo)
            if iter_state is None:
                msg = ("checkpoint step %d carries no data-iterator state "
                       "(saved without data_iter=) — restoring this "
                       "iterator would silently replay the epoch from "
                       "batch 0; re-save with save_checkpoint(..., "
                       "data_iter=it) or restore without data_iter"
                       % step_no)
                if explicit_iter:
                    from .checkpoint import CheckpointError

                    raise CheckpointError(msg)
                import warnings

                warnings.warn(msg + " (iterator left untouched)")
            else:
                try:
                    data_iter.load_state_dict(iter_state)
                except (ValueError, KeyError) as e:
                    # batching/shuffle/dataset drift: the iterator names
                    # the exact field; wrap it with the topologies so an
                    # elastic restart knows WHICH run disagrees
                    raise CheckpointTopologyError(
                        "checkpoint step %d: the data iterator refused "
                        "the checkpointed stream state: %s (saved "
                        "topology: %s; current topology: %s)"
                        % (step_no, e, saved_topo, self._topology())) \
                        from e
        elif (meta or {}).get("data_iter") is not None:
            import warnings

            warnings.warn(
                "checkpoint step %d carries data-iterator state but no "
                "data_iter was passed or attached — the data stream "
                "will replay its epoch from batch 0; pass "
                "restore_checkpoint(..., data_iter=it) (or "
                "attach_checkpoint(data_iter=it)) to resume mid-epoch"
                % step_no)
        for p, v in zip(self._gp, state["params"]):
            p._data._data = v
        for p, v in zip(self._aux, state["aux"]):
            p._data._data = v
        self._opt_state = state["opt_state"]
        self._key_dev = state["rng_key"]
        self._step_dev = state["step"]
        self._scaler_dev = tuple(state["loss_scale"])
        if self._svc_client is not None and "param_service" in state:
            self._svc_client.load_state_dict(state["param_service"])
        self._step_count = int(step_no)
        # the restored key IS the training stream: suppress the fresh
        # draw _ensure_built would otherwise do on a reseed epoch bump
        self._key_epoch = rng.epoch()
        if self.mesh is not None:
            # every leaf was device_put onto its training sharding by
            # the manager; skip the one-time placement pass
            self._placed = True
        return step_no

    def _resolve_iter_state(self, meta, saved_topo):
        """This process's share of the checkpointed data-stream state.
        A multi-process save carries one state per saved process under
        ``data_iter_parts``; they are re-split across the CURRENT
        process count (``distributed.resplit_iter_state`` — verbatim at
        the same width, re-stamped when every part agrees, refused with
        the topologies named when the shards diverged)."""
        parts = (meta or {}).get("data_iter_parts")
        if not parts:
            return (meta or {}).get("data_iter")
        from . import distributed as _dist
        from .checkpoint import CheckpointTopologyError

        try:
            return _dist.resplit_iter_state(
                parts, jax.process_index(), jax.process_count())
        except ValueError as e:
            raise CheckpointTopologyError(
                "%s (saved topology: %s; current topology: %s)"
                % (e, saved_topo, self._topology())) from e

    def attach_checkpoint(self, directory_or_manager, every=None,
                          keep_last=3, data_iter=None):
        """Bind a checkpoint manager to the step loop: saves at the next
        step boundary whenever a preemption/checkpoint request is
        pending (``checkpoint.install_preemption_hook`` / SIGTERM), and
        every ``every`` applied steps if given.  Returns the manager.

        ``data_iter`` — the training data iterator; every boundary save
        then includes its mid-epoch state (see ``save_checkpoint``), so
        a preemption-triggered checkpoint resumes the data stream at
        the exact next batch.  Without it, a loop that consumes a
        stateful iterator resumes by replaying data (graftlint GL008
        flags that pattern)."""
        from . import checkpoint as _ckpt

        if every is not None and int(every) < 1:
            raise ValueError("every must be >= 1 or None")
        if data_iter is not None:
            # fail NOW, while the mistake is cheap: an iterator without
            # the state protocol would otherwise surface as
            # NotImplementedError from state_dict() at the SIGTERM
            # boundary save — losing the preemption checkpoint entirely
            from ..io.io import DataIter as _DataIter

            sd = getattr(type(data_iter), "state_dict", None)
            if sd is None or sd is _DataIter.state_dict:
                raise ValueError(
                    "data_iter=%r does not implement the iterator-state "
                    "protocol (state_dict/load_state_dict) — wrap it in "
                    "io.ResilientIter or use a protocol-aware iterator "
                    "(NDArrayIter, ImageRecordIter, ...) so boundary "
                    "saves can carry the data position"
                    % type(data_iter).__name__)
        self._ckpt_manager = self._as_manager(directory_or_manager,
                                              keep_last)
        self._ckpt_every = int(every) if every else None
        self._ckpt_data_iter = data_iter
        self._ckpt_prev_count = self._step_count
        # requests predating the attach are not ours to honor
        self._ckpt_seen_request = _ckpt.request_seq()
        return self._ckpt_manager

    def _maybe_checkpoint(self):
        """Step-boundary hook: honor a pending preemption request (and
        the periodic schedule) against the attached manager.  The
        schedule runs off the HOST step mirror — never a per-step
        device sync; the device counter is read only when a save
        actually happens (inside save_checkpoint, which blocks anyway).
        """
        if self._ckpt_manager is None:
            return
        from . import checkpoint as _ckpt

        # per-step request bookkeeping: one request_checkpoint() (the
        # SIGTERM hook) must reach EVERY attached step loop, so each
        # remembers the last sequence IT honored — no global clear
        seq = _ckpt.request_seq()
        requested = seq > self._ckpt_seen_request
        due = requested
        if self._ckpt_every:
            # boundary CROSSING, not exact divisibility: run_steps
            # advances the counter by k per call, so `% every == 0`
            # would miss nearly every boundary for k > 1
            prev, cur = self._ckpt_prev_count, self._step_count
            self._ckpt_prev_count = cur
            due = due or prev // self._ckpt_every != cur // self._ckpt_every
        if due:
            try:
                self.save_checkpoint(self._ckpt_manager)
            except BaseException as e:
                import warnings

                if requested:
                    # a PREEMPTION-requested save failed (disk full,
                    # lost peer): log, restore the pre-hook signal
                    # disposition, and re-raise.  Leaving the hook
                    # installed would swallow every further SIGTERM
                    # into another doomed save request — after this, a
                    # repeated signal terminates the process normally
                    # and the last COMMITTED checkpoint is what resume
                    # sees.  A purely PERIODIC save failing (no signal
                    # involved) keeps the hook: the next boundary may
                    # well succeed, and graceful preemption must not be
                    # silently disabled by one transient blip.
                    warnings.warn(
                        "preemption checkpoint save failed (%s: %s); "
                        "restoring the previous signal disposition so a "
                        "repeated preemption signal terminates instead "
                        "of re-requesting a save that cannot succeed"
                        % (type(e).__name__, e))
                    _ckpt.uninstall_preemption_hook()
                else:
                    warnings.warn(
                        "periodic checkpoint save failed (%s: %s); the "
                        "last committed checkpoint is unchanged and the "
                        "schedule will retry at the next boundary"
                        % (type(e).__name__, e))
                raise
            self._ckpt_seen_request = seq


def make_train_step(net, loss_fn, optimizer="sgd", mesh=None, batch_axis="dp",
                    param_shardings=None, compute_dtype=None, donate=True,
                    pipeline_stages=None, num_micro=1, pipeline_axis="pp",
                    pipeline_remat=False, zero=0, lint=None, lint_suppress=(),
                    nonfinite=None, loss_scale=None, cost=None,
                    hbm_budget=None, cost_device="tpu-v5e", passes=None,
                    numerics=None, input_range=None,
                    skip_streak_budget=None, sync="allreduce",
                    staleness_bound=None, compression=None,
                    **opt_kwargs) -> TrainStep:
    """Build the fused train step (fwd+bwd+optimizer in one XLA program).

    ``pipeline_stages=K`` + ``num_micro=M`` runs the net as a K-stage SPMD
    pipeline over the mesh's ``pipeline_axis``: the (iterable, stacked)
    net's children are split into K congruent stages, the batch into M
    microbatches, and forward/backward run the software-pipelined 1F1B/
    GPipe tick schedule with per-rank microbatch gradient accumulation —
    still one jitted, donated program.  ``pipeline_remat=True`` recomputes
    stage activations in the backward ticks instead of stashing them.
    Composes with dp: a ``{'dp': d, 'pp': K}`` mesh shards microbatches
    over dp while stages flow over pp.

    ``zero=1`` turns on ZeRO-1 weight-update sharding over the mesh's
    ``batch_axis`` (arXiv:2004.13336): each replica consumes only its
    1/N gradient shard (the all-reduce + per-rank-slice pattern XLA's
    reduce-scatter-creation pass — the paper's contribution — compiles
    into a reduce-scatter on TPU), optimizer state lives dp-sharded
    (1/N per device, pad-and-slice for leading dims that don't divide),
    each replica updates only its weight shard, and the updated params
    all-gather back.  Composes with ``pipeline_stages`` on a dp×pp mesh
    (the accumulated microbatch grads reduce once per step).  Pass
    ``multi_precision=True`` (an optimizer kwarg) to keep f32 master
    weights in the — now 1/N-cost — optimizer state for bf16 params,
    and ``rescale_grad=`` to scale gradients as the reference update
    ops do.

    ``lint`` (default: env ``MXTPU_LINT``, else ``"warn"``) runs
    graftlint Level 1 over the traced step before its first compile —
    ``"error"`` raises :class:`~..analysis.LintError` on error-severity
    findings, ``"warn"`` emits a warning, ``"off"`` disables.
    ``lint_suppress`` drops the given ``GLxxx`` codes, or ``GL2*``-style
    prefix globs (docs/ANALYSIS.md).

    ``cost`` (default: env ``MXTPU_COST``, else ``"off"``) runs the
    graftcost trace-time cost model over the same pre-compile trace
    (``analysis/cost_model.py``): predicted FLOPs / fusion-aware HBM
    bytes / peak live-buffer memory / per-axis comm volume, surfaced as
    ``step.cost_report`` (a JSON-serializable
    :class:`~..analysis.cost_model.CostReport`).  ``"check"``
    additionally enforces the GL2xx diagnostics: GL201 — predicted peak
    memory over ``hbm_budget`` (bytes) — raises *at trace time, before
    any compile*; GL202/GL203/GL204 (multi-pass re-reads, comm-dominated
    roofline, remat/donation config without a memory win) warn.
    ``cost_device`` picks the roofline denominators from the device-spec
    registry (``tpu-v5e`` default; ``cpu-proxy`` for relative numbers
    off-chip).

    ``passes`` (default: env ``MXTPU_PASSES``, else none) runs the
    graftpass rewrite pipeline over the traced step before its first
    compile (``analysis/passes.py``, docs/PASSES.md): an ordered list
    of registry names (``"amp_bf16"``, ``"space_to_depth"``,
    ``"cse_dead_aux"``, ...) or :class:`~..analysis.GraftPass`
    instances.  Every pass declares an exactness contract the framework
    verifies by construction — abstract eval, re-lint (a pass may not
    introduce jaxpr-level graftlint findings: GL302), graftcost
    before/after
    receipts (``step.pass_receipts``; a pointless bit-exact rewrite is
    skipped: GL303) and a seeded concrete probe (GL301) — refusing,
    with :class:`~..analysis.LintError` and zero compiles spent, any
    rewrite that breaks its declaration.  Weight-quantizing passes
    no-op on a train step (its params are donated and updated in
    place); they belong on ``ServeEngine(passes=...)``.

    ``numerics`` (default: env ``MXTPU_NUMERICS``, else ``"off"``) runs
    the graftrange value-range & precision abstract interpreter over
    the same pre-compile trace (``analysis/value_range.py``,
    docs/ANALYSIS.md GL4xx): per-variable intervals, NaN-possibility
    and effective precision, checked as GL401 (possible overflow-to-inf
    — exp of unbounded logits without max-subtraction), GL402
    (invalid-domain op — log/rsqrt/div reachable at ≤0, the
    E[x²]−E[x]² cancellation), GL403 (bf16 under/overflow on a demoted
    edge — the per-op ``amp_bf16`` installation gate), GL404 (silent
    f64/weak-type promotion — the hand-fixed adam/attention-scale bug
    class) and GL405 (loss-scale advisory naming the suggested scale).
    ``"error"`` raises :class:`~..analysis.LintError` *before any
    compile*; findings surface as ``step.range_report``
    (:class:`~..analysis.value_range.RangeReport`), and
    ``step.analyze_numerics(x, y)`` runs the walk on demand with zero
    compiles.  ``input_range`` declares the batch's real value range —
    a ``(lo, hi)`` tuple for ``x`` or ``{"x": (lo, hi), "y": ...}`` —
    sharpening the analysis (unannotated floats are assumed
    unknown-but-finite; integer/uint8 inputs seed from their dtype).

    ``nonfinite`` contains bad steps INSIDE the program: ``"skip"``
    leaves params, aux state, optimizer state and the step counter
    bit-identical when any gradient is non-finite (one fused all-finite
    reduction + select guard — no per-param host syncs, donation-safe,
    composes with pipelining and ``zero=1``); ``"raise"`` additionally
    raises :class:`FloatingPointError` on the host (state still
    protected); ``"off"`` (default without a dynamic scaler) keeps the
    unguarded program.  ``loss_scale`` is ``None``, a static positive
    scale, ``"dynamic"``, or a :class:`DynamicLossScale` policy — the
    dynamic scale + counters ride the step's carried device state
    (halve on overflow, double every ``scale_window`` clean steps,
    matching ``contrib/amp/loss_scaler.py``) and are surfaced as
    ``step.loss_scale`` / ``step.skipped_steps``.
    ``sync`` picks the gradient-exchange rung
    (``parallel/param_service.py``, docs/RESILIENCE.md §8):
    ``"allreduce"`` (default) is the fused collective step;
    ``"async"`` runs bounded-staleness asynchronous push/pull against
    a parameter service — the optimizer moves server-side, each rank
    pushes (optionally compressed) gradients and pulls fresh params,
    and a rank may run at most ``staleness_bound`` steps (default 4)
    ahead of the slowest live peer before its pull blocks;
    ``"auto"`` starts on the collective rung and lets the supervisor's
    straggler detector degrade to async and recover back
    (``step.observe_stragglers`` / :class:`~.param_service.SyncPolicy`
    hysteresis).  Async requires ``mesh=None`` (one replica per rank
    process) and composes with ``compression`` — ``"topk"``,
    ``"randomk"``, ``"int8"``, ``"2bit"`` or a compressor instance
    (``kvstore/gradient_compression.py``): pushes shrink on the wire
    while error-feedback residuals keep convergence, ride the step's
    checkpoint (``param_service`` subtree) and are priced at trace
    time by graftcost (``report.meta["push_volume"]``, zero compiles).
    ``skip_streak_budget`` DECLARES a bound on consecutive skipped
    steps: the supervised loop (``parallel/supervisor.py``) enforces it
    as a divergence verdict, and declaring it (or a dynamic scale)
    silences graftlint GL012 — ``nonfinite="skip"`` under a static
    scale with no streak bound is a run that can stall forever while
    looking alive.  See ``docs/RESILIENCE.md`` for the policy matrix,
    and ``step.save_checkpoint`` / ``step.restore_checkpoint`` /
    ``step.attach_checkpoint`` for durable, shard-aware
    checkpoint/resume (``parallel/checkpoint.py``).
    """
    opt = FunctionalOptimizer(optimizer, **opt_kwargs)
    return TrainStep(net, loss_fn, opt, compute_dtype=compute_dtype, mesh=mesh,
                     batch_axis=batch_axis, param_shardings=param_shardings,
                     donate=donate, pipeline_stages=pipeline_stages,
                     num_micro=num_micro, pipeline_axis=pipeline_axis,
                     pipeline_remat=pipeline_remat, zero=zero, lint=lint,
                     lint_suppress=lint_suppress, nonfinite=nonfinite,
                     loss_scale=loss_scale, cost=cost, hbm_budget=hbm_budget,
                     cost_device=cost_device, passes=passes,
                     numerics=numerics, input_range=input_range,
                     skip_streak_budget=skip_streak_budget, sync=sync,
                     staleness_bound=staleness_bound,
                     compression=compression)
