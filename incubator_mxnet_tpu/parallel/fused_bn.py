"""Pallas fused ghost batch norm (+ReLU, +residual-add) for TPU.

The north-star ResNet-50 train step is HBM-bound (docs/PERF.md): XLA runs
BatchNorm as separate full passes over each conv output — a stats
reduction read, a normalize+activation read+write in fwd, and a reduce
pass plus an elementwise pass in bwd (23 ms/step of
`convert_reduce_fusion` at batch 256).  These kernels keep a slab of the
activation resident in VMEM and do

* fwd:  statistics + normalize + (residual add) + ReLU in ONE read of X,
* bwd:  the dgamma/dbeta reductions AND dX (+ residual grad) in one
        read of (dY, X),

cutting ~2 full HBM passes per BatchNorm layer.

The price is *ghost* statistics: mean/var are computed per group of
images (the slab must fit VMEM), not over the whole local batch.  This
matches the per-device semantics of the distributed north-star row
(`dist_sync_device` computes BN stats per worker over batch/N_workers in
the reference — `src/operator/nn/batch_norm.cc` never reduces stats
across devices), and ghost/sub-batch BN is a standard, documented
technique; it is exposed as an explicit opt-in (`ghost_bn` on the model
zoo / `group` here), never a silent default.

Layout (the whole game — a wrong view forces XLA to insert full-tensor
transposes around the custom call):

* C >= 128: X viewed as (L, N, C), L = H*W.  The conv's TPU layout for
  these tensors is {1,0,3,2} (minor dims C, N) == row-major (H, W, N, C)
  — a bitcast.  Channels ride the 128 lanes; the ghost group is a
  sublane block of N (multiples of 16 for bf16, so windows don't pad).
* C < 128: X viewed as (L, C, N).  XLA lays small-C tensors out as
  {0,1,3,2} (minor dims N, C) == row-major (H, W, C, N) — also a
  bitcast.  Channels ride sublanes; the ghost group is the lane block
  of N (=128): an even larger statistics group.

Layers whose whole-L windows can't fit VMEM no longer all fall back to
jnp (round 20, docs/PERF.md):

* **lane-fold** (C < 128): the C lanes pad to 128 anyway, so k = 128/C
  rows of L are packed into the padded lane dimension — the view is
  (L/k, N, k*C) and the per-window footprint shrinks by k.  Stats
  fold-reduce the k lane copies in-kernel; the ghost group stays the
  sublane image block, so ``bn_group`` semantics are unchanged.  This
  reclaims the 112x112x64 stem at bf16 (51.4 -> 25.7 MB windows).
* **spatial-tiled** (cross-tile stat accumulation): a two-phase kernel
  pair — phase 1 accumulates per-tile partial sums over a sequential
  tile grid dimension into revisited (G, 1, C) blocks, the moments
  finalize on the tiny partials in jnp, and a parallel phase-2 kernel
  re-reads X to normalize (fwd) / write dX (bwd).  The window covers an
  L-tile instead of whole L, at the honest price of ONE extra read of
  the operands (its own pallas_call, so graftcost charges it).  This
  reclaims the 56x56x256 identity exits (3 windows x 12.8 MB).

Only layers that fit none of the forms fall back to the equivalent jnp
formulation with the same ghost statistics.

Interpret mode runs the same kernels on CPU for tests, like
parallel/flash_attention.py.
"""
from __future__ import annotations

import functools
import os
import sys
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_I0 = np.int32(0)  # index-map literal pinned to i32 (package enables x64)

#: jax 0.4.x ships the TPU params type as ``TPUCompilerParams``; newer
#: releases renamed it ``CompilerParams``.  Resolve whichever exists —
#: interpret mode accepts either, so the CPU parity tests run the same
#: call path as the chip.
_CompilerParams = getattr(pltpu, "CompilerParams", None) \
    or getattr(pltpu, "TPUCompilerParams")

__all__ = ["ghost_bn_act", "ghost_bn_stats_merge", "plan_describe", "Plan"]

_VMEM_KERNEL_LIMIT = 120 * 1024 * 1024
_WINDOW_BUDGET = 104 * 1024 * 1024

#: spatial-tiling cap: beyond this many tiles the sequential stats grid
#: and the extra finalize pass stop paying for the reclaimed window
_MAX_TILES = 16

#: in-place output aliasing (dX over gY etc. — see _call_bwd).  A
#: debugging escape hatch; the plan's window accounting assumes True.
_IO_ALIASES = True


def _aliases(d):
    return d if _IO_ALIASES else {}


def _use_interpret():
    try:
        return jax.default_backend() != "tpu"
    except Exception:
        return True


def _rup(x, m):
    return -(-x // m) * m


def _sublane(itemsize):
    return 16 if itemsize == 2 else 8


# NB round-5 rewrite: the round-4 kernels split C >= 256 into 128-wide
# lane blocks, which turned every window DMA into cb*itemsize-byte
# strided runs (256 B at 512 B stride for the stage-2 exits) — exactly
# the measured ~55 % of the BW roofline.  The channel dim is now NEVER
# split in the LNC view: a (L, ng, C) block reads ng*C*itemsize
# CONTIGUOUS runs (4-16 KB on the ResNet-50 shapes).


# ---------------------------------------------------------------------------
# kernels (parameterized by which block axis carries channels)
# ---------------------------------------------------------------------------
# Block shape is (L, A, B); ch_axis 2 means channels on B (lanes, LNC
# view), ch_axis 1 means channels on A (sublanes, LCN view).  Reductions
# run over the other two axes; scoped-VMEM stack limits (~16 MB) force
# chunked loops over L instead of whole-slab f32 temps.


def _chunk(l, a, b, budget=1536 * 1024):
    """Largest divisor of L within the f32-temp budget; a slightly
    over-budget divisor beats degenerating to many 1-row loop iterations
    (L=49 at the 7x7 stages has divisors {1,7,49} only).  The bwd kernel
    keeps ~3 chunk-sized f32 temps live at once, so the over-budget
    stretch is capped at 2x (3 x 3 MB = 9 MB, under the ~16 MB scoped-
    VMEM stack limit); when even 2x can't reach a divisor (tiny caps
    from very large A*B blocks) the degenerate small chunk stands —
    slow-ish but VMEM-safe."""
    cap = max(1, min(budget // (a * b * 4), l))
    divs = [d for d in range(1, l + 1) if l % d == 0]
    best = max((d for d in divs if d <= cap), default=1)
    if best * 2 <= cap:
        over = [d for d in divs if cap < d <= 2 * cap]
        if over:
            return min(over)
    return best


def _bshape(vec, ch_axis):
    return vec[None, :, None] if ch_axis == 1 else vec[None, None, :]


def _fwd_kernel(x_ref, g_ref, b_ref, y_ref, m_ref, v_ref, *, eps, act, lc,
                ch_axis, r_ref=None, fold=1):
    l, a, b = x_ref.shape
    k = l // lc
    cnt = l * (b if ch_axis == 1 else a) * fold

    # per-chunk reduce only over the major (L) axis into an (A, B) f32
    # accumulator — cross-sublane/lane reduction happens ONCE at the end
    # (per-chunk cross reduces were the VPU bottleneck)
    def red(i, acc):
        s, ss = acc
        xc = x_ref[pl.ds(i * jnp.int32(lc), lc)].astype(jnp.float32)
        return s + jnp.sum(xc, axis=0), ss + jnp.sum(xc * xc, axis=0)
    zero = jnp.zeros((a, b), jnp.float32)
    sm, ssq = jax.lax.fori_loop(jnp.int32(0), jnp.int32(k), red,
                                (zero, zero))
    cross = 1 if ch_axis == 1 else 0
    sm = jnp.sum(sm, axis=cross)
    ssq = jnp.sum(ssq, axis=cross)
    if fold > 1:
        # lane-fold: the lane dim carries (fold, C) — fold-reduce to the
        # true channel axis before the moments
        sm = jnp.sum(sm.reshape(fold, -1), axis=0)
        ssq = jnp.sum(ssq.reshape(fold, -1), axis=0)
    m = sm / cnt
    v = jnp.maximum(ssq / cnt - m * m, 0.0)
    rstd = jax.lax.rsqrt(v + eps)
    g = g_ref[...].reshape(-1).astype(jnp.float32)
    bb = b_ref[...].reshape(-1).astype(jnp.float32)
    scale_c = g * rstd
    shift_c = bb - m * g * rstd
    if fold > 1:
        # tile the per-channel affine back across the fold copies so it
        # broadcasts against the (lc, A, fold*C) chunks
        scale_c = jnp.tile(scale_c, fold)
        shift_c = jnp.tile(shift_c, fold)
    scale = _bshape(scale_c, ch_axis)
    shift = _bshape(shift_c, ch_axis)

    def norm(i, _):
        sl = pl.ds(i * jnp.int32(lc), lc)
        y = x_ref[sl].astype(jnp.float32) * scale + shift
        if r_ref is not None:
            y = y + r_ref[sl].astype(jnp.float32)
        if act == "relu":
            y = jnp.maximum(y, 0.0)
        y_ref[sl] = y.astype(y_ref.dtype)
        return jnp.int32(0)
    jax.lax.fori_loop(jnp.int32(0), jnp.int32(k), norm, jnp.int32(0))
    m_ref[...] = m.reshape(m_ref.shape)
    v_ref[...] = v.reshape(v_ref.shape)


def _fwd_kernel_res(x_ref, r_ref, g_ref, b_ref, y_ref, m_ref, v_ref, *,
                    eps, act, lc, ch_axis, fold=1):
    _fwd_kernel(x_ref, g_ref, b_ref, y_ref, m_ref, v_ref, eps=eps, act=act,
                lc=lc, ch_axis=ch_axis, r_ref=r_ref, fold=fold)


def _bwd_kernel(gy_ref, x_ref, g_ref, b_ref, m_ref, v_ref, dx_ref, dg_ref,
                db_ref, *, eps, act, lc, ch_axis, y_ref=None, dr_ref=None,
                fold=1, gy2_ref=None):
    l, a, b = x_ref.shape
    k = l // lc
    cnt = l * (b if ch_axis == 1 else a) * fold
    m = m_ref[...].reshape(-1)
    v = v_ref[...].reshape(-1)
    rstd = jax.lax.rsqrt(v + eps)
    g = g_ref[...].reshape(-1).astype(jnp.float32)
    bb = b_ref[...].reshape(-1).astype(jnp.float32) if b_ref is not None \
        else None
    if fold > 1:
        m = jnp.tile(m, fold)
        rstd = jnp.tile(rstd, fold)
        g = jnp.tile(g, fold)
        if bb is not None:
            bb = jnp.tile(bb, fold)
    mb = _bshape(m, ch_axis)
    rb = _bshape(rstd, ch_axis)
    gb = _bshape(g, ch_axis)

    def gyld(sl):
        # dual-output join absorption: the block exit's two cotangents
        # (conv path + shortcut) sum on the VMEM window load, so the
        # surrounding program never materializes an add_any join
        gyc = gy_ref[sl].astype(jnp.float32)
        if gy2_ref is not None:
            gyc = gyc + gy2_ref[sl].astype(jnp.float32)
        return gyc

    def masked(sl, gyc, xhat):
        if act != "relu":
            return gyc
        if y_ref is not None:
            return jnp.where(y_ref[sl].astype(jnp.float32) > 0, gyc, 0.0)
        pre = xhat * gb + _bshape(bb, ch_axis)
        return jnp.where(pre > 0, gyc, 0.0)

    def red(i, acc):
        sdb, sdg = acc
        sl = pl.ds(i * jnp.int32(lc), lc)
        xhat = (x_ref[sl].astype(jnp.float32) - mb) * rb
        gp = masked(sl, gyld(sl), xhat)
        return sdb + jnp.sum(gp, axis=0), sdg + jnp.sum(gp * xhat, axis=0)
    zero = jnp.zeros((a, b), jnp.float32)
    db, dg = jax.lax.fori_loop(jnp.int32(0), jnp.int32(k), red, (zero, zero))
    cross = 1 if ch_axis == 1 else 0
    db = jnp.sum(db, axis=cross)
    dg = jnp.sum(dg, axis=cross)
    if fold > 1:
        # fold-reduce the lane copies FIRST (dX needs the per-channel
        # totals), then tile back for the write loop's broadcasts
        db = jnp.sum(db.reshape(fold, -1), axis=0)
        dg = jnp.sum(dg.reshape(fold, -1), axis=0)
    dbb = _bshape(jnp.tile(db, fold) if fold > 1 else db, ch_axis)
    dgb = _bshape(jnp.tile(dg, fold) if fold > 1 else dg, ch_axis)

    def wr(i, _):
        sl = pl.ds(i * jnp.int32(lc), lc)
        xhat = (x_ref[sl].astype(jnp.float32) - mb) * rb
        gp = masked(sl, gyld(sl), xhat)
        dx = gb * rb * (gp - (dbb + xhat * dgb) / cnt)
        dx_ref[sl] = dx.astype(dx_ref.dtype)
        if dr_ref is not None:
            dr_ref[sl] = gp.astype(dr_ref.dtype)
        return jnp.int32(0)
    jax.lax.fori_loop(jnp.int32(0), jnp.int32(k), wr, jnp.int32(0))
    dg_ref[...] = dg.reshape(dg_ref.shape)
    db_ref[...] = db.reshape(db_ref.shape)


def _bwd_kernel_res(gy_ref, x_ref, y_ref, g_ref, m_ref, v_ref, dx_ref,
                    dg_ref, db_ref, dr_ref, *, eps, act, lc, ch_axis,
                    fold=1):
    # residual variant: the post-add ReLU mask comes from the saved OUTPUT
    # (y > 0 iff pre+res > 0), so the residual tensor itself is not re-read
    _bwd_kernel(gy_ref, x_ref, g_ref, None, m_ref, v_ref, dx_ref, dg_ref,
                db_ref, eps=eps, act=act, lc=lc, ch_axis=ch_axis,
                y_ref=y_ref, dr_ref=dr_ref, fold=fold)


def _bwd_kernel_res_dual(gy_ref, gy2_ref, x_ref, y_ref, g_ref, m_ref, v_ref,
                         dx_ref, dg_ref, db_ref, dr_ref, *, eps, act, lc,
                         ch_axis, fold=1):
    # dual-cotangent residual variant (the block-exit join absorption):
    # gy1 (conv path) + gy2 (shortcut) sum on the window load
    _bwd_kernel(gy_ref, x_ref, g_ref, None, m_ref, v_ref, dx_ref, dg_ref,
                db_ref, eps=eps, act=act, lc=lc, ch_axis=ch_axis,
                y_ref=y_ref, dr_ref=dr_ref, fold=fold, gy2_ref=gy2_ref)


# ---------------------------------------------------------------------------
# spatial-tiled kernels (LNC only; cross-tile stat accumulation)
# ---------------------------------------------------------------------------
# The tile grid dim is SEQUENTIAL ("arbitrary" semantics, innermost), and
# the per-(group, channel) partial-sum blocks are revisited across it —
# the flash_attention.py accumulation idiom: init at tile 0, add after.


def _tile_acc(ref, val, t):
    @pl.when(t == 0)
    def _init():
        ref[...] = val.reshape(ref.shape)

    @pl.when(t != 0)
    def _add():
        ref[...] = ref[...] + val.reshape(ref.shape)


def _stats_tile_kernel(x_ref, s_ref, ss_ref, *, lc):
    """Phase-1 fwd: per-tile partial sum/sumsq over (L-tile, ng),
    accumulated across the sequential tile dim into (1, 1, C) blocks."""
    t = pl.program_id(1)
    l, a, b = x_ref.shape
    k = l // lc

    def red(i, acc):
        s, ss = acc
        xc = x_ref[pl.ds(i * jnp.int32(lc), lc)].astype(jnp.float32)
        return s + jnp.sum(xc, axis=0), ss + jnp.sum(xc * xc, axis=0)
    zero = jnp.zeros((a, b), jnp.float32)
    sm, ssq = jax.lax.fori_loop(jnp.int32(0), jnp.int32(k), red,
                                (zero, zero))
    _tile_acc(s_ref, jnp.sum(sm, axis=0), t)
    _tile_acc(ss_ref, jnp.sum(ssq, axis=0), t)


def _norm_tile_kernel(x_ref, g_ref, b_ref, m_ref, v_ref, y_ref, *, eps,
                      act, lc, r_ref=None):
    """Phase-2 fwd: normalize one tile with the finalized stats (the
    extra read of X the plan charges for)."""
    l, a, b = x_ref.shape
    k = l // lc
    m = m_ref[...].reshape(-1)
    rstd = jax.lax.rsqrt(v_ref[...].reshape(-1) + eps)
    g = g_ref[...].reshape(-1).astype(jnp.float32)
    bb = b_ref[...].reshape(-1).astype(jnp.float32)
    scale = (g * rstd)[None, None, :]
    shift = (bb - m * g * rstd)[None, None, :]

    def norm(i, _):
        sl = pl.ds(i * jnp.int32(lc), lc)
        y = x_ref[sl].astype(jnp.float32) * scale + shift
        if r_ref is not None:
            y = y + r_ref[sl].astype(jnp.float32)
        if act == "relu":
            y = jnp.maximum(y, 0.0)
        y_ref[sl] = y.astype(y_ref.dtype)
        return jnp.int32(0)
    jax.lax.fori_loop(jnp.int32(0), jnp.int32(k), norm, jnp.int32(0))


def _norm_tile_kernel_res(x_ref, r_ref, g_ref, b_ref, m_ref, v_ref, y_ref,
                          *, eps, act, lc):
    _norm_tile_kernel(x_ref, g_ref, b_ref, m_ref, v_ref, y_ref, eps=eps,
                      act=act, lc=lc, r_ref=r_ref)


def _tile_masked(gy_ref, y_ref, gb, bbv, act):
    """The shared ReLU cotangent mask: from the saved output when a
    residual was added (y > 0 iff pre+res > 0), else from the pre-act."""
    def masked(sl, gyc, xhat):
        if act != "relu":
            return gyc
        if y_ref is not None:
            return jnp.where(y_ref[sl].astype(jnp.float32) > 0, gyc, 0.0)
        pre = xhat * gb + bbv[None, None, :]
        return jnp.where(pre > 0, gyc, 0.0)
    return masked


def _bwd_red_tile_kernel(gy_ref, x_ref, g_ref, b_ref, m_ref, v_ref,
                         db_ref, dg_ref, *, eps, act, lc, y_ref=None):
    """Phase-1 bwd: per-tile partial dbeta/dgamma reductions, accumulated
    across the sequential tile dim."""
    t = pl.program_id(1)
    l, a, b = x_ref.shape
    k = l // lc
    m = m_ref[...].reshape(-1)
    rstd = jax.lax.rsqrt(v_ref[...].reshape(-1) + eps)
    mb, rb = m[None, None, :], rstd[None, None, :]
    gb = g_ref[...].reshape(-1).astype(jnp.float32)[None, None, :] \
        if g_ref is not None else None
    bbv = b_ref[...].reshape(-1).astype(jnp.float32) \
        if b_ref is not None else None
    masked = _tile_masked(gy_ref, y_ref, gb, bbv, act)

    def red(i, acc):
        sdb, sdg = acc
        sl = pl.ds(i * jnp.int32(lc), lc)
        xhat = (x_ref[sl].astype(jnp.float32) - mb) * rb
        gp = masked(sl, gy_ref[sl].astype(jnp.float32), xhat)
        return sdb + jnp.sum(gp, axis=0), sdg + jnp.sum(gp * xhat, axis=0)
    zero = jnp.zeros((a, b), jnp.float32)
    db, dg = jax.lax.fori_loop(jnp.int32(0), jnp.int32(k), red,
                               (zero, zero))
    _tile_acc(db_ref, jnp.sum(db, axis=0), t)
    _tile_acc(dg_ref, jnp.sum(dg, axis=0), t)


def _bwd_red_tile_kernel_res(gy_ref, x_ref, y_ref, m_ref, v_ref, db_ref,
                             dg_ref, dr_ref, *, eps, act, lc, gy2_ref=None):
    """Phase-1 residual bwd: the partial dbeta/dgamma reductions AND the
    masked cotangent dR (= gp) in the same read — gY (and the dual
    shortcut cotangent gy2) is consumed HERE, so phase 2 never re-reads
    it (the gY-read-once protocol; dR aliases gY's dead window)."""
    t = pl.program_id(1)
    l, a, b = x_ref.shape
    k = l // lc
    m = m_ref[...].reshape(-1)
    rstd = jax.lax.rsqrt(v_ref[...].reshape(-1) + eps)
    mb, rb = m[None, None, :], rstd[None, None, :]
    masked = _tile_masked(gy_ref, y_ref, None, None, act)

    def red(i, acc):
        sdb, sdg = acc
        sl = pl.ds(i * jnp.int32(lc), lc)
        xhat = (x_ref[sl].astype(jnp.float32) - mb) * rb
        gyc = gy_ref[sl].astype(jnp.float32)
        if gy2_ref is not None:
            gyc = gyc + gy2_ref[sl].astype(jnp.float32)
        gp = masked(sl, gyc, xhat)
        dr_ref[sl] = gp.astype(dr_ref.dtype)
        return sdb + jnp.sum(gp, axis=0), sdg + jnp.sum(gp * xhat, axis=0)
    zero = jnp.zeros((a, b), jnp.float32)
    db, dg = jax.lax.fori_loop(jnp.int32(0), jnp.int32(k), red,
                               (zero, zero))
    _tile_acc(db_ref, jnp.sum(db, axis=0), t)
    _tile_acc(dg_ref, jnp.sum(dg, axis=0), t)


def _bwd_red_tile_kernel_res_dual(gy_ref, gy2_ref, x_ref, y_ref, m_ref,
                                  v_ref, db_ref, dg_ref, dr_ref, *, eps,
                                  act, lc):
    _bwd_red_tile_kernel_res(gy_ref, x_ref, y_ref, m_ref, v_ref, db_ref,
                             dg_ref, dr_ref, eps=eps, act=act, lc=lc,
                             gy2_ref=gy2_ref)


def _bwd_dx_tile_kernel(gy_ref, x_ref, g_ref, b_ref, m_ref, v_ref, db_ref,
                        dg_ref, dx_ref, *, eps, act, lc, cnt):
    """Phase-2 bwd (no residual): dX for one tile from the cross-tile-
    reduced dbeta/dgamma totals; dX aliases the dead gY window."""
    l, a, b = x_ref.shape
    k = l // lc
    m = m_ref[...].reshape(-1)
    rstd = jax.lax.rsqrt(v_ref[...].reshape(-1) + eps)
    g = g_ref[...].reshape(-1).astype(jnp.float32)
    bbv = b_ref[...].reshape(-1).astype(jnp.float32) \
        if b_ref is not None else None
    mb, rb, gb = m[None, None, :], rstd[None, None, :], g[None, None, :]
    dbb = db_ref[...].reshape(-1)[None, None, :]
    dgb = dg_ref[...].reshape(-1)[None, None, :]
    masked = _tile_masked(gy_ref, None, gb, bbv, act)

    def wr(i, _):
        sl = pl.ds(i * jnp.int32(lc), lc)
        xhat = (x_ref[sl].astype(jnp.float32) - mb) * rb
        gp = masked(sl, gy_ref[sl].astype(jnp.float32), xhat)
        dx = gb * rb * (gp - (dbb + xhat * dgb) / cnt)
        dx_ref[sl] = dx.astype(dx_ref.dtype)
        return jnp.int32(0)
    jax.lax.fori_loop(jnp.int32(0), jnp.int32(k), wr, jnp.int32(0))


def _bwd_dx_from_dr_tile_kernel(dr_ref, x_ref, g_ref, m_ref, v_ref, db_ref,
                                dg_ref, dx_ref, *, eps, lc, cnt):
    """Phase-2 residual bwd: dX for one tile from the phase-1 masked
    cotangent dR and the cross-tile totals — reads (dR, X) only (no gY,
    no Y: the mask is already applied inside dR); dX aliases X's dead
    window."""
    l, a, b = x_ref.shape
    k = l // lc
    m = m_ref[...].reshape(-1)
    rstd = jax.lax.rsqrt(v_ref[...].reshape(-1) + eps)
    g = g_ref[...].reshape(-1).astype(jnp.float32)
    mb, rb, gb = m[None, None, :], rstd[None, None, :], g[None, None, :]
    dbb = db_ref[...].reshape(-1)[None, None, :]
    dgb = dg_ref[...].reshape(-1)[None, None, :]

    def wr(i, _):
        sl = pl.ds(i * jnp.int32(lc), lc)
        xhat = (x_ref[sl].astype(jnp.float32) - mb) * rb
        gp = dr_ref[sl].astype(jnp.float32)
        dx = gb * rb * (gp - (dbb + xhat * dgb) / cnt)
        dx_ref[sl] = dx.astype(dx_ref.dtype)
        return jnp.int32(0)
    jax.lax.fori_loop(jnp.int32(0), jnp.int32(k), wr, jnp.int32(0))


# ---------------------------------------------------------------------------
# pallas_call plumbing
# ---------------------------------------------------------------------------


def _specs(l, n, c, ab, ch_axis, fold=1):
    """Block specs for the (L, A, B) view.  ab = (A-block, B-block).
    Grid is (groups, channel-blocks); channel params/stats use the
    'equal-dim trick' shapes so small channel blocks stay legal.  With
    ``fold`` > 1 (lane-fold, LNC only) the X blocks carry fold*B lanes
    while params/stats stay at the true channel width — the kernels
    fold-reduce/tile between the two."""
    a_blk, b_blk = ab
    if ch_axis == 2:   # LNC: A=N (groups on sublanes), B=C
        xspec = pl.BlockSpec((l, a_blk, fold * b_blk),
                             lambda g, ci: (_I0, g, ci))
        pspec = pl.BlockSpec((1, b_blk), lambda g, ci: (_I0, ci))
        sspec = pl.BlockSpec((1, 1, b_blk), lambda g, ci: (g, _I0, ci))
        n_groups = n // a_blk
        pshape = (1, c)
        sshape = (n_groups, 1, c)
    else:              # LCN: A=C (channels on sublanes), B=N (groups)
        xspec = pl.BlockSpec((l, a_blk, b_blk), lambda g, ci: (_I0, ci, g))
        pspec = pl.BlockSpec((a_blk, 1), lambda g, ci: (ci, _I0))
        sspec = pl.BlockSpec((1, a_blk, 1), lambda g, ci: (g, ci, _I0))
        n_groups = n // b_blk
        pshape = (c, 1)
        sshape = (n_groups, c, 1)
    return xspec, pspec, sspec, n_groups, pshape, sshape


def _call_fwd(x_v, gamma, beta, residual, eps, act, ab, ch_axis,
              donate_res=False, fold=1):
    l = x_v.shape[0]
    n = x_v.shape[1] if ch_axis == 2 else x_v.shape[2]
    c = (x_v.shape[2] // fold) if ch_axis == 2 else x_v.shape[1]
    xspec, pspec, sspec, ngroups, pshape, sshape = _specs(l, n, c, ab,
                                                          ch_axis, fold)
    grid = (ngroups, c // (ab[1] if ch_axis == 2 else ab[0]))
    lc = _chunk(l, ab[0], ab[1] * (fold if ch_axis == 2 else 1))
    out_shape = [jax.ShapeDtypeStruct(x_v.shape, x_v.dtype),
                 jax.ShapeDtypeStruct(sshape, jnp.float32),
                 jax.ShapeDtypeStruct(sshape, jnp.float32)]
    aliases = {}
    if residual is None:
        kern = functools.partial(_fwd_kernel, eps=eps, act=act, lc=lc,
                                 ch_axis=ch_axis, fold=fold)
        in_specs = [xspec, pspec, pspec]
        args = (x_v, gamma.reshape(pshape), beta.reshape(pshape))
    else:
        kern = functools.partial(_fwd_kernel_res, eps=eps, act=act, lc=lc,
                                 ch_axis=ch_axis, fold=fold)
        in_specs = [xspec, xspec, pspec, pspec]
        args = (x_v, residual, gamma.reshape(pshape), beta.reshape(pshape))
        if donate_res:
            # the caller declared the residual dead after this layer
            # (the downsample-shortcut case): Y writes into its window
            # — the norm loop reads r[sl] strictly before y[sl] lands,
            # so the in-place chunk update is race-free
            aliases = {1: 0}
    y, m, v = pl.pallas_call(
        kern, grid=grid, in_specs=in_specs,
        out_specs=[xspec, sspec, sspec], out_shape=out_shape,
        input_output_aliases=_aliases(aliases),
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel"),
            vmem_limit_bytes=_VMEM_KERNEL_LIMIT),
        interpret=_use_interpret())(*args)
    return y, m.reshape(ngroups, c), v.reshape(ngroups, c)


def _call_bwd(gy, x_v, y_v, gamma, beta, m, v, eps, act, ab, ch_axis,
              fold=1, gy2=None):
    """One-read backward.  The cotangent gY and the saved X are both
    dead after this call (gY's only consumer is this vjp; X was saved
    exactly for it), so the kernels write their outputs in place:
    dX over gY (non-residual) / dR over gY and dX over X (residual) via
    ``input_output_aliases`` — the reduction loop finishes every chunk
    read before the write loop touches a window, and within the write
    loop each chunk is read strictly before it is overwritten.  That
    cuts the double-buffered VMEM budget from 3 (5 residual) full
    windows to 2 (3), which is what lets the 28x28x512 residual exits
    and the 56x56x256 downsample BN run the fused bwd at batch 256
    (docs/PERF.md round 19).  ``gy2`` is the dual-output shortcut
    cotangent (round 20): a block exit returning its tensor in TWO
    output positions receives the conv-path and shortcut cotangents
    separately, and the kernel sums them on the window load instead of
    the program paying a materialized add_any join."""
    l = x_v.shape[0]
    n = x_v.shape[1] if ch_axis == 2 else x_v.shape[2]
    c = (x_v.shape[2] // fold) if ch_axis == 2 else x_v.shape[1]
    xspec, pspec, sspec, ngroups, pshape, sshape = _specs(l, n, c, ab,
                                                          ch_axis, fold)
    grid = (ngroups, c // (ab[1] if ch_axis == 2 else ab[0]))
    lc = _chunk(l, ab[0], ab[1] * (fold if ch_axis == 2 else 1))
    dstat = jax.ShapeDtypeStruct(sshape, jnp.float32)
    m_s = m.reshape(sshape)
    v_s = v.reshape(sshape)
    if y_v is None:
        if gy2 is not None:
            # no dual non-residual kernel form (the model only marks
            # residual block exits dual) — merge upfront, stay correct
            gy = gy + gy2
        kern = functools.partial(_bwd_kernel, eps=eps, act=act, lc=lc,
                                 ch_axis=ch_axis, fold=fold)
        dx, dg, db = pl.pallas_call(
            kern, grid=grid,
            in_specs=[xspec, xspec, pspec, pspec, sspec, sspec],
            out_specs=[xspec, sspec, sspec],
            out_shape=[jax.ShapeDtypeStruct(x_v.shape, x_v.dtype), dstat,
                       dstat],
            input_output_aliases=_aliases({0: 0}),  # dX over dead gY
            compiler_params=_CompilerParams(
                dimension_semantics=("parallel", "parallel"),
                vmem_limit_bytes=_VMEM_KERNEL_LIMIT),
            interpret=_use_interpret())(
            gy, x_v, gamma.reshape(pshape), beta.reshape(pshape), m_s, v_s)
        dr = None
    else:
        if gy2 is None:
            kern = functools.partial(_bwd_kernel_res, eps=eps, act=act,
                                     lc=lc, ch_axis=ch_axis, fold=fold)
            in_specs = [xspec, xspec, xspec, pspec, sspec, sspec]
            args = (gy, x_v, y_v, gamma.reshape(pshape), m_s, v_s)
            aliases = {0: 3, 1: 0}  # dR/gY, dX/X
        else:
            kern = functools.partial(_bwd_kernel_res_dual, eps=eps,
                                     act=act, lc=lc, ch_axis=ch_axis,
                                     fold=fold)
            in_specs = [xspec, xspec, xspec, xspec, pspec, sspec, sspec]
            args = (gy, gy2, x_v, y_v, gamma.reshape(pshape), m_s, v_s)
            aliases = {0: 3, 2: 0}  # dR/gY1, dX/X
        dx, dg, db, dr = pl.pallas_call(
            kern, grid=grid, in_specs=in_specs,
            out_specs=[xspec, sspec, sspec, xspec],
            out_shape=[jax.ShapeDtypeStruct(x_v.shape, x_v.dtype), dstat,
                       dstat, jax.ShapeDtypeStruct(x_v.shape, x_v.dtype)],
            input_output_aliases=_aliases(aliases),
            compiler_params=_CompilerParams(
                dimension_semantics=("parallel", "parallel"),
                vmem_limit_bytes=_VMEM_KERNEL_LIMIT),
            interpret=_use_interpret())(*args)
    return (dx, dg.reshape(ngroups, c).sum(0), db.reshape(ngroups, c).sum(0),
            dr)


def _tile_specs(lt, ng, c):
    """Block specs for the spatial-tiled (LNC) grid (groups, tiles)."""
    xspec = pl.BlockSpec((lt, ng, c), lambda g, t: (t, g, _I0))
    pspec = pl.BlockSpec((1, c), lambda g, t: (_I0, _I0))
    sspec = pl.BlockSpec((1, 1, c), lambda g, t: (g, _I0, _I0))
    return xspec, pspec, sspec


def _tile_params(sequential):
    return _CompilerParams(
        dimension_semantics=("parallel",
                             "arbitrary" if sequential else "parallel"),
        vmem_limit_bytes=_VMEM_KERNEL_LIMIT)


def _call_fwd_tiled(x_v, gamma, beta, residual, eps, act, ab, lt,
                    donate_res=False):
    """Spatial-tiled forward (LNC only).  Phase 1 walks the L-tiles
    sequentially accumulating (G, 1, C) partial sums, the moments
    finalize on the tiny partials in plain jnp, and the fully-parallel
    phase-2 kernel re-reads X to normalize — one extra read of X vs the
    whole-L fused form, charged honestly as its own pallas_call."""
    l, n, c = x_v.shape
    ng = ab[0]
    ngroups, ntiles = n // ng, l // lt
    lc = _chunk(lt, ng, c)
    xspec, pspec, sspec = _tile_specs(lt, ng, c)
    sshape = (ngroups, 1, c)
    s, ss = pl.pallas_call(
        functools.partial(_stats_tile_kernel, lc=lc),
        grid=(ngroups, ntiles), in_specs=[xspec],
        out_specs=[sspec, sspec],
        out_shape=[jax.ShapeDtypeStruct(sshape, jnp.float32)] * 2,
        compiler_params=_tile_params(True),
        interpret=_use_interpret())(x_v)
    cnt = l * ng
    m = (s / cnt).reshape(ngroups, c)
    v = jnp.maximum((ss / cnt).reshape(ngroups, c) - m * m, 0.0)
    m_s, v_s = m.reshape(sshape), v.reshape(sshape)
    aliases = {}
    if residual is None:
        kern = functools.partial(_norm_tile_kernel, eps=eps, act=act, lc=lc)
        in_specs = [xspec, pspec, pspec, sspec, sspec]
        args = (x_v, gamma.reshape(1, c), beta.reshape(1, c), m_s, v_s)
    else:
        kern = functools.partial(_norm_tile_kernel_res, eps=eps, act=act,
                                 lc=lc)
        in_specs = [xspec, xspec, pspec, pspec, sspec, sspec]
        args = (x_v, residual, gamma.reshape(1, c), beta.reshape(1, c),
                m_s, v_s)
        if donate_res:
            aliases = {1: 0}  # Y over the dead (donated) residual window
    y = pl.pallas_call(
        kern, grid=(ngroups, ntiles), in_specs=in_specs, out_specs=xspec,
        out_shape=jax.ShapeDtypeStruct(x_v.shape, x_v.dtype),
        input_output_aliases=_aliases(aliases),
        compiler_params=_tile_params(False),
        interpret=_use_interpret())(*args)
    return y, m, v


def _call_bwd_tiled(gy, x_v, y_v, gamma, beta, m, v, eps, act, ab, lt,
                    gy2=None):
    """Spatial-tiled backward (LNC only).  No residual: sequential
    phase-1 dbeta/dgamma partial reductions, then a fully-parallel
    phase-2 dX with the cross-tile totals (dX over the dead gY window).
    Residual (round 20, the gY-read-once protocol): phase 1 reads
    (gY[, gY2], X, Y) ONCE, producing the stat partials AND the masked
    cotangent dR (aliasing gY's window); phase 2 reads only (dR, X) —
    the mask is baked into dR, so gY and Y are never re-read — and dX
    aliases X.  That is 5 operand-tile reads instead of 6 (8 dual)."""
    l, n, c = x_v.shape
    ng = ab[0]
    ngroups, ntiles = n // ng, l // lt
    lc = _chunk(lt, ng, c)
    xspec, pspec, sspec = _tile_specs(lt, ng, c)
    sshape = (ngroups, 1, c)
    dstat = jax.ShapeDtypeStruct(sshape, jnp.float32)
    m_s, v_s = m.reshape(sshape), v.reshape(sshape)
    cnt = l * ng
    if y_v is None:
        if gy2 is not None:
            gy = gy + gy2  # no dual non-residual form (see _call_bwd)
        red = functools.partial(_bwd_red_tile_kernel, eps=eps, act=act,
                                lc=lc)
        db, dg = pl.pallas_call(
            red, grid=(ngroups, ntiles),
            in_specs=[xspec, xspec, pspec, pspec, sspec, sspec],
            out_specs=[sspec, sspec], out_shape=[dstat, dstat],
            compiler_params=_tile_params(True),
            interpret=_use_interpret())(
            gy, x_v, gamma.reshape(1, c), beta.reshape(1, c), m_s, v_s)
        kern = functools.partial(_bwd_dx_tile_kernel, eps=eps, act=act,
                                 lc=lc, cnt=cnt)
        dx = pl.pallas_call(
            kern, grid=(ngroups, ntiles),
            in_specs=[xspec, xspec, pspec, pspec, sspec, sspec, sspec,
                      sspec],
            out_specs=xspec,
            out_shape=jax.ShapeDtypeStruct(x_v.shape, x_v.dtype),
            input_output_aliases=_aliases({0: 0}),  # dX over dead gY
            compiler_params=_tile_params(False),
            interpret=_use_interpret())(
            gy, x_v, gamma.reshape(1, c), beta.reshape(1, c), m_s, v_s,
            db, dg)
        dr = None
    else:
        if gy2 is None:
            red = functools.partial(_bwd_red_tile_kernel_res, eps=eps,
                                    act=act, lc=lc)
            in_specs = [xspec, xspec, xspec, sspec, sspec]
            args = (gy, x_v, y_v, m_s, v_s)
        else:
            red = functools.partial(_bwd_red_tile_kernel_res_dual, eps=eps,
                                    act=act, lc=lc)
            in_specs = [xspec, xspec, xspec, xspec, sspec, sspec]
            args = (gy, gy2, x_v, y_v, m_s, v_s)
        db, dg, dr = pl.pallas_call(
            red, grid=(ngroups, ntiles), in_specs=in_specs,
            out_specs=[sspec, sspec, xspec],
            out_shape=[dstat, dstat,
                       jax.ShapeDtypeStruct(x_v.shape, x_v.dtype)],
            input_output_aliases=_aliases({0: 2}),  # dR over dead gY
            compiler_params=_tile_params(True),
            interpret=_use_interpret())(*args)
        kern = functools.partial(_bwd_dx_from_dr_tile_kernel, eps=eps,
                                 lc=lc, cnt=cnt)
        dx = pl.pallas_call(
            kern, grid=(ngroups, ntiles),
            in_specs=[xspec, xspec, pspec, sspec, sspec, sspec, sspec],
            out_specs=xspec,
            out_shape=jax.ShapeDtypeStruct(x_v.shape, x_v.dtype),
            input_output_aliases=_aliases({1: 0}),  # dX over dead X
            compiler_params=_tile_params(False),
            interpret=_use_interpret())(
            dr, x_v, gamma.reshape(1, c), m_s, v_s, db, dg)
    return (dx, dg.reshape(ngroups, c).sum(0), db.reshape(ngroups, c).sum(0),
            dr)


# ---------------------------------------------------------------------------
# plan selection + views
# ---------------------------------------------------------------------------


class Plan(NamedTuple):
    """One BN layer's kernel selection.  Field ORDER is load-bearing:
    older callers index ``plan[0..2]`` as ``(ch_axis, ab, bwd_pallas)``.
    ``variant``/``bwd_variant`` name the kernel form per direction
    (``fused`` = whole-L one-read, ``lanefold`` = L-rows folded into the
    padded lanes, ``tiled`` = two-phase spatial tiles, ``jnp`` = the
    ghost fallback for that direction)."""
    ch_axis: int
    ab: Tuple[int, int]
    bwd_pallas: bool
    variant: str = "fused"
    bwd_variant: str = "fused"
    fold: int = 1        # lane-fold factor k = 128/C (lanefold only)
    l_tile: int = 0      # fwd L-tile rows (tiled fwd only)
    l_tile_bwd: int = 0  # bwd L-tile rows (tiled bwd only)
    window_bytes: int = 0  # padded per-window bytes of the fwd form


def _plan(n, c, l, itemsize, group, has_res, donate_res=False, dual=False):
    """Choose a :class:`Plan` or None for the full-jnp fallback.

    Feasibility is per DIRECTION: Mosaic double-buffers every window
    (x2) and pads sublanes/lanes to the dtype tile.  Window counts
    reflect the in-place aliasing ``_call_fwd``/``_call_bwd`` declare:
    fwd needs 2 windows (X in, Y out) + 1 for a residual — or +0 when
    the caller donates it (``donate_residual``: dead shortcut tensors
    alias into Y); bwd needs 2 (X in, dX over the dead gY window) + 1
    residual (Y for the post-add ReLU mask; dR rides the gY window and
    dX the X window) + 1 when the exit is dual (``dual``: the separate
    shortcut cotangent gY2 needs its own window).  The tiled residual
    bwd peaks in phase 1 at the same count (gY[, gY2], X, Y in, dR over
    gY); its phase 2 needs only 2 (dR and X in, dX over X) — under the
    phase-1 peak.

    Selection order on the LNC path (round 20): whole-L fused both
    directions > lane-fold both (C < 128: the window shrinks by
    k = 128/C, same one-read kernels) > whole-L fused fwd + spatial-
    tiled bwd > spatial-tiled both > whole-L fused fwd + jnp bwd (the
    legacy hybrid) > None.  Earlier forms read each operand once; the
    tiled forms pay one extra read of the operands (the stats phase) —
    still a win over the jnp fallback's unfused multi-pass traffic, and
    census-exempt custom DMA either way.
    """
    sub = _sublane(itemsize)

    def padded(a_blk, b_blk, rows=l):
        return rows * _rup(a_blk, sub) * _rup(b_blk, 128) * itemsize

    def fits(nwin, a_blk, b_blk, rows=l):
        return nwin * 2 * padded(a_blk, b_blk, rows) <= _WINDOW_BUDGET

    fw = (3 - (1 if donate_res else 0)) if has_res else 2
    bw = ((4 if dual else 3) if has_res else 2)
    if c >= 128 or n > 128:
        # LNC: full C on lanes, ghost group on sublanes.  Prefer
        # tile-multiple groups (a sub-tile group pads VMEM to the tile
        # without shrinking it), largest first; the user group is a CAP.
        cap = min(group if group else 32, n)
        ngs = sorted((g for g in range(1, cap + 1) if n % g == 0),
                     key=lambda g: (g % sub == 0, g), reverse=True)
        # prefer the largest group for which BOTH directions fuse (group
        # size doesn't change the bytes saved, a fused bwd does); fall
        # back to the largest fwd-only group
        best_fwd = None
        for ng in ngs:
            if fits(fw, ng, c):
                if fits(bw, ng, c):
                    return Plan(2, (ng, c), True,
                                window_bytes=padded(ng, c))
                if best_fwd is None:
                    best_fwd = ng
        # lane-fold: C < 128 pads its lanes to 128 anyway — pack
        # k = 128/C rows of L into the padding so the window shrinks by
        # k.  The ghost group stays the sublane image block (bn_group
        # cap semantics unchanged); stats fold-reduce in-kernel.
        fold = 128 // c if (c < 128 and 128 % c == 0) else 1
        if fold > 1 and l % fold == 0:
            lf = l // fold
            for ng in ngs:
                if fits(fw, ng, fold * c, lf):
                    bwd_ok = fits(bw, ng, fold * c, lf)
                    return Plan(2, (ng, c), bwd_ok, "lanefold",
                                "lanefold" if bwd_ok else "jnp",
                                fold=fold,
                                window_bytes=padded(ng, fold * c, lf))

        def tile_rows(nwin, ng):
            # largest L-divisor tile whose nwin windows fit, capped at
            # _MAX_TILES tiles (whole-L itself is the nt=1 case the
            # callers above already rejected)
            for nt in range(2, _MAX_TILES + 1):
                if l % nt == 0 and fits(nwin, ng, c, l // nt):
                    return l // nt
            return 0

        # whole-L fused fwd + spatial-tiled bwd: keeps the one-read fwd
        # and still retires the bwd multi-pass (the donated 56x56x256
        # downsample at batch 256)
        if best_fwd is not None:
            ltb = tile_rows(bw, best_fwd)
            if ltb:
                return Plan(2, (best_fwd, c), True, "fused", "tiled",
                            l_tile_bwd=ltb,
                            window_bytes=padded(best_fwd, c))
        # spatial-tiled both directions (the 56x56x256 identity exits)
        for ng in ngs:
            ltf = tile_rows(fw, ng)
            if ltf:
                ltb = tile_rows(bw, ng)
                return Plan(2, (ng, c), bool(ltb), "tiled",
                            "tiled" if ltb else "jnp",
                            l_tile=ltf, l_tile_bwd=ltb,
                            window_bytes=padded(ng, c, ltf))
        # whole-L fused fwd + jnp bwd (the legacy hybrid)
        if best_fwd is not None:
            return Plan(2, (best_fwd, c), False, "fused", "jnp",
                        window_bytes=padded(best_fwd, c))
        return None
    # small-N path (N <= 128, C < 128): channels on sublanes, the WHOLE
    # batch on lanes — exact full-batch statistics, contiguous
    # cb*N*itemsize runs (the block covers full N and a dense C-slice).
    # This kernel's ghost group IS the full lane block (= N): when the
    # caller capped the group below that, honoring the declared
    # bn_group semantics outranks the kernel — fall back to the jnp
    # formulation, which computes the capped per-group statistics.
    if group and group < n:
        return None
    cb = c
    while cb > 0 and not fits(fw, cb, n):
        cb -= sub
        while cb > 0 and c % cb:
            cb -= 1
    if cb <= 0:
        return None
    bwd_ok = fits(bw, cb, n)
    return Plan(1, (cb, n), bwd_ok, "fused", "fused" if bwd_ok else "jnp",
                window_bytes=padded(cb, n))


def _to_view(x, ch_axis, fold=1):
    n, c, h, w = x.shape
    if ch_axis == 2:   # (L, N, C): bitcast of layout {1,0,3,2}
        v = jnp.transpose(x, (2, 3, 0, 1)).reshape(h * w, n, c)
        if fold > 1:
            # lane-fold view (L/k, N, k*C): k consecutive L rows move
            # into the padded lane dim; feeds a custom kernel, so the
            # layout chain folds into the window DMA (cost_model.py)
            lf = h * w // fold
            v = jnp.transpose(v.reshape(lf, fold, n, c),
                              (0, 2, 1, 3)).reshape(lf, n, fold * c)
        return v
    # (L, C, N): bitcast of layout {0,1,3,2}
    return jnp.transpose(x, (2, 3, 1, 0)).reshape(h * w, c, n)


def _from_view(x_v, shape, ch_axis, fold=1):
    n, c, h, w = shape
    if ch_axis == 2:
        if fold > 1:
            lf = h * w // fold
            x_v = jnp.transpose(x_v.reshape(lf, n, fold, c),
                                (0, 2, 1, 3)).reshape(h * w, n, c)
        return jnp.transpose(x_v.reshape(h, w, n, c), (2, 3, 0, 1))
    return jnp.transpose(x_v.reshape(h, w, c, n), (3, 2, 0, 1))


# ---------------------------------------------------------------------------
# custom-vjp public entry
# ---------------------------------------------------------------------------


def _gbn_fwd(x, gamma, beta, residual, eps, act, group, donate_res=False,
             dual=False):
    n, c, h, w = x.shape
    plan = _plan(n, c, h * w, x.dtype.itemsize, group,
                 residual is not None, donate_res, dual)
    ch_axis = plan.ch_axis
    fold = plan.fold if plan.variant == "lanefold" else 1
    x_v = _to_view(x, ch_axis, fold)
    r_v = None if residual is None else _to_view(residual, ch_axis, fold)
    if plan.variant == "tiled":
        y_v, m, v = _call_fwd_tiled(x_v, gamma, beta, r_v, eps, act,
                                    plan.ab, plan.l_tile,
                                    donate_res=donate_res)
    else:
        y_v, m, v = _call_fwd(x_v, gamma, beta, r_v, eps, act, plan.ab,
                              ch_axis, donate_res=donate_res, fold=fold)
    y = _from_view(y_v, x.shape, ch_axis, fold)
    res = (x_v, y_v if residual is not None else None, gamma, beta, m, v,
           x.shape)
    return ((y, m, v), res)


def _gbn_bwd_jnp(gy, x, y, gamma, beta, m, v, eps, act, ng):
    """Ghost-BN backward in plain jnp over the SAME ghost groups as the
    kernels — the hybrid path for layers whose bwd windows don't fit
    VMEM but whose fwd does (the fwd still saves its stats read)."""
    n, c, h, w = x.shape
    g = n // ng
    f32 = jnp.float32
    x5 = x.astype(f32).reshape(g, ng, c, h, w)
    gy5 = gy.astype(f32).reshape(g, ng, c, h, w)
    mb = m.reshape(g, 1, c, 1, 1)
    rstd = jax.lax.rsqrt(v + eps).reshape(g, 1, c, 1, 1)
    gam = gamma.astype(f32).reshape(1, 1, c, 1, 1)
    xhat = (x5 - mb) * rstd
    if act == "relu":
        if y is not None:
            keep = y.astype(f32).reshape(g, ng, c, h, w) > 0
        else:
            keep = (xhat * gam
                    + beta.astype(f32).reshape(1, 1, c, 1, 1)) > 0
        gp = jnp.where(keep, gy5, 0.0)
    else:
        gp = gy5
    cnt = ng * h * w
    db = gp.sum(axis=(1, 3, 4))
    dg = (gp * xhat).sum(axis=(1, 3, 4))
    dx = (gam * rstd
          * (gp - (db.reshape(g, 1, c, 1, 1)
                   + xhat * dg.reshape(g, 1, c, 1, 1)) / cnt))
    dr = gp.reshape(n, c, h, w).astype(x.dtype) if y is not None else None
    return (dx.reshape(n, c, h, w).astype(x.dtype), dg.sum(0), db.sum(0),
            dr)


def _gbn_bwd_impl(eps, act, group, donate_res, dual, res, gy, gy2):
    x_v, y_v, gamma, beta, m, v, shape = res
    n, c, h, w = shape
    plan = _plan(n, c, h * w, x_v.dtype.itemsize, group, y_v is not None,
                 donate_res, dual)
    ch_axis = plan.ch_axis
    fold = plan.fold if plan.variant == "lanefold" else 1
    if plan.bwd_pallas:
        gy_v = _to_view(gy, ch_axis, fold)
        gy2_v = None if gy2 is None else _to_view(gy2, ch_axis, fold)
        if plan.bwd_variant == "tiled":
            dx, dg, db, dr = _call_bwd_tiled(gy_v, x_v, y_v, gamma, beta,
                                             m, v, eps, act, plan.ab,
                                             plan.l_tile_bwd, gy2=gy2_v)
        else:
            dx, dg, db, dr = _call_bwd(gy_v, x_v, y_v, gamma, beta, m, v,
                                       eps, act, plan.ab, ch_axis,
                                       fold=fold, gy2=gy2_v)
        dx = _from_view(dx, shape, ch_axis, fold)
        dr = None if dr is None else _from_view(dr, shape, ch_axis, fold)
    else:
        if gy2 is not None:
            gy = gy + gy2
        x = _from_view(x_v, shape, ch_axis, fold)
        y = None if y_v is None else _from_view(y_v, shape, ch_axis, fold)
        ng = plan.ab[0] if ch_axis == 2 else plan.ab[1]
        dx, dg, db, dr = _gbn_bwd_jnp(gy, x, y, gamma, beta, m, v, eps,
                                      act, ng)
    return (dx, dg.astype(gamma.dtype), db.astype(beta.dtype), dr)


def _gbn_bwd(eps, act, group, donate_res, res, ct):
    gy, _, _ = ct  # cotangents for the stat outputs are not propagated
    return _gbn_bwd_impl(eps, act, group, donate_res, False, res, gy, None)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
def _gbn_full(x, gamma, beta, residual, eps, act, group, donate_res):
    """Returns (y, group_mean, group_var) — stat outputs get zero vjp."""
    return _gbn_fwd(x, gamma, beta, residual, eps, act, group, donate_res)[0]


_gbn_full.defvjp(_gbn_fwd, _gbn_bwd)


def _gbn_fwd_dual(x, gamma, beta, residual, eps, act, group, donate_res):
    (y, m, v), res = _gbn_fwd(x, gamma, beta, residual, eps, act, group,
                              donate_res, dual=True)
    return ((y, y, m, v), res)


def _gbn_bwd_dual(eps, act, group, donate_res, res, ct):
    gy, gy2, _, _ = ct
    return _gbn_bwd_impl(eps, act, group, donate_res, True, res, gy, gy2)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
def _gbn_full_dual(x, gamma, beta, residual, eps, act, group, donate_res):
    """Dual-output form: returns (y, y, group_mean, group_var) — the SAME
    tensor exposed in two output positions so a residual block exit can
    route its conv path through one and its shortcut through the other.
    Autodiff then delivers the two cotangents separately and the fused
    bwd sums them on the VMEM window load, absorbing the add_any join
    the program would otherwise materialize (docs/PERF.md round 20)."""
    (y, m, v), _ = _gbn_fwd(x, gamma, beta, residual, eps, act, group,
                            donate_res, dual=True)
    return (y, y, m, v)


_gbn_full_dual.defvjp(_gbn_fwd_dual, _gbn_bwd_dual)


def ghost_bn_stats_merge(m, v):
    """(G, C) group stats -> (C,) whole-batch population stats via the law
    of total variance (for running-average updates)."""
    bm = jnp.mean(m, axis=0)
    bv = jnp.mean(v + m * m, axis=0) - bm * bm
    return bm, jnp.maximum(bv, 0.0)


def _gbn_ref(x, gamma, beta, residual, eps, act, group):
    """Pure-jnp ghost BN (same semantics, standard XLA passes) — the
    fallback for layers whose slab cannot fit the VMEM window budget
    (e.g. the 112x112 stem at batch 256)."""
    n, c, h, w = x.shape
    ng = min(n, group or 32)
    while n % ng:
        ng -= 1
    g = n // ng
    x32 = x.astype(jnp.float32).reshape(g, ng, c, h, w)
    m = jnp.mean(x32, axis=(1, 3, 4))
    v = jnp.maximum(jnp.mean(x32 * x32, axis=(1, 3, 4)) - m * m, 0.0)
    rstd = jax.lax.rsqrt(v + eps)
    g32 = gamma.astype(jnp.float32)
    scale = (g32[None] * rstd)[:, None, :, None, None]
    shift = (beta.astype(jnp.float32)[None]
             - m * g32[None] * rstd)[:, None, :, None, None]
    y = (x32 * scale + shift).reshape(n, c, h, w)
    if residual is not None:
        y = y + residual.astype(jnp.float32)
    if act == "relu":
        y = jnp.maximum(y, 0.0)
    return y.astype(x.dtype), m, v


def plan_describe(n, c, h, w, itemsize=2, group=0, has_res=False,
                  donate_res=False, dual=False):
    """One layer's kernel-plan decision as a plain dict — the inspectable
    face of :func:`_plan` (``tools/graftcost.py``'s per-layer table, the
    ``MXTPU_BN_PLAN`` trace log).  ``variant``/``bwd`` name the per-
    direction kernel form; ``window_mb`` is the padded per-window VMEM
    footprint the feasibility check charged; ``fold``/``l_tile`` are the
    lane-fold factor and spatial tile rows where those forms apply;
    ``dual`` marks a dual-cotangent block exit (one extra bwd window)."""
    plan = _plan(int(n), int(c), int(h) * int(w), int(itemsize),
                 int(group), bool(has_res), bool(donate_res), bool(dual))
    if plan is None:
        return {"variant": "jnp", "bwd": "jnp", "fold": 1, "l_tile": 0,
                "l_tile_bwd": 0, "window_mb": 0.0, "group": 0,
                "dual": bool(dual)}
    return {"variant": plan.variant,
            "bwd": plan.bwd_variant if plan.bwd_pallas else "jnp",
            "fold": plan.fold,
            "l_tile": plan.l_tile,
            "l_tile_bwd": plan.l_tile_bwd,
            "window_mb": round(plan.window_bytes / 1e6, 1),
            "group": plan.ab[0] if plan.ch_axis == 2 else plan.ab[1],
            "dual": bool(dual)}


_PLAN_LOGGED = set()


def _log_plan(shape, dtype, group, has_res, donate, dual=False):
    """Once-per-distinct-layer plan trace (MXTPU_BN_PLAN=1): the layer
    selection is automatic, this makes it visible without a debugger."""
    if not os.environ.get("MXTPU_BN_PLAN"):
        return
    key = (tuple(shape), str(dtype), int(group), bool(has_res),
           bool(donate), bool(dual))
    if key in _PLAN_LOGGED:
        return
    _PLAN_LOGGED.add(key)
    n, c, h, w = shape
    d = plan_describe(n, c, h, w, np.dtype(dtype).itemsize, group,
                      has_res, donate, dual)
    print("[ghost-bn] %dx%dx%dx%d %s group<=%d res=%d donate=%d dual=%d "
          "-> fwd=%s bwd=%s fold=%d l_tile=%d/%d window=%.1fMB group=%d"
          % (n, c, h, w, np.dtype(dtype).name, int(group), bool(has_res),
             bool(donate), bool(dual), d["variant"], d["bwd"], d["fold"],
             d["l_tile"], d["l_tile_bwd"], d["window_mb"], d["group"]),
          file=sys.stderr, flush=True)


def ghost_bn_act(x, gamma, beta, residual=None, eps=1e-3, act="relu",
                 group=0, donate_residual=False, dual_out=False):
    """Fused ghost-BN(+residual)+activation.

    x: (N, C, H, W).  Returns ``(y, group_mean, group_var)`` with stats of
    shape (G, C).  The ``group`` argument is a CAP on the ghost group:
    the sublane path picks the largest fitting divisor under it, the
    small-C lane path (whose group is the whole lane block) and the jnp
    fallback honor it exactly — deterministic per shape.  ``act`` is
    ``"relu"`` or ``"none"`` (the downsample-BN case).
    ``donate_residual=True`` declares the residual tensor dead after
    this layer (the downsample-shortcut case — NEVER an identity
    shortcut, which the surrounding program still reads): the fwd
    kernel then writes Y over the residual's window, saving one VMEM
    window and letting larger exits fuse.  ``dual_out=True`` (residual
    block exits feeding both the next block's conv path and its
    shortcut) returns ``(y, y, group_mean, group_var)`` — the same
    tensor in two output positions, so autodiff delivers the two
    downstream cotangents separately and the fused bwd sums them on the
    VMEM window load instead of the program materializing an add_any
    join (one extra bwd window; the plan accounts for it).
    Differentiable in x, gamma, beta and residual (stat outputs carry
    zero gradient — they feed running-stat updates, which the reference
    likewise excludes from autograd, ``src/operator/nn/batch_norm.cc``
    aux states).  Layers whose windows can't fit the VMEM budget use an
    equivalent jnp formulation with the same ghost-group statistics.
    """
    n, c, h, w = x.shape
    donate = bool(donate_residual) and residual is not None
    dual = bool(dual_out)
    _log_plan(x.shape, x.dtype, int(group), residual is not None, donate,
              dual)
    if _plan(n, c, h * w, x.dtype.itemsize, int(group),
             residual is not None, donate, dual) is None:
        y, m, v = _gbn_ref(x, gamma, beta, residual, float(eps), act,
                           int(group))
        return (y, y, m, v) if dual else (y, m, v)
    if dual:
        return _gbn_full_dual(x, gamma, beta, residual, float(eps), act,
                              int(group), donate)
    return _gbn_full(x, gamma, beta, residual, float(eps), act, int(group),
                     donate)
