"""Pallas fused ghost batch norm (+ReLU, +residual-add) for TPU.

The north-star ResNet-50 train step is HBM-bound (docs/PERF.md): XLA runs
BatchNorm as separate full passes over each conv output — a stats
reduction read, a normalize+activation read+write in fwd, and a reduce
pass plus an elementwise pass in bwd (23 ms/step of
`convert_reduce_fusion` at batch 256).  These kernels keep a slab of the
activation resident in VMEM and do

* fwd:  statistics + normalize + (residual add) + ReLU in ONE read of X,
* bwd:  the dgamma/dbeta reductions AND dX (+ residual grad) in one
        read of (dY, X),

cutting ~2 full HBM passes per BatchNorm layer.

The price is *ghost* statistics: mean/var are computed per group of
images (the slab must fit VMEM), not over the whole local batch.  This
matches the per-device semantics of the distributed north-star row
(`dist_sync_device` computes BN stats per worker over batch/N_workers in
the reference — `src/operator/nn/batch_norm.cc` never reduces stats
across devices), and ghost/sub-batch BN is a standard, documented
technique; it is exposed as an explicit opt-in (`ghost_bn` on the model
zoo / `group` here), never a silent default.

Layout (the whole game — a wrong view forces XLA to insert full-tensor
transposes around the custom call):

* C >= 128: X viewed as (L, N, C), L = H*W.  The conv's TPU layout for
  these tensors is {1,0,3,2} (minor dims C, N) == row-major (H, W, N, C)
  — a bitcast.  Channels ride the 128 lanes; the ghost group is a
  sublane block of N (multiples of 16 for bf16, so windows don't pad).
* C < 128: X viewed as (L, C, N).  XLA lays small-C tensors out as
  {0,1,3,2} (minor dims N, C) == row-major (H, W, C, N) — also a
  bitcast.  Channels ride sublanes; the ghost group is the lane block
  of N (=128): an even larger statistics group.

Layers whose windows can't fit VMEM (the 112x112 stem, the 56x56
residual exits) fall back to an equivalent jnp formulation with the same
ghost statistics.

Interpret mode runs the same kernels on CPU for tests, like
parallel/flash_attention.py.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_I0 = np.int32(0)  # index-map literal pinned to i32 (package enables x64)

#: jax 0.4.x ships the TPU params type as ``TPUCompilerParams``; newer
#: releases renamed it ``CompilerParams``.  Resolve whichever exists —
#: interpret mode accepts either, so the CPU parity tests run the same
#: call path as the chip.
_CompilerParams = getattr(pltpu, "CompilerParams", None) \
    or getattr(pltpu, "TPUCompilerParams")

__all__ = ["ghost_bn_act", "ghost_bn_stats_merge"]

_VMEM_KERNEL_LIMIT = 120 * 1024 * 1024
_WINDOW_BUDGET = 104 * 1024 * 1024

#: in-place output aliasing (dX over gY etc. — see _call_bwd).  A
#: debugging escape hatch; the plan's window accounting assumes True.
_IO_ALIASES = True


def _aliases(d):
    return d if _IO_ALIASES else {}


def _use_interpret():
    try:
        return jax.default_backend() != "tpu"
    except Exception:
        return True


def _rup(x, m):
    return -(-x // m) * m


def _sublane(itemsize):
    return 16 if itemsize == 2 else 8


# NB round-5 rewrite: the round-4 kernels split C >= 256 into 128-wide
# lane blocks, which turned every window DMA into cb*itemsize-byte
# strided runs (256 B at 512 B stride for the stage-2 exits) — exactly
# the measured ~55 % of the BW roofline.  The channel dim is now NEVER
# split in the LNC view: a (L, ng, C) block reads ng*C*itemsize
# CONTIGUOUS runs (4-16 KB on the ResNet-50 shapes).


# ---------------------------------------------------------------------------
# kernels (parameterized by which block axis carries channels)
# ---------------------------------------------------------------------------
# Block shape is (L, A, B); ch_axis 2 means channels on B (lanes, LNC
# view), ch_axis 1 means channels on A (sublanes, LCN view).  Reductions
# run over the other two axes; scoped-VMEM stack limits (~16 MB) force
# chunked loops over L instead of whole-slab f32 temps.


def _chunk(l, a, b, budget=1536 * 1024):
    """Largest divisor of L within the f32-temp budget; a slightly
    over-budget divisor beats degenerating to many 1-row loop iterations
    (L=49 at the 7x7 stages has divisors {1,7,49} only).  The bwd kernel
    keeps ~3 chunk-sized f32 temps live at once, so the over-budget
    stretch is capped at 2x (3 x 3 MB = 9 MB, under the ~16 MB scoped-
    VMEM stack limit); when even 2x can't reach a divisor (tiny caps
    from very large A*B blocks) the degenerate small chunk stands —
    slow-ish but VMEM-safe."""
    cap = max(1, min(budget // (a * b * 4), l))
    divs = [d for d in range(1, l + 1) if l % d == 0]
    best = max((d for d in divs if d <= cap), default=1)
    if best * 2 <= cap:
        over = [d for d in divs if cap < d <= 2 * cap]
        if over:
            return min(over)
    return best


def _bshape(vec, ch_axis):
    return vec[None, :, None] if ch_axis == 1 else vec[None, None, :]


def _fwd_kernel(x_ref, g_ref, b_ref, y_ref, m_ref, v_ref, *, eps, act, lc,
                ch_axis, r_ref=None):
    l, a, b = x_ref.shape
    k = l // lc
    cnt = l * (b if ch_axis == 1 else a)
    nch = a if ch_axis == 1 else b

    # per-chunk reduce only over the major (L) axis into an (A, B) f32
    # accumulator — cross-sublane/lane reduction happens ONCE at the end
    # (per-chunk cross reduces were the VPU bottleneck)
    def red(i, acc):
        s, ss = acc
        xc = x_ref[pl.ds(i * jnp.int32(lc), lc)].astype(jnp.float32)
        return s + jnp.sum(xc, axis=0), ss + jnp.sum(xc * xc, axis=0)
    zero = jnp.zeros((a, b), jnp.float32)
    sm, ssq = jax.lax.fori_loop(jnp.int32(0), jnp.int32(k), red,
                                (zero, zero))
    cross = 1 if ch_axis == 1 else 0
    sm = jnp.sum(sm, axis=cross)
    ssq = jnp.sum(ssq, axis=cross)
    m = sm / cnt
    v = jnp.maximum(ssq / cnt - m * m, 0.0)
    rstd = jax.lax.rsqrt(v + eps)
    g = g_ref[...].reshape(-1).astype(jnp.float32)
    bb = b_ref[...].reshape(-1).astype(jnp.float32)
    scale = _bshape(g * rstd, ch_axis)
    shift = _bshape(bb - m * g * rstd, ch_axis)

    def norm(i, _):
        sl = pl.ds(i * jnp.int32(lc), lc)
        y = x_ref[sl].astype(jnp.float32) * scale + shift
        if r_ref is not None:
            y = y + r_ref[sl].astype(jnp.float32)
        if act == "relu":
            y = jnp.maximum(y, 0.0)
        y_ref[sl] = y.astype(y_ref.dtype)
        return jnp.int32(0)
    jax.lax.fori_loop(jnp.int32(0), jnp.int32(k), norm, jnp.int32(0))
    m_ref[...] = m.reshape(m_ref.shape)
    v_ref[...] = v.reshape(v_ref.shape)


def _fwd_kernel_res(x_ref, r_ref, g_ref, b_ref, y_ref, m_ref, v_ref, *,
                    eps, act, lc, ch_axis):
    _fwd_kernel(x_ref, g_ref, b_ref, y_ref, m_ref, v_ref, eps=eps, act=act,
                lc=lc, ch_axis=ch_axis, r_ref=r_ref)


def _bwd_kernel(gy_ref, x_ref, g_ref, b_ref, m_ref, v_ref, dx_ref, dg_ref,
                db_ref, *, eps, act, lc, ch_axis, y_ref=None, dr_ref=None):
    l, a, b = x_ref.shape
    k = l // lc
    cnt = l * (b if ch_axis == 1 else a)
    m = m_ref[...].reshape(-1)
    v = v_ref[...].reshape(-1)
    rstd = jax.lax.rsqrt(v + eps)
    g = g_ref[...].reshape(-1).astype(jnp.float32)
    bb = b_ref[...].reshape(-1).astype(jnp.float32) if b_ref is not None \
        else None
    mb = _bshape(m, ch_axis)
    rb = _bshape(rstd, ch_axis)
    gb = _bshape(g, ch_axis)

    def masked(sl, gyc, xhat):
        if act != "relu":
            return gyc
        if y_ref is not None:
            return jnp.where(y_ref[sl].astype(jnp.float32) > 0, gyc, 0.0)
        pre = xhat * gb + _bshape(bb, ch_axis)
        return jnp.where(pre > 0, gyc, 0.0)

    def red(i, acc):
        sdb, sdg = acc
        sl = pl.ds(i * jnp.int32(lc), lc)
        xhat = (x_ref[sl].astype(jnp.float32) - mb) * rb
        gp = masked(sl, gy_ref[sl].astype(jnp.float32), xhat)
        return sdb + jnp.sum(gp, axis=0), sdg + jnp.sum(gp * xhat, axis=0)
    zero = jnp.zeros((a, b), jnp.float32)
    db, dg = jax.lax.fori_loop(jnp.int32(0), jnp.int32(k), red, (zero, zero))
    cross = 1 if ch_axis == 1 else 0
    db = jnp.sum(db, axis=cross)
    dg = jnp.sum(dg, axis=cross)
    dbb = _bshape(db, ch_axis)
    dgb = _bshape(dg, ch_axis)

    def wr(i, _):
        sl = pl.ds(i * jnp.int32(lc), lc)
        xhat = (x_ref[sl].astype(jnp.float32) - mb) * rb
        gp = masked(sl, gy_ref[sl].astype(jnp.float32), xhat)
        dx = gb * rb * (gp - (dbb + xhat * dgb) / cnt)
        dx_ref[sl] = dx.astype(dx_ref.dtype)
        if dr_ref is not None:
            dr_ref[sl] = gp.astype(dr_ref.dtype)
        return jnp.int32(0)
    jax.lax.fori_loop(jnp.int32(0), jnp.int32(k), wr, jnp.int32(0))
    dg_ref[...] = dg.reshape(dg_ref.shape)
    db_ref[...] = db.reshape(db_ref.shape)


def _bwd_kernel_res(gy_ref, x_ref, y_ref, g_ref, m_ref, v_ref, dx_ref,
                    dg_ref, db_ref, dr_ref, *, eps, act, lc, ch_axis):
    # residual variant: the post-add ReLU mask comes from the saved OUTPUT
    # (y > 0 iff pre+res > 0), so the residual tensor itself is not re-read
    _bwd_kernel(gy_ref, x_ref, g_ref, None, m_ref, v_ref, dx_ref, dg_ref,
                db_ref, eps=eps, act=act, lc=lc, ch_axis=ch_axis,
                y_ref=y_ref, dr_ref=dr_ref)


# ---------------------------------------------------------------------------
# pallas_call plumbing
# ---------------------------------------------------------------------------


def _specs(l, n, c, ab, ch_axis):
    """Block specs for the (L, A, B) view.  ab = (A-block, B-block).
    Grid is (groups, channel-blocks); channel params/stats use the
    'equal-dim trick' shapes so small channel blocks stay legal."""
    a_blk, b_blk = ab
    if ch_axis == 2:   # LNC: A=N (groups on sublanes), B=C
        xspec = pl.BlockSpec((l, a_blk, b_blk), lambda g, ci: (_I0, g, ci))
        pspec = pl.BlockSpec((1, b_blk), lambda g, ci: (_I0, ci))
        sspec = pl.BlockSpec((1, 1, b_blk), lambda g, ci: (g, _I0, ci))
        n_groups = n // a_blk
        pshape = (1, c)
        sshape = (n_groups, 1, c)
    else:              # LCN: A=C (channels on sublanes), B=N (groups)
        xspec = pl.BlockSpec((l, a_blk, b_blk), lambda g, ci: (_I0, ci, g))
        pspec = pl.BlockSpec((a_blk, 1), lambda g, ci: (ci, _I0))
        sspec = pl.BlockSpec((1, a_blk, 1), lambda g, ci: (g, ci, _I0))
        n_groups = n // b_blk
        pshape = (c, 1)
        sshape = (n_groups, c, 1)
    return xspec, pspec, sspec, n_groups, pshape, sshape


def _call_fwd(x_v, gamma, beta, residual, eps, act, ab, ch_axis,
              donate_res=False):
    l = x_v.shape[0]
    n = x_v.shape[1] if ch_axis == 2 else x_v.shape[2]
    c = x_v.shape[2] if ch_axis == 2 else x_v.shape[1]
    xspec, pspec, sspec, ngroups, pshape, sshape = _specs(l, n, c, ab,
                                                          ch_axis)
    grid = (ngroups, c // (ab[1] if ch_axis == 2 else ab[0]))
    lc = _chunk(l, *ab)
    out_shape = [jax.ShapeDtypeStruct(x_v.shape, x_v.dtype),
                 jax.ShapeDtypeStruct(sshape, jnp.float32),
                 jax.ShapeDtypeStruct(sshape, jnp.float32)]
    aliases = {}
    if residual is None:
        kern = functools.partial(_fwd_kernel, eps=eps, act=act, lc=lc,
                                 ch_axis=ch_axis)
        in_specs = [xspec, pspec, pspec]
        args = (x_v, gamma.reshape(pshape), beta.reshape(pshape))
    else:
        kern = functools.partial(_fwd_kernel_res, eps=eps, act=act, lc=lc,
                                 ch_axis=ch_axis)
        in_specs = [xspec, xspec, pspec, pspec]
        args = (x_v, residual, gamma.reshape(pshape), beta.reshape(pshape))
        if donate_res:
            # the caller declared the residual dead after this layer
            # (the downsample-shortcut case): Y writes into its window
            # — the norm loop reads r[sl] strictly before y[sl] lands,
            # so the in-place chunk update is race-free
            aliases = {1: 0}
    y, m, v = pl.pallas_call(
        kern, grid=grid, in_specs=in_specs,
        out_specs=[xspec, sspec, sspec], out_shape=out_shape,
        input_output_aliases=_aliases(aliases),
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel"),
            vmem_limit_bytes=_VMEM_KERNEL_LIMIT),
        interpret=_use_interpret())(*args)
    return y, m.reshape(ngroups, c), v.reshape(ngroups, c)


def _call_bwd(gy, x_v, y_v, gamma, beta, m, v, eps, act, ab, ch_axis):
    """One-read backward.  The cotangent gY and the saved X are both
    dead after this call (gY's only consumer is this vjp; X was saved
    exactly for it), so the kernels write their outputs in place:
    dX over gY (non-residual) / dR over gY and dX over X (residual) via
    ``input_output_aliases`` — the reduction loop finishes every chunk
    read before the write loop touches a window, and within the write
    loop each chunk is read strictly before it is overwritten.  That
    cuts the double-buffered VMEM budget from 3 (5 residual) full
    windows to 2 (3), which is what lets the 28x28x512 residual exits
    and the 56x56x256 downsample BN run the fused bwd at batch 256
    (docs/PERF.md round 19)."""
    l = x_v.shape[0]
    n = x_v.shape[1] if ch_axis == 2 else x_v.shape[2]
    c = x_v.shape[2] if ch_axis == 2 else x_v.shape[1]
    xspec, pspec, sspec, ngroups, pshape, sshape = _specs(l, n, c, ab,
                                                          ch_axis)
    grid = (ngroups, c // (ab[1] if ch_axis == 2 else ab[0]))
    lc = _chunk(l, *ab)
    dstat = jax.ShapeDtypeStruct(sshape, jnp.float32)
    m_s = m.reshape(sshape)
    v_s = v.reshape(sshape)
    if y_v is None:
        kern = functools.partial(_bwd_kernel, eps=eps, act=act, lc=lc,
                                 ch_axis=ch_axis)
        dx, dg, db = pl.pallas_call(
            kern, grid=grid,
            in_specs=[xspec, xspec, pspec, pspec, sspec, sspec],
            out_specs=[xspec, sspec, sspec],
            out_shape=[jax.ShapeDtypeStruct(x_v.shape, x_v.dtype), dstat,
                       dstat],
            input_output_aliases=_aliases({0: 0}),  # dX over dead gY
            compiler_params=_CompilerParams(
                dimension_semantics=("parallel", "parallel"),
                vmem_limit_bytes=_VMEM_KERNEL_LIMIT),
            interpret=_use_interpret())(
            gy, x_v, gamma.reshape(pshape), beta.reshape(pshape), m_s, v_s)
        dr = None
    else:
        kern = functools.partial(_bwd_kernel_res, eps=eps, act=act, lc=lc,
                                 ch_axis=ch_axis)
        dx, dg, db, dr = pl.pallas_call(
            kern, grid=grid,
            in_specs=[xspec, xspec, xspec, pspec, sspec, sspec],
            out_specs=[xspec, sspec, sspec, xspec],
            out_shape=[jax.ShapeDtypeStruct(x_v.shape, x_v.dtype), dstat,
                       dstat, jax.ShapeDtypeStruct(x_v.shape, x_v.dtype)],
            input_output_aliases=_aliases({0: 3, 1: 0}),  # dR/gY, dX/X
            compiler_params=_CompilerParams(
                dimension_semantics=("parallel", "parallel"),
                vmem_limit_bytes=_VMEM_KERNEL_LIMIT),
            interpret=_use_interpret())(
            gy, x_v, y_v, gamma.reshape(pshape), m_s, v_s)
    return (dx, dg.reshape(ngroups, c).sum(0), db.reshape(ngroups, c).sum(0),
            dr)


# ---------------------------------------------------------------------------
# plan selection + views
# ---------------------------------------------------------------------------


def _plan(n, c, l, itemsize, group, has_res, donate_res=False):
    """Choose ``(ch_axis, (A-block, B-block), bwd_pallas)`` or None for
    the full-jnp fallback.

    Feasibility is per DIRECTION: Mosaic double-buffers every window
    (x2) and pads sublanes/lanes to the dtype tile.  Window counts
    reflect the in-place aliasing ``_call_fwd``/``_call_bwd`` declare:
    fwd needs 2 windows (X in, Y out) + 1 for a residual — or +0 when
    the caller donates it (``donate_residual``: dead shortcut tensors
    alias into Y); bwd needs 2 (X in, dX over the dead gY window) + 1
    residual (Y for the post-add ReLU mask; dR rides the gY window and
    dX the X window).  A layer whose bwd windows bust the budget still
    runs the single-read Pallas FWD with an equivalent jnp bwd over the
    same ghost groups (hybrid) — every non-stem ResNet-50 BN keeps at
    least the fwd stats-read saving.
    """
    sub = _sublane(itemsize)

    def padded(a_blk, b_blk):
        return l * _rup(a_blk, sub) * _rup(b_blk, 128) * itemsize

    def fits(nwin, a_blk, b_blk):
        return nwin * 2 * padded(a_blk, b_blk) <= _WINDOW_BUDGET

    fw = (3 - (1 if donate_res else 0)) if has_res else 2
    bw = 3 if has_res else 2
    if c >= 128 or n > 128:
        # LNC: full C on lanes, ghost group on sublanes.  Prefer
        # tile-multiple groups (a sub-tile group pads VMEM to the tile
        # without shrinking it), largest first; the user group is a CAP.
        cap = min(group if group else 32, n)
        ngs = sorted((g for g in range(1, cap + 1) if n % g == 0),
                     key=lambda g: (g % sub == 0, g), reverse=True)
        # prefer the largest group for which BOTH directions fuse (group
        # size doesn't change the bytes saved, a fused bwd does); fall
        # back to the largest fwd-only group
        best_fwd = None
        for ng in ngs:
            if fits(fw, ng, c):
                if fits(bw, ng, c):
                    return 2, (ng, c), True
                if best_fwd is None:
                    best_fwd = ng
        if best_fwd is not None:
            return 2, (best_fwd, c), False
        return None
    # small-N path (N <= 128, C < 128): channels on sublanes, the WHOLE
    # batch on lanes — exact full-batch statistics, contiguous
    # cb*N*itemsize runs (the block covers full N and a dense C-slice).
    # This kernel's ghost group IS the full lane block (= N): when the
    # caller capped the group below that, honoring the declared
    # bn_group semantics outranks the kernel — fall back to the jnp
    # formulation, which computes the capped per-group statistics.
    if group and group < n:
        return None
    cb = c
    while cb > 0 and not fits(fw, cb, n):
        cb -= sub
        while cb > 0 and c % cb:
            cb -= 1
    if cb <= 0:
        return None
    return 1, (cb, n), fits(bw, cb, n)


def _to_view(x, ch_axis):
    n, c, h, w = x.shape
    if ch_axis == 2:   # (L, N, C): bitcast of layout {1,0,3,2}
        return jnp.transpose(x, (2, 3, 0, 1)).reshape(h * w, n, c)
    # (L, C, N): bitcast of layout {0,1,3,2}
    return jnp.transpose(x, (2, 3, 1, 0)).reshape(h * w, c, n)


def _from_view(x_v, shape, ch_axis):
    n, c, h, w = shape
    if ch_axis == 2:
        return jnp.transpose(x_v.reshape(h, w, n, c), (2, 3, 0, 1))
    return jnp.transpose(x_v.reshape(h, w, c, n), (3, 2, 0, 1))


# ---------------------------------------------------------------------------
# custom-vjp public entry
# ---------------------------------------------------------------------------


def _gbn_fwd(x, gamma, beta, residual, eps, act, group, donate_res=False):
    n, c, h, w = x.shape
    ch_axis, ab, _ = _plan(n, c, h * w, x.dtype.itemsize, group,
                           residual is not None, donate_res)
    x_v = _to_view(x, ch_axis)
    r_v = None if residual is None else _to_view(residual, ch_axis)
    y_v, m, v = _call_fwd(x_v, gamma, beta, r_v, eps, act, ab, ch_axis,
                          donate_res=donate_res)
    y = _from_view(y_v, x.shape, ch_axis)
    res = (x_v, y_v if residual is not None else None, gamma, beta, m, v,
           x.shape)
    return ((y, m, v), res)


def _gbn_bwd_jnp(gy, x, y, gamma, beta, m, v, eps, act, ng):
    """Ghost-BN backward in plain jnp over the SAME ghost groups as the
    kernels — the hybrid path for layers whose bwd windows don't fit
    VMEM but whose fwd does (the fwd still saves its stats read)."""
    n, c, h, w = x.shape
    g = n // ng
    f32 = jnp.float32
    x5 = x.astype(f32).reshape(g, ng, c, h, w)
    gy5 = gy.astype(f32).reshape(g, ng, c, h, w)
    mb = m.reshape(g, 1, c, 1, 1)
    rstd = jax.lax.rsqrt(v + eps).reshape(g, 1, c, 1, 1)
    gam = gamma.astype(f32).reshape(1, 1, c, 1, 1)
    xhat = (x5 - mb) * rstd
    if act == "relu":
        if y is not None:
            keep = y.astype(f32).reshape(g, ng, c, h, w) > 0
        else:
            keep = (xhat * gam
                    + beta.astype(f32).reshape(1, 1, c, 1, 1)) > 0
        gp = jnp.where(keep, gy5, 0.0)
    else:
        gp = gy5
    cnt = ng * h * w
    db = gp.sum(axis=(1, 3, 4))
    dg = (gp * xhat).sum(axis=(1, 3, 4))
    dx = (gam * rstd
          * (gp - (db.reshape(g, 1, c, 1, 1)
                   + xhat * dg.reshape(g, 1, c, 1, 1)) / cnt))
    dr = gp.reshape(n, c, h, w).astype(x.dtype) if y is not None else None
    return (dx.reshape(n, c, h, w).astype(x.dtype), dg.sum(0), db.sum(0),
            dr)


def _gbn_bwd(eps, act, group, donate_res, res, ct):
    x_v, y_v, gamma, beta, m, v, shape = res
    gy, _, _ = ct  # cotangents for the stat outputs are not propagated
    n, c, h, w = shape
    ch_axis, ab, bwd_pallas = _plan(n, c, h * w, x_v.dtype.itemsize, group,
                                    y_v is not None, donate_res)
    if bwd_pallas:
        gy_v = _to_view(gy, ch_axis)
        dx, dg, db, dr = _call_bwd(gy_v, x_v, y_v, gamma, beta, m, v, eps,
                                   act, ab, ch_axis)
        dx = _from_view(dx, shape, ch_axis)
        dr = None if dr is None else _from_view(dr, shape, ch_axis)
    else:
        x = _from_view(x_v, shape, ch_axis)
        y = None if y_v is None else _from_view(y_v, shape, ch_axis)
        ng = ab[0] if ch_axis == 2 else ab[1]
        dx, dg, db, dr = _gbn_bwd_jnp(gy, x, y, gamma, beta, m, v, eps,
                                      act, ng)
    return (dx, dg.astype(gamma.dtype), db.astype(beta.dtype), dr)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
def _gbn_full(x, gamma, beta, residual, eps, act, group, donate_res):
    """Returns (y, group_mean, group_var) — stat outputs get zero vjp."""
    return _gbn_fwd(x, gamma, beta, residual, eps, act, group, donate_res)[0]


_gbn_full.defvjp(_gbn_fwd, _gbn_bwd)


def ghost_bn_stats_merge(m, v):
    """(G, C) group stats -> (C,) whole-batch population stats via the law
    of total variance (for running-average updates)."""
    bm = jnp.mean(m, axis=0)
    bv = jnp.mean(v + m * m, axis=0) - bm * bm
    return bm, jnp.maximum(bv, 0.0)


def _gbn_ref(x, gamma, beta, residual, eps, act, group):
    """Pure-jnp ghost BN (same semantics, standard XLA passes) — the
    fallback for layers whose slab cannot fit the VMEM window budget
    (e.g. the 112x112 stem at batch 256)."""
    n, c, h, w = x.shape
    ng = min(n, group or 32)
    while n % ng:
        ng -= 1
    g = n // ng
    x32 = x.astype(jnp.float32).reshape(g, ng, c, h, w)
    m = jnp.mean(x32, axis=(1, 3, 4))
    v = jnp.maximum(jnp.mean(x32 * x32, axis=(1, 3, 4)) - m * m, 0.0)
    rstd = jax.lax.rsqrt(v + eps)
    g32 = gamma.astype(jnp.float32)
    scale = (g32[None] * rstd)[:, None, :, None, None]
    shift = (beta.astype(jnp.float32)[None]
             - m * g32[None] * rstd)[:, None, :, None, None]
    y = (x32 * scale + shift).reshape(n, c, h, w)
    if residual is not None:
        y = y + residual.astype(jnp.float32)
    if act == "relu":
        y = jnp.maximum(y, 0.0)
    return y.astype(x.dtype), m, v


def ghost_bn_act(x, gamma, beta, residual=None, eps=1e-3, act="relu",
                 group=0, donate_residual=False):
    """Fused ghost-BN(+residual)+activation.

    x: (N, C, H, W).  Returns ``(y, group_mean, group_var)`` with stats of
    shape (G, C).  The ``group`` argument is a CAP on the ghost group:
    the sublane path picks the largest fitting divisor under it, the
    small-C lane path (whose group is the whole lane block) and the jnp
    fallback honor it exactly — deterministic per shape.  ``act`` is
    ``"relu"`` or ``"none"`` (the downsample-BN case).
    ``donate_residual=True`` declares the residual tensor dead after
    this layer (the downsample-shortcut case — NEVER an identity
    shortcut, which the surrounding program still reads): the fwd
    kernel then writes Y over the residual's window, saving one VMEM
    window and letting larger exits fuse.  Differentiable in x, gamma,
    beta and residual (stat outputs carry zero gradient — they feed
    running-stat updates, which the reference likewise excludes from
    autograd, ``src/operator/nn/batch_norm.cc`` aux states).  Layers
    whose windows can't fit the VMEM budget use an equivalent jnp
    formulation with the same ghost-group statistics.
    """
    n, c, h, w = x.shape
    donate = bool(donate_residual) and residual is not None
    if _plan(n, c, h * w, x.dtype.itemsize, int(group),
             residual is not None, donate) is None:
        return _gbn_ref(x, gamma, beta, residual, float(eps), act,
                        int(group))
    return _gbn_full(x, gamma, beta, residual, float(eps), act, int(group),
                     donate)
