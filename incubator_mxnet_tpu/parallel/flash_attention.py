"""Pallas TPU flash attention (fwd + bwd kernels, custom VJP).

Replaces the reference's fused attention matmuls
(``src/operator/contrib/transformer.cc`` interleaved_matmul_selfatt_*)
with a blockwise-softmax kernel that never materializes the (S, S)
score matrix: Q tiles stay resident in VMEM while K/V tiles stream
through, with running max/sum rescaling (the numerics of
``parallel.ring_attention._block_attn_update``, pushed down into one
kernel so the MXU sees back-to-back (block_q × D) @ (D × block_k)
matmuls and HBM traffic is O(S·D) instead of O(S²)).

On non-TPU backends the kernels run in interpreter mode so the same code
path is testable on CPU (tests/conftest.py virtual mesh).
"""
from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["flash_attention"]

_NEG_INF = -1e30

# index-map constant pinned to i32: the package enables jax_enable_x64, and
# a python 0 in a BlockSpec index map lowers as i64, which Mosaic rejects
# (failed to legalize func.return (i32, i32, i64))
import numpy as _np
_I0 = _np.int32(0)


def _use_interpret():
    try:
        return jax.default_backend() != "tpu"
    except Exception:
        return True


def _cdiv(a, b):
    return (a + b - 1) // b


def _fit_block(size, block):
    """Largest divisor of ``size`` that is ≤ ``block`` — blocks must tile
    the sequence exactly (no out-of-bounds block reads)."""
    block = min(block, size)
    while size % block:
        block -= 1
    return block


# ---------------------------------------------------------------------------
# forward kernel
# ---------------------------------------------------------------------------

def _causal_mask(s, qi, ki, block_q, block_k, offset):
    """Right-aligned causal mask: query row i attends keys j with
    j <= i + offset, offset = kv_len - q_len (KV-cache decode
    convention, matching attention_reference's tril(klen - qlen))."""
    rows = qi * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)
    cols = ki * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)
    # explicit f32 fill: a python float would enter the kernel as f64 and
    # Mosaic cannot legalize the f64->f32 truncf
    return jnp.where(rows + offset >= cols, s, jnp.float32(_NEG_INF))


def _block_relevant(qi, ki, block_q, block_k, offset):
    """False iff the (qi, ki) tile lies entirely above the causal
    diagonal (its mask would zero everything) — skip ~half the grid."""
    last_row = qi * block_q + block_q - 1
    first_col = ki * block_k
    return first_col <= last_row + offset


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr,
                *, scale, causal, block_q, block_k, nk, offset):
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    relevant = _block_relevant(qi, ki, block_q, block_k, offset) \
        if causal else True

    @pl.when(relevant)
    def _compute():
        q = q_ref[0].astype(jnp.float32)               # (bq, D)
        k = k_ref[0].astype(jnp.float32)               # (bk, D)
        v = v_ref[0].astype(jnp.float32)               # (bk, D)
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale
        if causal:
            s = _causal_mask(s, qi, ki, block_q, block_k, offset)

        m_prev = m_scr[:]                              # (bq, 1)
        l_prev = l_scr[:]
        m_new = jnp.maximum(m_prev, s.max(axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        # rows with zero unmasked keys (causal, kv_len < q_len): every score
        # is _NEG_INF, so exp(s - m_new) would be 1 everywhere and emit
        # mean(V); force those rows to contribute nothing (output 0)
        p = jnp.where(m_new > jnp.float32(_NEG_INF / 2), p, jnp.float32(0.0))
        alpha = jnp.exp(m_prev - m_new)
        l_new = alpha * l_prev + p.sum(axis=-1, keepdims=True)
        acc_scr[:] = acc_scr[:] * alpha + jnp.dot(
            p, v, preferred_element_type=jnp.float32)
        m_scr[:] = m_new
        l_scr[:] = l_new

    @pl.when(ki == nk - 1)
    def _finish():
        l = l_scr[:]
        o_ref[0] = (acc_scr[:] / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)
        # lse carried as (bq, 1): a trailing unit lane keeps the block shape
        # Mosaic-legal (last dim equals the array dim; (1, bq) blocks are not)
        lse_ref[0] = m_scr[:] + jnp.log(jnp.maximum(l, 1e-30))


def _fwd(q, k, v, scale, causal, block_q, block_k, interpret):
    bh, s, d = q.shape
    sk = k.shape[1]
    block_q = _fit_block(s, block_q)
    block_k = _fit_block(sk, block_k)
    nq = s // block_q
    nk = sk // block_k
    kernel = functools.partial(_fwd_kernel, scale=scale, causal=causal,
                               block_q=block_q, block_k=block_k, nk=nk,
                               offset=sk - s)
    out, lse = pl.pallas_call(
        kernel,
        grid=(bh, nq, nk),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, _I0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, _I0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, _I0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, _I0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, _I0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, s, d), q.dtype),
            jax.ShapeDtypeStruct((bh, s, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v)
    return out, lse[:, :, 0]


# ---------------------------------------------------------------------------
# backward kernels
# ---------------------------------------------------------------------------

def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
                   dq_scr, *, scale, causal, block_q, block_k, nk, offset):
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    relevant = _block_relevant(qi, ki, block_q, block_k, offset) \
        if causal else True

    @pl.when(relevant)
    def _compute():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        lse = lse_ref[0]                                # (bq, 1)
        delta = delta_ref[0]                            # (bq, 1)

        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale
        if causal:
            s = _causal_mask(s, qi, ki, block_q, block_k, offset)
        p = jnp.exp(s - lse)
        # rows with zero unmasked keys have lse ~= _NEG_INF, which would
        # blow exp() up instead of zeroing it; mask on the raw scores
        p = jnp.where(s > jnp.float32(_NEG_INF / 2), p, jnp.float32(0.0))
        dp = jnp.dot(do, v.T, preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        dq_scr[:] = dq_scr[:] + jnp.dot(ds, k,
                                        preferred_element_type=jnp.float32)

    @pl.when(ki == nk - 1)
    def _finish():
        dq_ref[0] = dq_scr[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, dk_scr, dv_scr,
                    *, scale, causal, block_q, block_k, nq, offset):
    kj = pl.program_id(1)
    qi = pl.program_id(2)

    @pl.when(qi == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    relevant = _block_relevant(qi, kj, block_q, block_k, offset) \
        if causal else True

    @pl.when(relevant)
    def _compute():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        lse = lse_ref[0]                                # (bq, 1)
        delta = delta_ref[0]                            # (bq, 1)

        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale
        if causal:
            s = _causal_mask(s, qi, kj, block_q, block_k, offset)
        p = jnp.exp(s - lse)                            # (bq, bk)
        p = jnp.where(s > jnp.float32(_NEG_INF / 2), p, jnp.float32(0.0))
        dv_scr[:] = dv_scr[:] + jnp.dot(p.T, do,
                                        preferred_element_type=jnp.float32)
        dp = jnp.dot(do, v.T, preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        dk_scr[:] = dk_scr[:] + jnp.dot(ds.T, q,
                                        preferred_element_type=jnp.float32)

    @pl.when(qi == nq - 1)
    def _finish():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


def _bwd(scale, causal, block_q, block_k, interpret, res, g):
    q, k, v, out, lse = res
    do = g
    bh, s, d = q.shape
    sk = k.shape[1]
    bq = _fit_block(s, block_q)
    bk = _fit_block(sk, block_k)
    nq = s // bq
    nk = sk // bk
    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1, keepdims=True)             # (bh, s, 1)
    lse3 = lse[:, :, None]                              # (bh, s, 1)

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, causal=causal,
                          block_q=bq, block_k=bk, nk=nk, offset=sk - s),
        grid=(bh, nq, nk),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, _I0)),
            pl.BlockSpec((1, bk, d), lambda b, i, j: (b, j, _I0)),
            pl.BlockSpec((1, bk, d), lambda b, i, j: (b, j, _I0)),
            pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, _I0)),
            pl.BlockSpec((1, bq, 1), lambda b, i, j: (b, i, _I0)),
            pl.BlockSpec((1, bq, 1), lambda b, i, j: (b, i, _I0)),
        ],
        out_specs=pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, _I0)),
        out_shape=jax.ShapeDtypeStruct((bh, s, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32)],
        interpret=interpret,
    )(q, k, v, do, lse3, delta)

    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, causal=causal,
                          block_q=bq, block_k=bk, nq=nq, offset=sk - s),
        grid=(bh, nk, nq),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda b, j, i: (b, i, _I0)),
            pl.BlockSpec((1, bk, d), lambda b, j, i: (b, j, _I0)),
            pl.BlockSpec((1, bk, d), lambda b, j, i: (b, j, _I0)),
            pl.BlockSpec((1, bq, d), lambda b, j, i: (b, i, _I0)),
            pl.BlockSpec((1, bq, 1), lambda b, j, i: (b, i, _I0)),
            pl.BlockSpec((1, bq, 1), lambda b, j, i: (b, i, _I0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bk, d), lambda b, j, i: (b, j, _I0)),
            pl.BlockSpec((1, bk, d), lambda b, j, i: (b, j, _I0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, sk, d), k.dtype),
            jax.ShapeDtypeStruct((bh, sk, d), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((bk, d), jnp.float32),
            pltpu.VMEM((bk, d), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v, do, lse3, delta)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# public entry
# ---------------------------------------------------------------------------

@functools.lru_cache(maxsize=128)
def _make_attn(scale, causal, block_q, block_k, interpret):
    """One custom_vjp function per static-param tuple — cached so eager
    callers hit JAX's trace cache instead of re-tracing the kernels every
    invocation."""
    @jax.custom_vjp
    def _attn(qf, kf, vf):
        out, _ = _fwd(qf, kf, vf, scale, causal, block_q, block_k,
                      interpret)
        return out

    def _attn_fwd(qf, kf, vf):
        out, lse = _fwd(qf, kf, vf, scale, causal, block_q, block_k,
                        interpret)
        return out, (qf, kf, vf, out, lse)

    def _attn_bwd(res, g):
        return _bwd(scale, causal, block_q, block_k, interpret, res, g)

    _attn.defvjp(_attn_fwd, _attn_bwd)
    return _attn


def flash_attention(q, k, v, causal=False, scale: Optional[float] = None,
                    block_q=None, block_k=None, interpret=None,
                    use_pallas=None):
    """Flash attention over (B, H, S, D) tensors.

    Returns softmax(QKᵀ·scale [+ causal mask]) V without materializing
    the score matrix.  Differentiable.

    Backend policy (round-4 measurement, docs/PERF.md): on TPU the stock
    XLA fused attention (`jax.nn.dot_product_attention`) beat this
    module's Pallas kernels (5.8 vs 6.3 ms at 2048/8/128), so the XLA
    path is the DEFAULT; the Pallas kernels remain behind
    ``use_pallas=True`` (and keep serving ring attention's per-shard
    block compute, where the blockwise-update formulation is required).
    Interpret-mode (non-TPU backends) keeps Pallas so the kernels stay
    CPU-tested.
    """
    b, h, s, d = q.shape
    sk = k.shape[2]
    if scale is None:
        scale = 1.0 / math.sqrt(d)
    if interpret is None:
        interpret = _use_interpret()
    if use_pallas is None:
        use_pallas = interpret  # real-chip default: XLA fused attention
    if not use_pallas:
        # jax.nn.dot_product_attention is (B, S, H, D)
        out = jax.nn.dot_product_attention(
            q.transpose(0, 2, 1, 3), k.transpose(0, 2, 1, 3),
            v.transpose(0, 2, 1, 3), scale=float(scale),
            is_causal=bool(causal))
        return out.transpose(0, 2, 1, 3)

    if block_q is None or block_k is None:
        # defaults: 128x128; at long sequence bigger tiles amortize grid
        # overhead and keep the MXU on larger products.  Explicit
        # block_q/block_k always win (bench.py sweeps them).  _fit_block
        # still clamps to divisors of the actual lengths.
        bq_d, bk_d = ((256, 512) if sk >= 4096 else (128, 128))
        block_q = bq_d if block_q is None else block_q
        block_k = bk_d if block_k is None else block_k
    qf = q.reshape(b * h, s, d)
    kf = k.reshape(b * h, sk, d)
    vf = v.reshape(b * h, sk, d)
    _attn = _make_attn(float(scale), bool(causal), int(block_q),
                       int(block_k), bool(interpret))
    return _attn(qf, kf, vf).reshape(b, h, s, d)


# op-registry surface: mx.nd.contrib.flash_attention / mx.sym.contrib...
from ..ops.registry import register as _register_op  # noqa: E402


@_register_op("_contrib_flash_attention", num_inputs=3)
def _flash_attention_op(q, k, v, causal=False, scale=None, block_q=None,
                        block_k=None):
    """Fused attention op (the TPU answer to
    _contrib_interleaved_matmul_selfatt_* in transformer.cc)."""
    return flash_attention(
        q, k, v, causal=bool(causal), scale=scale,
        block_q=None if block_q is None else int(block_q),
        block_k=None if block_k is None else int(block_k))
