"""RecordIO: dmlc-compatible record file format + indexed variant.

Parity surface: ``python/mxnet/recordio.py`` (MXRecordIO, MXIndexedRecordIO,
IRHeader, pack/unpack/pack_img/unpack_img) over dmlc-core's recordio
(``3rdparty/dmlc-core`` — format used by ``src/io/iter_image_recordio_2.cc``).

The on-disk format is byte-compatible with dmlc recordio so `.rec` files made
by the reference's ``tools/im2rec.py`` can be read here and vice versa:

  [kMagic:4][lrec:4][data:len][pad to 4B]   per record
  lrec = (cflag << 29) | length;  cflag 0=whole 1=begin 2=middle 3=end
  records whose payload contains kMagic are split at those points.

TPU-native note: this pure-python implementation is the portable path; the
native C++ reader (``src/native`` in this repo) provides the threaded
high-throughput pipeline for training input.
"""
from __future__ import annotations

import os
import struct
import warnings
from collections import namedtuple

import numpy as np

__all__ = ["MXRecordIO", "MXIndexedRecordIO", "IRHeader",
           "pack", "unpack", "unpack_img", "pack_img"]

_kMagic = 0xCED7230A
_MAGIC_BYTES = struct.pack("<I", _kMagic)


def _corrupt_record_error(uri, offset, why):
    """A clear, locatable IOError for an unreadable record.  ``path``
    and ``offset`` ride the exception as attributes so the resilient
    reader (``io/resilient.py``) can quarantine the record by file
    offset instead of parsing the message."""
    err = IOError("%s at offset %d in %s" % (why, offset, uri))
    err.path = uri
    err.offset = int(offset)
    return err


def _torn_final_record(uri, offset, why):
    """A file cut mid-write by a crash is readable up to the tear
    (same policy as the atomic-save torn-file handling in
    ``ndarray/utils.py``): warn once and report end-of-file instead of
    raising on the final, partially-written record."""
    warnings.warn(
        "torn final record in %s at offset %d (%s) — file truncated "
        "mid-write? Records up to the tear were read; stopping here."
        % (uri, offset, why), stacklevel=3)


class MXRecordIO:
    """Sequential record reader/writer (recordio.py:36)."""

    def __init__(self, uri, flag):
        self.uri = uri
        self.flag = flag
        self.fh = None
        self.is_open = False
        self.writable = None
        self.open()

    def open(self):
        if self.flag == "w":
            self.writable = True
        elif self.flag == "r":
            self.writable = False
        else:
            raise ValueError("Invalid flag %s" % self.flag)
        # native fast path (src/native/recordio.cc) — byte-identical format
        self._nh = None
        self._nlib = None
        from ._native import get_lib
        lib = get_lib()
        if lib is not None:
            h = (lib.MXTRecordIOWriterCreate(self.uri.encode())
                 if self.writable
                 else lib.MXTRecordIOReaderCreate(self.uri.encode()))
            if h:
                self._nh = h
                self._nlib = lib
                self.fh = None
                self.is_open = True
                return
        self.fh = open(self.uri, "wb" if self.writable else "rb")
        self.is_open = True

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    def __getstate__(self):
        """Override pickling behavior (so DataLoader workers can reopen)."""
        is_open = self.is_open
        self.close()
        d = dict(self.__dict__)
        d["is_open"] = is_open
        d["fh"] = None
        d["_nh"] = None
        d["_nlib"] = None
        return d

    def __setstate__(self, d):
        self.__dict__.update(d)
        is_open = d.get("is_open", False)
        self.is_open = False
        self.fh = None
        if is_open:
            self.open()

    def close(self):
        if not self.is_open:
            return
        if getattr(self, "_nh", None):
            if self.writable:
                self._nlib.MXTRecordIOWriterFree(self._nh)
            else:
                self._nlib.MXTRecordIOReaderFree(self._nh)
            self._nh = None
            self._nlib = None
            self.is_open = False
        if self.fh is not None:
            self.fh.close()
            self.fh = None
            self.is_open = False

    def reset(self):
        self.close()
        self.open()

    def tell(self):
        if getattr(self, "_nh", None):
            if self.writable:
                return self._nlib.MXTRecordIOWriterTell(self._nh)
            return self._nlib.MXTRecordIOReaderTell(self._nh)
        return self.fh.tell()

    def seek(self, pos):
        """Reader byte-seek (MXRecordIOReaderSeek contract)."""
        assert not self.writable
        if getattr(self, "_nh", None):
            self._nlib.MXTRecordIOReaderSeek(self._nh, int(pos))
        else:
            self.fh.seek(int(pos))

    def write(self, buf):
        assert self.writable
        if not isinstance(buf, (bytes, bytearray)):
            buf = bytes(buf)
        if getattr(self, "_nh", None):
            rc = self._nlib.MXTRecordIOWriterWrite(self._nh, bytes(buf),
                                                   len(buf))
            if rc != 0:
                raise IOError("native recordio write failed (%d)" % rc)
            return
        # split payload at embedded magics, dmlc style
        parts = []
        start = 0
        n = len(buf)
        i = buf.find(_MAGIC_BYTES)
        while i != -1:
            parts.append(buf[start:i])
            start = i + 4
            i = buf.find(_MAGIC_BYTES, start)
        parts.append(buf[start:n])
        for k, part in enumerate(parts):
            if len(parts) == 1:
                cflag = 0
            elif k == 0:
                cflag = 1
            elif k == len(parts) - 1:
                cflag = 3
            else:
                cflag = 2
            lrec = (cflag << 29) | len(part)
            self.fh.write(_MAGIC_BYTES)
            self.fh.write(struct.pack("<I", lrec))
            self.fh.write(part)
            pad = (4 - (len(part) & 3)) & 3
            if pad:
                self.fh.write(b"\x00" * pad)

    def read(self):
        assert not self.writable
        if getattr(self, "_nh", None):
            import ctypes
            offset = self.tell()
            out = ctypes.c_char_p()
            out_len = ctypes.c_size_t()
            rc = self._nlib.MXTRecordIOReaderRead(
                self._nh, ctypes.byref(out), ctypes.byref(out_len))
            if rc == 0:
                return None
            if rc < 0:
                # classify through the python framing reader so the
                # native fast path keeps the same contract: a crash-torn
                # FINAL record warns and reads as end-of-file, real
                # corruption raises an IOError naming file + offset
                with open(self.uri, "rb") as fh:
                    fh.seek(offset)
                    try:
                        return self._read_python(fh)
                    finally:
                        # keep the native cursor in step (incl. the
                        # corrupt-record resync) so the NEXT read starts
                        # at the next frame boundary, not back inside
                        # the bad record.  Byte-seek explicitly —
                        # MXIndexedRecordIO.seek overrides with
                        # key-based semantics.
                        MXRecordIO.seek(self, fh.tell())
            return ctypes.string_at(out, out_len.value)
        return self._read_python(self.fh)

    def _resync(self, fh, bad_offset):
        """Scan forward from a corrupt frame for the next plausible
        frame boundary — a 4-byte-aligned magic word (every frame is
        padded to 4 bytes) — and leave ``fh`` there (EOF when none).
        A false positive (payload bytes that happen to spell the magic)
        just fails the next header check and resyncs again: progress is
        monotonic either way."""
        pos = (int(bad_offset) + 4 + 3) & ~3
        while True:
            fh.seek(pos)
            buf = fh.read(1 << 16)
            if not buf:
                fh.seek(0, 2)
                return
            i = 0
            while True:
                i = buf.find(_MAGIC_BYTES, i)
                if i == -1:
                    break
                if (pos + i) % 4 == 0:
                    fh.seek(pos + i)
                    return
                i += 1
            # keep a 3-byte overlap: an aligned magic can straddle the
            # chunk boundary only when the chunk ends off-alignment (EOF)
            pos += max(len(buf) - 3, 1)

    def _read_python(self, fh):
        """Python framing reader: validates magic/length per frame,
        tolerates a torn final record (warn + stop — a file cut
        mid-write by a crash is readable up to the tear) and raises a
        locatable ``IOError`` (``.path``/``.offset``) on corruption."""
        out = bytearray()
        expect_more = False
        while True:
            offset = fh.tell()
            head = fh.read(8)
            if len(head) == 0 and not expect_more:
                return None  # clean end of file
            if len(head) < 8:
                # EOF inside a record frame: the crash-torn-final-record
                # case — everything before this frame was intact
                _torn_final_record(
                    self.uri, offset,
                    "partial continuation frame" if expect_more
                    else "only %d of 8 header bytes" % len(head))
                return None
            magic, lrec = struct.unpack("<II", head)
            if magic != _kMagic:
                # resync BEFORE raising: leave the handle at the next
                # plausible frame boundary so one corrupt record costs
                # the caller one error (one skip-budget unit), not one
                # per 4 bytes of its payload
                self._resync(fh, offset)
                raise _corrupt_record_error(
                    self.uri, offset,
                    "invalid record magic 0x%08X (expected 0x%08X)"
                    % (magic, _kMagic))
            cflag = lrec >> 29
            length = lrec & ((1 << 29) - 1)
            if cflag in (2, 3) and not expect_more:
                # continuation frame with no begin: the begin frame was
                # the corrupt one we resynced past.  The framing here is
                # intact — skip the frame so the next read starts at the
                # following boundary, and report this piece as corrupt.
                fh.seek(length + ((4 - (length & 3)) & 3), 1)
                raise _corrupt_record_error(
                    self.uri, offset,
                    "continuation frame (cflag %d) without a begin frame"
                    % cflag)
            data = fh.read(length)
            if len(data) < length:
                # Short payload: either a crash-torn FINAL record
                # (header intact, payload cut at EOF) or a corrupt
                # length field MID-file whose inflated value over-read
                # into later, intact records.  Resync decides: a next
                # aligned magic inside the over-read bytes means intact
                # frames follow — cost the caller ONE error (like the
                # bad-magic path) instead of silently dropping the file
                # tail; a genuinely torn final record finds none and
                # still reads as warn + end-of-file.
                self._resync(fh, offset + 4)
                next_frame = fh.tell()
                fh.seek(0, 2)
                if next_frame < fh.tell():
                    fh.seek(next_frame)
                    raise _corrupt_record_error(
                        self.uri, offset,
                        "record length %d over-reads into a later frame "
                        "(only %d payload bytes before the next frame "
                        "boundary) — corrupt length field?"
                        % (length, len(data)))
                _torn_final_record(
                    self.uri, offset,
                    "header promises %d payload bytes, only %d on disk"
                    % (length, len(data)))
                return None
            pad = (4 - (length & 3)) & 3
            if pad:
                fh.read(pad)
            if cflag == 0:
                return bytes(data)
            if cflag == 1:
                out = bytearray(data)
                expect_more = True
            elif cflag == 2:
                out += _MAGIC_BYTES
                out += data
            elif cflag == 3:
                out += _MAGIC_BYTES
                out += data
                return bytes(out)


class MXIndexedRecordIO(MXRecordIO):
    """Random-access record file via .idx sidecar (recordio.py:160)."""

    def __init__(self, idx_path, uri, flag, key_type=int):
        self.idx_path = idx_path
        self.idx = {}
        self.keys = []
        self.key_type = key_type
        self.fidx = None
        super().__init__(uri, flag)

    def open(self):
        super().open()
        self.idx = {}
        self.keys = []
        if not self.writable and os.path.isfile(self.idx_path):
            with open(self.idx_path) as fin:
                for line in fin:
                    line = line.strip().split("\t")
                    key = self.key_type(line[0])
                    self.idx[key] = int(line[1])
                    self.keys.append(key)
        if self.writable:
            self.fidx = open(self.idx_path, "w")

    def close(self):
        if self.fidx is not None:
            self.fidx.close()
            self.fidx = None
        super().close()

    def __getstate__(self):
        d = super().__getstate__()
        d["fidx"] = None
        return d

    def seek(self, idx):
        assert not self.writable
        pos = self.idx[idx]
        if getattr(self, "_nh", None):
            self._nlib.MXTRecordIOReaderSeek(self._nh, pos)
        else:
            self.fh.seek(pos)

    def read_idx(self, idx):
        self.seek(idx)
        return self.read()

    def write_idx(self, idx, buf):
        assert self.writable
        key = self.key_type(idx)
        pos = self.tell()
        self.write(buf)
        self.fidx.write("%s\t%d\n" % (str(key), pos))
        self.idx[key] = pos
        self.keys.append(key)


# IRHeader: flag, label, id, id2 — struct 'IfQQ' (recordio.py:259)
IRHeader = namedtuple("HEADER", ["flag", "label", "id", "id2"])
_IR_FORMAT = "IfQQ"
_IR_SIZE = struct.calcsize(_IR_FORMAT)


def pack(header, s):
    """Pack IRHeader + raw bytes into one record payload (recordio.py:276)."""
    import numbers

    header = IRHeader(*header)
    if isinstance(header.label, numbers.Number):
        ret = struct.pack(_IR_FORMAT, header.flag, float(header.label),
                          header.id, header.id2)
    else:
        label = np.asarray(header.label, dtype=np.float32)
        header = header._replace(flag=label.size, label=0)
        ret = struct.pack(_IR_FORMAT, header.flag, header.label,
                          header.id, header.id2)
        ret += label.tobytes()
    return ret + s


def unpack(s):
    """Unpack a record payload into (IRHeader, bytes) (recordio.py:306)."""
    header = IRHeader(*struct.unpack(_IR_FORMAT, s[:_IR_SIZE]))
    s = s[_IR_SIZE:]
    if header.flag > 0:
        label = np.frombuffer(s[: header.flag * 4], dtype=np.float32)
        header = header._replace(label=label)
        s = s[header.flag * 4:]
    return header, s


def unpack_img(s, iscolor=1):
    """Unpack record → (header, image ndarray HWC uint8) (recordio.py:329)."""
    header, s = unpack(s)
    img = _imdecode(s, iscolor)
    return header, img


def pack_img(header, img, quality=95, img_fmt=".jpg"):
    """Pack header + encoded image (recordio.py:355)."""
    buf = _imencode(img, quality=quality, img_fmt=img_fmt)
    return pack(header, buf)


def _imdecode(buf, iscolor=1):
    """Decode an image from bytes without OpenCV.

    Supports raw .npy payloads always; JPEG/PNG when PIL or cv2 is present.
    """
    import io as _io

    if isinstance(buf, (bytes, bytearray)) and bytes(buf[:6]) == b"\x93NUMPY":
        return np.load(_io.BytesIO(bytes(buf)))
    try:
        import cv2  # noqa
        arr = np.frombuffer(buf, dtype=np.uint8)
        flag = 1 if iscolor else 0
        img = cv2.imdecode(arr, flag)
        return img[..., ::-1] if iscolor else img  # BGR→RGB
    except ImportError:
        pass
    try:
        from PIL import Image
        img = Image.open(_io.BytesIO(bytes(buf)))
        if iscolor:
            img = img.convert("RGB")
        else:
            img = img.convert("L")
        return np.asarray(img)
    except ImportError as e:
        raise ImportError(
            "decoding compressed images requires cv2 or PIL; "
            "raw .npy payloads are always supported") from e


def _imencode(img, quality=95, img_fmt=".jpg"):
    import io as _io

    img = np.asarray(img)
    if img_fmt == ".npy":
        bio = _io.BytesIO()
        np.save(bio, img)
        return bio.getvalue()
    try:
        from PIL import Image
        bio = _io.BytesIO()
        fmt = {"jpg": "JPEG", "jpeg": "JPEG", "png": "PNG"}[
            img_fmt.lstrip(".").lower()]
        Image.fromarray(img).save(bio, format=fmt, quality=quality)
        return bio.getvalue()
    except ImportError:
        # fall back to raw npy payload (decodable by _imdecode)
        bio = _io.BytesIO()
        np.save(bio, img)
        return bio.getvalue()
