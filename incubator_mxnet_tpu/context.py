"""Device contexts.

Parity surface: ``python/mxnet/context.py`` (reference), ``Context`` in
``include/mxnet/base.h:102-128``.  TPU-native twist: ``mx.tpu()`` is the
first-class accelerator; ``mx.gpu()`` is accepted as an alias for tpu so that
reference scripts run unmodified.  Device placement maps to ``jax.Device``.
"""
from __future__ import annotations

import threading
from typing import Optional

import jax

__all__ = ["Context", "cpu", "tpu", "gpu", "cpu_pinned", "current_context", "num_devices"]


class Context:
    """A device context (cpu / tpu). Usable as a ``with`` scope like the reference."""

    # device type enum kept name-compatible with include/mxnet/base.h:102
    devtype2str = {1: "cpu", 2: "tpu", 3: "cpu_pinned", 5: "cpu_shared"}
    devstr2type = {"cpu": 1, "tpu": 2, "gpu": 2, "cpu_pinned": 3, "cpu_shared": 5}
    _default_ctx = threading.local()

    def __init__(self, device_type: str, device_id: int = 0):
        if device_type not in self.devstr2type:
            raise ValueError("unknown device type %r" % (device_type,))
        if device_type == "gpu":
            device_type = "tpu"  # alias: accelerator == TPU in this framework
        self.device_type = device_type
        self.device_id = device_id
        self._old_ctx: Optional[Context] = None

    # -- jax integration ---------------------------------------------------
    @property
    def device_typeid(self) -> int:
        return self.devstr2type[self.device_type]

    def jax_device(self) -> Optional[jax.Device]:
        """Resolve to a concrete jax.Device (None => let JAX pick default).

        Uses *local* (process-addressable) devices: under multi-process
        distributed training ``jax.devices()`` includes peers' devices,
        which this process cannot place data on.
        """
        if self.device_type in ("cpu", "cpu_pinned", "cpu_shared"):
            devs = [d for d in jax.local_devices() if d.platform == "cpu"]
            if not devs:
                try:
                    devs = [d for d in jax.devices("cpu")
                            if d.process_index == jax.process_index()]
                except RuntimeError:
                    return None
                if not devs:
                    return None
        else:
            devs = [d for d in jax.local_devices() if d.platform != "cpu"]
            if not devs:  # CPU-only host: tpu context falls back to default device
                return None
        return devs[self.device_id % len(devs)]

    # -- scope protocol ----------------------------------------------------
    def __enter__(self):
        if not hasattr(Context._default_ctx, "value"):
            Context._default_ctx.value = Context("cpu", 0)
        self._old_ctx = Context._default_ctx.value
        Context._default_ctx.value = self
        return self

    def __exit__(self, *exc):
        Context._default_ctx.value = self._old_ctx

    def __eq__(self, other):
        return (
            isinstance(other, Context)
            and self.device_type == other.device_type
            and self.device_id == other.device_id
        )

    def __hash__(self):
        return hash((self.device_type, self.device_id))

    def __repr__(self):
        return "%s(%d)" % (self.device_type, self.device_id)

    def __str__(self):
        return self.__repr__()

    def empty_cache(self):  # parity: mx.Context.empty_cache
        jax.clear_caches()

    @classmethod
    def default_ctx(cls) -> "Context":
        if not hasattr(cls._default_ctx, "value"):
            cls._default_ctx.value = Context("cpu", 0)
        return cls._default_ctx.value


def cpu(device_id: int = 0) -> Context:
    return Context("cpu", device_id)


def cpu_pinned(device_id: int = 0) -> Context:
    return Context("cpu_pinned", device_id)


def tpu(device_id: int = 0) -> Context:
    return Context("tpu", device_id)


def gpu(device_id: int = 0) -> Context:
    """Reference-compat alias: accelerator contexts resolve to TPU devices."""
    return Context("tpu", device_id)


def current_context() -> Context:
    return Context.default_ctx()


def num_devices(device_type: str = "tpu") -> int:
    """Count of process-local devices (reference num_gpus counts local)."""
    if device_type in ("tpu", "gpu"):
        return len([d for d in jax.local_devices() if d.platform != "cpu"])
    return len([d for d in jax.local_devices() if d.platform == "cpu"]) or 1


def num_gpus() -> int:  # parity: mx.context.num_gpus
    return num_devices("tpu")
