"""Base types, dtype tables and errors.

TPU-native re-imagination of the reference's ``include/mxnet/base.h`` +
``python/mxnet/base.py``.  There is no C ABI here: the "runtime" is JAX/XLA,
so the base layer only needs dtype bookkeeping and error types.
"""
from __future__ import annotations

import numpy as _np

__all__ = [
    "MXNetError",
    "DType",
    "np_dtype",
    "dtype_name",
    "string_types",
    "_as_list",
]


def _as_list(obj):
    """Coerce to list (python/mxnet/base.py _as_list parity)."""
    if isinstance(obj, (list, tuple)):
        return list(obj)
    return [obj]


class MXNetError(RuntimeError):
    """Error raised by the framework (parity with mxnet.base.MXNetError)."""


string_types = (str,)

# Canonical dtype table. The reference enumerates dtypes in
# mshadow (3rdparty/mshadow/mshadow/base.h) as int flags; we key by name and
# numpy dtype instead — XLA handles layout/typing.
_DTYPE_ALIASES = {
    "float32": _np.float32,
    "float64": _np.float64,
    "float16": _np.float16,
    "bfloat16": "bfloat16",  # resolved lazily via ml_dtypes/jax
    "uint8": _np.uint8,
    "int8": _np.int8,
    "int32": _np.int32,
    "int64": _np.int64,
    "bool": _np.bool_,
}


def np_dtype(dtype):
    """Normalize a user-provided dtype (string/np.dtype/jnp dtype) to numpy dtype."""
    if dtype is None:
        return _np.dtype(_np.float32)
    if isinstance(dtype, str):
        if dtype == "bfloat16":
            import ml_dtypes

            return _np.dtype(ml_dtypes.bfloat16)
        dtype = _DTYPE_ALIASES.get(dtype, dtype)
    return _np.dtype(dtype)


def dtype_name(dtype) -> str:
    return _np.dtype(dtype).name


class DType:
    """Namespace of supported dtypes."""

    float16 = "float16"
    float32 = "float32"
    float64 = "float64"
    bfloat16 = "bfloat16"
    uint8 = "uint8"
    int8 = "int8"
    int32 = "int32"
    int64 = "int64"


def check_call(ret):  # pragma: no cover - API-compat shim
    """Parity shim for mxnet.base.check_call; no C ABI exists in this build."""
    return ret
