"""Runtime-compiled user kernels (``mx.rtc`` parity).

Reference: ``CudaModule`` (``python/mxnet/rtc.py:42`` + NVRTC compile in
``src/common/rtc.cc:49``) — user supplies CUDA C source at runtime, gets
launchable kernels.

TPU-native: the kernel language is **Pallas**.  ``PallasModule`` takes
Python source that defines Pallas kernel functions (``pl``, ``pltpu``,
``jax``, ``jnp`` are pre-imported into the compilation namespace, the
moral analog of nvrtc's builtin headers), compiles it at runtime, and
``get_kernel`` wraps a function for launching: grid/block specs map to the
reference's grid/block launch geometry, and the same code runs interpreted
on CPU backends (like the reference's debugging path) and Mosaic-compiled
on TPU.
"""
from __future__ import annotations

from typing import Any, Optional, Sequence

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .ndarray import NDArray

__all__ = ["PallasModule", "CudaModule"]


class _Kernel:
    """Launchable kernel (rtc.py Kernel.launch analog)."""

    def __init__(self, fn, name):
        self._fn = fn
        self.name = name

    def launch(self, args: Sequence[Any], out_shape, grid=None,
               in_specs=None, out_specs=None, scratch_shapes=(),
               interpret: Optional[bool] = None):
        """Run the kernel via ``pl.pallas_call``.

        args: NDArrays/jax arrays; out_shape: jax.ShapeDtypeStruct (or a
        (shape, dtype) tuple, or list thereof); grid/in_specs/out_specs:
        pallas launch geometry (the reference's grid_dims/block_dims).
        """
        if interpret is None:
            try:
                interpret = jax.default_backend() != "tpu"
            except Exception:
                interpret = True

        def norm_shape(s):
            if isinstance(s, jax.ShapeDtypeStruct):
                return s
            shape, dtype = s
            return jax.ShapeDtypeStruct(tuple(shape), jnp.dtype(dtype))

        multi = isinstance(out_shape, (list, tuple)) \
            and not (len(out_shape) == 2 and isinstance(out_shape[0],
                                                        (list, tuple))
                     and isinstance(out_shape[1], (str, type(jnp.float32))))
        shapes = [norm_shape(s) for s in out_shape] if multi \
            else norm_shape(out_shape)
        kwargs = {}
        if grid is not None:
            kwargs["grid"] = grid
        if in_specs is not None:
            kwargs["in_specs"] = in_specs
        if out_specs is not None:
            kwargs["out_specs"] = out_specs
        if scratch_shapes:
            kwargs["scratch_shapes"] = list(scratch_shapes)
        call = pl.pallas_call(self._fn, out_shape=shapes,
                              interpret=interpret, **kwargs)
        vals = [a._data if isinstance(a, NDArray) else jnp.asarray(a)
                for a in args]
        out = call(*vals)
        if isinstance(out, (list, tuple)):
            return [NDArray(o) for o in out]
        return NDArray(out)

    __call__ = launch


class PallasModule:
    """Compile Pallas source at runtime (CudaModule analog).

    Example::

        src = '''
        def scale_kernel(x_ref, o_ref, *, factor=2.0):
            o_ref[...] = x_ref[...] * factor
        '''
        mod = mx.rtc.PallasModule(src, exports=["scale_kernel"])
        k = mod.get_kernel("scale_kernel")
        y = k.launch([x], out_shape=(x.shape, x.dtype))
    """

    def __init__(self, source: str, options=(), exports=()):
        self.source = source
        self.exports = tuple(exports)
        ns = {"jax": jax, "jnp": jnp, "pl": pl, "pltpu": pltpu}
        exec(compile(source, "<rtc.PallasModule>", "exec"), ns)  # noqa: S102
        self._ns = ns
        for name in self.exports:
            if name not in ns:
                raise ValueError("export %r not defined in source" % name)

    def get_kernel(self, name: str, signature: str = "") -> _Kernel:
        """``signature`` accepted for reference API parity (types come from
        the launch arguments under JAX tracing, so it is unused)."""
        if name not in self._ns or not callable(self._ns[name]):
            raise ValueError("kernel %r not found" % name)
        return _Kernel(self._ns[name], name)


# The reference name: user code does mx.rtc.CudaModule(...); keep the name
# as an alias so ported scripts fail with a clear message only if they pass
# actual CUDA C (exec raises SyntaxError) rather than an AttributeError.
CudaModule = PallasModule
