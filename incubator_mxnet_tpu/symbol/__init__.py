"""``mx.sym`` — symbolic operator namespace.

Generated from the same op registry as ``mx.nd`` (the reference code-gens
both from MXSymbolGetAtomicSymbolInfo; see python/mxnet/symbol/register.py).
Composing creates graph nodes; missing parameter inputs auto-create variables
named ``{opname}_{arg}`` exactly like nnvm symbol composition.
"""
from __future__ import annotations

import inspect
import sys
from typing import Dict, List, Optional

from ..name import NameManager
from ..ops import registry as _reg
from .symbol import (AUX_SUFFIXES, PARAM_INPUT_NAMES, Group, Symbol, Variable,
                     _Node, _input_arg_names, _required_arg_names, load,
                     load_json, var)
from . import contrib  # noqa: F401

__all__ = ["Symbol", "var", "Variable", "Group", "load", "load_json", "zeros",
           "ones", "arange", "linalg"]

__is_symbol__ = True

# singleton node standing in for an absent optional input (e.g. bias with
# no_bias=True); excluded from list_arguments and bound to None at eval
_NULL_NODE = _Node(None, "__null__")


def _compose_num_outputs(opname, attrs):
    if opname == "Custom":
        from ..operator import custom_num_outputs
        a = {k: v for k, v in attrs.items() if k != "op_type"}
        return custom_num_outputs(attrs.get("op_type"), a)
    reg_op = _reg.OPS.get(opname)
    if reg_op is not None and (reg_op.num_outputs or 1) > 1:
        return reg_op.num_outputs
    if opname in ("SliceChannel", "split"):
        return int(attrs.get("num_outputs", 2))
    if opname in ("split_v2", "_split_v2"):
        sections = int(attrs.get("sections", 0))
        return sections if sections else len(attrs.get("indices", ())) + 1
    if opname == "topk" and attrs.get("ret_typ") == "both":
        return 2
    if opname in ("BatchNorm", "batch_norm") and attrs.get("output_mean_var"):
        return 3
    if opname in ("LayerNorm", "layer_norm") and attrs.get("output_mean_var"):
        return 3
    if opname == "GroupNorm" and attrs.get("output_mean_var"):
        return 3
    if opname == "RNN":
        return 3 if attrs.get("mode", "lstm") == "lstm" and attrs.get(
            "state_outputs") else (2 if attrs.get("state_outputs") else 1)
    if opname in ("_npi_average", "average") and str(
            attrs.get("returned", "False")).lower() not in ("false", "0"):
        return 2
    if opname == "amp_multicast":
        return int(attrs.get("num_outputs", 1))
    if opname in ("_linalg_slogdet", "linalg_slogdet", "batch_norm_stats",
                  "_linalg_gelqf", "linalg_gelqf", "_linalg_syevd",
                  "linalg_syevd"):
        return 2
    if opname == "moments":
        return 2
    return 1


def _invoke_symbol(opname, inputs: List[Optional[Symbol]], attrs, name=None):
    op = _reg.get_op(opname)
    attrs = {k: v for k, v in attrs.items() if v is not None}
    hint = opname.lower().strip("_")
    name = NameManager.current().get(name, hint)
    arg_names = _input_arg_names(op)

    entries = []
    if arg_names is None:
        # variadic op: all inputs positional symbols
        for s in inputs:
            entries.append(s._outputs[0])
    else:
        no_bias = attrs.get("no_bias", False)
        required = _required_arg_names(op)
        for pos, argname in enumerate(arg_names):
            if pos < len(inputs) and inputs[pos] is not None:
                entries.append(inputs[pos]._outputs[0])
            elif argname in attrs and isinstance(attrs.get(argname), Symbol):
                entries.append(attrs.pop(argname)._outputs[0])
            elif argname in PARAM_INPUT_NAMES or argname in required:
                if argname == "bias" and no_bias:
                    entries.append((_NULL_NODE, 0))
                else:
                    # auto-create free variable (nnvm compose semantics):
                    # e.g. fc1_weight, softmax_label
                    v = var("%s_%s" % (name, argname))
                    entries.append(v._outputs[0])
            else:
                if pos < len(inputs):
                    entries.append((_NULL_NODE, 0))
                else:
                    break  # trailing optional inputs omitted
    node = _Node(opname, name, attrs, entries,
                 num_outputs=_compose_num_outputs(opname, attrs))
    return Symbol([(node, i) for i in range(node.num_outputs)]) \
        if node.num_outputs > 1 else Symbol([(node, 0)])


def _make_wrapper(public_name, op):
    def wrapper(*args, name=None, attr=None, **kwargs):
        inputs = []
        for a in args:
            if isinstance(a, Symbol) or a is None:
                inputs.append(a)
            else:
                raise TypeError(
                    "mx.sym.%s expects Symbol inputs, got %r" % (public_name, a))
        # pull Symbol-valued kwargs as named inputs
        arg_names = _input_arg_names(op) or []
        for n in arg_names[len(inputs):]:
            if n in kwargs and isinstance(kwargs[n], Symbol):
                inputs.append(kwargs.pop(n))
            elif n in kwargs and kwargs[n] is None:
                kwargs.pop(n)
                inputs.append(None)
            else:
                break
        return _invoke_symbol(op.name, inputs, kwargs, name=name)

    wrapper.__name__ = public_name
    # full dmlc::Parameter-style schema docstring (MXSymbolGetAtomicSymbolInfo
    # analog) so help(mx.nd.op) shows inputs + typed parameters
    wrapper.__doc__ = _reg.op_doc(op.name)
    return wrapper


def __getattr__(attr_name):
    if attr_name.startswith("__"):
        raise AttributeError(attr_name)
    try:
        op = _reg.get_op(attr_name)
    except NotImplementedError:
        raise AttributeError("mx.sym has no operator %r" % attr_name) from None
    w = _make_wrapper(attr_name, op)
    setattr(sys.modules[__name__], attr_name, w)
    return w


from . import linalg  # noqa: E402  (needs _invoke_symbol above)


def zeros(shape, dtype="float32", **kwargs):
    return _invoke_symbol("_zeros", [], {"shape": shape, "dtype": dtype}, **kwargs)


def ones(shape, dtype="float32", **kwargs):
    return _invoke_symbol("_ones", [], {"shape": shape, "dtype": dtype}, **kwargs)


def arange(start, stop=None, step=1.0, repeat=1, dtype="float32", **kwargs):
    return _invoke_symbol("_arange", [], {"start": start, "stop": stop,
                                          "step": step, "repeat": repeat,
                                          "dtype": dtype}, **kwargs)
