"""``mx.sym.contrib`` — resolves ``name`` to the ``_contrib_name`` op, plus
symbolic control flow (reference: python/mxnet/symbol/contrib.py — foreach
:92, while_loop :272, cond :459; backing ops src/operator/control_flow.cc).

The subgraph Symbol is stored as a node attribute and lowered to
``lax.scan`` / ``lax.while_loop`` / ``lax.cond`` inside the executor's one
jitted program (see ops/control_flow.py)."""
from __future__ import annotations

import sys

from ..name import NameManager
from ..ops import registry as _reg

__all__ = ["foreach", "while_loop", "cond"]


from ..base import _as_list


def _free_variables(subgraph, exclude_names):
    """Var nodes of the subgraph that are NOT the fresh loop inputs —
    captured outer parameters (the reference cuts the graph the same way
    in symbol/contrib.py _get_graph_inputs)."""
    from .symbol import Symbol, _toposort
    seen = []
    for node in _toposort([n for n, _ in subgraph._outputs]):
        if node.is_var and node.name not in exclude_names \
                and node.name != "__null__":
            seen.append(node)
    return [Symbol([(n, 0)]) for n in seen]


def _make_cf_node(opname, name_hint, entries_syms, attrs, num_outputs, name):
    from .symbol import Symbol, _Node
    name = NameManager.current().get(name, name_hint)
    entries = [s._outputs[0] for s in entries_syms]
    node = _Node(opname, name, attrs, entries, num_outputs=num_outputs)
    return Symbol([(node, i) for i in range(num_outputs)])


def foreach(body, data, init_states, name="foreach"):
    """Symbolic scan: ``body(data_t, states) -> (outputs, new_states)``
    (symbol/contrib.py:92)."""
    from . import var as _var

    data_list = _as_list(data)
    states_list = _as_list(init_states)
    single_state = not isinstance(init_states, (list, tuple))

    data_names = tuple("__foreach_data%d" % i for i in range(len(data_list)))
    state_names = tuple("__foreach_state%d" % i
                        for i in range(len(states_list)))
    dvars = [_var(n) for n in data_names]
    svars = [_var(n) for n in state_names]
    outs, out_states = body(dvars[0] if len(dvars) == 1 else dvars,
                            svars[0] if single_state else svars)
    outs = _as_list(outs)
    out_states = _as_list(out_states)
    assert len(out_states) == len(states_list), \
        "body must return as many states as init_states"
    from .symbol import Group
    subgraph = Group(outs + out_states)
    free = _free_variables(subgraph, set(data_names) | set(state_names))
    attrs = dict(subgraph=subgraph, data_names=data_names,
                 state_names=state_names,
                 free_names=tuple(s.name for s in free),
                 num_out_data=len(outs))
    total = len(outs) + len(out_states)
    res = _make_cf_node("_foreach", "foreach",
                        data_list + states_list + free, attrs, total, name)
    res_list = list(res)
    out = res_list[0] if len(outs) == 1 else res_list[:len(outs)]
    st = res_list[len(outs):]
    return out, (st[0] if single_state else st)


def while_loop(cond, func, loop_vars, max_iterations=None, name="while_loop"):
    """Symbolic bounded while loop (symbol/contrib.py:272)."""
    from . import var as _var
    from .symbol import Group

    if max_iterations is None:
        raise ValueError("max_iterations is required")
    single_var = not isinstance(loop_vars, (list, tuple))
    vars_list = _as_list(loop_vars)
    var_names = tuple("__while_var%d" % i for i in range(len(vars_list)))
    vvars = [_var(n) for n in var_names]

    cond_out = cond(*vvars)
    cond_graph = Group([cond_out])
    outs, new_vars = func(*vvars)
    outs = _as_list(outs)
    new_vars = _as_list(new_vars)
    assert len(new_vars) == len(vars_list), \
        "func must return as many loop_vars as it consumes"
    body_graph = Group(outs + new_vars)
    free_syms = {}
    for s in _free_variables(cond_graph, set(var_names)) + \
            _free_variables(body_graph, set(var_names)):
        free_syms[s.name] = s
    free = list(free_syms.values())
    attrs = dict(cond_graph=cond_graph, body_graph=body_graph,
                 var_names=var_names,
                 free_names=tuple(s.name for s in free),
                 max_iterations=int(max_iterations),
                 num_out_data=len(outs))
    total = len(outs) + len(new_vars)
    res = _make_cf_node("_while_loop", "while_loop", vars_list + free,
                        attrs, total, name)
    res_list = list(res)
    out = res_list[0] if len(outs) == 1 else res_list[:len(outs)]
    vs = res_list[len(outs):]
    return out, (vs[0] if single_var else vs)


def cond(pred, then_func, else_func, inputs=None, name="cond"):
    """Symbolic conditional (symbol/contrib.py:459).  ``pred``/branches are
    zero-arg closures over outer symbols, like the reference."""
    from .symbol import Group

    pred_out = pred() if callable(pred) else pred
    pred_graph = Group([pred_out])
    then_out = _as_list(then_func())
    else_out = _as_list(else_func())
    assert len(then_out) == len(else_out), \
        "then and else branches must produce the same number of outputs"
    then_graph = Group(then_out)
    else_graph = Group(else_out)
    free_syms = {}
    for g in (pred_graph, then_graph, else_graph):
        for s in _free_variables(g, set()):
            free_syms[s.name] = s
    free = list(free_syms.values())
    attrs = dict(pred_graph=pred_graph, then_graph=then_graph,
                 else_graph=else_graph, pred_names=(), branch_names=(),
                 free_names=tuple(s.name for s in free))
    total = len(then_out)
    res = _make_cf_node("_cond", "cond", free, attrs, total, name)
    res_list = list(res)
    return res_list[0] if total == 1 else res_list


def __getattr__(name):
    if name.startswith("__"):
        raise AttributeError(name)
    from . import _make_wrapper
    for cand in ("_contrib_" + name, name):
        if cand in _reg.OPS:
            w = _make_wrapper(name, _reg.OPS[cand])
            setattr(sys.modules[__name__], name, w)
            return w
    raise AttributeError("mx.sym.contrib has no operator %r" % name)
