"""Symbol: the symbolic graph IR.

Parity: ``python/mxnet/symbol/symbol.py`` + the nnvm Graph the reference
builds through the C API (``src/c_api/c_api_symbolic.cc``).  This is a
from-scratch Python graph IR whose *execution* lowers the whole graph to one
XLA computation (via :mod:`..executor`) instead of binding per-node engine
ops like the reference's GraphExecutor.

Key behaviors reproduced:
- compose with auto-created variables for missing op inputs
  (``sym.FullyConnected(data, num_hidden=10, name='fc1')`` creates
  ``fc1_weight``/``fc1_bias`` vars),
- ``list_arguments`` / ``list_auxiliary_states`` / ``list_outputs``,
- shape/dtype inference, incl. backward inference of parameter shapes from
  data shapes (the reference's InferShape fixed-point pass,
  ``src/executor/infer_graph_attr_pass.cc``),
- JSON save/load (nodes / arg_nodes / heads layout like nnvm's JSON),
- ``bind`` / ``simple_bind`` / ``eval`` and gradient via the executor.
"""
from __future__ import annotations

import ast
import json
import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np

from ..base import MXNetError, np_dtype
from ..name import NameManager
from ..attribute import AttrScope
from ..ops import registry as _reg

__all__ = ["Symbol", "var", "Variable", "Group", "load", "load_json",
           "AUX_SUFFIXES", "PARAM_INPUT_NAMES"]

# input-arg names that denote auxiliary state (not gradient targets) —
# reference: mutable inputs listed via FMutateInputs (BatchNorm aux)
AUX_SUFFIXES = ("moving_mean", "moving_var", "running_mean", "running_var")

# op input names that are parameters (auto-var names use these suffixes)
PARAM_INPUT_NAMES = {"weight", "bias", "gamma", "beta", "moving_mean",
                     "moving_var", "alpha", "parameters", "state", "state_cell"}


class _Node:
    """One graph node: an op application or a variable."""

    __slots__ = ("op", "name", "attrs", "inputs", "num_outputs", "_attr_dict")

    def __init__(self, op: Optional[str], name: str, attrs=None, inputs=None,
                 num_outputs=1):
        self.op = op  # None => variable
        self.name = name
        self.attrs = dict(attrs or {})
        self.inputs: List[Tuple["_Node", int]] = list(inputs or [])
        self.num_outputs = num_outputs
        self._attr_dict = {}

    @property
    def is_var(self):
        return self.op is None


def _toposort(heads: Sequence[_Node]) -> List[_Node]:
    seen = {}
    order: List[_Node] = []
    stack = [(h, False) for h in reversed(heads)]
    while stack:
        node, processed = stack.pop()
        if processed:
            order.append(node)
            continue
        if id(node) in seen:
            continue
        seen[id(node)] = True
        stack.append((node, True))
        for parent, _ in reversed(node.inputs):
            if id(parent) not in seen:
                stack.append((parent, False))
    return order


class Symbol:
    """Handle to one-or-more outputs of a graph (symbol.py Symbol parity)."""

    __is_symbol__ = True

    def __init__(self, outputs: List[Tuple[_Node, int]]):
        self._outputs = list(outputs)

    # ------------------------------------------------------------ meta
    @property
    def name(self):
        if len(self._outputs) == 1:
            return self._outputs[0][0].name
        return None

    def attr(self, key):
        node = self._outputs[0][0]
        return node._attr_dict.get(key)

    def list_attr(self):
        return dict(self._outputs[0][0]._attr_dict)

    def attr_dict(self):
        out = {}
        for node in _toposort([n for n, _ in self._outputs]):
            if node._attr_dict:
                out[node.name] = dict(node._attr_dict)
        return out

    def _set_attr(self, **kwargs):
        self._outputs[0][0]._attr_dict.update(kwargs)

    def __repr__(self):
        if len(self._outputs) == 1:
            return "<Symbol %s>" % self.name
        return "<Symbol group [%s]>" % ", ".join(n.name for n, _ in self._outputs)

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    def __len__(self):
        return len(self.list_outputs())

    def __getitem__(self, index):
        if isinstance(index, str):
            names = self.list_outputs()
            index = names.index(index)
        if isinstance(index, slice):
            return Symbol(self._outputs[index])
        node, idx = self._outputs[index] if len(self._outputs) > 1 else (
            self._outputs[0][0], index)
        if len(self._outputs) == 1 and self._outputs[0][0].num_outputs > 1:
            return Symbol([(self._outputs[0][0], index)])
        return Symbol([self._outputs[index]])

    def __copy__(self):
        return Symbol(list(self._outputs))

    def __deepcopy__(self, memo):
        # graph nodes are immutable-by-convention; shallow copy suffices
        return Symbol(list(self._outputs))

    # ------------------------------------------------------------ listing
    def _all_nodes(self):
        return _toposort([n for n, _ in self._outputs])

    def list_arguments(self) -> List[str]:
        return [n.name for n in self._all_nodes()
                if n.is_var and n.name != "__null__"
                and not n.name.endswith(AUX_SUFFIXES)]

    def list_auxiliary_states(self) -> List[str]:
        return [n.name for n in self._all_nodes()
                if n.is_var and n.name.endswith(AUX_SUFFIXES)]

    def list_inputs(self) -> List[str]:
        return [n.name for n in self._all_nodes()
                if n.is_var and n.name != "__null__"]

    def list_outputs(self) -> List[str]:
        names = []
        for node, idx in self._outputs:
            if node.num_outputs > 1:
                names.append("%s_output%d" % (node.name, idx))
            else:
                names.append("%s_output" % node.name)
        return names

    def get_internals(self) -> "Symbol":
        outs = []
        for node in self._all_nodes():
            for i in range(node.num_outputs):
                outs.append((node, i))
        return Symbol(outs)

    def get_children(self) -> Optional["Symbol"]:
        node = self._outputs[0][0]
        if not node.inputs:
            return None
        return Symbol(list(node.inputs))

    # ------------------------------------------------------------ compose ops
    def _binop(self, other, opname, reverse=False):
        if isinstance(other, (int, float)):
            name = NameManager.current().get(None, opname.strip("_").lower())
            scalar_op = {"broadcast_add": "_plus_scalar",
                         "broadcast_sub": "_rminus_scalar" if reverse else "_minus_scalar",
                         "broadcast_mul": "_mul_scalar",
                         "broadcast_div": "_rdiv_scalar" if reverse else "_div_scalar",
                         "broadcast_power": "_rpower_scalar" if reverse else "_power_scalar",
                         "broadcast_mod": "_rmod_scalar" if reverse else "_mod_scalar",
                         "broadcast_equal": "_equal_scalar",
                         "broadcast_not_equal": "_not_equal_scalar",
                         "broadcast_greater": "_lesser_scalar" if reverse else "_greater_scalar",
                         "broadcast_greater_equal": "_lesser_equal_scalar" if reverse else "_greater_equal_scalar",
                         "broadcast_lesser": "_greater_scalar" if reverse else "_lesser_scalar",
                         "broadcast_lesser_equal": "_greater_equal_scalar" if reverse else "_lesser_equal_scalar"}[opname]
            node = _Node(scalar_op, name, {"scalar": float(other)},
                         [self._outputs[0]])
            return Symbol([(node, 0)])
        lhs, rhs = (other, self) if reverse else (self, other)
        name = NameManager.current().get(None, opname.strip("_").lower())
        node = _Node(opname, name, {}, [lhs._outputs[0], rhs._outputs[0]])
        return Symbol([(node, 0)])

    def __add__(self, other):
        return self._binop(other, "broadcast_add")

    __radd__ = __add__

    def __sub__(self, other):
        return self._binop(other, "broadcast_sub")

    def __rsub__(self, other):
        return self._binop(other, "broadcast_sub", reverse=True)

    def __mul__(self, other):
        return self._binop(other, "broadcast_mul")

    __rmul__ = __mul__

    def __truediv__(self, other):
        return self._binop(other, "broadcast_div")

    def __rtruediv__(self, other):
        return self._binop(other, "broadcast_div", reverse=True)

    def __pow__(self, other):
        return self._binop(other, "broadcast_power")

    def __neg__(self):
        return self.__mul__(-1.0)

    def __eq__(self, other):
        return self._binop(other, "broadcast_equal") if isinstance(
            other, (Symbol, int, float)) else NotImplemented

    def __ne__(self, other):
        return self._binop(other, "broadcast_not_equal") if isinstance(
            other, (Symbol, int, float)) else NotImplemented

    def __gt__(self, other):
        return self._binop(other, "broadcast_greater")

    def __ge__(self, other):
        return self._binop(other, "broadcast_greater_equal")

    def __lt__(self, other):
        return self._binop(other, "broadcast_lesser")

    def __le__(self, other):
        return self._binop(other, "broadcast_lesser_equal")

    def __hash__(self):
        return id(self)

    # generated-op methods (subset commonly used as methods)
    def _method_op(self, opname, **kwargs):
        from . import _invoke_symbol

        return _invoke_symbol(opname, [self], kwargs)

    def reshape(self, *shape, **kwargs):
        if len(shape) == 1 and isinstance(shape[0], (tuple, list)):
            shape = tuple(shape[0])
        shape = kwargs.pop("shape", shape)
        return self._method_op("Reshape", shape=shape, **kwargs)

    def transpose(self, axes=None):
        return self._method_op("transpose", axes=axes)

    def flatten(self):
        return self._method_op("Flatten")

    def sum(self, axis=None, keepdims=False):  # noqa: A003
        return self._method_op("sum", axis=axis, keepdims=keepdims)

    def mean(self, axis=None, keepdims=False):
        return self._method_op("mean", axis=axis, keepdims=keepdims)

    def astype(self, dtype):
        return self._method_op("Cast", dtype=dtype)

    def slice_axis(self, axis, begin, end):
        return self._method_op("slice_axis", axis=axis, begin=begin, end=end)

    def expand_dims(self, axis):
        return self._method_op("expand_dims", axis=axis)

    def softmax(self, axis=-1):
        return self._method_op("softmax", axis=axis)

    # ------------------------------------------------------------ inference
    def infer_shape(self, *args, **kwargs):
        """Return (arg_shapes, out_shapes, aux_shapes) — symbol.py:1045."""
        try:
            return self._infer_shape_impl(False, *args, **kwargs)
        except Exception:
            raise

    def infer_shape_partial(self, *args, **kwargs):
        return self._infer_shape_impl(True, *args, **kwargs)

    def _infer_shape_impl(self, partial, *args, **kwargs):
        known: Dict[str, Tuple[int, ...]] = {}
        if args:
            for name, shape in zip(self.list_arguments(), args):
                if shape is not None:
                    known[name] = tuple(shape)
        known.update({k: tuple(v) for k, v in kwargs.items() if v is not None})
        shapes, dtypes = _infer_graph(self, known, {}, partial=partial)
        arg_shapes = [shapes.get(n) for n in self.list_arguments()]
        aux_shapes = [shapes.get(n) for n in self.list_auxiliary_states()]
        out_shapes = [shapes.get(_entry_key(node, i)) for node, i in self._outputs]
        return arg_shapes, out_shapes, aux_shapes

    def infer_type(self, *args, **kwargs):
        known: Dict[str, Any] = {}
        if args:
            for name, dt in zip(self.list_arguments(), args):
                if dt is not None:
                    known[name] = np_dtype(dt)
        known.update({k: np_dtype(v) for k, v in kwargs.items() if v is not None})
        # dtype inference: run shape inference with dummy shapes where needed
        shapes, dtypes = _infer_graph(self, {}, known, partial=True)
        arg_types = [dtypes.get(n, np.dtype(np.float32)) for n in self.list_arguments()]
        aux_types = [dtypes.get(n, np.dtype(np.float32)) for n in self.list_auxiliary_states()]
        out_types = [dtypes.get(_entry_key(node, i), np.dtype(np.float32))
                     for node, i in self._outputs]
        return arg_types, out_types, aux_types

    # ------------------------------------------------------------ execution
    def eval_with(self, bindings):
        """Evaluate eagerly given {var_name: NDArray} (SymbolBlock path)."""
        from ..ndarray import NDArray

        vals = {k: (v._data if isinstance(v, NDArray) else v)
                for k, v in bindings.items()}
        outs = _eval_graph(self, vals)
        res = [NDArray(o) for o in outs]
        return res[0] if len(res) == 1 else res

    def eval(self, ctx=None, **kwargs):  # noqa: A003
        return self.eval_with(kwargs)

    def bind(self, ctx, args, args_grad=None, grad_req="write", aux_states=None,
             group2ctx=None, shared_exec=None):
        from ..executor import Executor

        return Executor(self, ctx, args, args_grad, grad_req, aux_states)

    def simple_bind(self, ctx, grad_req="write", type_dict=None,
                    stype_dict=None, group2ctx=None, shared_arg_names=None,
                    shared_exec=None, shared_buffer=None, **kwargs):
        from ..executor import Executor
        from ..ndarray import ndarray as _nd

        arg_shapes, _, aux_shapes = self.infer_shape(**kwargs)
        arg_names = self.list_arguments()
        aux_names = self.list_auxiliary_states()
        type_dict = type_dict or {}
        args = {}
        for name, shape in zip(arg_names, arg_shapes):
            if shape is None:
                raise MXNetError("simple_bind could not infer shape of %r" % name)
            args[name] = _nd.zeros(shape, ctx=ctx,
                                   dtype=type_dict.get(name, "float32"))
        args_grad = {}
        req = grad_req if isinstance(grad_req, dict) else {
            n: grad_req for n in arg_names}
        for name, shape in zip(arg_names, arg_shapes):
            if req.get(name, "write") != "null":
                args_grad[name] = _nd.zeros(shape, ctx=ctx,
                                            dtype=type_dict.get(name, "float32"))
        aux_states = {}
        for name, shape in zip(aux_names, aux_shapes):
            aux_states[name] = _nd.zeros(shape, ctx=ctx)
        return Executor(self, ctx, args, args_grad, grad_req, aux_states)

    # gradient (symbolic): handled through executor vjp; this returns a
    # placeholder symbol list for API parity
    def gradient(self, wrt):
        raise NotImplementedError(
            "symbolic gradient symbols: use Executor.backward (vjp-based)")

    # ------------------------------------------------------------ serialization
    def tojson(self) -> str:
        nodes = self._all_nodes()
        node_ids = {id(n): i for i, n in enumerate(nodes)}
        out_nodes = []
        arg_nodes = []
        for i, node in enumerate(nodes):
            if node.is_var:
                arg_nodes.append(i)
            entry = {
                "op": node.op if node.op else "null",
                "name": node.name,
                "inputs": [[node_ids[id(p)], idx, 0] for p, idx in node.inputs],
            }
            if node.attrs:
                entry["attrs"] = {k: _attr_to_str(v) for k, v in node.attrs.items()}
            if node.num_outputs != 1:
                entry["num_outputs"] = node.num_outputs
            out_nodes.append(entry)
        heads = [[node_ids[id(n)], idx, 0] for n, idx in self._outputs]
        return json.dumps({
            "nodes": out_nodes,
            "arg_nodes": arg_nodes,
            "node_row_ptr": list(range(len(nodes) + 1)),
            "heads": heads,
            "attrs": {"mxnet_version": ["int", 10600],
                      "framework": ["str", "incubator-mxnet-tpu"]},
        }, indent=2)

    def save(self, fname: str):
        with open(fname, "w") as f:
            f.write(self.tojson())

    def get_backend_symbol(self, backend):
        """Subgraph-backend hook (subgraph_property.h parity). The XLA
        lowering is the built-in 'backend'; returns self."""
        return self

    def optimize_for(self, backend, args=None, aux=None, ctx=None, **kwargs):
        return self


# ---------------------------------------------------------------------------
# helpers
# ---------------------------------------------------------------------------


_SUBGRAPH_PREFIX = "__subgraph_json__:"


def _attr_to_str(v):
    if isinstance(v, str):
        return v
    if isinstance(v, Symbol):
        # control-flow subgraph attrs round-trip as nested JSON
        return _SUBGRAPH_PREFIX + v.tojson()
    return repr(v)


def _parse_attr(s):
    if not isinstance(s, str):
        return s
    if s.startswith(_SUBGRAPH_PREFIX):
        return load_json(s[len(_SUBGRAPH_PREFIX):])
    try:
        return ast.literal_eval(s)
    except (ValueError, SyntaxError):
        return s


def _entry_key(node: _Node, idx: int) -> str:
    return "%s#%d" % (node.name, idx)


def var(name, attr=None, shape=None, lr_mult=None, wd_mult=None, dtype=None,
        init=None, stype=None, **kwargs) -> Symbol:
    """Create a variable symbol (sym.var / sym.Variable parity)."""
    if not isinstance(name, str):
        raise TypeError("Expect a string for variable name")
    node = _Node(None, name)
    attrs = AttrScope.current().get(attr)
    node._attr_dict.update(attrs or {})
    if shape is not None:
        node.attrs["__shape__"] = tuple(shape)
    if dtype is not None:
        node.attrs["__dtype__"] = str(np_dtype(dtype))
    return Symbol([(node, 0)])


Variable = var


def Group(symbols: Sequence[Symbol]) -> Symbol:  # noqa: N802 - parity name
    outs = []
    for s in symbols:
        outs.extend(s._outputs)
    return Symbol(outs)


def load(fname: str) -> Symbol:
    with open(fname) as f:
        return load_json(f.read())


def load_json(json_str: str) -> Symbol:
    """Load symbol JSON, including stock/legacy MXNet files.

    Upgrade handling (``src/nnvm/legacy_json_util.cc`` analog): op params
    live under modern ``attrs`` or legacy ``param``; per-node non-op
    attributes (``lr_mult``, ``ctx_group``, ...) under legacy ``attr`` are
    preserved separately; ``backward_source_id`` is ignored; ``heads``
    entries of length 2 or 3 are accepted; multi-output node arity is
    recovered from the highest referenced output index when the file does
    not record ``num_outputs``.
    """
    data = json.loads(json_str)
    nodes: List[_Node] = []
    max_ref: Dict[int, int] = {}
    for entry in data["nodes"]:
        op = entry.get("op")
        op = None if op in (None, "null") else op
        attrs = {k: _parse_attr(v) for k, v in (entry.get("attrs")
                                                or entry.get("param") or {}).items()}
        shape_attr = attrs.pop("__shape__", None)
        dtype_attr = attrs.pop("__dtype__", None)
        node = _Node(op, entry["name"], attrs,
                     num_outputs=entry.get("num_outputs", 1))
        node_attr = entry.get("attr")
        if isinstance(node_attr, dict):
            node._attr_dict.update(node_attr)
        if shape_attr is not None:
            node.attrs["__shape__"] = tuple(shape_attr)
        if dtype_attr is not None:
            node.attrs["__dtype__"] = dtype_attr
        for inp in entry.get("inputs", []):
            node.inputs.append((nodes[inp[0]], inp[1]))
            max_ref[inp[0]] = max(max_ref.get(inp[0], 0), inp[1])
        nodes.append(node)
    heads = data.get("heads")
    if not heads:
        heads = [[len(nodes) - 1, 0]]
    for h in heads:
        max_ref[h[0]] = max(max_ref.get(h[0], 0), h[1])
    for i, node in enumerate(nodes):
        if not node.is_var and max_ref.get(i, 0) + 1 > node.num_outputs:
            node.num_outputs = max_ref[i] + 1
    return Symbol([(nodes[h[0]], h[1]) for h in heads])


# ---------------------------------------------------------------------------
# graph evaluation + inference
# ---------------------------------------------------------------------------


def _node_outputs_count(node: _Node) -> int:
    return node.num_outputs


def _eval_node(node: _Node, in_vals: List[Any]):
    op = _reg.get_op(node.op)
    attrs = {k: v for k, v in node.attrs.items()
             if not k.startswith("__")}
    out = _reg.invoke_raw(op, in_vals, **attrs)
    return list(out) if isinstance(out, (tuple, list)) else [out]


def _eval_graph(symbol: Symbol, bindings: Dict[str, Any]) -> List[Any]:
    """Evaluate the graph on raw arrays; used inside Executor's jit."""
    cache: Dict[Tuple[int, int], Any] = {}
    for node in _toposort([n for n, _ in symbol._outputs]):
        if node.is_var:
            if node.name == "__null__":
                cache[(id(node), 0)] = None
                continue
            if node.name not in bindings:
                raise MXNetError("unbound variable %r" % node.name)
            cache[(id(node), 0)] = bindings[node.name]
        else:
            in_vals = [cache[(id(p), i)] for p, i in node.inputs]
            outs = _eval_node(node, in_vals)
            for i, o in enumerate(outs):
                cache[(id(node), i)] = o
    return [cache[(id(n), i)] for n, i in symbol._outputs]


def _param_shape_rules(node: _Node, data_shape, known):
    """Backward shape inference for parameter inputs (reference:
    per-op FInferShape filling unknown args — infer_graph_attr_pass.cc)."""
    op = node.op
    a = node.attrs
    out = {}
    if op == "FullyConnected":
        num_hidden = int(a.get("num_hidden"))
        flatten = a.get("flatten", True)
        in_units = int(np.prod(data_shape[1:])) if flatten else data_shape[-1]
        out["weight"] = (num_hidden, in_units)
        out["bias"] = (num_hidden,)
    elif op == "Convolution":
        nf = int(a.get("num_filter"))
        ng = int(a.get("num_group", 1))
        kernel = tuple(a.get("kernel"))
        out["weight"] = (nf, data_shape[1] // ng) + kernel
        out["bias"] = (nf,)
    elif op == "Deconvolution":
        nf = int(a.get("num_filter"))
        ng = int(a.get("num_group", 1))
        kernel = tuple(a.get("kernel"))
        out["weight"] = (data_shape[1], nf // ng) + kernel
        out["bias"] = (nf,)
    elif op in ("BatchNorm", "InstanceNorm"):
        axis = int(a.get("axis", 1))
        c = data_shape[axis]
        out["gamma"] = out["beta"] = (c,)
        out["moving_mean"] = out["moving_var"] = (c,)
    elif op == "LayerNorm":
        axis = int(a.get("axis", -1))
        out["gamma"] = out["beta"] = (data_shape[axis],)
    elif op == "GroupNorm":
        out["gamma"] = out["beta"] = (data_shape[1],)
    elif op == "Embedding":
        out["weight"] = (int(a.get("input_dim")), int(a.get("output_dim")))
    elif op in ("SoftmaxOutput", "Softmax", "softmax_output"):
        if a.get("multi_output"):
            out["label"] = (data_shape[0],) + tuple(data_shape[2:])
        elif a.get("preserve_shape"):
            out["label"] = tuple(data_shape[:-1])
        else:
            out["label"] = (data_shape[0],)
    elif op in ("LinearRegressionOutput", "MAERegressionOutput",
                "LogisticRegressionOutput", "linear_regression_output",
                "mae_regression_output", "logistic_regression_output"):
        out["label"] = tuple(data_shape)
    elif op == "LeakyReLU" and a.get("act_type") == "prelu":
        out["gamma"] = (data_shape[1] if len(data_shape) > 1 else 1,)
    elif op == "RNN":
        from ..ops import rnn as _rnn_ops

        out["parameters"] = (_rnn_ops.rnn_param_size(
            int(a.get("num_layers", 1)), data_shape[-1],
            int(a.get("state_size")), a.get("mode", "lstm"),
            bool(a.get("bidirectional", False))),)
        ndir = 2 if a.get("bidirectional") else 1
        out["state"] = (int(a.get("num_layers", 1)) * ndir, data_shape[1],
                        int(a.get("state_size")))
        out["state_cell"] = out["state"]
    return out


def _input_arg_names(op: _reg.Op):
    import inspect

    try:
        sig = inspect.signature(op.fn)
    except (TypeError, ValueError):
        return []
    names = []
    for p in sig.parameters.values():
        if p.kind == inspect.Parameter.VAR_POSITIONAL:
            return None
        if p.kind in (inspect.Parameter.POSITIONAL_ONLY,
                      inspect.Parameter.POSITIONAL_OR_KEYWORD):
            if p.default is inspect.Parameter.empty or p.name in PARAM_INPUT_NAMES \
                    or p.name in ("sequence_length", "label_lengths",
                                  "data_lengths", "r1_r2", "min_bias",
                                  "max_bias", "valid_length", "max_time"):
                names.append(p.name)
    return names


def _required_arg_names(op: _reg.Op):
    """Input args with no default — must be bound or auto-var'd at compose."""
    import inspect

    try:
        sig = inspect.signature(op.fn)
    except (TypeError, ValueError):
        return set()
    out = set()
    for p in sig.parameters.values():
        if p.kind in (inspect.Parameter.POSITIONAL_ONLY,
                      inspect.Parameter.POSITIONAL_OR_KEYWORD) \
                and p.default is inspect.Parameter.empty:
            out.add(p.name)
    return out


def _infer_graph(symbol: Symbol, known_shapes, known_dtypes, partial=False):
    """Abstract-evaluate the graph, solving unknown parameter-var shapes via
    per-op rules; returns ({name/entry: shape}, {name/entry: dtype})."""
    shapes: Dict[str, Any] = dict(known_shapes)
    dtypes: Dict[str, Any] = dict(known_dtypes)
    avals: Dict[Tuple[int, int], jax.ShapeDtypeStruct] = {}
    null_entries = set()
    nodes = _toposort([n for n, _ in symbol._outputs])
    for node in nodes:
        if node.is_var:
            if node.name == "__null__":
                null_entries.add((id(node), 0))
                continue
            shape = shapes.get(node.name, node.attrs.get("__shape__"))
            if shape is not None and all(s > 0 for s in shape):
                dt = dtypes.get(node.name, node.attrs.get("__dtype__", "float32"))
                avals[(id(node), 0)] = jax.ShapeDtypeStruct(tuple(shape),
                                                            np_dtype(dt))
                shapes[node.name] = tuple(shape)
                dtypes[node.name] = np_dtype(dt)
            continue
        op = _reg.get_op(node.op)
        # resolve unknown param-var inputs via data-shape rules
        if node.inputs and (id(node.inputs[0][0]), node.inputs[0][1]) in avals:
            data_aval = avals[(id(node.inputs[0][0]), node.inputs[0][1])]
            rules = _param_shape_rules(node, data_aval.shape, shapes)
            arg_names = _input_arg_names(op) or []
            for pos, (parent, pidx) in enumerate(node.inputs):
                if parent.is_var and (id(parent), pidx) not in avals:
                    argname = arg_names[pos] if pos < len(arg_names) else None
                    if argname in rules:
                        shapes[parent.name] = rules[argname]
                        dt = dtypes.get(parent.name, data_aval.dtype)
                        avals[(id(parent), 0)] = jax.ShapeDtypeStruct(
                            rules[argname], np_dtype(dt))
                        dtypes[parent.name] = np_dtype(dt)
        in_avals = []
        missing = False
        for parent, pidx in node.inputs:
            if (id(parent), pidx) in null_entries:
                in_avals.append(None)
                continue
            av = avals.get((id(parent), pidx))
            if av is None:
                missing = True
                break
            in_avals.append(av)
        if missing:
            if partial:
                continue
            unresolved = [p.name for p, i in node.inputs
                          if (id(p), i) not in avals and (id(p), i) not in null_entries]
            raise MXNetError(
                "infer_shape: cannot resolve inputs %s of node %s(%s)"
                % (unresolved, node.op, node.name))
        attrs = {k: v for k, v in node.attrs.items() if not k.startswith("__")}
        if op.needs_rng:
            attrs.setdefault("key", jax.ShapeDtypeStruct((2,), np.uint32))
            try:
                outs = op.infer(in_avals, **attrs)
            except Exception:
                attrs.pop("key")
                key = jax.random.PRNGKey(0)
                attrs["key"] = key
                outs = op.infer(in_avals, **attrs)
        else:
            outs = op.infer(in_avals, **attrs)
        node.num_outputs = len(outs)
        for i, o in enumerate(outs):
            avals[(id(node), i)] = o
            shapes[_entry_key(node, i)] = tuple(o.shape)
            dtypes[_entry_key(node, i)] = np.dtype(o.dtype)
    return shapes, dtypes
