"""Test helpers — the de-facto oracle toolkit of the reference.

Parity: ``python/mxnet/test_utils.py`` (2,464 LoC): ``default_context``
(:58), ``assert_almost_equal`` (:534), ``rand_ndarray`` (:377),
``check_numeric_gradient`` (:981), ``check_symbolic_forward/backward``
(:1124), ``check_consistency`` (:1422).

TPU analog of ``check_consistency``'s cpu-vs-gpu oracle: run the same symbol
on the default device (TPU when present) and on XLA-CPU, cross-compare.
"""
from __future__ import annotations

import numbers
from typing import Dict, List, Optional, Sequence, Union

import numpy as np

from .context import Context, cpu, current_context
from .ndarray import NDArray, array as nd_array
from .symbol import Symbol

__all__ = ["default_context", "set_default_context", "assert_almost_equal",
           "almost_equal", "same", "rand_ndarray", "rand_shape_2d",
           "rand_shape_3d", "rand_shape_nd", "check_numeric_gradient",
           "check_symbolic_forward", "check_symbolic_backward",
           "check_consistency", "simple_forward", "list_gpus",
           "rand_sparse_ndarray"]

_rng = np.random.RandomState(1234)


def default_context() -> Context:
    return current_context()


def set_default_context(ctx: Context):
    Context._default_ctx.value = ctx


def list_gpus():
    """Reference returns CUDA device ids; here: accelerator (TPU) ids."""
    import jax

    try:
        return [d.id for d in jax.devices() if d.platform != "cpu"]
    except Exception:
        return []


def same(a, b):
    return np.array_equal(np.asarray(a), np.asarray(b))


def almost_equal(a, b, rtol=None, atol=None, equal_nan=False):
    rtol = 1e-5 if rtol is None else rtol
    atol = 1e-20 if atol is None else atol
    return np.allclose(np.asarray(a), np.asarray(b), rtol=rtol, atol=atol,
                       equal_nan=equal_nan)


def assert_almost_equal(a, b, rtol=None, atol=None, names=("a", "b"),
                        equal_nan=False):
    a = a.asnumpy() if isinstance(a, NDArray) else np.asarray(a)
    b = b.asnumpy() if isinstance(b, NDArray) else np.asarray(b)
    rtol = 1e-5 if rtol is None else rtol
    atol = 1e-20 if atol is None else atol
    np.testing.assert_allclose(a, b, rtol=rtol, atol=atol,
                               equal_nan=equal_nan,
                               err_msg="%s vs %s" % names)


def rand_shape_2d(dim0=10, dim1=10):
    return _rng.randint(1, dim0 + 1), _rng.randint(1, dim1 + 1)


def rand_shape_3d(dim0=10, dim1=10, dim2=10):
    return (_rng.randint(1, dim0 + 1), _rng.randint(1, dim1 + 1),
            _rng.randint(1, dim2 + 1))


def rand_shape_nd(num_dim, dim=10):
    return tuple(_rng.randint(1, dim + 1, size=num_dim))


def rand_ndarray(shape, stype="default", density=None, dtype=None,
                 ctx=None, modifier_func=None, shuffle_csr_indices=False,
                 distribution="uniform"):
    dtype = np.float32 if dtype is None else np.dtype(dtype)
    if distribution == "powerlaw":
        data = _rng.pareto(2.0, size=shape).astype(dtype)
    else:
        data = _rng.uniform(-1.0, 1.0, size=shape).astype(dtype)
    if modifier_func is not None:
        data = np.vectorize(modifier_func)(data).astype(dtype)
    if stype in ("default", None):
        return nd_array(data, ctx=ctx)
    density = 0.1 if density is None else density
    mask = _rng.uniform(size=shape) < density
    data = data * mask
    from .ndarray import sparse as _sp

    if stype == "csr":
        return _sp.csr_matrix(data, ctx=ctx)
    if stype == "row_sparse":
        return _sp.row_sparse_array(data, ctx=ctx)
    raise ValueError("unknown stype %r" % stype)


def rand_sparse_ndarray(shape, stype, density=None, dtype=None, **kw):
    arr = rand_ndarray(shape, stype=stype, density=density, dtype=dtype)
    return arr, (arr.asnumpy(),)


def _norm_location(sym: Symbol, location):
    names = sym.list_arguments()
    if isinstance(location, dict):
        return {k: (v.asnumpy() if isinstance(v, NDArray) else np.asarray(v))
                for k, v in location.items()}
    return {n: (v.asnumpy() if isinstance(v, NDArray) else np.asarray(v))
            for n, v in zip(names, location)}


def _bind(sym: Symbol, location: Dict[str, np.ndarray], ctx, grad_req="write",
          aux_states=None):
    args = {k: nd_array(v, ctx=ctx) for k, v in location.items()}
    grads = {k: nd_array(np.zeros_like(v), ctx=ctx)
             for k, v in location.items()} if grad_req != "null" else None
    aux = None
    if aux_states:
        aux = {k: nd_array(v.asnumpy() if isinstance(v, NDArray)
                           else np.asarray(v), ctx=ctx)
               for k, v in aux_states.items()}
    return sym.bind(ctx, args=args, args_grad=grads, grad_req=grad_req,
                    aux_states=aux)


def simple_forward(sym, ctx=None, is_train=False, **inputs):
    outputs = _bind(sym, {k: np.asarray(v) for k, v in inputs.items()},
                    ctx or default_context(), grad_req="null").forward(
                        is_train=is_train)
    outs = [o.asnumpy() for o in outputs]
    return outs[0] if len(outs) == 1 else outs


def check_symbolic_forward(sym, location, expected, rtol=1e-5, atol=None,
                           ctx=None, aux_states=None, equal_nan=False):
    """Forward the symbol on `location`, compare against `expected`."""
    ctx = ctx or default_context()
    loc = _norm_location(sym, location)
    exe = _bind(sym, loc, ctx, grad_req="null", aux_states=aux_states)
    outputs = exe.forward(is_train=False)
    for out, exp in zip(outputs, expected):
        assert_almost_equal(out.asnumpy(), exp, rtol=rtol, atol=atol,
                            equal_nan=equal_nan)
    return [o.asnumpy() for o in outputs]


def check_symbolic_backward(sym, location, out_grads, expected, rtol=1e-5,
                            atol=None, ctx=None, aux_states=None,
                            grad_req="write", equal_nan=False):
    """Backward the symbol with `out_grads`, compare input grads."""
    ctx = ctx or default_context()
    loc = _norm_location(sym, location)
    exe = _bind(sym, loc, ctx, grad_req="write", aux_states=aux_states)
    exe.forward(is_train=True)
    exe.backward([nd_array(np.asarray(g), ctx=ctx) for g in out_grads])
    expected = expected if isinstance(expected, dict) else \
        dict(zip(sym.list_arguments(), expected))
    grads = dict(zip(sym.list_arguments(), exe.grad_arrays))
    for name, exp in expected.items():
        assert_almost_equal(grads[name].asnumpy(), exp, rtol=rtol, atol=atol,
                            equal_nan=equal_nan, names=("grad_" + name, "exp"))
    return {k: (v.asnumpy() if v is not None else None)
            for k, v in grads.items()}


def check_numeric_gradient(sym, location, aux_states=None, numeric_eps=1e-3,
                           rtol=1e-2, atol=None, grad_nodes=None, ctx=None,
                           dtype=np.float64):
    """Finite-difference gradient check (test_utils.py:981).

    Projects multi-output symbols to a scalar via a fixed random projection
    (the reference composes with MakeLoss the same way), then compares
    d(proj·out)/dx from the executor backward pass against central
    differences.
    """
    ctx = ctx or default_context()
    loc = {k: v.astype(dtype) for k, v in _norm_location(sym, location).items()}
    names = sym.list_arguments()
    grad_nodes = grad_nodes or [n for n in names if n in loc]

    proj_rng = np.random.RandomState(42)
    projs = None

    def eval_scalar(loc_now):
        nonlocal projs
        exe = _bind(sym, loc_now, ctx, grad_req="null", aux_states=aux_states)
        outs = [o.asnumpy() for o in exe.forward(is_train=True)]
        if projs is None:
            projs = [proj_rng.normal(size=o.shape) for o in outs]
        return sum(float(np.sum(o * p)) for o, p in zip(outs, projs))

    # symbolic gradient of the projected scalar
    exe = _bind(sym, loc, ctx, grad_req="write", aux_states=aux_states)
    outs = exe.forward(is_train=True)
    if projs is None:
        projs = [proj_rng.normal(size=o.shape) for o in outs]
    exe.backward([nd_array(p.astype(dtype), ctx=ctx) for p in projs])
    sym_grads = dict(zip(names, exe.grad_arrays))

    for name in grad_nodes:
        base = loc[name]
        num_grad = np.zeros_like(base, dtype=np.float64)
        flat = base.reshape(-1)
        for i in range(flat.size):
            orig = flat[i]
            flat[i] = orig + numeric_eps / 2
            fplus = eval_scalar(loc)
            flat[i] = orig - numeric_eps / 2
            fminus = eval_scalar(loc)
            flat[i] = orig
            num_grad.reshape(-1)[i] = (fplus - fminus) / numeric_eps
        assert_almost_equal(sym_grads[name].asnumpy(), num_grad, rtol=rtol,
                            atol=1e-4 if atol is None else atol,
                            names=("symbolic_grad_" + name, "numeric_grad"))


def check_consistency(sym, ctx_list=None, scale=1.0, grad_req="write",
                      arg_params=None, rtol=None, atol=None,
                      raise_on_err=True, shapes=None):
    """Cross-device/dtype oracle (test_utils.py:1422).

    ctx_list entries: dict(ctx=Context, <arg_name>=shape..., type_dict={...}).
    With ``ctx_list=None``, pass ``shapes={arg_name: shape}`` to compare
    [accelerator, XLA-CPU] at float32 — the TPU analog of the reference's
    gpu-vs-cpu comparison.
    """
    if ctx_list is None:
        if not shapes:
            raise ValueError(
                "check_consistency needs input shapes: pass ctx_list "
                "entries or shapes={arg_name: shape}")
        ctx_list = [{"ctx": default_context(), **shapes},
                    {"ctx": cpu(), **shapes}]
    results = []
    arg_names = sym.list_arguments()
    base_shapes = {k: v for k, v in ctx_list[0].items()
                   if k not in ("ctx", "type_dict")}
    # infer the shapes of auto-created parameter variables (fc_weight, ...)
    arg_shapes, _, _ = sym.infer_shape(**base_shapes)
    full_shapes = dict(zip(arg_names, arg_shapes))
    full_shapes.update(base_shapes)
    init = {n: _rng.normal(size=full_shapes[n], scale=scale)
            for n in arg_names if full_shapes.get(n) is not None}
    if arg_params:
        init.update({k: np.asarray(v) for k, v in arg_params.items()})
    for spec in ctx_list:
        ctx = spec.get("ctx", default_context())
        tdict = spec.get("type_dict", {})
        loc = {k: v.astype(tdict.get(k, np.float32)) for k, v in init.items()}
        exe = _bind(sym, loc, ctx, grad_req=grad_req)
        outs = [o.asnumpy() for o in exe.forward(is_train=grad_req != "null")]
        grads = None
        if grad_req != "null":
            exe.backward([nd_array(np.ones(o.shape, o.dtype), ctx=ctx)
                          for o in exe.outputs])
            grads = [g.asnumpy() if g is not None else None
                     for g in exe.grad_arrays]
        results.append((outs, grads, spec))
    ref_outs, ref_grads, _ = results[0]
    for outs, grads, spec in results[1:]:
        dt = list(spec.get("type_dict", {}).values())
        tol = (2e-2 if np.float16 in dt else 1e-3) if rtol is None else rtol
        for o, r in zip(outs, ref_outs):
            assert_almost_equal(o.astype(np.float64), r.astype(np.float64),
                                rtol=tol, atol=tol if atol is None else atol)
        if grads is not None and ref_grads is not None:
            for g, r in zip(grads, ref_grads):
                if g is not None and r is not None:
                    assert_almost_equal(g.astype(np.float64),
                                        r.astype(np.float64), rtol=tol,
                                        atol=tol if atol is None else atol)
    return results
