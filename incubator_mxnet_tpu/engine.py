"""Execution engine shims.

The reference runs every op through a C++ dependency engine
(``src/engine/threaded_engine_perdevice.cc``) that toposorts ops dynamically
over per-NDArray Vars.  On TPU, XLA + JAX's async dispatch already provide
asynchronous execution with correct data dependencies, so this module only
preserves the *API surface*: ``waitall`` (≡ Engine::WaitForAll), the bulk
scope (``MXNET_EXEC_BULK_EXEC_*`` semantics — a hint that is a no-op because
XLA fuses whole jitted programs anyway), and exception propagation happens at
``wait_to_read`` just like the reference surfaces async errors at WaitForVar.
"""
from __future__ import annotations

import contextlib
import ctypes
import threading

import jax

__all__ = ["waitall", "bulk", "set_bulk_size", "NativeEngine"]


def _native_lib():
    from ._native import get_lib
    return get_lib()


class NativeEngine:
    """Host-side dependency engine over the C++ scheduler
    (src/native/engine.cc; reference: include/mxnet/engine.h:117).

    Ops are Python callables with declared read (``const_vars``) / write
    (``mutable_vars``) sets over opaque Vars; the C++ side toposorts
    dynamically — writes serialize per var, reads run concurrently,
    exceptions surface at :meth:`wait_for_var` / :meth:`wait_for_all`
    exactly like the reference's WaitToRead rethrow.  Use it for host
    pipelines (prefetch, decode, checkpoint IO) around the XLA compute.
    """

    def __init__(self, num_workers=4):
        from ._native import OPR_FN, get_lib
        lib = get_lib()
        if lib is None:
            raise RuntimeError(
                "native engine unavailable (src/native build failed); "
                "host pipelining falls back to synchronous Python")
        self._lib = lib
        self._fn_type = OPR_FN
        self._handle = lib.MXTEngineCreate(int(num_workers))
        self._live = {}          # token -> CFUNCTYPE, kept until safe
        self._done = set()       # tokens whose callback has returned
        self._live_lock = threading.Lock()
        self._counter = 0

    def new_var(self):
        return self._lib.MXTEngineNewVar(self._handle)

    def delete_var(self, var):
        self._lib.MXTEngineDeleteVar(self._handle, var)

    def push(self, fn, const_vars=(), mutable_vars=(), name="pyop"):
        """Schedule ``fn()`` once all its var dependencies resolve
        (Engine::PushAsync, engine.h:204)."""
        const_vars = list(const_vars)
        mutable_vars = list(mutable_vars)
        # overlapping/duplicate vars would self-deadlock the dependency
        # queues; the reference CHECK-fails the same way (engine.h:291
        # DeduplicateVarHandle contract)
        if len(set(mutable_vars)) != len(mutable_vars):
            raise ValueError("duplicate handles in mutable_vars")
        if set(const_vars) & set(mutable_vars):
            raise ValueError(
                "const_vars and mutable_vars must be disjoint")
        const_vars = list(dict.fromkeys(const_vars))  # dedupe reads
        with self._live_lock:
            self._counter += 1
            token = self._counter
        # opportunistic safe prune.  Order matters: snapshot the done-set
        # FIRST, then read the outstanding count — a token done before an
        # observed count of zero has necessarily finished its C call
        # (done.add precedes the worker's outstanding decrement, so
        # reading 0 happens-after that op's frame unwound).  Tokens marked
        # done after the snapshot are left for next time, closing the
        # check-then-prune race with concurrent pushes.
        if len(self._done) > 256:
            with self._live_lock:
                snapshot = set(self._done)
            if self._lib.MXTEngineOutstanding(self._handle) == 0:
                with self._live_lock:
                    for t in snapshot:
                        self._live.pop(t, None)
                    self._done -= snapshot

        def trampoline(_ctx, _token=token):
            try:
                fn()
                rc = 0
            except Exception:
                rc = 1
            # only MARK done — dropping the CFUNCTYPE here would free the
            # ffi closure while the C worker is still returning through it
            with self._live_lock:
                self._done.add(_token)
            return rc

        cb = self._fn_type(trampoline)
        with self._live_lock:
            self._live[token] = cb
        n_c, n_m = len(const_vars), len(mutable_vars)
        c_arr = (ctypes.c_void_p * max(n_c, 1))(*const_vars)
        m_arr = (ctypes.c_void_p * max(n_m, 1))(*mutable_vars)
        self._lib.MXTEnginePushAsync(
            self._handle, cb, None, c_arr, n_c, m_arr, n_m,
            name.encode())

    def wait_for_var(self, var):
        buf = ctypes.create_string_buffer(512)
        rc = self._lib.MXTEngineWaitForVar(self._handle, var, buf, 512)
        if rc != 0:
            from .base import MXNetError
            raise MXNetError(buf.value.decode() or "engine op failed")

    def _prune(self):
        # safe point: tokens in _done finished their C call long ago
        # (wait_for_all barrier passed since), so their closures can go
        with self._live_lock:
            for t in self._done:
                self._live.pop(t, None)
            self._done.clear()

    def wait_for_all(self):
        buf = ctypes.create_string_buffer(512)
        rc = self._lib.MXTEngineWaitForAll(self._handle, buf, 512)
        self._prune()
        if rc != 0:
            from .base import MXNetError
            raise MXNetError(buf.value.decode() or "engine op failed")

    def close(self):
        if getattr(self, "_handle", None):
            self._lib.MXTEngineFree(self._handle)  # joins all workers
            self._handle = None
            with self._live_lock:
                self._live.clear()
                self._done.clear()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

_BULK_SIZE = 15  # parity default: MXNET_EXEC_BULK_EXEC_MAX_NODE_TRAIN


def waitall():
    """Block until all async computations are done (Engine::WaitForAll)."""
    try:
        jax.effects_barrier()
    except Exception:  # pragma: no cover
        pass
    # block on all live arrays is unnecessary; effects_barrier + a device sync
    # via a tiny transfer covers ordering for timing purposes.
    jax.device_get(jax.numpy.zeros(()))


def set_bulk_size(size: int) -> int:
    """Parity with mx.engine.set_bulk_size; returns previous size."""
    global _BULK_SIZE
    prev, _BULK_SIZE = _BULK_SIZE, size
    return prev


@contextlib.contextmanager
def bulk(size: int):
    """Parity with mx.engine.bulk scope (python/mxnet/engine.py:26-63).

    Under XLA the jit boundary is the bulking unit, so this is a hint-only
    scope retained for source compatibility.
    """
    prev = set_bulk_size(size)
    try:
        yield
    finally:
        set_bulk_size(prev)
