"""Execution engine shims.

The reference runs every op through a C++ dependency engine
(``src/engine/threaded_engine_perdevice.cc``) that toposorts ops dynamically
over per-NDArray Vars.  On TPU, XLA + JAX's async dispatch already provide
asynchronous execution with correct data dependencies, so this module only
preserves the *API surface*: ``waitall`` (≡ Engine::WaitForAll), the bulk
scope (``MXNET_EXEC_BULK_EXEC_*`` semantics — a hint that is a no-op because
XLA fuses whole jitted programs anyway), and exception propagation happens at
``wait_to_read`` just like the reference surfaces async errors at WaitForVar.
"""
from __future__ import annotations

import contextlib

import jax

__all__ = ["waitall", "bulk", "set_bulk_size"]

_BULK_SIZE = 15  # parity default: MXNET_EXEC_BULK_EXEC_MAX_NODE_TRAIN


def waitall():
    """Block until all async computations are done (Engine::WaitForAll)."""
    try:
        jax.effects_barrier()
    except Exception:  # pragma: no cover
        pass
    # block on all live arrays is unnecessary; effects_barrier + a device sync
    # via a tiny transfer covers ordering for timing purposes.
    jax.device_get(jax.numpy.zeros(()))


def set_bulk_size(size: int) -> int:
    """Parity with mx.engine.set_bulk_size; returns previous size."""
    global _BULK_SIZE
    prev, _BULK_SIZE = _BULK_SIZE, size
    return prev


@contextlib.contextmanager
def bulk(size: int):
    """Parity with mx.engine.bulk scope (python/mxnet/engine.py:26-63).

    Under XLA the jit boundary is the bulking unit, so this is a hint-only
    scope retained for source compatibility.
    """
    prev = set_bulk_size(size)
    try:
        yield
    finally:
        set_bulk_size(prev)
