"""graftrange: trace-time value-range & precision abstract interpreter.

graftlint checks program *structure* (GL0xx), graftcost prices its
*bytes* (GL2xx), graftpass rewrites it under verified contracts
(GL3xx) — but all three are numerically blind: ``amp_bf16`` demotes
every matmul regardless of operand magnitudes, the dynamic loss scaler
is runtime trial-and-error, and the repo has hand-fixed at least three
silent f64/instability bugs (the adam ``beta**int`` bias-correction
promotion, the ``np.float64`` attention scale) that a dtype/range
analysis would have caught at trace time.  This module is that
analysis: an abstract interpreter over the jaxpr that propagates, per
variable, a value interval, a NaN-possibility flag and the effective
precision, on the same zero-compile ``jit.trace()`` hook the other
analyzers share.  Following Relay's argument that a typed, analyzable
IR is what makes framework-level program analysis tractable
(arXiv:1810.00952), the jaxpr's avals carry the dtypes and the
interpreter adds the missing value semantics.

The abstract domain (:class:`VRange`) per variable:

- ``lo`` / ``hi`` — interval bounds.  ``None`` means *unknown but
  finite*: arithmetic over unknown magnitudes stays unknown (absorbing)
  instead of compounding to spurious infinities through deep matmul
  chains — only the exp family maps "unknown" to a proven overflow
  hazard, because ``exp`` overflows f32 at x ≈ 88.7, an utterly
  plausible logit.  A bound of ``±inf`` means the value can *really*
  be infinite (proven overflow).  Known bounds come from literals and
  consts (concrete values), caller annotations
  (``make_train_step(input_range=)``, the engine's warmup-observed
  sample), dtype facts (uint8 inputs, token-id iinfo ranges, bool) and
  the refinements below — and known bounds legitimately compound
  (an annotated ``[0, 1e20]`` squared proves overflow).
- ``positive`` — strictly greater than zero (``exp`` outputs, softmax
  denominators); refines a ``lo`` of 0/None for domain checks.
- ``nan`` — NaN possible on some input.
- ``dtype`` — the aval dtype (the effective-precision half: a float64
  var in a ≤f32 program is a silent promotion, GL404).

Relational refinements (what plain interval arithmetic cannot see):

- ``x - max(x)`` — a subtraction whose subtrahend chases (through
  ``stop_gradient`` / ``broadcast_in_dim`` / reshape / the
  ``max(-inf, .)`` jnp.max-initial idiom) to a ``reduce_max`` **of the
  same minuend** is bounded above by 0: ``jax.nn.softmax``'s
  max-subtraction lints clean while a manual ``exp(logits)`` without
  it trips GL401.
- ``x * x`` / ``square`` / ``abs`` / ``maximum(., c>=0)`` are
  non-negative: the in-repo BatchNorm's ``maximum(E[x²]-E[x]², 0)``
  clamp lints clean while the *unclamped* cancellation difference —
  whose interval admits small negatives — trips GL402 under a
  downstream ``rsqrt``/``log``.
- ``exp`` is treated as strictly positive (documented approximation:
  an attention row that is *entirely* mask ``-inf`` is the one NaN
  source this misses), so masked-softmax denominators divide clean.

The GL4xx family this computes (docs/ANALYSIS.md):

- **GL401** possible overflow-to-inf (exp of unbounded logits; proven
  out-of-dtype-range arithmetic).
- **GL402** possible invalid-domain op (log/sqrt/rsqrt reachable at a
  negative or zero value — the E[x²]−E[x]² pattern; division by a
  possibly-zero denominator — the unguarded ``amax`` scale).
- **GL403** bf16 under/overflow on a demoted edge (a convert to bf16,
  or an ``amp_bf16`` demotion candidate, whose proven range does not
  fit bfloat16) — the ``amp_bf16`` installation gate
  (:func:`bf16_fit`, ``analysis/passes.py``).
- **GL404** silent f64/weak-type promotion: an f64 value materializing
  from literals/consts in a program whose declared inputs are ≤f32 —
  the recurring hand-fixed bug class, machine-caught.
- **GL405** loss-scale advisory (:func:`loss_scale_diags`): the static
  bound on the smallest representable grad magnitude under the
  configured ``loss_scale`` and compute dtype, naming the suggested
  scale; an oversized static f16 scale that provably overflows every
  scaled grad is an error.

Entry points: :func:`analyze_ranges` over a ClosedJaxpr (inlining
pjit/remat/custom_* per call site like graftcost, widening scan/while
carries to a fixpoint), wired in as ``make_train_step(numerics=,
input_range=)`` / ``ServeEngine(numerics=)`` / ``MXTPU_NUMERICS``
(``step.range_report`` / ``engine.range_report``), the ``amp_bf16``
per-op gate, and the ``--ranges`` table printers in
``tools/graftpass.py`` / ``tools/graftlint.py``.
"""
from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from jax import core as jcore

from .diagnostics import Diagnostic, Severity

__all__ = ["VRange", "RangeReport", "analyze_ranges", "bf16_fit",
           "loss_scale_diags", "observed_range", "parse_range_arg",
           "BF16_MAX", "BF16_TINY_SUBNORMAL"]


def parse_range_arg(s) -> Tuple[float, float]:
    """Parse a CLI-style ``'lo,hi'`` range string — the ONE grammar
    behind every ``--input-range`` flag (tools/graftpass.py,
    tools/autotune.py).  Raises ``ValueError`` with a usable message
    for the CLIs to surface as a usage error."""
    lo, sep, hi = str(s).partition(",")
    try:
        if not sep:
            raise ValueError
        return (float(lo), float(hi))
    except ValueError:
        raise ValueError("expected 'lo,hi' (e.g. 0,1), got %r" % (s,))


def observed_range(value) -> Optional["VRange"]:
    """Observed extrema of one CONCRETE array as a :class:`VRange`
    seed — the ONE seeding discipline shared by the serving engine
    (frozen weights + warmup sample) and the ``--ranges`` CLIs.  A
    tensor containing non-finite values seeds ``nan=True`` with
    unknown bounds (the analysis stays sound); opaque/empty values
    seed nothing (None)."""
    try:
        arr = np.asarray(value)
    except Exception:  # noqa: BLE001 — device arrays: go through host
        import jax as _jax

        arr = np.asarray(_jax.device_get(value))
    if arr.dtype.kind not in ("f", "i", "u", "b") or arr.size == 0:
        return None
    a64 = arr.astype(np.float64, copy=False)
    if not np.isfinite(a64).all():
        return VRange(None, None, False, True)
    lo, hi = float(a64.min()), float(a64.max())
    return VRange(lo, hi, positive=lo > 0)


#: largest finite bfloat16 (same 8-bit exponent as f32, 7-bit mantissa)
BF16_MAX = 3.3895313892515355e38
#: smallest positive bfloat16 subnormal — f32 magnitudes below it flush
#: to zero when demoted
BF16_TINY_SUBNORMAL = 9.183549615799121e-41
#: exp-family ops whose overflow threshold is computed per output
#: dtype (f32 exp overflows at x ~ 88.7, f16 at ~ 11.09, f64 at ~ 709)
_EXP_FAMILY = ("exp", "exp2", "expm1", "cosh", "sinh")


def _exp_overflow_x(prim: str, dtype) -> float:
    """Input threshold past which ``prim`` overflows ``dtype``."""
    fm = _finite_max(dtype)
    if fm is None:
        fm = float(np.finfo(np.float32).max)
    ln_fm = math.log(fm)
    if prim == "exp2":
        return ln_fm / math.log(2.0)
    if prim in ("cosh", "sinh"):
        return ln_fm + math.log(2.0)  # cosh(x) ~ e^x / 2
    return ln_fm                      # exp / expm1


# ---------------------------------------------------------------------------
# the abstract value
# ---------------------------------------------------------------------------

@dataclass
class VRange:
    """Abstract value of one variable.  ``lo``/``hi`` of ``None`` mean
    *unknown but finite* on that side; ``±inf`` means provably can be
    infinite.  ``positive`` refines ``lo`` (strictly > 0); ``nan``
    means NaN is possible."""
    lo: Optional[float] = None
    hi: Optional[float] = None
    positive: bool = False
    nan: bool = False
    dtype: Any = None

    # -- predicates ----------------------------------------------------
    def max_abs(self) -> Optional[float]:
        """Largest possible magnitude, or None when unknown."""
        if self.lo is None or self.hi is None:
            return None
        return max(abs(self.lo), abs(self.hi))

    def may_be_negative(self) -> bool:
        return not self.positive and (self.lo is None or self.lo < 0)

    def may_be_zero(self) -> bool:
        if self.positive:
            return False  # strictly positive by refinement
        lo = self.lo
        hi = self.hi
        if lo is not None and lo > 0:
            return False
        if hi is not None and hi < 0:
            return False
        # unknown-unknown divisors are NOT flagged (a generic x/y would
        # drown the report); a *known* bound touching zero is
        return lo is not None or hi is not None

    def may_be_inf(self) -> bool:
        return (self.lo == -math.inf) or (self.hi == math.inf)

    def describe(self) -> str:
        def b(v, s):
            return s if v is None else "%.3g" % v

        s = "[%s, %s]" % (b(self.lo, "-?"), b(self.hi, "+?"))
        flags = []
        if self.positive:
            flags.append(">0")
        if self.nan:
            flags.append("nan?")
        return s + ("" if not flags else " " + ",".join(flags))


def _known(x: VRange) -> bool:
    return x.lo is not None and x.hi is not None


def _rng(lo, hi, positive=False, nan=False, dtype=None) -> VRange:
    return VRange(lo, hi, positive, nan, dtype)


def _unknown(dtype=None, nan=False, positive=False) -> VRange:
    return VRange(None, None, positive, nan, dtype)


def _join(a: VRange, b: VRange) -> VRange:
    lo = None if (a.lo is None or b.lo is None) else min(a.lo, b.lo)
    hi = None if (a.hi is None or b.hi is None) else max(a.hi, b.hi)
    return VRange(lo, hi, a.positive and b.positive, a.nan or b.nan,
                  a.dtype or b.dtype)


def _from_concrete(val, dtype=None) -> VRange:
    """VRange of a literal/const with a concrete value."""
    try:
        arr = np.asarray(val)
        if arr.dtype == np.bool_:
            return _rng(0.0, 1.0, dtype=arr.dtype)
        if arr.size == 0:
            return _rng(0.0, 0.0, dtype=arr.dtype)
        if arr.size > (1 << 22):       # don't scan huge consts
            return _unknown(dtype=arr.dtype)
        nan = bool(np.isnan(arr).any()) if arr.dtype.kind == "f" else False
        with np.errstate(invalid="ignore"):
            lo = float(np.nanmin(arr)) if not np.isnan(arr).all() \
                else math.nan
            hi = float(np.nanmax(arr)) if not np.isnan(arr).all() \
                else math.nan
        if math.isnan(lo) or math.isnan(hi):
            return _unknown(dtype=arr.dtype, nan=True)
        return _rng(lo, hi, positive=lo > 0, nan=nan, dtype=arr.dtype)
    except Exception:  # noqa: BLE001 — opaque consts stay unknown
        return _unknown(dtype=dtype)


def _default_for_aval(aval) -> VRange:
    """Conservative seed for an unannotated program input."""
    dt = getattr(aval, "dtype", None)
    if dt is None:
        return _unknown()
    try:
        dt = np.dtype(dt)
    except TypeError:
        return _unknown()  # extended dtypes (PRNG keys) stay opaque
    if dt == np.bool_:
        return _rng(0.0, 1.0, dtype=dt)
    if dt.kind in ("i", "u"):
        info = np.iinfo(dt)
        return _rng(float(info.min), float(info.max),
                    positive=info.min > 0, dtype=dt)
    # floats: unknown magnitude, assumed finite and non-NaN at entry
    return _unknown(dtype=dt)


def _finite_max(dtype) -> Optional[float]:
    """Largest finite value of a float dtype, or None for non-floats.
    ml_dtypes floats (bfloat16, float8) have numpy kind 'V' and
    ``np.finfo`` rejects them ("not inexact") — they go through
    ``ml_dtypes.finfo``; a bare kind-check would silently disable the
    bf16 overflow clamp (the GL403 convert check)."""
    try:
        dt = np.dtype(dtype)
    except TypeError:
        return None
    if dt.kind == "f":
        return float(np.finfo(dt).max)
    try:
        import ml_dtypes

        return float(ml_dtypes.finfo(dt).max)
    except Exception:  # noqa: BLE001 — ints/bools/opaque dtypes
        return None


def bf16_fit(vr: VRange) -> Tuple[bool, str]:
    """Does a value with this range survive demotion to bfloat16?

    Unknown bounds fit (bf16 shares f32's exponent range — only a
    *proven* excursion past it is a hazard); a known magnitude above
    ``BF16_MAX`` overflows to inf, and a known nonzero magnitude
    entirely below the smallest bf16 subnormal flushes to zero.
    Returns ``(ok, reason)``."""
    m = vr.max_abs()
    if m is None:
        return True, ""
    if m > BF16_MAX:
        return False, ("operand range %s exceeds the bf16 finite max "
                       "%.3g — demotion overflows to inf"
                       % (vr.describe(), BF16_MAX))
    if 0.0 < m < BF16_TINY_SUBNORMAL:
        return False, ("operand magnitudes (at most %.3g) sit entirely "
                       "below the smallest bf16 subnormal %.3g — "
                       "demotion flushes the tensor to zero"
                       % (m, BF16_TINY_SUBNORMAL))
    return True, ""


# ---------------------------------------------------------------------------
# interval arithmetic helpers (None = unknown-finite)
# ---------------------------------------------------------------------------

def _n_add(a: Optional[float], b: Optional[float]) -> Optional[float]:
    if a is None or b is None:
        # unknown + anything-finite-or-unknown = unknown; an infinite
        # side dominates even an unknown one
        if a in (math.inf, -math.inf):
            return a
        if b in (math.inf, -math.inf):
            return b
        return None
    s = a + b
    return None if math.isnan(s) else s


def _n_mul_candidates(a: VRange, b: VRange) -> Tuple[Optional[float],
                                                     Optional[float]]:
    if not _known(a) or not _known(b):
        # magnitudes unknown: result unknown-finite (the absorbing rule
        # that keeps deep products from compounding to fake infinities);
        # a genuinely-infinite operand still yields unknown, carried by
        # the caller's may_be_inf handling
        return None, None
    cands = []
    for x in (a.lo, a.hi):
        for y in (b.lo, b.hi):
            with np.errstate(invalid="ignore", over="ignore"):
                v = x * y
            cands.append(0.0 if math.isnan(v) else v)
    return min(cands), max(cands)


def _clamp_overflow(vr: VRange, dtype) -> Tuple[VRange, bool]:
    """Known bounds past the output dtype's finite max become ±inf.
    Returns (possibly-widened range, overflowed?)."""
    fm = _finite_max(dtype)
    if fm is None:
        return vr, False
    over = False
    lo, hi = vr.lo, vr.hi
    if hi is not None and hi > fm:
        hi, over = math.inf, True
    if lo is not None and lo < -fm:
        lo, over = -math.inf, True
    if over:
        return VRange(lo, hi, vr.positive, vr.nan, dtype), True
    return vr, False


# ---------------------------------------------------------------------------
# the report
# ---------------------------------------------------------------------------

@dataclass
class RangeReport:
    """One program's range analysis: the per-var table raw material,
    hazard sites and the aggregated GL4xx diagnostics."""
    rows: List[Dict[str, Any]] = field(default_factory=list)
    sites: Dict[str, List[Dict[str, Any]]] = field(default_factory=dict)
    diagnostics: List[Diagnostic] = field(default_factory=list)
    meta: Dict[str, Any] = field(default_factory=dict)
    #: top-level Var -> VRange (the amp gate's lookup map); not
    #: serialized
    var_ranges: Dict[Any, VRange] = field(default_factory=dict)

    def by_code(self, code: str) -> List[Diagnostic]:
        return [d for d in self.diagnostics if d.code == code]

    @property
    def errors(self) -> List[Diagnostic]:
        return [d for d in self.diagnostics
                if d.severity >= Severity.ERROR]

    def to_dict(self) -> dict:
        return {"version": 1,
                "rows": list(self.rows),
                "sites": {k: list(v) for k, v in sorted(self.sites.items())},
                "diagnostics": [d.to_dict() for d in self.diagnostics],
                "meta": dict(self.meta)}

    def format(self, max_rows: int = 48,
               include_diagnostics: bool = True) -> str:
        """The per-var range table (tools/graftpass.py --ranges).
        ``include_diagnostics=False`` prints rows only — for callers
        that already rendered the diagnostics through their own
        (filtered) report."""
        lines = ["%-28s %-12s %-14s %-22s %s"
                 % ("var", "kind", "dtype/shape", "range", "flags")]
        for r in self.rows[:max_rows]:
            flags = []
            if r.get("positive"):
                flags.append(">0")
            if r.get("nan"):
                flags.append("nan?")
            if r.get("inf"):
                flags.append("inf?")
            lines.append("%-28s %-12s %-14s %-22s %s"
                         % (str(r.get("name", "?"))[:28], r.get("kind", ""),
                            "%s%s" % (r.get("dtype", "?"),
                                      list(r.get("shape", ()))),
                            r.get("range", "?"), ",".join(flags)))
        if len(self.rows) > max_rows:
            lines.append("... (%d more rows)" % (len(self.rows) - max_rows))
        if include_diagnostics:
            for d in self.diagnostics:
                lines.append(d.format())
        return "\n".join(lines)


# ---------------------------------------------------------------------------
# the interpreter
# ---------------------------------------------------------------------------

#: call-like primitives whose bodies are walked inline (per call site,
#: like graftcost: a pjit boundary has no numeric meaning)
_INLINE = {"pjit", "closed_call", "core_call", "xla_call", "named_call",
           "remat", "remat2", "checkpoint", "custom_jvp_call",
           "custom_vjp_call", "custom_jvp_call_jaxpr",
           "custom_vjp_call_jaxpr", "custom_lin"}

#: ops through which the max-subtraction / provenance chase sees
_TRANSPARENT = {"stop_gradient", "broadcast_in_dim", "reshape", "squeeze",
                "expand_dims", "copy", "convert_element_type",
                "transpose"}

_PASS_THROUGH = {"reshape", "transpose", "broadcast_in_dim", "squeeze",
                 "expand_dims", "rev", "slice", "dynamic_slice",
                 "stop_gradient", "copy", "real", "reduce_precision",
                 "gather", "take", "take_along_axis", "pad",
                 "dynamic_update_slice", "concatenate", "tie_in",
                 "optimization_barrier"}

#: bounded elementwise maps: prim -> (lo, hi, positive)
_BOUNDED = {"tanh": (-1.0, 1.0, False), "sin": (-1.0, 1.0, False),
            "cos": (-1.0, 1.0, False), "erf": (-1.0, 1.0, False),
            "logistic": (0.0, 1.0, True), "erfc": (0.0, 2.0, True)}


class _Site:
    """One hazard site (pre-aggregation)."""
    __slots__ = ("code", "prim", "where", "detail", "severity")

    def __init__(self, code, prim, where, detail,
                 severity=Severity.ERROR):
        self.code, self.prim, self.where = code, prim, where
        self.detail, self.severity = detail, severity


class _Interp:
    def __init__(self, axis_sizes: Optional[Dict[str, int]] = None):
        #: named-axis sizes (caller-seeded; shard_map meshes extend it
        #: for their bodies) — the psum-family transfer's multiplier
        self.axis_sizes: Dict[str, int] = dict(axis_sizes or {})
        self.sites: List[_Site] = []
        #: does any DECLARED program input (top-level invar) carry f64?
        #: — only then is the program legitimately-f64 and GL404 quiet
        self.f64_inputs = False
        #: ids of f64 constvars: closure-captured f64 arrays are GL404
        #: *origins* (like f64 literals), never a license for f64
        self.f64_consts: set = set()

    # -- provenance chase ---------------------------------------------
    @staticmethod
    def _chase(var, producers, depth=12):
        """Follow ``var`` back through value-transparent ops (and
        ``max``/``min`` against an infinite literal — the jnp.max
        ``initial=`` idiom)."""
        while isinstance(var, jcore.Var) and depth > 0:
            eqn = producers.get(id(var))
            if eqn is None:
                return var, None
            prim = eqn.primitive.name
            if prim in _TRANSPARENT and eqn.invars:
                var = eqn.invars[0]
            elif prim in ("max", "min") and len(eqn.invars) == 2:
                lits = [v for v in eqn.invars
                        if isinstance(v, jcore.Literal)]
                others = [v for v in eqn.invars
                          if not isinstance(v, jcore.Literal)]
                if len(lits) == 1 and len(others) == 1 \
                        and np.isinf(np.asarray(lits[0].val)).all():
                    var = others[0]
                else:
                    return var, eqn
            else:
                return var, eqn
            depth -= 1
        return var, None

    def _is_max_of(self, sub_rhs, minuend, producers):
        """True when ``sub_rhs`` chases to ``reduce_max(minuend)`` (or
        ``reduce_max`` of something ``minuend`` itself chases to) —
        the softmax max-subtraction pattern."""
        root, eqn = self._chase(sub_rhs, producers)
        if eqn is None or eqn.primitive.name not in ("reduce_max", "max"):
            return False
        if eqn.primitive.name == "max":
            # max(-inf, reduce_max(x)) already unwrapped by _chase;
            # a residual two-var max is not the pattern
            return False
        operand = eqn.invars[0]
        m_root, _ = self._chase(minuend, producers)
        o_root, _ = self._chase(operand, producers)
        return o_root is m_root or operand is minuend

    # -- one equation --------------------------------------------------
    def eval_eqn(self, eqn, ins: List[VRange], producers,
                 where: str) -> List[VRange]:
        prim = eqn.primitive.name
        out_avals = [getattr(v, "aval", None) for v in eqn.outvars]
        odt = getattr(out_avals[0], "dtype", None) if out_avals else None

        def done(vr: VRange, flag_overflow=True) -> List[VRange]:
            vr.dtype = odt
            if flag_overflow and vr.may_be_inf():
                was_inf = any(x.may_be_inf() for x in ins)
                if not was_inf:
                    self.sites.append(_Site(
                        "GL401", prim, where,
                        "%s of %s can overflow to inf"
                        % (prim, ins[0].describe() if ins else "?")))
            return [vr] + [_unknown(getattr(a, "dtype", None))
                           for a in out_avals[1:]]

        nan = any(x.nan for x in ins)
        if prim in _PASS_THROUGH:
            base = ins[0] if ins else _unknown()
            out = VRange(base.lo, base.hi, base.positive, nan, odt)
            if prim in ("pad", "dynamic_update_slice", "concatenate"):
                # pad's padding VALUE is operand 1 — joining all
                # operands covers it (no blanket [0,0] join: a pad of
                # positives with a positive fill must stay positive)
                out = _join(out, _join_all(ins)) if len(ins) > 1 else out
            return [out] + [_unknown(getattr(a, "dtype", None))
                            for a in out_avals[1:]]

        if prim in ("add", "add_any", "sub", "sub_any"):
            a, b = ins[0], ins[1]
            if prim.startswith("sub"):
                if self._is_max_of(eqn.invars[1], eqn.invars[0], producers):
                    # x - max(x) <= 0 (and well-defined: max >= x
                    # elementwise, so the inf-inf NaN of a fully-masked
                    # row is the documented miss)
                    return done(VRange(None, 0.0, False, False, odt),
                                flag_overflow=False)
                lo = _n_add(a.lo, None if b.hi is None else -b.hi)
                hi = _n_add(a.hi, None if b.lo is None else -b.lo)
                pos = False
            else:
                lo = _n_add(a.lo, b.lo)
                hi = _n_add(a.hi, b.hi)
                pos = (a.positive and not b.may_be_negative()) or \
                      (b.positive and not a.may_be_negative())
            # inf + (-inf) / inf - inf: NaN possible
            if (a.may_be_inf() or b.may_be_inf()):
                nan = True
            vr, _ = _clamp_overflow(VRange(lo, hi, pos, nan, odt), odt)
            return done(vr)

        if prim == "mul":
            a, b = ins[0], ins[1]
            if len(eqn.invars) == 2 and eqn.invars[0] is eqn.invars[1] \
                    and isinstance(eqn.invars[0], jcore.Var):
                m = a.max_abs()
                vr, _ = _clamp_overflow(
                    VRange(0.0, None if m is None else m * m, False,
                           nan, odt), odt)
                return done(vr)
            lo, hi = _n_mul_candidates(a, b)
            # sign awareness survives unknown magnitudes: a product of
            # non-negatives is non-negative (beta2*var + (1-beta2)*g**2
            # must keep its lo=0 for the adam sqrt to lint clean)
            a_nn = a.positive or (a.lo is not None and a.lo >= 0)
            b_nn = b.positive or (b.lo is not None and b.lo >= 0)
            if lo is None and a_nn and b_nn:
                lo = 0.0 if (a.lo is None or b.lo is None) \
                    else a.lo * b.lo
            pos = a.positive and b.positive
            if (a.may_be_inf() and b.may_be_zero()) or \
                    (b.may_be_inf() and a.may_be_zero()):
                nan = True
            vr, _ = _clamp_overflow(VRange(lo, hi, pos, nan, odt), odt)
            return done(vr)

        if prim in ("div", "rem"):
            a, b = ins[0], ins[1]
            if prim == "div" and b.may_be_zero():
                self.sites.append(_Site(
                    "GL402", prim, where,
                    "division by a possibly-zero denominator %s"
                    % b.describe()))
                nan = True
            if b.lo is not None and b.lo > 0:
                # strictly-positive divisor with a known floor: bounds
                # survive per-side even when the other side is unknown
                # (mean = sum/n must keep the sum's lo=0)
                if a.lo is None:
                    lo = None
                elif a.lo >= 0:
                    lo = 0.0 if b.hi is None else a.lo / b.hi
                else:
                    lo = a.lo / b.lo
                if a.hi is None:
                    hi = None
                elif a.hi >= 0:
                    hi = a.hi / b.lo
                else:
                    hi = 0.0 if b.hi is None else a.hi / b.hi
                vr = VRange(lo, hi, a.positive and b.positive, nan, odt)
            else:
                a_nn = a.positive or (a.lo is not None and a.lo >= 0)
                vr = VRange(0.0 if (a_nn and b.positive) else None,
                            None, a.positive and b.positive, nan, odt)
            return done(vr)

        if prim == "neg":
            a = ins[0]
            return done(VRange(None if a.hi is None else -a.hi,
                               None if a.lo is None else -a.lo,
                               False, nan, odt))

        if prim in ("abs", "sign"):
            a = ins[0]
            if prim == "sign":
                return done(VRange(-1.0, 1.0, a.positive, nan, odt))
            m = a.max_abs()
            lo = 0.0
            if a.positive and a.lo is not None:
                lo = abs(a.lo)
            return done(VRange(lo, m, a.positive, nan, odt))

        if prim in ("max", "min", "clamp"):
            if prim == "clamp":
                lo_b, x, hi_b = ins[0], ins[1], ins[2]
                lo = x.lo if lo_b.lo is None else (
                    lo_b.lo if x.lo is None else max(x.lo, lo_b.lo))
                hi = x.hi if hi_b.hi is None else (
                    hi_b.hi if x.hi is None else min(x.hi, hi_b.hi))
                return done(VRange(lo, hi, x.positive or
                                   (lo_b.positive), nan, odt))
            a, b = ins[0], ins[1]
            if prim == "max":
                lo = a.lo if b.lo is None else (
                    b.lo if a.lo is None else max(a.lo, b.lo))
                # a known non-negative arm clamps from below even when
                # the other arm is unknown (the BN maximum(.., 0) guard)
                if lo is None:
                    for arm in (a, b):
                        if arm.lo is not None and arm.lo >= 0:
                            lo = arm.lo
                hi = None if (a.hi is None or b.hi is None) \
                    else max(a.hi, b.hi)
                pos = a.positive or b.positive or \
                    (lo is not None and lo > 0)
            else:
                hi = a.hi if b.hi is None else (
                    b.hi if a.hi is None else min(a.hi, b.hi))
                lo = None if (a.lo is None or b.lo is None) \
                    else min(a.lo, b.lo)
                pos = a.positive and b.positive
            return done(VRange(lo, hi, pos, nan, odt))

        if prim in _EXP_FAMILY:
            a = ins[0]
            thr = _exp_overflow_x(prim, odt)
            hi_in = a.hi if prim != "cosh" else a.max_abs()
            overflow = hi_in is None or hi_in > thr
            if prim == "sinh" and not overflow:
                overflow = a.lo is None or a.lo < -thr
            if overflow:
                self.sites.append(_Site(
                    "GL401", prim, where,
                    "%s of operand range %s overflows %s past x ~ %.3g "
                    "(inf in the program)"
                    % (prim, a.describe(),
                       str(odt) if odt is not None else "f32", thr)))
            # the specific site above is the one GL401 record for this
            # eqn; flag_overflow=False keeps done() from adding a
            # second, generic copy of it
            lo_out: Optional[float]
            if prim in ("exp", "exp2"):
                base = math.e if prim == "exp" else 2.0
                lo_out = 0.0 if a.lo is None else \
                    _safe_pow(base, a.lo)
                hi_out = math.inf if overflow else _safe_pow(base, hi_in)
                return done(VRange(lo_out, hi_out, True, nan, odt),
                            flag_overflow=False)
            if prim == "expm1":
                lo_out = -1.0 if a.lo is None else math.expm1(min(a.lo,
                                                                  700.0))
                hi_out = math.inf if overflow else math.expm1(hi_in)
                return done(VRange(lo_out, hi_out, False, nan, odt),
                            flag_overflow=False)
            return done(VRange(None, math.inf if overflow else None,
                               prim == "cosh", nan, odt),
                        flag_overflow=False)

        if prim in ("log", "log1p", "log2"):
            a = ins[0]
            shift = 1.0 if prim == "log1p" else 0.0
            bad = (a.lo is None and not a.positive) or \
                  (a.lo is not None and a.lo + shift <= 0
                   and not (a.positive and shift == 0))
            if bad:
                self.sites.append(_Site(
                    "GL402", prim, where,
                    "%s of operand range %s reachable at <= %g (NaN / "
                    "-inf in the program)" % (prim, a.describe(), -shift)))
                nan = True
            return done(VRange(None, None, False, nan, odt),
                        flag_overflow=False)

        if prim in ("sqrt", "rsqrt", "cbrt"):
            a = ins[0]
            if prim != "cbrt":
                neg = a.may_be_negative()
                zero_hazard = prim == "rsqrt" and a.may_be_zero() \
                    and not a.positive
                if neg or zero_hazard:
                    self.sites.append(_Site(
                        "GL402", prim, where,
                        "%s of operand range %s reachable at %s"
                        % (prim, a.describe(),
                           "< 0 (NaN)" if neg else "0 (inf)")))
                    nan = nan or neg
            if prim == "sqrt":
                lo = math.sqrt(a.lo) if (a.lo is not None and a.lo > 0) \
                    else 0.0
                hi = None if a.hi is None or a.hi < 0 \
                    else math.sqrt(max(a.hi, 0.0))
                return done(VRange(lo, hi, a.positive, nan, odt))
            return done(VRange(None, None, prim == "rsqrt" and a.positive,
                               nan, odt), flag_overflow=False)

        if prim == "integer_pow":
            a = ins[0]
            y = int(eqn.params.get("y", 1))
            if y < 0 and a.may_be_zero():
                self.sites.append(_Site(
                    "GL402", prim, where,
                    "x**%d with base range %s reachable at 0"
                    % (y, a.describe())))
                nan = True
            if y >= 0 and y % 2 == 0:
                m = a.max_abs()
                vr = VRange(0.0, None if m is None else _safe_pow(m, y),
                            a.positive, nan, odt)
            elif y >= 0:
                lo = None if a.lo is None else _safe_pow_signed(a.lo, y)
                hi = None if a.hi is None else _safe_pow_signed(a.hi, y)
                vr = VRange(lo, hi, a.positive, nan, odt)
            else:
                vr = VRange(None, None, a.positive, nan, odt)
            vr, _ = _clamp_overflow(vr, odt)
            return done(vr)

        if prim == "pow":
            a, b = ins[0], ins[1]
            if a.may_be_negative():
                # fractional powers of negatives NaN; stay quiet unless
                # the exponent is known non-integer? conservative: nan
                nan = True
            pos = a.positive
            if _known(a) and _known(b) and a.lo >= 0:
                cands = [_safe_pow(x, y) for x in (a.lo, a.hi)
                         for y in (b.lo, b.hi)]
                vr = VRange(min(cands), max(cands), pos, nan, odt)
            else:
                vr = VRange(0.0 if a.positive or (a.lo is not None
                                                  and a.lo >= 0)
                            else None, None, pos, nan, odt)
            vr, over = _clamp_overflow(vr, odt)
            return done(vr)

        if prim in ("reduce_sum", "cumsum"):
            a = ins[0]
            n = _red_count(eqn, prim)
            lo = None if a.lo is None else a.lo * n
            hi = None if a.hi is None else a.hi * n
            vr, _ = _clamp_overflow(
                VRange(lo, hi, a.positive, nan, odt), odt)
            return done(vr)

        if prim in ("reduce_max", "reduce_min", "cummax", "cummin",
                    "sort"):
            a = ins[0]
            return done(VRange(a.lo, a.hi, a.positive, nan, odt))

        if prim in ("reduce_prod", "cumprod"):
            return done(_unknown(odt, nan=nan))

        if prim in ("reduce_and", "reduce_or", "reduce_xor", "argmax",
                    "argmin", "top_k", "eq", "ne", "lt", "le", "gt",
                    "ge", "and", "or", "xor", "not", "is_finite",
                    "population_count", "clz", "iota", "axis_index"):
            if prim == "iota":
                n = max(int(np.prod(getattr(out_avals[0], "shape", (1,))
                                    or (1,))), 1)
                return done(VRange(0.0, float(n - 1), False, False, odt))
            if prim in ("argmax", "argmin", "top_k"):
                return done(_rng(0.0, None, dtype=odt))
            if prim == "axis_index":
                ax = eqn.params.get("axis_name")
                size = self.axis_sizes.get(ax)
                return done(VRange(0.0, None if size is None
                                   else float(size) - 1, False, False,
                                   odt), flag_overflow=False)
            if prim in ("population_count", "clz"):
                bits = np.dtype(odt).itemsize * 8 if odt is not None \
                    else 64
                return done(VRange(0.0, float(bits), False, False, odt),
                            flag_overflow=False)
            if prim in ("and", "or", "xor", "not", "reduce_and",
                        "reduce_or", "reduce_xor") \
                    and not (odt is not None
                             and np.dtype(odt) == np.bool_):
                # integer bitwise ops: a [0,1] "proven" bound would be
                # a lie — fall back to the dtype range
                return done(_default_for_aval(out_avals[0]),
                            flag_overflow=False)
            # boolean logic / comparisons / is_finite
            return done(VRange(0.0, 1.0, False, False, odt),
                        flag_overflow=False)

        if prim in ("dot_general", "conv_general_dilated"):
            a, b = ins[0], ins[1]
            k = _contraction_len(eqn)
            am, bm = a.max_abs(), b.max_abs()
            if am is None or bm is None:
                vr = VRange(None, None, False, nan, odt)
            else:
                m = am * bm * k
                vr = VRange(-m, m, False, nan, odt)
                vr, _ = _clamp_overflow(vr, odt)
            return done(vr)

        if prim == "select_n":
            cases = ins[1:]
            if not cases:
                return done(_unknown(odt, nan=nan))
            out = cases[0]
            for c in cases[1:]:
                out = _join(out, c)
            # the predicate's nan does not poison a select of clean arms
            out = VRange(out.lo, out.hi, out.positive,
                         any(c.nan for c in cases), odt)
            return done(out, flag_overflow=False)

        if prim == "convert_element_type":
            a = ins[0]
            src = getattr(getattr(eqn.invars[0], "aval", None), "dtype",
                          None)
            vr, over = _clamp_overflow(
                VRange(a.lo, a.hi, a.positive, nan, odt), odt)
            if over and _dtype_name(odt) == "bfloat16":
                self.sites.append(_Site(
                    "GL403", prim, where,
                    "convert %s -> bfloat16 of a value with proven "
                    "range %s — past the bf16 finite max %.3g, the "
                    "demoted edge is inf" % (src, a.describe(),
                                             BF16_MAX)))
            m = a.max_abs()
            if m is not None and 0.0 < m < BF16_TINY_SUBNORMAL \
                    and _dtype_name(odt) == "bfloat16":
                self.sites.append(_Site(
                    "GL403", prim, where,
                    "convert %s -> bfloat16 of magnitudes at most %.3g "
                    "— entirely below the smallest bf16 subnormal, the "
                    "demoted edge flushes to zero" % (src, m)))
            return done(vr, flag_overflow=over)

        if prim in ("erf_inv", "atanh"):
            # ±inf only at the exact boundary of the domain (measure
            # zero through jax.random's open intervals): unknown-finite
            return done(_unknown(odt, nan=nan), flag_overflow=False)

        if prim in _BOUNDED:
            lo, hi, pos = _BOUNDED[prim]
            return done(VRange(lo, hi, pos, nan, odt))

        if prim in ("reduce_window_max", "reduce_window_min"):
            a = ins[0]
            return done(VRange(a.lo, a.hi, a.positive, nan, odt))
        if prim == "reduce_window_sum":
            return done(_unknown(odt, nan=nan))

        if prim in ("psum", "psum2", "pmax", "pmin", "all_gather",
                    "reduce_scatter", "psum_scatter", "ppermute",
                    "pshuffle", "all_to_all", "pbroadcast"):
            a = ins[0] if ins else _unknown()
            if prim in ("psum", "psum2", "reduce_scatter",
                        "psum_scatter"):
                # a sum of n per-device terms: bounds scale by the
                # axis size when it is known (a [0,1] value psummed
                # over an 8-way axis is [0,8]); unknown axes absorb
                axes = eqn.params.get("axes",
                                      eqn.params.get("axis_name"))
                axes = axes if isinstance(axes, (tuple, list)) \
                    else (axes,)
                n = 1.0
                for ax in axes:
                    size = self.axis_sizes.get(ax)
                    if size is None:
                        n = None
                        break
                    n *= float(size)
                if n is None:
                    return done(_unknown(odt, nan=nan,
                                         positive=a.positive))
                lo = None if a.lo is None else a.lo * n
                hi = None if a.hi is None else a.hi * n
                vr, _ = _clamp_overflow(
                    VRange(lo, hi, a.positive, nan, odt), odt)
                return done(vr)
            return done(VRange(a.lo, a.hi, a.positive, nan, odt))

        if prim in ("random_bits", "threefry2x32", "rng_bit_generator",
                    "random_wrap", "random_unwrap", "random_split",
                    "random_seed", "random_fold_in"):
            return [_default_for_aval(a) for a in out_avals]

        if prim in ("scatter", "scatter_add", "scatter-add",
                    "select_and_scatter_add", "select_and_gather_add"):
            out = _join_all(ins) if ins else _unknown()
            out = _join(out, _rng(0.0, 0.0))  # scatter init zeros
            out.dtype = odt
            out.nan = nan
            return [out] + [_unknown(getattr(a, "dtype", None))
                            for a in out_avals[1:]]

        if prim == "square":
            a = ins[0]
            m = a.max_abs()
            vr = VRange(0.0, None if m is None else m * m, a.positive,
                        nan, odt)
            vr, _ = _clamp_overflow(vr, odt)
            return done(vr)

        # anything else: unknown-finite, nan-propagating
        return [_unknown(getattr(a, "dtype", None), nan=nan)
                for a in out_avals] or [_unknown(nan=nan)]

    # -- one jaxpr ------------------------------------------------------
    def walk(self, jaxpr, env: Dict[Any, VRange], consts: Sequence[Any],
             where: str = "jaxpr", depth: int = 0,
             collect: bool = True) -> List[VRange]:
        """Forward pass over one (open) jaxpr.  ``env`` must already
        bind ``jaxpr.invars``; constvars are bound from ``consts``
        (concrete values when available)."""
        producers: Dict[int, Any] = {}

        for cv, cval in zip(jaxpr.constvars, consts):
            env[cv] = _from_concrete(cval,
                                     getattr(cv.aval, "dtype", None))
        for cv in jaxpr.constvars:
            if _dtype_is_f64(getattr(cv.aval, "dtype", None)):
                # an f64 CONST is a promotion origin, not a license:
                # its first consumer is the GL404 site
                self.f64_consts.add(id(cv))
        for cv in jaxpr.constvars[len(consts):]:
            env[cv] = _default_for_aval(cv.aval)

        def read(v) -> VRange:
            if isinstance(v, jcore.Literal):
                return _from_concrete(v.val,
                                      getattr(v.aval, "dtype", None))
            return env.get(v) or _default_for_aval(v.aval)

        sites_enabled = collect
        for n, eqn in enumerate(jaxpr.eqns):
            prim = eqn.primitive.name
            w = "%s[%d] %s" % (where, n, prim)
            ins = [read(v) for v in eqn.invars]
            # GL404: an f64 output materializing with no non-literal
            # f64 operand — the value was promoted by a literal/const
            if sites_enabled:
                self._check_f64(eqn, w)
            if prim in _INLINE and depth < 24:
                outs = self._call(eqn, ins, w, depth, collect)
            elif prim == "scan":
                outs = self._scan(eqn, ins, w, depth, collect)
            elif prim == "while":
                outs = self._while(eqn, ins, w, depth, collect)
            elif prim == "cond":
                outs = self._cond(eqn, ins, w, depth, collect)
            elif prim == "shard_map":
                outs = self._shard_map(eqn, ins, w, depth, collect)
            else:
                n_sites = len(self.sites)
                outs = self.eval_eqn(eqn, ins, producers, w)
                if not sites_enabled:
                    del self.sites[n_sites:]
            for v, o in zip(eqn.outvars, outs):
                if isinstance(v, jcore.Var):
                    env[v] = o
                    producers[id(v)] = eqn
        return [read(v) for v in jaxpr.outvars]

    def _check_f64(self, eqn, where):
        outs_f64 = [v for v in eqn.outvars
                    if _dtype_is_f64(getattr(getattr(v, "aval", None),
                                             "dtype", None))]
        if not outs_f64 or self.f64_inputs:
            return
        has_var_f64 = any(
            isinstance(v, jcore.Var) and id(v) not in self.f64_consts
            and _dtype_is_f64(getattr(v.aval, "dtype", None))
            for v in eqn.invars)
        if has_var_f64:
            # fed by an already-f64 value (itself flagged at its own
            # origin): one site per promotion chain, not per consumer
            return
        lit_f64 = [v for v in eqn.invars
                   if isinstance(v, jcore.Literal)
                   and _dtype_is_f64(getattr(v.aval, "dtype", None))]
        const_f64 = any(isinstance(v, jcore.Var)
                        and id(v) in self.f64_consts
                        for v in eqn.invars)
        if lit_f64:
            via = ("an f64 literal operand (%s)"
                   % np.asarray(lit_f64[0].val).ravel()[:1])
        elif const_f64:
            via = "a closure-captured f64 const operand"
        else:
            via = "weak-type promotion of its operands"
        self.sites.append(_Site(
            "GL404", eqn.primitive.name, where,
            "%s produces float64 via %s although no program input is "
            "f64 — a silent promotion under the package-wide x64 flag "
            "(the beta**int / np.float64-scale bug class)"
            % (eqn.primitive.name, via)))

    # -- control flow ---------------------------------------------------
    def _bodies(self, params):
        for v in params.values():
            vs = v if isinstance(v, (tuple, list)) else (v,)
            for u in vs:
                if isinstance(u, jcore.ClosedJaxpr):
                    yield u
                elif isinstance(u, jcore.Jaxpr):
                    yield jcore.ClosedJaxpr(u, ())

    def _call(self, eqn, ins, where, depth, collect):
        for body in self._bodies(eqn.params):
            j = body.jaxpr
            if len(j.invars) != len(ins):
                continue
            env = dict(zip(j.invars, ins))
            outs = self.walk(j, env, body.consts, where, depth + 1,
                             collect)
            if len(outs) == len(eqn.outvars):
                return outs
        return [_unknown(getattr(getattr(v, "aval", None), "dtype", None))
                for v in eqn.outvars]

    def _scan(self, eqn, ins, where, depth, collect):
        p = eqn.params
        body = p["jaxpr"]
        j = body.jaxpr
        n_consts = int(p.get("num_consts", 0))
        n_carry = int(p.get("num_carry", 0))
        consts_in = ins[:n_consts]
        carry = list(ins[n_consts:n_consts + n_carry])
        xs = ins[n_consts + n_carry:]
        # xs enter the body one slice at a time: same range.  Settle
        # the carry SILENTLY first (join per iteration; anything still
        # growing after 3 passes widens to unknown-finite), then run
        # ONE diagnostic walk with the settled carry — hazards driven
        # by a growing carry (exp of a doubling value) are seen at the
        # widened bounds, and the ys ranges come from that same sound
        # walk, never from an unconverged intermediate iterate.
        for it in range(3):
            env = dict(zip(j.invars, consts_in + carry + xs))
            outs = self.walk(j, env, body.consts, where, depth + 1,
                             collect=False)
            new_carry = [_join(c, o) for c, o in zip(carry, outs[:n_carry])]
            if all(_same_range(c, nc)
                   for c, nc in zip(carry, new_carry)):
                carry = new_carry
                break
            if it == 2:
                carry = [
                    VRange(None, None, c.positive and nc.positive,
                           c.nan or nc.nan, nc.dtype)
                    if not _same_range(c, nc) else nc
                    for c, nc in zip(carry, new_carry)]
            else:
                carry = new_carry
        env = dict(zip(j.invars, consts_in + carry + xs))
        outs = self.walk(j, env, body.consts, where, depth + 1, collect)
        carry = [_join(c, o) for c, o in zip(carry, outs[:n_carry])]
        return carry + outs[n_carry:]

    def _while(self, eqn, ins, where, depth, collect):
        p = eqn.params
        body = p.get("body_jaxpr")
        n_c = int(p.get("body_nconsts", 0))
        cn = int(p.get("cond_nconsts", 0))
        carry = [VRange(None, None, False, c.nan, c.dtype)
                 for c in ins[cn + n_c:]]
        if body is not None:
            j = body.jaxpr
            env = dict(zip(j.invars, ins[cn:cn + n_c] + carry))
            outs = self.walk(j, env, body.consts, where, depth + 1,
                             collect)
            return [_join(c, o) for c, o in zip(carry, outs)]
        return carry

    def _cond(self, eqn, ins, where, depth, collect):
        branches = eqn.params.get("branches", ())
        opnds = ins[1:]
        joined: Optional[List[VRange]] = None
        for br in branches:
            closed = br if isinstance(br, jcore.ClosedJaxpr) \
                else jcore.ClosedJaxpr(br, ())
            j = closed.jaxpr
            if len(j.invars) != len(opnds):
                continue
            env = dict(zip(j.invars, opnds))
            outs = self.walk(j, env, closed.consts, where, depth + 1,
                             collect)
            joined = outs if joined is None else \
                [_join(a, b) for a, b in zip(joined, outs)]
        return joined or [_unknown(getattr(getattr(v, "aval", None),
                                           "dtype", None))
                          for v in eqn.outvars]

    def _shard_map(self, eqn, ins, where, depth, collect):
        body = eqn.params.get("jaxpr")
        if body is None:
            return [_unknown() for _ in eqn.outvars]
        closed = body if isinstance(body, jcore.ClosedJaxpr) \
            else jcore.ClosedJaxpr(body, ())
        j = closed.jaxpr
        if len(j.invars) != len(ins):
            return [_unknown() for _ in eqn.outvars]
        env = dict(zip(j.invars, ins))
        mesh = eqn.params.get("mesh")
        saved = self.axis_sizes
        if mesh is not None:
            self.axis_sizes = dict(saved)
            self.axis_sizes.update({str(k): int(v)
                                    for k, v in dict(mesh.shape).items()})
        try:
            return self.walk(j, env, closed.consts, where, depth + 1,
                             collect)
        finally:
            self.axis_sizes = saved


def _join_all(ins: Sequence[VRange]) -> VRange:
    out = ins[0]
    for x in ins[1:]:
        out = _join(out, x)
    return out


def _same_range(a: VRange, b: VRange) -> bool:
    return a.lo == b.lo and a.hi == b.hi and a.positive == b.positive \
        and a.nan == b.nan


def _safe_pow(base: float, y: float) -> float:
    try:
        with np.errstate(over="ignore"):
            v = math.pow(base, y)
    except OverflowError:
        return math.inf
    except (ValueError, ZeroDivisionError):
        return math.inf  # 0**-n / domain corner: treat as unbounded
    return v


def _safe_pow_signed(x: float, y: int) -> float:
    s = -1.0 if (x < 0 and y % 2 == 1) else 1.0
    return s * _safe_pow(abs(x), y)


def _dtype_name(dt) -> str:
    try:
        return np.dtype(dt).name
    except TypeError:
        return str(dt)


def _dtype_is_f64(dt) -> bool:
    try:
        return np.dtype(dt) == np.float64
    except TypeError:
        return False


def _red_count(eqn, prim) -> float:
    if prim == "cumsum":
        axis = eqn.params.get("axis", 0)
        shape = getattr(eqn.invars[0].aval, "shape", ())
        return float(shape[axis]) if shape else 1.0
    axes = eqn.params.get("axes", ())
    shape = getattr(eqn.invars[0].aval, "shape", ())
    n = 1.0
    for a in axes:
        if a < len(shape) and isinstance(shape[a], (int, np.integer)):
            n *= float(shape[a])
    return max(n, 1.0)


def _contraction_len(eqn) -> float:
    prim = eqn.primitive.name
    if prim == "dot_general":
        (lhs_c, _), _ = eqn.params["dimension_numbers"]
        shape = getattr(eqn.invars[0].aval, "shape", ())
        k = 1.0
        for d in lhs_c:
            if d < len(shape):
                k *= float(shape[d])
        return max(k, 1.0)
    dn = eqn.params["dimension_numbers"]
    rhs = getattr(eqn.invars[1].aval, "shape", ())
    k = float(rhs[dn.rhs_spec[1]]) if rhs else 1.0
    for d in dn.rhs_spec[2:]:
        k *= float(rhs[d])
    return max(k, 1.0)


# ---------------------------------------------------------------------------
# diagnostics assembly
# ---------------------------------------------------------------------------

def _aggregate(sites: List[_Site]) -> List[Diagnostic]:
    """One diagnostic per code, naming the count and the first sites —
    a deep net can hit one hazard hundreds of times and the report must
    stay readable (the GL202 aggregation discipline)."""
    hints = {
        "GL401": "subtract the row-wise max before exp (jax.nn.softmax/"
                 "log_softmax already do), clamp the operand, or declare "
                 "the real input range via make_train_step(input_range=) "
                 "so the analysis can prove the bound",
        "GL402": "clamp the operand non-negative before the root "
                 "(jnp.maximum(v, 0.0) + eps — the in-repo BatchNorm "
                 "form) or guard the denominator away from zero "
                 "(jnp.maximum(amax, tiny), ops/quantization.py)",
        "GL403": "exclude the op from bf16 demotion (the amp_bf16 pass "
                 "does this automatically under numerics='warn'), or "
                 "rescale/clamp the edge into bf16 range",
        "GL404": "compute the scalar in f32 (np.float32(...) / "
                 "jnp.float32) — the adam bias-correction and decoder "
                 "attention-scale fixes — or drop the x64 flag "
                 "dependence; weak Python floats promote through "
                 "integer operands",
        "GL405": "set loss_scale to the suggested value (or 'dynamic'); "
                 "bf16/f32 share f32's exponent range, so scaling only "
                 "pays for f16 gradients",
    }
    by_code: Dict[str, List[_Site]] = {}
    for s in sites:
        by_code.setdefault(s.code, []).append(s)
    out: List[Diagnostic] = []
    for code in sorted(by_code):
        group = by_code[code]
        sev = max(s.severity for s in group)
        shown = "; ".join("%s (%s)" % (s.detail, s.where)
                          for s in group[:3])
        more = "" if len(group) <= 3 else " (+%d more sites)" \
            % (len(group) - 3)
        out.append(Diagnostic(
            code, sev,
            "%d site(s): %s%s" % (len(group), shown, more),
            where="graftrange value-range walk",
            hint=hints.get(code, "")))
    return out


def loss_scale_diags(compute_dtype, loss_scale, dynamic: bool,
                     where: str = "") -> List[Diagnostic]:
    """GL405: static loss-scale advisory from the configured scale and
    compute dtype — the numerics of ``contrib/amp/loss_scaler.py`` as
    a trace-time bound instead of runtime trial and error.

    ``loss_scale`` is the static scale (float) or None; ``dynamic``
    marks a DynamicLossScale config (self-tuning: no advisory).  The
    smallest unscaled-grad magnitude representable after scaling is
    ``tiny(dtype)/S``; the overflow ceiling is ``max(dtype)/S``."""
    diags: List[Diagnostic] = []
    dt = np.dtype(compute_dtype) if compute_dtype is not None \
        else np.dtype(np.float32)
    is_f16 = dt == np.float16
    if dynamic:
        return diags
    s = float(loss_scale) if loss_scale else None
    if is_f16:
        f16 = np.finfo(np.float16)
        if s is None:
            diags.append(Diagnostic(
                "GL405", Severity.WARNING,
                "compute dtype float16 with no loss scale: gradient "
                "magnitudes below %.3g flush to zero in the backward "
                "pass — suggested loss_scale: 2**14 (or 'dynamic')"
                % float(f16.tiny), where=where,
                hint="make_train_step(loss_scale=2**14) or "
                     "loss_scale='dynamic'"))
        elif float(f16.max) / s < 1.0:
            diags.append(Diagnostic(
                "GL405", Severity.ERROR,
                "static loss_scale %.3g with compute dtype float16: "
                "the scaled-grad overflow ceiling f16max/S = %.3g sits "
                "below 1.0, so any gradient of ordinary magnitude "
                "overflows and EVERY step is skipped — suggested "
                "loss_scale: 2**14" % (s, float(f16.max) / s),
                where=where,
                hint="make_train_step(loss_scale=2**14) or "
                     "loss_scale='dynamic'"))
        return diags
    if s is not None and s != 1.0:
        diags.append(Diagnostic(
            "GL405", Severity.WARNING,
            "static loss_scale %.3g with compute dtype %s: bf16/f32 "
            "share float32's exponent range, so scaling buys no "
            "representable-gradient headroom here (the smallest "
            "representable grad magnitude is already ~1e-38) — "
            "suggested scale: 1 (drop loss_scale), or reserve scaling "
            "for float16" % (s, dt.name), where=where,
            hint="drop loss_scale, or keep 'dynamic' only as an "
                 "overflow tripwire"))
    return diags


# ---------------------------------------------------------------------------
# entry point
# ---------------------------------------------------------------------------

def analyze_ranges(closed_jaxpr, *,
                   input_ranges: Optional[Dict[int, Any]] = None,
                   invar_labels: Optional[Dict[int, str]] = None,
                   axis_sizes: Optional[Dict[str, int]] = None,
                   collect: bool = True,
                   meta: Optional[Dict[str, Any]] = None) -> RangeReport:
    """Abstractly interpret value ranges over one traced program (no
    compile, no execution — the walk runs on the ``jit.trace()`` jaxpr
    the first call reuses).

    ``input_ranges`` maps flat invar indices to ``(lo, hi)`` /
    ``(lo, hi, positive)`` tuples or :class:`VRange` seeds — declared
    annotations (``make_train_step(input_range=)``), observed warmup
    samples, optimizer-state facts.  Unannotated floats default to
    *unknown finite*; integers/bools to their dtype ranges.
    ``invar_labels`` names invars in the report table.  ``axis_sizes``
    seeds named-axis sizes for collectives outside any ``shard_map``
    (inside one, sizes come from its mesh) — the psum-family bound
    multiplier.  ``collect=False`` skips hazard-site collection (the
    amp gate's cheap mode: only ``var_ranges`` is needed).
    """
    jaxpr = closed_jaxpr.jaxpr if isinstance(closed_jaxpr,
                                             jcore.ClosedJaxpr) \
        else closed_jaxpr
    consts = getattr(closed_jaxpr, "consts", ())
    interp = _Interp(axis_sizes=axis_sizes)
    env: Dict[Any, VRange] = {}
    input_ranges = input_ranges or {}
    labels = invar_labels or {}
    for i, v in enumerate(jaxpr.invars):
        seed = input_ranges.get(i)
        if seed is None:
            vr = _default_for_aval(v.aval)
        elif isinstance(seed, VRange):
            vr = VRange(seed.lo, seed.hi, seed.positive, seed.nan,
                        getattr(v.aval, "dtype", None))
        else:
            t = tuple(seed)
            lo = None if t[0] is None else float(t[0])
            hi = None if (len(t) < 2 or t[1] is None) else float(t[1])
            pos = bool(t[2]) if len(t) > 2 else (lo is not None and lo > 0)
            vr = VRange(lo, hi, pos, False,
                        getattr(v.aval, "dtype", None))
        env[v] = vr
        if _dtype_is_f64(getattr(v.aval, "dtype", None)):
            interp.f64_inputs = True
    outs = interp.walk(jaxpr, env, consts, collect=collect)

    report = RangeReport(meta=dict(meta or {}))
    report.var_ranges = {v: env[v] for v in env
                         if isinstance(v, jcore.Var)}
    if collect:
        for i, v in enumerate(jaxpr.invars):
            vr = env[v]
            report.rows.append({
                "name": labels.get(i, "in[%d]" % i), "kind": "input",
                "dtype": str(getattr(v.aval, "dtype", "?")),
                "shape": tuple(getattr(v.aval, "shape", ())),
                "range": vr.describe(), "lo": vr.lo, "hi": vr.hi,
                "positive": vr.positive, "nan": vr.nan,
                "inf": vr.may_be_inf()})
        for i, (v, vr) in enumerate(zip(jaxpr.outvars, outs)):
            report.rows.append({
                "name": "out[%d]" % i, "kind": "output",
                "dtype": str(getattr(getattr(v, "aval", None), "dtype",
                                     "?")),
                "shape": tuple(getattr(getattr(v, "aval", None), "shape",
                                       ())),
                "range": vr.describe(), "lo": vr.lo, "hi": vr.hi,
                "positive": vr.positive, "nan": vr.nan,
                "inf": vr.may_be_inf()})
        for s in interp.sites:
            report.sites.setdefault(s.code, []).append(
                {"prim": s.prim, "where": s.where, "detail": s.detail})
        report.diagnostics = _aggregate(interp.sites)
    return report
